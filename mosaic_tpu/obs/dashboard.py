"""Live ops dashboard: JSON endpoints + one self-contained HTML page.

Reference counterpart: the Spark UI.  Standalone we extend the stdlib
``serve_metrics`` scrape server into a small operator console — no
templates, no JS bundles, no new dependencies; the page is one inline
HTML string that polls the JSON endpoints below with ``fetch()``.

Routes:

* ``/``                 — the polling HTML page
* ``/metrics``          — the OpenMetrics exposition (scraper compat)
* ``/api/summary``      — alerts_active, series/metric counts, uptime
* ``/api/series``       — known time-series names (``?prefix=``)
* ``/api/timeseries``   — windowed stats + raw points for one series
  (``?name=...&window=300``)
* ``/api/alerts``       — active SLO breaches + recent breach events
* ``/api/traces``       — recent completed trace trees (tracer on)
* ``/api/planner``      — planner decisions/coefficients report
* ``/api/devices``      — per-device attribution (``obs.devicemon``)
* ``/api/memory``       — the device-memory ledger snapshot
  (``obs.memwatch``): per-device live/peak/capacity/pressure, top
  live holders by (site, trace, device), recent leaks, budget state
* ``/memory``           — the memory page over ``/api/memory``
* ``/api/profile``      — profiler snapshot: host stacks (``?trace=``
  filters to one trace context), kernel ledger, collapsed text
* ``/profile``          — the flamegraph view over ``/api/profile``
* ``/api/queries``      — live query console: in-flight tickets
  (``obs.inflight``) + recent audit completions (``?limit=``)
* ``/api/principals``   — per-principal meter totals (``obs.accounting``)
* ``/api/server``       — query-server state (``serve/``): queue,
  quotas, per-tenant admission/shed counters
* ``/api/history``      — workload history (``obs.history``): merged
  window payloads + totals from ``mosaic.history.dir`` (``?dir=``
  overrides; ``?window=<ms>`` re-windows), plus the live partition
  heat report (``obs.heat``); ``{"enabled": False}`` when no history
  dir is configured
* ``POST /api/queries/<id>/cancel`` — request cooperative cancellation
  of an in-flight query (POST-only: GET answers 405; an unknown id
  answers a JSON 404)

API hygiene: every JSON response carries ``Cache-Control: no-store``
(live state must never be served from a browser cache), and unknown
``/api/*`` paths answer a JSON 404 body — a poller never gets an HTML
error page where it expects JSON.

``serve_dashboard(port=0)`` returns the same stoppable
:class:`~.openmetrics.ServerHandle` as ``serve_metrics`` — close it
with ``handle.close()``.
"""

from __future__ import annotations

import http.server
import json
import re
import time
import urllib.parse
from typing import Dict, Optional

from .metrics import metrics
from .openmetrics import CONTENT_TYPE, ServerHandle, start_server, \
    to_openmetrics
from .recorder import recorder
from .timeseries import timeseries
from .tracer import tracer

__all__ = ["serve_dashboard"]

_MAX_POINTS = 500          # raw points per /api/timeseries response
_MAX_TRACES = 20
_MAX_EVENTS = 50
_MAX_AUDIT = 100           # recent completions per /api/queries
_CANCEL_RE = re.compile(r"^/api/queries/([^/]+)/cancel$")


def _summary(t0: float) -> Dict[str, object]:
    from .slo import monitor
    from .timeseries import sampler
    rep = metrics.report()
    smp = sampler()
    return {
        "ts": time.time(),
        "uptime_s": round(time.time() - t0, 1),
        "alerts_active": monitor.alerts_active(),
        "breaches": monitor.breach_count(),
        "series": len(timeseries),
        "counters": len(rep["counters"]),
        "gauges": len(rep["gauges"]),
        "histograms": len(rep["histograms"]),
        "metrics_enabled": metrics.enabled,
        "sampler": {"running": smp is not None and smp.alive,
                    "interval_ms": smp.interval_ms if smp else 0,
                    "ticks": smp.ticks if smp else 0},
    }


def _timeseries_payload(qs: Dict[str, list]) -> Dict[str, object]:
    name = (qs.get("name") or [""])[0]
    try:
        window = float((qs.get("window") or ["300"])[0])
    except ValueError:
        window = 300.0
    s = timeseries.series(name)
    if s is None:
        return {"name": name, "window_s": window, "found": False,
                "stats": {}, "points": []}
    now = time.time()
    pts = [(t, v) for t, v in s.raw if t >= now - window]
    if len(pts) > _MAX_POINTS:
        step = len(pts) / _MAX_POINTS
        pts = [pts[int(i * step)] for i in range(_MAX_POINTS)]
    return {
        "name": name, "window_s": window, "found": True,
        "stats": s.window_stats(window, now),
        "rate": s.rate(window, now),
        "p99": s.quantile_over_window(99, window, now),
        "points": [[round(t, 3), v] for t, v in pts],
    }


def _alerts_payload() -> Dict[str, object]:
    from .slo import monitor
    return {
        "active": monitor.active_alerts(),
        "objectives": [o["name"] for o in
                       monitor.report()["objectives"]],
        "recent_breaches": recorder.events("slo_breach")[-_MAX_EVENTS:],
        "recent_recoveries":
            recorder.events("slo_recovered")[-_MAX_EVENTS:],
    }


def _traces_payload() -> Dict[str, object]:
    traces = tracer.report().get("traces", {})
    items = list(traces.items())[-_MAX_TRACES:]
    return {"traces": {tid: {"name": t.get("name"),
                             "spans": t.get("spans", [])[:200]}
                       for tid, t in items}}


def _planner_payload() -> Dict[str, object]:
    try:
        from ..sql.planner import planner
        return planner.report()
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def _devices_payload() -> Dict[str, object]:
    from .devicemon import devicemon
    return devicemon.report()


def _memory_payload() -> Dict[str, object]:
    from .memwatch import mem_budget, memwatch
    snap = memwatch.snapshot()
    snap["budget"] = {"budget_bytes": mem_budget.budget_bytes(),
                      "pressure_high": mem_budget.pressure_high()}
    if metrics.enabled:
        rep = metrics.report()
        snap["counters"] = {
            "chunk_shrink": rep["counters"].get("mem/chunk_shrink", 0.0),
            "admit_denied": rep["counters"].get("mem/admit_denied", 0.0),
            "release_skipped":
                rep["counters"].get("mem/release_skipped", 0.0),
        }
    return snap


def _queries_payload(qs: Dict[str, list]) -> Dict[str, object]:
    from .accounting import audit
    from .inflight import inflight
    try:
        limit = int((qs.get("limit") or ["20"])[0])
    except ValueError:
        limit = 20
    limit = max(1, min(limit, _MAX_AUDIT))
    return {
        "inflight": inflight.list_active(),
        "recent": audit.records(limit=limit),
        "audited": audit.written(),
    }


def _principals_payload() -> Dict[str, object]:
    from .accounting import meter
    return {"principals": meter.report()}


def _server_payload() -> Dict[str, object]:
    """The query-server panel: the live :class:`~..serve.server.
    QueryServer`'s stats, or ``{"running": False}`` when no server is
    up in this process (the dashboard works stand-alone)."""
    try:
        from ..serve.server import current_server
    except Exception:
        return {"running": False}
    srv = current_server()
    if srv is None:
        return {"running": False}
    try:
        return srv.stats()
    except Exception as exc:
        return {"running": True,
                "error": f"{type(exc).__name__}: {exc}"}


def _fleet_payload(qs: Dict[str, list]) -> Dict[str, object]:
    """The fleet panel: merged cross-worker view from the spool dir
    (``?dir=`` overrides ``mosaic.obs.fleet.dir``).  ``?bundle=1``
    returns the full fleet bundle (stitched traces + every worker's
    recent events) instead of the summary view.  No spool dir
    configured -> ``{"enabled": False}``, same stand-alone contract as
    the server panel."""
    from .. import config as _config
    directory = (qs.get("dir") or [""])[0] or \
        _config.default_config().obs_fleet_dir
    if not directory:
        return {"enabled": False}
    from .fleet import aggregator_for
    agg = aggregator_for(directory)
    try:
        view = agg.scan()
        if (qs.get("bundle") or [""])[0] in ("1", "true"):
            return dict(agg.bundle(view), enabled=True)
        traces = agg.stitched_traces(view)
        return {"enabled": True,
                "fleet": view.payload(),
                "supervisor": _supervisor_status(directory),
                "slo_fleet": agg.evaluate_slo(view),
                "traces": {tid: {"workers": t["workers"],
                                 "spans": len(t["spans"])}
                           for tid, t in traces.items()}}
    except Exception as exc:      # a broken spool dir must not 500
        return {"enabled": True, "dir": directory,
                "error": f"{type(exc).__name__}: {exc}"}


def _supervisor_status(directory: str):
    """The serving-fleet supervisor's status file, when the spool dir
    doubles as a ServeFleet runtime dir (serve/supervisor.py writes
    ``supervisor.json`` atomically each health tick).  None when no
    supervisor runs over this directory; a torn/absent file is a
    degrade, never a panel error."""
    import json as _json
    import os as _os
    path = _os.path.join(directory, "supervisor.json")
    try:
        with open(path) as f:
            return _json.load(f)
    except (OSError, ValueError):
        return None


def _history_payload(qs: Dict[str, list]) -> Dict[str, object]:
    """The workload-history panel: merged windows + totals for the
    history dir (``?dir=`` overrides ``mosaic.history.dir`` / the
    feed's resolved dir) plus the live heat report.  No dir ->
    ``{"enabled": False}``; a broken dir degrades to an error field,
    never a 500 (same stand-alone contract as the fleet panel)."""
    from .heat import heat
    from .history import history, report
    directory = (qs.get("dir") or [""])[0] or history.directory()
    out: Dict[str, object] = {"heat": heat.report(top=10)}
    if not directory:
        out["enabled"] = False
        return out
    out["enabled"] = True
    try:
        window = (qs.get("window") or [""])[0]
        out.update(report(directory,
                          float(window) if window else None))
        out["write_errors"] = history.write_errors()
    except Exception as exc:
        out["dir"] = directory
        out["error"] = f"{type(exc).__name__}: {exc}"
    return out


def _profile_payload(qs: Dict[str, list]) -> Dict[str, object]:
    from .profiler import ledger, profiler
    trace = (qs.get("trace") or [None])[0] or None
    p = profiler()
    out: Dict[str, object] = {
        "running": p is not None and p.alive,
        "ledger": ledger.report(),
    }
    if p is not None:
        rep = p.report(max_stacks=_MAX_POINTS)
        if trace:
            rep["stacks"] = [s for s in rep["stacks"]
                             if s["trace"] == trace]
        out["host"] = rep
        out["collapsed"] = p.collapsed(trace)
    else:
        out["host"] = {}
        out["collapsed"] = ""
    return out


_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>mosaic_tpu ops</title>
<style>
 body{font:13px/1.5 system-ui,sans-serif;margin:1.5em;max-width:70em}
 h1{font-size:1.2em} h2{font-size:1em;margin:1.2em 0 .3em}
 table{border-collapse:collapse} td,th{padding:.15em .7em;
  border-bottom:1px solid #ddd;text-align:left;font-variant-numeric:
  tabular-nums}
 .ok{color:#2a7} .bad{color:#c33;font-weight:600}
 #alerts li{color:#c33} code{background:#f4f4f4;padding:0 .3em}
 svg{border:1px solid #ddd;background:#fafafa}
</style></head><body>
<h1>mosaic_tpu ops dashboard</h1>
<p><a href="/profile">profiler / flamegraph</a> ·
 <a href="/memory">memory</a> ·
 <a href="/metrics">openmetrics</a></p>
<div id="summary">loading…</div>
<h2>Active alerts</h2><ul id="alerts"><li class="ok">none</li></ul>
<h2>Series <select id="pick"></select>
 <span id="stats"></span></h2>
<svg id="chart" width="640" height="120"></svg>
<h2>Devices</h2><table id="devices"></table>
<h2>Queries in flight</h2><table id="queries"></table>
<h2>Recent completions</h2><table id="recent"></table>
<h2>Principals</h2><table id="principals"></table>
<h2>Query server</h2><div id="server">not running</div>
<table id="servertab"></table>
<h2>Workload history</h2><div id="history">not configured</div>
<table id="histwin"></table>
<h2>Partition heat</h2><table id="heat"></table>
<script>
const $=id=>document.getElementById(id);
async function j(u){const r=await fetch(u);return r.json()}
function draw(pts){const s=$("chart");if(!pts.length){s.innerHTML="";
 return}const xs=pts.map(p=>p[0]),ys=pts.map(p=>p[1]);
 const x0=Math.min(...xs),x1=Math.max(...xs)||x0+1,
 y0=Math.min(...ys),y1=Math.max(...ys);const yr=(y1-y0)||1;
 const d=pts.map((p,i)=>(i?"L":"M")+(620*(p[0]-x0)/(x1-x0||1)+10)+
 ","+(110-100*(p[1]-y0)/yr)).join(" ");
 s.innerHTML='<path d="'+d+'" fill="none" stroke="#27c"/>'}
async function tick(){
 const s=await j("/api/summary");
 $("summary").innerHTML=
  (s.alerts_active?'<span class="bad">'+s.alerts_active+
   ' alert(s) active</span>':'<span class="ok">healthy</span>')+
  " — "+s.series+" series, "+s.counters+" counters, sampler "+
  (s.sampler.running?s.sampler.interval_ms+"ms ("+s.sampler.ticks+
   " ticks)":"off")+", up "+s.uptime_s+"s";
 const a=await j("/api/alerts");
 $("alerts").innerHTML=a.active.length?a.active.map(x=>"<li>"+x.name+
  " ("+x.kind+") short="+x.short.toFixed(4)+" long="+
  x.long.toFixed(4)+" budget="+x.budget.toFixed(4)+"</li>").join("")
  :'<li class="ok">none</li>';
 const names=(await j("/api/series")).names;
 const pick=$("pick");const cur=pick.value;
 pick.innerHTML=names.map(n=>"<option"+(n===cur?" selected":"")+">"+
  n+"</option>").join("");
 if(pick.value){const ts=await j("/api/timeseries?name="+
  encodeURIComponent(pick.value)+"&window=300");
  $("stats").textContent=" n="+ts.stats.count+" mean="+
   (+ts.stats.mean).toPrecision(4)+" max="+
   (+ts.stats.max).toPrecision(4)+" p99="+(+ts.p99).toPrecision(4);
  draw(ts.points)}
 const d=await j("/api/devices");
 $("devices").innerHTML="<tr><th>device</th><th>busy_s</th>"+
  "<th>util</th><th>rows</th><th>peak_bytes</th></tr>"+
  Object.entries(d.devices).map(([k,v])=>"<tr><td>"+k+"</td><td>"+
   v.busy_s.toFixed(3)+"</td><td>"+(v.util||0).toFixed(2)+
   "</td><td>"+v.rows+"</td><td>"+(v.peak_bytes||"-")+
   "</td></tr>").join("");
 const esc=t=>String(t).replace(/&/g,"&amp;").replace(/</g,"&lt;");
 const q=await j("/api/queries");
 $("queries").innerHTML="<tr><th>id</th><th>principal</th>"+
  "<th>sql</th><th>operator</th><th>wall_ms</th><th>rows</th>"+
  "<th></th></tr>"+(q.inflight.length?q.inflight.map(x=>"<tr><td>"+
   esc(x.query_id)+"</td><td>"+esc(x.principal)+"</td><td><code>"+
   esc(x.sql)+"</code></td><td>"+esc(x.operator)+"</td><td>"+
   x.cost.wall_ms.toFixed(0)+"</td><td>"+x.cost.rows+"</td><td>"+
   (x.cancel_requested?"cancelling…":'<button onclick="cancelQ(\\''+
    x.query_id+'\\')">cancel</button>')+"</td></tr>").join(""):
   '<tr><td colspan="7" class="ok">idle</td></tr>');
 $("recent").innerHTML="<tr><th>id</th><th>principal</th>"+
  "<th>outcome</th><th>wall_ms</th><th>device_s</th><th>rows</th>"+
  "</tr>"+q.recent.slice().reverse().map(r=>"<tr><td>"+
   esc(r.query_id)+"</td><td>"+esc(r.principal)+"</td><td"+
   (r.outcome==="ok"?">":' class="bad">')+esc(r.outcome)+
   "</td><td>"+r.cost.wall_ms.toFixed(0)+"</td><td>"+
   r.cost.device_s.toFixed(4)+"</td><td>"+r.cost.rows_out+
   "</td></tr>").join("");
 const pr=await j("/api/principals");
 $("principals").innerHTML="<tr><th>principal</th><th>queries</th>"+
  "<th>wall_ms</th><th>device_s</th><th>rows_out</th>"+
  "<th>h2d_bytes</th><th>compiles</th></tr>"+
  Object.entries(pr.principals).map(([p,v])=>"<tr><td>"+esc(p)+
   "</td><td>"+v.queries+"</td><td>"+v.wall_ms.toFixed(0)+
   "</td><td>"+v.device_s.toFixed(4)+"</td><td>"+v.rows_out+
   "</td><td>"+v.h2d_bytes+"</td><td>"+v.compiles+
   "</td></tr>").join("");
 const sv=await j("/api/server");
 if(!sv.running){$("server").textContent="not running";
  $("server").className="ok";$("servertab").innerHTML="";}
 else{
  $("server").className=sv.draining?"bad":"ok";
  $("server").textContent=sv.addr+(sv.draining?" DRAINING":" serving")+
   " · workers "+sv.workers.busy+"/"+sv.workers.total+
   " · queue "+sv.queue.queued+"/"+sv.quotas.queue_depth+
   " · running "+sv.queue.running+
   (sv.counters.shed?" · shed "+sv.counters.shed:"");
  $("servertab").innerHTML="<tr><th>tenant</th><th>queued</th>"+
   "<th>running</th><th>admitted</th><th>shed</th></tr>"+
   Object.entries(sv.queue.principals).map(([p,v])=>"<tr><td>"+
    esc(p)+"</td><td>"+v.queued+"</td><td>"+v.running+"</td><td>"+
    v.admitted+"</td><td"+(v.shed?' class="bad">':">")+v.shed+
    "</td></tr>").join("");
 }
 const hi=await j("/api/history");
 const he=hi.heat||{cells:[]};
 $("heat").innerHTML="<tr><th>cell</th><th>scans</th><th>rows</th>"+
  "<th>bytes</th><th>bytes/row</th></tr>"+(he.cells.length?
  he.cells.map(c=>"<tr><td>"+c.cell+"</td><td>"+c.scans.toFixed(1)+
   "</td><td>"+c.rows.toFixed(0)+"</td><td>"+c.bytes.toFixed(0)+
   "</td><td>"+c.bytes_per_row.toFixed(1)+"</td></tr>").join("")
  :'<tr><td colspan="5" class="ok">no partitions touched</td></tr>');
 if(!hi.enabled){$("history").textContent="not configured";
  $("history").className="ok";$("histwin").innerHTML="";}
 else if(hi.error){$("history").className="bad";
  $("history").textContent=hi.dir+" — "+hi.error;}
 else{
  const tq=(hi.totals||{}).queries||0;
  $("history").className="ok";
  $("history").textContent=hi.dir+" — "+tq+" queries in "+
   (hi.windows||[]).length+" window(s)"+
   (hi.write_errors?", "+hi.write_errors+" write error(s)":"");
  $("histwin").innerHTML="<tr><th>window</th><th>queries</th>"+
   "<th>errors</th><th>p50 ms</th><th>p95 ms</th>"+
   "<th>mispredicts</th></tr>"+(hi.windows||[]).slice(-8).map(w=>{
    const op=Object.values(w.operators||{});
    const p50=op.length?Math.max(...op.map(o=>o.p50_ms)):0;
    const p95=op.length?Math.max(...op.map(o=>o.p95_ms)):0;
    const err=(w.outcomes||{}).error||0;
    return "<tr><td>"+w.window+"</td><td>"+w.queries+"</td><td"+
     (err?' class="bad">':">")+err+"</td><td>"+
     p50.toFixed(1)+"</td><td>"+p95.toFixed(1)+"</td><td>"+
     (w.mispredicts||0)+"</td></tr>"}).join("");
 }
}
async function cancelQ(id){
 await fetch("/api/queries/"+encodeURIComponent(id)+"/cancel",
  {method:"POST"});tick()}
tick();setInterval(tick,2000);
</script></body></html>
"""

# The flamegraph view: folds /api/profile's collapsed stacks into a
# trie client-side and renders one SVG rect per node (width = sample
# share, icicle layout, root on top).  Same zero-dependency rules as
# the main page: inline HTML, stdlib server, fetch() polling.
_PROFILE_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>mosaic_tpu profile</title>
<style>
 body{font:13px/1.5 system-ui,sans-serif;margin:1.5em;max-width:80em}
 h1{font-size:1.2em} h2{font-size:1em;margin:1.2em 0 .3em}
 table{border-collapse:collapse} td,th{padding:.15em .7em;
  border-bottom:1px solid #ddd;text-align:left;font-variant-numeric:
  tabular-nums}
 svg{border:1px solid #ddd;background:#fafafa;width:100%}
 svg text{font:10px monospace;pointer-events:none}
 #meta{color:#666}
</style></head><body>
<h1>mosaic_tpu profile <a href="/" style="font-size:.7em">(dashboard)
</a></h1>
<div id="meta">loading…</div>
<h2>Flame graph (host samples) <select id="trace"></select></h2>
<svg id="fg" height="0"></svg>
<h2>Kernel ledger</h2><table id="ledger"></table>
<script>
const $=id=>document.getElementById(id);
async function j(u){const r=await fetch(u);return r.json()}
function fold(stacks){const root={n:"all",v:0,c:{}};
 for(const s of stacks){root.v+=s.count;let cur=root;
  for(const f of s.frames){cur=cur.c[f]||(cur.c[f]={n:f,v:0,c:{}});
   cur.v+=s.count}}
 return root}
function render(root){const W=1200,H=16,rows=[];
 (function walk(node,x,d){rows.push([node,x,d]);let cx=x;
  for(const k of Object.keys(node.c).sort())
   {walk(node.c[k],cx,d+1);cx+=node.c[k].v}})(root,0,0);
 const depth=Math.max(...rows.map(r=>r[2]))+1;
 const sv=$("fg");sv.setAttribute("viewBox","0 0 "+W+" "+depth*H);
 sv.setAttribute("height",depth*H);
 sv.innerHTML=rows.map(([n,x,d])=>{const w=W*n.v/(root.v||1);
  if(w<1)return"";const px=W*x/(root.v||1);
  const hue=(n.n.split("").reduce((a,c)=>a+c.charCodeAt(0),0)%60)+10;
  return '<g><title>'+n.n+' ('+n.v+' samples)</title>'+
   '<rect x="'+px+'" y="'+d*H+'" width="'+Math.max(w-.5,.5)+
   '" height="'+(H-1)+'" fill="hsl('+hue+',70%,72%)"/>'+
   (w>60?'<text x="'+(px+3)+'" y="'+(d*H+H-5)+'">'+
    n.n.replace(/&/g,"&amp;").replace(/</g,"&lt;")
     .slice(0,Math.floor(w/7))+'</text>':'')+'</g>'}).join("")}
async function tick(){
 const sel=$("trace"),cur=sel.value;
 const p=await j("/api/profile"+(cur&&cur!=="(all)"?
  "?trace="+encodeURIComponent(cur):""));
 const h=p.host||{};
 $("meta").textContent=p.running?
  "sampler on @ "+h.hz+" Hz — "+h.samples+" samples, "+
  h.distinct_stacks+" distinct stacks, "+h.truncated+" truncated":
  "host sampler off (start_profiler() / MOSAIC_TPU_PROFILE_HZ) — "+
  "ledger below is always on";
 const traces=Object.entries(h.traces||{});
 sel.innerHTML=["(all)",...traces.map(([t,i])=>t)].map(t=>
  "<option"+(t===cur?" selected":"")+">"+t+"</option>").join("");
 render(fold(h.stacks||[]));
 const L=p.ledger||{kernels:[]};
 $("ledger").innerHTML="<tr><th>kernel</th><th>key</th>"+
  "<th>launches</th><th>seconds</th><th>rows/s</th><th>gflops/s</th>"+
  "</tr>"+L.kernels.map(k=>{const f=k.name.startsWith("fused:");
   return "<tr"+(f?' style="background:#eef6ee"':"")+"><td>"+
   (f?"<b>"+k.name+"</b> <span style=\"color:#484\">⧉</span>":k.name)+
   "</td><td><code>"+
   k.key.slice(0,60)+"</code></td><td>"+k.launches+"</td><td>"+
   k.seconds.toFixed(4)+"</td><td>"+(k.rows_per_s||"-")+"</td><td>"+
   (k.gflops_s||"-")+"</td></tr>"}).join("");
}
tick();setInterval(tick,3000);
</script></body></html>
"""


# The memory page: per-device live/peak/pressure bars over the
# /api/memory ledger snapshot, top live holders, and the leak list.
# Same zero-dependency rules as the other pages.
_MEMORY_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>mosaic_tpu memory</title>
<style>
 body{font:13px/1.5 system-ui,sans-serif;margin:1.5em;max-width:70em}
 h1{font-size:1.2em} h2{font-size:1em;margin:1.2em 0 .3em}
 table{border-collapse:collapse} td,th{padding:.15em .7em;
  border-bottom:1px solid #ddd;text-align:left;font-variant-numeric:
  tabular-nums}
 .ok{color:#2a7} .bad{color:#c33;font-weight:600}
 .bar{display:inline-block;height:.7em;background:#27c;
  vertical-align:baseline} code{background:#f4f4f4;padding:0 .3em}
 #meta{color:#666}
</style></head><body>
<h1>mosaic_tpu device memory <a href="/" style="font-size:.7em">
(dashboard)</a></h1>
<div id="meta">loading…</div>
<h2>Devices</h2><table id="devs"></table>
<h2>Top live holders</h2><table id="holders"></table>
<h2>Site peak attribution</h2><table id="sites"></table>
<h2>Leaks</h2><table id="leaks"></table>
<script>
const $=id=>document.getElementById(id);
async function j(u){const r=await fetch(u);return r.json()}
const fmt=b=>b>=1<<30?(b/2**30).toFixed(2)+" GiB":b>=1<<20?
 (b/2**20).toFixed(2)+" MiB":b>=1024?(b/1024).toFixed(1)+" KiB":b+" B";
async function tick(){
 const m=await j("/api/memory");
 const t=m.totals||{},c=m.counters||{};
 $("meta").innerHTML=(m.enabled?"ledger on":
  '<span class="bad">ledger off</span>')+" — live "+
  fmt(t.live_bytes||0)+" in "+(t.live_buffers||0)+" buffers, "+
  t.registered+" registered / "+t.released+" released, budget "+
  (m.budget.budget_bytes?fmt(m.budget.budget_bytes):"unlimited")+
  ", shrinks "+(c.chunk_shrink||0)+", admit denials "+
  (c.admit_denied||0)+", leaks "+
  (t.leaks?'<span class="bad">'+t.leaks+"</span>":"0");
 $("devs").innerHTML="<tr><th>device</th><th>live</th><th>peak</th>"+
  "<th>capacity</th><th>pressure</th></tr>"+
  Object.entries(m.devices).map(([k,v])=>"<tr><td>"+k+"</td><td>"+
   fmt(v.live_bytes)+"</td><td>"+fmt(v.peak_bytes)+"</td><td>"+
   fmt(v.capacity_bytes)+'</td><td><span class="bar" style="width:'+
   Math.min(100,100*v.pressure)+'px"></span> '+
   (100*v.pressure).toFixed(2)+"%</td></tr>").join("");
 $("holders").innerHTML="<tr><th>site</th><th>trace</th>"+
  "<th>device</th><th>bytes</th></tr>"+(m.holders.length?
  m.holders.map(h=>"<tr><td><code>"+h.site+"</code></td><td>"+
   (h.trace||"-")+"</td><td>"+h.device+"</td><td>"+fmt(h.bytes)+
   "</td></tr>").join("")
  :'<tr><td colspan="4" class="ok">nothing live</td></tr>');
 $("sites").innerHTML="<tr><th>site</th><th>peak bytes</th></tr>"+
  Object.entries(m.site_peak_bytes).map(([s,b])=>"<tr><td><code>"+
   s+"</code></td><td>"+fmt(b)+"</td></tr>").join("");
 $("leaks").innerHTML="<tr><th>query</th><th>site</th><th>bytes</th>"+
  "<th>buffers</th></tr>"+(m.leaks.length?m.leaks.map(l=>
  '<tr class="bad"><td>'+l.query_id+"</td><td><code>"+l.site+
  "</code></td><td>"+fmt(l.bytes)+"</td><td>"+l.buffers+
  "</td></tr>").join("")
  :'<tr><td colspan="4" class="ok">none</td></tr>');
}
tick();setInterval(tick,2000);
</script></body></html>
"""


def serve_dashboard(port: int = 0, addr: str = "127.0.0.1"
                    ) -> ServerHandle:
    """Start the ops dashboard; returns a stoppable
    :class:`~.openmetrics.ServerHandle` (ephemeral port by default —
    read it off ``handle.port``)."""
    t0 = time.time()

    class _Handler(http.server.BaseHTTPRequestHandler):
        def _send(self, body: bytes, ctype: str, code: int = 200,
                  extra: Optional[Dict[str, str]] = None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _json(self, payload, code: int = 200,
                  extra: Optional[Dict[str, str]] = None) -> None:
            # no-store: these are live snapshots; a cached /api/queries
            # would show phantom in-flight queries
            hdrs = {"Cache-Control": "no-store"}
            hdrs.update(extra or {})
            self._send(json.dumps(payload, default=str).encode(),
                       "application/json", code=code, extra=hdrs)

        def _api_404(self, path: str) -> None:
            self._json({"error": "not found", "path": path}, code=404)

        def do_GET(self):
            path, _, query = self.path.partition("?")
            qs = urllib.parse.parse_qs(query)
            try:
                if path == "/":
                    self._send(_PAGE.encode(), "text/html; charset=utf-8")
                elif path == "/metrics":
                    self._send(to_openmetrics().encode(), CONTENT_TYPE)
                elif path == "/api/summary":
                    self._json(_summary(t0))
                elif path == "/api/series":
                    prefix = (qs.get("prefix") or [""])[0]
                    self._json({"names": timeseries.names(prefix)})
                elif path == "/api/timeseries":
                    self._json(_timeseries_payload(qs))
                elif path == "/api/alerts":
                    self._json(_alerts_payload())
                elif path == "/api/traces":
                    self._json(_traces_payload())
                elif path == "/api/planner":
                    self._json(_planner_payload())
                elif path == "/api/devices":
                    self._json(_devices_payload())
                elif path == "/api/memory":
                    self._json(_memory_payload())
                elif path == "/api/profile":
                    self._json(_profile_payload(qs))
                elif path == "/api/queries":
                    self._json(_queries_payload(qs))
                elif path == "/api/principals":
                    self._json(_principals_payload())
                elif path == "/api/server":
                    self._json(_server_payload())
                elif path == "/api/fleet":
                    self._json(_fleet_payload(qs))
                elif path == "/api/history":
                    self._json(_history_payload(qs))
                elif _CANCEL_RE.match(path):
                    # cancel mutates: POST-only, so a prefetching
                    # browser/crawler can never kill a query
                    self._json({"error": "method not allowed",
                                "path": path}, code=405,
                               extra={"Allow": "POST"})
                elif path == "/profile":
                    self._send(_PROFILE_PAGE.encode(),
                               "text/html; charset=utf-8")
                elif path == "/memory":
                    self._send(_MEMORY_PAGE.encode(),
                               "text/html; charset=utf-8")
                elif path.startswith("/api/"):
                    self._api_404(path)
                else:
                    self.send_error(404)
            except BrokenPipeError:
                pass              # poller navigated away mid-response

        def do_POST(self):
            path, _, _ = self.path.partition("?")
            try:
                m = _CANCEL_RE.match(path)
                if m:
                    from .inflight import inflight
                    qid = m.group(1)
                    ok = inflight.cancel(qid)
                    self._json({"query_id": qid, "cancelled": ok},
                               code=200 if ok else 404)
                elif path.startswith("/api/"):
                    self._api_404(path)
                else:
                    self.send_error(404)
            except BrokenPipeError:
                pass

        def log_message(self, *args):   # polls must not spam stderr
            pass

    return start_server(_Handler, port, addr, "mosaic-ops-dashboard")
