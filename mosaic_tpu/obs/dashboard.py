"""Live ops dashboard: JSON endpoints + one self-contained HTML page.

Reference counterpart: the Spark UI.  Standalone we extend the stdlib
``serve_metrics`` scrape server into a small operator console — no
templates, no JS bundles, no new dependencies; the page is one inline
HTML string that polls the JSON endpoints below with ``fetch()``.

Routes:

* ``/``                 — the polling HTML page
* ``/metrics``          — the OpenMetrics exposition (scraper compat)
* ``/api/summary``      — alerts_active, series/metric counts, uptime
* ``/api/series``       — known time-series names (``?prefix=``)
* ``/api/timeseries``   — windowed stats + raw points for one series
  (``?name=...&window=300``)
* ``/api/alerts``       — active SLO breaches + recent breach events
* ``/api/traces``       — recent completed trace trees (tracer on)
* ``/api/planner``      — planner decisions/coefficients report
* ``/api/devices``      — per-device attribution (``obs.devicemon``)

``serve_dashboard(port=0)`` returns the same stoppable
:class:`~.openmetrics.ServerHandle` as ``serve_metrics`` — close it
with ``handle.close()``.
"""

from __future__ import annotations

import http.server
import json
import time
import urllib.parse
from typing import Dict, Optional

from .metrics import metrics
from .openmetrics import CONTENT_TYPE, ServerHandle, start_server, \
    to_openmetrics
from .recorder import recorder
from .timeseries import timeseries
from .tracer import tracer

__all__ = ["serve_dashboard"]

_MAX_POINTS = 500          # raw points per /api/timeseries response
_MAX_TRACES = 20
_MAX_EVENTS = 50


def _summary(t0: float) -> Dict[str, object]:
    from .slo import monitor
    from .timeseries import sampler
    rep = metrics.report()
    smp = sampler()
    return {
        "ts": time.time(),
        "uptime_s": round(time.time() - t0, 1),
        "alerts_active": monitor.alerts_active(),
        "breaches": monitor.breach_count(),
        "series": len(timeseries),
        "counters": len(rep["counters"]),
        "gauges": len(rep["gauges"]),
        "histograms": len(rep["histograms"]),
        "metrics_enabled": metrics.enabled,
        "sampler": {"running": smp is not None and smp.alive,
                    "interval_ms": smp.interval_ms if smp else 0,
                    "ticks": smp.ticks if smp else 0},
    }


def _timeseries_payload(qs: Dict[str, list]) -> Dict[str, object]:
    name = (qs.get("name") or [""])[0]
    try:
        window = float((qs.get("window") or ["300"])[0])
    except ValueError:
        window = 300.0
    s = timeseries.series(name)
    if s is None:
        return {"name": name, "window_s": window, "found": False,
                "stats": {}, "points": []}
    now = time.time()
    pts = [(t, v) for t, v in s.raw if t >= now - window]
    if len(pts) > _MAX_POINTS:
        step = len(pts) / _MAX_POINTS
        pts = [pts[int(i * step)] for i in range(_MAX_POINTS)]
    return {
        "name": name, "window_s": window, "found": True,
        "stats": s.window_stats(window, now),
        "rate": s.rate(window, now),
        "p99": s.quantile_over_window(99, window, now),
        "points": [[round(t, 3), v] for t, v in pts],
    }


def _alerts_payload() -> Dict[str, object]:
    from .slo import monitor
    return {
        "active": monitor.active_alerts(),
        "objectives": [o["name"] for o in
                       monitor.report()["objectives"]],
        "recent_breaches": recorder.events("slo_breach")[-_MAX_EVENTS:],
        "recent_recoveries":
            recorder.events("slo_recovered")[-_MAX_EVENTS:],
    }


def _traces_payload() -> Dict[str, object]:
    traces = tracer.report().get("traces", {})
    items = list(traces.items())[-_MAX_TRACES:]
    return {"traces": {tid: {"name": t.get("name"),
                             "spans": t.get("spans", [])[:200]}
                       for tid, t in items}}


def _planner_payload() -> Dict[str, object]:
    try:
        from ..sql.planner import planner
        return planner.report()
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def _devices_payload() -> Dict[str, object]:
    from .devicemon import devicemon
    return devicemon.report()


_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>mosaic_tpu ops</title>
<style>
 body{font:13px/1.5 system-ui,sans-serif;margin:1.5em;max-width:70em}
 h1{font-size:1.2em} h2{font-size:1em;margin:1.2em 0 .3em}
 table{border-collapse:collapse} td,th{padding:.15em .7em;
  border-bottom:1px solid #ddd;text-align:left;font-variant-numeric:
  tabular-nums}
 .ok{color:#2a7} .bad{color:#c33;font-weight:600}
 #alerts li{color:#c33} code{background:#f4f4f4;padding:0 .3em}
 svg{border:1px solid #ddd;background:#fafafa}
</style></head><body>
<h1>mosaic_tpu ops dashboard</h1>
<div id="summary">loading…</div>
<h2>Active alerts</h2><ul id="alerts"><li class="ok">none</li></ul>
<h2>Series <select id="pick"></select>
 <span id="stats"></span></h2>
<svg id="chart" width="640" height="120"></svg>
<h2>Devices</h2><table id="devices"></table>
<script>
const $=id=>document.getElementById(id);
async function j(u){const r=await fetch(u);return r.json()}
function draw(pts){const s=$("chart");if(!pts.length){s.innerHTML="";
 return}const xs=pts.map(p=>p[0]),ys=pts.map(p=>p[1]);
 const x0=Math.min(...xs),x1=Math.max(...xs)||x0+1,
 y0=Math.min(...ys),y1=Math.max(...ys);const yr=(y1-y0)||1;
 const d=pts.map((p,i)=>(i?"L":"M")+(620*(p[0]-x0)/(x1-x0||1)+10)+
 ","+(110-100*(p[1]-y0)/yr)).join(" ");
 s.innerHTML='<path d="'+d+'" fill="none" stroke="#27c"/>'}
async function tick(){
 const s=await j("/api/summary");
 $("summary").innerHTML=
  (s.alerts_active?'<span class="bad">'+s.alerts_active+
   ' alert(s) active</span>':'<span class="ok">healthy</span>')+
  " — "+s.series+" series, "+s.counters+" counters, sampler "+
  (s.sampler.running?s.sampler.interval_ms+"ms ("+s.sampler.ticks+
   " ticks)":"off")+", up "+s.uptime_s+"s";
 const a=await j("/api/alerts");
 $("alerts").innerHTML=a.active.length?a.active.map(x=>"<li>"+x.name+
  " ("+x.kind+") short="+x.short.toFixed(4)+" long="+
  x.long.toFixed(4)+" budget="+x.budget.toFixed(4)+"</li>").join("")
  :'<li class="ok">none</li>';
 const names=(await j("/api/series")).names;
 const pick=$("pick");const cur=pick.value;
 pick.innerHTML=names.map(n=>"<option"+(n===cur?" selected":"")+">"+
  n+"</option>").join("");
 if(pick.value){const ts=await j("/api/timeseries?name="+
  encodeURIComponent(pick.value)+"&window=300");
  $("stats").textContent=" n="+ts.stats.count+" mean="+
   (+ts.stats.mean).toPrecision(4)+" max="+
   (+ts.stats.max).toPrecision(4)+" p99="+(+ts.p99).toPrecision(4);
  draw(ts.points)}
 const d=await j("/api/devices");
 $("devices").innerHTML="<tr><th>device</th><th>busy_s</th>"+
  "<th>util</th><th>rows</th><th>peak_bytes</th></tr>"+
  Object.entries(d.devices).map(([k,v])=>"<tr><td>"+k+"</td><td>"+
   v.busy_s.toFixed(3)+"</td><td>"+(v.util||0).toFixed(2)+
   "</td><td>"+v.rows+"</td><td>"+(v.peak_bytes||"-")+
   "</td></tr>").join("");
}
tick();setInterval(tick,2000);
</script></body></html>
"""


def serve_dashboard(port: int = 0, addr: str = "127.0.0.1"
                    ) -> ServerHandle:
    """Start the ops dashboard; returns a stoppable
    :class:`~.openmetrics.ServerHandle` (ephemeral port by default —
    read it off ``handle.port``)."""
    t0 = time.time()

    class _Handler(http.server.BaseHTTPRequestHandler):
        def _send(self, body: bytes, ctype: str) -> None:
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _json(self, payload) -> None:
            self._send(json.dumps(payload, default=str).encode(),
                       "application/json")

        def do_GET(self):
            path, _, query = self.path.partition("?")
            qs = urllib.parse.parse_qs(query)
            try:
                if path == "/":
                    self._send(_PAGE.encode(), "text/html; charset=utf-8")
                elif path == "/metrics":
                    self._send(to_openmetrics().encode(), CONTENT_TYPE)
                elif path == "/api/summary":
                    self._json(_summary(t0))
                elif path == "/api/series":
                    prefix = (qs.get("prefix") or [""])[0]
                    self._json({"names": timeseries.names(prefix)})
                elif path == "/api/timeseries":
                    self._json(_timeseries_payload(qs))
                elif path == "/api/alerts":
                    self._json(_alerts_payload())
                elif path == "/api/traces":
                    self._json(_traces_payload())
                elif path == "/api/planner":
                    self._json(_planner_payload())
                elif path == "/api/devices":
                    self._json(_devices_payload())
                else:
                    self.send_error(404)
            except BrokenPipeError:
                pass              # poller navigated away mid-response

        def log_message(self, *args):   # polls must not spam stderr
            pass

    return start_server(_Handler, port, addr, "mosaic-ops-dashboard")
