"""Continuous per-device attribution: memory, bytes, busy-time.

Reference counterpart: the Spark executor page — per-executor task
time, shuffle bytes, peak memory.  Standalone the "executors" are mesh
devices, and nothing in JAX hands us per-device *time* on the host
side, so the monitor attributes **wall time to devices by observed
load share**: a sharded operator that ran ``seconds`` of wall clock
with per-shard matched-row counts ``w`` charges device ``i`` with
``seconds * w[i] / sum(w)``.  That is exactly the quantity the
skew gauges already measure — a device holding 3x the rows of its
peers accrues 3x the busy time — and it needs no extra host syncs:
the weights come from readbacks the join already performs on the
``mosaic.shard.skew.refresh`` cadence.

Feeds, all folded here:

* ``attribute(op, seconds, weights)`` — sharded pip_join / overlay
  wall time, split per device (also kept per-operator for the
  EXPLAIN ANALYZE ``device_ms`` column);
* ``observe_rows(site, counts)`` — per-device row counts from the
  overlay exchange accounting (``device/rows/<dev>`` counters);
* ``sample(store)`` — the sampler-tick fold: refreshes
  ``sample_memory`` watermarks (so ``mem/*`` gauges populate
  continuously), then writes per-device busy/peak series and a
  ``device/util/<dev>`` utilization gauge (busy-share since the
  previous tick, clamped to [0, 1]).

Everything is a no-op while the metrics registry is disabled — same
one-check contract as the rest of ``obs``.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, List, Mapping, Optional, Sequence

from .metrics import metrics

__all__ = ["DeviceMonitor", "devicemon", "mesh_device_keys",
           "format_device_ms"]


def mesh_device_keys(mesh) -> List[str]:
    """``platform:id`` keys for a mesh's devices in flat (shard)
    order — the key spelling ``sample_memory`` gauges use."""
    return [f"{d.platform}:{d.id}" for d in mesh.devices.flat]


def _default_keys(n: int) -> List[str]:
    """Device keys when the caller has no mesh handy: the visible jax
    devices if they cover ``n`` shards, else positional ``shard:<i>``."""
    if "jax" in sys.modules:
        try:
            import jax
            devs = jax.devices()
            if len(devs) >= n:
                return [f"{d.platform}:{d.id}" for d in devs[:n]]
        except Exception:
            pass
    return [f"shard:{i}" for i in range(n)]


class DeviceMonitor:
    """Process-global per-device busy-time / row / memory fold."""

    def __init__(self):
        self._lock = threading.Lock()
        self._busy: Dict[str, float] = {}          # dev -> seconds
        self._op_dev: Dict[str, Dict[str, float]] = {}  # op -> dev -> s
        self._rows: Dict[str, float] = {}          # dev -> rows routed
        self._last_tick: Optional[float] = None
        self._last_busy: Dict[str, float] = {}

    def reset(self) -> None:
        with self._lock:
            self._busy.clear()
            self._op_dev.clear()
            self._rows.clear()
            self._last_tick = None
            self._last_busy.clear()

    # -- attribution feeds -------------------------------------------
    def attribute(self, op: str, seconds: float,
                  weights: Optional[Sequence[float]] = None,
                  devices: Optional[Sequence[str]] = None) -> None:
        """Charge ``seconds`` of wall time to devices proportional to
        ``weights`` (uniform when None/degenerate)."""
        if not metrics.enabled or seconds <= 0:
            return
        if weights is None and devices is None:
            devices = _default_keys(1)
        if devices is None:
            devices = _default_keys(len(weights))
        ws = [max(0.0, float(w)) for w in weights] \
            if weights is not None else [1.0] * len(devices)
        if len(ws) != len(devices) or not devices:
            return
        total = sum(ws)
        if total <= 0:
            ws = [1.0] * len(devices)
            total = float(len(devices))
        with self._lock:
            per_op = self._op_dev.setdefault(op, {})
            for dev, w in zip(devices, ws):
                share = seconds * w / total
                self._busy[dev] = self._busy.get(dev, 0.0) + share
                per_op[dev] = per_op.get(dev, 0.0) + share

    def observe_rows(self, site: str,
                     counts: Sequence[float]) -> None:
        """Per-device routed-row counts from an exchange (the overlay
        accounting's hash-destination bincount)."""
        if not metrics.enabled:
            return
        devices = _default_keys(len(counts))
        with self._lock:
            for dev, c in zip(devices, counts):
                self._rows[dev] = self._rows.get(dev, 0.0) + float(c)
        for dev, c in zip(devices, counts):
            metrics.count(f"device/rows/{dev}", float(c))

    # -- reads --------------------------------------------------------
    def op_device_totals(self) -> Dict[str, Dict[str, float]]:
        """op -> device -> attributed seconds (cumulative); the
        EXPLAIN ANALYZE ``device_ms`` column diffs this around each
        stage."""
        with self._lock:
            return {op: dict(d) for op, d in self._op_dev.items()}

    def busy_by_device(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._busy)

    # -- the sampler-tick fold ---------------------------------------
    def sample(self, store=None, now: Optional[float] = None) -> None:
        """One fold pass: refresh memory watermarks, emit per-device
        series + utilization gauges.  Never initializes a jax backend
        (memory sampling is skipped until jax is already imported)."""
        if not metrics.enabled:
            return
        now = time.time() if now is None else now
        if store is None:
            from .timeseries import timeseries as store
        if "jax" in sys.modules:
            try:
                from .jaxmon import sample_memory
                mem = sample_memory()
            except Exception:
                mem = {}
            for dev, st in mem.items():
                store.record(f"device/peak_bytes/{dev}",
                             float(st.get("peak_bytes") or 0.0), now)
        with self._lock:
            busy = dict(self._busy)
            rows = dict(self._rows)
            last_tick, last_busy = self._last_tick, dict(self._last_busy)
            self._last_tick = now
            self._last_busy = dict(busy)
        for dev, s in busy.items():
            store.record(f"device/busy_s/{dev}", s, now)
        for dev, r in rows.items():
            store.record(f"device/rows/{dev}", r, now)
        if last_tick is not None and now > last_tick:
            dt = now - last_tick
            for dev, s in busy.items():
                util = (s - last_busy.get(dev, 0.0)) / dt
                metrics.gauge(f"device/util/{dev}",
                              min(1.0, max(0.0, util)))

    def report(self) -> Dict[str, object]:
        with self._lock:
            busy = dict(self._busy)
            rows = dict(self._rows)
            ops = {op: dict(d) for op, d in self._op_dev.items()}
        gauges = metrics.report()["gauges"]
        try:
            from .memwatch import memwatch
            live = memwatch.live_by_device() if memwatch.enabled else {}
        except Exception:
            live = {}
        devs = sorted(set(busy) | set(rows) | set(live))
        return {
            "devices": {
                dev: {
                    "busy_s": busy.get(dev, 0.0),
                    "rows": rows.get(dev, 0.0),
                    "util": gauges.get(f"device/util/{dev}", 0.0),
                    "peak_bytes": gauges.get(f"mem/peak_bytes/{dev}"),
                    # ledger-attributed live bytes + pressure (the
                    # allocator peak above is the backend's view; this
                    # is what WE can name a holder for)
                    "live_bytes": int(live.get(dev, 0)),
                    "pressure": gauges.get(f"mem/pressure/{dev}", 0.0),
                } for dev in devs
            },
            "ops": ops,
        }


#: the process-global monitor
devicemon = DeviceMonitor()


def format_device_ms(delta: Mapping[str, float]) -> str:
    """Render a per-device seconds delta as the EXPLAIN ANALYZE
    ``device_ms`` cell: ``"cpu:0=1.2 cpu:1=1.1"`` (ms), ``"-"`` when
    nothing was attributed."""
    parts = [f"{dev}={delta[dev] * 1e3:.1f}"
             for dev in sorted(delta) if delta[dev] > 0]
    return " ".join(parts) if parts else "-"
