"""Fleet aggregator: N per-process spools -> one exact telemetry view.

The scheduler/executor split (ROADMAP item 1; the LocationSpark
scheduler argument) turns one process into a fleet, and every
process-local surface — registry, SLO monitor, dashboard — needs a
fleet-level twin.  :class:`FleetAggregator` reads every
``worker-*.json`` spool under one directory (see :mod:`.spool`) and
merges them with fixed, loss-free rules:

* **counters** — summed over every READABLE spool, stale included: a
  crashed worker's completed work doesn't un-happen.
* **gauges** — max over FRESH workers only, annotated with the owning
  worker pid; a dead worker's last queue depth is not a fact about the
  fleet now.
* **histograms** — bucket-wise sums.  Every process uses the identical
  exponential bucket layout (``metrics._NBUCKETS``/``_PER_OCTAVE``),
  so the merged histogram's p50/p95/p99 are EXACTLY what one registry
  fed every sample would report (tests prove bit-equality).  A scale
  mismatch between workers (different unit bases for the same name)
  cannot be merged exactly and degrades: ``fleet_merge_error`` event,
  histogram skipped.
* **staleness** — a spool whose mtime is older than
  ``mosaic.obs.fleet.stale.ms`` flags its worker stale
  (``fleet_worker_stale`` event, once per transition) and degrades the
  view; it never raises.  Torn JSON / alien versions likewise:
  ``fleet_merge_error`` + skip.

:class:`FleetStore` re-hydrates each worker's spooled series tails
into real :class:`~.timeseries.Series` objects and exposes the same
windowed-read API as :class:`~.timeseries.TimeSeriesStore`, so
:meth:`SLObjective.evaluate` runs over the fleet unchanged.  The one
non-obvious rule: a fleet counter RATE is the SUM of per-worker rates
— interleaving cumulative counters from different processes into one
series would make (last - first) nonsense.

:func:`FleetAggregator.stitched_traces` reunites cross-process traces:
every ``trace_link`` event maps a worker-local trace id to the W3C
trace id it served, and every ``span`` event under a linked local
trace joins that W3C trace's tree (see ``context.link_traceparent``).
"""

from __future__ import annotations

import glob
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .metrics import Histogram, metrics
from .recorder import recorder
from .spool import SpoolError, read_spool
from .timeseries import Series

__all__ = ["WorkerState", "FleetStore", "FleetAggregator",
           "aggregator_for", "merge_history"]


class WorkerState:
    """One spool file's disposition in a scan."""

    __slots__ = ("pid", "path", "ts", "age_s", "stale", "error",
                 "snapshot")

    def __init__(self, pid: int, path: str):
        self.pid = pid
        self.path = path
        self.ts = 0.0            # spool mtime
        self.age_s = 0.0
        self.stale = False
        self.error: Optional[str] = None
        self.snapshot: Optional[Dict[str, Any]] = None

    @property
    def readable(self) -> bool:
        return self.snapshot is not None

    @property
    def fresh(self) -> bool:
        return self.readable and not self.stale

    def summary(self) -> Dict[str, Any]:
        return {"pid": self.pid, "path": self.path,
                "ts": self.ts, "age_s": round(self.age_s, 3),
                "stale": self.stale, "error": self.error}


class FleetStore:
    """Per-worker series with the TimeSeriesStore windowed-read API
    (duck-typed — ``SLObjective.evaluate`` takes any store).  Built
    from spool snapshots by :meth:`FleetAggregator.fleet_store`."""

    def __init__(self, series_by_worker: Dict[int, Dict[str, Series]]):
        self._workers = series_by_worker

    def _series(self, name: str) -> List[Series]:
        return [ss[name] for ss in self._workers.values()
                if name in ss]

    def names(self, prefix: str = "") -> List[str]:
        out = set()
        for ss in self._workers.values():
            out.update(n for n in ss if n.startswith(prefix))
        return sorted(out)

    def window_stats(self, name: str, seconds: float,
                     now: Optional[float] = None) -> Dict[str, float]:
        now = time.time() if now is None else now
        parts = [s.window_stats(seconds, now)
                 for s in self._series(name)]
        parts = [p for p in parts if p["count"]]
        if not parts:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                    "mean": 0.0}
        count = sum(p["count"] for p in parts)
        total = sum(p["sum"] for p in parts)
        return {"count": count, "sum": total,
                "min": min(p["min"] for p in parts),
                "max": max(p["max"] for p in parts),
                "mean": total / count}

    def rate(self, name: str, seconds: float,
             now: Optional[float] = None) -> float:
        # fleet rate = sum of per-worker counter rates; cumulative
        # counters from different processes must never interleave
        now = time.time() if now is None else now
        return sum(s.rate(seconds, now) for s in self._series(name))

    def max_over_window(self, name: str, seconds: float,
                        now: Optional[float] = None) -> float:
        now = time.time() if now is None else now
        vals = [s.max_over_window(seconds, now)
                for s in self._series(name)]
        return max(vals) if vals else 0.0

    def quantile_over_window(self, name: str, q: float, seconds: float,
                             now: Optional[float] = None) -> float:
        """Weighted merge across workers — the same (min, max,
        mean-weighted) bucket spread Series.quantile_over_window uses,
        pooled over every worker's window."""
        import math
        now = time.time() if now is None else now
        weighted: List[Tuple[float, int]] = []
        for s in self._series(name):
            pts, bks = s._window(now - seconds)
            weighted.extend((v, 1) for _, v in pts)
            for b in bks:
                if b.count == 1:
                    weighted.append((b.sum, 1))
                    continue
                weighted.append((b.min, 1))
                weighted.append((b.max, 1))
                if b.count > 2:
                    mean = (b.sum - b.min - b.max) / (b.count - 2)
                    weighted.append((mean, b.count - 2))
        if not weighted:
            return 0.0
        weighted.sort(key=lambda w: w[0])
        total = sum(w for _, w in weighted)
        target = max(1, math.ceil(total * q / 100.0))
        run = 0
        for v, w in weighted:
            run += w
            if run >= target:
                return v
        return weighted[-1][0]

    def fraction_over(self, name: str, threshold: float, seconds: float,
                      now: Optional[float] = None) -> Tuple[int, int]:
        now = time.time() if now is None else now
        bad = total = 0
        for s in self._series(name):
            b, t = s.fraction_over(threshold, seconds, now)
            bad += b
            total += t
        return bad, total


class FleetView:
    """One scan's merged result.  ``histograms`` holds live
    :class:`Histogram` objects (exact percentiles on demand);
    :meth:`payload` renders the JSON-able form."""

    def __init__(self, ts: float, directory: str,
                 workers: List[WorkerState]):
        self.ts = ts
        self.directory = directory
        self.workers = workers
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, Dict[str, Any]] = {}
        self.histograms: Dict[str, Histogram] = {}
        self.slo_active: List[Dict[str, Any]] = []
        self.slo_breaches = 0
        self.inflight: List[Dict[str, Any]] = []
        self.merge_errors = 0

    def payload(self) -> Dict[str, Any]:
        return {
            "ts": self.ts,
            "dir": self.directory,
            "workers": [w.summary() for w in self.workers],
            "stale": sorted(w.pid for w in self.workers if w.stale),
            "counters": dict(sorted(self.counters.items())),
            "gauges": {n: dict(g) for n, g in
                       sorted(self.gauges.items())},
            "histograms": {n: h.snapshot() for n, h in
                           sorted(self.histograms.items())},
            "slo": {"active": self.slo_active,
                    "breaches": self.slo_breaches},
            "inflight": self.inflight,
            "merge_errors": self.merge_errors,
        }


class FleetAggregator:
    """Scans one spool directory; owns per-worker stale-episode state
    so each stale transition records exactly one event."""

    def __init__(self, directory: str,
                 stale_ms: Optional[float] = None):
        self.directory = directory
        self._stale_ms = stale_ms
        self._lock = threading.Lock()
        self._stale_pids: set = set()

    def _stale_after_s(self) -> float:
        if self._stale_ms is not None:
            return self._stale_ms / 1e3
        from .. import config as _config
        return _config.default_config().obs_fleet_stale_ms / 1e3

    def _merge_error(self, view: FleetView, worker: WorkerState,
                     why: str) -> None:
        worker.error = why
        view.merge_errors += 1
        recorder.record("fleet_merge_error", pid=worker.pid,
                        path=worker.path, why=why[:300])
        if metrics.enabled:
            metrics.count("fleet/merge_errors")

    # -- the scan
    def scan(self, now: Optional[float] = None) -> FleetView:
        """Read every spool and merge.  Never raises for a bad spool:
        torn/alien/stale files degrade the view and say so."""
        now = time.time() if now is None else now
        stale_after = self._stale_after_s()
        workers: List[WorkerState] = []
        for path in sorted(glob.glob(
                os.path.join(self.directory, "worker-*.json"))):
            stem = os.path.basename(path)[len("worker-"):-len(".json")]
            try:
                pid = int(stem)
            except ValueError:
                continue
            workers.append(WorkerState(pid, path))
        view = FleetView(now, self.directory, workers)
        for w in workers:
            try:
                w.ts = os.path.getmtime(w.path)
            except OSError as e:       # raced a worker's os.replace
                self._merge_error(view, w, f"stat: {e}")
                continue
            w.age_s = max(0.0, now - w.ts)
            w.stale = w.age_s > stale_after
            try:
                w.snapshot = read_spool(w.path)
            except (SpoolError, OSError) as e:
                self._merge_error(view, w, str(e))
                continue
            self._merge_worker(view, w)
        self._note_stale_transitions(view)
        if metrics.enabled:
            metrics.gauge("fleet/workers", float(len(workers)))
            metrics.gauge("fleet/stale_workers",
                          float(sum(1 for w in workers if w.stale)))
        return view

    def _merge_worker(self, view: FleetView, w: WorkerState) -> None:
        snap = w.snapshot or {}
        reg = snap.get("metrics", {})
        for name, v in reg.get("counters", {}).items():
            view.counters[name] = view.counters.get(name, 0.0) \
                + float(v)
        if w.fresh:
            for name, v in reg.get("gauges", {}).items():
                cur = view.gauges.get(name)
                if cur is None or float(v) > cur["value"]:
                    view.gauges[name] = {"value": float(v),
                                         "worker": w.pid}
        for name, h in reg.get("histograms", {}).items():
            try:
                self._merge_histogram(view, w, name, h)
            except (KeyError, TypeError, ValueError) as e:
                self._merge_error(view, w,
                                  f"histogram {name}: {e}")
        slo = snap.get("slo", {})
        for alert in slo.get("active", []):
            view.slo_active.append(dict(alert, worker=w.pid))
        view.slo_breaches += int(slo.get("breaches", 0))
        for q in snap.get("inflight", []):
            view.inflight.append(dict(q, worker=w.pid))

    def _merge_histogram(self, view: FleetView, w: WorkerState,
                         name: str, h: Dict[str, Any]) -> None:
        scale = float(h["scale"])
        counts = [int(c) for c in h["counts"]]
        merged = view.histograms.get(name)
        if merged is None:
            merged = view.histograms[name] = Histogram(name, scale)
        elif merged.scale != scale:
            # different unit bases: bucket-wise addition would be a
            # lie, and exactness is the whole contract
            self._merge_error(view, w,
                              f"histogram {name}: scale "
                              f"{scale} != {merged.scale}")
            return
        if len(counts) != len(merged.counts):
            self._merge_error(view, w,
                              f"histogram {name}: {len(counts)} "
                              f"buckets != {len(merged.counts)}")
            return
        for i, c in enumerate(counts):
            merged.counts[i] += c
        n = int(h["count"])
        merged.count += n
        merged.sum += float(h["sum"])
        if n:
            merged.min = min(merged.min, float(h["min"]))
            merged.max = max(merged.max, float(h["max"]))

    def _note_stale_transitions(self, view: FleetView) -> None:
        now_stale = {w.pid for w in view.workers if w.stale}
        with self._lock:
            newly = now_stale - self._stale_pids
            self._stale_pids = now_stale
        for w in view.workers:
            if w.pid in newly:
                recorder.record("fleet_worker_stale", pid=w.pid,
                                age_s=round(w.age_s, 3),
                                path=w.path)
                if metrics.enabled:
                    metrics.count("fleet/stale_transitions")

    # -- series / SLO
    def fleet_store(self, view: Optional[FleetView] = None
                    ) -> FleetStore:
        """Per-worker Series re-hydrated from the spool tails."""
        view = view if view is not None else self.scan()
        by_worker: Dict[int, Dict[str, Series]] = {}
        for w in view.workers:
            if not w.readable:
                continue
            ss: Dict[str, Series] = {}
            for name, snap in (w.snapshot or {}).get("series",
                                                     {}).items():
                try:
                    ss[name] = Series.from_snapshot(name, snap)
                except (TypeError, ValueError) as e:
                    self._merge_error(view, w, f"series {name}: {e}")
            by_worker[w.pid] = ss
        return FleetStore(by_worker)

    def evaluate_slo(self, view: Optional[FleetView] = None,
                     objectives=None,
                     now: Optional[float] = None
                     ) -> List[Dict[str, Any]]:
        """Fleet-level burn-rate evaluation over the merged series
        (stateless — alerting episodes stay per-worker)."""
        from .slo import evaluate_fleet
        view = view if view is not None else self.scan()
        return evaluate_fleet(self.fleet_store(view),
                              objectives=objectives,
                              now=now if now is not None else view.ts)

    # -- cross-process traces
    def stitched_traces(self, view: Optional[FleetView] = None
                        ) -> Dict[str, Dict[str, Any]]:
        """W3C trace id -> the stitched cross-process tree: every
        worker-local trace that recorded a ``trace_link`` to that id
        contributes its spans (tagged with worker + local trace id);
        ``links`` carries each hop's parent span for tree layout."""
        view = view if view is not None else self.scan()
        traces: Dict[str, Dict[str, Any]] = {}
        for w in view.workers:
            if not w.readable:
                continue
            events = (w.snapshot or {}).get("events", [])
            links = {}           # local trace id -> link event
            for ev in events:
                if ev.get("kind") == "trace_link" and ev.get("trace"):
                    links[ev["trace"]] = ev
            if not links:
                continue
            for local, link in links.items():
                t = traces.setdefault(link["w3c_trace"], {
                    "workers": [], "links": [], "spans": []})
                if w.pid not in t["workers"]:
                    t["workers"].append(w.pid)
                t["links"].append({
                    "worker": w.pid, "local_trace": local,
                    "parent_span": link.get("w3c_parent"),
                    "name": link.get("name")})
            for ev in events:
                if ev.get("kind") != "span":
                    continue
                link = links.get(ev.get("trace"))
                if link is None:
                    continue
                traces[link["w3c_trace"]]["spans"].append({
                    "worker": w.pid,
                    "local_trace": ev["trace"],
                    "name": ev.get("name"),
                    "span": ev.get("span"),
                    "parent": ev.get("parent"),
                    "dur_s": ev.get("dur_s"),
                    "ts": ev.get("ts"),
                    **({"error": ev["error"]} if "error" in ev
                       else {}),
                })
        return traces

    # -- the fleet bundle
    def bundle(self, view: Optional[FleetView] = None
               ) -> Dict[str, Any]:
        """Self-contained fleet post-mortem: merged view + fleet SLO
        evaluation + stitched traces + every worker's recent events."""
        view = view if view is not None else self.scan()
        return {
            "reason": "fleet",
            "ts": view.ts,
            "fleet": view.payload(),
            "slo_fleet": self.evaluate_slo(view),
            "traces": self.stitched_traces(view),
            "events_by_worker": {
                w.pid: (w.snapshot or {}).get("events", [])
                for w in view.workers if w.readable},
        }


_agg_lock = threading.Lock()
_aggregators: Dict[str, FleetAggregator] = {}


def aggregator_for(directory: str) -> FleetAggregator:
    """The process-wide aggregator for a spool dir (cached: stale
    transitions are episodes, and episodes need a memory)."""
    with _agg_lock:
        agg = _aggregators.get(directory)
        if agg is None:
            agg = _aggregators[directory] = FleetAggregator(directory)
        return agg


def merge_history(directories: List[str],
                  window_ms: Optional[float] = None
                  ) -> Dict[str, Any]:
    """Fleet-wide workload history: every worker's history dir merged
    window by window with the same exactness discipline as spool
    merging — histogram buckets sum, so fleet percentiles are computed
    from the union, never averaged from per-worker percentiles.  An
    unreadable directory degrades (``fleet_merge_error`` event +
    ``fleet/merge_errors`` counter) and the rest still merge."""
    # NB: ``from . import history`` would resolve to the package's
    # re-exported HistoryFeed singleton, not the submodule
    from .history import (_resolve_window_ms, merge_summary,
                          merged_windows, new_summary, summary_payload)
    windows: Dict[int, Dict[str, Any]] = {}
    merged_dirs: List[str] = []
    errors = 0
    for d in directories:
        try:
            per = merged_windows(d, window_ms)
        except (OSError, ValueError, TypeError, KeyError) as e:
            errors += 1
            recorder.record("fleet_merge_error", pid=0, path=d,
                            error=f"history: {e}")
            if metrics.enabled:
                metrics.count("fleet/merge_errors")
            continue
        merged_dirs.append(d)
        for wid, s in per.items():
            cur = windows.get(wid)
            if cur is None:
                windows[wid] = s
            else:
                try:
                    merge_summary(cur, s)
                except (KeyError, TypeError, ValueError) as e:
                    errors += 1
                    recorder.record("fleet_merge_error", pid=0,
                                    path=d,
                                    error=f"history window {wid}: {e}")
                    if metrics.enabled:
                        metrics.count("fleet/merge_errors")
    totals = new_summary(None, _resolve_window_ms(window_ms))
    for wid in sorted(windows):
        try:
            merge_summary(totals, windows[wid])
        except (KeyError, TypeError, ValueError):
            pass
    return {
        "dirs": merged_dirs,
        "errors": errors,
        "windows": [summary_payload(windows[w])
                    for w in sorted(windows)],
        "totals": summary_payload(totals),
    }
