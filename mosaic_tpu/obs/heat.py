"""Per-partition access heat: time-decayed store-cell statistics.

ROADMAP item 1's replica-aware routing (and the LocationSpark
scheduler/executor argument, arxiv 1907.03736) is only as good as the
access statistics behind it.  :class:`HeatTracker` keeps those
statistics live, keyed by store grid cell:

* **feeds** — :meth:`~..store.reader.ChipStore.iter_chunks` /
  :meth:`~..store.reader.ChipStore.read_partition` touch each scanned
  partition with its rows read; the store-fed sharded join's
  staged-bytes ledger (``run.staged_bytes_by_partition``) charges the
  bytes each partition actually staged to a device.  A bbox-pruned
  partition is never touched — it stays cold, provably.
* **decay** — every accumulator halves per ``mosaic.heat.halflife.ms``
  of wall time (0 = no decay), applied lazily per cell on touch and
  read, so heat tracks the workload's present, not its history.
* **report** — :meth:`HeatTracker.report` ranks the top-K hot
  partitions (rows, scans, bytes, bytes/row) and derives the hot/cold
  skew ratio (hottest cell's decayed rows over the mean).
* **prior** — :meth:`HeatTracker.prior` folds cell heat into the
  ``nbins``×``nbins`` density lattice a
  :class:`~..parallel.placement.SkewRebalancer` packs from, and
  :meth:`SkewRebalancer.prime` seeds placement with it
  (``mosaic.heat.prior``).  Strictly a placement hint: placement only
  moves which device computes which rows, so a primed run's outputs
  are bit-for-bit identical to an unprimed one.

Always on: one dict update per touched partition span, no
configuration needed to collect (only to *use* the prior).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .metrics import metrics

__all__ = ["HeatTracker", "heat"]


class _CellHeat:
    __slots__ = ("scans", "rows", "bytes", "ts")

    def __init__(self, ts: float):
        self.scans = 0.0
        self.rows = 0.0
        self.bytes = 0.0
        self.ts = ts


class HeatTracker:
    """Process-global decayed per-cell access accumulators."""

    def __init__(self, halflife_ms: Optional[float] = None):
        self._halflife_ms = halflife_ms
        self._lock = threading.Lock()
        self._cells: Dict[int, _CellHeat] = {}

    def _halflife_s(self) -> float:
        if self._halflife_ms is not None:
            return float(self._halflife_ms) / 1e3
        from .. import config as _config
        return float(getattr(_config.default_config(),
                             "heat_halflife_ms", 300_000.0)) / 1e3

    def _decay_locked(self, e: _CellHeat, now: float) -> None:
        hl = self._halflife_s()
        if hl > 0 and now > e.ts:
            f = 0.5 ** ((now - e.ts) / hl)
            e.scans *= f
            e.rows *= f
            e.bytes *= f
        e.ts = max(e.ts, now)

    # -- feeds --------------------------------------------------------
    def touch(self, cell: int, rows: int = 0, nbytes: int = 0,
              scans: int = 1, now: Optional[float] = None) -> None:
        """Charge one access to a store cell (rows read, bytes staged,
        scan count — any subset)."""
        now = time.time() if now is None else now
        with self._lock:
            e = self._cells.get(int(cell))
            if e is None:
                e = self._cells[int(cell)] = _CellHeat(now)
            self._decay_locked(e, now)
            e.scans += float(scans)
            e.rows += float(rows)
            e.bytes += float(nbytes)
            tracked = len(self._cells)
        if metrics.enabled:
            metrics.count("heat/touches")
            metrics.gauge("heat/partitions_tracked", float(tracked))

    # -- reads --------------------------------------------------------
    def _snapshot(self, now: float) -> List[Tuple[int, _CellHeat]]:
        with self._lock:
            for e in self._cells.values():
                self._decay_locked(e, now)
            return [(c, e) for c, e in self._cells.items()]

    def report(self, top: int = 10,
               now: Optional[float] = None) -> Dict[str, Any]:
        """Top-K hot partitions + hot/cold skew.  ``skew`` is the
        hottest cell's decayed rows over the mean (1.0 = perfectly
        even; large = one partition carries the workload)."""
        now = time.time() if now is None else now
        cells = self._snapshot(now)
        ranked = sorted(cells, key=lambda ce: (-ce[1].rows,
                                               -ce[1].scans, ce[0]))
        rows = [e.rows for _, e in cells]
        mean = (sum(rows) / len(rows)) if rows else 0.0
        return {
            "tracked": len(cells),
            "total_rows": round(sum(rows), 3),
            "total_bytes": round(sum(e.bytes for _, e in cells), 3),
            "skew": round(max(rows) / mean, 3) if mean > 0 else 1.0,
            "cells": [{
                "cell": c,
                "scans": round(e.scans, 3),
                "rows": round(e.rows, 3),
                "bytes": round(e.bytes, 3),
                "bytes_per_row": round(e.bytes / e.rows, 3)
                if e.rows > 0 else 0.0,
            } for c, e in ranked[:max(0, int(top))]],
        }

    def prior(self, nbins: int, bbox,
              centers: Dict[int, Tuple[float, float]],
              now: Optional[float] = None) -> Optional[np.ndarray]:
        """The ``nbins``×``nbins`` density lattice (flattened, the
        :class:`SkewRebalancer` layout) implied by current heat:
        each tracked cell's decayed rows land in the lattice bin its
        bbox centroid falls in.  None when no tracked cell maps into
        ``centers`` — the rebalancer then starts cold, as before."""
        now = time.time() if now is None else now
        nb = max(2, int(nbins))
        bb = np.asarray(bbox, np.float64)
        span = np.maximum(bb[2:] - bb[:2], 1e-9)
        dens = np.zeros(nb * nb, np.float64)
        hit = False
        for c, e in self._snapshot(now):
            xy = centers.get(c)
            if xy is None or e.rows <= 0:
                continue
            ij = ((np.asarray(xy, np.float64) - bb[:2]) / span
                  * nb).astype(np.int64)
            ij = np.clip(ij, 0, nb - 1)
            dens[ij[0] * nb + ij[1]] += e.rows
            hit = True
        return dens if hit else None

    def reset(self) -> None:
        with self._lock:
            self._cells.clear()


#: the process-global tracker the store read paths feed
heat = HeatTracker()
