"""Workload history plane: durable per-query statistics on disk.

Every telemetry surface so far — metrics, traces, accounting, memwatch,
fleet spools — dies with the process.  This module is the durable
layer underneath them: a crash-safe, rotating on-disk **history store**
that receives exactly one record per completed query from
:func:`~.accounting.complete` (principal, outcome, the full cost
vector, planner strategy picks + mispredict count, fusion groups run,
and the store partitions the query touched) and keeps it readable
across process lifetimes.  ROADMAP item 3's SOLAR-style learned
partitioning (arxiv 2504.01292) trains on exactly these persisted run
stats; item 1's replica-aware routing reads the partition-touch
columns (see :mod:`.heat`).

On-disk layout under ``mosaic.history.dir`` (env
``MOSAIC_TPU_HISTORY_DIR`` pins the directory over conf):

* ``history-<pid>.open.jsonl`` — THIS process's open segment: a
  version header line followed by one JSON record per completed
  query, appended + flushed per record.  Per-pid naming makes
  concurrent writers from different processes safe by construction.
* ``history-<ts>-<pid>-<n>.jsonl`` — closed segments.  Rotation
  (size over ``mosaic.history.segment.bytes`` or age over
  ``mosaic.history.segment.age.ms``) finalizes the open segment via
  fsync + ``os.replace`` — the repo's atomic-publish convention — so
  a closed segment is never torn.  ``mosaic.history.retain`` caps how
  many closed segments survive (oldest dropped first).
* ``summary-<window>.json`` — compaction output: closed segments fold
  into one versioned summary record per ``mosaic.history.window.ms``
  time window (written tmp + fsync + ``os.replace``), then the
  segments are deleted.  Summaries carry per-operator wall-time
  histograms in the registry's exact exponential-bucket layout
  (:class:`~.metrics.Histogram`), so merging summaries — across
  windows, or across fleet workers (:func:`~.fleet.merge_history`) —
  reproduces p50/p95 **bit-for-bit** against a single store fed every
  record, the same exactness discipline as spool merging.

Degrade, not die: a torn or wrong-version segment (kill -9 mid-write,
alien build) degrades to a ``history_segment_torn`` recorder event +
``history/segments_torn`` counter — readers keep every record before
the tear and never raise; writers swallow ``OSError`` into
``history/write_errors`` so a full disk cannot fail a query.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .metrics import _NBUCKETS, Histogram, _bucket_of, metrics
from .recorder import recorder

__all__ = ["HISTORY_VERSION", "HistoryStore", "history",
           "history_record", "read_segment", "read_summary",
           "segment_paths", "summary_paths", "load_records",
           "new_summary", "fold_record", "merge_summary",
           "summarize_records", "summary_payload", "report",
           "window_diff"]

HISTORY_VERSION = 1

#: wall-time histograms in summaries bucket milliseconds — bucket 0
#: tops out at 1 us of wall, the range covers ~70 min per query
_WALL_SCALE = 1e-3

#: cost-vector fields summed per principal in a window summary
_COST_FIELDS = ("wall_ms", "device_s", "rows_in", "rows_out",
                "h2d_bytes", "d2h_bytes", "mem_peak_bytes", "compiles")

#: a window-vs-window p50/p95 regression past this fraction is flagged
SLIP_THRESHOLD = 0.20


def _note_torn(path: str, why: str) -> None:
    """The degrade path for anything unusable on disk: event +
    counter, never an exception."""
    recorder.record("history_segment_torn", path=path, why=why[:300])
    if metrics.enabled:
        metrics.count("history/segments_torn")


# ------------------------------------------------------------ file map

def segment_paths(directory: str) -> Tuple[List[str], List[str]]:
    """(closed segments sorted oldest-first, open segments) under
    ``directory`` — name order IS age order for closed segments (the
    rotation timestamp is zero-padded)."""
    allseg = glob.glob(os.path.join(directory, "history-*.jsonl"))
    opens = sorted(p for p in allseg if p.endswith(".open.jsonl"))
    closed = sorted(p for p in allseg if not p.endswith(".open.jsonl"))
    return closed, opens


def summary_paths(directory: str) -> List[str]:
    return sorted(glob.glob(os.path.join(directory, "summary-*.json")))


# ----------------------------------------------------------- segments

def read_segment(path: str) -> List[Dict[str, Any]]:
    """Every intact record in one segment.  Torn tails (a kill -9
    mid-append), torn headers, and alien versions degrade per
    :func:`_note_torn` — the records before a tear are kept, the loss
    is confined to what follows it."""
    recs: List[Dict[str, Any]] = []
    try:
        with open(path, encoding="utf-8") as fh:
            lines = fh.read().split("\n")
    except OSError as e:
        _note_torn(path, f"unreadable: {e}")
        return recs
    if not lines or not lines[0].strip():
        _note_torn(path, "empty segment (no header)")
        return recs
    try:
        header = json.loads(lines[0])
    except ValueError as e:
        _note_torn(path, f"torn header: {e}")
        return recs
    if not isinstance(header, dict) or \
            header.get("history") != HISTORY_VERSION:
        got = header.get("history") if isinstance(header, dict) \
            else header
        _note_torn(path, f"version {got!r} != {HISTORY_VERSION}")
        return recs
    for i, line in enumerate(lines[1:], start=2):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError as e:
            _note_torn(path, f"torn record at line {i}: {e}")
            break
        if isinstance(rec, dict):
            recs.append(rec)
    return recs


def load_records(directory: str) -> List[Dict[str, Any]]:
    """Raw per-query records from every segment (closed oldest-first,
    then open) — the ``mosaicstat top`` substrate.  Compacted records
    live only in summaries and are not returned here."""
    closed, opens = segment_paths(directory)
    out: List[Dict[str, Any]] = []
    for p in closed + opens:
        out.extend(read_segment(p))
    return out


# ---------------------------------------------------------- summaries

def _new_hist(scale: float = _WALL_SCALE) -> Dict[str, Any]:
    return {"scale": scale, "counts": [0] * _NBUCKETS,
            "count": 0, "sum": 0.0, "min": 0.0, "max": 0.0}


def _hist_observe(h: Dict[str, Any], v: float) -> None:
    v = float(v)
    h["counts"][_bucket_of(v, h["scale"])] += 1
    h["count"] += 1
    h["sum"] += v
    if h["count"] == 1:
        h["min"] = v
        h["max"] = v
    else:
        h["min"] = min(h["min"], v)
        h["max"] = max(h["max"], v)


def _hist_merge(dst: Dict[str, Any], src: Dict[str, Any]) -> None:
    """Bucket-wise sum — exact iff the layouts match (the fleet
    aggregator's contract); a mismatch raises for the caller's
    degrade path."""
    if float(src["scale"]) != float(dst["scale"]):
        raise ValueError(f"histogram scale {src['scale']} "
                         f"!= {dst['scale']}")
    counts = [int(c) for c in src["counts"]]
    if len(counts) != len(dst["counts"]):
        raise ValueError(f"{len(counts)} buckets "
                         f"!= {len(dst['counts'])}")
    for i, c in enumerate(counts):
        dst["counts"][i] += c
    n = int(src["count"])
    if n:
        dst["min"] = float(src["min"]) if dst["count"] == 0 \
            else min(dst["min"], float(src["min"]))
        dst["max"] = max(dst["max"], float(src["max"]))
    dst["count"] += n
    dst["sum"] += float(src["sum"])


def _as_histogram(name: str, h: Dict[str, Any]) -> Histogram:
    """Re-hydrate a summary histogram for exact percentile reads."""
    import math
    hh = Histogram(name, float(h["scale"]))
    hh.counts = [int(c) for c in h["counts"]]
    hh.count = int(h["count"])
    hh.sum = float(h["sum"])
    hh.min = float(h["min"]) if hh.count else math.inf
    hh.max = float(h["max"])
    return hh


def new_summary(window: Optional[int],
                window_ms: float) -> Dict[str, Any]:
    """An empty per-window summary record (``window`` None = the
    all-windows totals accumulator)."""
    return {
        "history": HISTORY_VERSION,
        "window": window,
        "window_ms": float(window_ms),
        "start_ts": 0.0,
        "end_ts": 0.0,
        "queries": 0,
        "outcomes": {},
        "principals": {},
        "operators": {},
        "strategies": {},
        "fusion_groups": {},
        "mispredicts": 0,
        "partitions": {},
    }


def fold_record(summary: Dict[str, Any], rec: Dict[str, Any]) -> None:
    """Fold one per-query record into a window summary."""
    cost = rec.get("cost") or {}
    ts = float(rec.get("end_ts") or rec.get("start_ts") or 0.0)
    if summary["queries"] == 0:
        summary["start_ts"] = ts
        summary["end_ts"] = ts
    else:
        summary["start_ts"] = min(summary["start_ts"], ts)
        summary["end_ts"] = max(summary["end_ts"], ts)
    summary["queries"] += 1
    outcome = str(rec.get("outcome", "ok"))
    summary["outcomes"][outcome] = \
        summary["outcomes"].get(outcome, 0) + 1
    p = str(rec.get("principal", "anonymous"))
    pt = summary["principals"].get(p)
    if pt is None:
        pt = summary["principals"][p] = dict(
            {"queries": 0}, **{f: 0 for f in _COST_FIELDS})
    pt["queries"] += 1
    for f in _COST_FIELDS:
        v = cost.get(f, 0)
        pt[f] = pt[f] + (float(v) if f in ("wall_ms", "device_s")
                         else int(v))
    op = str(rec.get("operator") or "-")
    h = summary["operators"].get(op)
    if h is None:
        h = summary["operators"][op] = _new_hist()
    _hist_observe(h, float(cost.get("wall_ms", 0.0)))
    for sop, strat in (rec.get("strategies") or {}).items():
        per = summary["strategies"].setdefault(str(sop), {})
        per[str(strat)] = per.get(str(strat), 0) + 1
    for g in rec.get("fusion_groups") or ():
        summary["fusion_groups"][str(g)] = \
            summary["fusion_groups"].get(str(g), 0) + 1
    summary["mispredicts"] += int(rec.get("mispredicts", 0))
    for cell, pv in (rec.get("partitions") or {}).items():
        e = summary["partitions"].get(str(cell))
        if e is None:
            e = summary["partitions"][str(cell)] = \
                {"queries": 0, "rows": 0, "bytes": 0}
        e["queries"] += 1
        e["rows"] += int((pv or {}).get("rows", 0))
        e["bytes"] += int((pv or {}).get("bytes", 0))


def merge_summary(dst: Dict[str, Any], src: Dict[str, Any]) -> None:
    """Exact summary merge: integer counters summed, histograms
    bucket-wise (raises ``ValueError`` on a layout mismatch — the
    caller degrades).  Merging N workers' summaries for one window
    reproduces the single-store summary's percentiles bit-for-bit."""
    if src.get("queries", 0):
        if dst["queries"] == 0:
            dst["start_ts"] = float(src["start_ts"])
            dst["end_ts"] = float(src["end_ts"])
        else:
            dst["start_ts"] = min(dst["start_ts"],
                                  float(src["start_ts"]))
            dst["end_ts"] = max(dst["end_ts"], float(src["end_ts"]))
    dst["queries"] += int(src.get("queries", 0))
    for o, n in (src.get("outcomes") or {}).items():
        dst["outcomes"][o] = dst["outcomes"].get(o, 0) + int(n)
    for p, pt in (src.get("principals") or {}).items():
        cur = dst["principals"].get(p)
        if cur is None:
            cur = dst["principals"][p] = dict(
                {"queries": 0}, **{f: 0 for f in _COST_FIELDS})
        cur["queries"] += int(pt.get("queries", 0))
        for f in _COST_FIELDS:
            v = pt.get(f, 0)
            cur[f] = cur[f] + (float(v) if f in ("wall_ms", "device_s")
                               else int(v))
    for op, h in (src.get("operators") or {}).items():
        cur = dst["operators"].get(op)
        if cur is None:
            dst["operators"][op] = {
                "scale": float(h["scale"]),
                "counts": [int(c) for c in h["counts"]],
                "count": int(h["count"]), "sum": float(h["sum"]),
                "min": float(h["min"]), "max": float(h["max"])}
        else:
            _hist_merge(cur, h)
    for sop, per in (src.get("strategies") or {}).items():
        cur = dst["strategies"].setdefault(sop, {})
        for strat, n in per.items():
            cur[strat] = cur.get(strat, 0) + int(n)
    for g, n in (src.get("fusion_groups") or {}).items():
        dst["fusion_groups"][g] = dst["fusion_groups"].get(g, 0) \
            + int(n)
    dst["mispredicts"] += int(src.get("mispredicts", 0))
    for cell, pv in (src.get("partitions") or {}).items():
        e = dst["partitions"].get(cell)
        if e is None:
            e = dst["partitions"][cell] = \
                {"queries": 0, "rows": 0, "bytes": 0}
        e["queries"] += int(pv.get("queries", 0))
        e["rows"] += int(pv.get("rows", 0))
        e["bytes"] += int(pv.get("bytes", 0))


def _window_of(rec: Dict[str, Any], window_ms: float) -> int:
    ts = float(rec.get("end_ts") or rec.get("start_ts") or 0.0)
    if window_ms <= 0:
        return 0
    return int(ts * 1e3 // window_ms)


def summarize_records(records: List[Dict[str, Any]],
                      window_ms: float) -> Dict[int, Dict[str, Any]]:
    """Window id -> summary for a record stream (the in-memory twin of
    compaction; the fleet-merge oracle tests run through this)."""
    out: Dict[int, Dict[str, Any]] = {}
    for rec in records:
        wid = _window_of(rec, window_ms)
        s = out.get(wid)
        if s is None:
            s = out[wid] = new_summary(wid, window_ms)
        fold_record(s, rec)
    return out


def summary_payload(s: Dict[str, Any]) -> Dict[str, Any]:
    """The JSON view of a summary: raw bucket arrays replaced with
    derived per-operator latency stats (p50/p95 exact to one bucket)."""
    ops = {}
    for op, h in sorted(s.get("operators", {}).items()):
        hh = _as_histogram(op, h)
        ops[op] = {
            "count": hh.count,
            "mean_ms": round(hh.sum / hh.count, 3) if hh.count else 0.0,
            "p50_ms": round(hh.percentile(50), 3),
            "p95_ms": round(hh.percentile(95), 3),
            "max_ms": round(hh.max, 3),
        }
    return {
        "window": s.get("window"),
        "window_ms": s.get("window_ms"),
        "start_ts": round(float(s.get("start_ts", 0.0)), 3),
        "end_ts": round(float(s.get("end_ts", 0.0)), 3),
        "queries": s.get("queries", 0),
        "outcomes": dict(sorted(s.get("outcomes", {}).items())),
        "principals": {p: dict(t) for p, t in
                       sorted(s.get("principals", {}).items())},
        "operators": ops,
        "strategies": {op: dict(sorted(per.items())) for op, per in
                       sorted(s.get("strategies", {}).items())},
        "fusion_groups": dict(sorted(
            s.get("fusion_groups", {}).items())),
        "mispredicts": s.get("mispredicts", 0),
        "partitions": {c: dict(v) for c, v in
                       sorted(s.get("partitions", {}).items(),
                              key=lambda kv: (-kv[1]["rows"],
                                              kv[0]))},
    }


def read_summary(path: str) -> Optional[Dict[str, Any]]:
    """One summary file, or None after the torn/alien degrade path."""
    try:
        with open(path, encoding="utf-8") as fh:
            s = json.load(fh)
    except (OSError, ValueError) as e:
        _note_torn(path, f"torn summary: {e}")
        return None
    if not isinstance(s, dict) or \
            s.get("history") != HISTORY_VERSION:
        got = s.get("history") if isinstance(s, dict) else s
        _note_torn(path, f"summary version {got!r} "
                         f"!= {HISTORY_VERSION}")
        return None
    return s


# ----------------------------------------------------------- reports

def _resolve_window_ms(window_ms: Optional[float]) -> float:
    if window_ms is not None:
        return float(window_ms)
    # env pin first (same contract as MOSAIC_TPU_HISTORY_DIR): a CI
    # lane or operator shell with no conf can still window a drill
    env = os.environ.get("MOSAIC_TPU_HISTORY_WINDOW_MS", "").strip()
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    from .. import config as _config
    return float(getattr(_config.default_config(),
                         "history_window_ms", 3_600_000.0))


def merged_windows(directory: str,
                   window_ms: Optional[float] = None
                   ) -> Dict[int, Dict[str, Any]]:
    """Window id -> exact summary over EVERYTHING in a history dir:
    on-disk summaries merged with raw segment records windowed at
    ``window_ms``.  Torn anything degrades (event + counter)."""
    window_ms = _resolve_window_ms(window_ms)
    windows: Dict[int, Dict[str, Any]] = {}
    for sp in summary_paths(directory):
        s = read_summary(sp)
        if s is None:
            continue
        wid = int(s.get("window", 0))
        cur = windows.get(wid)
        if cur is None:
            windows[wid] = s
        else:
            try:
                merge_summary(cur, s)
            except (KeyError, TypeError, ValueError) as e:
                _note_torn(sp, f"unmergeable summary: {e}")
    for wid, s in summarize_records(load_records(directory),
                                    window_ms).items():
        cur = windows.get(wid)
        if cur is None:
            windows[wid] = s
        else:
            try:
                merge_summary(cur, s)
            except (KeyError, TypeError, ValueError) as e:
                _note_torn(directory, f"unmergeable window {wid}: {e}")
    return windows


def report(directory: str,
           window_ms: Optional[float] = None) -> Dict[str, Any]:
    """The merged JSON view of one history dir: every window's payload
    (oldest first) plus all-windows totals."""
    windows = merged_windows(directory, window_ms)
    totals = new_summary(None, _resolve_window_ms(window_ms))
    for wid in sorted(windows):
        try:
            merge_summary(totals, windows[wid])
        except (KeyError, TypeError, ValueError) as e:
            _note_torn(directory, f"unmergeable window {wid}: {e}")
    return {
        "dir": directory,
        "windows": [summary_payload(windows[w])
                    for w in sorted(windows)],
        "totals": summary_payload(totals),
    }


def window_diff(a: Dict[str, Any],
                b: Dict[str, Any]) -> Dict[str, Any]:
    """Window-vs-window regression diff over two summary payloads
    (``a`` the baseline, ``b`` the candidate): per-operator p50/p95
    with the fractional slip, flagging operators past
    ``SLIP_THRESHOLD`` (+20%)."""
    ops: Dict[str, Any] = {}
    flagged: List[str] = []
    for op in sorted(set(a.get("operators", {}))
                     | set(b.get("operators", {}))):
        ah = a.get("operators", {}).get(op)
        bh = b.get("operators", {}).get(op)
        row: Dict[str, Any] = {
            "a_p50_ms": ah["p50_ms"] if ah else None,
            "b_p50_ms": bh["p50_ms"] if bh else None,
            "a_p95_ms": ah["p95_ms"] if ah else None,
            "b_p95_ms": bh["p95_ms"] if bh else None,
        }
        if ah and bh:
            for q in ("p50", "p95"):
                base = float(ah[f"{q}_ms"])
                cand = float(bh[f"{q}_ms"])
                slip = (cand - base) / base if base > 0 else 0.0
                row[f"slip_{q}"] = round(slip, 4)
            row["flagged"] = bool(
                row["slip_p50"] > SLIP_THRESHOLD or
                row["slip_p95"] > SLIP_THRESHOLD)
            if row["flagged"]:
                flagged.append(op)
        else:
            row["flagged"] = False
        ops[op] = row
    return {
        "a": a.get("window"),
        "b": b.get("window"),
        "a_queries": a.get("queries", 0),
        "b_queries": b.get("queries", 0),
        "threshold": SLIP_THRESHOLD,
        "operators": ops,
        "flagged": flagged,
    }


# -------------------------------------------------------- the writer

class HistoryStore:
    """One process's append side of a history directory (reads are
    module functions — any process may read or compact any dir).

    Rotation/retention/compaction knobs default to the live conf per
    call (``SET`` takes effect immediately); constructor overrides pin
    them for tests."""

    def __init__(self, directory: str, *,
                 segment_bytes: Optional[int] = None,
                 segment_age_ms: Optional[float] = None,
                 retain: Optional[int] = None,
                 window_ms: Optional[float] = None):
        self.directory = str(directory)
        self._segment_bytes = segment_bytes
        self._segment_age_ms = segment_age_ms
        self._retain = retain
        self._window_ms = window_ms
        self._lock = threading.Lock()
        self._fh = None
        self._open_bytes = 0
        self._opened_ts = 0.0
        self._rotations = 0

    # -- conf ---------------------------------------------------------
    def _cfg(self):
        from .. import config as _config
        return _config.default_config()

    def segment_bytes(self) -> int:
        if self._segment_bytes is not None:
            return int(self._segment_bytes)
        return int(getattr(self._cfg(), "history_segment_bytes",
                           1_048_576))

    def segment_age_ms(self) -> float:
        if self._segment_age_ms is not None:
            return float(self._segment_age_ms)
        return float(getattr(self._cfg(), "history_segment_age_ms",
                             0.0))

    def retain(self) -> int:
        if self._retain is not None:
            return int(self._retain)
        return int(getattr(self._cfg(), "history_retain", 64))

    def window_ms(self) -> float:
        if self._window_ms is not None:
            return float(self._window_ms)
        return _resolve_window_ms(None)

    # -- paths --------------------------------------------------------
    @property
    def open_path(self) -> str:
        return os.path.join(self.directory,
                            f"history-{os.getpid()}.open.jsonl")

    def _closed_path(self, ts: float) -> str:
        n = self._rotations          # callers hold self._lock
        path = os.path.join(
            self.directory,
            f"history-{int(ts * 1e3):013d}-{os.getpid()}-{n:04d}"
            ".jsonl")
        while os.path.exists(path):
            n += 1
            path = os.path.join(
                self.directory,
                f"history-{int(ts * 1e3):013d}-{os.getpid()}-{n:04d}"
                ".jsonl")
        return path

    # -- append -------------------------------------------------------
    def _ensure_open_locked(self):
        if self._fh is not None:
            return self._fh
        os.makedirs(self.directory, exist_ok=True)
        if os.path.exists(self.open_path):
            # a previous incarnation of this pid left an open segment
            # behind (crash, or pid reuse): publish it as closed so
            # its records survive and this run starts a fresh header
            self._publish_locked(self.open_path)
        now = time.time()
        fh = open(self.open_path, "w", encoding="utf-8")
        header = json.dumps({"history": HISTORY_VERSION,
                             "pid": os.getpid(), "opened_ts": now})
        fh.write(header + "\n")
        fh.flush()
        self._fh = fh
        self._open_bytes = len(header) + 1
        self._opened_ts = now
        return fh

    def _publish_locked(self, open_path: str) -> None:
        """fsync + atomic rename of an open segment to its closed
        name — after this a reader can never see it torn."""
        closed = self._closed_path(time.time())
        if self._fh is not None:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()
            self._fh = None
        else:
            fd = os.open(open_path, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        os.replace(open_path, closed)
        self._rotations += 1
        self._open_bytes = 0
        if metrics.enabled:
            metrics.count("history/segments_rotated")

    def append(self, record: Dict[str, Any]) -> None:
        """Append one completed-query record to the open segment,
        rotating first if the segment is over size or age.  Raises
        ``OSError`` on I/O trouble — the feed singleton downgrades it
        to a counter so queries never fail over history."""
        from ..resilience import faults
        faults.maybe_fail("history.write")
        line = json.dumps(record, default=str, sort_keys=True)
        with self._lock:
            fh = self._ensure_open_locked()
            age_ms = (time.time() - self._opened_ts) * 1e3
            max_age = self.segment_age_ms()
            if self._open_bytes + len(line) + 1 > self.segment_bytes() \
                    and self._open_bytes > 0 or \
                    (max_age > 0 and age_ms > max_age):
                self._publish_locked(self.open_path)
                self._enforce_retention_locked()
                fh = self._ensure_open_locked()
            fh.write(line + "\n")
            fh.flush()
            self._open_bytes += len(line) + 1
        if metrics.enabled:
            metrics.count("history/records_written")

    def rotate(self) -> Optional[str]:
        """Force-publish the open segment (bench round boundaries and
        tests); returns the closed path, or None with nothing open."""
        with self._lock:
            if self._fh is None and \
                    not os.path.exists(self.open_path):
                return None
            before = {p for p in segment_paths(self.directory)[0]}
            self._publish_locked(self.open_path)
            self._enforce_retention_locked()
            after = segment_paths(self.directory)[0]
            new = [p for p in after if p not in before]
            return new[-1] if new else None

    def _enforce_retention_locked(self) -> None:
        cap = self.retain()
        if cap <= 0:
            return
        closed, _ = segment_paths(self.directory)
        for p in closed[:max(0, len(closed) - cap)]:
            try:
                os.remove(p)
            except OSError:
                continue
            if metrics.enabled:
                metrics.count("history/segments_dropped")

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    # -- compaction ---------------------------------------------------
    def compact(self) -> Dict[str, Any]:
        """Fold every CLOSED segment into per-window summary files
        (tmp + fsync + ``os.replace``), then delete the segments.
        Open segments are untouched.  Torn segments contribute their
        readable prefix and are removed with the rest — their loss is
        already counted.  Returns compaction stats for bench."""
        window_ms = self.window_ms()
        closed, _ = segment_paths(self.directory)
        bytes_before = 0
        records = 0
        by_window: Dict[int, Dict[str, Any]] = {}
        for p in closed:
            try:
                bytes_before += os.path.getsize(p)
            except OSError:
                pass
            for rec in read_segment(p):
                records += 1
                wid = _window_of(rec, window_ms)
                s = by_window.get(wid)
                if s is None:
                    s = by_window[wid] = new_summary(wid, window_ms)
                fold_record(s, rec)
        bytes_after = 0
        for wid, s in sorted(by_window.items()):
            path = os.path.join(self.directory,
                                f"summary-{wid:013d}.json")
            if os.path.exists(path):
                prev = read_summary(path)
                if prev is not None:
                    try:
                        merge_summary(prev, s)
                        s = prev
                    except (KeyError, TypeError, ValueError) as e:
                        _note_torn(path, f"unmergeable summary: {e}")
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(s, fh, sort_keys=True)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            try:
                bytes_after += os.path.getsize(path)
            except OSError:
                pass
        for p in closed:
            try:
                os.remove(p)
            except OSError:
                continue
            if metrics.enabled:
                metrics.count("history/segments_compacted")
        return {"segments": len(closed), "records": records,
                "summaries": len(by_window),
                "bytes_before": bytes_before,
                "bytes_after": bytes_after}


# ----------------------------------------------------------- the feed

def history_record(record: Dict[str, Any],
                   ticket) -> Dict[str, Any]:
    """The audit completion record widened with the ticket's history
    columns: mispredict count, fusion groups run, partitions touched
    (rows read + bytes staged per store cell)."""
    hrec = dict(record)
    hrec["mispredicts"] = int(getattr(ticket, "mispredicts", 0) or 0)
    hrec["fusion_groups"] = [str(g) for g in
                             getattr(ticket, "fusion_groups", ()) or ()]
    parts = getattr(ticket, "partitions", None) or {}
    hrec["partitions"] = {str(c): {"rows": int(v[0]),
                                   "bytes": int(v[1])}
                          for c, v in parts.items()}
    return hrec


class HistoryFeed:
    """The conf-driven process singleton :func:`~.accounting.complete`
    writes through.  Re-resolves ``mosaic.history.dir`` (or the
    ``MOSAIC_TPU_HISTORY_DIR`` env pin) per record so ``SET`` takes
    effect immediately; "" keeps the plane off at one string check
    per completed query."""

    def __init__(self):
        self._lock = threading.Lock()
        self._dir: Optional[str] = None
        self._store: Optional[HistoryStore] = None
        self._write_errors = 0

    @staticmethod
    def _resolve_dir() -> str:
        env = os.environ.get("MOSAIC_TPU_HISTORY_DIR")
        if env is not None:
            return env.strip()
        from .. import config as _config
        return getattr(_config.default_config(), "history_dir",
                       "") or ""

    def directory(self) -> str:
        """The resolved history dir ("" = plane off)."""
        return self._resolve_dir()

    def store(self) -> Optional[HistoryStore]:
        d = self._resolve_dir()
        with self._lock:
            if not d:
                if self._store is not None:
                    self._store.close()
                    self._store = None
                    self._dir = None
                return None
            if self._store is None or self._dir != d:
                if self._store is not None:
                    self._store.close()
                self._store = HistoryStore(d)
                self._dir = d
            return self._store

    def record_completion(self, record: Dict[str, Any],
                          ticket) -> Optional[Dict[str, Any]]:
        """Write one completed query's history record; never raises
        (full disk / injected I/O faults land in
        ``history/write_errors``)."""
        st = self.store()
        if st is None:
            return None
        hrec = history_record(record, ticket)
        try:
            st.append(hrec)
        except OSError:
            with self._lock:
                self._write_errors += 1
            if metrics.enabled:
                metrics.count("history/write_errors")
            return None
        return hrec

    def write_errors(self) -> int:
        with self._lock:
            return self._write_errors

    def reset(self) -> None:
        with self._lock:
            if self._store is not None:
                self._store.close()
            self._store = None
            self._dir = None
            self._write_errors = 0


#: the process-global feed accounting.complete writes through
history = HistoryFeed()
