"""In-flight query registry: tickets, cooperative cancellation, deadlines.

Reference counterpart: the Spark UI's "running queries" pane plus
``spark.sparkContext.cancelJobGroup`` — the pair that makes a
multi-tenant service operable.  Standalone, ``SQLSession.sql()`` was a
fire-and-forget call: no identity, no deadline, no way to stop a
runaway query.  This module is the registry half of ROADMAP item 3's
metering arc (the enforcement half — quotas, admission control —
builds on it later).

Every query registers a :class:`QueryTicket` (query id, principal,
SQL text, start time, current operator, live row/byte counters) in
the process-global :class:`InflightRegistry` for its lifetime.
Cancellation is **cooperative**: :func:`cancel` (or an expired
``mosaic.query.deadline.ms`` deadline) only flags the ticket; the
running query observes the flag at its next :func:`checkpoint` — one
is placed at every engine operator boundary and between
``perf.pipeline.stream`` chunks — and raises :class:`QueryCancelled`
there.  Device work is never abandoned mid-launch: the streamed
executor drains its worker before the error propagates, so a
cancelled streamed query stops within one chunk boundary with no
leaked threads or device buffers.

Attribution rides the trace context (``obs.context``): the ticket is
keyed by its query's trace id, worker threads inherit the trace, so
kernel-ledger launch times and pipeline H2D bytes observed anywhere
under the query charge the right ticket (the per-principal meter in
``obs.accounting`` folds completed tickets).

Quiescent cost: one empty-dict check per probe when no query is
registered anywhere in the process; env ``MOSAIC_TPU_ACCOUNTING=0``
disables registration entirely (the bench overhead A/B's off arm).
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional

from .context import current_trace_id

__all__ = ["QueryCancelled", "QueryTicket", "InflightRegistry",
           "inflight", "checkpoint", "charge_device_seconds",
           "charge_h2d_bytes", "charge_d2h_bytes", "note_rows",
           "note_rows_in", "note_strategies", "note_mispredict",
           "note_fusion_group", "note_partitions",
           "note_partition_bytes", "note_refine", "ticket_observer"]

_qids = itertools.count(1)


class QueryCancelled(RuntimeError):
    """Raised inside a query at the first checkpoint after a cancel
    or deadline expiry.  Deliberately NOT a :class:`~..sql.engine.
    SQLError`: cancellation is an operator/deadline action, not a
    client mistake — the engine records it with its own outcome
    (``cancelled`` / ``deadline``) and bumps neither ``sql/errors``
    nor the client-error path."""

    def __init__(self, query_id: str, reason: str = "cancel"):
        self.query_id = query_id
        #: ``"cancel"`` (explicit cancel()) or ``"deadline"``
        self.reason = reason
        outcome = "deadline" if reason == "deadline" else "cancelled"
        super().__init__(f"query {query_id} {outcome} "
                         f"({'deadline exceeded' if reason == 'deadline' else 'cancel requested'})")

    @property
    def outcome(self) -> str:
        return "deadline" if self.reason == "deadline" else "cancelled"


class QueryTicket:
    """One registered query: identity + live progress counters.

    Mutated from multiple threads (the query's own, pipeline workers,
    the dashboard's cancel handler); every mutation is a single
    GIL-atomic attribute write or an int/float augmented assignment
    under the registry's read patterns — small races only smear live
    counters, never correctness."""

    def __init__(self, query_id: str, principal: str, sql: str,
                 trace_id: Optional[str], deadline_ms: float = 0.0):
        self.query_id = query_id
        self.principal = principal
        self.sql = sql
        self.trace_id = trace_id
        self.start_ts = time.time()
        self._t0 = time.perf_counter()
        #: absolute perf_counter deadline, or None
        self.deadline = (self._t0 + deadline_ms / 1e3
                         if deadline_ms and deadline_ms > 0 else None)
        self.operator = "-"          # current engine operator
        self.rows = 0                # rows out of the last stage
        self.rows_in = 0             # rows out of the scan/join stage
        self.compiles0 = 0.0         # jax/recompiles at registration
        self.h2d_bytes = 0           # pipeline staging charged here
        self.d2h_bytes = 0           # pipeline/fusion fetches charged
        self.device_s = 0.0          # kernel-ledger launch seconds
        self.mem_live_bytes = 0      # memwatch ledger: live right now
        self.mem_peak_bytes = 0      # memwatch ledger: high-water mark
        self.strategies: Dict[str, str] = {}   # planner picks per op
        self.mispredicts = 0         # planner estimates past the factor
        self.fusion_groups: List[str] = []     # fused groups executed
        #: store cells touched: cell -> [rows read, bytes staged] (the
        #: history record's partition-heat columns)
        self.partitions: Dict[int, List[int]] = {}
        #: adaptive-refinement counters (cells_refined / cells_flat /
        #: refined_points / flat_points), accumulated over every
        #: refined join the query ran — the cost vector's refine columns
        self.refine: Dict[str, int] = {}
        #: per-call refinement summaries: (operator at call time,
        #: summary string) — EXPLAIN ANALYZE's refine column
        self.refine_ops: List[tuple] = []
        self.status = "running"
        self._cancel_reason: Optional[str] = None

    # -- cooperative cancellation
    def request_cancel(self, reason: str = "cancel") -> None:
        if self._cancel_reason is None:
            self._cancel_reason = reason

    @property
    def cancel_requested(self) -> bool:
        return self._cancel_reason is not None

    def check(self) -> None:
        """Raise :class:`QueryCancelled` if flagged or past deadline."""
        if self._cancel_reason is not None:
            raise QueryCancelled(self.query_id, self._cancel_reason)
        if self.deadline is not None and \
                time.perf_counter() > self.deadline:
            self._cancel_reason = "deadline"
            raise QueryCancelled(self.query_id, "deadline")

    # -- reads
    @property
    def wall_ms(self) -> float:
        return (time.perf_counter() - self._t0) * 1e3

    def cost(self) -> Dict[str, object]:
        """The live cost vector (partial until the query completes)."""
        return {
            "wall_ms": round(self.wall_ms, 3),
            "device_s": round(self.device_s, 6),
            "rows": int(self.rows),
            "h2d_bytes": int(self.h2d_bytes),
            "d2h_bytes": int(self.d2h_bytes),
            "mem_live_bytes": int(self.mem_live_bytes),
            "mem_peak_bytes": int(self.mem_peak_bytes),
            "cells_refined": int(self.refine.get("cells_refined", 0)),
            "cells_flat": int(self.refine.get("cells_flat", 0)),
        }

    def snapshot(self) -> Dict[str, object]:
        """JSON-ready state for ``/api/queries``."""
        return {
            "query_id": self.query_id,
            "principal": self.principal,
            "sql": self.sql,
            "trace": self.trace_id,
            "start_ts": round(self.start_ts, 3),
            "status": self.status,
            "operator": self.operator,
            "cancel_requested": self.cancel_requested,
            "deadline_ms": round((self.deadline - self._t0) * 1e3, 1)
            if self.deadline is not None else 0.0,
            "cost": self.cost(),
        }


class InflightRegistry:
    """Process-global map of running queries, keyed by query id AND by
    trace id (the checkpoint/attribution lookup key)."""

    def __init__(self):
        env = os.environ.get("MOSAIC_TPU_ACCOUNTING", "").strip().lower()
        #: registration switch (``MOSAIC_TPU_ACCOUNTING=0`` = off —
        #: the bench overhead A/B's off arm); checks stay one empty-
        #: dict probe either way
        self.enabled = env not in ("0", "off", "false", "no")
        self._lock = threading.Lock()
        self._active: Dict[str, QueryTicket] = {}       # qid -> ticket
        self._by_trace: Dict[str, QueryTicket] = {}     # trace -> ticket

    # -- lifecycle
    def register(self, sql: str, principal: str = "anonymous",
                 deadline_ms: float = 0.0,
                 trace_id: Optional[str] = None) -> Optional[QueryTicket]:
        """Open a ticket (None when accounting is disabled).
        ``trace_id`` defaults to the active trace context's id."""
        if not self.enabled:
            return None
        if trace_id is None:
            trace_id = current_trace_id()
        t = QueryTicket(f"q{os.getpid()}-{next(_qids)}", principal,
                        sql, trace_id, deadline_ms)
        from .metrics import metrics
        t.compiles0 = metrics.counter_value("jax/recompiles")
        with self._lock:
            self._active[t.query_id] = t
            if trace_id is not None:
                self._by_trace[trace_id] = t
        if metrics.enabled:
            metrics.count("inflight/registered")
            metrics.gauge("inflight/active", float(len(self._active)))
        cb = getattr(_registration_observer, "cb", None)
        if cb is not None:
            # observer trouble must never fail the query it watches
            try:
                cb(t)
            except Exception:
                pass
        return t

    def finish(self, ticket: Optional[QueryTicket],
               status: str = "ok") -> None:
        """Close a ticket (idempotent; None passes through)."""
        if ticket is None:
            return
        ticket.status = status
        with self._lock:
            self._active.pop(ticket.query_id, None)
            if ticket.trace_id is not None and \
                    self._by_trace.get(ticket.trace_id) is ticket:
                self._by_trace.pop(ticket.trace_id, None)
        from .metrics import metrics
        if metrics.enabled:
            metrics.gauge("inflight/active", float(len(self._active)))

    # -- control
    def cancel(self, query_id: str, reason: str = "cancel") -> bool:
        """Flag a running query for cancellation; True if it was
        found in flight.  The query raises at its next checkpoint."""
        with self._lock:
            t = self._active.get(query_id)
        if t is None:
            return False
        t.request_cancel(reason)
        from .metrics import metrics
        if metrics.enabled:
            metrics.count("inflight/cancel_requests")
        from .recorder import recorder
        recorder.record("query_cancel_requested", query_id=query_id,
                        principal=t.principal, reason=reason)
        return True

    # -- reads
    def get(self, query_id: str) -> Optional[QueryTicket]:
        with self._lock:
            return self._active.get(query_id)

    def ticket_for_trace(self, trace_id: Optional[str]
                         ) -> Optional[QueryTicket]:
        if trace_id is None:
            return None
        return self._by_trace.get(trace_id)

    def list_active(self) -> List[Dict[str, object]]:
        with self._lock:
            tickets = list(self._active.values())
        return [t.snapshot() for t in
                sorted(tickets, key=lambda t: t.start_ts)]

    def __len__(self) -> int:
        return len(self._active)


#: the process-global registry every SQLSession.sql() call feeds
inflight = InflightRegistry()

#: thread-local ticket-registration observer (see ticket_observer)
_registration_observer = threading.local()


@contextlib.contextmanager
def ticket_observer(cb: Callable[[QueryTicket], None]) -> Iterator[None]:
    """Watch ticket registrations made on THIS thread.

    ``SQLSession.sql()`` opens its own trace and registers its own
    ticket, so a caller that needs the query id — the serve layer's
    per-request handler, which must route client disconnects and
    server deadlines into :meth:`InflightRegistry.cancel` — has no
    handle on it.  Inside this context every :meth:`~InflightRegistry.
    register` call on the current thread passes the fresh ticket to
    ``cb`` before any query work runs.  Thread-local by design:
    pipeline workers spawned by the query inherit its *trace*, not
    this hook, so nested streamed stages never re-observe.  Observer
    exceptions are swallowed (watching a query must not fail it)."""
    prev = getattr(_registration_observer, "cb", None)
    _registration_observer.cb = cb
    try:
        yield
    finally:
        _registration_observer.cb = prev


# ------------------------------------------------------------- probes
#
# Module-level helpers with the one-empty-dict-check quiescent cost.
# They key off the ACTIVE TRACE: worker threads inherit the spawning
# query's trace (obs.context.install_thread_propagation), so charges
# from pipeline workers land on the right ticket.

def _active_ticket() -> Optional[QueryTicket]:
    if not inflight._by_trace:          # quiescent fast path
        return None
    return inflight._by_trace.get(current_trace_id())


def checkpoint(operator: Optional[str] = None) -> None:
    """Cooperative cancellation probe: update the active ticket's
    current operator and raise :class:`QueryCancelled` if it was
    cancelled or blew its deadline.  No-op (one dict check) outside
    any registered query."""
    t = _active_ticket()
    if t is None:
        return
    if operator is not None:
        t.operator = operator
    t.check()


def charge_device_seconds(seconds: float) -> None:
    """Charge kernel-launch wall time to the active ticket (called
    from :meth:`~.profiler.KernelLedger.observe` — the trace join
    that gives the per-principal meter its device_s column)."""
    t = _active_ticket()
    if t is not None:
        t.device_s += float(seconds)


def charge_h2d_bytes(n: int) -> None:
    """Charge host->device staging bytes to the active ticket."""
    t = _active_ticket()
    if t is not None:
        t.h2d_bytes += int(n)


def charge_d2h_bytes(n: int) -> None:
    """Charge device->host fetch bytes to the active ticket (pipeline
    chunk drains and the fused group's one device_get — the same trace
    join the device-seconds charge uses)."""
    t = _active_ticket()
    if t is not None:
        t.d2h_bytes += int(n)


def note_rows(rows: int) -> None:
    """Record the latest stage's output rows on the active ticket."""
    t = _active_ticket()
    if t is not None:
        t.rows = int(rows)


def note_rows_in(rows: int) -> None:
    """Record the source stage's (scan/join) output rows — the audit
    record's rows_in column."""
    t = _active_ticket()
    if t is not None:
        t.rows_in = int(rows)


def note_strategies(strategies: Dict[str, str]) -> None:
    """Attach the planner's per-operator strategy picks to the active
    ticket (they land in the audit completion record)."""
    t = _active_ticket()
    if t is not None:
        t.strategies.update(strategies)


def note_refine(stats: Dict[str, int],
                summary: Optional[str] = None) -> None:
    """Accumulate one refined-join run's counters (``cells_refined``,
    ``cells_flat``, ``refined_points``, ``flat_points``) on the active
    ticket and, when ``summary`` is given, append it to the per-call
    refinement log under the operator the query is currently in — the
    EXPLAIN ANALYZE ``refine`` column's source."""
    t = _active_ticket()
    if t is None:
        return
    for k, v in dict(stats).items():
        try:
            t.refine[k] = t.refine.get(k, 0) + int(v)
        except (TypeError, ValueError):
            pass                      # non-scalar stats stay off the sum
    if summary:
        t.refine_ops.append((t.operator, str(summary)))


def note_mispredict() -> None:
    """Count one planner cardinality mispredict against the active
    ticket (the history record's planner-accuracy column)."""
    t = _active_ticket()
    if t is not None:
        t.mispredicts += 1


def note_fusion_group(name: str) -> None:
    """Record one fused-group execution on the active ticket."""
    t = _active_ticket()
    if t is not None:
        t.fusion_groups.append(str(name))


def note_partitions(spans) -> None:
    """Charge ``(cell, rows)`` store-read spans to the active ticket's
    partition ledger (the chip-store chunk/partition read paths)."""
    t = _active_ticket()
    if t is None:
        return
    for cell, rows in spans:
        e = t.partitions.get(cell)
        if e is None:
            t.partitions[cell] = [int(rows), 0]
        else:
            e[0] += int(rows)


def note_partition_bytes(by_cell) -> None:
    """Charge per-partition staged bytes (the store-fed join's
    ``staged_bytes_by_partition`` ledger) to the active ticket."""
    t = _active_ticket()
    if t is None:
        return
    for cell, nbytes in dict(by_cell).items():
        e = t.partitions.get(cell)
        if e is None:
            t.partitions[cell] = [0, int(nbytes)]
        else:
            e[1] += int(nbytes)
