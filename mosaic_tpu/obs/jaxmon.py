"""JAX runtime telemetry: JIT compile accounting + device memory peaks.

Two feeds, both recorded into ``obs.metrics``:

* **Compile events** via ``jax.monitoring`` listeners.  XLA's monitoring
  events are anonymous (no function names), so each backend compile is
  attributed to the tracer's innermost active host span at the moment it
  fires — e.g. a recompile triggered inside ``mc.call("st_area", ...)``
  lands on ``jax/recompiles/call/st_area``.  A per-label count crossing
  ``STORM_THRESHOLD`` flags a **recompile storm** (the classic ragged
  geometry-batch failure mode: every batch a new shape, every shape a
  new compile) with a one-shot warning plus a ``jax/recompile_storms``
  counter.
* **Memory watermarks** via ``Device.memory_stats()``.  TPU/GPU backends
  report allocator stats (``peak_bytes_in_use``); CPU backends return
  ``None``, in which case the host's peak RSS stands in so the gauge
  still exists on CPU runs (named ``mem/peak_bytes/<device>``, source
  recorded in ``mem/source/<device>``... see ``sample_memory``).

Listeners are process-global and idempotent to install; they cost one
attribute check per event while the registry is disabled.
"""

from __future__ import annotations

import threading
import warnings
from typing import Dict, Optional

from .metrics import metrics
from .recorder import recorder
from .tracer import tracer

__all__ = ["install_jax_listeners", "sample_memory", "STORM_THRESHOLD",
           "record_cost_analysis", "last_watermarks",
           "device_capacity"]

# a label re-compiling this many times is a storm (ragged batches)
STORM_THRESHOLD = 8

_BACKEND_COMPILE = "/jax/core/compile/backend_compile_duration"
_TRACE_DUR = "/jax/core/compile/jaxpr_trace_duration"
_LOWER_DUR = "/jax/core/compile/jaxpr_to_mlir_module_duration"

_install_lock = threading.Lock()
_installed = False
_storms_flagged = set()


def _on_duration(name: str, dur: float, **kw) -> None:
    if name == _BACKEND_COMPILE:
        # the flight recorder is on even with metrics off: a crash
        # bundle should show which compiles preceded the failure
        recorder.record("jax_compile",
                        label=tracer.current_label() or "<toplevel>",
                        dur_s=round(float(dur), 6))
    if not metrics.enabled:
        return
    if name == _BACKEND_COMPILE:
        label = tracer.current_label() or "<toplevel>"
        metrics.count("jax/recompiles")
        metrics.count(f"jax/recompiles/{label}")
        metrics.observe("jax/compile_s", dur)
        n = metrics.counter_value(f"jax/recompiles/{label}")
        if n >= STORM_THRESHOLD and label not in _storms_flagged:
            _storms_flagged.add(label)
            metrics.count("jax/recompile_storms")
            warnings.warn(
                f"recompile storm: {int(n)} XLA compiles attributed to "
                f"span {label!r} — likely ragged batch shapes; pad or "
                f"bucket inputs to stabilise shapes", RuntimeWarning,
                stacklevel=2)
    elif name == _TRACE_DUR:
        metrics.observe("jax/trace_s", dur)
    elif name == _LOWER_DUR:
        metrics.observe("jax/lower_s", dur)


def _on_event(name: str, **kw) -> None:
    if not metrics.enabled:
        return
    if name.startswith("/jax/compilation_cache/"):
        metrics.count(f"jax/cache/{name.rsplit('/', 1)[1]}")


def install_jax_listeners() -> bool:
    """Register the monitoring listeners once per process.  Returns True
    if this call performed the installation."""
    global _installed
    with _install_lock:
        if _installed:
            return False
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
        _installed = True
        return True


#: most recent sample_memory() result — the flight recorder embeds it
#: in dump bundles so a post-mortem shows the last known watermarks
#: even when the registry was disabled
_last_watermarks: Dict[str, Dict[str, Optional[float]]] = {}


def last_watermarks() -> Dict[str, Dict[str, Optional[float]]]:
    """The most recent :func:`sample_memory` result (``{}`` before the
    first sample)."""
    return dict(_last_watermarks)


def sample_memory(devices=None) -> Dict[str, Dict[str, Optional[float]]]:
    """Sample per-device memory watermarks into gauges.

    For each device, records ``mem/peak_bytes/<platform>:<id>`` (max-
    tracked, so repeated samples keep the high-water mark) and
    ``mem/source/<platform>:<id>`` (1 = allocator stats, 0 = host-RSS
    fallback), and returns the raw stats.  Devices without allocator
    stats (CPU) fall back to the process peak RSS; the ``source``
    field says which one you got.  The ``obs.timeseries`` sampler
    calls this on its cadence, so the gauges populate continuously on
    bench and SQL paths instead of only when called by hand.
    """
    import jax
    out: Dict[str, Dict[str, Optional[float]]] = {}
    host_peak = _host_peak_rss_bytes()
    for d in (devices if devices is not None else jax.devices()):
        key = f"{d.platform}:{d.id}"
        try:
            st = d.memory_stats()
        except Exception:
            st = None
        if st:
            peak = float(st.get("peak_bytes_in_use",
                                st.get("bytes_in_use", 0.0)))
            out[key] = {"peak_bytes": peak,
                        "bytes_in_use": float(st.get("bytes_in_use", 0.0)),
                        "source": "allocator"}
        else:
            peak = float(host_peak)
            out[key] = {"peak_bytes": peak, "bytes_in_use": None,
                        "source": "host_rss"}
        metrics.gauge_max(f"mem/peak_bytes/{key}", peak)
        metrics.gauge(f"mem/source/{key}",
                      1.0 if out[key]["source"] == "allocator" else 0.0)
    if host_peak:
        metrics.gauge_max("mem/host_peak_rss_bytes", float(host_peak))
    _last_watermarks.clear()
    _last_watermarks.update(out)
    return out


def record_cost_analysis(label: str, compiled) -> Dict[str, float]:
    """Record XLA cost-model figures of a compiled function as gauges.

    ``compiled`` is a ``jax.stages.Compiled``
    (``jit(f).lower(args).compile()``) or an already-extracted
    ``cost_analysis()`` result (plain dict, or the single-element list
    older jax versions return).  Records ``xla/<figure>/<label>``
    gauges (``flops``, ``bytes_accessed``, ``transcendentals``) plus a
    recorder event, and returns the figures — ``{}`` when the backend
    exposes no cost model, never raises.
    """
    try:
        ca = compiled.cost_analysis() \
            if hasattr(compiled, "cost_analysis") else compiled
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    out: Dict[str, float] = {}
    for key in ("flops", "bytes accessed", "transcendentals"):
        v = ca.get(key)
        if v is None:
            continue
        fig = key.replace(" ", "_")
        out[fig] = float(v)
        metrics.gauge(f"xla/{fig}/{label}", float(v))
    if out:
        recorder.record("xla_cost", label=label,
                        **{k: v for k, v in out.items()})
        try:
            # join the cost model into the kernel ledger: any ledger
            # row whose name matches this label gains flops/bytes (and
            # with observed launch times, derived gflops/s)
            from .profiler import ledger
            ledger.record_cost(label, out)
        except Exception:
            pass
    return out


def _host_peak_rss_bytes() -> int:
    try:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return 0


def _host_total_bytes() -> int:
    """Total physical host memory (the CPU-backend capacity stand-in);
    0 when the platform can't say."""
    try:
        import os
        return int(os.sysconf("SC_PHYS_PAGES")) * \
            int(os.sysconf("SC_PAGE_SIZE"))
    except (AttributeError, OSError, ValueError):
        return 0


_capacity_cache: Dict[str, float] = {}


def device_capacity(devices=None) -> Dict[str, float]:
    """Per-device memory capacity in bytes — the memwatch pressure
    denominator.  Allocator backends (TPU/GPU) report ``bytes_limit``
    in ``memory_stats()``; CPU backends fall back to total host RAM.
    Cached after the first full read (capacities are static)."""
    if devices is None and _capacity_cache:
        return dict(_capacity_cache)
    import jax
    host = float(_host_total_bytes())
    out: Dict[str, float] = {}
    for d in (devices if devices is not None else jax.devices()):
        key = f"{d.platform}:{d.id}"
        try:
            st = d.memory_stats()
        except Exception:
            st = None
        cap = float(st.get("bytes_limit", 0) or 0) if st else 0.0
        out[key] = cap if cap > 0 else host
    if devices is None:
        _capacity_cache.update(out)
    return out
