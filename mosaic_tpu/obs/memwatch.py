"""Device-memory plane: live-buffer ledger, budget, leak sentinel.

The obs stack attributes wall time (:class:`~.profiler.KernelLedger`),
rows, and transfer bytes per query — device *memory* was the blind
spot: ``jaxmon`` samples peak watermarks but nothing says which
query / kernel / chunk holds live bytes right now.  The ROADMAP's next
arc (admission control for a multi-tenant server, out-of-core joins)
needs exactly that signal, so this module tracks live device buffers
at the choke points the codebase already owns:

* ``perf.pipeline.stream`` — chunk staging (device_put) and kernel
  outputs, registered at dispatch and released when the host fetch
  completes;
* ``perf.jit_cache`` — every cached kernel's launch output, noted
  transiently (fetched-immediately buffers move peaks, not live);
* ``perf/fusion.py`` — the fused group's on-device intermediate
  between launch and its one D2H fetch;
* sharded ``parallel/pip_join`` — per-device shards (a sharded staged
  buffer splits its bytes across the mesh devices it lands on).

Everything is keyed ``(site, trace id, device)``.  Worker threads
inherit the query's trace (``obs.context``), so the ledger joins into
the :class:`~.inflight.QueryTicket` cost vector exactly the way the
KernelLedger's device-seconds do — per-query ``mem_live_bytes`` /
``mem_peak_bytes`` with zero extra plumbing.  Gauges:
``mem/live_bytes/<dev>``, ``mem/pressure/<dev>`` (live vs. device
capacity from ``jaxmon.device_capacity``), and the ``mem/pressure_max``
aggregate the ``device_mem_pressure`` SLO watches.

**Leak sentinel**: at query completion (``obs.accounting.complete``),
buffers still registered to that trace fire exactly one ``mem_leak``
flight-recorder event + one ``mem/leaks`` count — naming the worst
offending site — and are then force-released so the live gauges return
to zero (degrade-not-die: a lost release must not wedge the budget).
The ``memwatch.release`` fault site models a lost release for drills.

**MemoryBudget**: ``admit(estimated_bytes)`` gates work against
``mosaic.mem.budget.bytes`` (0 = unlimited) using the planner's
pre-pass byte estimate, and ``shrink_needed()`` tells
``pipeline.stream`` to halve chunk rows when any device's pressure
crosses ``mosaic.mem.pressure.high`` — the stream degrades instead of
dying, bit-for-bit identically (chunk boundaries are invisible in
results).

Kill switches: ``mosaic.obs.mem.enabled`` conf (default on) or env
``MOSAIC_TPU_MEMWATCH=0`` (the bench overhead A/B's off arm).
Quiescent cost per probe: one env-pinned bool plus one config read.
"""

from __future__ import annotations

import collections
import itertools
import os
import sys
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from .context import current_trace_id
from .inflight import inflight
from .metrics import metrics

__all__ = ["DeviceMemoryLedger", "MemoryBudget", "memwatch",
           "mem_budget", "device_keys_of"]


def _default_device() -> str:
    """The key buffers land on when the caller knows no better: the
    first visible jax device (never *initializes* a backend)."""
    if "jax" in sys.modules:
        try:
            import jax
            d = jax.devices()[0]
            return f"{d.platform}:{d.id}"
        except Exception:
            pass
    return "host:0"


def device_keys_of(tree) -> List[str]:
    """``platform:id`` keys for the device(s) holding a pytree's
    arrays — a sharded array contributes every device in its sharding.
    Empty list when nothing is device-backed (host numpy trees)."""
    if "jax" not in sys.modules:
        return []
    try:
        import jax
        devs = set()
        for leaf in jax.tree_util.tree_leaves(tree):
            getter = getattr(leaf, "devices", None)
            if callable(getter):
                try:
                    devs.update(getter())
                    continue
                except Exception:
                    pass
            d = getattr(leaf, "device", None)
            if d is not None and hasattr(d, "platform"):
                devs.add(d)
        return sorted(f"{d.platform}:{d.id}" for d in devs)
    except Exception:
        return []


class DeviceMemoryLedger:
    """Process-global live-buffer ledger keyed (site, trace, device).

    ``register`` returns an opaque token; ``release(token)`` balances
    it.  Mutations happen from query threads and the stream's fetch
    worker concurrently — every update runs under one lock, and the
    per-register work is a handful of dict ops (chunk-granular call
    sites, never per-row)."""

    def __init__(self):
        env = os.environ.get("MOSAIC_TPU_MEMWATCH", "").strip().lower()
        self._env_off = env in ("0", "off", "false", "no")
        self._lock = threading.Lock()
        self._tokens = itertools.count(1)
        # token -> (site, trace, devices tuple, per-device byte shares)
        self._handles: Dict[int, Tuple[str, Optional[str],
                                       Tuple[str, ...],
                                       Tuple[int, ...]]] = {}
        self._dev_live: Dict[str, int] = {}
        self._dev_peak: Dict[str, int] = {}
        self._by_key: Dict[Tuple[str, Optional[str], str], int] = {}
        self._key_peak: Dict[Tuple[str, Optional[str], str], int] = {}
        self._trace_live: Dict[str, int] = {}
        self._trace_peak: Dict[str, int] = {}
        self._trace_alloc: Dict[str, int] = {}
        self._capacity: Dict[str, float] = {}
        self._registered = 0
        self._released = 0
        self._release_skipped = 0
        self._leak_count = 0
        self._leaks: "collections.deque" = collections.deque(maxlen=64)

    @property
    def enabled(self) -> bool:
        """``mosaic.obs.mem.enabled`` (default on); env
        ``MOSAIC_TPU_MEMWATCH=0`` pins it off (the A/B off arm)."""
        if self._env_off:
            return False
        try:
            from .. import config as _config
            return bool(_config.default_config().obs_mem_enabled)
        except Exception:
            return True

    def reset(self) -> None:
        """Forget everything (tests); the env pin is kept."""
        with self._lock:
            self._handles.clear()
            self._dev_live.clear()
            self._dev_peak.clear()
            self._by_key.clear()
            self._key_peak.clear()
            self._trace_live.clear()
            self._trace_peak.clear()
            self._trace_alloc.clear()
            self._registered = 0
            self._released = 0
            self._release_skipped = 0
            self._leak_count = 0
            self._leaks.clear()

    # -- the write path ----------------------------------------------
    def register(self, site: str, nbytes: int,
                 devices: Optional[Iterable[str]] = None,
                 trace: Optional[str] = None) -> Optional[int]:
        """Track a live device buffer of ``nbytes`` at ``site``;
        returns the release token (None when disabled / empty, which
        :meth:`release` passes through).  ``devices`` splits the bytes
        evenly across a sharded buffer's devices; ``trace`` defaults to
        the calling thread's active trace."""
        nbytes = int(nbytes)
        if nbytes <= 0 or not self.enabled:
            return None
        if trace is None:
            trace = current_trace_id()
        devs = tuple(d for d in (devices or ()) if d) or \
            (_default_device(),)
        share = nbytes // len(devs)
        shares = [share] * len(devs)
        shares[0] += nbytes - share * len(devs)
        with self._lock:
            token = next(self._tokens)
            self._handles[token] = (site, trace, devs, tuple(shares))
            self._registered += 1
            for d, s in zip(devs, shares):
                live = self._dev_live.get(d, 0) + s
                self._dev_live[d] = live
                if live > self._dev_peak.get(d, 0):
                    self._dev_peak[d] = live
                k = (site, trace, d)
                kl = self._by_key.get(k, 0) + s
                self._by_key[k] = kl
                if kl > self._key_peak.get(k, 0):
                    self._key_peak[k] = kl
            if trace is not None:
                tl = self._trace_live.get(trace, 0) + nbytes
                self._trace_live[trace] = tl
                if tl > self._trace_peak.get(trace, 0):
                    self._trace_peak[trace] = tl
                self._trace_alloc[trace] = \
                    self._trace_alloc.get(trace, 0) + nbytes
                self._prune_traces_locked()
        self._after_change(trace)
        return token

    def release(self, token: Optional[int]) -> None:
        """Balance one :meth:`register`; None passes through.  The
        ``memwatch.release`` fault site models a *lost* release (the
        leak drill): an injected fault here keeps the buffer
        registered — the sentinel names it at query completion — and
        never propagates to the data path."""
        if token is None:
            return
        try:
            from ..resilience import faults
            faults.maybe_fail("memwatch.release")
        except ImportError:
            pass
        except Exception:
            with self._lock:
                self._release_skipped += 1
            if metrics.enabled:
                metrics.count("mem/release_skipped")
            return
        self._release_token(token)

    def _release_token(self, token: int):
        with self._lock:
            h = self._handles.pop(token, None)
            if h is None:
                return None
            site, trace, devs, shares = h
            self._released += 1
            for d, s in zip(devs, shares):
                self._dev_live[d] = max(0, self._dev_live.get(d, 0) - s)
                k = (site, trace, d)
                left = self._by_key.get(k, 0) - s
                if left <= 0:
                    self._by_key.pop(k, None)
                else:
                    self._by_key[k] = left
            if trace is not None and trace in self._trace_live:
                self._trace_live[trace] = \
                    max(0, self._trace_live[trace] - sum(shares))
        self._after_change(trace)
        return h

    def note_transient(self, site: str, nbytes: int,
                       trace: Optional[str] = None) -> None:
        """Account a fetched-immediately device buffer (a cached
        kernel's launch output): peaks and the per-trace allocation
        total move, live bytes do not — no token, nothing to leak."""
        nbytes = int(nbytes)
        if nbytes <= 0 or not self.enabled:
            return
        if trace is None:
            trace = current_trace_id()
        dev = _default_device()
        with self._lock:
            cand = self._dev_live.get(dev, 0) + nbytes
            if cand > self._dev_peak.get(dev, 0):
                self._dev_peak[dev] = cand
            k = (site, trace, dev)
            kc = self._by_key.get(k, 0) + nbytes
            if kc > self._key_peak.get(k, 0):
                self._key_peak[k] = kc
            tpeak = 0
            if trace is not None:
                tl = self._trace_live.get(trace, 0) + nbytes
                if tl > self._trace_peak.get(trace, 0):
                    self._trace_peak[trace] = tl
                tpeak = self._trace_peak[trace]
                self._trace_alloc[trace] = \
                    self._trace_alloc.get(trace, 0) + nbytes
                self._prune_traces_locked()
        if trace is not None and inflight._by_trace:
            t = inflight._by_trace.get(trace)
            if t is not None and tpeak > t.mem_peak_bytes:
                t.mem_peak_bytes = int(tpeak)

    # -- the leak sentinel -------------------------------------------
    def on_query_complete(self, ticket) -> int:
        """Close a query's memory books (called once per completion by
        ``obs.accounting.complete``): finalize the ticket's peak/live
        bytes, and if any buffer is still registered to the query's
        trace, fire exactly one ``mem_leak`` event + ``mem/leaks``
        count naming the worst site, then force-release the stragglers
        so live gauges return to zero.  Returns the leaked-buffer
        count."""
        if ticket is None or not self.enabled:
            return 0
        trace = getattr(ticket, "trace_id", None)
        if trace is None:
            return 0
        with self._lock:
            leaked = [(tok, h) for tok, h in self._handles.items()
                      if h[1] == trace]
        sites: Dict[str, int] = {}
        total = 0
        for tok, h in leaked:
            site, _, _, shares = h
            nb = int(sum(shares))
            total += nb
            sites[site] = sites.get(site, 0) + nb
            self._release_token(tok)
        with self._lock:
            peak = self._trace_peak.pop(trace, 0)
            self._trace_live.pop(trace, None)
            self._trace_alloc.pop(trace, None)
        if peak > getattr(ticket, "mem_peak_bytes", 0):
            ticket.mem_peak_bytes = int(peak)
        ticket.mem_live_bytes = 0
        if leaked:
            worst = max(sites, key=lambda s: sites[s])
            rec = {"ts": round(time.time(), 3), "trace": trace,
                   "query_id": ticket.query_id, "site": worst,
                   "sites": dict(sites), "bytes": total,
                   "buffers": len(leaked)}
            with self._lock:
                self._leak_count += 1
                self._leaks.append(rec)
            if metrics.enabled:
                metrics.count("mem/leaks")
            from .recorder import recorder
            recorder.record("mem_leak", trace=trace,
                            query_id=ticket.query_id, site=worst,
                            sites=dict(sites), bytes=total,
                            buffers=len(leaked))
        return len(leaked)

    # -- capacity / pressure -----------------------------------------
    def capacity(self, dev: str) -> float:
        """Best-known capacity of ``dev`` in bytes (allocator
        ``bytes_limit`` when the backend reports one, host RAM
        otherwise; 0.0 = unknown).  Cached — capacities are static."""
        cap = self._capacity.get(dev)
        if cap:
            return cap
        caps: Dict[str, float] = {}
        if "jax" in sys.modules:
            try:
                from .jaxmon import device_capacity
                caps = device_capacity()
            except Exception:
                caps = {}
        cap = float(caps.get(dev, 0.0))
        if cap <= 0:
            try:
                from .jaxmon import _host_total_bytes
                cap = float(_host_total_bytes())
            except Exception:
                cap = 0.0
        if cap > 0:
            with self._lock:
                self._capacity[dev] = cap
        return cap

    def effective_capacity(self, dev: str) -> float:
        """The pressure denominator: the configured budget when one is
        set (and smaller), else the device capacity."""
        try:
            from .. import config as _config
            b = float(_config.default_config().mem_budget_bytes)
        except Exception:
            b = 0.0
        cap = self.capacity(dev)
        if b > 0:
            return b if cap <= 0 else min(b, cap)
        return cap

    def pressure(self, dev: str,
                 live: Optional[int] = None) -> float:
        cap = self.effective_capacity(dev)
        if cap <= 0:
            return 0.0
        if live is None:
            with self._lock:
                live = self._dev_live.get(dev, 0)
        return float(live) / cap

    def max_pressure(self) -> float:
        with self._lock:
            devl = dict(self._dev_live)
        p = 0.0
        for d, v in devl.items():
            p = max(p, self.pressure(d, live=v))
        return p

    # -- reads --------------------------------------------------------
    def total_live(self) -> int:
        with self._lock:
            return int(sum(self._dev_live.values()))

    def live_bytes(self, dev: Optional[str] = None) -> int:
        with self._lock:
            if dev is not None:
                return int(self._dev_live.get(dev, 0))
            return int(sum(self._dev_live.values()))

    def live_by_device(self) -> Dict[str, int]:
        with self._lock:
            return {d: int(v) for d, v in self._dev_live.items()}

    def live_buffers(self) -> int:
        with self._lock:
            return len(self._handles)

    def trace_live_bytes(self, trace: Optional[str]) -> int:
        if trace is None:
            return 0
        with self._lock:
            return int(self._trace_live.get(trace, 0))

    def trace_peak_bytes(self, trace: Optional[str]) -> int:
        if trace is None:
            return 0
        with self._lock:
            return int(self._trace_peak.get(trace, 0))

    def current_trace_alloc_bytes(self) -> int:
        """Cumulative bytes registered/noted under the calling
        thread's trace — the EXPLAIN ANALYZE ``peak_bytes`` column
        diffs this around each stage."""
        tid = current_trace_id()
        if tid is None:
            return 0
        with self._lock:
            return int(self._trace_alloc.get(tid, 0))

    def leaks(self) -> List[Dict[str, object]]:
        with self._lock:
            return [dict(r) for r in self._leaks]

    def leak_count(self) -> int:
        with self._lock:
            return self._leak_count

    def snapshot(self, top: int = 20) -> Dict[str, object]:
        """JSON-ready ledger state: per-device live/peak/capacity/
        pressure, top live holders by (site, trace, device), site peak
        attribution, and the recent leak list — embedded in flight
        bundles and served at ``/api/memory``."""
        with self._lock:
            dev_live = dict(self._dev_live)
            dev_peak = dict(self._dev_peak)
            holders = sorted(self._by_key.items(),
                             key=lambda kv: -kv[1])[:top]
            site_peaks: Dict[str, int] = {}
            for (site, _, _), b in self._key_peak.items():
                site_peaks[site] = site_peaks.get(site, 0) + b
            leaks = [dict(r) for r in self._leaks]
            totals = {"live_bytes": int(sum(dev_live.values())),
                      "live_buffers": len(self._handles),
                      "registered": self._registered,
                      "released": self._released,
                      "release_skipped": self._release_skipped,
                      "leaks": self._leak_count}
        devices: Dict[str, Dict[str, object]] = {}
        for d in sorted(set(dev_live) | set(dev_peak)):
            cap = self.effective_capacity(d)
            live = int(dev_live.get(d, 0))
            devices[d] = {
                "live_bytes": live,
                "peak_bytes": int(dev_peak.get(d, 0)),
                "capacity_bytes": int(cap),
                "pressure": round(live / cap, 6) if cap > 0 else 0.0,
            }
        return {
            "enabled": self.enabled,
            "devices": devices,
            "holders": [{"site": s, "trace": t, "device": d,
                         "bytes": int(b)}
                        for (s, t, d), b in holders],
            "site_peak_bytes": {s: int(b)
                                for s, b in sorted(site_peaks.items())},
            "leaks": leaks,
            "totals": totals,
        }

    # -- internals ----------------------------------------------------
    def _after_change(self, trace: Optional[str]) -> None:
        """Refresh gauges + the owning ticket after any live-bytes
        move (outside the ledger lock)."""
        if metrics.enabled:
            with self._lock:
                devl = dict(self._dev_live)
            pmax = 0.0
            for d, v in devl.items():
                metrics.gauge(f"mem/live_bytes/{d}", float(v))
                p = self.pressure(d, live=v)
                metrics.gauge(f"mem/pressure/{d}", p)
                pmax = max(pmax, p)
            metrics.gauge("mem/pressure_max", pmax)
        if trace is not None and inflight._by_trace:
            t = inflight._by_trace.get(trace)
            if t is not None:
                with self._lock:
                    live = self._trace_live.get(trace, 0)
                    peak = self._trace_peak.get(trace, 0)
                t.mem_live_bytes = int(live)
                if peak > t.mem_peak_bytes:
                    t.mem_peak_bytes = int(peak)

    def _prune_traces_locked(self) -> None:
        # traces that never complete (non-query work) would grow the
        # side tables forever; drop the oldest quarter past 1024
        if len(self._trace_alloc) > 1024:
            for k in list(itertools.islice(iter(self._trace_alloc),
                                           256)):
                self._trace_alloc.pop(k, None)
                self._trace_live.pop(k, None)
                self._trace_peak.pop(k, None)


class MemoryBudget:
    """Admission + degrade decisions over the ledger.

    ``mosaic.mem.budget.bytes`` (0 = unlimited) caps what the process
    should hold live on device; ``mosaic.mem.pressure.high`` (default
    0.85) is the fraction of the effective capacity past which the
    streaming executor halves chunk rows (``mem/chunk_shrink``)."""

    def __init__(self, ledger: DeviceMemoryLedger):
        self._ledger = ledger

    @staticmethod
    def budget_bytes() -> int:
        try:
            from .. import config as _config
            return int(_config.default_config().mem_budget_bytes)
        except Exception:
            return 0

    @staticmethod
    def pressure_high() -> float:
        try:
            from .. import config as _config
            return float(_config.default_config().mem_pressure_high)
        except Exception:
            return 0.85

    def admit(self, estimated_bytes: int) -> bool:
        """True when ``estimated_bytes`` more device bytes fit under
        the budget (always, when no budget is set).  A denial is
        advisory — callers degrade (shrink chunks, queue) rather than
        fail; it is counted (``mem/admit_denied``) and flight-recorded
        so the admission-control arc has ground truth."""
        b = self.budget_bytes()
        if b <= 0 or not self._ledger.enabled:
            return True
        est = max(0, int(estimated_bytes))
        live = self._ledger.total_live()
        if live + est <= b:
            return True
        if metrics.enabled:
            metrics.count("mem/admit_denied")
        from .recorder import recorder
        recorder.record("mem_admit_denied", estimated_bytes=est,
                        live_bytes=live, budget_bytes=b)
        return False

    def shrink_needed(self) -> bool:
        """True when any device's pressure is at/over the high-water
        fraction — the stream's cue to halve its next chunk."""
        if not self._ledger.enabled:
            return False
        hi = self.pressure_high()
        return hi > 0 and self._ledger.max_pressure() >= hi


#: the process-global ledger every choke point feeds
memwatch = DeviceMemoryLedger()
#: the budget consulted by pipeline.stream and the SQL admission check
mem_budget = MemoryBudget(memwatch)
