"""Metrics registry: counters, gauges, exponential-bucket histograms.

Reference counterpart: Mosaic leans on the Spark UI / Dropwizard metric
sinks for runtime counters; standalone on JAX we keep a process-global
registry the rest of the package records into.  Three instrument kinds:

* **counter** — monotonically accumulating float (bytes moved, rejects,
  recompiles).
* **gauge** — last-value or max-tracked float (shard skew, HBM peak).
* **histogram** — exponential buckets, 4 per power of two (~19% relative
  bucket width), so p50/p95/p99 are derivable to within one bucket.

Everything is thread-safe and costs one attribute check per call when
the registry is disabled (the hot-path contract shared with
``obs.tracer``).  Enable with ``MOSAIC_TPU_METRICS=1`` (or
``MOSAIC_TPU_TRACE=1``, which implies it) or ``metrics.enable()``.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Dict, List, Optional

__all__ = ["Histogram", "MetricsRegistry", "metrics"]

_NBUCKETS = 128
_PER_OCTAVE = 4           # buckets per power of two
_DEF_SCALE = 1e-6         # upper bound of bucket 0 (1 us for seconds)
_LOG2 = math.log(2.0)


def _bucket_of(value: float, scale: float) -> int:
    if value <= scale:
        return 0
    i = int(math.log(value / scale) / _LOG2 * _PER_OCTAVE) + 1
    return i if i < _NBUCKETS else _NBUCKETS - 1


def _bucket_upper(i: int, scale: float) -> float:
    return scale * 2.0 ** (i / _PER_OCTAVE)


class Histogram:
    """Fixed-size exponential-bucket histogram.

    With 128 buckets at 4/octave and the default 1 us scale the range
    covers 1 us .. ~4300 s before the overflow bucket — every host span
    this package times.  ``scale`` can be raised for non-time units.
    """

    __slots__ = ("name", "scale", "counts", "count", "sum", "min", "max")

    def __init__(self, name: str, scale: float = _DEF_SCALE):
        self.name = name
        self.scale = scale
        self.counts: List[int] = [0] * _NBUCKETS
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        self.counts[_bucket_of(v, self.scale)] += 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def percentile(self, q: float) -> float:
        """Value at quantile ``q`` (percent), exact to one bucket width."""
        if self.count == 0:
            return 0.0
        target = max(1, math.ceil(self.count * q / 100.0))
        run = 0
        for i, c in enumerate(self.counts):
            run += c
            if run >= target:
                return min(_bucket_upper(i, self.scale), self.max)
        return self.max

    def snapshot(self) -> Dict[str, float]:
        if self.count == 0:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                    "mean": 0.0, "p50": 0.0, "p95": 0.0, "p99": 0.0}
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "mean": self.sum / self.count,
            "p50": self.percentile(50),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
        }


class MetricsRegistry:
    """Process-global counters / gauges / histograms, thread-safe,
    one attribute check per call when disabled."""

    def __init__(self):
        self._enabled = bool(os.environ.get("MOSAIC_TPU_METRICS")
                             or os.environ.get("MOSAIC_TPU_TRACE"))
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Histogram] = {}

    # -- switches
    def enable(self) -> None:
        # graftlint: ignore[lock-unguarded-attr] — GIL-atomic bool store; probes read it unlocked by design
        self._enabled = True

    def disable(self) -> None:
        # graftlint: ignore[lock-unguarded-attr] — GIL-atomic bool store; probes read it unlocked by design
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()

    # -- counters
    def count(self, name: str, value: float = 1.0) -> None:
        if not self._enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def counter_value(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    # -- gauges
    def gauge(self, name: str, value: float) -> None:
        if not self._enabled:
            return
        with self._lock:
            self._gauges[name] = float(value)

    def gauge_max(self, name: str, value: float) -> None:
        if not self._enabled:
            return
        with self._lock:
            prev = self._gauges.get(name)
            if prev is None or value > prev:
                self._gauges[name] = float(value)

    def gauge_value(self, name: str) -> Optional[float]:
        with self._lock:
            return self._gauges.get(name)

    # -- histograms
    def observe(self, name: str, value: float,
                scale: float = _DEF_SCALE) -> None:
        if not self._enabled:
            return
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(name, scale)
            h.observe(value)

    def histogram(self, name: str) -> Optional[Histogram]:
        with self._lock:
            return self._hists.get(name)

    def percentile(self, name: str, q: float) -> float:
        with self._lock:
            h = self._hists.get(name)
        return h.percentile(q) if h is not None else 0.0

    def histograms(self) -> Dict[str, Histogram]:
        """Live histogram objects by name (the OpenMetrics exporter
        needs raw bucket counts, not the percentile snapshot)."""
        with self._lock:
            return dict(self._hists)

    # -- export
    def to_openmetrics(self) -> str:
        """Prometheus/OpenMetrics text exposition of the registry
        (see ``obs.openmetrics``)."""
        from .openmetrics import to_openmetrics
        return to_openmetrics(self)

    # -- reporting
    def report(self) -> Dict[str, object]:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {n: h.snapshot()
                               for n, h in self._hists.items()},
            }

    def full_snapshot(self) -> Dict[str, object]:
        """Consistent raw-state copy for the fleet spool: unlike
        :meth:`report` the histograms carry their BUCKET COUNTS, so an
        aggregator can merge N processes bucket-wise and reproduce
        p50/p95/p99 exactly (identical exponential buckets everywhere
        — same ``_NBUCKETS``/``_PER_OCTAVE``; only ``scale`` varies
        per histogram and travels in the snapshot).  One lock hold for
        the whole copy: no torn counter-vs-histogram view."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {
                    n: {"scale": h.scale,
                        "counts": list(h.counts),
                        "count": h.count,
                        "sum": h.sum,
                        "min": h.min if h.count else 0.0,
                        "max": h.max}
                    for n, h in self._hists.items()},
            }


metrics = MetricsRegistry()
