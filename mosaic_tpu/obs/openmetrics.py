"""OpenMetrics / Prometheus text exposition + stdlib scrape endpoint.

Reference counterpart: the reference's runtime counters surface through
Spark's Dropwizard metric sinks (JMX/Prometheus servlet); standalone we
render the ``obs.metrics`` registry in the Prometheus text exposition
format so any Prometheus/OpenMetrics scraper ingests it unchanged:

* counters  -> ``mosaic_<name>_total``
* gauges    -> ``mosaic_<name>``
* histograms -> cumulative ``_bucket{le="..."}`` series (the registry's
  exponential buckets, non-empty ones only, plus ``+Inf``), ``_count``,
  ``_sum``

Metric names are sanitized to ``[a-zA-Z0-9_]`` under a ``mosaic_``
namespace prefix (``sql/scan_s`` -> ``mosaic_sql_scan_s``).

Per-principal accounting series (``principal/<field>/<principal>``
from ``obs.accounting``) render as ONE labeled family per field —
``mosaic_principal_<field>_total{principal="..."}`` — instead of one
sanitized name per tenant, so a scraper can aggregate/alert across
principals with plain label matchers.  Principal names are free-form
user input, so label values (and HELP text) are escaped per the
Prometheus text format: ``\\`` -> ``\\\\``, ``"`` -> ``\\"``, newline
-> ``\\n``.

:func:`serve_metrics` starts a stdlib-only ``ThreadingHTTPServer`` on a
daemon thread serving ``GET /metrics`` — no third-party client library,
matching the package's no-new-deps rule.
"""

from __future__ import annotations

import http.server
import math
import re
import threading
from typing import List, Optional

from .metrics import MetricsRegistry, _bucket_upper, metrics

__all__ = ["to_openmetrics", "fleet_to_openmetrics", "serve_metrics",
           "ServerHandle"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

#: Prometheus content type for the text exposition format
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _sanitize(name: str) -> str:
    s = _NAME_RE.sub("_", name)
    if not s or s[0].isdigit():
        s = "_" + s
    return "mosaic_" + s


def _fmt(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    return f"{float(v):.10g}"


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping (backslash first —
    it is the escape character itself)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _escape_help(v: str) -> str:
    """HELP-line escaping: only ``\\`` and newline are special there
    (quotes are literal in HELP text)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _split_principal(name: str):
    """``principal/<field>/<principal>`` -> (field, principal), else
    None.  maxsplit keeps any further ``/`` inside the principal."""
    parts = name.split("/", 2)
    if len(parts) == 3 and parts[0] == "principal":
        return parts[1], parts[2]
    return None


def _principal_family(lines: List[str], field: str, kind: str,
                      samples) -> None:
    m = _sanitize(f"principal_{field}")
    if kind == "counter":
        m += "_total"
    lines.append(f"# HELP {m} " + _escape_help(
        f"Per-principal {field} from the query accounting plane "
        "(obs.accounting)."))
    lines.append(f"# TYPE {m} {kind}")
    for principal, v in sorted(samples):
        lines.append(
            f'{m}{{principal="{_escape_label_value(principal)}"}}'
            f' {_fmt(v)}')


def to_openmetrics(registry: Optional[MetricsRegistry] = None) -> str:
    """Render a registry (default: the process-global one) in the
    Prometheus text exposition format, terminated by ``# EOF``."""
    reg = registry if registry is not None else metrics
    rep = reg.report()
    lines: List[str] = []
    principals: dict = {}      # (field, kind) -> [(principal, value)]
    for name, v in sorted(rep["counters"].items()):
        hit = _split_principal(name)
        if hit is not None:
            principals.setdefault((hit[0], "counter"), []) \
                .append((hit[1], v))
            continue
        m = _sanitize(name) + "_total"
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {_fmt(v)}")
    for name, v in sorted(rep["gauges"].items()):
        hit = _split_principal(name)
        if hit is not None:
            principals.setdefault((hit[0], "gauge"), []) \
                .append((hit[1], v))
            continue
        m = _sanitize(name)
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m} {_fmt(v)}")
    for (field, kind), samples in sorted(principals.items()):
        _principal_family(lines, field, kind, samples)
    for name, h in sorted(reg.histograms().items()):
        m = _sanitize(name)
        lines.append(f"# TYPE {m} histogram")
        cum = 0
        for i, c in enumerate(h.counts):
            if c:
                cum += c
                le = _fmt(_bucket_upper(i, h.scale))
                lines.append(f'{m}_bucket{{le="{le}"}} {cum}')
        lines.append(f'{m}_bucket{{le="+Inf"}} {h.count}')
        lines.append(f"{m}_count {h.count}")
        lines.append(f"{m}_sum {_fmt(h.sum)}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def fleet_to_openmetrics(view) -> str:
    """Render a :class:`~.fleet.FleetView` as one exposition: counters
    and gauges become labeled families with one ``{worker="<pid>"}``
    sample per contributing worker (a scraper re-derives the fleet sum
    / max with plain label aggregation — and can tell WHICH worker is
    hot), while histograms render from the aggregator's bucket-wise
    EXACT merge, unlabeled: per-worker quantiles still live on each
    worker's own ``/metrics``, but a fleet p99 computed any other way
    would be an approximation.  Stale workers keep their counter
    samples (completed work stands) and lose their gauge samples,
    mirroring the aggregator's merge rules."""
    lines: List[str] = []
    counters: dict = {}        # name -> [(pid, value)]
    gauges: dict = {}
    for w in view.workers:
        if not w.readable:
            continue
        reg = (w.snapshot or {}).get("metrics", {})
        for name, v in reg.get("counters", {}).items():
            counters.setdefault(name, []).append((w.pid, v))
        if not w.stale:
            for name, v in reg.get("gauges", {}).items():
                gauges.setdefault(name, []).append((w.pid, v))
    for name, samples in sorted(counters.items()):
        m = _sanitize(name) + "_total"
        lines.append(f"# TYPE {m} counter")
        for pid, v in sorted(samples):
            lines.append(f'{m}{{worker="{pid}"}} {_fmt(v)}')
    for name, samples in sorted(gauges.items()):
        m = _sanitize(name)
        lines.append(f"# TYPE {m} gauge")
        for pid, v in sorted(samples):
            lines.append(f'{m}{{worker="{pid}"}} {_fmt(v)}')
    for name, h in sorted(view.histograms.items()):
        m = _sanitize(name)
        lines.append(f"# TYPE {m} histogram")
        cum = 0
        for i, c in enumerate(h.counts):
            if c:
                cum += c
                le = _fmt(_bucket_upper(i, h.scale))
                lines.append(f'{m}_bucket{{le="{le}"}} {cum}')
        lines.append(f'{m}_bucket{{le="+Inf"}} {h.count}')
        lines.append(f"{m}_count {h.count}")
        lines.append(f"{m}_sum {_fmt(h.sum)}")
    lines.append("# TYPE mosaic_fleet_workers gauge")
    lines.append(f"mosaic_fleet_workers {len(view.workers)}")
    lines.append("# TYPE mosaic_fleet_stale_workers gauge")
    lines.append("mosaic_fleet_stale_workers "
                 f"{sum(1 for w in view.workers if w.stale)}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


class ServerHandle:
    """A started HTTP endpoint you can actually stop.

    Wraps the ``ThreadingHTTPServer`` + its serve thread; ``close()``
    shuts the server down, closes the listening socket and joins the
    thread.  ``shutdown()`` / ``server_close()`` / ``server_address``
    are kept as aliases so existing callers of the raw server keep
    working; the handle is also a context manager."""

    def __init__(self, server, thread):
        self._server = server
        self._thread = thread
        self._closed = False

    @property
    def server_address(self):
        return self._server.server_address

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._server.shutdown()
        self._server.server_close()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    # raw-server compat: callers used server.shutdown();
    # server.server_close() as the teardown pair
    def shutdown(self) -> None:
        if not self._closed:
            self._server.shutdown()

    def server_close(self) -> None:
        self.close()

    def __enter__(self) -> "ServerHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def start_server(handler_cls, port: int, addr: str,
                 name: str) -> ServerHandle:
    """Spin a ``ThreadingHTTPServer`` on a named daemon thread and
    return the stoppable handle (shared by the metrics endpoint and
    the ops dashboard)."""
    server = http.server.ThreadingHTTPServer((addr, port), handler_cls)
    thread = threading.Thread(target=server.serve_forever,
                              name=name, daemon=True)
    thread.start()
    return ServerHandle(server, thread)


def serve_metrics(port: int = 9464, addr: str = "127.0.0.1",
                  registry: Optional[MetricsRegistry] = None
                  ) -> ServerHandle:
    """Start a scrape endpoint on a daemon thread; returns a
    :class:`ServerHandle`.

    ``GET /metrics`` (or ``/``) answers with :func:`to_openmetrics` at
    scrape time.  Pass ``port=0`` for an ephemeral port — the bound one
    is ``handle.port``.  Stop with ``handle.close()``.
    """

    class _Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.split("?", 1)[0] not in ("/", "/metrics"):
                self.send_error(404)
                return
            body = to_openmetrics(registry).encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # scrapes must not spam stderr
            pass

    return start_server(_Handler, port, addr, "mosaic-metrics-http")
