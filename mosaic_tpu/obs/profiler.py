"""Continuous profiling plane: host sampler, kernel ledger, capture.

The telemetry plane (metrics / time-series / SLO burn rates) answers
*that* a query is slow; this module answers *where the time went*.
Three capture modes share one report format:

* **Sampling host profiler** — :class:`HostProfiler`, a daemon thread
  walking ``sys._current_frames()`` at ``mosaic.obs.profile.hz``
  (env ``MOSAIC_TPU_PROFILE_HZ`` pins it; 0 = off, the production
  default — bench.py turns it on for every run).  Samples fold into
  collapsed-stack counts keyed by the active trace context of the
  sampled thread (``obs.context`` keeps a thread-ident → trace side
  table, because a ``ContextVar`` is not readable from another
  thread), so two interleaved SQL queries get disjoint profiles.
* **Per-kernel device-cost ledger** — :class:`KernelLedger`, keyed by
  the same ``(name, key)`` pairs as ``perf.jit_cache.kernel_cache``.
  The streaming executor and the sharded join feed observed per-chunk
  launch wall-times (dispatch → host fetch complete, clamped to the
  previous chunk's completion so spans never overlap);
  ``obs.jaxmon.record_cost_analysis`` feeds XLA flops/bytes figures.
  The join lets EXPLAIN ANALYZE and bench records attribute device
  time to named kernels per size-bucket.
* **Triggered capture** — flight-recorder bundles embed
  :func:`capture_snapshot` (host stacks + ledger), so SLO breaches
  and slow-query dumps carry a profile automatically; when
  ``mosaic.obs.profile.trace.ms`` > 0, :func:`maybe_device_capture`
  additionally records a bounded ``jax.profiler`` timeline via the
  existing ``tracer.device_trace``.

Exports: :meth:`HostProfiler.collapsed` (Brendan-Gregg collapsed-stack
text, ``flamegraph.pl``-ready) and :meth:`HostProfiler.speedscope`
(https://www.speedscope.app JSON).  The ops dashboard serves both
(``/api/profile`` + the ``/profile`` flamegraph view).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["HostProfiler", "KernelLedger", "ledger", "profiler",
           "start_profiler", "stop_profiler", "configure_profiler",
           "capture_snapshot", "maybe_device_capture",
           "DEFAULT_PROFILE_HZ"]

#: cadence used when the profiler is enabled without an explicit rate.
#: 97 Hz (prime) avoids phase-locking with the 500 ms telemetry
#: sampler and with millisecond-periodic workloads.
DEFAULT_PROFILE_HZ = 97.0

_MAX_STACKS = 10_000       # distinct (trace, stack) keys before drops
_MAX_DEPTH = 64            # frames kept per sample (deepest dropped)
_SNAPSHOT_STACKS = 200     # stacks embedded per flight bundle


def _frame_label(code) -> str:
    return f"{os.path.basename(code.co_filename)}:{code.co_name}"


class HostProfiler:
    """Sampling profiler over ``sys._current_frames()``.

    ``sample()`` is one pass (callable directly from tests);
    ``start()`` runs it on a daemon thread at ``hz``.  Aggregation is
    bounded: at most ``max_stacks`` distinct (trace, stack) keys are
    retained — overflow lands in ``truncated`` instead of growing
    memory.  The sampling thread itself (and, on inline calls, the
    calling thread) is excluded from its own samples.
    """

    def __init__(self, hz: float = DEFAULT_PROFILE_HZ,
                 max_stacks: int = _MAX_STACKS,
                 max_depth: int = _MAX_DEPTH):
        self.hz = min(1000.0, max(0.5, float(hz)))
        self.max_stacks = int(max_stacks)
        self.max_depth = int(max_depth)
        self.samples = 0
        self.truncated = 0
        self._lock = threading.Lock()
        # (trace_id | None, root-first frame tuple) -> sample count
        self._stacks: Dict[Tuple[Optional[str], Tuple[str, ...]], int] = {}
        self._trace_names: Dict[str, str] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="mosaic-obs-profiler", daemon=True)

    # -- lifecycle (mirrors timeseries.Sampler)
    def start(self) -> "HostProfiler":
        self._thread.start()
        return self

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def close(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def _loop(self) -> None:
        while not self._stop.wait(1.0 / self.hz):
            try:
                self.sample()
            except Exception:
                pass          # a sampling hiccup must never kill the
                              # thread (next tick retries)

    # -- the probe
    def sample(self) -> None:
        """One sampling pass over every live thread's current stack."""
        from .context import thread_trace_map
        me = threading.get_ident()
        own = self._thread.ident
        traces = thread_trace_map()
        for ident, frame in sys._current_frames().items():
            if ident == me or ident == own:
                continue
            stack: List[str] = []
            f = frame
            while f is not None and len(stack) < self.max_depth:
                stack.append(_frame_label(f.f_code))
                f = f.f_back
            if not stack:
                continue
            stack.reverse()               # root first (collapsed order)
            ctx = traces.get(ident)
            key = (ctx.trace_id if ctx is not None else None,
                   tuple(stack))
            with self._lock:
                if key in self._stacks:
                    self._stacks[key] += 1
                elif len(self._stacks) < self.max_stacks:
                    self._stacks[key] = 1
                    if ctx is not None:
                        self._trace_names[ctx.trace_id] = ctx.name
                else:
                    self.truncated += 1
        with self._lock:
            self.samples += 1

    def reset(self) -> None:
        with self._lock:
            self._stacks.clear()
            self._trace_names.clear()
            self.samples = 0
            self.truncated = 0

    # -- reads / exports
    def report(self, max_stacks: Optional[int] = None) -> Dict[str, Any]:
        """Aggregated profile: stacks sorted by weight, plus a
        per-trace sample rollup (disjoint per query — the attribution
        contract)."""
        with self._lock:
            items = sorted(self._stacks.items(), key=lambda kv: -kv[1])
            names = dict(self._trace_names)
        if max_stacks is not None:
            items = items[:max_stacks]
        traces: Dict[str, Dict[str, Any]] = {}
        for (tid, _), c in items:
            if tid is None:
                continue
            t = traces.setdefault(
                tid, {"name": names.get(tid, ""), "samples": 0})
            t["samples"] += c
        return {
            "hz": self.hz,
            "samples": self.samples,
            "distinct_stacks": len(items),
            "truncated": self.truncated,
            "stacks": [{"trace": tid, "trace_name": names.get(tid, ""),
                        "frames": list(frames), "count": c}
                       for (tid, frames), c in items],
            "traces": traces,
        }

    def collapsed(self, trace: Optional[str] = None) -> str:
        """Collapsed-stack text (``frame;frame;frame count`` per line,
        root first) — pipe into ``flamegraph.pl`` or paste into
        speedscope.  ``trace`` filters to one trace context."""
        with self._lock:
            items = sorted(self._stacks.items(), key=lambda kv: -kv[1])
        lines = [f"{';'.join(frames)} {c}"
                 for (tid, frames), c in items
                 if trace is None or tid == trace]
        return "\n".join(lines)

    def speedscope(self, trace: Optional[str] = None,
                   name: str = "mosaic_tpu host profile") -> Dict[str, Any]:
        """The profile in speedscope's sampled-profile JSON schema."""
        with self._lock:
            items = [((tid, frames), c)
                     for (tid, frames), c in self._stacks.items()
                     if trace is None or tid == trace]
        frame_ix: Dict[str, int] = {}
        frames_out: List[Dict[str, str]] = []
        samples: List[List[int]] = []
        weights: List[int] = []
        for (_, frames), c in items:
            row = []
            for fr in frames:
                if fr not in frame_ix:
                    frame_ix[fr] = len(frames_out)
                    frames_out.append({"name": fr})
                row.append(frame_ix[fr])
            samples.append(row)
            weights.append(c)
        total = sum(weights)
        return {
            "$schema": "https://www.speedscope.app/file-format-schema.json",
            "exporter": "mosaic_tpu.obs.profiler",
            "name": name,
            "shared": {"frames": frames_out},
            "profiles": [{
                "type": "sampled",
                "name": name,
                "unit": "none",
                "startValue": 0,
                "endValue": total,
                "samples": samples,
                "weights": weights,
            }],
        }


# ------------------------------------------------------ kernel ledger

class KernelLedger:
    """Per-kernel device-cost accounting, keyed like the jit cache.

    ``observe(name, key, seconds, rows)`` accumulates launch wall
    time per ``(name, key)``; ``record_cost(name, figures)`` attaches
    XLA cost-model figures (flops / bytes_accessed — fed by
    ``obs.jaxmon.record_cost_analysis``); ``register(name, key)``
    marks a kernel known (the jit cache calls it on every build) so
    the report lists compiled-but-unobserved kernels too.  Always on
    (one dict update per chunk launch — noise next to a device
    dispatch); bounded at ``max_entries`` distinct keys.
    """

    def __init__(self, max_entries: int = 512):
        self.max_entries = int(max_entries)
        self.enabled = True
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._costs: Dict[str, Dict[str, float]] = {}
        self.dropped = 0

    def _entry_locked(self, name: str, key
                      ) -> Optional[Dict[str, Any]]:
        k = (name, repr(key))
        e = self._entries.get(k)
        if e is None:
            if len(self._entries) >= self.max_entries:
                self.dropped += 1
                return None
            e = self._entries[k] = {
                "name": name, "key": k[1], "launches": 0,
                "seconds": 0.0, "rows": 0}
        return e

    def register(self, name: str, key) -> None:
        """Mark a kernel known (zero launches until observed)."""
        if not self.enabled:
            return
        with self._lock:
            self._entry_locked(name, key)

    def observe(self, name: str, key, seconds: float,
                rows: int = 0) -> None:
        """Charge one launch's wall time to ``(name, key)``."""
        if not self.enabled:
            return
        with self._lock:
            e = self._entry_locked(name, key)
            if e is None:
                return
            e["launches"] += 1
            e["seconds"] += float(seconds)
            e["rows"] += int(rows)
        # query accounting join: observe() runs on the thread that
        # launched the kernel, which carries the owning query's trace
        # (obs.context thread propagation), so the same seconds charge
        # the in-flight ticket — the per-principal device_s column
        # (one empty-dict check when no query is registered)
        from .inflight import charge_device_seconds
        charge_device_seconds(float(seconds))

    def record_cost(self, name: str, figures: Dict[str, float]) -> None:
        """Attach XLA cost-analysis figures to every ``name`` entry."""
        if not self.enabled or not figures:
            return
        with self._lock:
            self._costs[name] = {k: float(v) for k, v in figures.items()
                                 if isinstance(v, (int, float))}

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self._costs.clear()
            self.dropped = 0

    def seconds(self, *names: str) -> float:
        """Total observed wall seconds over kernels named ``names``
        (all kernels when empty)."""
        with self._lock:
            return sum(e["seconds"] for e in self._entries.values()
                       if not names or e["name"] in names)

    def report(self) -> Dict[str, Any]:
        """``{"kernels": [...], "total_s": float, "dropped": int}`` —
        kernels sorted by wall time, each joined with its cost figures
        and derived rates (gflops_s / rows_per_s) where available."""
        with self._lock:
            entries = [dict(e) for e in self._entries.values()]
            costs = {n: dict(f) for n, f in self._costs.items()}
        out = []
        for e in sorted(entries, key=lambda e: -e["seconds"]):
            cost = costs.get(e["name"])
            if cost:
                e["cost"] = cost
                if e["seconds"] > 0 and cost.get("flops"):
                    e["gflops_s"] = round(
                        cost["flops"] * e["launches"]
                        / e["seconds"] / 1e9, 3)
            if e["seconds"] > 0 and e["rows"]:
                e["rows_per_s"] = round(e["rows"] / e["seconds"])
            e["seconds"] = round(e["seconds"], 6)
            out.append(e)
        return {"kernels": out,
                "total_s": round(sum(e["seconds"] for e in out), 6),
                "dropped": self.dropped}


#: the process-global ledger every instrumented launch feeds
ledger = KernelLedger()


# --------------------------------------------------- global lifecycle

_prof_lock = threading.Lock()
_active_profiler: Optional[HostProfiler] = None
_conf_hz: Optional[float] = None     # last rate applied via conf

#: env var pinning the sampling rate over the conf key
PROFILE_HZ_ENV = "MOSAIC_TPU_PROFILE_HZ"


def profiler() -> Optional[HostProfiler]:
    """The running host profiler, or None."""
    return _active_profiler


def start_profiler(hz: Optional[float] = None) -> HostProfiler:
    """(Re)start the process host profiler; stops a previous one
    first.  The flight recorder notes the transition."""
    global _active_profiler
    with _prof_lock:
        if _active_profiler is not None:
            _active_profiler.close()
        _active_profiler = HostProfiler(
            hz if hz is not None else DEFAULT_PROFILE_HZ).start()
        p = _active_profiler
    from .recorder import recorder
    recorder.record("profiler", action="start", hz=p.hz)
    return p


def stop_profiler() -> None:
    global _active_profiler
    with _prof_lock:
        if _active_profiler is not None:
            _active_profiler.close()
            _active_profiler = None


def configure_profiler(conf_hz: float) -> None:
    """Conf-driven lifecycle (``mosaic.obs.profile.hz`` via
    ``set_default_config``): > 0 starts/retunes, 0 stops.  Change-
    detecting, and only ever stops what a conf started — a
    programmatic ``start_profiler()`` survives unrelated ``SET``
    statements.  ``MOSAIC_TPU_PROFILE_HZ`` pins the rate: conf values
    are ignored while it is set."""
    global _conf_hz
    if os.environ.get(PROFILE_HZ_ENV):
        return
    hz = float(conf_hz)
    with _prof_lock:
        # check-and-set under the lock: two concurrent SETs reading
        # the same prev would both decide to start/stop
        prev = _conf_hz
        if prev is not None and hz == prev:
            return
        _conf_hz = hz
    if hz > 0:
        start_profiler(hz)
    elif prev:
        stop_profiler()


# ----------------------------------------------------- capture modes

def capture_snapshot() -> Dict[str, Any]:
    """One profiler snapshot for flight-recorder bundles: bounded host
    stacks + collapsed text + the kernel ledger.  Empty-but-shaped
    when no profiler runs (the ledger is always on)."""
    p = profiler()
    out: Dict[str, Any] = {"ledger": ledger.report()}
    if p is not None:
        out["host"] = p.report(max_stacks=_SNAPSHOT_STACKS)
        out["collapsed"] = p.collapsed()
    else:
        out["host"] = {}
        out["collapsed"] = ""
    return out


_capture_lock = threading.Lock()
_capture_busy = False


def maybe_device_capture(reason: str) -> Optional[str]:
    """Bounded ``jax.profiler`` capture on a trigger (SLO breach /
    slow query), gated on ``mosaic.obs.profile.trace.ms`` > 0.

    Runs ``tracer.device_trace`` for the configured duration on a
    daemon thread and returns the log directory immediately (None
    when disabled, when jax was never imported — a trigger must not
    *initialize* a backend — or when a capture is already running:
    ``jax.profiler`` supports one trace at a time)."""
    from .. import config as _config
    ms = float(getattr(_config.default_config(),
                       "obs_profile_trace_ms", 0.0))
    if ms <= 0 or "jax" not in sys.modules:
        return None
    global _capture_busy
    with _capture_lock:
        if _capture_busy:
            return None
        _capture_busy = True
    import tempfile
    logdir = os.path.join(
        os.environ.get("MOSAIC_TPU_DUMP_DIR") or os.path.join(
            tempfile.gettempdir(), "mosaic_tpu_flight"),
        f"device_trace_{os.getpid()}_{reason}")

    def _run():
        global _capture_busy
        try:
            from .tracer import device_trace
            with device_trace(logdir):
                time.sleep(ms / 1e3)
            from .recorder import recorder
            recorder.record("device_trace", logdir=logdir,
                            ms=ms, reason=reason)
        except Exception:
            pass              # a failed capture must never take down
                              # the trigger path
        finally:
            with _capture_lock:
                _capture_busy = False

    threading.Thread(target=_run, name="mosaic-device-capture",
                     daemon=True).start()
    return logdir
