"""Flight recorder: always-on bounded ring of structured events.

Reference counterpart: the Spark event log + UI survive a failed query
and answer "what just happened"; standalone we keep a process-global
:class:`FlightRecorder` — a bounded, lock-cheap ring of small dict
events that is **on by default** (even with the tracer off) and costs
one attribute check per probe when disabled.

What lands in the ring: span completions (when the tracer is on),
retry attempts/recoveries/giveups, armed fault-plan firings, codec
``ErrorRecord``s from degrade-not-die ingestion, JAX backend-compile
events, config mutations, SQL query begin/slow-query marks, and dump
marks themselves.  Every event automatically carries the active trace
id (see ``obs.context``), so a dump reconstructs the failing span
chain of the query that died.

``dump()`` writes a self-contained JSON bundle — events + metrics
snapshot + resolved config + jax platform/device info — to
``MOSAIC_TPU_DUMP_DIR`` (default: a ``mosaic_tpu_flight`` dir under
the system tempdir).  Automatic dumps: unhandled exceptions (via a
chained ``sys.excepthook``, installed at ``mosaic_tpu.obs`` import)
and slow SQL queries (``mosaic.obs.slow.query.ms`` conf).

Env knobs: ``MOSAIC_TPU_RECORDER=0`` disables, ``MOSAIC_TPU_RECORDER_EVENTS``
sizes the ring (default 4096), ``MOSAIC_TPU_DUMP_DIR`` redirects dumps.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from .context import current_trace

__all__ = ["FlightRecorder", "EVENTS", "recorder",
           "install_excepthook"]

_DEF_CAPACITY = 4096

#: The event catalogue: every ``kind`` string any ``record()`` call in
#: the tree may emit.  Dashboards, dumps, and tests filter
#: ``events(kind)`` by exact string — an undeclared kind is invisible
#: to all of them, and a declared-but-unemitted kind is a dead panel.
#: ``graftlint``'s contract-recorder-event rule enforces both
#: directions; add the name here in the same PR that adds the emitter.
EVENTS = frozenset({
    # lifecycle / tracing
    "span", "sql", "slow_query", "config", "audit",
    # cancellation + accounting plane
    "query_cancel_requested",
    # resilience: retries, faults, degrade-not-die ingestion
    "retry", "retry_recovered", "retry_giveup", "fault_injected",
    "codec_error", "codec_record_dropped",
    # jax / device plane
    "jax_compile", "xla_cost", "device_trace",
    # planner + fusion
    "planner_decision", "planner_mispredict", "planner_stats_loaded",
    "planner_stats_corrupt", "planner_stats_save_failed",
    "fusion_group", "fusion_bailout", "fusion_plan_error",
    # adaptive PIP refinement (parallel/pip_join.py): a refined run
    # failed mid-flight and transparently re-ran on the flat path
    "refine_bailout",
    # learned layout advisor (sql/layout.py): one store-layout
    # recommendation, with the evidence it was derived from
    "layout_advice",
    # memory plane
    "mem_admit_denied", "mem_chunk_shrink", "mem_leak",
    # query service (serve/): overload shedding + drain lifecycle
    "serve_shed", "serve_drain",
    # fleet telemetry plane (spool/fleet): cross-process trace links
    # and aggregator degrade paths
    "trace_link", "fleet_worker_stale", "fleet_merge_error",
    # serving fleet supervisor (serve/supervisor.py): worker process
    # lifecycle + the crash-loop circuit breaker
    "fleet_worker_spawn", "fleet_worker_exit", "fleet_degraded",
    # SLO + profiler
    "slo_breach", "slo_recovered", "profiler",
    # pipeline observer hook failures
    "pipeline_observe_error",
    # out-of-core chip store: torn-shard degrade (reader found fewer
    # bytes on disk than the manifest promised and recovered per the
    # on_error policy)
    "store_shard_torn",
    # workload history plane (obs/history.py): a segment file failed
    # validation (torn tail, wrong version, unparseable header) and
    # was skipped or prefix-truncated instead of raising
    "history_segment_torn",
    # recorder-internal marks
    "dump", "dump_suppressed", "dump_suppressed_flush", "error",
    "unhandled_error",
})


def _jax_info() -> Dict[str, Any]:
    """Platform/device snapshot for bundles — best-effort, and only if
    jax is already imported (a crash dump must never *initialize* a
    backend)."""
    if "jax" not in sys.modules:
        return {"imported": False}
    try:
        import jax
        devs = jax.devices()
        return {
            "imported": True,
            "version": jax.__version__,
            "backend": jax.default_backend(),
            "device_count": len(devs),
            "devices": [f"{d.platform}:{d.id}" for d in devs],
        }
    except Exception as e:  # backend init failures must not mask dumps
        return {"imported": True, "error": f"{type(e).__name__}: {e}"}


class FlightRecorder:
    """Bounded structured event ring; one attribute check per
    ``record()`` when disabled."""

    def __init__(self):
        env = os.environ.get("MOSAIC_TPU_RECORDER", "").strip().lower()
        self._enabled = env not in ("0", "off", "false", "no")
        try:
            cap = int(os.environ.get("MOSAIC_TPU_RECORDER_EVENTS",
                                     _DEF_CAPACITY))
        except ValueError:
            cap = _DEF_CAPACITY
        self._lock = threading.Lock()
        self._events: "collections.deque[Dict[str, Any]]" = \
            collections.deque(maxlen=max(16, cap))
        self._seq = 0
        self._dumps = 0
        self._dropped = 0            # events evicted off the ring
        self._last_auto_dump: Optional[float] = None
        self._suppressed = 0         # auto-dumps held by the cooldown

    # -- switches
    def enable(self) -> None:
        # graftlint: ignore[lock-unguarded-attr] — GIL-atomic bool store; record() reads it unlocked by design
        self._enabled = True

    def disable(self) -> None:
        # graftlint: ignore[lock-unguarded-attr] — GIL-atomic bool store; record() reads it unlocked by design
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def capacity(self) -> int:
        return self._events.maxlen or 0

    def reset(self, capacity: Optional[int] = None) -> None:
        """Clear the ring; optionally resize it (tests exercise bounds
        with a small ring)."""
        with self._lock:
            if capacity is not None:
                self._events = collections.deque(
                    maxlen=max(16, int(capacity)))
            else:
                self._events.clear()
            self._seq = 0
            self._dropped = 0
            self._last_auto_dump = None
            self._suppressed = 0

    # -- the probe
    def record(self, kind: str, **fields) -> None:
        """Append one structured event.  The active trace id (if any)
        is attached automatically."""
        if not self._enabled:
            return
        ev: Dict[str, Any] = {"seq": 0, "ts": time.time(), "kind": kind}
        ctx = current_trace()
        if ctx is not None:
            ev["trace"] = ctx.trace_id
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            # a full ring wraps silently at append — count the
            # eviction so truncated flight recordings are detectable
            dropping = len(self._events) == self._events.maxlen
            if dropping:
                self._dropped += 1
            self._events.append(ev)
        if dropping:
            from .metrics import metrics
            if metrics.enabled:
                metrics.count("obs/recorder_dropped")

    @property
    def dropped(self) -> int:
        """Events evicted off the ring since the last reset."""
        with self._lock:
            return self._dropped

    def events(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        """Snapshot of retained events, oldest first (optionally
        filtered by kind)."""
        with self._lock:
            evs = list(self._events)
        if kind is None:
            return evs
        return [e for e in evs if e.get("kind") == kind]

    # -- bundles
    def bundle(self, reason: str = "manual",
               error: Optional[str] = None) -> Dict[str, Any]:
        """Self-contained post-mortem: events + metrics snapshot +
        metric time-series history + last memory watermarks + resolved
        config + jax platform info."""
        import dataclasses

        from .metrics import metrics
        try:
            from .. import config as _config
            cfg = dataclasses.asdict(_config.default_config())
        except Exception:
            cfg = {}
        try:                    # history survives into the bundle: the
            from .timeseries import timeseries   # post-mortem shows the
            ts_snap = timeseries.snapshot()      # minutes before, not
        except Exception:                        # just the final values
            ts_snap = {}
        try:
            from .jaxmon import last_watermarks
            mem = last_watermarks()
        except Exception:
            mem = {}
        try:                    # triggered capture: host stacks + the
            from .profiler import capture_snapshot   # kernel ledger
            prof = capture_snapshot()                # ride along in
        except Exception:                            # every bundle
            prof = {}
        try:                    # query console state: what was running
            from .accounting import audit, meter     # at dump time +
            from .inflight import inflight           # who spent what
            queries = {"inflight": inflight.list_active(),
                       "recent": audit.records(limit=50),
                       "principals": meter.report()}
        except Exception:
            queries = {}
        try:                    # device-memory ledger: who HOLDS live
            from .memwatch import memwatch           # bytes right now —
            device_memory = memwatch.snapshot()      # the mem-pressure
        except Exception:                            # breach post-mortem
            device_memory = {}
        b: Dict[str, Any] = {
            "reason": reason,
            "ts": time.time(),
            "pid": os.getpid(),
            "events": self.events(),
            "dropped": self.dropped,
            "metrics": metrics.report(),
            "timeseries": ts_snap,
            "memory": mem,
            "device_memory": device_memory,
            "profile": prof,
            "queries": queries,
            "config": cfg,
            "jax": _jax_info(),
        }
        if error is not None:
            b["error"] = error
        return b

    def dump(self, path: Optional[str] = None, reason: str = "manual",
             error: Optional[str] = None) -> str:
        """Write a bundle as JSON (atomic rename); returns the path."""
        b = self.bundle(reason=reason, error=error)
        if path is None:
            d = os.environ.get("MOSAIC_TPU_DUMP_DIR") or os.path.join(
                tempfile.gettempdir(), "mosaic_tpu_flight")
            os.makedirs(d, exist_ok=True)
            with self._lock:
                self._dumps += 1
                n = self._dumps
            path = os.path.join(
                d, f"flight_{os.getpid()}_{n:03d}_{reason}.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(b, f, default=str)
        os.replace(tmp, path)
        self.record("dump", path=path, reason=reason)
        return path

    def dump_throttled(self, reason: str = "auto",
                       error: Optional[str] = None) -> Optional[str]:
        """Cooldown-gated :meth:`dump` shared by every automatic
        trigger (slow queries AND SLO breach dumps — a sustained slow
        workload must not become a dump storm).

        At most one dump per ``mosaic.obs.dump.cooldown.ms`` (default
        30 s; 0 disables the gate).  A held dump returns None and
        records a ``dump_suppressed`` event carrying how many dumps
        the cooldown has swallowed since the last one that went
        through; an allowed dump's bundle likewise carries the count.
        Also fires the optional bounded device-profiler capture
        (``obs.profiler.maybe_device_capture``) on allowed dumps."""
        try:
            from .. import config as _config
            cd_ms = float(getattr(_config.default_config(),
                                  "obs_dump_cooldown_ms", 30_000.0))
        except Exception:
            cd_ms = 30_000.0
        now = time.time()
        with self._lock:
            held = (cd_ms > 0 and self._last_auto_dump is not None
                    and (now - self._last_auto_dump) * 1e3 < cd_ms)
            if held:
                self._suppressed += 1
                suppressed = self._suppressed
            else:
                self._last_auto_dump = now
                suppressed = self._suppressed
                self._suppressed = 0
        if held:
            self.record("dump_suppressed", reason=reason,
                        suppressed=suppressed, cooldown_ms=cd_ms)
            return None
        if suppressed:
            self.record("dump_suppressed_flush", reason=reason,
                        suppressed=suppressed)
        try:
            from .profiler import maybe_device_capture
            maybe_device_capture(reason)
        except Exception:
            pass
        return self.dump(reason=reason, error=error)

    @contextlib.contextmanager
    def dump_on_error(self, reason: str = "unhandled_error"):
        """Dump a bundle when the body raises, then re-raise."""
        try:
            yield
        except BaseException as e:
            msg = f"{type(e).__name__}: {e}"[:300]
            self.record("error", error=msg)
            try:
                self.dump(reason=reason, error=msg)
            except OSError:
                pass
            raise


recorder = FlightRecorder()


# ------------------------------------------------ crash auto-dump

_hook_lock = threading.Lock()
_hook_installed = False


def install_excepthook() -> bool:
    """Chain a ``sys.excepthook`` that dumps a flight bundle on any
    unhandled exception (once per process).  The previous hook always
    runs afterwards."""
    global _hook_installed
    with _hook_lock:
        if _hook_installed:
            return False
        prev = sys.excepthook

        def hook(tp, val, tb):
            try:
                if recorder.enabled:
                    msg = f"{tp.__name__}: {val}"[:300]
                    recorder.record("unhandled_error", error=msg)
                    recorder.dump(reason="unhandled_error", error=msg)
            except Exception:
                pass
            prev(tp, val, tb)

        sys.excepthook = hook
        _hook_installed = True
        return True
