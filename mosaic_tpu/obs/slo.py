"""Declarative SLO objectives with multi-window burn-rate alerting.

Reference counterpart: none — the reference delegates "is the service
healthy" to whatever the Spark operator wired up.  ROADMAP item 3 (a
multi-tenant query service) needs the decision made in-process, so
this module evaluates a small set of :class:`SLObjective` records
against the ``obs.timeseries`` store on every sampler tick.

Burn-rate semantics (the Google-SRE multi-window pattern): an
objective with target ``objective`` has error budget ``1 −
objective``; it breaches when the bad fraction exceeds ``burn ×
budget`` in **both** the short and the long window.  The short window
makes alerts fast, the long window keeps one-sample blips from
paging.  Rate/ceiling objectives compare the windowed rate / max
against a fixed threshold in both windows instead.

On a breach *transition* (ok → breached) the monitor emits exactly
one ``slo_breach`` flight-recorder event, bumps ``slo/breaches``,
flips ``slo/active/<name>`` to 1 and raises the ``obs/alerts_active``
gauge — the ``slo/*`` names export as ``mosaic_slo_*`` OpenMetrics
series through the standard sanitizer.  Staying breached is silent
(no alert storms); recovery emits ``slo_recovered`` and drops the
gauges.  ``SET mosaic.obs.slo.dump = true`` additionally writes a
flight-recorder bundle at each breach transition.

Objective kinds:

* ``latency``   — fraction of ``series`` points above ``threshold_ms``
  (ms-valued series, e.g. ``sql/query_ms``) vs. the error budget;
* ``error_rate`` — windowed rate of ``bad`` counter over rate of
  ``total`` counter vs. the error budget;
* ``counter_rate`` — windowed rate of ``series`` vs. ``max_rate``
  events/s (the compile-storm budget);
* ``gauge_max`` — windowed max of ``series`` vs. ``ceiling`` (the
  shard-skew ceiling).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple

from .metrics import metrics
from .recorder import recorder
from .timeseries import TimeSeriesStore, timeseries

__all__ = ["SLObjective", "SLOMonitor", "monitor",
           "default_objectives", "principal_objectives",
           "serve_objectives", "fleet_objectives", "evaluate_fleet",
           "KINDS"]

KINDS = ("latency", "error_rate", "counter_rate", "gauge_max")


@dataclasses.dataclass(frozen=True)
class SLObjective:
    """One declarative objective; see module docstring for kinds."""

    name: str
    kind: str
    series: str = ""                 # latency / counter_rate / gauge_max
    bad: str = ""                    # error_rate: failure counter
    total: str = ""                  # error_rate: attempt counter
    threshold_ms: float = 0.0        # latency: a point above this is bad
    objective: float = 0.99          # latency/error_rate good-fraction
    burn: float = 1.0                # budget multiplier before alerting
    max_rate: float = 0.0            # counter_rate ceiling (events/s)
    ceiling: float = 0.0             # gauge_max ceiling
    windows: Tuple[float, float] = (60.0, 300.0)   # (short, long) s
    min_points: int = 1              # latency: short-window floor

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"SLO {self.name!r}: unknown kind "
                             f"{self.kind!r} (have {KINDS})")

    def _bad_frac(self, store: TimeSeriesStore, win: float,
                  now: float) -> Tuple[float, float]:
        """(bad fraction, observation weight) over one window."""
        if self.kind == "latency":
            bad, total = store.fraction_over(
                self.series, self.threshold_ms, win, now)
            return (bad / total if total else 0.0), float(total)
        if self.kind == "error_rate":
            bad = max(0.0, store.rate(self.bad, win, now))
            total = max(0.0, store.rate(self.total, win, now))
            return (bad / total if total > 0 else 0.0), total
        if self.kind == "counter_rate":
            r = max(0.0, store.rate(self.series, win, now))
            # normalized so the shared burn×budget compare applies
            return (r / self.max_rate if self.max_rate > 0 else 0.0), r
        st = store.window_stats(self.series, win, now)   # gauge_max
        if not st["count"] or self.ceiling <= 0:
            return 0.0, 0.0
        return st["max"] / self.ceiling, float(st["count"])

    def evaluate(self, store: TimeSeriesStore,
                 now: Optional[float] = None) -> Dict[str, object]:
        """One multi-window check -> {breached, short, long, budget}."""
        now = time.time() if now is None else now
        short_w, long_w = self.windows
        f_short, w_short = self._bad_frac(store, short_w, now)
        f_long, _ = self._bad_frac(store, long_w, now)
        if self.kind in ("latency", "error_rate"):
            budget = self.burn * (1.0 - self.objective)
        else:
            budget = self.burn       # rates/ceilings are pre-normalized
        breached = f_short > budget and f_long > budget
        if self.kind == "latency" and w_short < self.min_points:
            breached = False
        return {"name": self.name, "kind": self.kind,
                "breached": breached, "budget": budget,
                "short": f_short, "long": f_long,
                "windows": list(self.windows)}


def default_objectives() -> List[SLObjective]:
    """The shipped objectives — deliberately loose enough that a clean
    tier-1 suite run (sampler on) raises zero alerts; the slo-smoke CI
    lane asserts exactly that, plus that a tightened copy does fire."""
    return [
        # per-operator latency: a sql() call taking > 30 s is bad; more
        # than 5% bad in both windows pages
        SLObjective(name="sql_latency", kind="latency",
                    series="sql/query_ms", threshold_ms=30_000.0,
                    objective=0.95, min_points=3),
        # internal query failures (SQLError user mistakes excluded —
        # engine counts only unexpected errors into sql/errors)
        SLObjective(name="sql_errors", kind="error_rate",
                    bad="sql/errors", total="sql/queries",
                    objective=0.90),
        # compile-storm budget: sustained > 2 XLA compiles/s means
        # ragged shapes are defeating every cache layer
        SLObjective(name="compile_storm", kind="counter_rate",
                    series="jax/recompiles", max_rate=2.0),
        # shard-skew ceiling: max/mean per-device load above 8x for
        # five minutes means placement has collapsed
        SLObjective(name="shard_skew", kind="gauge_max",
                    series="shard/skew/pip_join", ceiling=8.0,
                    windows=(60.0, 300.0)),
        # device-memory pressure: ledger-attributed live bytes at the
        # effective capacity (budget or HBM) in both windows — the
        # breach dump's bundle embeds the full ledger snapshot, so the
        # post-mortem names the holders.  Clean runs sit near zero
        # pressure; only a configured tiny budget (the mem-smoke
        # drill) or real saturation crosses 1.0.
        SLObjective(name="device_mem_pressure", kind="gauge_max",
                    series="mem/pressure_max", ceiling=1.0,
                    windows=(60.0, 300.0)),
    ]


def principal_objectives(principal: str,
                         query_ms_ceiling: float = 60_000.0,
                         max_qps: float = 50.0) -> List[SLObjective]:
    """The per-principal objective pair the accounting plane registers
    on first sight of each principal (obs/accounting.py): a
    ``gauge_max`` ceiling on the tenant's per-query latency series and
    a ``counter_rate`` ceiling on its query rate.  Deliberately loose,
    like :func:`default_objectives` — tenants get burn-rate alerting
    with zero per-tenant config, operators tighten via
    :meth:`SLOMonitor.add_objective` (same-name replace)."""
    return [
        SLObjective(name=f"principal_latency:{principal}",
                    kind="gauge_max",
                    series=f"principal/query_ms/{principal}",
                    ceiling=query_ms_ceiling),
        SLObjective(name=f"principal_qps:{principal}",
                    kind="counter_rate",
                    series=f"principal/queries/{principal}",
                    max_rate=max_qps),
    ]


def serve_objectives(queue_depth: int,
                     request_ms_ceiling: float = 30_000.0
                     ) -> List[SLObjective]:
    """The query-server objective pair ``QueryServer.start``
    registers: a ``gauge_max`` ceiling on end-to-end request latency
    (the serve/request_ms series the server records per request) and a
    ``gauge_max`` on admission-queue occupancy at 90% of the
    configured depth — sustained near-full queue means the server is
    living off the shed path, which is degrade-not-die working as
    designed but an operator signal all the same."""
    return [
        SLObjective(name="serve_request_latency",
                    kind="gauge_max",
                    series="serve/request_ms",
                    ceiling=request_ms_ceiling),
        SLObjective(name="serve_queue_saturation",
                    kind="gauge_max",
                    series="serve/queue_depth",
                    ceiling=max(1.0, 0.9 * float(queue_depth))),
    ]


def fleet_objectives() -> List[SLObjective]:
    """The objective :class:`~..serve.supervisor.ServeFleet` registers
    in the supervisor process: any worker slot parked by the
    crash-loop circuit breaker (the ``fleet/degraded_workers`` series
    the health tick records) is a breach — the fleet is serving, but
    at N-1, and an operator should know before the next worker
    follows.  Ceiling 0.5 so the first degraded slot (gauge 1.0)
    crosses; a clean respawn never records a nonzero point."""
    return [
        SLObjective(name="fleet_degraded", kind="gauge_max",
                    series="fleet/degraded_workers", ceiling=0.5,
                    windows=(30.0, 60.0)),
    ]


class SLOMonitor:
    """Evaluates objectives against the store; owns breach-episode
    state so each breach alerts exactly once."""

    def __init__(self, objectives: Optional[List[SLObjective]] = None,
                 store: Optional[TimeSeriesStore] = None):
        self._lock = threading.Lock()
        self._objectives = list(objectives) if objectives is not None \
            else default_objectives()
        self._store = store if store is not None else timeseries
        self._breached: Dict[str, Dict[str, object]] = {}
        self._breach_count = 0

    # -- objective management
    def objectives(self) -> List[SLObjective]:
        with self._lock:
            return list(self._objectives)

    def set_objectives(self, objectives: List[SLObjective]) -> None:
        with self._lock:
            self._objectives = list(objectives)

    def add_objective(self, obj: SLObjective) -> None:
        with self._lock:
            self._objectives = [o for o in self._objectives
                                if o.name != obj.name] + [obj]

    def reset(self, objectives: Optional[List[SLObjective]] = None) -> None:
        """Clear episode state (and optionally swap objectives);
        clears the alert gauges it owns."""
        with self._lock:
            for name in self._breached:
                metrics.gauge(f"slo/active/{name}", 0.0)
            self._breached.clear()
            self._breach_count = 0
            if objectives is not None:
                self._objectives = list(objectives)
        metrics.gauge("obs/alerts_active", 0.0)

    # -- evaluation
    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, object]]:
        """Evaluate every objective; returns the state *transitions*
        this call produced (new breaches + recoveries).  Called from
        the sampler tick; safe to call directly."""
        now = time.time() if now is None else now
        with self._lock:
            objectives = list(self._objectives)
        transitions: List[Dict[str, object]] = []
        for obj in objectives:
            try:
                res = obj.evaluate(self._store, now)
            except Exception:
                continue              # a bad objective must not stop
                                      # the others from evaluating
            with self._lock:
                was = obj.name in self._breached
                if res["breached"] and not was:
                    self._breached[obj.name] = res
                    self._breach_count += 1
                    transitions.append(dict(res, transition="breach"))
                elif not res["breached"] and was:
                    self._breached.pop(obj.name)
                    transitions.append(dict(res, transition="recovery"))
                elif res["breached"]:
                    self._breached[obj.name] = res   # refresh values
                n_active = len(self._breached)
            if res["breached"] and not was:
                self._on_breach(obj, res)
            elif was and not res["breached"]:
                recorder.record("slo_recovered", objective=obj.name,
                                slo_kind=obj.kind)
                metrics.gauge(f"slo/active/{obj.name}", 0.0)
            metrics.gauge("obs/alerts_active", float(n_active))
        return transitions

    def _on_breach(self, obj: SLObjective, res: Dict[str, object]) -> None:
        recorder.record(
            "slo_breach", objective=obj.name, slo_kind=obj.kind,
            short=round(float(res["short"]), 6),
            long=round(float(res["long"]), 6),
            budget=round(float(res["budget"]), 6),
            windows=res["windows"])
        metrics.count("slo/breaches")
        metrics.count(f"slo/breaches/{obj.name}")
        metrics.gauge(f"slo/active/{obj.name}", 1.0)
        from .. import config as _config
        if getattr(_config.default_config(), "obs_slo_dump", False):
            # throttled: shares the mosaic.obs.dump.cooldown.ms gate
            # with slow-query dumps (no dump storms under sustained
            # breach churn); the bundle embeds the profiler snapshot
            try:
                recorder.dump_throttled(reason=f"slo_{obj.name}")
            except OSError:
                pass

    # -- reads
    def active_alerts(self) -> List[Dict[str, object]]:
        with self._lock:
            return [dict(v) for v in self._breached.values()]

    def alerts_active(self) -> int:
        with self._lock:
            return len(self._breached)

    def breach_count(self) -> int:
        with self._lock:
            return self._breach_count

    def report(self) -> Dict[str, object]:
        with self._lock:
            return {
                "objectives": [dataclasses.asdict(o)
                               for o in self._objectives],
                "active": [dict(v) for v in self._breached.values()],
                "breaches": self._breach_count,
            }


def evaluate_fleet(store, objectives: Optional[List[SLObjective]] = None,
                   now: Optional[float] = None) -> List[Dict[str, object]]:
    """Fleet-level burn-rate evaluation: run every objective against a
    merged store (any object with the TimeSeriesStore windowed-read
    API — :class:`~.fleet.FleetStore` in practice; objectives are
    duck-typed over it already).  Stateless by design: breach-episode
    bookkeeping (alert once, recover once) stays with each worker's
    own :class:`SLOMonitor`; the fleet answer is "is the FLEET burning
    budget right now", recomputed per call.  Returns one result dict
    per objective, bad objectives skipped the way the monitor skips
    them."""
    now = time.time() if now is None else now
    objs = list(objectives) if objectives is not None \
        else default_objectives()
    out: List[Dict[str, object]] = []
    for obj in objs:
        try:
            out.append(obj.evaluate(store, now))
        except Exception:
            continue              # same contract as SLOMonitor.evaluate
    return out


#: the process-global monitor the sampler drives
monitor = SLOMonitor()
