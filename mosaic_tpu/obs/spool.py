"""Per-process telemetry spool: the worker half of the fleet plane.

Every observability surface in this package is process-local; the
multi-process worker fleet (ROADMAP item 1) needs each process to
EXPORT its state so an aggregator (:mod:`.fleet`) can merge N workers
into one view.  A spool is one atomic, versioned JSON file per process
— ``worker-<pid>.json`` under ``mosaic.obs.fleet.dir`` — rewritten in
place on every Sampler tick (see ``timeseries.Sampler.tick``), so the
file's mtime doubles as the worker's heartbeat.

Contents (``SPOOL_VERSION`` 1):

* ``metrics`` — the registry's RAW state via
  :meth:`MetricsRegistry.full_snapshot`: counters, gauges, and
  histograms with their bucket counts.  Buckets are the exactness
  contract: every process uses identical exponential buckets, so the
  aggregator's bucket-wise sum reproduces fleet p50/p95/p99 precisely.
* ``series`` — per-series raw/rollup tails within
  ``mosaic.obs.fleet.window.ms``, in ``Series.snapshot()`` shape, so
  fleet-level SLO burn rates evaluate over real per-worker history.
* ``slo`` — active alerts + cumulative breach count.
* ``inflight`` — currently running query summaries.
* ``events`` — the last ``mosaic.obs.fleet.events`` flight-recorder
  events (``span`` + ``trace_link`` among them: the raw material for
  cross-process trace stitching).

Writes are atomic (tmp + ``os.replace``, the recorder-dump idiom) so a
reader can never observe a torn file from a LIVE worker; a torn spool
on disk means the process died mid-rename eons ago, and the aggregator
treats it as a degrade case, not an error.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

__all__ = ["SPOOL_VERSION", "spool_path", "spool_snapshot",
           "write_spool", "read_spool", "SpoolError"]

SPOOL_VERSION = 1

_write_lock = threading.Lock()


class SpoolError(ValueError):
    """A spool file could not be used (torn JSON, wrong version,
    missing sections).  Raised by :func:`read_spool`; the aggregator
    catches it and degrades."""


def spool_path(directory: str, pid: Optional[int] = None) -> str:
    """The spool file for ``pid`` (default: this process)."""
    return os.path.join(directory,
                        f"worker-{pid or os.getpid()}.json")


def _windowed_series(window_s: float,
                     now: float) -> Dict[str, Dict[str, Any]]:
    """Per-series snapshots clipped to the spool window.  Reads the
    live Series objects the way the store's own windowed reads do
    (fetch under the store lock, iterate unlocked); a concurrent
    append can at worst race us into the except arm for one series."""
    from .timeseries import timeseries
    cutoff = now - window_s
    out: Dict[str, Dict[str, Any]] = {}
    for name in timeseries.names():
        s = timeseries.series(name)
        if s is None:
            continue
        try:
            out[name] = {
                "raw": [[t, v] for t, v in s.raw if t >= cutoff],
                "mid": [list(b) for b in s.mid if b.ts1 >= cutoff],
                "coarse": [list(b) for b in s.coarse
                           if b.ts1 >= cutoff],
                "dropped": s.dropped,
            }
        except RuntimeError:
            continue          # deque resized mid-iteration; next tick
    return out


def spool_snapshot(now: Optional[float] = None,
                   window_s: Optional[float] = None,
                   events_cap: Optional[int] = None) -> Dict[str, Any]:
    """Assemble this process's spool record (pure read — no I/O)."""
    from .. import config as _config
    from .inflight import inflight
    from .metrics import metrics
    from .recorder import recorder
    from .slo import monitor
    cfg = _config.default_config()
    now = time.time() if now is None else now
    if window_s is None:
        window_s = cfg.obs_fleet_window_ms / 1e3
    if events_cap is None:
        events_cap = cfg.obs_fleet_events
    evs = recorder.events()
    return {
        "version": SPOOL_VERSION,
        "pid": os.getpid(),
        "ts": now,
        "metrics": metrics.full_snapshot(),
        "series": _windowed_series(window_s, now),
        "slo": {"active": monitor.active_alerts(),
                "breaches": monitor.breach_count()},
        "inflight": inflight.list_active(),
        "events": evs[-events_cap:] if events_cap else [],
    }


def write_spool(directory: Optional[str] = None,
                now: Optional[float] = None) -> Optional[str]:
    """Write this process's spool atomically; returns the path, or
    None when spooling is off (no directory configured).  Failures
    never propagate past the metrics counter — a full disk must not
    take the sampler thread (or a query) down with it."""
    from .. import config as _config
    from .metrics import metrics
    directory = directory if directory is not None \
        else _config.default_config().obs_fleet_dir
    if not directory:
        return None
    path = spool_path(directory)
    try:
        snap = spool_snapshot(now=now)
        blob = json.dumps(snap)
        with _write_lock:
            os.makedirs(directory, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(blob)
            os.replace(tmp, path)
    except (OSError, TypeError, ValueError):
        if metrics.enabled:
            metrics.count("fleet/spool_write_errors")
        return None
    if metrics.enabled:
        metrics.count("fleet/spool_writes")
    return path


def read_spool(path: str) -> Dict[str, Any]:
    """Parse + validate one spool file.  Raises :class:`SpoolError`
    for anything unusable (torn JSON, version from a different build,
    non-dict payload) — the aggregator's degrade paths key off it."""
    try:
        with open(path, encoding="utf-8") as fh:
            snap = json.load(fh)
    except ValueError as e:
        raise SpoolError(f"torn spool {path}: {e}") from None
    if not isinstance(snap, dict):
        raise SpoolError(f"spool {path}: not an object")
    if snap.get("version") != SPOOL_VERSION:
        raise SpoolError(f"spool {path}: version "
                         f"{snap.get('version')!r} != {SPOOL_VERSION}")
    if not isinstance(snap.get("metrics"), dict):
        raise SpoolError(f"spool {path}: missing metrics section")
    return snap
