"""Bounded in-process metric time-series with multi-resolution rollups.

Reference counterpart: the reference leans on Spark's metrics sinks +
an external Prometheus for history; standalone we keep a small
process-local store so "what did shard skew do over the last five
minutes" is answerable without any scrape infrastructure — the SLO
burn-rate evaluator (``obs.slo``), the device monitor
(``obs.devicemon``) and the ops dashboard (``obs.dashboard``) all read
from here, and flight-recorder bundles embed a snapshot.

Layout per series — three chained resolutions, strictly partitioned
in time (a point lives in exactly one level at any moment):

* **raw** — the newest ``RAW_CAP`` ``(ts, value)`` points, exact;
* **mid** — when raw overflows, the oldest ``FOLD`` raw points fold
  into one :class:`Bucket` (count/sum/min/max/first/last — lossless
  for every windowed stat except exact quantiles);
* **coarse** — when mid overflows, the oldest ``FOLD`` mid buckets
  merge into one coarse bucket; when coarse overflows the oldest
  bucket is dropped (the only true loss, counted in ``dropped``).

With the defaults (500 raw, 512+512 buckets, fold 10) one series
retains ~56k points — ~7.8 h of history at the 500 ms default sampler
cadence — in a few hundred KB.

The :class:`Sampler` is a daemon thread that, every
``mosaic.obs.sample.ms`` (env ``MOSAIC_TPU_OBS_SAMPLE_MS`` pins it),
snapshots every registry counter/gauge (+ histogram count/sum) into
the store, folds per-device memory watermarks via ``obs.devicemon``,
and drives the SLO evaluator — so alerting works with no query
traffic at all.  Cadence 0 (the default) means no thread exists.
"""

from __future__ import annotations

import collections
import math
import os
import threading
import time
from typing import Dict, List, NamedTuple, Optional, Tuple

__all__ = ["Bucket", "Series", "TimeSeriesStore", "timeseries",
           "Sampler", "start_sampler", "stop_sampler", "sampler",
           "configure_sampler", "DEFAULT_SAMPLE_MS"]

RAW_CAP = 500            # exact points per series (multiple of FOLD)
BUCKET_CAP = 512         # buckets per rollup level
FOLD = 10                # raw points per mid bucket; mids per coarse
MAX_SERIES = 2048        # distinct series names before drops

#: cadence used when the sampler is enabled without an explicit value
DEFAULT_SAMPLE_MS = 500.0


class Bucket(NamedTuple):
    """One rollup bucket: count/sum/min/max are lossless under
    merging; first/last keep rate() exact across resolutions."""
    ts0: float
    ts1: float
    count: int
    sum: float
    min: float
    max: float
    first: float
    last: float


def _fold_points(pts: List[Tuple[float, float]]) -> Bucket:
    vs = [v for _, v in pts]
    return Bucket(pts[0][0], pts[-1][0], len(vs), sum(vs),
                  min(vs), max(vs), vs[0], vs[-1])


def _merge_buckets(bs: List[Bucket]) -> Bucket:
    return Bucket(bs[0].ts0, bs[-1].ts1,
                  sum(b.count for b in bs), sum(b.sum for b in bs),
                  min(b.min for b in bs), max(b.max for b in bs),
                  bs[0].first, bs[-1].last)


class Series:
    """One named series: raw ring + two rollup levels.  Not
    thread-safe on its own — the store serializes access."""

    __slots__ = ("name", "raw", "mid", "coarse", "dropped")

    def __init__(self, name: str):
        self.name = name
        self.raw: "collections.deque[Tuple[float, float]]" = \
            collections.deque()
        self.mid: "collections.deque[Bucket]" = collections.deque()
        self.coarse: "collections.deque[Bucket]" = collections.deque()
        self.dropped = 0          # coarse buckets lost off the far end

    def append(self, ts: float, value: float) -> None:
        self.raw.append((ts, value))
        if len(self.raw) > RAW_CAP:
            self.mid.append(_fold_points(
                [self.raw.popleft() for _ in range(FOLD)]))
            if len(self.mid) > BUCKET_CAP:
                self.coarse.append(_merge_buckets(
                    [self.mid.popleft() for _ in range(FOLD)]))
                if len(self.coarse) > BUCKET_CAP:
                    self.coarse.popleft()
                    self.dropped += 1

    def __len__(self) -> int:
        return (len(self.raw) + sum(b.count for b in self.mid)
                + sum(b.count for b in self.coarse))

    # -- windowed reads ----------------------------------------------
    def _window(self, cutoff: float):
        """(points, buckets) at/after ``cutoff`` — disjoint by
        construction (levels partition time).  A bucket straddling the
        cutoff is included whole: windowed stats are exact to one
        bucket of slack past the raw horizon, exact to the point
        within it."""
        pts = [(t, v) for t, v in self.raw if t >= cutoff]
        bks = [b for dq in (self.coarse, self.mid) for b in dq
               if b.ts1 >= cutoff]
        return pts, bks

    def window_stats(self, seconds: float,
                     now: Optional[float] = None) -> Dict[str, float]:
        now = time.time() if now is None else now
        pts, bks = self._window(now - seconds)
        count = len(pts) + sum(b.count for b in bks)
        if not count:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                    "mean": 0.0}
        s = sum(v for _, v in pts) + sum(b.sum for b in bks)
        lo = min([v for _, v in pts] + [b.min for b in bks])
        hi = max([v for _, v in pts] + [b.max for b in bks])
        return {"count": count, "sum": s, "min": lo, "max": hi,
                "mean": s / count}

    def rate(self, seconds: float,
             now: Optional[float] = None) -> float:
        """(last - first) / elapsed over the window — the counter
        rate.  0.0 with fewer than two observations."""
        now = time.time() if now is None else now
        pts, bks = self._window(now - seconds)
        if bks:                       # oldest observation in window
            t0, first = bks[0].ts0, bks[0].first
        elif pts:
            t0, first = pts[0]
        else:
            return 0.0
        if pts:                       # newest observation in window
            tl, last = pts[-1]
        else:
            tl, last = bks[-1].ts1, bks[-1].last
        dt = tl - t0
        return (last - first) / dt if dt > 0 else 0.0

    def max_over_window(self, seconds: float,
                        now: Optional[float] = None) -> float:
        return self.window_stats(seconds, now)["max"]

    def quantile_over_window(self, q: float, seconds: float,
                             now: Optional[float] = None) -> float:
        """Value at percentile ``q`` over the window — exact while the
        window sits inside the raw ring; past it, each rollup bucket
        contributes its (min, max, mean×(count−2)) weighted spread."""
        now = time.time() if now is None else now
        pts, bks = self._window(now - seconds)
        weighted: List[Tuple[float, int]] = [(v, 1) for _, v in pts]
        for b in bks:
            if b.count == 1:
                weighted.append((b.sum, 1))
                continue
            weighted.append((b.min, 1))
            weighted.append((b.max, 1))
            if b.count > 2:
                mean = (b.sum - b.min - b.max) / (b.count - 2)
                weighted.append((mean, b.count - 2))
        if not weighted:
            return 0.0
        weighted.sort(key=lambda w: w[0])
        total = sum(w for _, w in weighted)
        target = max(1, math.ceil(total * q / 100.0))
        run = 0
        for v, w in weighted:
            run += w
            if run >= target:
                return v
        return weighted[-1][0]

    def fraction_over(self, threshold: float, seconds: float,
                      now: Optional[float] = None) -> Tuple[int, int]:
        """(points above threshold, total points) over the window.
        Exact on raw; rollup buckets interpolate linearly between
        min and max (whole bucket counts when min > threshold, none
        when max <= threshold)."""
        now = time.time() if now is None else now
        pts, bks = self._window(now - seconds)
        bad = sum(1 for _, v in pts if v > threshold)
        total = len(pts)
        for b in bks:
            total += b.count
            if b.min > threshold:
                bad += b.count
            elif b.max > threshold:
                span = b.max - b.min
                frac = (b.max - threshold) / span if span > 0 else 0.5
                bad += max(1, int(round(b.count * frac)))
        return bad, total

    # -- persistence -------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        return {
            "raw": [[t, v] for t, v in self.raw],
            "mid": [list(b) for b in self.mid],
            "coarse": [list(b) for b in self.coarse],
            "dropped": self.dropped,
        }

    @classmethod
    def from_snapshot(cls, name: str, snap: Dict[str, object]) -> "Series":
        s = cls(name)
        s.raw.extend((float(t), float(v)) for t, v in snap.get("raw", []))
        s.mid.extend(Bucket(*b) for b in snap.get("mid", []))
        s.coarse.extend(Bucket(*b) for b in snap.get("coarse", []))
        s.dropped = int(snap.get("dropped", 0))
        return s


class TimeSeriesStore:
    """Thread-safe map of name -> :class:`Series`.  Recording into an
    unknown name creates it (up to ``MAX_SERIES``; beyond that new
    names are counted in ``names_dropped`` and ignored — bounded
    memory is the contract)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._series: Dict[str, Series] = {}
        self.names_dropped = 0

    def record(self, name: str, value: float,
               ts: Optional[float] = None) -> None:
        ts = time.time() if ts is None else ts
        with self._lock:
            s = self._series.get(name)
            if s is None:
                if len(self._series) >= MAX_SERIES:
                    self.names_dropped += 1
                    return
                s = self._series[name] = Series(name)
            s.append(ts, float(value))

    def series(self, name: str) -> Optional[Series]:
        with self._lock:
            return self._series.get(name)

    def names(self, prefix: str = "") -> List[str]:
        with self._lock:
            return sorted(n for n in self._series if n.startswith(prefix))

    def __len__(self) -> int:
        with self._lock:
            return len(self._series)

    def reset(self) -> None:
        with self._lock:
            self._series.clear()
            self.names_dropped = 0

    # windowed reads proxy to the series (0/empty when absent)
    def window_stats(self, name: str, seconds: float,
                     now: Optional[float] = None) -> Dict[str, float]:
        s = self.series(name)
        return s.window_stats(seconds, now) if s is not None else \
            {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0, "mean": 0.0}

    def rate(self, name: str, seconds: float,
             now: Optional[float] = None) -> float:
        s = self.series(name)
        return s.rate(seconds, now) if s is not None else 0.0

    def max_over_window(self, name: str, seconds: float,
                        now: Optional[float] = None) -> float:
        s = self.series(name)
        return s.max_over_window(seconds, now) if s is not None else 0.0

    def quantile_over_window(self, name: str, q: float, seconds: float,
                             now: Optional[float] = None) -> float:
        s = self.series(name)
        return s.quantile_over_window(q, seconds, now) \
            if s is not None else 0.0

    def fraction_over(self, name: str, threshold: float, seconds: float,
                      now: Optional[float] = None) -> Tuple[int, int]:
        s = self.series(name)
        return s.fraction_over(threshold, seconds, now) \
            if s is not None else (0, 0)

    # -- persistence (flight-recorder bundles) -----------------------
    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {"version": 1, "ts": time.time(),
                    "series": {n: s.snapshot()
                               for n, s in self._series.items()}}

    def restore(self, snap: Dict[str, object]) -> int:
        """Replace series present in ``snap``; returns how many were
        restored.  Unknown versions restore nothing (degrade, never
        raise — same contract as the planner's stats file)."""
        if not isinstance(snap, dict) or snap.get("version") != 1:
            return 0
        loaded = {}
        for n, s in (snap.get("series") or {}).items():
            try:
                loaded[n] = Series.from_snapshot(n, s)
            except (TypeError, ValueError, KeyError):
                continue
        with self._lock:
            self._series.update(loaded)
        return len(loaded)


#: the process-global store everything records into
timeseries = TimeSeriesStore()


# ------------------------------------------------------------ sampler

class Sampler:
    """Background thread snapshotting the metrics registry into the
    store every ``interval_ms`` — plus the devicemon fold and the SLO
    evaluation, so alerting runs even while no queries execute."""

    def __init__(self, interval_ms: float, store: TimeSeriesStore,
                 registry=None):
        from .metrics import metrics as _metrics
        self.interval_ms = max(10.0, float(interval_ms))
        self.store = store
        self.registry = registry if registry is not None else _metrics
        self.ticks = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="mosaic-obs-sampler", daemon=True)

    def start(self) -> "Sampler":
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_ms / 1e3):
            try:
                self.tick()
            except Exception:
                pass              # a sampling hiccup must never kill
                                  # the thread (next tick retries)

    def tick(self, now: Optional[float] = None) -> None:
        """One sampling pass (callable directly from tests)."""
        now = time.time() if now is None else now
        # devicemon first: it refreshes mem/* gauges so the registry
        # pass below snapshots this tick's values, not last tick's
        try:
            from .devicemon import devicemon
            devicemon.sample(self.store, now=now)
        except Exception:
            pass
        rep = self.registry.report()
        for name, v in rep["counters"].items():
            self.store.record(name, v, now)
        for name, v in rep["gauges"].items():
            self.store.record(name, v, now)
        for name, h in rep["histograms"].items():
            self.store.record(f"{name}:count", h["count"], now)
            self.store.record(f"{name}:sum", h["sum"], now)
        try:
            from .slo import monitor
            monitor.evaluate(now=now)
        except Exception:
            pass
        # fleet spool: export this process's telemetry for the
        # aggregator.  write_spool() is a no-op when mosaic.obs.fleet.
        # dir is unset and swallows its own I/O errors, but the tick
        # must survive even an import-time surprise
        try:
            from .spool import write_spool
            write_spool(now=now)
        except Exception:
            pass
        self.ticks += 1

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def close(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)


_sampler_lock = threading.Lock()
_active_sampler: Optional[Sampler] = None
_conf_ms: Optional[float] = None     # last cadence applied via conf


def sampler() -> Optional[Sampler]:
    """The running sampler, or None."""
    return _active_sampler


def start_sampler(interval_ms: Optional[float] = None,
                  store: Optional[TimeSeriesStore] = None,
                  registry=None) -> Sampler:
    """(Re)start the process sampler; stops a previous one first."""
    global _active_sampler
    with _sampler_lock:
        if _active_sampler is not None:
            _active_sampler.close()
        _active_sampler = Sampler(
            interval_ms if interval_ms is not None else DEFAULT_SAMPLE_MS,
            store if store is not None else timeseries,
            registry).start()
        return _active_sampler


def stop_sampler() -> None:
    global _active_sampler
    with _sampler_lock:
        if _active_sampler is not None:
            _active_sampler.close()
            _active_sampler = None


def configure_sampler(conf_ms: float) -> None:
    """Conf-driven lifecycle (``mosaic.obs.sample.ms`` via
    ``set_default_config``): >0 starts/retunes, 0 stops.  Change-
    detecting — repeated configs with the same value are no-ops, so a
    programmatically-started sampler survives unrelated ``SET``
    statements.  The env var ``MOSAIC_TPU_OBS_SAMPLE_MS`` pins the
    cadence: conf values are ignored while it is set."""
    global _conf_ms
    if os.environ.get("MOSAIC_TPU_OBS_SAMPLE_MS"):
        return
    ms = float(conf_ms)
    with _sampler_lock:
        # check-and-set under the lock: two concurrent SETs reading
        # the same prev would both decide to start/stop
        prev = _conf_ms
        if prev is not None and ms == prev:
            return
        _conf_ms = ms
    if ms > 0:
        start_sampler(ms)
    elif prev:              # only stop what a conf actually started —
        stop_sampler()      # a programmatic start_sampler() survives
                            # unrelated SET statements
