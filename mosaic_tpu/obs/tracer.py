"""Span tracer: per-stage histograms, counters, Chrome-trace events.

Reference counterpart: Mosaic has no custom tracer — it leans on the
Spark UI for task timing and records ``last_command``/``last_error``/
``full_error`` into raster tile metadata for post-hoc debugging
(core/raster/operator/gdal/GDALCalc.scala:39-55); micro-benchmarks use
``SparkSuite.benchmark`` (test/SparkSuite.scala:30-36).  Standalone, we
supply the equivalent surface ourselves:

* ``tracer`` — process-global span timer.  Each span aggregates
  total/calls/max (the original flat counters) **and** an
  exponential-bucket histogram so ``report()`` carries p50/p95/p99 per
  stage.  Spans also append to a bounded event ring that
  ``obs.chrometrace.export_chrome_trace`` turns into a Perfetto-loadable
  JSON timeline.  Disabled by default; enable with ``tracer.enable()``
  or ``MOSAIC_TPU_TRACE=1``.  ``MosaicContext.call`` wraps every by-name
  dispatch in a span, so external engines driving the string surface get
  per-function wall times for free.
* **Trace-scoped span trees** — the span stack lives in a
  ``contextvars.ContextVar`` (not a thread-local), so it follows the
  active :class:`~mosaic_tpu.obs.context.TraceContext`: every completed
  span carries its trace id, a process-unique span id, and its parent's
  span id.  ``report()["traces"]`` groups spans per trace;
  two interleaved SQL queries land in two distinct trees.
* ``record_command`` / ``record_error`` — the GDALCalc metadata pattern:
  raster operators stamp what ran (and what failed) into ``tile.meta``;
  both also bump registry counters so fleet-wide rates are visible.
* ``device_trace`` — context manager around ``jax.profiler.trace`` for
  XLA/TPU timeline captures (inspect with tensorboard or xprof; lay the
  Chrome-trace export of host spans beside it to line host stages up
  with device activity).

``tracer.enable()`` also enables the metrics registry (span call-sites
feed counters/gauges into it); ``disable()`` turns the registry back off
unless ``MOSAIC_TPU_METRICS`` asked for it independently.  Completed
spans additionally land in the flight recorder (``obs.recorder``) so a
crash dump contains the failing span chain.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import os
import threading
import time
from typing import Dict, List, NamedTuple, Optional, Tuple

from .context import current_trace, next_span_id
from .metrics import Histogram, metrics
from .recorder import recorder

__all__ = ["Tracer", "tracer", "SpanEvent", "record_command",
           "record_error", "device_trace"]

_MAX_EVENTS = 100_000   # bounded Chrome-trace ring (~10 MB of JSON)

#: active span stack: tuple of (name, span_id) pairs.  A ContextVar
#: (copy-on-write tuples) instead of a thread-local list so the stack
#: follows the trace context across threads and executors.
_SPAN_STACK: "contextvars.ContextVar[Tuple[Tuple[str, int], ...]]" = \
    contextvars.ContextVar("mosaic_span_stack", default=())


class SpanEvent(NamedTuple):
    """One completed span in the event ring."""

    qual: str                  # qualified name ("outer/inner")
    start_s: float             # offset from the tracer epoch
    dur_s: float
    tid: int                   # python thread ident
    native_tid: int            # OS thread id (Perfetto lanes)
    trace_id: Optional[str]    # active TraceContext (None outside)
    trace_name: Optional[str]
    span_id: int
    parent_id: Optional[int]
    error: Optional[str]       # "ExcType: msg" when the body raised


class _Span:
    __slots__ = ("name", "total_s", "calls", "max_s", "hist")

    def __init__(self, name: str):
        self.name = name
        self.total_s = 0.0
        self.calls = 0
        self.max_s = 0.0
        self.hist = Histogram(name)


class Tracer:
    """Span wall-times + named counters, thread-safe, ~zero cost when
    disabled (one attribute check per span)."""

    def __init__(self):
        self._enabled = bool(os.environ.get("MOSAIC_TPU_TRACE"))
        self._lock = threading.Lock()
        self._spans: Dict[str, _Span] = {}
        self._counters: Dict[str, float] = {}
        self._events: "collections.deque[SpanEvent]" \
            = collections.deque(maxlen=_MAX_EVENTS)
        self._epoch = time.perf_counter()

    # -- switches
    def enable(self) -> None:
        # graftlint: ignore[lock-unguarded-attr] — GIL-atomic bool store; probes read it unlocked by design
        self._enabled = True
        metrics.enable()

    def disable(self) -> None:
        # graftlint: ignore[lock-unguarded-attr] — GIL-atomic bool store; probes read it unlocked by design
        self._enabled = False
        if not os.environ.get("MOSAIC_TPU_METRICS"):
            metrics.disable()

    @property
    def enabled(self) -> bool:
        return self._enabled

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self._counters.clear()
            self._events.clear()
            self._epoch = time.perf_counter()
        metrics.reset()

    # -- spans
    @contextlib.contextmanager
    def span(self, name: str):
        if not self._enabled:
            yield
            return
        stack = _SPAN_STACK.get()
        sid = next_span_id()
        parent = stack[-1][1] if stack else None
        qual = "/".join([n for n, _ in stack] + [name])
        token = _SPAN_STACK.set(stack + ((name, sid),))
        t0 = time.perf_counter()
        err: Optional[str] = None
        try:
            yield
        except BaseException as e:
            err = f"{type(e).__name__}: {e}"[:200]
            raise
        finally:
            dt = time.perf_counter() - t0
            _SPAN_STACK.reset(token)
            ctx = current_trace()
            try:
                ntid = threading.get_native_id()
            except Exception:
                ntid = threading.get_ident()
            ev = SpanEvent(
                qual, t0 - self._epoch, dt, threading.get_ident(),
                ntid, ctx.trace_id if ctx else None,
                ctx.name if ctx else None, sid, parent, err)
            with self._lock:
                s = self._spans.setdefault(qual, _Span(qual))
                s.total_s += dt
                s.calls += 1
                s.max_s = max(s.max_s, dt)
                s.hist.observe(dt)
                self._events.append(ev)
            extra = {"error": err} if err else {}
            recorder.record("span", name=qual, span=sid,
                            parent=parent, dur_s=round(dt, 6), **extra)

    def current_label(self) -> Optional[str]:
        """Innermost active span in this context (None outside spans).
        Used by ``obs.jaxmon`` to attribute anonymous JAX compile events
        to whatever stage triggered them."""
        stack = _SPAN_STACK.get()
        return "/".join(n for n, _ in stack) if stack else None

    # -- counters
    def count(self, name: str, value: float = 1.0) -> None:
        if not self._enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    # -- Chrome-trace events
    def events(self) -> List[SpanEvent]:
        """Snapshot of completed :class:`SpanEvent` records, oldest
        first."""
        with self._lock:
            return list(self._events)

    # -- reporting
    def report(self) -> Dict[str, object]:
        """One-stop snapshot: per-stage span histograms plus everything
        the metrics registry holds (counters merged; tracer-local names
        win on collision), plus per-trace span trees under
        ``"traces"``: ``{trace_id: {"name": ..., "spans": [...]}}``
        with each span carrying ``span_id``/``parent_id`` links."""
        reg = metrics.report()
        with self._lock:
            spans = {}
            for n, s in self._spans.items():
                h = s.hist.snapshot()
                spans[n] = {"total_s": s.total_s, "calls": s.calls,
                            "max_s": s.max_s, "p50_s": h["p50"],
                            "p95_s": h["p95"], "p99_s": h["p99"]}
            counters = dict(reg["counters"])
            counters.update(self._counters)
            traces: Dict[str, dict] = {}
            for ev in self._events:
                if ev.trace_id is None:
                    continue
                t = traces.setdefault(
                    ev.trace_id, {"name": ev.trace_name, "spans": []})
                rec = {"name": ev.qual, "span_id": ev.span_id,
                       "parent_id": ev.parent_id, "start_s": ev.start_s,
                       "dur_s": ev.dur_s, "thread": ev.native_tid}
                if ev.error:
                    rec["error"] = ev.error
                t["spans"].append(rec)
            return {
                "spans": spans,
                "counters": counters,
                "gauges": reg["gauges"],
                "histograms": reg["histograms"],
                "traces": traces,
            }

    def format_report(self) -> str:
        rep = self.report()
        lines = [f"{'span':<44} {'calls':>6} {'total_s':>9} "
                 f"{'p50_s':>8} {'p95_s':>8} {'max_s':>8}"]
        for n, s in sorted(rep["spans"].items(),
                           key=lambda kv: -kv[1]["total_s"]):
            lines.append(f"{n:<44} {s['calls']:>6} "
                         f"{s['total_s']:>9.4f} {s['p50_s']:>8.4f} "
                         f"{s['p95_s']:>8.4f} {s['max_s']:>8.4f}")
        for tid, t in sorted(rep["traces"].items()):
            errs = sum(1 for s in t["spans"] if s.get("error"))
            lines.append(f"trace {tid} ({t['name']}): "
                         f"{len(t['spans'])} spans"
                         + (f", {errs} errored" if errs else ""))
        for n, v in sorted(rep["counters"].items()):
            lines.append(f"counter {n} = {v:g}")
        for n, v in sorted(rep["gauges"].items()):
            lines.append(f"gauge {n} = {v:g}")
        for n, h in sorted(rep["histograms"].items()):
            lines.append(f"hist {n}: count={h['count']} "
                         f"p50={h['p50']:g} p95={h['p95']:g} "
                         f"p99={h['p99']:g}")
        return "\n".join(lines)


tracer = Tracer()


# -- raster-op provenance (reference: GDALCalc.scala:39-55 records
#    last_command / last_error / full_error into tile metadata)

def record_command(tile, command: str) -> None:
    tile.meta["last_command"] = command
    metrics.count("raster/commands")


def record_error(tile, err: BaseException) -> None:
    tile.meta["last_error"] = f"{type(err).__name__}: {err}"[:200]
    tile.meta["full_error"] = repr(err)
    metrics.count(f"raster/errors/{type(err).__name__}")


@contextlib.contextmanager
def device_trace(logdir: str, host_tracer_level: int = 2):
    """Capture an XLA/TPU profiler timeline into ``logdir`` (reference
    analogue: the Spark UI stage timeline).  View with xprof/tensorboard."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()
