"""Span tracer: per-stage histograms, counters, Chrome-trace events.

Reference counterpart: Mosaic has no custom tracer — it leans on the
Spark UI for task timing and records ``last_command``/``last_error``/
``full_error`` into raster tile metadata for post-hoc debugging
(core/raster/operator/gdal/GDALCalc.scala:39-55); micro-benchmarks use
``SparkSuite.benchmark`` (test/SparkSuite.scala:30-36).  Standalone, we
supply the equivalent surface ourselves:

* ``tracer`` — process-global span timer.  Each span aggregates
  total/calls/max (the original flat counters) **and** an
  exponential-bucket histogram so ``report()`` carries p50/p95/p99 per
  stage.  Spans also append to a bounded event ring that
  ``obs.chrometrace.export_chrome_trace`` turns into a Perfetto-loadable
  JSON timeline.  Disabled by default; enable with ``tracer.enable()``
  or ``MOSAIC_TPU_TRACE=1``.  ``MosaicContext.call`` wraps every by-name
  dispatch in a span, so external engines driving the string surface get
  per-function wall times for free.
* ``record_command`` / ``record_error`` — the GDALCalc metadata pattern:
  raster operators stamp what ran (and what failed) into ``tile.meta``;
  both also bump registry counters so fleet-wide rates are visible.
* ``device_trace`` — context manager around ``jax.profiler.trace`` for
  XLA/TPU timeline captures (inspect with tensorboard or xprof; lay the
  Chrome-trace export of host spans beside it to line host stages up
  with device activity).

``tracer.enable()`` also enables the metrics registry (span call-sites
feed counters/gauges into it); ``disable()`` turns the registry back off
unless ``MOSAIC_TPU_METRICS`` asked for it independently.
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from .metrics import Histogram, metrics

__all__ = ["Tracer", "tracer", "record_command", "record_error",
           "device_trace"]

_MAX_EVENTS = 100_000   # bounded Chrome-trace ring (~10 MB of JSON)


class _Span:
    __slots__ = ("name", "total_s", "calls", "max_s", "hist")

    def __init__(self, name: str):
        self.name = name
        self.total_s = 0.0
        self.calls = 0
        self.max_s = 0.0
        self.hist = Histogram(name)


class Tracer:
    """Span wall-times + named counters, thread-safe, ~zero cost when
    disabled (one attribute check per span)."""

    def __init__(self):
        self._enabled = bool(os.environ.get("MOSAIC_TPU_TRACE"))
        self._lock = threading.Lock()
        self._spans: Dict[str, _Span] = {}
        self._counters: Dict[str, float] = {}
        self._stack = threading.local()
        self._events: "collections.deque[Tuple[str, float, float, int]]" \
            = collections.deque(maxlen=_MAX_EVENTS)
        self._epoch = time.perf_counter()

    # -- switches
    def enable(self) -> None:
        self._enabled = True
        metrics.enable()

    def disable(self) -> None:
        self._enabled = False
        if not os.environ.get("MOSAIC_TPU_METRICS"):
            metrics.disable()

    @property
    def enabled(self) -> bool:
        return self._enabled

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self._counters.clear()
            self._events.clear()
            self._epoch = time.perf_counter()
        metrics.reset()

    # -- spans
    @contextlib.contextmanager
    def span(self, name: str):
        if not self._enabled:
            yield
            return
        stack: List[str] = getattr(self._stack, "names", None) or []
        self._stack.names = stack
        stack.append(name)
        qual = "/".join(stack)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            dt = t1 - t0
            stack.pop()
            with self._lock:
                s = self._spans.setdefault(qual, _Span(qual))
                s.total_s += dt
                s.calls += 1
                s.max_s = max(s.max_s, dt)
                s.hist.observe(dt)
                self._events.append(
                    (qual, t0 - self._epoch, dt, threading.get_ident()))

    def current_label(self) -> Optional[str]:
        """Innermost active span on this thread (None outside spans).
        Used by ``obs.jaxmon`` to attribute anonymous JAX compile events
        to whatever stage triggered them."""
        stack = getattr(self._stack, "names", None)
        return "/".join(stack) if stack else None

    # -- counters
    def count(self, name: str, value: float = 1.0) -> None:
        if not self._enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    # -- Chrome-trace events
    def events(self) -> List[Tuple[str, float, float, int]]:
        """Snapshot of (qualified name, start offset s, duration s,
        thread id) complete-span events, oldest first."""
        with self._lock:
            return list(self._events)

    # -- reporting
    def report(self) -> Dict[str, object]:
        """One-stop snapshot: per-stage span histograms plus everything
        the metrics registry holds (counters merged; tracer-local names
        win on collision)."""
        reg = metrics.report()
        with self._lock:
            spans = {}
            for n, s in self._spans.items():
                h = s.hist.snapshot()
                spans[n] = {"total_s": s.total_s, "calls": s.calls,
                            "max_s": s.max_s, "p50_s": h["p50"],
                            "p95_s": h["p95"], "p99_s": h["p99"]}
            counters = dict(reg["counters"])
            counters.update(self._counters)
            return {
                "spans": spans,
                "counters": counters,
                "gauges": reg["gauges"],
                "histograms": reg["histograms"],
            }

    def format_report(self) -> str:
        rep = self.report()
        lines = [f"{'span':<44} {'calls':>6} {'total_s':>9} "
                 f"{'p50_s':>8} {'p95_s':>8} {'max_s':>8}"]
        for n, s in sorted(rep["spans"].items(),
                           key=lambda kv: -kv[1]["total_s"]):
            lines.append(f"{n:<44} {s['calls']:>6} "
                         f"{s['total_s']:>9.4f} {s['p50_s']:>8.4f} "
                         f"{s['p95_s']:>8.4f} {s['max_s']:>8.4f}")
        for n, v in sorted(rep["counters"].items()):
            lines.append(f"counter {n} = {v:g}")
        for n, v in sorted(rep["gauges"].items()):
            lines.append(f"gauge {n} = {v:g}")
        for n, h in sorted(rep["histograms"].items()):
            lines.append(f"hist {n}: count={h['count']} "
                         f"p50={h['p50']:g} p95={h['p95']:g} "
                         f"p99={h['p99']:g}")
        return "\n".join(lines)


tracer = Tracer()


# -- raster-op provenance (reference: GDALCalc.scala:39-55 records
#    last_command / last_error / full_error into tile metadata)

def record_command(tile, command: str) -> None:
    tile.meta["last_command"] = command
    metrics.count("raster/commands")


def record_error(tile, err: BaseException) -> None:
    tile.meta["last_error"] = f"{type(err).__name__}: {err}"[:200]
    tile.meta["full_error"] = repr(err)
    metrics.count(f"raster/errors/{type(err).__name__}")


@contextlib.contextmanager
def device_trace(logdir: str, host_tracer_level: int = 2):
    """Capture an XLA/TPU profiler timeline into ``logdir`` (reference
    analogue: the Spark UI stage timeline).  View with xprof/tensorboard."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()
