"""Device-side sorted-table lookups.

The reference's PIP join is a Spark hash-exchange equi-join on cell id
(SURVEY.md P2/P3; Quickstart join on ``pickup_h3 == mosaic_index.index_id``).
On TPU the broadcast side (the tessellated polygon index) is a sorted int64
table resident in HBM and the "join" is a vectorized binary search — a
handful of gathers per point, no hashing, no dynamic shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def searchsorted(table: jnp.ndarray, keys: jnp.ndarray) -> jnp.ndarray:
    """Branchless binary search: first index where table[i] >= key.

    table [T] sorted int64, keys [...] int64 -> [...] int32 in [0, T].
    Unrolled to ceil(log2(T)) steps — static shapes, no while_loop, so XLA
    fuses it with the surrounding gather/compare work.
    """
    t = table.shape[0]
    if t == 0:
        return jnp.zeros(keys.shape, jnp.int32)
    lo = jnp.zeros(keys.shape, jnp.int32)
    hi = jnp.full(keys.shape, t, jnp.int32)
    steps = max(1, t.bit_length())
    for _ in range(steps):
        mid = (lo + hi) >> 1
        v = table[jnp.clip(mid, 0, t - 1)]
        active = lo < hi
        go_right = active & (v < keys)
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
    return lo


def lookup(table: jnp.ndarray, keys: jnp.ndarray):
    """(index, found) of each key in a sorted table (exact match)."""
    if table.shape[0] == 0:
        return (jnp.zeros(keys.shape, jnp.int32),
                jnp.zeros(keys.shape, bool))
    idx = searchsorted(table, keys)
    safe = jnp.clip(idx, 0, table.shape[0] - 1)
    found = table[safe] == keys
    return safe, found
