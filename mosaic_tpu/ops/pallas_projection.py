"""Pallas TPU kernel: H3 lattice projection (the PIP join's front end).

The dense-window join is three stages: projection (pure arithmetic),
entry-table gather, chip-pool gather + parity.  The gathers are XLA's
job (TPU gather issue rate is the constraint, not fusion); the
projection is the hot arithmetic stage — ~200 f32 ops/point of
double-single (df) chains ending in cube rounding — and is exactly the
shape Pallas wants: one VMEM-resident elementwise pass, no HBM round
trips between the trig, the 20-face selection and the rounding.

Face tables are baked into the kernel as python-float constants and the
20-face argmax/selection is an unrolled select chain — no gathers, no
dynamic shapes, every op in the Mosaic-supported set.

df arithmetic here is BARRIER-FREE: ops/twofloat.py pins intermediates
with optimization_barrier to survive XLA:CPU's fma contraction, but
inside a Pallas kernel the Mosaic compiler lowers ops 1:1 (no
contraction pass), and optimization_barrier is not lowerable — so the
kernel carries its own plain Dekker helpers.  Consequence: the
interpret-mode (CPU) tests only check structural agreement with the
reference path; the full precision contract is asserted on real TPU in
tests_tpu/.

Status: opt-in (MOSAIC_PIP_PALLAS=1 routes the dense join's projection
through this kernel) until validated on hardware; semantics are pinned
by tests either way.
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.index.h3.constants import M_SIN60
from ..core.index.h3.hexmath import face_center_xyz, scaled_bases

_BLOCK = 1024


# ---------------------------------------------- barrier-free df helpers

def _two_sum(a, b):
    s = a + b
    bb = s - a
    return s, (a - (s - bb)) + (b - bb)


def _fast_two_sum(a, b):
    s = a + b
    return s, b - (s - a)


def _two_prod(a, b):
    split = jnp.float32(4097.0)
    p = a * b
    ca = split * a
    ahi = ca - (ca - a)
    alo = a - ahi
    cb = split * b
    bhi = cb - (cb - b)
    blo = b - bhi
    err = ((ahi * bhi - p) + ahi * blo + alo * bhi) + alo * blo
    return p, err


def _df_add(x, y):
    s, e = _two_sum(x[0], y[0])
    e = e + (x[1] + y[1])
    return _fast_two_sum(s, e)


def _df_sub(x, y):
    return _df_add(x, (-y[0], -y[1]))


def _df_mul(x, y):
    p, e = _two_prod(x[0], y[0])
    e = e + (x[0] * y[1] + x[1] * y[0])
    return _fast_two_sum(p, e)


def _df_div(x, y):
    q1 = x[0] / y[0]
    r = _df_sub(x, _df_mul(y, (q1, jnp.float32(0.0))))
    q2 = (r[0] + r[1]) / y[0]
    return _fast_two_sum(q1, q2)


def _df_const(v: float):
    hi = np.float32(v)
    lo = np.float32(np.float64(v) - np.float64(hi))
    return (jnp.float32(hi), jnp.float32(lo))


def _df_poly_sin(d):
    d2 = _df_mul(d, d)
    t = _df_sub(_df_const(1.0), (d2[0] * np.float32(1 / 20.0),
                                 d2[1] * np.float32(1 / 20.0)))
    t = _df_sub(_df_const(1.0),
                _df_mul((d2[0] * np.float32(1 / 6.0),
                         d2[1] * np.float32(1 / 6.0)), t))
    return _df_mul(d, t)


def _df_poly_cos(d):
    d2 = _df_mul(d, d)
    t = _df_sub(_df_const(1.0), (d2[0] * np.float32(1 / 30.0),
                                 d2[1] * np.float32(1 / 30.0)))
    t = _df_sub(_df_const(1.0),
                _df_mul((d2[0] * np.float32(1 / 12.0),
                         d2[1] * np.float32(1 / 12.0)), t))
    return _df_sub(_df_const(1.0),
                   _df_mul((d2[0] * np.float32(0.5),
                            d2[1] * np.float32(0.5)), t))


def _trig_local(d_deg, origin_deg: float):
    rad = _df_mul((d_deg, jnp.float32(0.0)), _df_const(math.pi / 180.0))
    s_d = _df_poly_sin(rad)
    c_d = _df_poly_cos(rad)
    o = math.radians(origin_deg)
    s0 = _df_const(math.sin(o))
    c0 = _df_const(math.cos(o))
    sin = _df_add(_df_mul(s0, c_d), _df_mul(c0, s_d))
    cos = _df_sub(_df_mul(c0, c_d), _df_mul(s0, s_d))
    return sin, cos


def _make_kernel(res: int, origin: Tuple[float, float]):
    f_xyz = face_center_xyz()                          # [20, 3] f64
    e1, e2 = scaled_bases(res)
    tables = np.concatenate([f_xyz, e1, e2], axis=1)   # [20, 9]
    t_hi = tables.astype(np.float32)
    t_lo = (tables - t_hi.astype(np.float64)).astype(np.float32)
    lon0, lat0 = origin

    def kernel(x_ref, y_ref, face_ref, a_ref, b_ref, m_ref, g_ref):
        x = x_ref[...]
        y = y_ref[...]
        sin_lat, cos_lat = _trig_local(y, lat0)
        sin_lng, cos_lng = _trig_local(x, lon0)
        X = _df_mul(cos_lat, cos_lng)
        Y = _df_mul(cos_lat, sin_lng)
        Z = sin_lat

        # 20-face argmax on hi parts (unrolled)
        best = jnp.full_like(x, -2.0)
        second = jnp.full_like(x, -2.0)
        face = jnp.zeros_like(x, dtype=jnp.int32)
        for f in range(20):
            d = (X[0] * np.float32(f_xyz[f, 0]) +
                 Y[0] * np.float32(f_xyz[f, 1]) +
                 Z[0] * np.float32(f_xyz[f, 2]))
            better = d > best
            second = jnp.where(better, best, jnp.maximum(second, d))
            face = jnp.where(better, jnp.int32(f), face)
            best = jnp.where(better, d, best)
        gap = best - second

        # per-face basis selection (unrolled selects, exact)
        sel = [(jnp.zeros_like(x), jnp.zeros_like(x)) for _ in range(9)]
        for f in range(20):
            hit = face == f
            for k in range(9):
                sel[k] = (jnp.where(hit, np.float32(t_hi[f, k]),
                                    sel[k][0]),
                          jnp.where(hit, np.float32(t_lo[f, k]),
                                    sel[k][1]))

        def dot3(k):
            acc = _df_mul(X, sel[k])
            acc = _df_add(acc, _df_mul(Y, sel[k + 1]))
            return _df_add(acc, _df_mul(Z, sel[k + 2]))

        u = dot3(0)
        px = _df_div(dot3(3), u)
        py = _df_div(dot3(6), u)

        rf = _df_mul(py, _df_const(1.0 / M_SIN60))
        qf = _df_sub(px, (rf[0] * np.float32(0.5),
                          rf[1] * np.float32(0.5)))
        sf = _df_sub((-qf[0], -qf[1]), rf)

        def df_round(v):
            r = jnp.round(v[0])
            frac = (v[0] - r) + v[1]
            adj = jnp.where(frac > 0.5, 1.0, 0.0) - \
                jnp.where(frac < -0.5, 1.0, 0.0)
            return r + adj, frac - adj

        rq, fq = df_round(qf)
        rr, fr = df_round(rf)
        rs, fs = df_round(sf)
        dq = jnp.abs(fq)
        dr = jnp.abs(fr)
        ds = jnp.abs(fs)
        fix_q = (dq > dr) & (dq > ds)
        fix_r = (~fix_q) & (dr > ds)
        rq2 = jnp.where(fix_q, -rr - rs, rq)
        rr2 = jnp.where(fix_r, -rq2 - rs, rr)
        fq = fq + (rq - rq2)
        fr = fr + (rr - rr2)

        vx = fq + np.float32(0.5) * fr
        vy = np.float32(M_SIN60) * fr
        h = np.float32(0.5) * vx
        sv = np.float32(M_SIN60) * vy
        proj = jnp.maximum(jnp.abs(vx),
                           jnp.maximum(jnp.abs(h + sv),
                                       jnp.abs(h - sv)))
        face_ref[...] = face
        a_ref[...] = (rq2 + rr2).astype(jnp.int32)
        b_ref[...] = rr2.astype(jnp.int32)
        m_ref[...] = jnp.maximum(np.float32(0.5) - proj,
                                 np.float32(0.0))
        g_ref[...] = gap

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("res", "origin", "interpret"))
def project_lattice_pallas(xy_local: jnp.ndarray, res: int,
                           origin: Tuple[float, float],
                           interpret: bool = False):
    """Pallas version of jaxkernel._project_df (df path, localized
    input): [N, 2] local degrees -> (face, a, b, margin_lattice,
    facegap).  N is padded internally to the block size."""
    from jax.experimental import pallas as pl

    n = xy_local.shape[0]
    nb = -(-max(n, 1) // _BLOCK)
    pad = nb * _BLOCK - n
    x = jnp.pad(xy_local[:, 0].astype(jnp.float32), (0, pad))
    y = jnp.pad(xy_local[:, 1].astype(jnp.float32), (0, pad))
    x = x.reshape(nb, _BLOCK)
    y = y.reshape(nb, _BLOCK)
    kernel = _make_kernel(res, origin)
    spec = pl.BlockSpec((1, _BLOCK), lambda i: (i, 0))
    out = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[spec, spec],
        out_specs=[spec] * 5,
        out_shape=[
            jax.ShapeDtypeStruct((nb, _BLOCK), jnp.int32),
            jax.ShapeDtypeStruct((nb, _BLOCK), jnp.int32),
            jax.ShapeDtypeStruct((nb, _BLOCK), jnp.int32),
            jax.ShapeDtypeStruct((nb, _BLOCK), jnp.float32),
            jax.ShapeDtypeStruct((nb, _BLOCK), jnp.float32),
        ],
        interpret=interpret,
    )(x, y)
    face, a, b, margin, gap = [o.reshape(-1)[:n] for o in out]
    return face, a, b, margin, gap
