"""Double-single ("df") arithmetic: ~46-bit precision from f32 pairs.

TPUs have no fast float64 (the VPU/MXU are f32/bf16 engines), but the
grid kernels need better-than-f32 precision in a few places — the H3
gnomonic projection (ops/../index/h3/jaxkernel.py) must place a point on
a hex lattice whose extent is ~6e5 cell widths at res 15, and the PIP
join's edge-crossing test must be exact relative to the f32-quantized
chip representation.  The reference gets this for free from JVM/JNI
float64 (H3IndexSystem.scala:168 -> native h3); here the classic
Dekker/Knuth error-free transformations provide it as plain f32 tensor
ops that XLA fuses like any other elementwise work (~5-17 flops per op).

A df value is a pair (hi, lo) with hi = fl(hi + lo) and |lo| <= ulp(hi)/2,
representing hi + lo exactly.  All ops assume round-to-nearest f32 and no
reassociation — XLA preserves both (it does not apply unsafe FP
optimizations to these ops).

References: Dekker (1971), "A floating-point technique for extending the
available precision"; Hida/Li/Bailey's ddfun patterns.  The constants use
the f32 Veltkamp split factor 2^12 + 1.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

_SPLIT = np.float32(4097.0)          # 2^12 + 1 (f32 has 24-bit mantissa)


def _ob(x: jnp.ndarray) -> jnp.ndarray:
    """Pin a rounded intermediate.

    XLA CPU evaluates f32 chains with excess precision by default
    (xla_allow_excess_precision), which makes Dekker error terms vanish —
    (a - (s - bb)) is only the rounding error if s was actually rounded
    to f32.  An optimization_barrier forces the materialization without
    blocking unrelated fusion.  Measured: without it, two_sum's error
    term collapses to 0 on XLA:CPU and df degrades to plain f32."""
    return jax.lax.optimization_barrier(x)


class DF(NamedTuple):
    """A double-single value hi + lo (both f32 tensors)."""

    hi: jnp.ndarray
    lo: jnp.ndarray

    def to_f32(self) -> jnp.ndarray:
        return self.hi

    def neg(self) -> "DF":
        return DF(-self.hi, -self.lo)


def df_const(x: Union[float, np.ndarray]) -> DF:
    """Split host f64 value(s) into an exact df pair (trace-time)."""
    x = np.asarray(x, np.float64)
    hi = x.astype(np.float32)
    lo = (x - hi.astype(np.float64)).astype(np.float32)
    return DF(jnp.asarray(hi), jnp.asarray(lo))


def df_from_f32(x: jnp.ndarray) -> DF:
    return DF(x, jnp.zeros_like(x))


def two_sum(a: jnp.ndarray, b: jnp.ndarray) -> Tuple[jnp.ndarray,
                                                     jnp.ndarray]:
    """s + err == a + b exactly (Knuth; no magnitude assumption)."""
    s = _ob(a + b)
    bb = _ob(s - a)
    err = (a - _ob(s - bb)) + (b - bb)
    return s, err


def fast_two_sum(a: jnp.ndarray, b: jnp.ndarray) -> Tuple[jnp.ndarray,
                                                          jnp.ndarray]:
    """s + err == a + b exactly, REQUIRES |a| >= |b| (Dekker)."""
    s = _ob(a + b)
    err = b - _ob(s - a)
    return s, err


def two_prod(a: jnp.ndarray, b: jnp.ndarray) -> Tuple[jnp.ndarray,
                                                      jnp.ndarray]:
    """p + err == a * b exactly (Veltkamp split; no fma dependence)."""
    p = _ob(a * b)
    ca = _ob(_SPLIT * a)
    ahi = _ob(ca - _ob(ca - a))
    alo = a - ahi
    cb = _ob(_SPLIT * b)
    bhi = _ob(cb - _ob(cb - b))
    blo = b - bhi
    err = ((_ob(ahi * bhi) - p) + _ob(ahi * blo) + _ob(alo * bhi)) + \
        alo * blo
    return p, err


def df_add(x: DF, y: DF) -> DF:
    """df + df (~11 flops, error <= 4 ulp²)."""
    s, e = two_sum(x.hi, y.hi)
    e = e + (x.lo + y.lo)
    hi, lo = fast_two_sum(s, e)
    return DF(hi, lo)


def df_sub(x: DF, y: DF) -> DF:
    return df_add(x, y.neg())


def df_mul(x: DF, y: DF) -> DF:
    """df * df (~20 flops)."""
    p, e = two_prod(x.hi, y.hi)
    e = e + (x.hi * y.lo + x.lo * y.hi)
    hi, lo = fast_two_sum(p, e)
    return DF(hi, lo)


def df_mul_f32(x: DF, c: jnp.ndarray) -> DF:
    p, e = two_prod(x.hi, c)
    e = e + x.lo * c
    hi, lo = fast_two_sum(p, e)
    return DF(hi, lo)


def df_div(x: DF, y: DF) -> DF:
    """df / df via one Newton-corrected quotient."""
    q1 = x.hi / y.hi
    r = df_sub(x, df_mul_f32(y, q1))
    q2 = (r.hi + r.lo) / y.hi
    hi, lo = fast_two_sum(q1, q2)
    return DF(hi, lo)


def df_dot3(ax: DF, ay: DF, az: DF, bx: DF, by: DF, bz: DF) -> DF:
    """ax*bx + ay*by + az*bz in df."""
    return df_add(df_add(df_mul(ax, bx), df_mul(ay, by)), df_mul(az, bz))


def df_round(x: DF) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(nearest integer as f32, signed residual x - round(x) as f32).

    hi - round(hi) is exact (same-binade subtraction), so the residual
    carries the full df precision collapsed to f32 — valid while the
    residual magnitude stays well above ulp(hi), which the caller's
    error budget guarantees."""
    r = jnp.round(x.hi)
    frac = (x.hi - r) + x.lo
    # df rounding can land on the far side of a half-integer boundary
    adj = jnp.where(frac > 0.5, 1.0, 0.0) - jnp.where(frac < -0.5, 1.0,
                                                      0.0)
    r = r + adj
    frac = frac - adj
    return r, frac


def df_poly_sin(d: DF) -> DF:
    """sin(d) for |d| <= 0.04 rad by Taylor series in df.

    Error < d^7/5040 ~ 3e-14 at the bound — below df resolution.  The
    H3 kernel guarantees the bound by limiting the localized window
    (jaxkernel.MAX_LOCAL_DEG)."""
    d2 = df_mul(d, d)
    # d * (1 - d2/6 * (1 - d2/20))
    t = df_sub(df_const(1.0), df_mul_f32(d2, np.float32(1.0 / 20.0)))
    t = df_sub(df_const(1.0), df_mul(df_mul_f32(d2, np.float32(1.0 / 6.0)),
                                     t))
    return df_mul(d, t)


def df_poly_cos(d: DF) -> DF:
    """cos(d) for |d| <= 0.04 rad by Taylor series in df (err < 1e-15)."""
    d2 = df_mul(d, d)
    # 1 - d2/2 * (1 - d2/12 * (1 - d2/30))
    t = df_sub(df_const(1.0), df_mul_f32(d2, np.float32(1.0 / 30.0)))
    t = df_sub(df_const(1.0), df_mul(df_mul_f32(d2, np.float32(1.0 / 12.0)),
                                     t))
    t = df_sub(df_const(1.0), df_mul(df_mul_f32(d2, np.float32(0.5)), t))
    return t
