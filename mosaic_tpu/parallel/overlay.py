"""Distributed polygon x polygon overlay join (P3): both sides sharded.

Reference mechanism: Spark hash-exchanges tessellated chips on cell id
(expressions/index/MosaicExplode.scala:70-79 feeding an equi-join), so
neither polygon set needs to fit on one executor.  SURVEY.md P3 names
the TPU-native equivalent: the equi-join becomes a cell-id-bucketed
all-to-all over ICI.

Pipeline (shard_map over the mesh's data axis):

  1. each device holds an arbitrary row-block of A-chips and B-chips
     (ingest placement);
  2. rows route to device hash(cell) % D via ONE jax.lax.all_to_all
     (fixed-capacity buckets: static shapes; overflow is counted and
     surfaced, never silently dropped);
  3. the local join is the sorted-table probe from the PIP join — sort
     local A rows by cell, binary-search each B row, probe duplicates;
  4. chip-pair ST_Intersects runs as dense f32 edge tests (segment
     crossings + representative-vertex containment);
  5. per-pair hits psum into a replicated [GA, GB] boolean matrix.

Exactness contract (same shape as pip_join): f32 hazards — near-touching
edges within EPS of crossing, or representative vertices within EPS of a
boundary — flag the pair; flagged pairs re-run on host in f64 against
the ORIGINAL geometries (overlay_host_pair).  ST_Intersects of two
polygons that merely share a tessellation cell but do not touch is
False, so the cell co-location is only the candidate filter, exactly as
in the reference.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

from ..core.geometry.array import GeometryArray
from ..core.index.base import IndexSystem
from ..core.tessellate import tessellate
from ..obs.context import traced
from ..resilience import faults
from ..types import ChipSet

EPS_DEG = 1e-6


# ----------------------------------------------------------- host packing

def pack_chip_rows(polys: GeometryArray, res: int, grid: IndexSystem,
                   chips: Optional[ChipSet] = None,
                   origin: Optional[np.ndarray] = None,
                   edge_cap: Optional[int] = None):
    """ChipSet -> dense device rows (cell i64, geom i32, edges [E, 4]
    f32 local, valid bool).

    Core chips carry the full cell boundary as their edge soup?  No —
    core cells are *fully covered* by their polygon, so for overlay
    purposes a core chip is the cell itself; tessellate(keep_core_geom
    =True) already emits the cell polygon for core chips."""
    if chips is None:
        chips = tessellate(polys, res, grid, keep_core_geom=True)
    from ..core.geometry.padded import build_edges_np
    A, B, M = build_edges_np(chips.geoms)
    if origin is None:
        bb = polys.bboxes()
        origin = np.round(np.array(
            [np.nanmean(bb[:, [0, 2]]), np.nanmean(bb[:, [1, 3]])]), 1)
    cap = edge_cap or A.shape[1]
    n, e = A.shape[:2]
    edges = np.full((n, cap, 4), 1e9, np.float32)
    e = min(e, cap)
    edges[:, :e, 0] = (A[:, :e, 0] - origin[0]).astype(np.float32)
    edges[:, :e, 1] = (A[:, :e, 1] - origin[1]).astype(np.float32)
    edges[:, :e, 2] = (B[:, :e, 0] - origin[0]).astype(np.float32)
    edges[:, :e, 3] = (B[:, :e, 1] - origin[1]).astype(np.float32)
    edges[~np.broadcast_to(M[:, :cap, None], edges.shape)] = 1e9
    valid = M[:, :cap].any(axis=1)
    assert M[:, cap:].sum() == 0, "edge_cap clipped real edges"
    return (chips.cell_id.astype(np.int64),
            chips.geom_id.astype(np.int32), edges, valid, origin, chips)


def _pad_rows(cell, ids, edges, valid, rows_per_dev: int, n_dev: int):
    """Round-robin row-block placement padded to [n_dev*rows_per_dev].
    ``ids`` keeps its dtype (int32 geom ids or int64 row ids)."""
    n = len(cell)
    total = rows_per_dev * n_dev
    assert n <= total, (n, total)
    pad = total - n
    cell = np.concatenate([cell, np.full(pad, -1, np.int64)])
    ids = np.concatenate([ids, np.full(pad, -1, ids.dtype)])
    edges = np.concatenate(
        [edges, np.full((pad, *edges.shape[1:]), 1e9, np.float32)])
    valid = np.concatenate([valid, np.zeros(pad, bool)])
    return cell, ids, edges, valid


# ----------------------------------------------------------- device logic

def _hash_dest(cell, n_dev: int):
    """Cheap int64 mix -> device index (valid rows only)."""
    import jax.numpy as jnp
    mix = np.uint64(0x9E3779B97F4A7C15).astype(np.int64)  # wraps signed
    h = cell * jnp.int64(mix)
    h = h ^ (h >> 29)
    return (h % n_dev + n_dev).astype(jnp.int32) % n_dev


def _hash_dest_np(cell: np.ndarray, n_dev: int) -> np.ndarray:
    """Host mirror of _hash_dest (same int64 wraparound semantics) —
    lets callers size the exchange buckets EXACTLY before compiling,
    so hash skew never triggers the double-capacity re-jit loop
    (VERDICT round-3 weak #5)."""
    mix = np.uint64(0x9E3779B97F4A7C15).astype(np.int64)
    with np.errstate(over="ignore"):
        h = np.asarray(cell, np.int64) * mix
    h = h ^ (h >> 29)
    return ((h % n_dev + n_dev) % n_dev).astype(np.int32)


def _exact_bucket_cap(cells: np.ndarray, valid: np.ndarray,
                      n_dev: int) -> int:
    """Exact per-device row count maximum for the exchange."""
    if not valid.any():
        return 64
    d = _hash_dest_np(cells[valid], n_dev)
    return max(64, int(np.bincount(d, minlength=n_dev).max()))


def _account_exchange(site: str, D: int, bucket_cap: int, cap_e: int,
                      id_bytes: int, cells: np.ndarray,
                      valid: np.ndarray) -> None:
    """Host-side collective accounting for one `_exchange_rows` run.

    Bytes come from the static send-buffer shapes each device pushes
    through the four all_to_alls (per row: cell i64 + id column +
    [cap_e, 4] f32 edges + valid bool; D*bucket_cap rows per device, D
    devices); shard skew is max/mean of the exact host-side hash
    destination counts (`_hash_dest_np` mirrors the device hash).  One
    attribute check when metrics are disabled."""
    from ..obs import metrics
    if not metrics.enabled:
        return
    row_bytes = 8 + id_bytes + cap_e * 16 + 1
    moved = float(D) * D * bucket_cap * row_bytes
    metrics.count("collective/all_to_all_bytes", moved)
    metrics.count(f"collective/all_to_all_bytes/{site}", moved)
    metrics.count("collective/all_to_all_calls", 4)
    v = np.asarray(valid, bool)
    if v.any():
        counts = np.bincount(_hash_dest_np(np.asarray(cells)[v], D),
                             minlength=D)
        mean = float(counts.mean())
        skew = float(counts.max()) / mean if mean else 1.0
        metrics.gauge(f"shard/skew/{site}", skew)
        # also a distribution so repeated exchanges build a time
        # series (p50/p95/p99), not just a last-value gauge
        metrics.observe(f"shard/skew_series/{site}", skew)
        metrics.gauge(f"shard/rows_max/{site}", float(counts.max()))
        # per-device fold: the exchange's routed-row counts land in
        # the device monitor (device/rows/* counters + dashboard)
        from ..obs.devicemon import devicemon
        devicemon.observe_rows(site, counts)


def _exact_dup_cap(cells_a: np.ndarray, valid_a: np.ndarray,
                   cells_b: np.ndarray, valid_b: np.ndarray) -> int:
    """Exact probe width: the max chip multiplicity among A cells that
    are actually PROBED (cells also present on the B side — sizing on
    all A cells over-ran the dup loop ~3x on the overlay bench)."""
    if not valid_a.any() or not valid_b.any():
        return 1
    ca = cells_a[valid_a]
    probed = np.isin(ca, cells_b[valid_b])
    if not probed.any():
        return 1
    _, counts = np.unique(ca[probed], return_counts=True)
    return max(1, int(counts.max()))


def _chip_pair_test(ea, eb, eps=EPS_DEG):
    """f32 intersects + hazard flag for one chip pair.

    ea, eb [E, 4] (ax, ay, bx, by; 1e9 sentinel padding).  Returns
    (hit, hazard).  hit = any proper segment crossing, or a
    representative vertex of one inside the other (if no edges cross,
    the chips are disjoint or nested — one containment test each way
    decides).  hazard = any orientation test or containment crossing
    within ``eps`` (absolute degrees; the caller scales it with the
    local-frame extent so it always covers f32 coordinate
    quantization)."""
    import jax.numpy as jnp

    a1 = ea[:, None, 0:2]
    b1 = ea[:, None, 2:4]
    a2 = eb[None, :, 0:2]
    b2 = eb[None, :, 2:4]

    def orient(p, q, r):
        return (q[..., 0] - p[..., 0]) * (r[..., 1] - p[..., 1]) - \
               (q[..., 1] - p[..., 1]) * (r[..., 0] - p[..., 0])

    d1 = orient(a2, b2, a1)
    d2 = orient(a2, b2, b1)
    d3 = orient(a1, b1, a2)
    d4 = orient(a1, b1, b2)
    pad = (jnp.abs(ea[:, None, 0]) > 1e8) | \
        (jnp.abs(eb[None, :, 0]) > 1e8)
    proper = ((d1 > 0) != (d2 > 0)) & ((d3 > 0) != (d4 > 0)) & ~pad
    # hazard band: an endpoint within EPS_DEG (absolute degrees) of the
    # other segment's line — |orient|/len(other) IS that perpendicular
    # distance.  (A len1*len2 normalization made the band proportional
    # to edge length: a ~100 m footprint edge got a 5e-10 deg band and a
    # real f32 miscall shipped unflagged — caught by the bench's
    # overlay parity check.)
    l1 = jnp.maximum(jnp.linalg.norm(b1 - a1, axis=-1), 1e-30)
    l2 = jnp.maximum(jnp.linalg.norm(b2 - a2, axis=-1), 1e-30)
    tiny = ((jnp.minimum(jnp.abs(d1), jnp.abs(d2)) / l2 < eps) |
            (jnp.minimum(jnp.abs(d3), jnp.abs(d4)) / l1 < eps)) & \
        ~pad
    crossing = jnp.any(proper)

    def contains(point, e):
        px, py = point[0], point[1]
        ax, ay, bx, by = e[:, 0], e[:, 1], e[:, 2], e[:, 3]
        epad = jnp.abs(ax) > 1e8
        straddle = ((ay <= py) != (by <= py)) & ~epad
        t = (py - ay) / jnp.where(by == ay, 1.0, by - ay)
        xi = ax + t * (bx - ax)
        hits = straddle & (px < xi)
        inside = (jnp.sum(hits) & 1).astype(bool)
        near = jnp.any(straddle & (jnp.abs(px - xi) < eps)) | \
            jnp.any((jnp.abs(py - ay) < eps) & ~epad &
                    (px < jnp.maximum(ax, bx) + eps))
        return inside, near

    ina, na = contains(ea[0, 0:2], eb)
    inb, nb = contains(eb[0, 0:2], ea)
    hit = crossing | ina | inb
    hazard = jnp.any(tiny) | na | nb
    return hit, hazard


def _local_sorted_join(cell_a, geom_a, edges_a, valid_a,
                       cell_b, geom_b, edges_b, valid_b,
                       ga: int, gb: int, dup_cap: int,
                       eps: float = EPS_DEG):
    """Sorted-table probe join of local rows; returns (hits [ga, gb]
    i32, hazards [ga, gb] i32, max_dup_needed)."""
    import jax
    import jax.numpy as jnp

    big = jnp.int64(0x7FFFFFFFFFFFFFFF)
    key_a = jnp.where(valid_a, cell_a, big)
    order = jnp.argsort(key_a)
    key_a = key_a[order]
    geom_a = geom_a[order]
    edges_a = edges_a[order]

    start = jnp.searchsorted(key_a, jnp.where(valid_b, cell_b, -big))
    upper = jnp.searchsorted(key_a, jnp.where(valid_b, cell_b, -big),
                             side="right")
    dup_needed = jnp.max(jnp.where(valid_b, upper - start, 0))

    pair_fn = jax.vmap(
        lambda ea, eb: _chip_pair_test(ea, eb, jnp.float32(eps)))
    na = key_a.shape[0]

    # duplicate probe as a fori_loop: program size stays constant when
    # crowded cells force dup_cap up (an unrolled python loop re-traced
    # thousands of pair-test vmaps at dup_cap retries)
    def body(j, carry):
        hits, hazards = carry
        s = jnp.clip(start + j, 0, max(na - 1, 0))
        match = valid_b & (start + j < upper)
        h, hz = pair_fn(edges_a[s], edges_b)
        ga_i = jnp.where(match, geom_a[s], 0)
        gb_i = jnp.where(match, geom_b, 0)
        add_h = (h & match).astype(jnp.int32)
        add_z = (hz & match).astype(jnp.int32)
        hits = hits.at[ga_i, gb_i].max(add_h, mode="drop")
        hazards = hazards.at[ga_i, gb_i].max(add_z, mode="drop")
        return hits, hazards

    # under shard_map the carry must already be device-varying (the loop
    # body's scatters are), so seed it with a varying zero
    zero = (cell_b[:1].astype(jnp.int32) * 0).reshape(())
    init = jnp.zeros((ga, gb), jnp.int32) + zero
    hits, hazards = jax.lax.fori_loop(0, dup_cap, body, (init, init))
    return hits, hazards, dup_needed


def make_overlay_fn(ga: int, gb: int, edge_cap_a: int, edge_cap_b: int,
                    mesh=None, axis: str = "data",
                    bucket_cap: int = 0, dup_cap: int = 8,
                    eps: float = EPS_DEG):
    """Build the (optionally sharded) overlay ST_Intersects kernel.

    Returns fn(cell_a, geom_a, edges_a, valid_a, cell_b, ...) ->
    (hits [ga, gb] i32, hazards [ga, gb] i32, diag [3] i32 =
    (overflow_a, overflow_b, dup_needed)).  Without a mesh it is the
    single-device join (no exchange); with a mesh, rows all_to_all to
    hash(cell) % D first."""
    import jax
    import jax.numpy as jnp

    from ..perf.jit_cache import kernel_cache

    if mesh is None:
        def fn(ca, gea, ea, va, cb, geb, eb, vb):
            h, z, dn = _local_sorted_join(ca, gea, ea, va, cb, geb, eb,
                                          vb, ga, gb, dup_cap, eps)
            return h, z, jnp.stack([jnp.int32(0), jnp.int32(0),
                                    dn.astype(jnp.int32)])
        return kernel_cache.get_or_build(
            "overlay/dense", (ga, gb, dup_cap, eps),
            lambda: jax.jit(fn))

    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:      # moved in newer jax; older keeps it here
        from jax.experimental.shard_map import shard_map
    D = mesh.shape[axis]
    assert bucket_cap > 0, "sharded overlay needs a bucket capacity"

    def local(ca, gea, ea, va, cb, geb, eb, vb):
        ca, gea, ea, va, ofa = _exchange_rows(
            ca, gea, ea, va, D, axis, bucket_cap, edge_cap_a)
        cb, geb, eb, vb, ofb = _exchange_rows(
            cb, geb, eb, vb, D, axis, bucket_cap, edge_cap_b)
        h, z, dn = _local_sorted_join(ca, gea, ea, va, cb, geb, eb, vb,
                                      ga, gb, dup_cap, eps)
        diag = jnp.stack([ofa.astype(jnp.int32), ofb.astype(jnp.int32),
                          dn.astype(jnp.int32)])
        return (jax.lax.psum(h, axis), jax.lax.psum(z, axis),
                jax.lax.pmax(diag, axis))

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis),
                  P(axis), P(axis), P(axis), P(axis)),
        out_specs=(P(), P(), P()))
    # id(mesh): same-shaped kernels on different meshes must not alias
    return kernel_cache.get_or_build(
        "overlay/dense_sharded",
        (ga, gb, edge_cap_a, edge_cap_b, id(mesh), axis, bucket_cap,
         dup_cap, eps),
        lambda: jax.jit(fn))


# ----------------------------------------------------- ragged pair output

def _compact_keys(keys, cap: int):
    """[M] int64 keys (-1 invalid) -> ([cap] desc-sorted keys, count,
    overflow).  Fixed capacity + overflow count: the same
    never-silently-drop discipline as the exchange buckets."""
    import jax.numpy as jnp
    valid = keys >= 0
    total = jnp.sum(valid)
    srt = jnp.sort(keys)[::-1]
    return srt[:cap], jnp.minimum(total, cap), \
        jnp.maximum(total - cap, 0)


def _local_pair_join(cell_a, row_a, edges_a, valid_a,
                     cell_b, row_b, edges_b, valid_b,
                     row_mult: int, dup_cap: int, pair_cap: int,
                     eps: float):
    """Sorted-table probe join emitting (hit|hazard) ROW pairs as a
    compacted key list instead of scattering into a dense matrix
    (VERDICT round-3 missing #4: the replicated [GA, GB] psum cannot
    scale to millions of footprints).  Key = row_a * row_mult + row_b
    over GLOBAL chip row ids; the caller maps rows to geometries or
    chip edges.  Returns (keys [pair_cap], count, overflow,
    dup_needed)."""
    import jax
    import jax.numpy as jnp

    big = jnp.int64(0x7FFFFFFFFFFFFFFF)
    key_a = jnp.where(valid_a, cell_a, big)
    order = jnp.argsort(key_a)
    key_a = key_a[order]
    row_a = row_a[order]
    edges_a = edges_a[order]

    probe = jnp.where(valid_b, cell_b, -big)
    start = jnp.searchsorted(key_a, probe)
    upper = jnp.searchsorted(key_a, probe, side="right")
    dup_needed = jnp.max(jnp.where(valid_b, upper - start, 0))

    pair_fn = jax.vmap(
        lambda ea, eb: _chip_pair_test(ea, eb, jnp.float32(eps)))
    na = key_a.shape[0]
    nb = cell_b.shape[0]

    def body(j, buf):
        s = jnp.clip(start + j, 0, max(na - 1, 0))
        match = valid_b & (start + j < upper)
        h, hz = pair_fn(edges_a[s], edges_b)
        emit = match & (h | hz)
        keys = jnp.where(
            emit, row_a[s] * jnp.int64(row_mult) + row_b,
            jnp.int64(-1))
        return jax.lax.dynamic_update_slice(buf, keys, (j * nb,))

    zero = (cell_b[:1] * 0).reshape(())     # device-varying seed
    buf = jnp.full((dup_cap * nb,), jnp.int64(-1)) + zero
    buf = jax.lax.fori_loop(0, dup_cap, body, buf)
    keys, count, overflow = _compact_keys(buf, pair_cap)
    return keys, count, overflow, dup_needed


def make_overlay_pairs_fn(row_mult: int, edge_cap_a: int,
                          edge_cap_b: int, mesh=None,
                          axis: str = "data", bucket_cap: int = 0,
                          dup_cap: int = 8, pair_cap: int = 0,
                          eps: float = EPS_DEG):
    """Build the pair-emitting overlay join kernel.

    fn(cell_a, row_a, edges_a, valid_a, cell_b, row_b, edges_b,
    valid_b) -> (keys, count, overflow_diag).  Without a mesh: one
    device, keys [pair_cap].  With a mesh: rows all_to_all to
    hash(cell) % D, each device emits its own compacted key block
    (out_specs sharded — NO replicated matrix, NO psum), and the diag
    carries (bucket_overflow_a, bucket_overflow_b, dup_needed,
    pair_overflow) maxed across devices."""
    import jax
    import jax.numpy as jnp

    from ..perf.jit_cache import kernel_cache

    assert pair_cap > 0
    if mesh is None:
        def fn(ca, ra, ea, va, cb, rb, eb, vb):
            keys, count, ovf, dn = _local_pair_join(
                ca, ra, ea, va, cb, rb, eb, vb, row_mult, dup_cap,
                pair_cap, eps)
            diag = jnp.stack([jnp.int32(0), jnp.int32(0),
                              dn.astype(jnp.int32),
                              ovf.astype(jnp.int32)])
            return keys, count[None], diag
        return kernel_cache.get_or_build(
            "overlay/pairs", (row_mult, dup_cap, pair_cap, eps),
            lambda: jax.jit(fn))

    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:      # moved in newer jax; older keeps it here
        from jax.experimental.shard_map import shard_map
    D = mesh.shape[axis]
    assert bucket_cap > 0

    def local(ca, ra, ea, va, cb, rb, eb, vb):
        ca, ra, ea, va, ofa = _exchange_rows(
            ca, ra, ea, va, D, axis, bucket_cap, edge_cap_a)
        cb, rb, eb, vb, ofb = _exchange_rows(
            cb, rb, eb, vb, D, axis, bucket_cap, edge_cap_b)
        keys, count, ovf, dn = _local_pair_join(
            ca, ra, ea, va, cb, rb, eb, vb, row_mult, dup_cap,
            pair_cap, eps)
        diag = jnp.stack([ofa.astype(jnp.int32), ofb.astype(jnp.int32),
                          dn.astype(jnp.int32), ovf.astype(jnp.int32)])
        return keys, count[None], jax.lax.pmax(diag, axis)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(axis),) * 8,
        out_specs=(P(axis), P(axis), P()))
    # id(mesh): same-shaped kernels on different meshes must not alias
    return kernel_cache.get_or_build(
        "overlay/pairs_sharded",
        (row_mult, edge_cap_a, edge_cap_b, id(mesh), axis, bucket_cap,
         dup_cap, pair_cap, eps),
        lambda: jax.jit(fn))


def _exchange_rows(cell, row, edges, valid, D: int, axis: str,
                   bucket_cap: int, cap_e: int):
    """all_to_all row exchange keyed on hash(cell) % D, carrying one
    id column (geom ids or global row ids — dtype preserved).  The
    single exchange implementation behind both the dense-matrix and
    the pair-emitting overlay paths."""
    import jax
    import jax.numpy as jnp
    dest = jnp.where(valid, _hash_dest(cell, D), D)
    order = jnp.argsort(dest)
    dest_s = dest[order]
    pos = jnp.arange(dest.shape[0], dtype=jnp.int32) - \
        jnp.searchsorted(dest_s, dest_s).astype(jnp.int32)
    overflow = jnp.sum((pos >= bucket_cap) & (dest_s < D))
    okrow = (dest_s < D) & (pos < bucket_cap)
    # bad rows route to device index D: out of bounds, so the
    # mode="drop" scatters discard them instead of clobbering the
    # last in-bounds slot
    d_i = jnp.where(okrow, dest_s, D)
    p_i = jnp.where(okrow, pos, 0)
    sc = jnp.full((D, bucket_cap), jnp.int64(-1))
    sr = jnp.full((D, bucket_cap), jnp.asarray(-1, row.dtype))
    se = jnp.full((D, bucket_cap, cap_e, 4), jnp.float32(1e9))
    sv = jnp.zeros((D, bucket_cap), bool)
    sc = sc.at[d_i, p_i].set(jnp.where(okrow, cell[order], -1),
                             mode="drop")
    sr = sr.at[d_i, p_i].set(jnp.where(okrow, row[order], -1),
                             mode="drop")
    se = se.at[d_i, p_i].set(jnp.where(okrow[:, None, None],
                                       edges[order], 1e9), mode="drop")
    sv = sv.at[d_i, p_i].set(okrow & valid[order], mode="drop")
    rc = jax.lax.all_to_all(sc, axis, 0, 0)
    rr = jax.lax.all_to_all(sr, axis, 0, 0)
    re = jax.lax.all_to_all(se, axis, 0, 0)
    rv = jax.lax.all_to_all(sv, axis, 0, 0)
    flat = lambda x: x.reshape((D * bucket_cap,) + x.shape[2:])
    return flat(rc), flat(rr), flat(re), flat(rv), overflow


@traced("overlay", "overlay/row_pairs")
def overlay_row_pairs(chips_a, chips_b, polys_a: GeometryArray,
                      polys_b: GeometryArray, res: int,
                      grid: IndexSystem, mesh=None,
                      axis: str = "data",
                      origin: Optional[np.ndarray] = None):
    """Distributed chip-row pair discovery: all (rowA, rowB) chip pairs
    that share a cell and (possibly) touch, as a ragged host list.

    Returns (rows_a [K], rows_b [K]) global chip-row indices.  Memory
    is bounded per device (capacity + overflow retry); the dense
    [GA, GB] matrix never materializes."""
    import jax.numpy as jnp

    ra = pack_chip_rows(polys_a, res, grid, chips=chips_a,
                        origin=origin)
    origin = ra[4]
    rb = pack_chip_rows(polys_b, res, grid, chips=chips_b,
                        origin=origin)
    ca, _, ea, va = ra[:4]
    cb, _, eb, vb = rb[:4]
    rowa = np.arange(len(ca), dtype=np.int64)
    rowb = np.arange(len(cb), dtype=np.int64)
    row_mult = int(len(cb)) + 1
    ext = 1.0
    for arr in (ea, eb):
        fin = arr[np.abs(arr) < 1e8]
        if len(fin):
            ext = max(ext, float(np.abs(fin).max()))
    eps = max(EPS_DEG, 64.0 * float(np.spacing(np.float32(ext))))

    dup_cap = faults.degrade("overlay.dup_cap",
                             _exact_dup_cap(ca, va, cb, vb))
    if mesh is not None:
        D = mesh.shape[axis]
        rpa = -(-len(ca) // D)
        rpb = -(-len(cb) // D)
        bucket_cap = faults.degrade(
            "overlay.bucket_cap",
            max(_exact_bucket_cap(ca, va, D),
                _exact_bucket_cap(cb, vb, D)))
        ca, rowa, ea, va = _pad_rows(ca, rowa, ea, va, rpa, D)
        cb, rowb, eb, vb = _pad_rows(cb, rowb, eb, vb, rpb, D)
        pair_cap = max(1024, 4 * max(rpa, rpb))
    else:
        pair_cap = max(1024, 4 * len(ca))
    args = tuple(jnp.asarray(v) for v in
                 (ca, rowa, ea, va, cb, rowb, eb, vb))
    while True:
        if mesh is None:
            fn = make_overlay_pairs_fn(
                row_mult, ea.shape[1], eb.shape[1], dup_cap=dup_cap,
                pair_cap=pair_cap, eps=eps)
        else:
            fn = make_overlay_pairs_fn(
                row_mult, ea.shape[1], eb.shape[1], mesh=mesh,
                axis=axis, bucket_cap=bucket_cap, dup_cap=dup_cap,
                pair_cap=pair_cap, eps=eps)
            _account_exchange("overlay_pairs", D, bucket_cap,
                              ea.shape[1], 8, ca, va)
            _account_exchange("overlay_pairs", D, bucket_cap,
                              eb.shape[1], 8, cb, vb)
        keys, counts, diag = fn(*args)
        diag = np.asarray(diag)
        if mesh is not None and (diag[0] > 0 or diag[1] > 0):
            bucket_cap *= 2
            continue
        if diag[2] > dup_cap:
            dup_cap = int(2 ** np.ceil(np.log2(max(diag[2], 2))))
            continue
        if diag[3] > 0:
            pair_cap *= 2
            continue
        break
    keys = np.asarray(keys).reshape(-1)
    counts = np.asarray(counts).reshape(-1)
    if mesh is None:
        valid = keys[:int(counts[0])]
    else:
        blocks = keys.reshape(len(counts), -1)
        valid = np.concatenate([blocks[d, :int(counts[d])]
                                for d in range(len(counts))])
    valid = np.unique(valid)
    return valid // row_mult, valid % row_mult


@traced("overlay", "overlay/intersection_area")
def overlay_intersection_area(polys_a: GeometryArray,
                              polys_b: GeometryArray, res: int,
                              grid: IndexSystem, mesh=None,
                              axis: str = "data"):
    """Distributed exact ST_IntersectionAgg AREA: for every
    intersecting polygon pair, the planar area of the intersection.

    Mechanism (reference: tessellate + equi-join on cell id feeding
    ST_IntersectionAgg, MosaicExplode.scala:70-79 +
    ST_IntersectionAgg.scala:41-58): chips partition each polygon
    within each cell, so area(A∩B) = Σ over shared cells of
    area(chipA ∩ chipB).  The sharded join emits candidate chip-row
    pairs (ragged, capacity-bounded); the exact per-pair areas run
    through the native fragment-shoelace kernel
    (clip.pairs_intersection_area), and a segment-sum folds them into
    per-(geomA, geomB) totals.

    Returns (ga [K], gb [K], area [K]) for pairs with area > 0."""
    from ..core.geometry.clip import pairs_intersection_area
    chips_a = tessellate(polys_a, res, grid, keep_core_geom=True)
    chips_b = tessellate(polys_b, res, grid, keep_core_geom=True)
    rows_a, rows_b = overlay_row_pairs(chips_a, chips_b, polys_a,
                                       polys_b, res, grid, mesh, axis)
    areas = pairs_intersection_area(chips_a.geoms, rows_a,
                                    chips_b.geoms, rows_b)
    ga = chips_a.geom_id[rows_a].astype(np.int64)
    gb = chips_b.geom_id[rows_b].astype(np.int64)
    mult = int(chips_b.geom_id.max(initial=0)) + 1
    key = ga * mult + gb
    uk, inv = np.unique(key, return_inverse=True)
    tot = np.zeros(len(uk))
    np.add.at(tot, inv, areas)
    keep = tot > 0
    return (uk[keep] // mult, uk[keep] % mult, tot[keep])


# ------------------------------------------------------------ host oracle

def overlay_host_pair(polys_a: GeometryArray, polys_b: GeometryArray,
                      ia: int, ib: int) -> bool:
    """Exact f64 ST_Intersects of one polygon pair (edge crossings +
    mutual containment via crossing number)."""
    from ..core.tessellate import _pip, _poly_edges, _seg_cross
    ea = _poly_edges(polys_a, ia)
    eb = _poly_edges(polys_b, ib)
    if len(ea) == 0 or len(eb) == 0:
        return False
    if np.any(_seg_cross(ea[:, None, 0], ea[:, None, 1],
                         eb[None, :, 0], eb[None, :, 1])):
        return True
    return bool(_pip(ea[:1, 0], eb)[0] or _pip(eb[:1, 0], ea)[0])


def overlay_host_truth(polys_a: GeometryArray,
                       polys_b: GeometryArray) -> np.ndarray:
    """[GA, GB] exact boolean intersects matrix (bbox-pruned)."""
    ba = polys_a.bboxes()
    bb = polys_b.bboxes()
    out = np.zeros((len(polys_a), len(polys_b)), bool)
    for i in range(len(polys_a)):
        cand = np.nonzero((ba[i, 0] <= bb[:, 2]) & (bb[:, 0] <= ba[i, 2])
                          & (ba[i, 1] <= bb[:, 3]) &
                          (bb[:, 1] <= ba[i, 3]))[0]
        for j in cand:
            out[i, j] = overlay_host_pair(polys_a, polys_b, i, int(j))
    return out


# -------------------------------------------------------------- end2end

@traced("overlay", "overlay/intersects")
def overlay_intersects(polys_a: GeometryArray, polys_b: GeometryArray,
                       res: int, grid: IndexSystem, mesh=None,
                       axis: str = "data") -> np.ndarray:
    """Distributed exact ST_Intersects overlay: [GA, GB] bool.

    Tessellates both sides, runs the (sharded) chip join, then resolves
    f32-hazard pairs on host in f64.  This is the BASELINE config 3
    (building footprints x flood zones) engine."""
    import jax.numpy as jnp

    rows_a = pack_chip_rows(polys_a, res, grid)
    origin = rows_a[4]
    rows_b = pack_chip_rows(polys_b, res, grid, origin=origin)
    ca, gea, ea, va = rows_a[:4]
    cb, geb, eb, vb = rows_b[:4]
    ga, gb = len(polys_a), len(polys_b)
    # hazard band scaled with the local-frame extent: f32 quantization
    # of a coordinate of magnitude m displaces vertices by ~ulp(m), so
    # a fixed 1e-6 band under-flags continent-scale inputs
    ext = 1.0
    for arr in (ea, eb):
        fin = arr[np.abs(arr) < 1e8]
        if len(fin):
            ext = max(ext, float(np.abs(fin).max()))
    eps = max(EPS_DEG, 64.0 * float(np.spacing(np.float32(ext))))

    dup_cap = faults.degrade("overlay.dup_cap",
                             _exact_dup_cap(ca, va, cb, vb))
    if mesh is not None:
        D = mesh.shape[axis]
        rpa = -(-len(ca) // D)
        rpb = -(-len(cb) // D)
        # size the exchange exactly from the host-computed hash — no
        # overflow retry/recompile is possible for buckets or dups
        # (unless a chaos plan degrades the capacity on purpose, which
        # exercises the overflow-retry loop below)
        bucket_cap = faults.degrade(
            "overlay.bucket_cap",
            max(_exact_bucket_cap(ca, va, D),
                _exact_bucket_cap(cb, vb, D)))
        ca, gea, ea, va = _pad_rows(ca, gea, ea, va, rpa, D)
        cb, geb, eb, vb = _pad_rows(cb, geb, eb, vb, rpb, D)
    args = tuple(jnp.asarray(v) for v in
                 (ca, gea, ea, va, cb, geb, eb, vb))
    # retry loops: bucket/dup capacities are static shapes, so a skewed
    # hash or a crowded cell grows them and re-runs instead of failing
    # (overflow is always detected, never silent)
    import time as _time
    t0 = _time.perf_counter()
    while True:
        if mesh is None:
            fn = make_overlay_fn(ga, gb, ea.shape[1], eb.shape[1],
                                 dup_cap=dup_cap, eps=eps)
        else:
            fn = make_overlay_fn(ga, gb, ea.shape[1], eb.shape[1],
                                 mesh=mesh, axis=axis,
                                 bucket_cap=bucket_cap, dup_cap=dup_cap,
                                 eps=eps)
            _account_exchange("overlay", D, bucket_cap, ea.shape[1], 4,
                              ca, va)
            _account_exchange("overlay", D, bucket_cap, eb.shape[1], 4,
                              cb, vb)
        h, z, diag = fn(*args)
        diag = np.asarray(diag)
        if mesh is not None and (diag[0] > 0 or diag[1] > 0):
            bucket_cap *= 2
            continue
        if diag[2] > dup_cap:
            dup_cap = int(2 ** np.ceil(np.log2(max(diag[2], 2))))
            continue
        break
    from ..obs import metrics
    if mesh is not None and metrics.enabled:
        # charge the sharded run's wall time to devices by routed-row
        # share (both sides' hash-destination counts) — feeds the
        # EXPLAIN ANALYZE device_ms column via obs.devicemon
        from ..obs.devicemon import devicemon
        w = np.zeros(D, np.int64)
        for cc, vv in ((ca, va), (cb, vb)):
            vv = np.asarray(vv, bool)
            if vv.any():
                w += np.bincount(
                    _hash_dest_np(np.asarray(cc)[vv], D), minlength=D)
        devicemon.attribute("overlay", _time.perf_counter() - t0, w)

    hits = np.asarray(h) > 0
    hz = np.asarray(z) > 0
    # f64 resolution of flagged pairs against the ORIGINAL geometries
    for i, j in zip(*np.nonzero(hz)):
        hits[i, j] = overlay_host_pair(polys_a, polys_b, int(i), int(j))
    return hits
