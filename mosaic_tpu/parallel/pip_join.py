"""The index-accelerated point-in-polygon join — the flagship pipeline.

Reference counterpart: the Quickstart workload
(notebooks/examples/python/Quickstart/QuickstartNotebook.ipynb): points get
``grid_pointascellid``, polygons get ``grid_tessellateexplode``, Spark
equi-joins on cell id, then filters ``is_core OR st_contains(chip, point)``.

TPU-first redesign: the tessellated polygon side becomes a device-resident
sorted cell-id table (core cells + border cells with padded chip edge
blocks).  The per-point pipeline is one fused XLA computation:

    cell   = grid.point_to_cell_jax(points)          # closed-form bit math
    islot  = binary-search cell in core/border table # ops.lookup
    inside = crossing-parity vs the <=D chips in the cell
    zone   = core hit ? core zone : first chip hit

No shuffle is needed while the polygon side fits in HBM (the reference's
broadcast-join regime; ~300 taxi zones → a few MB of chips).  Points shard
over the mesh's data axis via jax.sharding; the table replicates.  For
polygon×polygon joins both sides shard — see overlay.py (cell-bucketed
all_to_all).

Precision: device compute is float32; points whose distance to a chip
boundary is below ``eps`` are flagged and re-checked on host in float64
against the same chips, so results match the exact host path
(config.MosaicConfig.exact_fallback).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.geometry.array import GeometryArray
from ..core.geometry.padded import build_edges
from ..core.index.base import IndexSystem
from ..core.tessellate import tessellate
from ..ops.lookup import lookup
from ..types import ChipSet


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class PIPIndex:
    """Device-resident tessellation index of a polygon batch.

    core_cells   [C]        sorted cell ids fully inside some polygon
    core_zone    [C]        polygon id per core cell
    border_cells [B]        sorted cell ids on some polygon's boundary
                            (duplicates allowed: one entry per chip)
    border_zone  [B]        polygon id per chip
    chip_a/b     [B, E, 2]  chip edges (float32)
    chip_mask    [B, E]
    max_dup      static     max chips sharing one cell id (probe width)
    res          static     grid resolution
    """

    core_cells: jnp.ndarray
    core_zone: jnp.ndarray
    border_cells: jnp.ndarray
    border_zone: jnp.ndarray
    chip_a: jnp.ndarray
    chip_b: jnp.ndarray
    chip_mask: jnp.ndarray
    #: local-frame origin (lon, lat float64): chip coords are stored
    #: origin-shifted so float32 edge-crossing arithmetic operates on
    #: small magnitudes (absolute lon ~74° costs ~4e-5° of cancellation
    #: error — far above the eps band; shifted it is ~1e-7°)
    origin: jnp.ndarray
    max_dup: int
    res: int

    def tree_flatten(self):
        return ((self.core_cells, self.core_zone, self.border_cells,
                 self.border_zone, self.chip_a, self.chip_b,
                 self.chip_mask, self.origin),
                (self.max_dup, self.res))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    @property
    def num_chips(self) -> int:
        return self.border_cells.shape[0]


def build_pip_index(polys: GeometryArray, res: int, grid: IndexSystem,
                    chips: Optional[ChipSet] = None,
                    dtype=jnp.float32) -> PIPIndex:
    """Tessellate polygons and lay the chips out for device lookup.

    Float32 cell-assignment hazards need no special index structure: the
    device quantizer reports a boundary margin, and low-margin points are
    flagged for the float64 host recheck (see make_pip_join_fn)."""
    if chips is None:
        chips = tessellate(polys, res, grid, keep_core_geom=False)
    bb = polys.bboxes()
    origin = np.round(np.array(
        [np.nanmean(bb[:, [0, 2]]), np.nanmean(bb[:, [1, 3]])]), 1)
    core = chips.is_core
    core_cells = chips.cell_id[core]
    core_zone = chips.geom_id[core]
    order = np.argsort(core_cells, kind="stable")
    core_cells, core_zone = core_cells[order], core_zone[order]

    b_cells = chips.cell_id[~core]
    b_zone = chips.geom_id[~core]
    border_idx = np.nonzero(~core)[0]
    order = np.argsort(b_cells, kind="stable")
    b_cells, b_zone = b_cells[order], b_zone[order]
    if len(b_cells):
        _, counts = np.unique(b_cells, return_counts=True)
        max_dup = int(counts.max())
    else:
        max_dup = 1
    if len(b_cells):
        chip_geoms = chips.geoms.take(border_idx[order])
        chip_geoms.coords = chip_geoms.coords - origin[None, :2]
    else:
        chip_geoms = GeometryArray.empty()
    e = build_edges(chip_geoms, dtype=dtype) if len(b_cells) else None
    if e is None:
        cap = 8
        a = jnp.zeros((0, cap, 2), dtype)
        b = jnp.zeros((0, cap, 2), dtype)
        m = jnp.zeros((0, cap), bool)
    else:
        a, b, m = e.a, e.b, e.mask
    return PIPIndex(
        core_cells=jnp.asarray(core_cells), core_zone=jnp.asarray(
            core_zone.astype(np.int32)),
        border_cells=jnp.asarray(b_cells), border_zone=jnp.asarray(
            b_zone.astype(np.int32)),
        chip_a=a, chip_b=b, chip_mask=m,
        origin=jnp.asarray(origin, jnp.float64),
        max_dup=max_dup, res=res)


# ------------------------------------------------------------ device side

def _chip_pip(points: jnp.ndarray, idx: PIPIndex,
              slots: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Crossing-parity containment of each point in the chip at its slot.

    points [N, 2], slots [N] int32 -> (inside [N] bool, min boundary
    distance² [N]).  One gather of that chip's edges per point; the [N, E]
    broadcast is the hot inner loop of the whole join.
    """
    a = idx.chip_a[slots]           # [N, E, 2]
    b = idx.chip_b[slots]
    mask = idx.chip_mask[slots]
    px = points[:, None, 0]
    py = points[:, None, 1]
    ax, ay = a[..., 0], a[..., 1]
    bx, by = b[..., 0], b[..., 1]
    straddle = (ay <= py) != (by <= py)
    t = (py - ay) / jnp.where(by == ay, jnp.ones_like(by), by - ay)
    xi = ax + t * (bx - ax)
    hits = straddle & (px < xi) & mask
    inside = (jnp.sum(hits, axis=-1) & 1).astype(bool)
    # boundary distance² for the exact-fallback band
    ab = b - a
    ap = points[:, None, :] - a
    denom = jnp.sum(ab * ab, axis=-1)
    tt = jnp.clip(jnp.sum(ap * ab, axis=-1) / jnp.where(denom == 0,
                                                        1.0, denom), 0., 1.)
    proj = a + tt[..., None] * ab
    d = points[:, None, :] - proj
    d2 = jnp.where(mask, jnp.sum(d * d, axis=-1), jnp.inf)
    return inside, jnp.min(d2, axis=-1)


def pip_assign(points: jnp.ndarray, cells: jnp.ndarray, idx: PIPIndex,
               eps: float = 1e-5) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Assign each point to a polygon id (or -1).

    points [N, 2] (grid CRS), cells [N] int64 (precomputed cell per point).
    Returns (zone [N] int32, uncertain [N] bool).  ``uncertain`` marks
    points within eps of a chip boundary — the float64 host recheck set.
    """
    n = points.shape[0]
    slot, in_core = lookup(idx.core_cells, cells)
    zone = jnp.where(in_core, idx.core_zone[slot], jnp.int32(-1))

    b0, in_border = lookup(idx.border_cells, cells)
    uncertain = jnp.zeros(n, bool)
    for d in range(idx.max_dup):
        s = jnp.clip(b0 + d, 0, max(idx.num_chips - 1, 0))
        valid = in_border & (idx.border_cells[s] == cells) & \
            (b0 + d < max(idx.num_chips, 1))
        inside, d2 = _chip_pip(points, idx, s)
        hit = valid & inside & (zone < 0)
        zone = jnp.where(hit, idx.border_zone[s], zone)
        uncertain |= valid & (d2 < eps * eps)
    return zone, uncertain


def localize(idx: PIPIndex, points64: np.ndarray) -> np.ndarray:
    """Absolute float64 points -> local-frame float32 device input.

    The origin shift happens in float64 BEFORE the float32 cast, so the
    device sees full point precision in the frame the chips live in."""
    return np.asarray(points64 - np.asarray(idx.origin)[None],
                      np.float32)


def make_pip_join_fn(idx: PIPIndex, grid: IndexSystem, eps: float = 1e-5,
                     margin_eps: float = 3e-5):
    """Close the index over a jittable ``local_points -> (zone,
    uncertain)``; inputs come from ``localize`` (local-frame float32).

    Exactness contract: every float32 hazard raises ``uncertain``, and
    host_recheck resolves those in float64 — (a) points within ``eps`` of
    a chip boundary (crossing-parity rounding), (b) points whose
    cell-boundary margin is below ``margin_eps`` (cell assignment could
    differ from the float64 path: local→absolute rounding ~4e-6° plus
    f32 projection error), (c) points near the grid's domain edge.
    Out-of-domain points are forced to zone −1."""

    def fn(points: jnp.ndarray):
        absolute = points + idx.origin.astype(points.dtype)
        cells, margin = grid.point_to_cell_jax_margin(absolute, idx.res)
        zone, uncertain = pip_assign(points, cells, idx, eps)
        uncertain |= margin < margin_eps
        inb = grid.point_in_bounds_jax(absolute)
        near_edge = jnp.zeros_like(inb)
        # 8-neighborhood offsets: diagonals matter for points just outside
        # a domain corner on both axes
        for dx in (-eps, 0., eps):
            for dy in (-eps, 0., eps):
                if dx == 0. and dy == 0.:
                    continue
                off = jnp.asarray([dx, dy], points.dtype)
                near_edge |= grid.point_in_bounds_jax(
                    absolute + off) != inb
        return jnp.where(inb, zone, jnp.int32(-1)), uncertain | near_edge

    return fn


# ----------------------------------------------------------- sharded path

def make_sharded_pip_join(idx: PIPIndex, grid: IndexSystem, mesh,
                          eps: float = 1e-5, margin_eps: float = 3e-5,
                          axis: str = "data"):
    """The multi-chip join: points shard over ``axis``, the index
    replicates (the reference's broadcast-join regime, SURVEY.md P2).

    Returns a jitted fn points[N,2] -> (zone [N], uncertain [N]) with N
    divisible by the mesh axis size.  Collectives only appear in
    aggregations layered on top (see zone_histogram)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    fn = make_pip_join_fn(idx, grid, eps, margin_eps)
    pts_sharding = NamedSharding(mesh, P(axis, None))
    out_sharding = (NamedSharding(mesh, P(axis)),
                    NamedSharding(mesh, P(axis)))
    return jax.jit(fn, in_shardings=(pts_sharding,),
                   out_shardings=out_sharding)


def zone_histogram(zone: jnp.ndarray, num_zones: int) -> jnp.ndarray:
    """Per-zone match counts — the canonical aggregation after the join
    (reference: groupBy(index_id).count()).  A scatter-add segment sum
    (O(N), not an O(N·Z) one-hot); unmatched (-1) rows are dropped.
    Under pjit this lowers to a sharded segment-sum + psum over the data
    axis.

    ``.at[].add(mode="drop")`` normalizes negative indices NumPy-style
    *before* dropping, so -1 would wrap to the last zone; remap invalid
    rows to ``num_zones`` (genuinely out of bounds) so drop applies."""
    zone = jnp.where(zone < 0, jnp.int32(num_zones), zone)
    return jnp.zeros(num_zones, jnp.int32).at[zone].add(
        1, mode="drop", indices_are_sorted=False)


def pip_host_truth(points64: np.ndarray,
                   polys: GeometryArray) -> np.ndarray:
    """The exact float64 host oracle: first polygon containing each point
    (crossing-number, first-match tie-break) — the single source of truth
    that host_recheck, tests and bench all compare against."""
    from ..core.tessellate import _pip, _poly_edges
    truth = np.full(len(points64), -1, np.int32)
    for gi in range(len(polys)):
        inside = _pip(points64, _poly_edges(polys, gi))
        truth = np.where((truth < 0) & inside, gi, truth)
    return truth


def host_recheck(points64: np.ndarray, zone: np.ndarray,
                 uncertain: np.ndarray, polys: GeometryArray) -> np.ndarray:
    """Re-run the uncertain points in float64 against the original polygons
    (not the chips) on host — the exact tie-break authority."""
    sel = np.nonzero(uncertain)[0]
    if len(sel) == 0:
        return zone
    zone = zone.copy()
    zone[sel] = pip_host_truth(points64[sel], polys)
    return zone
