"""The index-accelerated point-in-polygon join — the flagship pipeline.

Reference counterpart: the Quickstart workload
(notebooks/examples/python/Quickstart/QuickstartNotebook.ipynb): points get
``grid_pointascellid``, polygons get ``grid_tessellateexplode``, Spark
equi-joins on cell id, then filters ``is_core OR st_contains(chip, point)``.

TPU-first redesign: the tessellated polygon side becomes a device-resident
sorted cell-id table (core cells + border cells with padded chip edge
blocks).  The per-point pipeline is one fused XLA computation:

    cell   = grid.point_to_cell_jax(points)          # closed-form bit math
    islot  = binary-search cell in core/border table # ops.lookup
    inside = crossing-parity vs the <=D chips in the cell
    zone   = core hit ? core zone : first chip hit

No shuffle is needed while the polygon side fits in HBM (the reference's
broadcast-join regime; ~300 taxi zones → a few MB of chips).  Points shard
over the mesh's data axis via jax.sharding; the table replicates.  For
polygon×polygon joins both sides shard — see overlay.py (cell-bucketed
all_to_all).

Precision: device compute is float32; points whose distance to a chip
boundary is below ``eps`` are flagged and re-checked on host in float64
against the same chips, so results match the exact host path
(config.MosaicConfig.exact_fallback).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.geometry.array import GeometryArray
from ..core.geometry.padded import build_edges
from ..core.index.base import IndexSystem
from ..core.tessellate import tessellate
from ..ops.lookup import lookup
from ..perf.pipeline import chunk_rows, stream
from ..types import ChipSet

#: f32 hazard band (degrees) around chip edges for the crossing-parity
#: test: covers the f32 representation of points and chip vertices
#: (~1.5e-8 deg at city magnitudes) and the f32 edge-intersection
#: arithmetic (~1e-7 deg), with ~8x safety.
EPS_EDGE_DEG = 1e-6


def _workload_origin(polys: GeometryArray) -> np.ndarray:
    """Shared local-frame origin of a polygon batch: round(mean bbox).
    Both index types use this, so localize() inputs are interchangeable
    between them for the same polygons."""
    bb = polys.bboxes()
    return np.round(np.array(
        [np.nanmean(bb[:, [0, 2]]), np.nanmean(bb[:, [1, 3]])]), 1)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class PIPIndex:
    """Device-resident tessellation index of a polygon batch.

    core_cells   [C]        sorted cell ids fully inside some polygon
    core_zone    [C]        polygon id per core cell
    border_cells [B]        sorted cell ids on some polygon's boundary
                            (duplicates allowed: one entry per chip)
    border_zone  [B]        polygon id per chip
    chip_a/b     [B, E, 2]  chip edges (float32)
    chip_mask    [B, E]
    max_dup      static     max chips sharing one cell id (probe width)
    res          static     grid resolution
    """

    core_cells: jnp.ndarray
    core_zone: jnp.ndarray
    border_cells: jnp.ndarray
    border_zone: jnp.ndarray
    chip_a: jnp.ndarray
    chip_b: jnp.ndarray
    chip_mask: jnp.ndarray
    #: local-frame origin (lon, lat float64): chip coords are stored
    #: origin-shifted so float32 edge-crossing arithmetic operates on
    #: small magnitudes (absolute lon ~74° costs ~4e-5° of cancellation
    #: error — far above the eps band; shifted it is ~1e-7°)
    origin: jnp.ndarray
    max_dup: int
    res: int
    #: exact max chord-vs-gnomonic cell-edge deviation (planar degrees)
    #: over THIS index's cells — the extra cell-assignment uncertainty
    #: band the join must honor (see cells_edge_sagitta_deg)
    sagitta_deg: float = 0.0

    def tree_flatten(self):
        return ((self.core_cells, self.core_zone, self.border_cells,
                 self.border_zone, self.chip_a, self.chip_b,
                 self.chip_mask, self.origin),
                (self.max_dup, self.res, self.sagitta_deg))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    @property
    def num_chips(self) -> int:
        return self.border_cells.shape[0]


def build_pip_index(polys: GeometryArray, res: int, grid: IndexSystem,
                    chips: Optional[ChipSet] = None,
                    dtype=jnp.float32, dense: str = "auto"):
    """Tessellate polygons and lay the chips out for device lookup.

    Returns a DensePIPIndex (one-gather lattice-window fast path) when
    the workload allows it, else the grid-agnostic sorted-table
    PIPIndex.  ``dense``: "auto" | "never" | "require".

    Float32 cell-assignment hazards need no special index structure: the
    device quantizer reports a boundary margin, and low-margin points are
    flagged for the float64 host recheck (see make_pip_join_fn)."""
    if chips is None:
        chips = tessellate(polys, res, grid, keep_core_geom=False)
    if dense != "never":
        d = build_dense_pip_index(polys, res, grid, chips=chips)
        if d is not None:
            return d
        if dense == "require":
            raise ValueError("workload does not fit the dense fast path")
    origin = _workload_origin(polys)
    core = chips.is_core
    core_cells = chips.cell_id[core]
    core_zone = chips.geom_id[core]
    order = np.argsort(core_cells, kind="stable")
    core_cells, core_zone = core_cells[order], core_zone[order]

    b_cells = chips.cell_id[~core]
    b_zone = chips.geom_id[~core]
    border_idx = np.nonzero(~core)[0]
    order = np.argsort(b_cells, kind="stable")
    b_cells, b_zone = b_cells[order], b_zone[order]
    if len(b_cells):
        _, counts = np.unique(b_cells, return_counts=True)
        max_dup = int(counts.max())
    else:
        max_dup = 1
    if len(b_cells):
        chip_geoms = chips.geoms.take(border_idx[order])
        chip_geoms.coords = chip_geoms.coords - origin[None, :2]
    else:
        chip_geoms = GeometryArray.empty()
    e = build_edges(chip_geoms, dtype=dtype) if len(b_cells) else None
    if e is None:
        cap = 8
        a = jnp.zeros((0, cap, 2), dtype)
        b = jnp.zeros((0, cap, 2), dtype)
        m = jnp.zeros((0, cap), bool)
    else:
        a, b, m = e.a, e.b, e.mask
    return PIPIndex(
        core_cells=jnp.asarray(core_cells), core_zone=jnp.asarray(
            core_zone.astype(np.int32)),
        border_cells=jnp.asarray(b_cells), border_zone=jnp.asarray(
            b_zone.astype(np.int32)),
        chip_a=a, chip_b=b, chip_mask=m,
        origin=jnp.asarray(origin, jnp.float64),
        max_dup=max_dup, res=res,
        sagitta_deg=(grid.cells_edge_sagitta_deg(
            np.unique(chips.cell_id)) if hasattr(
                grid, "cells_edge_sagitta_deg") else 0.0))


# ------------------------------------------------------------ device side

def _chip_pip(points: jnp.ndarray, idx: PIPIndex,
              slots: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Crossing-parity containment of each point in the chip at its slot.

    points [N, 2], slots [N] int32 -> (inside [N] bool, min boundary
    distance² [N]).  One gather of that chip's edges per point; the [N, E]
    broadcast is the hot inner loop of the whole join.
    """
    a = idx.chip_a[slots]           # [N, E, 2]
    b = idx.chip_b[slots]
    mask = idx.chip_mask[slots]
    px = points[:, None, 0]
    py = points[:, None, 1]
    ax, ay = a[..., 0], a[..., 1]
    bx, by = b[..., 0], b[..., 1]
    straddle = (ay <= py) != (by <= py)
    t = (py - ay) / jnp.where(by == ay, jnp.ones_like(by), by - ay)
    xi = ax + t * (bx - ax)
    hits = straddle & (px < xi) & mask
    inside = (jnp.sum(hits, axis=-1) & 1).astype(bool)
    # boundary distance² for the exact-fallback band
    ab = b - a
    ap = points[:, None, :] - a
    denom = jnp.sum(ab * ab, axis=-1)
    tt = jnp.clip(jnp.sum(ap * ab, axis=-1) / jnp.where(denom == 0,
                                                        1.0, denom), 0., 1.)
    proj = a + tt[..., None] * ab
    d = points[:, None, :] - proj
    d2 = jnp.where(mask, jnp.sum(d * d, axis=-1), jnp.inf)
    return inside, jnp.min(d2, axis=-1)


def pip_assign(points: jnp.ndarray, cells: jnp.ndarray, idx: PIPIndex,
               eps: float = 1e-5) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Assign each point to a polygon id (or -1).

    points [N, 2] (grid CRS), cells [N] int64 (precomputed cell per point).
    Returns (zone [N] int32, uncertain [N] bool).  ``uncertain`` marks
    points within eps of a chip boundary — the float64 host recheck set.
    """
    n = points.shape[0]
    # size-0 tables are legal (a workload can tessellate to border-only
    # chips, or — under adaptive refinement — a sub-level can come out
    # core-only); lookup() already returns found=False there, but the
    # zone/edge gathers need static guards too.
    if idx.core_cells.shape[0]:
        slot, in_core = lookup(idx.core_cells, cells)
        zone = jnp.where(in_core, idx.core_zone[slot], jnp.int32(-1))
    else:
        zone = jnp.full(n, -1, jnp.int32)

    b0, in_border = lookup(idx.border_cells, cells)
    uncertain = jnp.zeros(n, bool)
    for d in range(idx.max_dup if idx.num_chips else 0):
        s = jnp.clip(b0 + d, 0, max(idx.num_chips - 1, 0))
        valid = in_border & (idx.border_cells[s] == cells) & \
            (b0 + d < max(idx.num_chips, 1))
        inside, d2 = _chip_pip(points, idx, s)
        hit = valid & inside & (zone < 0)
        zone = jnp.where(hit, idx.border_zone[s], zone)
        uncertain |= valid & (d2 < eps * eps)
    return zone, uncertain


def localize(idx: PIPIndex, points64: np.ndarray) -> np.ndarray:
    """Absolute float64 points -> local-frame float32 device input.

    The origin shift happens in float64 BEFORE the float32 cast, so the
    device sees full point precision in the frame the chips live in."""
    return np.asarray(points64 - np.asarray(idx.origin)[None],
                      np.float32)


def make_pip_join_fn(idx, grid: IndexSystem, eps: Optional[float] = None,
                     margin_eps: Optional[float] = None,
                     precision: str = "auto"):
    """Close the index over a jittable ``local_points -> (zone,
    uncertain)``; inputs come from ``localize`` (local-frame float32).
    Dense indexes dispatch to make_dense_pip_join_fn.

    ``precision`` pins the dense path's projection arithmetic ("f32" /
    "df" / "f64"; see ``h3.jaxkernel.pick_precision``).  "auto" resolves
    per backend — note it picks native f64 on CPU whenever
    ``jax_enable_x64`` is on, which is exact-but-slow; throughput
    benchmarks that enable x64 for other subsystems should pin the
    arithmetic they mean to measure.  Exactness does not depend on the
    choice: wider-error paths raise ``uncertain`` over a wider margin
    band and the f64 host recheck resolves them.

    Exactness contract: every float32 hazard raises ``uncertain``, and
    host_recheck resolves those in float64 — (a) points within ``eps`` of
    a chip boundary (crossing-parity rounding), (b) points whose
    cell-boundary margin is below ``margin_eps`` (cell assignment could
    differ from the float64 path: local→absolute rounding ~4e-6° plus
    f32 projection error), (c) points near the grid's domain edge.
    Out-of-domain points are forced to zone −1."""
    if isinstance(idx, DensePIPIndex):
        return make_dense_pip_join_fn(
            idx, eps=EPS_EDGE_DEG if eps is None else eps,
            precision=precision, margin_eps_deg=margin_eps)
    # sorted-path defaults (wider: its f32 absolute-coordinate cell
    # assignment carries more error than the dense path's projection).
    # The margin additionally covers the cell-edge sagitta — the gap
    # between the true gnomonic cell boundary (which assigns points)
    # and the straight lon/lat chord the chips were clipped against
    # (round-4: a continent-extent res-2 join silently dropped points
    # inside that band)
    eps = 1e-5 if eps is None else eps
    if margin_eps is None:
        # margin from point_to_cell_jax_margin is PLANAR DEGREES, and
        # idx.sagitta_deg is the exact bound over this index's cells
        # (a radians-valued global sample here understated the band
        # 57x and missed high-latitude cells — round-4 review)
        margin_eps = max(3e-5, 2.0 * idx.sagitta_deg)

    def fn(points: jnp.ndarray):
        absolute = points + idx.origin.astype(points.dtype)
        cells, margin = grid.point_to_cell_jax_margin(absolute, idx.res)
        zone, uncertain = pip_assign(points, cells, idx, eps)
        uncertain |= margin < margin_eps
        inb = grid.point_in_bounds_jax(absolute)
        near_edge = jnp.zeros_like(inb)
        # 8-neighborhood offsets: diagonals matter for points just outside
        # a domain corner on both axes
        for dx in (-eps, 0., eps):
            for dy in (-eps, 0., eps):
                if dx == 0. and dy == 0.:
                    continue
                off = jnp.asarray([dx, dy], points.dtype)
                near_edge |= grid.point_in_bounds_jax(
                    absolute + off) != inb
        return jnp.where(inb, zone, jnp.int32(-1)), uncertain | near_edge

    return fn


def _resolve_chunk(chunk: Optional[int]) -> int:
    """Caller-supplied chunk rows, else ``mosaic.stream.chunk.rows``
    (the previous hard-coded 262_144 is now that key's default)."""
    if chunk is not None:
        return int(chunk)
    from ..config import default_config
    return int(default_config().stream_chunk_rows)


def make_streamed_pip_join(idx, grid: IndexSystem,
                           polys: Optional[GeometryArray] = None,
                           chunk: Optional[int] = None,
                           eps: Optional[float] = None,
                           margin_eps: Optional[float] = None,
                           precision: str = "auto"):
    """End-to-end chunked join with transfer/compute/recheck overlap.

    The single-shot path stages the WHOLE point batch on device, runs
    one launch, then rechecks on host — three serial phases.  This
    wrapper cuts the batch into ``chunk``-row pieces and runs them
    through :func:`mosaic_tpu.perf.pipeline.stream`: the localize +
    upload of chunk N+1 rides along with device compute on chunk N,
    and the f64 host recheck of chunk N−1 runs on the pipeline's
    worker thread.  Exactness is untouched — same kernel, same
    recheck authority (``polys`` is required for a sorted
    :class:`PIPIndex`, optional for dense).

    Returns ``run(points64_abs) -> (zone [N] int32, rechecked
    count)``."""
    chunk = _resolve_chunk(chunk)
    from ..perf.jit_cache import kernel_cache
    # named jit-cache entry (not a bare jax.jit) so the kernel ledger
    # can attribute the streamed join's wall time to "pip/streamed"
    fn = kernel_cache.get_or_build(
        "pip/streamed", (id(idx), id(grid), eps, margin_eps, precision),
        lambda: jax.jit(
            make_pip_join_fn(idx, grid, eps, margin_eps, precision)))
    recheck = host_recheck_fn(idx, polys)
    origin = np.asarray(idx.origin)
    ledger_key = (id(idx), id(grid), eps, margin_eps, precision)

    def run(points64: np.ndarray):
        from ..obs import metrics, tracer
        from ..obs.context import root_trace
        from ..obs.inflight import checkpoint
        from ..obs.profiler import ledger
        checkpoint("pip_join/streamed")   # cancel before first chunk;
        # stream() itself re-probes at every chunk boundary
        points64 = np.asarray(points64, np.float64)[:, :2]
        n = len(points64)
        zone_out = np.empty(n, np.int32)
        state = {"rechecked": 0}

        def put(sl):
            # f64 origin shift BEFORE the f32 cast (= localize());
            # device_put is async, so this overlaps the running launch
            return jax.device_put(np.asarray(
                points64[sl] - origin[None], np.float32))

        def consume(i, sl, host):
            z, unc = host
            zone_out[sl] = recheck(points64[sl], z, unc)
            state["rechecked"] += int(unc.sum())

        def observe(i, sl, seconds):
            ledger.observe("pip/streamed", ledger_key, seconds,
                           rows=sl.stop - sl.start)

        with root_trace("pip_join"), tracer.span("pip_join/streamed"):
            stream(chunk_rows(n, chunk), compute=fn, put=put,
                   consume=consume, observe=observe,
                   site="pip_join/streamed")
        if metrics.enabled:
            metrics.count("pip_join/streamed_points", float(n))
            metrics.count("pip_join/streamed_chunks",
                          float(-(-n // chunk) if n else 0))
        return zone_out, state["rechecked"]

    return run


# ----------------------------------------------------------- sharded path

#: padding rows in the sharded streamed path get this local-frame
#: coordinate (degrees): far outside every workload extent, so both
#: index paths resolve them to zone -1 without tripping the f64
#: recheck, yet small enough that f32 trig in the projections stays
#: finite (1e9-style sentinels risk inf/nan there)
_PAD_SENTINEL_DEG = 4.0e3


def _shard_skew_readback(zones_padded: np.ndarray, D: int):
    """Per-shard matched-candidate counts from a [D*rows] zone vector
    (padding rows read zone -1 and drop out).  Records the skew gauge,
    its time series, and the max-candidates gauge."""
    from ..obs import metrics
    c = (zones_padded.reshape(D, -1) >= 0).sum(axis=1)
    mean = float(c.mean())
    skew = float(c.max()) / mean if mean else 1.0
    metrics.gauge("shard/skew/pip_join", skew)
    # same quantity as a distribution: shard/skew_series/pip_join_p50/
    # p95/p99 expose how imbalance evolves, not just the last readback
    metrics.observe("shard/skew_series/pip_join", skew)
    metrics.gauge("shard/candidates_max/pip_join", float(c.max()))
    return c


def make_sharded_pip_join(idx, grid: IndexSystem, mesh,
                          eps: Optional[float] = None,
                          margin_eps: Optional[float] = None,
                          axis: str = "data"):
    """The multi-chip join: points shard over ``axis``, the index
    replicates (the reference's broadcast-join regime, SURVEY.md P2).

    Returns a fn points[N,2] -> (zone [N], uncertain [N]) with N
    divisible by the mesh axis size.  Collectives only appear in
    aggregations layered on top (see zone_histogram).  The jitted
    kernel lives in ``perf.jit_cache.kernel_cache`` (the cached entry
    closes over ``idx``/``mesh``, pinning both ids for the entry's
    lifetime), so rebuilding the wrapper for the same index+mesh costs
    a dict hit, not a retrace.

    Observability: with the metrics registry enabled, the wrapper
    records the replicated-index footprint (the broadcast-join's data
    movement: every device holds the whole index) and, every
    ``mosaic.shard.skew.refresh``-th call (default 16 — each readback
    is a host sync on the hot path), the per-shard matched-candidate
    skew (max/mean of zone >= 0 counts per shard) as both the
    ``shard/skew/pip_join`` gauge and the ``shard/skew_series``
    distribution.  For the skew-aware streamed composition see
    :func:`make_sharded_streamed_pip_join`."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..config import default_config
    from ..obs import metrics
    from ..perf.jit_cache import kernel_cache

    fn = make_pip_join_fn(idx, grid, eps, margin_eps)
    pts_sharding = NamedSharding(mesh, P(axis, None))
    out_sharding = (NamedSharding(mesh, P(axis)),
                    NamedSharding(mesh, P(axis)))
    jfn = kernel_cache.get_or_build(
        "pip/sharded_wrap", (id(idx), id(mesh), axis, eps, margin_eps),
        lambda: jax.jit(fn, in_shardings=(pts_sharding,),
                        out_shardings=out_sharding))
    D = mesh.shape[axis]
    idx_bytes = sum(int(np.asarray(leaf).nbytes)
                    for leaf in jax.tree_util.tree_leaves(idx))
    state = {"calls": 0, "weights": None}

    def wrapped(points):
        import time as _time
        from ..obs import tracer
        from ..obs.context import root_trace
        from ..obs.devicemon import devicemon, mesh_device_keys
        with root_trace("pip_join"), tracer.span("pip_join/sharded"):
            t0 = _time.perf_counter()
            out = jfn(points)
            dt = _time.perf_counter() - t0
        from ..obs.profiler import ledger
        ledger.observe("pip/sharded_wrap",
                       (id(idx), id(mesh), axis, eps, margin_eps),
                       dt, rows=int(points.shape[0]))
        if metrics.enabled:
            metrics.gauge("collective/replicated_index_bytes",
                          float(idx_bytes) * D)
            n = int(points.shape[0])
            metrics.count("collective/points_scatter_bytes",
                          float(points.size) * points.dtype.itemsize)
            metrics.gauge("shard/points_per_shard/pip_join", n / D)
            k = max(1, default_config().shard_skew_refresh)
            if state["calls"] % k == 0:
                if state["calls"] == 0:
                    metrics.count("collective/broadcast_bytes",
                                  float(idx_bytes) * max(D - 1, 1))
                state["weights"] = \
                    _shard_skew_readback(np.asarray(out[0]), D)
            # charge dispatch wall time to devices by the last
            # observed per-shard load (uniform until first readback)
            devicemon.attribute("pip_join", dt, state["weights"],
                                mesh_device_keys(mesh))
            state["calls"] += 1
        return out

    return wrapped


def make_sharded_streamed_pip_join(idx, grid: IndexSystem, mesh,
                                   polys: Optional[GeometryArray] = None,
                                   chunk: Optional[int] = None,
                                   eps: Optional[float] = None,
                                   margin_eps: Optional[float] = None,
                                   axis: str = "data",
                                   refresh: Optional[int] = None,
                                   nbins: int = 16):
    """The sharded flagship: :func:`make_streamed_pip_join` composed
    with the mesh.  One pipeline, three layers of the perf stack:

    * **double-buffered staging** — chunks flow through
      ``perf.pipeline.stream``: the scatter (host device_put of chunk
      N+1, split across the mesh by ``NamedSharding``) overlaps the
      sharded compute on chunk N, and the f64 recheck of chunk N−1
      drains on the pipeline's worker thread.
    * **bucketed kernel cache** — each chunk pads (sentinel rows, zone
      −1 by construction) to ``pow2_bucket(rows / D) * D`` and the
      jitted sharded kernel is keyed into
      ``perf.jit_cache.kernel_cache`` per (index, mesh, bucket): one
      XLA compile per bucket per mesh shape, zero in a warm process
      (asserted by the multichip-smoke CI lane).
    * **skew-aware placement** — a :class:`.placement.SkewRebalancer`
      learns per-grid-cell matched-candidate density from every
      consumed chunk (free: the zones are already on host) and, every
      ``refresh`` chunks (``mosaic.shard.skew.refresh``, default 16),
      greedily re-packs cells onto shards; rows then scatter to
      per-shard slots via :func:`.placement.placement_slots` instead
      of arrival order.  The inverse permutation is applied on the
      host gather, so results are bit-for-bit identical to the
      single-device streamed path — placement only moves *where* each
      row is computed.

    ``polys`` is required for a sorted :class:`PIPIndex` (recheck
    authority), optional for dense.  Returns ``run(points64_abs) ->
    (zone [N] int32, rechecked count)``; ``run.rebalancer`` exposes
    the placement pass for inspection."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..config import default_config
    from ..obs import metrics
    from ..perf.bucketing import pow2_bucket
    from ..perf.jit_cache import kernel_cache
    from .placement import SkewRebalancer, placement_slots

    chunk = _resolve_chunk(chunk)
    fn = make_pip_join_fn(idx, grid, eps, margin_eps)
    recheck = host_recheck_fn(idx, polys)
    origin = np.asarray(idx.origin)
    D = mesh.shape[axis]
    pts_sharding = NamedSharding(mesh, P(axis, None))
    out_sharding = (NamedSharding(mesh, P(axis)),
                    NamedSharding(mesh, P(axis)))
    if refresh is None:
        refresh = default_config().shard_skew_refresh
    rebalancer = SkewRebalancer(D, refresh=refresh, nbins=nbins)
    idx_bytes = sum(int(np.asarray(leaf).nbytes)
                    for leaf in jax.tree_util.tree_leaves(idx))

    def kernel(rows):
        # one jit wrapper per padded bucket per mesh; the entry closes
        # over idx and the mesh-bound shardings, pinning both ids
        return kernel_cache.get_or_build(
            "pip/sharded_stream",
            (id(idx), id(mesh), axis, rows, eps, margin_eps),
            lambda: jax.jit(fn, in_shardings=(pts_sharding,),
                            out_shardings=out_sharding))

    def run(points64: np.ndarray):
        from ..obs import tracer
        from ..obs.context import root_trace
        from ..obs.inflight import checkpoint
        checkpoint("pip_join/sharded_streamed")
        points64 = np.asarray(points64, np.float64)[:, :2]
        n = len(points64)
        zone_out = np.empty(n, np.int32)
        state = {"rechecked": 0, "slots": {}}

        def put(sl):
            rows = sl.stop - sl.start
            per = pow2_bucket(-(-rows // D), floor=64)
            pref = rebalancer.preferred(points64[sl])
            slots = placement_slots(pref, rows, D, per)
            buf = np.full((per * D, 2), _PAD_SENTINEL_DEG, np.float32)
            # f64 origin shift BEFORE the f32 cast (= localize()), same
            # values as the single-device put — only the row order and
            # padding differ
            buf[slots] = (points64[sl] - origin[None]).astype(np.float32)
            state["slots"][sl.start] = slots
            # device_put against the sharding splits the buffer across
            # the mesh asynchronously, overlapping the running launch
            return per * D, jax.device_put(buf, pts_sharding)

        def compute(staged):
            rows, dev = staged
            return kernel(rows)(dev)

        def consume(i, sl, host):
            zp, up = host
            zp = np.asarray(zp)
            slots = state["slots"].pop(sl.start)
            z = zp[slots]
            unc = np.asarray(up)[slots]
            zone_out[sl] = recheck(points64[sl], z, unc)
            state["rechecked"] += int(unc.sum())
            # feedback is free here — the shard results are already on
            # host, unlike the monolithic path's cadenced device sync
            rebalancer.observe(points64[sl], z >= 0)
            if metrics.enabled:
                c = _shard_skew_readback(zp, D)
                w = state.get("weights")
                state["weights"] = c if w is None else w + c
                metrics.gauge("shard/skew_planned/pip_join",
                              rebalancer.planned_skew())

        def observe(i, sl, seconds):
            from ..obs.profiler import ledger
            rows = sl.stop - sl.start
            padded = pow2_bucket(-(-rows // D), floor=64) * D
            # same key shape as the kernel() cache entry, so the ledger
            # row lines up with the per-bucket jit-cache kernel
            ledger.observe("pip/sharded_stream",
                           (id(idx), id(mesh), axis, padded, eps,
                            margin_eps), seconds, rows=rows)

        import time as _time
        t0 = _time.perf_counter()
        with root_trace("pip_join"), \
                tracer.span("pip_join/sharded_streamed"):
            stream(chunk_rows(n, chunk), compute=compute, put=put,
                   consume=consume, observe=observe,
                   site="pip_join/sharded")
        if metrics.enabled:
            # per-device wall-time attribution: the run's matched-row
            # counts per shard (summed over chunks) are the load share
            from ..obs.devicemon import devicemon, mesh_device_keys
            devicemon.attribute("pip_join",
                                _time.perf_counter() - t0,
                                state.get("weights"),
                                mesh_device_keys(mesh))
        if metrics.enabled:
            metrics.gauge("collective/replicated_index_bytes",
                          float(idx_bytes) * D)
            metrics.gauge("shard/points_per_shard/pip_join", n / D)
            metrics.count("collective/points_scatter_bytes", 8.0 * n)
            metrics.count("pip_join/sharded_points", float(n))
            metrics.count("pip_join/sharded_chunks",
                          float(-(-n // chunk) if n else 0))
        return zone_out, state["rechecked"]

    run.rebalancer = rebalancer
    return run


def make_store_sharded_pip_join(store, idx, grid: IndexSystem, mesh,
                                polys: Optional[GeometryArray] = None,
                                chunk: Optional[int] = None,
                                eps: Optional[float] = None,
                                margin_eps: Optional[float] = None,
                                axis: str = "data",
                                refresh: Optional[int] = None,
                                nbins: int = 16):
    """The sharded flagship fed from an out-of-core chip store.

    Same three-layer pipeline as :func:`make_sharded_streamed_pip_join`
    — double-buffered staging, bucketed kernel cache, skew-aware
    placement — but the chunk source is
    :meth:`~..store.reader.ChipStore.iter_chunks`: a GENERATOR that
    prunes partitions against the query bbox from the manifest alone,
    then reads one shard at a time off disk.  The host never holds
    more than the pipeline's look-ahead window, so the dataset can be
    arbitrarily larger than RAM; a pruned partition contributes ZERO
    staged bytes (provable from ``run.staged_bytes_by_partition`` and
    the memwatch ledger's ``pip_join/store/staged`` site).

    Placement is PARTITION-level here: every row of a store partition
    inherits the shard the :class:`.placement.SkewRebalancer` prefers
    for that partition's bbox centroid, so the placement pass moves
    whole partitions between devices instead of individual rows — the
    granularity the store's on-disk layout already paid for (density
    feedback still learns from every consumed row, as before).
    Results stay bit-for-bit identical to the in-memory sharded path
    over the same points in store order: placement and padding only
    move *where* rows are computed, and the f64 host recheck is the
    same authority.

    Returns ``run(bbox=None) -> (zone [rows] int32, rechecked
    count)`` over the scanned rows in store order (manifest partition
    order, ingest order within a partition).  ``run.rebalancer``
    exposes the placement pass; after each call
    ``run.staged_bytes_by_partition`` maps cell id -> bytes that
    partition's rows staged (row-proportional share of each chunk's
    padded buffer)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..config import default_config
    from ..obs import metrics
    from ..perf.bucketing import pow2_bucket
    from ..perf.jit_cache import kernel_cache
    from .placement import SkewRebalancer, placement_slots

    chunk = _resolve_chunk(chunk)
    fn = make_pip_join_fn(idx, grid, eps, margin_eps)
    recheck = host_recheck_fn(idx, polys)
    origin = np.asarray(idx.origin)
    D = mesh.shape[axis]
    pts_sharding = NamedSharding(mesh, P(axis, None))
    out_sharding = (NamedSharding(mesh, P(axis)),
                    NamedSharding(mesh, P(axis)))
    if refresh is None:
        refresh = default_config().shard_skew_refresh
    rebalancer = SkewRebalancer(D, refresh=refresh, nbins=nbins)
    idx_bytes = sum(int(np.asarray(leaf).nbytes)
                    for leaf in jax.tree_util.tree_leaves(idx))
    # partition bbox centroids: the rebalancer's placement key — one
    # preferred-shard query per partition span, not per row
    cent = {p.cell: ((p.bbox[0] + p.bbox[2]) / 2.0,
                     (p.bbox[1] + p.bbox[3]) / 2.0)
            for p in store.partitions}
    if default_config().heat_prior:
        # seed placement from accumulated partition heat (obs/heat.py)
        # — a pure hint: the rebalancer only moves rows between
        # shards, so outputs stay bit-identical to an unprimed run
        from ..obs.heat import heat
        hp = heat.prior(nbins, store.bbox, cent)
        if hp is not None:
            rebalancer.prime(np.asarray(store.bbox, np.float64), hp)
            if metrics.enabled:
                metrics.count("heat/prior_primes")

    def kernel(rows):
        # shares the in-memory sharded path's cache family: a store
        # query and an array query of the same bucket reuse one compile
        return kernel_cache.get_or_build(
            "pip/sharded_stream",
            (id(idx), id(mesh), axis, rows, eps, margin_eps),
            lambda: jax.jit(fn, in_shardings=(pts_sharding,),
                            out_shardings=out_sharding))

    def run(bbox=None):
        from ..obs import tracer
        from ..obs.context import root_trace
        from ..obs.inflight import checkpoint
        checkpoint("pip_join/store")
        state = {"rechecked": 0, "slots": {}, "weights": None}
        staged_by_part: dict = {}
        rows_total = 0

        def put(ck):
            rows = ck.rows
            per = pow2_bucket(-(-rows // D), floor=64)
            pref = None
            if rebalancer.armed:
                # whole-partition placement: each span's rows go where
                # the rebalancer wants that partition's centroid
                cpts = np.asarray([cent[c] for c, _ in ck.parts],
                                  np.float64)
                pref = np.repeat(rebalancer.preferred(cpts),
                                 [r for _, r in ck.parts])
            slots = placement_slots(pref, rows, D, per)
            buf = np.full((per * D, 2), _PAD_SENTINEL_DEG, np.float32)
            buf[slots] = (ck.points - origin[None]).astype(np.float32)
            state["slots"][ck.offset] = slots
            # per-partition staging ledger: this chunk's padded buffer
            # split across its spans by row share (cumulative rounding
            # so the shares sum EXACTLY to buf.nbytes — the ledger
            # then reconciles against pipeline/h2d_bytes byte for
            # byte).  A pruned partition never appears here: it never
            # reached a chunk.
            seen = acc = 0
            for c, r in ck.parts:
                seen += r
                share = buf.nbytes * seen // rows - acc
                acc += share
                staged_by_part[c] = staged_by_part.get(c, 0) + share
            return per * D, jax.device_put(buf, pts_sharding)

        def compute(staged):
            rows, dev = staged
            return kernel(rows)(dev)

        def consume(i, ck, host):
            nonlocal rows_total
            zp, up = host
            zp = np.asarray(zp)
            slots = state["slots"].pop(ck.offset)
            z = zp[slots]
            unc = np.asarray(up)[slots]
            zone = recheck(ck.points, z, unc)
            state["rechecked"] += int(unc.sum())
            rows_total += ck.rows
            # density feedback stays row-level (free: already on host)
            rebalancer.observe(ck.points, z >= 0)
            if metrics.enabled:
                c = _shard_skew_readback(zp, D)
                w = state.get("weights")
                state["weights"] = c if w is None else w + c
                metrics.gauge("shard/skew_planned/pip_join",
                              rebalancer.planned_skew())
            return zone

        def observe(i, ck, seconds):
            from ..obs.profiler import ledger
            padded = pow2_bucket(-(-ck.rows // D), floor=64) * D
            ledger.observe("pip/sharded_stream",
                           (id(idx), id(mesh), axis, padded, eps,
                            margin_eps), seconds, rows=ck.rows)

        import time as _time
        t0 = _time.perf_counter()
        with root_trace("pip_join"), \
                tracer.span("pip_join/store_streamed"):
            zones = stream(store.iter_chunks(bbox=bbox,
                                             chunk_rows=chunk),
                           compute=compute, put=put, consume=consume,
                           observe=observe, site="pip_join/store")
        zone_out = np.concatenate(zones) if zones \
            else np.empty(0, np.int32)
        run.staged_bytes_by_partition = staged_by_part
        if staged_by_part:
            # per-partition staged bytes feed heat + the query's
            # durable history record (rows already fed at chunk emit)
            from ..obs.heat import heat
            from ..obs.inflight import note_partition_bytes
            for c, b in staged_by_part.items():
                heat.touch(c, nbytes=b, scans=0)
            note_partition_bytes(staged_by_part)
        if metrics.enabled:
            from ..obs.devicemon import devicemon, mesh_device_keys
            devicemon.attribute("pip_join",
                                _time.perf_counter() - t0,
                                state.get("weights"),
                                mesh_device_keys(mesh))
            metrics.gauge("collective/replicated_index_bytes",
                          float(idx_bytes) * D)
            metrics.gauge("shard/points_per_shard/pip_join",
                          rows_total / D)
            metrics.count("collective/points_scatter_bytes",
                          8.0 * rows_total)
            metrics.count("pip_join/store_points", float(rows_total))
            metrics.count("pip_join/store_chunks", float(len(zones)))
        return zone_out, state["rechecked"]

    run.rebalancer = rebalancer
    run.staged_bytes_by_partition = {}
    return run


def make_planned_pip_join(idx, grid: IndexSystem,
                          polys: Optional[GeometryArray] = None,
                          mesh=None,
                          eps: Optional[float] = None,
                          margin_eps: Optional[float] = None,
                          precision: str = "auto",
                          axis: str = "data"):
    """Cost-based adaptive entry point over the whole PIP join family.

    Per call the planner (sql/planner.py) picks monolithic single
    launch vs. :func:`make_streamed_pip_join` (per chunk class) vs.
    :func:`make_sharded_streamed_pip_join` from its learned
    per-(strategy, size-class) cost coefficients — cold it falls back
    to the batch-vs-chunk threshold.  Every candidate is a pure
    strategy transform: same localize (f64 origin shift before the f32
    cast), same jitted kernel, same f64 recheck authority, so the
    zones are bit-for-bit identical whichever path runs.  The cheap
    pre-pass feeds the estimate: the fraction of the point batch's
    bbox overlapping the polygon extent bounds the matched rows.

    After each call the observed wall time and matched-row count flow
    back into the planner, so a workload's second run is planned from
    measurement.  ``run.calibrate(points64)`` runs EVERY candidate
    once (asserting pairwise parity) to seed the coefficients — the
    bench's A/B sweep uses it so the crossover is learned, not guessed.

    Returns ``run(points64_abs) -> (zone [N] int32, rechecked
    count)``; ``run.last_decision`` exposes the most recent pick."""
    import time as _time
    from ..sql.planner import planner

    variants: dict = {}
    mesh_devices = int(np.prod(list(mesh.shape.values()))) \
        if mesh is not None else 1
    poly_ext = None
    if polys is not None and len(polys):
        bb = polys.bboxes()
        poly_ext = (float(np.nanmin(bb[:, 0])),
                    float(np.nanmin(bb[:, 1])),
                    float(np.nanmax(bb[:, 2])),
                    float(np.nanmax(bb[:, 3])))

    def _variant(strategy: str, chunk: int):
        key = (strategy, chunk if strategy == "streamed" else 0)
        if key in variants:
            return variants[key]
        if strategy == "monolithic":
            from ..perf.jit_cache import kernel_cache
            fn = kernel_cache.get_or_build(
                "pip/monolithic",
                (id(idx), id(grid), eps, margin_eps, precision),
                lambda: jax.jit(make_pip_join_fn(
                    idx, grid, eps, margin_eps, precision)))
            recheck = host_recheck_fn(idx, polys)
            origin = np.asarray(idx.origin)

            def mono(points64):
                points64 = np.asarray(points64, np.float64)[:, :2]
                z, unc = fn(jnp.asarray(np.asarray(
                    points64 - origin[None], np.float32)))
                z = np.asarray(z)
                unc = np.asarray(unc)
                return recheck(points64, z, unc), int(unc.sum())

            variants[key] = mono
        elif strategy == "sharded":
            variants[key] = make_sharded_streamed_pip_join(
                idx, grid, mesh, polys=polys, chunk=chunk, eps=eps,
                margin_eps=margin_eps, axis=axis)
        else:
            variants[key] = make_streamed_pip_join(
                idx, grid, polys=polys, chunk=chunk, eps=eps,
                margin_eps=margin_eps, precision=precision)
        return variants[key]

    def _overlap_frac(points64: np.ndarray) -> Optional[float]:
        # bbox-overlap sketch: what fraction of the point batch's bbox
        # intersects the polygon extent — an upper bound on match rate
        if poly_ext is None or not len(points64):
            return None
        lo = points64.min(axis=0)
        hi = points64.max(axis=0)
        w = max(hi[0] - lo[0], 1e-12) * max(hi[1] - lo[1], 1e-12)
        iw = max(0.0, min(hi[0], poly_ext[2]) - max(lo[0], poly_ext[0]))
        ih = max(0.0, min(hi[1], poly_ext[3]) - max(lo[1], poly_ext[1]))
        return min(1.0, (iw * ih) / w)

    def run(points64: np.ndarray):
        points64 = np.asarray(points64, np.float64)[:, :2]
        n = len(points64)
        d = planner.decide_pip_join(n, mesh_devices,
                                    in_extent_frac=_overlap_frac(
                                        points64))
        strategy, chunk = d.strategy, getattr(
            d, "chunk", planner.chunk_rows())
        if strategy == "sharded" and mesh is None:
            strategy = "streamed"   # forced sharded without a mesh
        t0 = _time.perf_counter()
        zone, rechecked = _variant(strategy, chunk)(points64)
        planner.observe_decision(d, _time.perf_counter() - t0,
                                 rows_out=int((zone >= 0).sum()))
        run.last_decision = d
        return zone, rechecked

    def calibrate(points64: np.ndarray):
        """Run every candidate once on this batch: seeds the planner's
        coefficients AND asserts the paths agree bit-for-bit."""
        points64 = np.asarray(points64, np.float64)[:, :2]
        n = len(points64)
        ref = None
        cands = planner.pip_join_candidates(n, mesh_devices)
        if mesh_devices > 1:
            from ..config import default_config
            if default_config().heat_prior:
                # mosaic.heat.prior beyond the store-fed join: a hot
                # skewed workload calibrates the skew-aware sharded
                # path FIRST, so its warm-up (placement readbacks,
                # bucket compiles) happens before any timed candidate
                # and the learned coefficients favor the path the
                # workload's heat says it needs.  Pure ordering hint:
                # every candidate still runs and pairwise parity is
                # still asserted, so results are bit-identical.
                from ..obs import metrics
                from ..obs.heat import heat
                rep = heat.report(top=1)
                if rep["tracked"] and rep["skew"] >= 2.0:
                    cands = sorted(cands, key=lambda sc:
                                   0 if sc[0] == "sharded" else 1)
                    if metrics.enabled:
                        metrics.count("heat/calibrate_hints")
        for strategy, chunk in cands:
            fn = _variant(strategy, chunk)
            fn(points64)            # warm: keep compiles out of the
            t0 = _time.perf_counter()   # learned coefficients
            zone, _ = fn(points64)
            wall = _time.perf_counter() - t0
            planner.observe_op(planner.pip_cost_key(strategy, chunk),
                               n, wall,
                               rows_out=int((zone >= 0).sum()))
            if ref is None:
                ref = zone
            elif not np.array_equal(ref, zone):
                raise AssertionError(
                    f"pip_join strategy {strategy!r} (chunk {chunk}) "
                    "diverged from the reference path")
        return ref

    run.calibrate = calibrate
    run.last_decision = None
    return run


# ------------------------------------------------- adaptive refinement

def _chips_clean(chips: ChipSet) -> bool:
    """True when a chipset's index is *clean*: no cell id appears in
    both the core and border sets, and no cell is core for two
    polygons — the same two conditions whose violation rejects the
    dense fast path (overlap_regime / duplicate_core).

    Why it matters: in a clean index a core-confident device hit
    implies NO other polygon intersects that cell at all (any
    intersection would have produced a chip there), so the core zone
    is the unique container; border-only hits take the first border
    slot, and the stable build sort keeps slots in geom-id order, so
    they resolve to the lowest containing id — exactly
    :func:`pip_host_truth`'s first-match rule.  Hence every point's
    full (device + f64 recheck) output equals the host oracle, at ANY
    resolution, which is what makes refined-vs-flat bit-parity a
    theorem instead of a hope.  An unclean chipset (overlapping
    polygons sharing a core cell) voids that argument — the refined
    join then declines to refine and runs the flat path unchanged."""
    core = chips.is_core
    core_cells = chips.cell_id[core]
    if len(np.intersect1d(core_cells, chips.cell_id[~core])):
        return False
    return len(np.unique(core_cells)) == len(core_cells)


def make_refined_pip_join(polys: GeometryArray, grid: IndexSystem,
                          res: int,
                          chunk: Optional[int] = None,
                          eps: Optional[float] = None,
                          margin_eps: Optional[float] = None,
                          precision: str = "auto"):
    """Adaptive per-cell refinement of the flagship join.

    The flat join pays ``max_dup`` serial chip probes per point — the
    worst cell's duplication sets every point's cost.  This wrapper
    starts at the caller's ``res`` exactly like the flat path, measures
    per-cell candidate-pair selectivity from the first batch's leading
    ``mosaic.join.refine.sample.rows`` points, and re-tessellates ONLY
    the dense border cells' polygons ``mosaic.join.refine.depth``
    levels deeper (arxiv 1802.09488's adaptive-grid argument).  Points
    the f64 device cell kernel routes to a dense cell run against the
    refined index (smaller chips, lower dup); everyone else runs
    against the *same* base index the flat path uses.

    Bit-parity: both levels are gated on :func:`_chips_clean` — a
    clean index's output equals :func:`pip_host_truth` for every
    point, so routing points between two clean levels cannot change a
    single zone.  Overlap regimes fail the gate and decline to refine
    (flat path, unchanged).  The refined part's recheck authority is
    the polygon SUBSET whose bboxes touch a dense cell (order
    preserved, ids remapped), which provably contains every polygon
    that can hold a dense-routed point.

    Strategy selection is the planner's ``refine/`` decision
    (:meth:`~..sql.planner.CostPlanner.decide_refine`): learned
    refined-vs-flat coefficients, cold dense-pair-fraction crossover,
    ``mosaic.planner.force.refine`` pin, and the
    ``mosaic.join.refine.enabled`` kill switch that beats any pin.
    Kernels live in ``perf.jit_cache`` under the ``pip/refined``
    family keyed per (level, pow2 row bucket) — a warm process with a
    persistent cache dir compiles nothing new.  Any failure inside the
    refined path (fault site ``join.refine``) transparently re-runs
    the batch on the flat path (``refine_bailout`` event +
    ``pip_join/refine_bailouts`` counter), mirroring FusionBailout.

    Returns ``run(points64_abs) -> (zone [N] int32, rechecked count)``
    with ``run.last_decision`` (the planner pick) and ``run.stats``
    (levels / cells_refined / cells_flat / refined_points /
    flat_points for the most recent call)."""
    import time as _time
    from ..config import default_config
    from ..core.tessellate import tessellate_subset
    from ..obs import metrics
    from ..obs.inflight import (QueryCancelled, checkpoint, note_refine,
                                note_strategies)
    from ..perf.bucketing import pow2_bucket
    from ..perf.jit_cache import kernel_cache
    from ..resilience import faults
    from ..sql.planner import Decision, planner

    chunk = _resolve_chunk(chunk)
    chips = tessellate(polys, res, grid, keep_core_geom=False)
    idx_base = build_pip_index(polys, res, grid, chips=chips,
                               dense="never")
    clean_base = _chips_clean(chips)
    recheck_base = host_recheck_fn(idx_base, polys)
    b_cells = chips.cell_id[~chips.is_core]
    u_cells, u_dup = (np.unique(b_cells, return_counts=True)
                      if len(b_cells) else
                      (np.empty(0, np.int64), np.empty(0, np.int64)))
    state = {"probed": False, "dense": np.empty(0, np.int64),
             "frac": 0.0, "depth": 0, "ref": None, "ref_unclean": False,
             "flat": None, "route_host": False}

    def _route_cells(pts64: np.ndarray) -> np.ndarray:
        """Base-level cell ids for the hot/cold routing split, via the
        jitted device kernel (f64 under the global x64 switch,
        canonical-pinned against the host path by
        tests/test_h3_canonical.py) — the interpreted host assignment
        at flagship sizes costs more than the join itself.  Routing is
        never answer authority: a cold-routed point runs the full base
        index, and _ensure_refined's bbox inflation holds every
        polygon that can contain a hot-routed point, so either routing
        outcome yields the oracle zone."""
        rows = len(pts64)
        if rows == 0:
            return np.empty(0, np.int64)
        if not state["route_host"]:
            try:
                per = pow2_bucket(rows, floor=64)
                buf = np.empty((per, 2), np.float64)
                buf[:rows] = pts64
                buf[rows:] = pts64[0]
                fn = kernel_cache.get_or_build(
                    "pip/route", (id(grid), res, per),
                    lambda: jax.jit(
                        lambda p: grid.point_to_cell_jax(p, res)))
                return np.asarray(fn(jnp.asarray(buf)))[:rows]
            except Exception:       # host-only grid: route there instead
                state["route_host"] = True
        return grid.point_to_cell(pts64, res)

    def _probe(points64: np.ndarray) -> None:
        """Sticky selectivity probe: per-border-cell estimated
        candidate pairs = (sample points in cell) x (chips in cell)."""
        cfg = default_config()
        sample = points64[:max(1, int(cfg.join_refine_sample_rows))]
        if not len(u_cells) or not len(sample):
            return
        cells = _route_cells(np.asarray(sample, np.float64))
        pos = np.searchsorted(u_cells, cells)
        posc = np.clip(pos, 0, len(u_cells) - 1)
        valid = (pos < len(u_cells)) & (u_cells[posc] == cells)
        counts = np.bincount(posc[valid], minlength=len(u_cells))
        pairs = counts.astype(np.float64) * u_dup
        total = float(pairs.sum())
        floor = int(cfg.join_refine_dup_threshold)
        sel = np.nonzero((u_dup >= floor) & (counts > 0))[0]
        cap = max(1, int(cfg.join_refine_max_cells))
        if len(sel) > cap:
            sel = sel[np.argsort(-pairs[sel], kind="stable")[:cap]]
        state["dense"] = np.sort(u_cells[sel])
        state["frac"] = float(pairs[sel].sum()) / total if total else 0.0

    def _ensure_refined(depth: int) -> bool:
        """Build the deeper index over the dense cells' polygons once
        (sticky at the first requested depth); False = parity gate
        failed at the refined level, caller must run flat."""
        if state["ref"] is not None:
            return True
        if state["ref_unclean"]:
            return False
        dense = state["dense"]
        if not len(dense):
            state["ref"] = {"empty": True}
            state["depth"] = max(1, int(depth))
            return True
        verts, counts = grid.cell_boundary(dense)
        m = np.arange(verts.shape[1])[None, :] < counts[:, None]
        vx, vy = verts[..., 0], verts[..., 1]
        cb = np.stack([np.where(m, vx, np.inf).min(1),
                       np.where(m, vy, np.inf).min(1),
                       np.where(m, vx, -np.inf).max(1),
                       np.where(m, vy, -np.inf).max(1)], axis=1)
        # inflate by the chord-vs-gnomonic sagitta: the true cell edge
        # can bow past the vertex-chord bbox, and the subset must hold
        # EVERY polygon that can contain a dense-routed point
        pad = max(1e-9, 2.0 * float(idx_base.sagitta_deg))
        cb += np.array([-pad, -pad, pad, pad])
        pb = polys.bboxes()
        inter = ~((pb[:, None, 0] > cb[None, :, 2]) |
                  (pb[:, None, 2] < cb[None, :, 0]) |
                  (pb[:, None, 1] > cb[None, :, 3]) |
                  (pb[:, None, 3] < cb[None, :, 1]))
        sub_ids = np.nonzero(inter.any(axis=1))[0]
        depth = max(1, int(depth))
        sub, sub_chips = tessellate_subset(polys, sub_ids, res + depth,
                                           grid, keep_core_geom=False)
        if not _chips_clean(sub_chips):
            state["ref_unclean"] = True
            return False
        idx_ref = build_pip_index(sub, res + depth, grid,
                                  chips=sub_chips, dense="never")
        state["ref"] = {"idx": idx_ref, "orig": sub_ids.astype(np.int32),
                        "recheck": host_recheck_fn(idx_ref, sub)}
        state["depth"] = depth
        return True

    def _kernel(idx_level, rows: int):
        # one entry per (level index, pow2 bucket): a warm process
        # with a persistent cache dir loads both executables from disk
        return kernel_cache.get_or_build(
            "pip/refined",
            (id(idx_level), idx_level.res, rows, eps, margin_eps,
             precision),
            lambda: jax.jit(make_pip_join_fn(
                idx_level, grid, eps, margin_eps, precision)))

    def _run_part(idx_level, recheck, pts64: np.ndarray):
        rows = len(pts64)
        if rows == 0:
            return np.empty(0, np.int32), 0
        # greedy pow2 decomposition rather than one rounded-up bucket:
        # the hot/cold split lands wherever the data says (a 51% part
        # would pad to ~2x its rows, and padding rows run the kernel
        # at full price).  Stopping an eighth below the leading bucket
        # bounds the waste at 12.5% across at most 5 launches, every
        # one still cached per (level, bucket).
        lead = 1 << (rows.bit_length() - 1)
        floor = max(64, lead >> 3)
        origin = np.asarray(idx_level.origin)[None]
        z = np.empty(rows, np.int32)
        unc = np.empty(rows, bool)
        s = 0
        while s < rows:
            rem = rows - s
            per = max(floor, 1 << (rem.bit_length() - 1))
            take = min(rem, per)
            buf = np.full((per, 2), _PAD_SENTINEL_DEG, np.float32)
            # f64 origin shift before the f32 cast (= localize()); pad
            # rows keep the sentinel and resolve to -1 without recheck
            buf[:take] = np.asarray(pts64[s:s + take] - origin,
                                    np.float32)
            zz, uu = _kernel(idx_level, per)(jnp.asarray(buf))
            z[s:s + take] = np.asarray(zz)[:take]
            unc[s:s + take] = np.asarray(uu)[:take]
            s += take
        return recheck(pts64, z, unc), int(unc.sum())

    def _flat():
        if state["flat"] is None:
            state["flat"] = make_streamed_pip_join(
                idx_base, grid, polys=polys, chunk=chunk, eps=eps,
                margin_eps=margin_eps, precision=precision)
        return state["flat"]

    def _refined(points64: np.ndarray):
        from ..obs import tracer
        from ..obs.context import root_trace
        ref = state["ref"]
        dense = state["dense"]
        n = len(points64)
        zone = np.empty(n, np.int32)
        rechecked = refined_pts = 0
        with root_trace("pip_join"), tracer.span("pip_join/refined"):
            for sl in chunk_rows(n, chunk):
                checkpoint()
                faults.maybe_fail("join.refine")
                pts = points64[sl]
                if len(dense) and "idx" in ref:
                    cells = _route_cells(pts)
                    pos = np.searchsorted(dense, cells)
                    posc = np.clip(pos, 0, len(dense) - 1)
                    hot = (pos < len(dense)) & (dense[posc] == cells)
                else:
                    hot = np.zeros(len(pts), bool)
                out = np.empty(len(pts), np.int32)
                za, ra = _run_part(idx_base, recheck_base, pts[~hot])
                out[~hot] = za
                if hot.any():
                    zb, rb = _run_part(ref["idx"], ref["recheck"],
                                       pts[hot])
                    orig = ref["orig"]
                    out[hot] = np.where(
                        zb >= 0, orig[np.clip(zb, 0, len(orig) - 1)],
                        np.int32(-1))
                    rechecked += rb
                    refined_pts += int(hot.sum())
                rechecked += ra
                zone[sl] = out
        return zone, rechecked, refined_pts

    def run(points64: np.ndarray):
        points64 = np.asarray(points64, np.float64)[:, :2]
        n = len(points64)
        if not state["probed"]:
            _probe(points64)
            state["probed"] = True
        if not clean_base:
            # parity gate, not a cost call: the clean-index theorem
            # doesn't hold here, so refinement is off the table no
            # matter what the planner (or a pin) would prefer
            d = Decision("refine", "flat",
                         "overlap regime at base level (parity gate)",
                         n, cost_key="refine/flat", key_n=n,
                         forced=True)
            d.depth = 0
            planner.record_decision(d)
        else:
            d = planner.decide_refine(n, state["frac"],
                                      idx_base.max_dup)
            if d.strategy == "refined" and \
                    not _ensure_refined(getattr(d, "depth", 1)):
                d.strategy = "flat"
                d.reason = ("overlap regime at refined level "
                            "(parity gate)")
                d.cost_key = "refine/flat"
                d.forced = True
                planner.record_decision(d)
        t0 = _time.perf_counter()
        refined_pts = 0
        bailed = False
        if d.strategy == "refined":
            try:
                zone, rechecked, refined_pts = _refined(points64)
            except (QueryCancelled, KeyboardInterrupt):
                raise
            except Exception as e:          # transparent flat fallback
                bailed = True
                if metrics.enabled:
                    metrics.count("pip_join/refine_bailouts")
                from ..obs.recorder import recorder
                recorder.record("refine_bailout",
                                error=type(e).__name__,
                                detail=str(e)[:200], rows=n)
                refined_pts = 0
                zone, rechecked = _flat()(points64)
        else:
            zone, rechecked = _flat()(points64)
        wall = _time.perf_counter() - t0
        planner.observe_decision(d, wall,
                                 rows_out=int((zone >= 0).sum()))
        depth = state["depth"] or int(getattr(d, "depth", 1) or 1)
        # stats describe what RAN (the decision object keeps what was
        # decided — they differ exactly when a bailout demoted the run)
        refined_run = (d.strategy == "refined" and not bailed
                       and state["ref"] is not None and refined_pts > 0)
        cells_refined = len(state["dense"]) if refined_run else 0
        stats = {
            "levels": [res, res + depth] if refined_run else [res],
            "cells_refined": cells_refined,
            "cells_flat": len(u_cells) - cells_refined,
            "refined_points": int(refined_pts),
            "flat_points": int(n - refined_pts),
            "strategy": "refined" if refined_run else "flat",
        }
        if metrics.enabled and refined_pts:
            metrics.count("pip_join/refined_points",
                          float(refined_pts))
        note_strategies({"refine": d.label + (" (bailout)" if bailed
                                              else "")})
        if refined_run:
            summary = (f"L{res}+{depth}: {cells_refined} refined / "
                       f"{stats['cells_flat']} flat cells, "
                       f"{refined_pts}/{n} pts")
        else:
            summary = "flat"
        note_refine({k: stats[k] for k in
                     ("cells_refined", "cells_flat", "refined_points",
                      "flat_points")}, summary=summary)
        run.stats = stats
        run.last_decision = d
        return zone, rechecked

    run.stats = None
    run.last_decision = None
    return run


def zone_histogram(zone: jnp.ndarray, num_zones: int) -> jnp.ndarray:
    """Per-zone match counts — the canonical aggregation after the join
    (reference: groupBy(index_id).count()).  A scatter-add segment sum
    (O(N), not an O(N·Z) one-hot); unmatched (-1) rows are dropped.
    Under pjit this lowers to a sharded segment-sum + psum over the data
    axis.

    ``.at[].add(mode="drop")`` normalizes negative indices NumPy-style
    *before* dropping, so -1 would wrap to the last zone; remap invalid
    rows to ``num_zones`` (genuinely out of bounds) so drop applies."""
    zone = jnp.where(zone < 0, jnp.int32(num_zones), zone)
    return jnp.zeros(num_zones, jnp.int32).at[zone].add(
        1, mode="drop", indices_are_sorted=False)


# --------------------------------------------------- dense lattice index
#
# The sorted-table path above is grid-agnostic but pays ~29 serial
# binary-search gathers per point; measured on TPU v5e that was 56% of
# the whole join (scratch: 1.9 s of a 3.4 s step at 4M points — TPU
# gathers cost ~16-30 ns per row regardless of row width).  For H3
# workloads that fit one icosahedron face (any city/metro/state-scale
# join), the H3 kernel's intermediate (face, a, b) lattice coords index
# a dense window table directly: ONE int32 gather replaces both binary
# searches, and all chips of a cell are packed into ONE pool row so the
# edge test is ONE more gather.  Design rule: one gather per point per
# logical step.

CORE_FLAG = np.int32(1) << 30


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DensePIPIndex:
    """Device-resident dense-window tessellation index (H3, one face).

    entry  [W*H] i32   per lattice cell: -1 empty; CORE_FLAG|zone core;
                       else group index into pool
    pool   [G, E, 5]   merged chip edges per border cell, local-frame
                       f32: ax, ay, bx, by, zslot (-1 pad; pad coords
                       at +1e9 so they never straddle/flag)
    gzones [G, Z] i32  distinct zone ids per group (-1 pad)
    origin [2] f64     local-frame origin (lon, lat)
    static: face0, a0, b0, W, H, res, err_lattice (margin threshold),
            n_zones
    host-side aux (not traced): recheck CSR in f64 (see host_recheck_fn)
    """

    entry: jnp.ndarray
    pool: jnp.ndarray
    gzones: jnp.ndarray
    #: [G] bool — group's chip edges exceed the pool width (a complex
    #: coastline cell): every point landing there is flagged uncertain
    #: and resolved by the exact f64 host recheck, so ONE wide cell
    #: cannot pad the whole pool (real NYC zones: max 308 edges vs
    #: mean 19 made the kernel 12x slower than the synthetic bench)
    gwide: jnp.ndarray
    origin: np.ndarray
    face0: int
    a0: int
    b0: int
    W: int
    H: int
    res: int
    err_lattice: float
    n_zones: int
    #: max |local degree| over window cells (+ slack); join queries
    #: beyond this are out-of-domain by construction
    ext_deg: float = 2.0
    aux: Optional[dict] = None

    def tree_flatten(self):
        return ((self.entry, self.pool, self.gzones, self.gwide),
                (self.origin.tobytes(), self.face0, self.a0, self.b0,
                 self.W, self.H, self.res, self.err_lattice,
                 self.n_zones, self.ext_deg))

    @classmethod
    def tree_unflatten(cls, aux, children):
        origin = np.frombuffer(aux[0], np.float64)
        return cls(*children, origin, *aux[1:])

    @property
    def num_chips(self) -> int:
        return int(self.pool.shape[0])


def _host_lattice(grid, pts_deg: np.ndarray, res: int):
    """f64 (face, a, b) of absolute lon/lat degree points (host truth)."""
    from ..core.index.h3 import hexmath as hm
    latlng = np.radians(np.asarray(pts_deg, np.float64)[:, ::-1])
    face, hex2d = hm.project_lattice(latlng, res)
    ijk = hm.hex2d_to_ijk(hex2d)
    return face, ijk[:, 0] - ijk[:, 2], ijk[:, 1] - ijk[:, 2]


#: why the last build_dense_pip_index call fell back (None = it
#: didn't) — surfaced so a workload quietly losing the fast path is
#: diagnosable (VERDICT round-3 weak #9); also counted in the tracer
#: as dense_reject/<reason>
LAST_DENSE_REJECT: Optional[str] = None


def _dense_reject(reason: str) -> None:
    global LAST_DENSE_REJECT
    LAST_DENSE_REJECT = reason
    try:
        from ..obs import tracer
        tracer.count(f"dense_reject/{reason}")
    except Exception:
        pass


def build_dense_pip_index(polys: GeometryArray, res: int, grid,
                          chips: Optional[ChipSet] = None,
                          precision: str = "auto"
                          ) -> Optional[DensePIPIndex]:
    """Build the dense-window index, or None when the workload doesn't
    fit the fast path (non-H3 grid, cells spanning icosahedron faces,
    window larger than the df Taylor bound, or overlapping polygons
    putting one cell in both core and border sets — the sorted-table
    path handles those).  The reject reason lands in
    ``LAST_DENSE_REJECT`` and the tracer counters."""
    global LAST_DENSE_REJECT
    LAST_DENSE_REJECT = None
    from ..core.geometry.padded import build_edges_np
    from ..core.index.h3.jaxkernel import (MAX_LOCAL_DEG, err_lattice_bound,
                                           pick_precision)
    from ..core.index.h3.system import H3IndexSystem

    if not isinstance(grid, H3IndexSystem):
        _dense_reject("non_h3_grid")
        return None
    if chips is None:
        chips = tessellate(polys, res, grid, keep_core_geom=False)
    if len(chips) == 0:
        _dense_reject("no_chips")
        return None

    cells = np.unique(chips.cell_id)
    centers = grid.cell_center(cells)                    # [C, 2] deg
    origin = _workload_origin(polys)
    _, circ = grid._cell_metrics_deg(res)                # max circumradius
    # 2x: circumradius is angular degrees; lon extent is circ/cos(lat)
    ext = float(max(np.max(np.abs(centers[:, 0] - origin[0])),
                    np.max(np.abs(centers[:, 1] - origin[1])))) + 2 * circ
    if ext > MAX_LOCAL_DEG - 0.1:
        _dense_reject("window_extent")
        return None
    face_c, a_c, b_c = _host_lattice(grid, centers, res)
    if len(np.unique(face_c)) != 1:
        _dense_reject("multi_face")
        return None
    # face-edge safety: every window cell must be interior enough that
    # no point of it can argmax to another face (facegap ≈ angular
    # distance to the face boundary; 0.02 ≈ 1.1 degrees of arc)
    from ..core.index.h3.hexmath import geo_to_xyz, face_center_xyz
    xyz = geo_to_xyz(np.radians(centers[:, ::-1]))
    dots = xyz @ face_center_xyz().T
    srt = np.sort(dots, axis=1)
    if np.min(srt[:, -1] - srt[:, -2]) < 0.02:
        _dense_reject("face_edge_band")
        return None

    core = chips.is_core
    core_cells = chips.cell_id[core]
    if len(np.intersect1d(core_cells, chips.cell_id[~core])):
        _dense_reject("overlap_regime")
        return None                                      # overlap regime
    if len(np.unique(core_cells)) != len(core_cells):
        _dense_reject("duplicate_core")
        return None

    face0 = int(face_c[0])
    a0, b0 = int(a_c.min()) - 1, int(b_c.min()) - 1
    W = int(a_c.max()) - a0 + 2
    H = int(b_c.max()) - b0 + 2
    if W * H > 64_000_000:
        _dense_reject("window_too_large")
        return None

    lat_of = {int(c): (int(a), int(b))
              for c, a, b in zip(cells, a_c, b_c)}

    entry = np.full(W * H, -1, np.int32)

    def lin(cell):
        a, b = lat_of[int(cell)]
        return (a - a0) * H + (b - b0)

    for c, z in zip(core_cells, chips.geom_id[core]):
        entry[lin(c)] = np.int32(z) | CORE_FLAG

    # ---- border groups: all chips of a cell merged into one edge soup
    b_cells = chips.cell_id[~core]
    b_zone = chips.geom_id[~core].astype(np.int32)
    border_idx = np.nonzero(~core)[0]
    order = np.argsort(b_cells, kind="stable")
    b_cells, b_zone = b_cells[order], b_zone[order]
    chip_geoms = chips.geoms.take(border_idx[order])
    A, B, M = build_edges_np(chip_geoms)                 # [Bc, cap, 2] f64
    cnt = M.sum(axis=1)

    ucells, ustart = np.unique(b_cells, return_index=True)
    G = len(ucells)
    gidx = np.searchsorted(ucells, b_cells)              # chip -> group
    gedges = np.bincount(gidx, weights=cnt).astype(np.int64)
    # pool width covers the 98th-percentile group; wider groups are
    # truncated and their cells flagged always-uncertain (host f64
    # resolves them exactly) — one pathological cell must not pad the
    # kernel for every point
    emax = int(gedges.max()) if G else 0
    etarget = int(max(np.quantile(gedges, 0.98), 8)) if G else 8
    E = 8
    while E < min(emax, etarget):
        E *= 2
    E = min(E, 512)
    gwide_np = gedges > E
    if G and float(gwide_np.mean()) > 0.2:
        # most cells would bounce to host: dense is the wrong shape
        _dense_reject("pathological_cell")
        return None

    # distinct zones per group, first-appearance order; per-chip zslot
    Z = 1
    gzone_lists: list = [[] for _ in range(G)]
    zslot_chip = np.zeros(len(b_cells), np.int32)
    for i in range(len(b_cells)):
        zl = gzone_lists[gidx[i]]
        z = int(b_zone[i])
        if z not in zl:
            zl.append(z)
        zslot_chip[i] = zl.index(z)
    Z = max(1, max(len(zl) for zl in gzone_lists))
    gzones = np.full((G, Z), -1, np.int32)
    for g, zl in enumerate(gzone_lists):
        gzones[g, :len(zl)] = zl

    for g, c in enumerate(ucells):
        entry[lin(c)] = np.int32(g)

    # flatten valid edges in (group, chip, edge) order — already sorted
    flat_a = A[M]                                        # [Etot, 2] f64
    flat_b = B[M]
    edge_chip = np.repeat(np.arange(len(b_cells)), cnt.astype(np.int64))
    edge_group = gidx[edge_chip]
    edge_zslot = zslot_chip[edge_chip]
    gstart = np.zeros(G + 1, np.int64)
    np.cumsum(gedges, out=gstart[1:])
    pos = np.arange(len(flat_a)) - gstart[edge_group]

    pool = np.full((max(G, 1), E, 5), 1e9, np.float32)
    pool[..., 4] = -1.0
    loc_a = flat_a - origin[None]
    loc_b = flat_b - origin[None]
    fits = pos < E                       # wide-group overflow truncated
    eg, ep = edge_group[fits], pos[fits]
    pool[eg, ep, 0] = loc_a[fits, 0].astype(np.float32)
    pool[eg, ep, 1] = loc_a[fits, 1].astype(np.float32)
    pool[eg, ep, 2] = loc_b[fits, 0].astype(np.float32)
    pool[eg, ep, 3] = loc_b[fits, 1].astype(np.float32)
    pool[eg, ep, 4] = edge_zslot[fits].astype(np.float32)

    prec = pick_precision(precision)
    ext_deg = float(ext) + 0.1
    err = err_lattice_bound(res, prec, ext_deg, localized=True)
    # widen by the cell-edge sagitta: points between the true (gnomonic)
    # cell boundary and the straight lon/lat chord the chips were
    # clipped against must re-rank on host (negligible at city
    # resolutions, dominant at coarse ones).  Exact over the window's
    # own cells; degrees -> lattice units via the gnomonic scale.
    from ..core.index.h3.constants import M_SQRT7, RES0_U_GNOMONIC
    sag_deg = grid.cells_edge_sagitta_deg(cells) if hasattr(
        grid, "cells_edge_sagitta_deg") else 0.0
    err = max(err, 2.0 * np.radians(sag_deg) * M_SQRT7 ** res /
              RES0_U_GNOMONIC)
    aux = {
        "flat_a": flat_a, "flat_b": flat_b,
        "edge_zslot": edge_zslot.astype(np.int64),
        "gstart": gstart, "gzones64": gzones.astype(np.int64),
        "grid": grid, "polys": polys,
    }
    return DensePIPIndex(
        entry=jnp.asarray(entry), pool=jnp.asarray(pool),
        gzones=jnp.asarray(gzones),
        gwide=jnp.asarray(np.resize(gwide_np, max(G, 1))),
        origin=origin, face0=face0,
        a0=a0, b0=b0, W=W, H=H, res=res, err_lattice=float(err),
        n_zones=len(polys), ext_deg=ext_deg, aux=aux)


def make_dense_pip_join_fn(idx: DensePIPIndex, eps: float = EPS_EDGE_DEG,
                           precision: str = "auto",
                           margin_eps_deg: Optional[float] = None):
    """Jittable ``local_points -> (zone, uncertain)`` on the dense index.

    Exactness contract (same as the sorted path): every f32 hazard
    raises ``uncertain`` — (a) hex-boundary margin below the validated
    projection error bound (cell assignment could differ from f64),
    (b) nearest-face ambiguity, (c) edge-crossing tests within ``eps``
    of flipping (horizontal crossing distance or ray-through-vertex).
    Points beyond the window's local extent are out-of-domain by
    construction: zone -1, certain (their projection may even be outside
    the df Taylor validity radius, so it must not be consulted).
    host_recheck_fn resolves flagged points in f64."""
    from ..core.index.h3.jaxkernel import (FACEGAP_EPS, err_lattice_bound,
                                           pick_precision,
                                           project_lattice_jax)
    Z = int(idx.gzones.shape[1])
    # margin threshold must match the arithmetic that actually runs —
    # idx.err_lattice was derived at build time, possibly on another
    # backend/precision; recompute for the resolved path and take the
    # wider of the two
    err_lat = max(idx.err_lattice, err_lattice_bound(
        idx.res, pick_precision(precision), idx.ext_deg, localized=True))
    if margin_eps_deg is not None:
        # honor a caller-requested degree band: degrees -> lattice units
        from ..core.index.h3.constants import M_SQRT7, RES0_U_GNOMONIC
        scale = M_SQRT7 ** idx.res / RES0_U_GNOMONIC
        err_lat = max(err_lat, margin_eps_deg * np.pi / 180.0 * scale)
    far_lim = np.float32(idx.ext_deg + 0.05)

    import os
    use_pallas = os.environ.get("MOSAIC_PIP_PALLAS", "").lower() in (
        "1", "true", "yes")
    if use_pallas:
        # the Pallas kernel runs df arithmetic regardless of the
        # requested precision; the margin threshold must match it
        err_lat = max(err_lat, err_lattice_bound(
            idx.res, "df", idx.ext_deg, localized=True))

    def fn(points):
        if use_pallas:
            # opt-in Pallas projection kernel (ops/pallas_projection.py)
            # until validated on hardware; same contract, same outputs
            from ..ops.pallas_projection import project_lattice_pallas
            face, ai, bi, margin, facegap = project_lattice_pallas(
                points, idx.res,
                # graftlint: ignore[jit-host-sync] — idx.origin is a host-side numpy constant closed over, folds at trace time
                (float(idx.origin[0]), float(idx.origin[1])))
        else:
            face, ai, bi, margin, facegap = project_lattice_jax(
                points, idx.res, idx.origin, precision=precision)
        far = (jnp.abs(points[..., 0]) > far_lim) | \
            (jnp.abs(points[..., 1]) > far_lim)
        ia = ai - idx.a0
        ib = bi - idx.b0
        inw = ((face == idx.face0) & (ia >= 0) & (ia < idx.W) &
               (ib >= 0) & (ib < idx.H))
        lidx = jnp.where(inw, ia * idx.H + ib, 0)
        e = jnp.where(inw, idx.entry[lidx], jnp.int32(-1))
        is_core = (e >= 0) & ((e & CORE_FLAG) != 0)
        zone_core = jnp.where(is_core, e & ~CORE_FLAG, jnp.int32(-1))
        is_border = (e >= 0) & ~is_core

        g = jnp.where(is_border, e, 0)
        rec = idx.pool[g]                               # [N, E, 5]
        ax, ay = rec[..., 0], rec[..., 1]
        bx, by = rec[..., 2], rec[..., 3]
        zs = rec[..., 4].astype(jnp.int32)
        px = points[..., None, 0]
        py = points[..., None, 1]
        straddle = (ay <= py) != (by <= py)
        t = (py - ay) / jnp.where(by == ay, jnp.ones_like(by), by - ay)
        xi = ax + t * (bx - ax)
        crossed = straddle & (px < xi)
        near_cross = straddle & (jnp.abs(px - xi) < eps)
        near_vertex = (jnp.abs(py - ay) < eps) & \
            (px < jnp.maximum(ax, bx) + eps)
        edge_flag = jnp.any(near_cross | near_vertex, axis=-1) & is_border

        inside = []
        for z in range(Z):
            cnt = jnp.sum(crossed & (zs == z), axis=-1)
            inside.append((cnt & 1).astype(bool))
        inside = jnp.stack(inside, axis=-1)             # [N, Z]
        first = jnp.argmax(inside, axis=-1)
        any_in = jnp.any(inside, axis=-1)
        gz = idx.gzones[g]                              # [N, Z]
        zone_border = jnp.where(
            any_in & is_border,
            jnp.take_along_axis(gz, first[..., None], axis=-1)[..., 0],
            jnp.int32(-1))

        zone = jnp.where(is_core, zone_core, zone_border)
        wide = idx.gwide[g] & is_border
        uncertain = (margin < np.float32(err_lat)) | \
            (facegap < np.float32(FACEGAP_EPS)) | edge_flag | wide
        zone = jnp.where(far, jnp.int32(-1), zone)
        uncertain = uncertain & ~far
        return zone, uncertain

    return fn


def host_recheck_fn(idx, polys: Optional[GeometryArray] = None):
    """Vectorized f64 host recheck bound to an index (either kind).

    Returns ``recheck(points64_abs, zone, uncertain) -> zone`` that
    reruns the flagged points through the SAME chip semantics in f64 —
    exact cell assignment (host lattice), exact crossing parity against
    the original unquantized chip edges.  Replaces the per-polygon
    Python loop (round-2 host_recheck) that VERDICT.md flagged as
    unscalable: this is a handful of numpy passes over the flagged
    subset.

    For a sorted ``PIPIndex`` (no dense aux tables) the recheck
    authority is the original polygons — pass ``polys``; the returned
    closure wraps :func:`host_recheck`.  (Round-4 fix: this used to
    raise AttributeError on the sorted index type.)"""
    if not isinstance(idx, DensePIPIndex):
        if polys is None:
            raise ValueError(
                "host_recheck_fn on a sorted PIPIndex needs the original "
                "polygons: host_recheck_fn(idx, polys)")
        return lambda pts, zone, uncertain: host_recheck(
            np.asarray(pts), np.asarray(zone), np.asarray(uncertain),
            polys)
    aux = idx.aux
    assert aux is not None, "recheck needs the build-time aux tables"
    entry = np.asarray(idx.entry)
    Z = int(idx.gzones.shape[1])
    # native-kernel tables, prepared ONCE at bind time (per-call work
    # must scale with the flagged subset, not the chip-edge pool) —
    # and only when the native path can actually run
    try:
        from .. import native as _native
    except ImportError:
        _native = None
    if _native is not None and (_native.get_lib() is None or Z > 16):
        _native = None
    if _native is not None:
        flat_native = np.ascontiguousarray(
            np.concatenate([aux["flat_a"], aux["flat_b"]], axis=1))
        ezslot_native = aux["edge_zslot"].astype(np.int32)
        gzones_native = np.ascontiguousarray(
            aux["gzones64"].astype(np.int32))

    def recheck(points64: np.ndarray, zone: np.ndarray,
                uncertain: np.ndarray) -> np.ndarray:
        sel = np.nonzero(uncertain)[0]
        if len(sel) == 0:
            return zone
        zone = np.asarray(zone).copy()
        pts = np.asarray(points64)[sel]
        face, a, b = _host_lattice(aux["grid"], pts, idx.res)
        ia = a - idx.a0
        ib = b - idx.b0
        inw = ((face == idx.face0) & (ia >= 0) & (ia < idx.W) &
               (ib >= 0) & (ib < idx.H))
        e = np.where(inw, entry[np.where(inw, ia * idx.H + ib, 0)], -1)
        out = np.full(len(sel), -1, np.int32)
        is_core = (e >= 0) & ((e & int(CORE_FLAG)) != 0)
        out[is_core] = (e[is_core] & ~int(CORE_FLAG))

        isb = (e >= 0) & ~is_core
        bsel = np.nonzero(isb)[0]
        if len(bsel):
            # native chip-parity core when the C++ layer is available
            if _native is not None:
                grp = np.full(len(sel), -1, np.int64)
                grp[bsel] = e[bsel]
                nz = _native.recheck_zones(
                    pts, grp, flat_native, ezslot_native,
                    aux["gstart"], gzones_native)
                if nz is not None:
                    out[bsel] = nz[bsel]
                    zone[sel] = out
                    return zone
            g = e[bsel].astype(np.int64)
            gstart = aux["gstart"]
            cnt = (gstart[g + 1] - gstart[g]).astype(np.int64)
            total = int(cnt.sum())
            pidx = np.repeat(np.arange(len(bsel)), cnt)
            estart = np.repeat(gstart[g], cnt)
            local = np.arange(total) - np.repeat(
                np.concatenate([[0], np.cumsum(cnt)[:-1]]), cnt)
            eidx = estart + local
            pa = aux["flat_a"][eidx]
            pb = aux["flat_b"][eidx]
            zsl = aux["edge_zslot"][eidx]
            P = pts[bsel][pidx]
            ay, by = pa[:, 1], pb[:, 1]
            straddle = (ay <= P[:, 1]) != (by <= P[:, 1])
            denom = np.where(by == ay, 1.0, by - ay)
            xi = pa[:, 0] + (P[:, 1] - ay) / denom * (pb[:, 0] - pa[:, 0])
            crossed = straddle & (P[:, 0] < xi)
            counts = np.bincount(pidx * Z + zsl, weights=crossed,
                                 minlength=len(bsel) * Z)
            odd = (counts.reshape(len(bsel), Z).astype(np.int64) & 1)\
                .astype(bool)
            anyin = odd.any(axis=1)
            first = odd.argmax(axis=1)
            gz = aux["gzones64"][g, first]
            out[bsel[anyin]] = gz[anyin].astype(np.int32)
        zone[sel] = out
        return zone

    return recheck


def pip_host_truth(points64: np.ndarray,
                   polys: GeometryArray) -> np.ndarray:
    """The exact float64 host oracle: first polygon containing each point
    (crossing-number, first-match tie-break) — the single source of truth
    that host_recheck, tests and bench all compare against.

    Routes through the native C++ kernel (mosaic_tpu.native, the
    JTS/GEOS-analogue layer) when the toolchain built it — bit-identical
    crossing rule — and falls back to the numpy broadcast loop."""
    from ..core.tessellate import _pip, _poly_edges
    edges_list = [_poly_edges(polys, gi) for gi in range(len(polys))]
    try:
        from .. import native
    except ImportError:
        native = None
    if native is not None and len(polys):
        gs = np.zeros(len(polys) + 1, np.int64)
        np.cumsum([len(e) for e in edges_list], out=gs[1:])
        flat = np.concatenate(edges_list).reshape(-1, 4)
        # unavailability is signalled by None (no compiler); real
        # errors must raise, not silently fall back to the slow path
        out = native.pip_first_match(np.asarray(points64)[:, :2], flat,
                                     gs)
        if out is not None:
            return out
    truth = np.full(len(points64), -1, np.int32)
    for gi in range(len(polys)):
        inside = _pip(points64, edges_list[gi])
        truth = np.where((truth < 0) & inside, gi, truth)
    return truth


def host_recheck(points64: np.ndarray, zone: np.ndarray,
                 uncertain: np.ndarray, polys: GeometryArray) -> np.ndarray:
    """Re-run the uncertain points in float64 against the original polygons
    (not the chips) on host — the exact tie-break authority."""
    sel = np.nonzero(uncertain)[0]
    if len(sel) == 0:
        return zone
    zone = zone.copy()
    zone[sel] = pip_host_truth(points64[sel], polys)
    return zone
