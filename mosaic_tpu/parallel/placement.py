"""Skew-aware chip→device placement for the sharded join.

Row-order sharding (``P("data")`` splits the batch into D contiguous
blocks) is only balanced when matched work is uncorrelated with row
order.  Real point feeds are usually sorted by something spatial
(zone, tile, ingest region), so one shard ends up holding most of the
matched candidates while the rest grind padding — the classic
distributed-spatial-join skew problem (LocationSpark, arxiv
1907.03736; the partition-parallel join blueprint of arxiv 1908.11740
makes the same observation for partition assignment).

:class:`SkewRebalancer` is the placement pass the sharded streamed
join consults per chunk:

* **observe** — every consumed chunk feeds back which coarse grid
  cells (a ``nbins``×``nbins`` lattice over the observed extent) its
  matched candidates landed in; densities decay exponentially so the
  placement tracks drift.
* **rebalance** — every ``refresh`` observations (the
  ``mosaic.shard.skew.refresh`` conf key's cadence) the bins are
  re-packed greedily: bins in descending density order, each to the
  currently least-loaded shard.  Recomputed, not first-call-only.
* **place** — :func:`placement_slots` turns the per-row shard
  preference into slot indices inside the padded device buffer: each
  shard's block holds at most ``cap`` rows, overflow spills to shards
  with spare capacity, and padding fills the rest.  The inverse is a
  plain gather, so rebalancing never changes results — only which
  device computes which row.

Pure numpy; one branch when no stats have been observed yet (identity
placement — arrival order)."""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["SkewRebalancer", "placement_slots"]


def placement_slots(pref: Optional[np.ndarray], n: int, n_shards: int,
                    cap: int) -> np.ndarray:
    """Slot index inside a ``[n_shards * cap]``-row padded buffer for
    each of ``n`` rows.

    ``pref`` is the preferred shard per row (or None for identity
    placement: rows fill shard blocks in arrival order).  Each shard's
    block is ``[s * cap, (s + 1) * cap)``; rows keep their relative
    order inside a block (stable), and rows preferring a full shard
    spill to the shards with free capacity.  Requires
    ``n <= n_shards * cap``; every returned slot is unique."""
    if n > n_shards * cap:
        raise ValueError(f"{n} rows exceed {n_shards}x{cap} capacity")
    if pref is None:
        return np.arange(n, dtype=np.int64)

    def ranks(shard):
        order = np.argsort(shard, kind="stable")
        counts = np.bincount(shard, minlength=n_shards)
        starts = np.zeros(n_shards, np.int64)
        np.cumsum(counts[:-1], out=starts[1:])
        rank = np.empty(n, np.int64)
        rank[order] = np.arange(n) - starts[shard[order]]
        return rank, counts

    shard = np.asarray(pref, np.int64).copy()
    rank, counts = ranks(shard)
    over = rank >= cap
    if over.any():
        free = cap - np.minimum(counts, cap)
        targets = np.repeat(np.arange(n_shards), free)[:int(over.sum())]
        shard[over] = targets
        rank, _ = ranks(shard)
    return shard * cap + rank


class SkewRebalancer:
    """Greedy bin-packing of coarse grid cells onto shards by observed
    matched-candidate density (see module docstring)."""

    def __init__(self, n_shards: int, refresh: int = 16,
                 nbins: int = 16, decay: float = 0.5):
        self.n_shards = int(n_shards)
        self.refresh = max(1, int(refresh))
        self.nbins = max(2, int(nbins))
        self.decay = float(decay)
        self._bbox: Optional[np.ndarray] = None   # x0, y0, x1, y1
        self._density: Optional[np.ndarray] = None
        self._assign: Optional[np.ndarray] = None  # bin -> shard
        self._loads: Optional[np.ndarray] = None
        self.observations = 0
        self.rebalances = 0

    # -- binning -------------------------------------------------------
    def _bins(self, pts: np.ndarray) -> np.ndarray:
        bb = self._bbox
        nb = self.nbins
        span = np.maximum(bb[2:] - bb[:2], 1e-9)
        ij = ((pts[:, :2] - bb[:2]) / span * nb).astype(np.int64)
        ij = np.clip(ij, 0, nb - 1)
        return ij[:, 0] * nb + ij[:, 1]

    # -- feedback ------------------------------------------------------
    def observe(self, pts64: np.ndarray,
                matched: np.ndarray) -> None:
        """Feed back one consumed chunk: which bins its matched rows
        (zone >= 0) landed in.  Every ``refresh``-th observation
        triggers a greedy re-pack."""
        pts = np.asarray(pts64)[:, :2]
        if self._bbox is None:
            lo, hi = pts.min(axis=0), pts.max(axis=0)
            pad = np.maximum((hi - lo) * 0.01, 1e-6)
            self._bbox = np.concatenate([lo - pad, hi + pad])
        cnt = np.bincount(self._bins(pts)[np.asarray(matched, bool)],
                          minlength=self.nbins * self.nbins
                          ).astype(np.float64)
        if self._density is None:
            self._density = cnt
        else:
            self._density = self.decay * self._density + cnt
        self.observations += 1
        if self.observations % self.refresh == 0:
            self.rebalance()

    def prime(self, bbox, density) -> None:
        """Seed the lattice from an external heat prior (the decayed
        per-partition access heat ``obs.heat`` folds into this bin
        layout) and pack immediately, so the very first chunk places
        skew-aware instead of identity.  A pure placement hint: only
        *where* rows compute changes, never what they compute —
        subsequent ``observe`` feedback decays the prior like any
        other observation."""
        d = np.asarray(density, np.float64).ravel()
        if d.size != self.nbins * self.nbins:
            raise ValueError(f"prior has {d.size} bins, lattice needs "
                             f"{self.nbins * self.nbins}")
        self._bbox = np.asarray(bbox, np.float64).copy()
        self._density = d.copy()
        self.rebalance()

    def rebalance(self) -> None:
        """Greedy bin-packing: bins in descending density order, each
        onto the currently least-loaded shard."""
        dens = self._density
        if dens is None or dens.sum() <= 0:
            return
        assign = np.zeros(len(dens), np.int64)
        loads = np.zeros(self.n_shards)
        for b in np.argsort(dens, kind="stable")[::-1]:
            s = int(np.argmin(loads))
            assign[b] = s
            loads[s] += dens[b]
        self._assign = assign
        self._loads = loads
        self.rebalances += 1

    # -- placement -----------------------------------------------------
    @property
    def armed(self) -> bool:
        return self._assign is not None

    def preferred(self, pts64: np.ndarray) -> Optional[np.ndarray]:
        """Preferred shard per row under the current bin→shard
        assignment, or None before the first rebalance (identity
        placement)."""
        if self._assign is None:
            return None
        return self._assign[self._bins(np.asarray(pts64)[:, :2])]

    def planned_skew(self) -> float:
        """max/mean of the per-shard packed density — the placement's
        own estimate of residual imbalance (1.0 = perfectly even)."""
        if self._loads is None:
            return 1.0
        mean = float(self._loads.mean())
        return float(self._loads.max()) / mean if mean else 1.0

    def contiguous_skew(self) -> float:
        """max/mean the observed density would load shards with under
        naive contiguous-block bin placement — the unrebalanced
        spatial-partition baseline the greedy pack is cut against."""
        if self._density is None:
            return 1.0
        blocks = np.array_split(self._density, self.n_shards)
        loads = np.asarray([b.sum() for b in blocks])
        mean = float(loads.mean())
        return float(loads.max()) / mean if mean else 1.0
