"""Sharded-raster halo exchange: stencils over a row-sharded raster.

Reference counterpart: the GDALBlock + Padding machinery
(core/raster/gdal/GDALBlock.scala) that the reference uses to run
stencil operators over tiled rasters — each block reads a halo of
neighbouring pixels so window operators are exact at block seams.

TPU-native redesign: the raster shards as row slabs over the mesh's
data axis and the halo is TWO ``jax.lax.ppermute`` shifts inside a
``shard_map`` — each device sends its top rows up and bottom rows down
the ring, concatenates [halo_above; slab; halo_below], and runs the
stencil on the widened slab.  The collectives ride ICI; no host
round-trips, no re-tiling.  Outer edges replicate the zero padding of
the single-device operator, so the sharded result equals
``rops.convolve`` to f32 reduction-order tolerance (pinned by
tests/test_raster_halo.py).
"""

from __future__ import annotations

import numpy as np

from ..core.raster.tile import RasterTile
from ..perf.jit_cache import kernel_cache
from ..perf.pipeline import stream

__all__ = ["sharded_convolve", "sharded_convolve_stream"]


def _convolve_fn(kernel: np.ndarray, mesh, axis: str, shape):
    """Validate + return the compiled sharded stencil for tiles of
    ``shape`` = (bands, H, W) (cached in the process kernel cache)."""
    import jax
    import jax.numpy as jnp
    try:
        from jax import shard_map
    except ImportError:      # moved in newer jax; older keeps it here
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    k = np.asarray(kernel, np.float64)
    kh, kw = k.shape
    if kh % 2 == 0 or kw % 2 == 0:
        raise ValueError("sharded_convolve requires odd kernel dims "
                         "(same-shape output)")
    halo = kh // 2
    D = mesh.shape[axis]
    bands, H, W = shape
    if H % D != 0:
        raise ValueError(f"the {axis} axis size {D} must divide the "
                         f"tile height {H} (retile or pad first)")
    if H // D < halo:
        raise ValueError(f"slab height {H // D} smaller than the "
                         f"kernel halo {halo}")
    kj = jnp.asarray(k.astype(np.float32))

    def local(slab):
        # slab [bands, H/D, W]; exchange halo rows around the ring
        idx = jax.lax.axis_index(axis)
        up = [(i, (i - 1) % D) for i in range(D)]      # send towards 0
        down = [(i, (i + 1) % D) for i in range(D)]
        # rows just above my slab = PREVIOUS device's bottom rows
        # (sent downward); rows below = NEXT device's top rows
        above_rx = jax.lax.ppermute(slab[:, -halo:], axis, down)
        below_rx = jax.lax.ppermute(slab[:, :halo], axis, up)
        # outer edges: zero rows, matching the SAME-pad zero fill of
        # the single-device convolve
        above = jnp.where(idx == 0, jnp.zeros_like(above_rx),
                          above_rx)
        below = jnp.where(idx == D - 1, jnp.zeros_like(below_rx),
                          below_rx)
        wide = jnp.concatenate([above, slab, below], axis=1)
        out = jax.lax.conv_general_dilated(
            wide[:, None], kj[None, None], window_strides=(1, 1),
            padding=((0, 0), (kw // 2, kw // 2)),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return out[:, 0]

    # cache the compiled stencil: a fresh closure per call would
    # retrace + recompile for every same-shaped tile in a pipeline
    key = (id(mesh), axis, D, kh, kw, bands, H, W, k.tobytes())
    return kernel_cache.get_or_build(
        "raster/halo_convolve", key,
        lambda: jax.jit(shard_map(
            local, mesh=mesh,
            in_specs=P(None, axis, None),
            out_specs=P(None, axis, None))))


def _count_halo_bytes(kernel, mesh, axis, shape, n_tiles=1):
    from ..obs import metrics
    if metrics.enabled:
        # two ppermute shifts move `halo` rows per device each way:
        # bands * halo * W f32 per device per shift, D devices
        halo = np.asarray(kernel).shape[0] // 2
        D = mesh.shape[axis]
        bands, _, W = shape
        moved = 2.0 * D * bands * halo * W * 4 * n_tiles
        metrics.count("collective/ppermute_bytes", moved)
        metrics.count("collective/ppermute_bytes/raster_halo", moved)
        metrics.count("collective/ppermute_calls", 2 * n_tiles)


def sharded_convolve(tile: RasterTile, kernel: np.ndarray, mesh,
                     axis: str = "data") -> RasterTile:
    """rops.convolve over a mesh: row-slab sharding + halo exchange.

    The mesh axis size must divide the tile's height (callers can
    retile/pad; keeping the constraint explicit avoids silently uneven
    slabs)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    fn = _convolve_fn(kernel, mesh, axis, tile.data.shape)
    data = np.where(tile.valid_mask(),
                    np.asarray(tile.data, np.float32), 0.0)
    from ..obs import tracer
    from ..obs.context import root_trace
    _count_halo_bytes(kernel, mesh, axis, tile.data.shape)
    arr = jax.device_put(
        jnp.asarray(data),
        NamedSharding(mesh, P(None, axis, None)))
    with root_trace("raster_halo"), tracer.span("halo/convolve"):
        out = np.asarray(fn(arr))
    return RasterTile(out, tile.gt, nodata=None, srid=tile.srid,
                      meta={"op": "convolve", "sharded": "halo"})


def sharded_convolve_stream(tiles, kernel: np.ndarray, mesh,
                            axis: str = "data") -> list:
    """Convolve MANY same-shaped tiles with upload/compute overlap.

    One compiled stencil serves the whole batch; the double-buffered
    executor uploads tile N+1 while the collectives run on tile N and
    fetches tile N-1 on a worker thread (perf.pipeline.stream).
    Returns the output :class:`RasterTile` list in input order."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    tiles = list(tiles)
    if not tiles:
        return []
    shape = tiles[0].data.shape
    for t in tiles[1:]:
        if t.data.shape != shape:
            raise ValueError(
                f"sharded_convolve_stream needs same-shaped tiles "
                f"(got {t.data.shape} after {shape}); group by shape "
                "first")
    fn = _convolve_fn(kernel, mesh, axis, shape)
    _count_halo_bytes(kernel, mesh, axis, shape, n_tiles=len(tiles))
    sharding = NamedSharding(mesh, P(None, axis, None))

    def put(tile):
        data = np.where(tile.valid_mask(),
                        np.asarray(tile.data, np.float32), 0.0)
        return jax.device_put(jnp.asarray(data), sharding)

    def consume(i, tile, host):
        return RasterTile(host, tile.gt, nodata=None, srid=tile.srid,
                          meta={"op": "convolve", "sharded": "halo"})

    from ..obs import tracer
    from ..obs.context import root_trace
    with root_trace("raster_halo"), tracer.span("halo/convolve_stream"):
        return stream(tiles, compute=fn, put=put, consume=consume)
