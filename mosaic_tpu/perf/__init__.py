"""Performance layer: shape bucketing, kernel caching, streaming.

The three ingredients of the modern-hardware recipe (adaptive
geospatial joins, arxiv 1802.09488; pipelined device joins, 3DPipe)
applied to the chipping/join hot path:

* ``perf.bucketing`` — one shared power-of-2 padding policy for every
  ragged batch (polygon edge counts, ring sizes, pair blocks), so each
  variable-length workload compiles **once per bucket** instead of
  re-tracing per shape.
* ``perf.jit_cache`` — the process-level compiled-kernel LRU unifying
  the ad-hoc ``dict`` caches that had grown in ``core/tessellate.py``,
  ``models/knn.py`` and ``parallel/raster_halo.py``, plus the wiring
  for JAX's **persistent** compilation cache (conf key
  ``mosaic.jit.cache.dir`` / env ``MOSAIC_TPU_JIT_CACHE_DIR``) so the
  first-call compile cost vanishes on warm starts.  Hit/miss/eviction
  counters land in ``obs.metrics`` under ``perf/jit_cache/*``.
* ``perf.pipeline`` — a double-buffered chunk executor: host→device
  transfer of chunk N+1 overlaps device compute on chunk N, and the
  host-side consumption (f64 recheck, re-rank) of chunk N−1 runs on a
  worker thread.  Used by the streamed PIP join, the KNN brute-force
  top-k and the multi-tile raster halo convolve.
* ``perf.fusion`` — whole-query fusion for the SQL engine: adjacent
  size-class-compatible operators (filter → project/aggregate) compile
  into ONE jitted XLA program keyed into ``kernel_cache`` as
  ``fused:<opset>:<sig>``, with zero intermediate host transfers and
  bit-for-bit parity with the unfused path.  Planner-gated per query
  (``decide_fusion``, conf ``mosaic.fusion.enabled``).  Imported
  lazily by ``sql.planner`` — not re-exported here.
"""

from __future__ import annotations

from .bucketing import (iter_size_buckets, pad_rows, pad_to_block,
                        pow2_bucket)
from .jit_cache import (JitCache, configure_persistent_cache,
                        kernel_cache, persistent_cache_dir)
from .pipeline import chunk_rows, donate_jit, stream

__all__ = [
    "pow2_bucket", "iter_size_buckets", "pad_rows", "pad_to_block",
    "JitCache", "kernel_cache", "configure_persistent_cache",
    "persistent_cache_dir",
    "stream", "chunk_rows", "donate_jit",
]
