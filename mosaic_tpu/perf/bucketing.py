"""Shared power-of-2 shape-bucketing policy for ragged batches.

Every XLA compile is keyed on input shapes, so a ragged workload
(polygon edge counts, ring vertex counts, sparse pair blocks) fed to
``jax.jit`` at its natural sizes re-traces per batch — the classic
recompile storm.  The fix used across this package is to PAD each
ragged dimension up to a power of two so the whole workload collapses
onto O(log(max size)) compiled shapes.  Before this module the policy
lived as three hand-synced inline loops in ``core/tessellate.py``
(edge-count buckets, ring-size buckets, parity row blocks); they now
share these helpers, and new kernels (``perf.pipeline`` users, the
pair-check kernel) get the same policy for free.

Pure numpy — safe to import before jax, costs nothing when the jitted
paths are off.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

__all__ = ["pow2_bucket", "iter_size_buckets", "pad_rows",
           "pad_to_block"]


def pow2_bucket(n: int, floor: int = 4,
                cap: Optional[int] = None) -> int:
    """Smallest power of two >= max(n, floor), clamped to ``cap``.

    The floor stops tiny batches from fragmenting into 1/2/4-wide
    compiles; the cap bounds the padding waste for huge outliers
    (callers then block-loop over the capped width)."""
    n = max(int(n), 1)
    b = max(int(floor), 1 << int(np.ceil(np.log2(n))))
    if cap is not None:
        b = min(b, int(cap))
    return b


def iter_size_buckets(sizes, floor: int = 4
                      ) -> Iterator[Tuple[int, np.ndarray]]:
    """Group items into pow2 size buckets: yields ``(width, indices)``.

    ``sizes[i]`` is item i's ragged dimension; each yielded bucket
    satisfies ``sizes[indices] <= width`` with ``width`` the pow2
    bucket of its smallest member — identical semantics to the inline
    ``while start < T`` loops this replaces in ``tessellate``.  Items
    come out sorted by size (stable), so bucket membership is
    deterministic for a given input order."""
    sizes = np.asarray(sizes)
    order = np.argsort(sizes, kind="stable")
    s = 0
    while s < len(order):
        width = pow2_bucket(sizes[order[s]], floor)
        e = s
        while e < len(order) and sizes[order[e]] <= width:
            e += 1
        yield width, order[s:e]
        s = e


def pad_rows(arr: np.ndarray, rows: int, fill=0.0) -> np.ndarray:
    """Pad axis 0 of ``arr`` up to ``rows`` with ``fill`` (no copy when
    already that size)."""
    n = arr.shape[0]
    if n == rows:
        return arr
    if n > rows:
        raise ValueError(f"cannot pad {n} rows down to {rows}")
    out = np.full((rows, *arr.shape[1:]), fill, dtype=arr.dtype)
    out[:n] = arr
    return out


def pad_to_block(block: int, *arrays, fills=None):
    """Pad several same-length arrays to ``block`` rows at once.

    ``fills`` is an optional per-array fill sequence (default 0).
    Returns the padded tuple plus the original row count."""
    n = arrays[0].shape[0]
    if fills is None:
        fills = [0.0] * len(arrays)
    return tuple(pad_rows(a, block, f)
                 for a, f in zip(arrays, fills)) + (n,)
