"""Whole-query fusion: one XLA program per operator group.

The SQL engine dispatches one kernel per operator with a host
round-trip at every boundary — filter materializes a mask, compacts on
host, projection/aggregation re-enter the device (or worse, a python
loop) on the compacted copy.  The planner sees the whole plan before
execution, so adjacent size-class-compatible operators can instead be
stitched into ONE jitted XLA program: device buffers flow stage to
stage, XLA's loop fusion deletes the intermediates outright, and only
the group's final output crosses back to host (the 3DPipe pipelined
execution argument, arxiv 2604.19982, grafted onto the planner/jit-
cache stack with SOLAR's adaptive-selection stance, arxiv 2504.01292:
learn per size-class when fusion wins, never guess).

Fusion is a **pure strategy transform** — results are bit-for-bit
identical to the unfused path — so eligibility is decided by typing
rules that guarantee numpy/XLA parity, not by hope:

* elementwise f32/f64 arithmetic and every comparison are exact IEEE
  ops on both sides (XLA:CPU does not contract by default), and
  pointwise ops commute with row compaction, so filter+project chains
  fuse freely over bool/int/float columns;
* ``min``/``max``/``count``/``first`` are order-independent exact;
  float ``sum``/``avg`` are NOT (numpy's pairwise vs XLA's reduction
  order), so fused sums are restricted to integer columns and guarded
  at runtime by ``n * max|v| < 2**53`` — exact in any order, equal to
  the unfused float64 accumulation bit for bit;
* mixed-dtype operands, ``%``, object/string/geometry columns,
  generators, GROUP BY/HAVING, Star expansion, and registry Calls all
  break the group cleanly — those rows run the unfused path unchanged.

Compiles are keyed into :data:`~.jit_cache.kernel_cache` under
``fused:<opset>:<sig8>`` with one entry per (group signature, pow2
size bucket) — the row count rides in as a traced scalar, so warm
runs perform zero XLA compiles.  Every launch lands in the
:class:`~..obs.profiler.KernelLedger` under the same name (dashboard
ledger rows show fused kernels distinctly) and feeds the planner's
``fusion/<opset>`` cost coefficient, which is what
:meth:`~..sql.planner.Planner.decide_fusion` compares against the sum
of the members' unfused coefficients.  Cancellation keeps its
one-chunk guarantee: a ``checkpoint("fusion")`` probe runs at the
group boundary before any device work (chaos site ``fusion.group``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import metrics, recorder
from ..sql.parser import (Binary, Call, Column, Literal, Query, Star,
                          Unary)
from .bucketing import pad_rows, pow2_bucket
from .jit_cache import kernel_cache

__all__ = ["FusionBailout", "FusionGroup", "FusionPlan", "FusedResult",
           "plan_fusion", "execute_group", "MIN_GROUP_OPS",
           "SUM_EXACT_BOUND"]

#: a group below this many member ops is not worth a compile — except
#: a lone aggregate, whose unfused path is a per-row python loop
MIN_GROUP_OPS = 2

#: fused integer sums require ``n * max|v|`` under this bound so the
#: int64 device sum and the unfused float64 accumulation are BOTH
#: exact (every partial sum representable) and therefore identical
SUM_EXACT_BOUND = float(2 ** 53)

#: numpy dtype kinds a fused column may carry (no unsigned — unary
#: minus and literal promotion differ between numpy and XLA there)
_ELIGIBLE_KINDS = "bif"

_CMP_OPS = ("=", "!=", "<", "<=", ">", ">=")
_ARITH_OPS = ("+", "-", "*")


class FusionBailout(Exception):
    """A planned-fused group cannot run fused after all (runtime shape
    of the data differs from the catalog pre-pass — e.g. a LEFT JOIN
    emitted NULLs, or an integer sum failed the exactness bound).  The
    engine falls back to the unfused path for the same stages."""


class _Ineligible(Exception):
    """Static eligibility walk: this expression/op breaks the group."""


# ------------------------------------------------------ group objects

@dataclasses.dataclass
class _AggSpec:
    """One fused aggregate output column."""

    kind: str              # countstar | count | sum | avg | min | max | first
    name: str              # output column name
    expr: object = None    # argument AST (None for count(*))


@dataclasses.dataclass
class FusionGroup:
    """One contiguous run of fusible operators, compiled as a unit."""

    gid: str                       # "g1" — the EXPLAIN `fused` column
    ops: List[str]                 # member operator names, in order
    opset: str                     # "filter+aggregate" — cost-key part
    sig: str                       # sha1[:8] of exprs + column dtypes
    name: str                      # kernel-cache name: fused:<opset>:<sig>
    cols: List[Tuple[Optional[str], str, str]]  # (qualifier, name, dtype.str)
    raw_index: Dict[Tuple[Optional[str], str], int]  # AST (qual, name) -> col
    where: Optional[object]        # filter AST (None when not a member)
    terminal: str                  # "project" | "aggregate"
    item_names: List[str]          # project output names (project groups)
    item_exprs: List[object]       # project output ASTs
    agg_specs: List[_AggSpec]      # aggregate outputs (aggregate groups)
    sum_cols: List[int]            # col indices needing the 2**53 bound
    decision: object = None        # planner Decision that gated this
    est_n: int = 0                 # input-row estimate the gate used


class FusionPlan:
    """The query's fused groups (at most one in the current engine
    shape — the fusible region is filter → terminal — but the map API
    keeps EXPLAIN and the engine agnostic of that)."""

    def __init__(self, groups: Sequence[FusionGroup]):
        self.groups = list(groups)

    def gid_for(self, op: str) -> str:
        g = self.group_with(op)
        return g.gid if g is not None else "-"

    def group_with(self, op: str) -> Optional[FusionGroup]:
        for g in self.groups:
            if op in g.ops:
                return g
        return None


@dataclasses.dataclass
class FusedResult:
    """What one group execution produced for the engine."""

    rows_filter: int               # rows passing the fused WHERE
    mask: Optional[np.ndarray]     # host bool mask (project groups only)
    out: object                    # terminal Table (engine unpacks it)
    wall_s: float


# ------------------------------------------------- static eligibility

class _TypeWalk:
    """Type-inference walk over the expression AST, enforcing the
    numpy/XLA parity rules and collecting referenced columns.

    Types are numpy dtypes for array-valued subexpressions, or the
    weak-literal markers ``"wi"``/``"wf"`` for python scalars (which
    both numpy and XLA promote without widening the array operand).
    """

    def __init__(self, resolver):
        self.resolver = resolver           # (name, qual) -> (qual, name, dtype)
        self.cols: List[Tuple[Optional[str], str, str]] = []
        self._index: Dict[Tuple[Optional[str], str], int] = {}
        #: every raw AST spelling seen -> column index, so the traced
        #: program can look Columns up without a resolver (an
        #: unqualified and a qualified reference share one input)
        self.raw: Dict[Tuple[Optional[str], str], int] = {}

    def col_index(self, qual, name) -> int:
        rq, rn, dt = self.resolver(name, qual)
        key = (rq, rn)
        if key not in self._index:
            self._index[key] = len(self.cols)
            self.cols.append((rq, rn, dt.str))
        self.raw[(qual, name)] = self._index[key]
        return self._index[key]

    # -- promotion rules (see module docstring) -----------------------

    @staticmethod
    def _combine(a, b, op: str):
        """Result type of a binary ``op`` — raises when numpy and XLA
        would promote differently (mixed concrete dtypes, small ints
        against float literals, ``%`` always)."""
        if op == "%":
            raise _Ineligible("% differs between numpy and XLA for "
                              "negative operands")
        weak_a, weak_b = isinstance(a, str), isinstance(b, str)
        if weak_a and weak_b:
            t = "wf" if "wf" in (a, b) else "wi"
        elif weak_a or weak_b:
            w, c = (a, b) if weak_a else (b, a)
            if c.kind == "b":
                raise _Ineligible("arithmetic on bool columns")
            if w == "wf" and c.kind == "i" and c.itemsize < 8:
                # numpy promotes int32 + float literal to f64; XLA
                # keeps the array width and lands on f32
                raise _Ineligible(
                    f"float literal against {c} widens differently")
            t = np.dtype(np.float64) if (w == "wf" and c.kind == "i") \
                else c
        else:
            if a != b:
                raise _Ineligible(f"mixed operand dtypes {a} vs {b}")
            if a.kind == "b":
                raise _Ineligible("arithmetic on bool columns")
            t = a
        if op == "/":
            if t in ("wi", "wf"):
                return "wf"
            if t.kind == "i":
                return np.dtype(np.float64)   # both sides: true divide
        return t

    def check_literal(self, e: Literal):
        v = e.value
        if isinstance(v, bool) or isinstance(v, (int, np.integer)):
            if not (-(2 ** 63) <= int(v) < 2 ** 63):
                raise _Ineligible("integer literal beyond int64")
            return "wi"
        if isinstance(v, (float, np.floating)):
            return "wf"
        raise _Ineligible(f"literal {v!r} is not numeric")

    def visit(self, e):
        """Type of ``e``; raises :class:`_Ineligible` on any construct
        whose fused evaluation could differ from the unfused one."""
        if isinstance(e, Literal):
            return self.check_literal(e)
        if isinstance(e, Column):
            _, _, dt = self.cols[self.col_index(e.table, e.name)]
            return np.dtype(dt)
        if isinstance(e, Unary):
            t = self.visit(e.operand)
            if e.op == "-":
                if isinstance(t, np.dtype) and t.kind == "b":
                    raise _Ineligible("unary minus on bool")
                return t
            if e.op == "not":
                return np.dtype(bool)
            if e.op in ("isnull", "notnull"):
                if not isinstance(t, np.dtype):
                    raise _Ineligible(f"{e.op} on a literal")
                return np.dtype(bool)
            raise _Ineligible(f"unary {e.op}")
        if isinstance(e, Binary):
            if e.op in ("and", "or"):
                self.visit(e.left)
                self.visit(e.right)
                return np.dtype(bool)
            a, b = self.visit(e.left), self.visit(e.right)
            self._literal_fits(e.left, b)
            self._literal_fits(e.right, a)
            t = self._combine(a, b, e.op)
            return np.dtype(bool) if e.op in _CMP_OPS else t
        raise _Ineligible(f"{type(e).__name__} breaks fusion")

    @staticmethod
    def _literal_fits(e, other) -> None:
        """An int literal beyond its partner column's dtype range
        promotes differently (numpy widens, XLA wraps/raises)."""
        if isinstance(e, Literal) and isinstance(other, np.dtype) and \
                other.kind == "i" and \
                isinstance(e.value, (int, np.integer)) and \
                not isinstance(e.value, bool):
            info = np.iinfo(other)
            if not (info.min <= int(e.value) <= info.max):
                raise _Ineligible(
                    f"literal {e.value} outside {other} range")


def _static_resolver(tables: Dict[str, object]):
    """Column resolution against the catalog tables, mirroring
    ``_Env.resolve`` semantics; only ndarray columns of eligible
    dtype resolve — everything else breaks the group."""

    def resolve(name: str, qual: Optional[str]):
        if qual is not None:
            t = tables.get(qual)
            if t is None or name not in t.columns:
                raise _Ineligible(f"unresolvable column {qual}.{name}")
            hits = [(qual, t)]
        else:
            hits = [(q, t) for q, t in tables.items()
                    if name in t.columns]
            if len(hits) != 1:
                raise _Ineligible(f"column {name!r} resolves to "
                                  f"{len(hits)} tables")
        q, t = hits[0]
        c = t.columns[name]
        if not isinstance(c, np.ndarray) or \
                c.dtype.kind not in _ELIGIBLE_KINDS or \
                c.dtype.itemsize > 8:
            raise _Ineligible(
                f"column {name!r} dtype "
                f"{getattr(c, 'dtype', type(c).__name__)} is host-only")
        return q, name, c.dtype

    return resolve


def _serialize(e, walk: _TypeWalk) -> str:
    """Deterministic AST spelling for the group signature (columns by
    collected index, so the signature is name-independent)."""
    if isinstance(e, Literal):
        v = e.value
        return f"L{type(v).__name__}:{v!r}"
    if isinstance(e, Column):
        return f"C{walk.col_index(e.table, e.name)}"
    if isinstance(e, Unary):
        return f"U{e.op}({_serialize(e.operand, walk)})"
    if isinstance(e, Binary):
        return (f"B{e.op}({_serialize(e.left, walk)},"
                f"{_serialize(e.right, walk)})")
    if isinstance(e, Call):
        args = ",".join("*" if isinstance(a, Star)
                        else _serialize(a, walk) for a in e.args)
        return f"A{e.name}({args})"
    raise _Ineligible(f"cannot serialize {type(e).__name__}")


def _check_agg_item(it, pos: int, walk: _TypeWalk,
                    default_name) -> _AggSpec:
    e = it.expr
    from ..sql.engine import AGGREGATES
    if not (isinstance(e, Call) and e.name in AGGREGATES):
        raise _Ineligible(f"non-aggregate item in implicit group")
    name = it.alias or default_name(e, pos)
    if e.name == "count":
        if len(e.args) == 0 or isinstance(e.args[0], Star):
            return _AggSpec("countstar", name)
        t = walk.visit(e.args[0])
        if not isinstance(t, np.dtype):
            raise _Ineligible("count of a literal")
        return _AggSpec("count", name, e.args[0])
    if len(e.args) != 1:
        raise _Ineligible(f"{e.name} arity")
    arg = e.args[0]
    t = walk.visit(arg)
    if not isinstance(t, np.dtype) or t.kind == "b":
        raise _Ineligible(f"{e.name} needs a numeric column expression")
    if e.name in ("sum", "avg", "mean"):
        # order-independent exactness needs integer values with a
        # runtime magnitude bound — and the bound needs the raw column,
        # so the argument must be a bare column reference
        if not isinstance(arg, Column) or t.kind != "i":
            raise _Ineligible(
                f"{e.name} fuses only over integer columns "
                "(float sums are reduction-order dependent)")
        kind = "avg" if e.name in ("avg", "mean") else "sum"
        return _AggSpec(kind, name, arg)
    if e.name in ("min", "max", "first"):
        return _AggSpec(e.name, name, arg)
    raise _Ineligible(f"aggregate {e.name}")


def plan_fusion(q: Query, session, plan) -> Optional[FusionPlan]:
    """The fusion pass: walk the planner's pre-pass plan, form the
    (single, in this engine shape) contiguous eligible group, and gate
    it through :meth:`~..sql.planner.Planner.decide_fusion`.  Returns
    None when fusion is off, nothing is eligible, or the planner says
    the unfused path is cheaper at this size class."""
    from ..config import default_config
    from ..sql.engine import AGGREGATES, GENERATORS
    from ..sql.planner import planner
    cfg = default_config()
    if not getattr(cfg, "fusion_enabled", True):
        return None
    if any(isinstance(it.expr, Call) and it.expr.name in GENERATORS
           for it in q.items):
        return None          # exploded columns are host-shaped (wkb)
    try:
        tables = {(q.table.alias or q.table.name).lower():
                  session.table(q.table.name)}
        if q.join is not None:
            tables[(q.join.alias or q.join.name).lower()] = \
                session.table(q.join.name)
    except Exception:
        return None          # engine will raise its own error
    has_agg = any(isinstance(it.expr, Call) and
                  it.expr.name in AGGREGATES for it in q.items)

    def eligible(member: str) -> bool:
        """Probe one candidate member with a throwaway collector."""
        w = _TypeWalk(_static_resolver(tables))
        try:
            if member == "filter":
                w.visit(q.where)
            elif member == "aggregate":
                if q.group_by is not None or q.having is not None:
                    raise _Ineligible("grouped aggregation is host-side")
                for pos, it in enumerate(q.items):
                    _check_agg_item(it, pos, w, session._default_name)
            else:                                  # project
                for it in q.items:
                    if isinstance(it.expr, Star):
                        raise _Ineligible("Star expansion")
                    t = w.visit(it.expr)
                    if not isinstance(t, np.dtype):
                        raise _Ineligible("constant projection")
            return True
        except _Ineligible:
            return False

    terminal = "aggregate" if (q.group_by is not None or has_agg) \
        else "project"
    ops: List[str] = []
    if q.where is not None and eligible("filter"):
        ops.append("filter")
    if eligible(terminal):
        ops.append(terminal)
    elif ops:
        ops = []             # [filter] alone is not worth a compile
    if "aggregate" not in ops and len(ops) < MIN_GROUP_OPS:
        return None
    max_ops = max(int(getattr(cfg, "fusion_max_ops", 8)), 1)
    while len(ops) > max_ops:
        ops.pop(0)           # keep the terminal; earlier ops unfuse
    if "aggregate" not in ops and len(ops) < MIN_GROUP_OPS:
        return None

    # final pass with ONE shared collector, in member order, so column
    # indices (and the signature) are deterministic
    walk = _TypeWalk(_static_resolver(tables))
    parts: List[str] = []
    where = None
    item_names: List[str] = []
    item_exprs: List[object] = []
    agg_specs: List[_AggSpec] = []
    try:
        if "filter" in ops:
            where = q.where
            walk.visit(where)
            parts.append(f"F:{_serialize(where, walk)}")
        if ops[-1] == "aggregate":
            for pos, it in enumerate(q.items):
                agg_specs.append(_check_agg_item(
                    it, pos, walk, session._default_name))
            parts.append("A:" + ";".join(
                f"{s.kind}:{_serialize(s.expr, walk) if s.expr is not None else '*'}"
                for s in agg_specs))
        else:
            for pos, it in enumerate(q.items):
                walk.visit(it.expr)
                item_names.append(it.alias or
                                  session._default_name(it.expr, pos))
                item_exprs.append(it.expr)
            parts.append("P:" + ";".join(
                f"{n}={_serialize(e, walk)}"
                for n, e in zip(item_names, item_exprs)))
    except _Ineligible:       # raced catalog change; stay unfused
        return None
    sum_cols = sorted({walk.col_index(s.expr.table, s.expr.name)
                       for s in agg_specs if s.kind in ("sum", "avg")})
    opset = "+".join(ops)
    src = (opset + "|" + ";".join(parts) + "|" +
           ",".join(dt for _, _, dt in walk.cols))
    sig = hashlib.sha1(src.encode()).hexdigest()[:8]
    n_est = len(next(iter(tables.values())))
    step = plan.steps.get(ops[0]) if plan is not None else None
    if step is not None and step.key_n > 0:
        n_est = step.key_n
    d = planner.decide_fusion(opset, ops, n_est)
    if d.strategy != "fused":
        return None
    group = FusionGroup(
        gid="g1", ops=ops, opset=opset, sig=sig,
        name=f"fused:{opset}:{sig}", cols=walk.cols,
        raw_index=dict(walk.raw), where=where,
        terminal=ops[-1], item_names=item_names, item_exprs=item_exprs,
        agg_specs=agg_specs, sum_cols=sum_cols, decision=d,
        est_n=n_est)
    return FusionPlan([group])


# ----------------------------------------------------- jnp evaluation

def _jnp_eval(e, cenv, jnp, bucket: int):
    """Trace-time mirror of ``SQLSession._eval`` over jnp arrays.
    Literals stay python scalars (weak-typed on both sides), so the
    traced program promotes exactly like the numpy evaluator."""
    if isinstance(e, Literal):
        return e.value
    if isinstance(e, Column):
        return cenv[e.table, e.name]
    if isinstance(e, Unary):
        v = _jnp_eval(e.operand, cenv, jnp, bucket)
        if e.op == "-":
            return -v
        if e.op == "not":
            return ~_jnp_mask(v, jnp, bucket)
        isna = jnp.isnan(v) if v.dtype.kind == "f" \
            else jnp.zeros(bucket, bool)
        return isna if e.op == "isnull" else ~isna
    if isinstance(e, Binary):
        if e.op in ("and", "or"):
            a = _jnp_mask(_jnp_eval(e.left, cenv, jnp, bucket), jnp,
                          bucket)
            b = _jnp_mask(_jnp_eval(e.right, cenv, jnp, bucket), jnp,
                          bucket)
            return (a & b) if e.op == "and" else (a | b)
        a = _jnp_eval(e.left, cenv, jnp, bucket)
        b = _jnp_eval(e.right, cenv, jnp, bucket)
        import operator as op_
        fn = {"+": op_.add, "-": op_.sub, "*": op_.mul,
              "/": op_.truediv,
              "=": op_.eq, "!=": op_.ne, "<": op_.lt,
              "<=": op_.le, ">": op_.gt, ">=": op_.ge}[e.op]
        return fn(a, b)
    raise FusionBailout(f"cannot trace {type(e).__name__}")


def _jnp_mask(v, jnp, bucket: int):
    """``_as_mask`` under trace: scalars broadcast, numerics cast to
    bool (NaN -> True, matching numpy's astype(bool))."""
    if isinstance(v, (bool, int, float)):
        return jnp.full(bucket, bool(v))
    return v if v.dtype == bool else (v != 0) | (
        jnp.isnan(v) if v.dtype.kind == "f" else False)


def _agg_device(spec: _AggSpec, cenv, mask, jnp, bucket: int):
    """Device-side outputs for one aggregate spec.  Scalar results
    only — the single host fetch at group end is the group's ONLY
    device->host transfer."""
    i64 = jnp.int64
    if spec.kind == "countstar":
        return (jnp.sum(mask, dtype=i64),)
    v = _jnp_eval(spec.expr, cenv, jnp, bucket)
    ok = mask & ~jnp.isnan(v) if v.dtype.kind == "f" else mask
    if spec.kind == "count":
        return (jnp.sum(ok, dtype=i64),)
    cnt = jnp.sum(ok, dtype=i64)
    if spec.kind in ("sum", "avg"):
        return (jnp.sum(jnp.where(ok, v, 0).astype(i64)), cnt)
    if spec.kind in ("min", "max"):
        if v.dtype.kind == "f":
            fill = np.asarray(np.inf if spec.kind == "min" else -np.inf,
                              v.dtype)
        else:
            info = np.iinfo(np.dtype(str(v.dtype)))
            fill = np.asarray(info.max if spec.kind == "min"
                              else info.min, v.dtype)
        red = jnp.min if spec.kind == "min" else jnp.max
        return (red(jnp.where(ok, v, fill)), cnt)
    if spec.kind == "first":
        return (v[jnp.argmax(ok)], cnt)
    raise FusionBailout(f"aggregate {spec.kind}")


def _build_program(group: FusionGroup, bucket: int):
    """The jitted whole-group program for one size bucket.  Inputs:
    the referenced columns padded to ``bucket`` rows plus the live row
    count as a TRACED scalar — so every query landing in this bucket
    reuses one compile (warm-zero)."""
    import jax
    import jax.numpy as jnp

    def prog(*args):
        cols, n = args[:-1], args[-1]
        cenv = {raw: cols[i] for raw, i in group.raw_index.items()}
        mask = jnp.arange(bucket) < n
        if group.where is not None:
            mask = mask & _jnp_mask(
                _jnp_eval(group.where, cenv, jnp, bucket), jnp, bucket)
        outs = [jnp.sum(mask, dtype=jnp.int64)]
        if group.terminal == "project":
            outs.append(mask)
            for e in group.item_exprs:
                outs.append(_jnp_eval(e, cenv, jnp, bucket))
        else:
            for spec in group.agg_specs:
                outs.extend(_agg_device(spec, cenv, mask, jnp, bucket))
        return tuple(outs)

    return jax.jit(prog)


# --------------------------------------------------------- execution

def execute_group(group: FusionGroup, q: Query, env,
                  session) -> FusedResult:
    """Run one fused group over the engine's live environment.

    Re-checks runtime eligibility against the ACTUAL columns (a LEFT
    JOIN may have null-converted what the catalog pre-pass saw, an
    integer sum may exceed the exactness bound) and raises
    :class:`FusionBailout` — never a wrong answer — when the data
    disagrees with the plan."""
    import jax
    from ..obs.devicemon import devicemon
    from ..obs.inflight import (charge_d2h_bytes, charge_h2d_bytes,
                                checkpoint, note_fusion_group)
    from ..obs.memwatch import device_keys_of, memwatch
    from ..obs.profiler import ledger
    from ..resilience import faults
    from ..sql.engine import Table
    from ..sql.planner import planner

    if not jax.config.jax_enable_x64:
        raise FusionBailout("jax_enable_x64 is off (import mosaic_tpu "
                            "enables it); 64-bit columns would downcast")
    n = session._env_len(env)
    if n == 0:
        raise FusionBailout("empty input")
    cols: List[np.ndarray] = []
    for qual, name, dt in group.cols:
        try:
            c = env.resolve(name, qual)
        except Exception as e:
            raise FusionBailout(f"column {name!r}: {e}") from e
        if not isinstance(c, np.ndarray) or c.dtype.str != dt:
            raise FusionBailout(
                f"column {name!r} is {getattr(c, 'dtype', type(c).__name__)}"
                f" at runtime, planned {dt}")
        cols.append(c)
    for ci in group.sum_cols:
        mx = float(np.abs(cols[ci]).max()) if len(cols[ci]) else 0.0
        if mx * n >= SUM_EXACT_BOUND:
            raise FusionBailout(
                f"integer sum over column {group.cols[ci][1]!r} may "
                f"exceed 2**53 (n={n}, max|v|={mx:.0f}) — exactness "
                "not guaranteed in either order")

    # group boundary: the cooperative cancellation probe + chaos site
    # (a cancel landing mid-stall raises at the NEXT stage boundary)
    checkpoint("fusion")
    faults.stall("fusion.group")

    bucket = pow2_bucket(n)
    # a miss here means the first call below JIT-compiles (jax.jit is
    # lazy) — that wall belongs to the compile, not the kernel, so it
    # must not feed the planner's fusion cost coefficient
    cold = kernel_cache.stats()["misses"]
    fn = kernel_cache.get_or_build(group.name, (bucket,),
                                   lambda: _build_program(group, bucket))
    cold = kernel_cache.stats()["misses"] > cold
    padded = [pad_rows(np.ascontiguousarray(c), bucket) for c in cols]
    h2d = sum(int(p.nbytes) for p in padded)
    if metrics.enabled:
        metrics.count("fusion/h2d_bytes", h2d)
    charge_h2d_bytes(h2d)
    t0 = time.perf_counter()
    dev_out = fn(*padded, np.int64(n))
    # the fused program's device outputs live from launch to the one
    # group fetch below — register the span so the memory ledger can
    # attribute the group's device footprint to this query's trace
    mem_tok = memwatch.register(
        f"fusion/{group.name}",
        sum(int(getattr(o, "nbytes", 0)) for o in dev_out),
        devices=device_keys_of(dev_out)) if memwatch.enabled else None
    try:
        host = list(jax.device_get(dev_out))  # the ONE group fetch
    finally:
        # a fetch unwinding (cancel mid-device_get, chaos fault) must
        # still drain the span — a stranded token reads as a leak
        memwatch.release(mem_tok)
    wall = time.perf_counter() - t0
    d2h = sum(int(h.nbytes) for h in host)
    if metrics.enabled:
        metrics.count("fusion/groups")
        metrics.count("fusion/fetches")
        metrics.count("fusion/d2h_bytes", d2h)
    # the fused fetch bypasses pipeline.stream, so charge the owning
    # query here — same trace join the device-seconds charge uses
    charge_d2h_bytes(d2h)
    ledger.observe(group.name, (bucket,), wall, rows=n)
    devicemon.attribute(group.name, wall)
    if not cold:
        # warm launches teach the planner the steady-state fused cost;
        # a cold wall is dominated by the one-off XLA compile and
        # would flip decide_fusion to "unfused" forever
        planner.observe_op(f"fusion/{group.opset}", n, wall)
    note_fusion_group(group.name)
    recorder.record("fusion_group", name=group.name, rows=n,
                    bucket=bucket, wall_ms=round(wall * 1e3, 3))

    rows_filter = int(host[0])
    if group.terminal == "project":
        mask = host[1]
        out = Table({name: col[mask] for name, col in
                     zip(group.item_names, host[2:])})
        return FusedResult(rows_filter, mask, out, wall)
    out_cols: Dict[str, object] = {}
    i = 1
    for spec in group.agg_specs:
        if spec.kind in ("countstar", "count"):
            out_cols[spec.name] = np.asarray([int(host[i])], np.int64)
            i += 1
            continue
        v, cnt = host[i], int(host[i + 1])
        i += 2
        if spec.kind == "avg":
            out_cols[spec.name] = np.asarray(
                [float(v) / cnt if cnt else np.nan])
        else:
            out_cols[spec.name] = np.asarray(
                [float(v) if cnt else np.nan])
    return FusedResult(rows_filter, None, Table(out_cols), wall)
