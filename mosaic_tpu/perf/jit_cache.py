"""Process-level compiled-kernel cache + persistent compilation cache.

Two layers, addressing two different compile costs:

* :class:`JitCache` — an LRU of ``jax.jit``-wrapped callables keyed on
  ``(kernel name, shape/dtype/static-arg key)``.  It unifies the
  ad-hoc module/instance ``dict`` caches that had accumulated in
  ``core/tessellate.py`` (``_PARITY_JIT``/``_CLIP_JIT``),
  ``models/knn.py`` (``SpatialKNN._step_cache``) and
  ``parallel/raster_halo.py`` (``_JIT_CACHE``) — one bounded cache,
  one eviction policy, one set of hit/miss/eviction counters in
  ``obs.metrics`` (``perf/jit_cache/hit|miss|evict`` plus per-kernel
  ``.../miss/<name>``).  The counters also accumulate locally so tests
  can assert on them without enabling the registry.
* :func:`configure_persistent_cache` — wires JAX's on-disk compilation
  cache (``jax_compilation_cache_dir``) with thresholds dropped to
  zero so every entry persists.  A warm-started process then loads
  compiled executables from disk instead of re-running XLA: the
  first-call warmup disappears.  NOTE: ``jax.monitoring`` still fires
  ``backend_compile`` duration events on persistent-cache HITS (the
  event wraps the lookup), so "did anything actually compile" must be
  read from the ``jax/cache/cache_misses`` counter
  (``obs.jaxmon._on_event``), not from ``jax/recompiles`` — the bench
  record and the CI warm-start assertion both do.

The configuration must be identical and applied BEFORE the first
compile in every process that shares a cache directory: the cache key
hashes the compile options, so config drift between runs silently
turns hits into misses.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Callable, Dict, Optional

from ..obs.metrics import metrics

__all__ = ["JitCache", "kernel_cache", "configure_persistent_cache",
           "persistent_cache_dir"]

#: env var mirroring the ``mosaic.jit.cache.dir`` conf key
JIT_CACHE_DIR_ENV = "MOSAIC_TPU_JIT_CACHE_DIR"


class JitCache:
    """Bounded LRU of compiled functions, thread-safe.

    Keys are ``(name, key)`` where ``name`` identifies the kernel
    builder (a stable string, NOT a function id — ids recycle) and
    ``key`` captures everything the compiled artifact depends on:
    padded shapes, dtypes, static arguments, and — for sharded
    kernels — ``id(mesh)`` (a jitted fn bakes its mesh's shardings).
    """

    def __init__(self, capacity: int = 256, scope: str = "kernel"):
        self.capacity = int(capacity)
        self.scope = scope
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, Callable]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get_or_build(self, name: str, key,
                     build: Callable[[], Callable]) -> Callable:
        """Return the cached callable for ``(name, key)``, building
        (and caching) it on first use.  ``build`` runs outside the
        lock-free fast path but inside the miss path's lock — builders
        are cheap ``jax.jit(...)`` wrappings (compilation itself is
        lazy, at first call of the returned fn)."""
        full = (name, key)
        with self._lock:
            fn = self._entries.get(full)
            if fn is not None:
                self._entries.move_to_end(full)
                self.hits += 1
                if metrics.enabled:
                    metrics.count("perf/jit_cache/hit")
                return fn
            fn = self._instrument(name, build())
            self._entries[full] = fn
            self.misses += 1
            if metrics.enabled:
                metrics.count("perf/jit_cache/miss")
                metrics.count(f"perf/jit_cache/miss/{name}")
            try:
                # seed a kernel-ledger row so every named cache entry
                # shows up in profiler reports even before its first
                # observed launch (lazy import: perf must not require
                # the profiler at import time)
                from ..obs.profiler import ledger
                ledger.register(name, key)
            except Exception:
                pass
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1
                if metrics.enabled:
                    metrics.count("perf/jit_cache/evict")
        return fn

    @staticmethod
    def _instrument(name: str, fn: Callable) -> Callable:
        """Wrap a freshly built kernel so each launch notes its output
        bytes with the device-memory ledger (``memwatch``) as a
        transient under ``jit/<name>`` — the attribution feed that
        gives every cached operator (not just the streamed paths) a
        per-trace peak-bytes figure.  Fully fenced: ledger trouble
        never reaches the kernel, and non-callable cache entries pass
        through untouched."""
        if not callable(fn):
            return fn

        def _launch(*args, **kwargs):
            out = fn(*args, **kwargs)
            try:
                from ..obs.memwatch import memwatch
                if memwatch.enabled:
                    import jax
                    nb = sum(int(getattr(leaf, "nbytes", 0)) for leaf
                             in jax.tree_util.tree_leaves(out))
                    if nb:
                        memwatch.note_transient(f"jit/{name}", nb)
            except Exception:
                pass
            return out

        return _launch

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "size": len(self._entries)}


#: the process-global kernel cache every bucketed kernel goes through
kernel_cache = JitCache()


_persist_lock = threading.Lock()
_persist_dir: Optional[str] = None


def persistent_cache_dir() -> Optional[str]:
    """The directory the persistent compilation cache was wired to in
    this process (None = not configured)."""
    return _persist_dir


def configure_persistent_cache(path: Optional[str] = None
                               ) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``path``.

    Resolution order: explicit argument > ``MOSAIC_TPU_JIT_CACHE_DIR``
    env > the active config's ``mosaic.jit.cache.dir``.  Returns the
    resolved directory, or None when nothing is configured (a no-op —
    the in-memory caches still work).  Idempotent; re-pointing at a
    different directory is honored (last call wins) but logged to the
    flight recorder either way.

    Thresholds are dropped so EVERY compile persists
    (``min_entry_size_bytes=-1``, ``min_compile_time_secs=0``): this
    package's kernels are many and individually fast to compile, and
    the 1-2 ms disk hit beats even the cheapest recompile.  Call this
    before the first compile with the SAME settings in every process
    sharing the directory — the cache key hashes compile options, so
    drift turns hits into misses."""
    global _persist_dir
    if path is None:
        path = os.environ.get(JIT_CACHE_DIR_ENV)
    if path is None:
        from ..config import default_config
        path = getattr(default_config(), "jit_cache_dir", "") or None
    if not path:
        return _persist_dir
    path = str(path)
    with _persist_lock:
        if _persist_dir == path:
            return _persist_dir
        os.makedirs(path, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        _persist_dir = path
    from ..obs.recorder import recorder
    recorder.record("config", key="mosaic.jit.cache.dir", value=path)
    return _persist_dir
