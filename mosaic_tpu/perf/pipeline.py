"""Double-buffered host↔device streaming executor.

The chipping/join hot path repeats one shape: a big host batch is cut
into chunks, each chunk goes device-side, a jitted kernel runs, and a
host pass (f64 recheck, f64 re-rank, plain np.asarray) consumes the
result.  Run naively that is a serial put→compute→fetch→host loop;
every stage idles while the others work.  :func:`stream` overlaps the
three (the 3DPipe join pipeline shape, arxiv 2604.19982):

* ``put(chunk N+1)`` — ``jax.device_put`` is asynchronous, so the
  host→device transfer of the NEXT chunk is issued right after chunk
  N's compute is dispatched and rides along while the device works;
* ``compute(chunk N)`` — jitted dispatch, returns device arrays
  without blocking;
* ``consume(chunk N-1)`` — runs on ONE worker thread; its first act
  (``np.asarray`` on the device result) blocks THAT thread until the
  device finishes, so the device→host copy and the host-side f64 work
  overlap the next chunk's compute.  A single worker keeps results in
  chunk order and the host pass free of locking.

Buffer donation: wrap the kernel with :func:`donate_jit` so each
chunk's device input buffer is donated to its launch — the executor
never reuses a chunk's input, and donation lets XLA alias it instead
of holding both live (halves the steady-state footprint of the
streamed join).  CPU backends ignore donation; the wrapper skips it
there to avoid the per-launch warning.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..obs import metrics
from ..resilience import faults

__all__ = ["stream", "chunk_rows", "donate_jit"]


def _tree_bytes(x) -> int:
    """Total buffer bytes across a pytree's array leaves (0 for
    leaves with no nbytes — slices, scalars, handles)."""
    import jax
    return sum(int(getattr(leaf, "nbytes", 0))
               for leaf in jax.tree_util.tree_leaves(x))


def chunk_rows(n: int, chunk: int) -> List[slice]:
    """Row slices cutting ``n`` rows into ``chunk``-sized pieces (the
    last may be short)."""
    chunk = max(1, int(chunk))
    return [slice(s, min(s + chunk, n)) for s in range(0, n, chunk)]


def donate_jit(fn, donate_argnums=(0,)):
    """``jax.jit`` with donated input buffers where the backend honors
    donation (TPU/GPU); plain ``jit`` on CPU, which ignores donation
    and would warn on every launch."""
    import jax
    if jax.devices()[0].platform == "cpu":
        return jax.jit(fn)
    return jax.jit(fn, donate_argnums=donate_argnums)


def _to_host(out):
    import jax
    return jax.tree_util.tree_map(np.asarray, out)


def stream(chunks: Sequence, compute: Callable,
           put: Optional[Callable] = None,
           consume: Optional[Callable] = None,
           observe: Optional[Callable] = None) -> list:
    """Run ``chunks`` through the double-buffered pipeline; returns the
    per-chunk results in order.

    ``put(payload) -> device input`` (default ``jax.device_put``),
    ``compute(device input) -> device output`` (a jitted fn — must
    dispatch asynchronously), ``consume(i, payload, host output) ->
    result`` (optional; receives the output already fetched to host
    numpy, runs on the worker thread in chunk order).  Without
    ``consume`` the host-fetched outputs themselves are returned.

    ``observe(i, payload, seconds)`` (optional) receives each chunk's
    launch wall time — compute dispatch to host-fetch completion,
    clamped to the previous chunk's completion so the per-chunk spans
    are disjoint and sum to (at most, and in steady state almost
    exactly) the pipeline's busy wall time.  This is the kernel
    ledger's wall-time feed (``obs.profiler``); callbacks run on the
    single worker thread, in chunk order.  An observer that raises
    cannot kill the stream: the call is fenced — the error is counted
    (``pipeline/observe_errors``) and flight-recorded once per stream,
    and the chunk completes normally.

    Cancellation: each loop iteration starts with an
    ``obs.inflight.checkpoint`` probe, so a query cancelled (or past
    its deadline) mid-stream stops within one chunk boundary.
    Exceptions from any stage — including :class:`~..obs.inflight.
    QueryCancelled` from the probe — propagate to the caller; the
    worker is drained first so no device work is abandoned mid-flight
    (the executor's ``with`` block joins the worker on the way out, so
    a cancelled stream leaks no threads or in-flight device buffers)."""
    chunks = list(chunks)
    if not chunks:
        return []
    import time as _time
    import jax
    from ..obs.inflight import charge_h2d_bytes, checkpoint, inflight
    if put is None:
        put = jax.device_put
    dispatch_ts: list = [0.0] * len(chunks)
    obs_state = {"last_done": 0.0, "observe_failed": False}

    def fetch(i, payload, out):
        faults.maybe_fail("pipeline.fetch")
        host = _to_host(out)        # blocks the WORKER until ready
        if observe is not None:     # single worker: in-order, race-free
            now = _time.perf_counter()
            start = max(dispatch_ts[i], obs_state["last_done"])
            obs_state["last_done"] = now
            try:
                observe(i, payload, now - start)
            except Exception as exc:
                # observability must never take down the data path:
                # count every failure, flight-record the first per
                # stream (single worker, so the flag is race-free)
                metrics.count("pipeline/observe_errors")
                if not obs_state["observe_failed"]:
                    obs_state["observe_failed"] = True
                    from ..obs import recorder
                    recorder.record(
                        "pipeline_observe_error", chunk=i,
                        error=f"{type(exc).__name__}: {exc}")
        if metrics.enabled:         # device->host drain, per chunk
            metrics.count("pipeline/d2h_bytes", _tree_bytes(host))
        return consume(i, payload, host) if consume is not None \
            else host

    def staged(payload):
        dev = put(payload)
        # the tree walk is skipped entirely when nothing is listening
        if metrics.enabled or inflight._by_trace:
            nb = _tree_bytes(dev)
            if metrics.enabled:     # host->device staging, per chunk
                metrics.count("pipeline/h2d_bytes", nb)
            charge_h2d_bytes(nb)    # per-query attribution
        return dev

    results: list = [None] * len(chunks)
    with ThreadPoolExecutor(max_workers=1) as pool:
        futs = []
        dev = staged(chunks[0])
        for i, payload in enumerate(chunks):
            checkpoint("pipeline.stream")    # chunk-boundary cancel
            # latency chaos: "pipeline.chunk" mode=delay stalls the
            # dispatch loop (the cancellation drill's stall point —
            # a cancel landing mid-stall raises at the NEXT chunk's
            # checkpoint, one boundary later)
            faults.stall("pipeline.chunk")
            dispatch_ts[i] = _time.perf_counter()
            out = compute(dev)
            if i + 1 < len(chunks):
                dev = staged(chunks[i + 1])  # overlap H2D with compute
            futs.append(pool.submit(fetch, i, payload, out))
        for i, f in enumerate(futs):
            results[i] = f.result()
    return results
