"""Double-buffered host↔device streaming executor.

The chipping/join hot path repeats one shape: a big host batch is cut
into chunks, each chunk goes device-side, a jitted kernel runs, and a
host pass (f64 recheck, f64 re-rank, plain np.asarray) consumes the
result.  Run naively that is a serial put→compute→fetch→host loop;
every stage idles while the others work.  :func:`stream` overlaps the
three (the 3DPipe join pipeline shape, arxiv 2604.19982):

* ``put(chunk N+1)`` — ``jax.device_put`` is asynchronous, so the
  host→device transfer of the NEXT chunk is issued right after chunk
  N's compute is dispatched and rides along while the device works;
* ``compute(chunk N)`` — jitted dispatch, returns device arrays
  without blocking;
* ``consume(chunk N-1)`` — runs on ONE worker thread; its first act
  (``np.asarray`` on the device result) blocks THAT thread until the
  device finishes, so the device→host copy and the host-side f64 work
  overlap the next chunk's compute.  A single worker keeps results in
  chunk order and the host pass free of locking.

Buffer donation: wrap the kernel with :func:`donate_jit` so each
chunk's device input buffer is donated to its launch — the executor
never reuses a chunk's input, and donation lets XLA alias it instead
of holding both live (halves the steady-state footprint of the
streamed join).  CPU backends ignore donation; the wrapper skips it
there to avoid the per-launch warning.
"""

from __future__ import annotations

from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, List, Optional

import numpy as np

from ..obs import metrics
from ..resilience import faults

__all__ = ["stream", "chunk_rows", "donate_jit", "staged_put"]

#: fetches allowed in flight before the dispatch loop drains the
#: oldest — double buffering needs exactly one fetch overlapping the
#: next chunk's compute; anything beyond that only accumulates host
#: and device buffers with total stream length
_MAX_INFLIGHT_FETCHES = 2

#: pressure-driven halving floor: a slice this short never splits
#: (guards against a pathological budget dissolving the stream into
#: per-row launches)
_MIN_SHRINK_ROWS = 64

#: iterator-exhaustion sentinel for the lazy chunk pull (``None`` is a
#: legal chunk payload, so exhaustion needs its own marker)
_DONE = object()


def _tree_bytes(x) -> int:
    """Total buffer bytes across a pytree's array leaves (0 for
    leaves with no nbytes — slices, scalars, handles)."""
    import jax
    return sum(int(getattr(leaf, "nbytes", 0))
               for leaf in jax.tree_util.tree_leaves(x))


def chunk_rows(n: int, chunk: int) -> List[slice]:
    """Row slices cutting ``n`` rows into ``chunk``-sized pieces (the
    last may be short)."""
    chunk = max(1, int(chunk))
    return [slice(s, min(s + chunk, n)) for s in range(0, n, chunk)]


def donate_jit(fn, donate_argnums=(0,)):
    """``jax.jit`` with donated input buffers where the backend honors
    donation (TPU/GPU); plain ``jit`` on CPU, which ignores donation
    and would warn on every launch."""
    import jax
    if jax.devices()[0].platform == "cpu":
        return jax.jit(fn)
    return jax.jit(fn, donate_argnums=donate_argnums)


def _to_host(out):
    import jax
    return jax.tree_util.tree_map(np.asarray, out)


def staged_put(payload, site: str = "pipeline.staged",
               put: Optional[Callable] = None):
    """Stage one host batch device-side through the pipeline's
    accounting choke: ``jax.device_put`` (or ``put``), H2D byte
    metrics + per-query ticket charge, and a device-memory ledger
    registration under ``site``.  Returns ``(device_value, token)``;
    the caller owns the token and must ``memwatch.release(token)``
    once the staged buffer is consumed (token is None whenever the
    ledger is off).  This is the single-launch counterpart of
    :func:`stream`'s internal staging — non-streamed call sites (the
    serve layer's micro-batch launch) use it so the jit-raw-device-put
    lint choke and the leak sentinel both see their transfers."""
    import jax
    from ..obs.inflight import charge_h2d_bytes, inflight
    from ..obs.memwatch import device_keys_of, memwatch
    dev = (put or jax.device_put)(payload)
    tok = None
    # the tree walk is skipped entirely when nothing is listening
    if metrics.enabled or inflight._by_trace or memwatch.enabled:
        nb = _tree_bytes(dev)
        if metrics.enabled:         # host->device staging bytes
            metrics.count("pipeline/h2d_bytes", nb)
        charge_h2d_bytes(nb)        # per-query attribution
        if memwatch.enabled:
            tok = memwatch.register(site, nb,
                                    devices=device_keys_of(dev))
    return dev, tok


def stream(chunks: Iterable, compute: Callable,
           put: Optional[Callable] = None,
           consume: Optional[Callable] = None,
           observe: Optional[Callable] = None,
           site: str = "pipeline.stream") -> list:
    """Run ``chunks`` through the double-buffered pipeline; returns the
    per-chunk results in order.

    ``put(payload) -> device input`` (default ``jax.device_put``),
    ``compute(device input) -> device output`` (a jitted fn — must
    dispatch asynchronously), ``consume(i, payload, host output) ->
    result`` (optional; receives the output already fetched to host
    numpy, runs on the worker thread in chunk order).  Without
    ``consume`` the host-fetched outputs themselves are returned.

    ``observe(i, payload, seconds)`` (optional) receives each chunk's
    launch wall time — compute dispatch to host-fetch completion,
    clamped to the previous chunk's completion so the per-chunk spans
    are disjoint and sum to (at most, and in steady state almost
    exactly) the pipeline's busy wall time.  This is the kernel
    ledger's wall-time feed (``obs.profiler``); callbacks run on the
    single worker thread, in chunk order.  An observer that raises
    cannot kill the stream: the call is fenced — the error is counted
    (``pipeline/observe_errors``) and flight-recorded once per stream,
    and the chunk completes normally.

    ``site`` names this stream in the device-memory ledger
    (``obs.memwatch``): each chunk's staged input registers as
    ``<site>/staged`` and its device output as ``<site>/out``, both
    released when the worker's host fetch completes — so the ledger's
    live-bytes gauges track the pipeline's true in-flight footprint
    and the leak sentinel can name the site that failed to release.

    Memory footprint is bounded two ways:

    * the dispatch loop keeps at most ``_MAX_INFLIGHT_FETCHES``
      fetches outstanding, resolving the oldest before dispatching
      further — completed host chunks and queued work items no longer
      accumulate with total stream length (double buffering is
      preserved: the next chunk's compute still overlaps the previous
      chunk's drain);
    * under memory pressure (``obs.memwatch.mem_budget`` past
      ``mosaic.mem.pressure.high``), the NEXT chunk — when it is a
      row ``slice`` — is halved before staging (repeatedly, floor
      ``_MIN_SHRINK_ROWS`` rows), counted in ``mem/chunk_shrink``.
      Results stay bit-identical because consumers key on the slice
      payload, not the chunk index: the same rows arrive, in order,
      across more launches (degrade, not die).

    Cancellation: each loop iteration starts with an
    ``obs.inflight.checkpoint`` probe, so a query cancelled (or past
    its deadline) mid-stream stops within one chunk boundary.
    Exceptions from any stage — including :class:`~..obs.inflight.
    QueryCancelled` from the probe — propagate to the caller; the
    worker is drained first so no device work is abandoned mid-flight
    (the executor's ``with`` block joins the worker on the way out, so
    a cancelled stream leaks no threads or in-flight device buffers).

    ``chunks`` may be any iterable — including a GENERATOR that
    produces chunks lazily (the out-of-core chip store's scan path,
    ``store.reader.ChipStore.iter_chunks``).  The pipeline never
    materializes the chunk list: it pulls exactly one chunk ahead of
    the running compute (the double-buffer look-ahead), so the host
    working set stays bounded by the in-flight window regardless of
    how many chunks — or how many bytes — the source will eventually
    yield."""
    import time as _time
    import jax
    from ..obs.inflight import charge_d2h_bytes, checkpoint, inflight
    from ..obs.memwatch import device_keys_of, mem_budget, memwatch
    if put is None:
        put = jax.device_put
    obs_state = {"last_done": 0.0, "observe_failed": False,
                 "shrunk": False}

    def fetch(i, payload, out, dispatch_t, tok_in, tok_out):
        try:
            faults.maybe_fail("pipeline.fetch")
            host = _to_host(out)    # blocks the WORKER until ready
        finally:
            # the chunk's device buffers are drained — input consumed
            # by the launch, output copied out — and both must leave
            # the ledger even when the fetch itself unwinds (fault,
            # cancel): a raise above this line used to strand both
            # tokens until the query-complete sentinel swept them
            memwatch.release(tok_out)
            memwatch.release(tok_in)
        if observe is not None:     # single worker: in-order, race-free
            now = _time.perf_counter()
            start = max(dispatch_t, obs_state["last_done"])
            obs_state["last_done"] = now
            try:
                observe(i, payload, now - start)
            except Exception as exc:
                # observability must never take down the data path:
                # count every failure, flight-record the first per
                # stream (single worker, so the flag is race-free)
                metrics.count("pipeline/observe_errors")
                if not obs_state["observe_failed"]:
                    obs_state["observe_failed"] = True
                    from ..obs import recorder
                    recorder.record(
                        "pipeline_observe_error", chunk=i,
                        error=f"{type(exc).__name__}: {exc}")
        if metrics.enabled or inflight._by_trace:
            nb = _tree_bytes(host)  # device->host drain, per chunk
            if metrics.enabled:
                metrics.count("pipeline/d2h_bytes", nb)
            charge_d2h_bytes(nb)    # per-query attribution
        return consume(i, payload, host) if consume is not None \
            else host

    def staged(payload):
        return staged_put(payload, site=f"{site}/staged", put=put)

    # lazy source: chunks are pulled one at a time from the iterator —
    # a split pushes its halves back onto the head of this small deque,
    # so the pending window never holds more than one source chunk's
    # worth of slices
    source = iter(chunks)
    pending: deque = deque()

    def pull() -> bool:
        """Ensure at least one chunk is pending; False when the source
        is exhausted."""
        if not pending:
            nxt = next(source, _DONE)
            if nxt is _DONE:
                return False
            pending.append(nxt)
        return True

    def maybe_split():
        # degrade-not-die: while any device sits past the pressure
        # high-water mark, halve the next chunk's rows before staging
        # it.  Only row slices split (the array-backed call sites chunk
        # by slice); consumers key on the slice, so the extra
        # boundaries are invisible in the results.
        while (mem_budget.shrink_needed()
               and pending and isinstance(pending[0], slice)
               and (pending[0].stop - pending[0].start) > _MIN_SHRINK_ROWS):
            sl = pending.popleft()
            mid = (sl.start + sl.stop) // 2
            pending.appendleft(slice(mid, sl.stop))
            pending.appendleft(slice(sl.start, mid))
            if metrics.enabled:
                metrics.count("mem/chunk_shrink")
            if not obs_state["shrunk"]:   # flight-record once per stream
                obs_state["shrunk"] = True
                from ..obs import recorder
                recorder.record("mem_chunk_shrink", site=site,
                                rows=sl.stop - sl.start)

    if not pull():
        return []
    results: list = []
    with ThreadPoolExecutor(max_workers=1) as pool:
        futs: deque = deque()
        maybe_split()
        payload = pending.popleft()
        dev, tok = staged(payload)
        try:
            i = 0
            while payload is not _DONE:
                checkpoint("pipeline.stream")   # chunk-boundary cancel
                # latency chaos: "pipeline.chunk" mode=delay stalls the
                # dispatch loop (the cancellation drill's stall point —
                # a cancel landing mid-stall raises at the NEXT chunk's
                # checkpoint, one boundary later)
                faults.stall("pipeline.chunk")
                dispatch_t = _time.perf_counter()
                out = compute(dev)
                tok_out = memwatch.register(
                    f"{site}/out", _tree_bytes(out),
                    devices=device_keys_of(out)) \
                    if memwatch.enabled else None
                if pull():
                    maybe_split()
                    nxt_payload = pending.popleft()
                    nxt = staged(nxt_payload)    # overlap H2D w/ compute
                else:
                    nxt_payload, nxt = _DONE, (None, None)
                futs.append(pool.submit(fetch, i, payload, out,
                                        dispatch_t, tok, tok_out))
                (dev, tok), payload = nxt, nxt_payload
                # bounded in-flight window: resolve the oldest fetch
                # once the window fills, so host results and queued
                # work items stop scaling with total stream length
                while len(futs) > _MAX_INFLIGHT_FETCHES:
                    results.append(futs.popleft().result())
                i += 1
            while futs:
                results.append(futs.popleft().result())
        finally:
            # a stream unwinding mid-loop (cancel, deadline, fault)
            # has staged the next chunk without dispatching it — drop
            # its registration so clean cancellation never reads as a
            # leak (in-flight fetches release their own tokens as the
            # executor exit joins the worker)
            memwatch.release(tok)
    return results
