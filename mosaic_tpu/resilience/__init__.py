"""Resilience layer: fault injection, retry/backoff, degrade-not-die.

The reference Mosaic inherits Spark's task retry and per-record error
semantics; our TPU-native stack supplies the equivalent explicitly:

* ``resilience.faults`` — deterministic, seedable fault plans armed via
  ``MOSAIC_TPU_FAULT_PLAN`` or :func:`faults.arm`, consulted by cheap
  probes (``maybe_fail`` / ``corrupt`` / ``degrade``) placed at named
  sites across io / raster / native / parallel;
* ``resilience.retry`` — declarative :class:`RetryPolicy` (attempt
  budget, exponential backoff, deterministic jitter, exception
  allowlist, obs counters) applied to checkpoint IO and native
  compile/load;
* ``resilience.ingest`` — ``on_error="raise"|"skip"|"null"`` policy for
  every codec: malformed records become structured
  :class:`ErrorRecord`\\ s plus ``io/records_dropped`` metrics instead
  of process-killing exceptions;
* ``resilience.testing`` — the ``fault_plan`` pytest fixture.

See docs/usage/resilience.md.
"""

from . import faults
from .faults import FaultPlan, FaultRule, InjectedFault
from .ingest import (ON_ERROR_MODES, CodecError, ErrorRecord, ErrorSink,
                     decode_guard)
from .retry import (CHECKPOINT_RETRY, NATIVE_COMPILE_RETRY,
                    NATIVE_LOAD_RETRY, RetryPolicy, retrying)

__all__ = [
    "faults", "FaultPlan", "FaultRule", "InjectedFault",
    "RetryPolicy", "retrying", "CHECKPOINT_RETRY",
    "NATIVE_COMPILE_RETRY", "NATIVE_LOAD_RETRY",
    "CodecError", "ErrorRecord", "ErrorSink", "decode_guard",
    "ON_ERROR_MODES",
]
