"""Deterministic, seedable fault injection.

Reference counterpart: the reference inherits Spark's task-retry and
speculative-execution machinery, and its test suites lean on Spark's
local-cluster failure semantics for free.  Standalone on JAX we get
neither, so chaos becomes a first-class, *deterministic* instrument:
a :class:`FaultPlan` is armed process-wide (programmatically or via
``MOSAIC_TPU_FAULT_PLAN``) and cheap probes placed at named sites in
the io / raster / native / parallel layers consult it.

Four probe kinds:

* ``maybe_fail(site)`` — raise an injected exception (an
  :class:`InjectedFault` subclass of a realistic base type such as
  ``OSError``) when the plan selects this invocation;
* ``corrupt(site, data)`` — deterministically truncate or bit-flip a
  byte payload (codec chaos: damaged strips / messages / records);
* ``degrade(site, value)`` — shrink an integer capacity (collective
  skew amplification: forces bucket/dup overflow-retry paths);
* ``stall(site)`` — sleep an injected ``delay_ms`` (latency chaos:
  deterministic slow queries for SLO-alert drills, results intact).

Every decision is a pure function of ``(seed, site, per-site call
number)`` — re-running the same workload under the same plan injects
the same faults at the same places, so chaos tests are ordinary,
reproducible tier-1 tests (fixture: ``mosaic_tpu.resilience.testing``).

Disarmed cost is one module-global ``None`` check per probe.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import hashlib
import os
import random
import struct as _struct
import threading
import zlib as _zlib
from typing import Dict, List, Optional, Tuple, Type

from ..obs import metrics

__all__ = ["InjectedFault", "FaultRule", "FaultPlan", "arm", "disarm",
           "active", "maybe_fail", "corrupt", "degrade", "stall"]


class InjectedFault(Exception):
    """Marker mixin: every exception a FaultPlan raises is-a
    InjectedFault, so handlers/tests can tell chaos from real damage
    while production code still sees the realistic base type."""


_INJECTED_TYPES: Dict[type, type] = {}


def injected_type(base: Type[BaseException]) -> type:
    """``OSError`` -> ``InjectedOSError`` (subclass of both)."""
    t = _INJECTED_TYPES.get(base)
    if t is None:
        t = type("Injected" + base.__name__, (base, InjectedFault), {})
        _INJECTED_TYPES[base] = t
    return t


#: error= spec values -> base exception types
ERROR_TYPES: Dict[str, Type[BaseException]] = {
    "OSError": OSError,
    "IOError": OSError,
    "TimeoutError": TimeoutError,
    "ValueError": ValueError,
    "IndexError": IndexError,
    "RuntimeError": RuntimeError,
    "MemoryError": MemoryError,
    "struct.error": _struct.error,
    "zlib.error": _zlib.error,
}

_MODES = ("raise", "truncate", "flip", "degrade", "delay")


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """One clause of a plan: which sites, how often, what happens."""

    pattern: str                      # fnmatch over the site name
    rate: float = 0.0                 # per-call injection probability
    fails: int = 0                    # fail the first N calls instead
    error: Type[BaseException] = OSError
    mode: str = "raise"       # raise | truncate | flip | degrade | delay
    factor: int = 4                   # degrade: capacity divisor
    delay_ms: float = 100.0           # delay: injected stall length

    def matches(self, site: str) -> bool:
        return fnmatch.fnmatchcase(site, self.pattern)


class FaultPlan:
    """Seeded set of :class:`FaultRule`\\ s with per-site call counters.

    Decisions are deterministic: call ``n`` at ``site`` is selected iff
    ``n < fails`` (transient-failure rules) or the 64-bit hash of
    ``(seed, site, n)`` falls under ``rate``.
    """

    def __init__(self, seed: int = 0,
                 rules: Tuple[FaultRule, ...] = ()):
        self.seed = int(seed)
        self.rules: List[FaultRule] = list(rules)
        self.injected: List[Tuple[str, int, str]] = []  # (site, n, kind)
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- spec DSL -----------------------------------------------------
    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """Parse the ``MOSAIC_TPU_FAULT_PLAN`` mini-DSL.

        ``spec := clause (';' clause)*`` where a clause is ``seed=N``
        or ``site=PATTERN[,rate=F][,fails=N][,error=NAME][,mode=M]
        [,factor=N][,delay_ms=F]``, e.g.::

            seed=1234;site=checkpoint.*,rate=0.1,error=OSError;
            site=native.compile,fails=1;
            site=overlay.*,mode=degrade,rate=1.0,factor=4;
            site=sql.query,mode=delay,fails=1,delay_ms=120
        """
        seed = 0
        rules: List[FaultRule] = []
        for clause in filter(None,
                             (c.strip() for c in spec.split(";"))):
            kv: Dict[str, str] = {}
            for part in clause.split(","):
                if "=" not in part:
                    raise ValueError(
                        f"fault-plan clause {clause!r}: bad item "
                        f"{part!r} (want key=value)")
                k, v = part.split("=", 1)
                kv[k.strip()] = v.strip()
            if list(kv) == ["seed"]:
                seed = int(kv["seed"])
                continue
            if "site" not in kv:
                raise ValueError(
                    f"fault-plan clause {clause!r} missing site=")
            err = kv.get("error", "OSError")
            if err not in ERROR_TYPES:
                raise ValueError(
                    f"fault-plan error {err!r} unknown "
                    f"(have: {sorted(ERROR_TYPES)})")
            mode = kv.get("mode", "raise")
            if mode not in _MODES:
                raise ValueError(f"fault-plan mode {mode!r} unknown "
                                 f"(have: {_MODES})")
            rules.append(FaultRule(
                pattern=kv["site"],
                rate=float(kv.get("rate", 0.0)),
                fails=int(kv.get("fails", 0)),
                error=ERROR_TYPES[err],
                mode=mode,
                factor=int(kv.get("factor", 4)),
                delay_ms=float(kv.get("delay_ms", 100.0))))
        return cls(seed=seed, rules=tuple(rules))

    # -- decision core ------------------------------------------------
    def _next_call(self, site: str) -> int:
        with self._lock:
            n = self._counts.get(site, 0)
            self._counts[site] = n + 1
            return n

    def _hit(self, rule: FaultRule, site: str, n: int) -> bool:
        if rule.fails:
            return n < rule.fails
        if rule.rate <= 0.0:
            return False
        h = hashlib.sha256(
            f"{self.seed}:{site}:{n}".encode()).digest()
        return int.from_bytes(h[:8], "big") / 2.0 ** 64 < rule.rate

    def _record(self, site: str, n: int, kind: str) -> None:
        # probes fire on whatever thread hit the site; the ledger list
        # shares the counter lock (callers never hold it here)
        with self._lock:
            self.injected.append((site, n, kind))
        metrics.count("faults/injected")
        metrics.count(f"faults/injected/{site}")
        from ..obs.recorder import recorder
        recorder.record("fault_injected", site=site, call=n,
                        fault=kind, seed=self.seed)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self.injected.clear()

    # -- probes -------------------------------------------------------
    def maybe_fail(self, site: str) -> None:
        n = self._next_call(site)
        for rule in self.rules:
            if rule.mode != "raise" or not rule.matches(site):
                continue
            if self._hit(rule, site, n):
                self._record(site, n, rule.error.__name__)
                raise injected_type(rule.error)(
                    f"injected fault at {site} "
                    f"(call {n}, seed {self.seed})")

    def corrupt(self, site: str, data: bytes) -> bytes:
        n = self._next_call(site)
        for rule in self.rules:
            if rule.mode not in ("truncate", "flip") \
                    or not rule.matches(site):
                continue
            if self._hit(rule, site, n) and len(data):
                rnd = random.Random(f"{self.seed}:{site}:{n}")
                if rule.mode == "truncate":
                    data = data[:rnd.randrange(len(data))]
                else:
                    i = rnd.randrange(len(data))
                    b = bytearray(data)
                    b[i] ^= 0xFF
                    data = bytes(b)
                self._record(site, n, rule.mode)
                return data
        return data

    def degrade(self, site: str, value: int) -> int:
        n = self._next_call(site)
        for rule in self.rules:
            if rule.mode != "degrade" or not rule.matches(site):
                continue
            if self._hit(rule, site, n):
                self._record(site, n, "degrade")
                return max(1, int(value) // max(rule.factor, 1))
        return value

    def stall(self, site: str) -> float:
        """Sleep ``delay_ms`` when selected (latency chaos: slow
        queries / SLO drills without breaking results); returns the
        injected delay in seconds (0.0 = not selected)."""
        import time as _time
        n = self._next_call(site)
        for rule in self.rules:
            if rule.mode != "delay" or not rule.matches(site):
                continue
            if self._hit(rule, site, n):
                self._record(site, n, "delay")
                _time.sleep(rule.delay_ms / 1e3)
                return rule.delay_ms / 1e3
        return 0.0


# ---------------------------------------------------------- module API

_active: Optional[FaultPlan] = None


def arm(plan) -> FaultPlan:
    """Arm a plan process-wide (a FaultPlan or a spec string)."""
    global _active
    if isinstance(plan, str):
        plan = FaultPlan.from_spec(plan)
    _active = plan
    return plan


def disarm() -> None:
    global _active
    _active = None


def active() -> Optional[FaultPlan]:
    return _active


def maybe_fail(site: str) -> None:
    """Probe: raise the armed plan's injected exception, or no-op."""
    p = _active
    if p is not None:
        p.maybe_fail(site)


def corrupt(site: str, data: bytes) -> bytes:
    """Probe: deterministically damage a byte payload, or pass through."""
    p = _active
    return data if p is None else p.corrupt(site, data)


def degrade(site: str, value: int) -> int:
    """Probe: shrink a capacity (skew amplification), or pass through."""
    p = _active
    return value if p is None else p.degrade(site, value)


def stall(site: str) -> float:
    """Probe: sleep the armed plan's injected delay, or no-op.
    Returns the injected seconds (0.0 when disarmed / not selected)."""
    p = _active
    return 0.0 if p is None else p.stall(site)


# env arming: chaos lanes set MOSAIC_TPU_FAULT_PLAN before pytest
_env_spec = os.environ.get("MOSAIC_TPU_FAULT_PLAN")
if _env_spec:
    arm(FaultPlan.from_spec(_env_spec))
