"""Degrade-not-die ingestion: structured decode errors + on_error policy.

Reference counterpart: the reference's OGR/GDAL readers inherit Spark's
per-record error semantics — ``spark.read...option("mode",
"PERMISSIVE")``-style handling where a malformed record becomes a null
row instead of a dead executor.  Our pure-Python codecs previously
leaked raw ``struct.error`` / ``zlib.error`` / ``IndexError`` from the
byte level, killing the whole batch on one truncated strip.

Two pieces:

* :func:`decode_guard` — wraps a low-level decode region so raw parser
  exceptions surface as :class:`CodecError` (a ``ValueError``) naming
  the file, feature, and byte offset.
* :class:`ErrorSink` — carries an ``on_error`` policy
  (``"raise" | "skip" | "null"``) through a codec.  ``raise`` (the
  default, from ``MosaicConfig.io_on_error``) preserves fail-fast
  behaviour; ``skip`` / ``null`` convert malformed records into
  :class:`ErrorRecord`\\ s and ``io/records_dropped`` metrics and keep
  decoding the intact remainder.
"""

from __future__ import annotations

import contextlib
import dataclasses
import struct
import zlib
from typing import List, Optional

from ..obs import metrics
from ..obs.recorder import recorder

__all__ = ["ErrorRecord", "CodecError", "ErrorSink", "decode_guard",
           "ON_ERROR_MODES"]

ON_ERROR_MODES = ("raise", "skip", "null")

#: raw exception types a decode region may leak from the byte level
_RAW_DECODE_ERRORS = (struct.error, zlib.error, IndexError, KeyError,
                      TypeError, UnicodeDecodeError, OverflowError,
                      ValueError)


@dataclasses.dataclass(frozen=True)
class ErrorRecord:
    """One malformed record, structured: where, what, why."""

    path: Optional[str]       # file path (None for in-memory bytes)
    feature: Optional[str]    # e.g. "strip 3", "message 1", "record 7"
    offset: Optional[int]     # byte offset where decoding failed
    reason: str               # first line of the underlying error
    error_type: str           # underlying exception class name


class CodecError(ValueError):
    """Decode failure with location context.

    A ``ValueError`` so existing ``pytest.raises(ValueError)`` /
    caller ``except ValueError`` contracts hold, but carrying the
    (path, feature, offset) triple as attributes and in the message.
    """

    def __init__(self, reason: str, path: Optional[str] = None,
                 feature: Optional[str] = None,
                 offset: Optional[int] = None):
        self.path = path
        self.feature = feature
        self.offset = offset
        self.reason = reason
        loc = []
        if path is not None:
            loc.append(str(path))
        if feature is not None:
            loc.append(str(feature))
        if offset is not None:
            loc.append(f"byte offset {offset}")
        prefix = " @ ".join(loc)
        super().__init__(f"{prefix}: {reason}" if prefix else reason)

    def record(self) -> ErrorRecord:
        return ErrorRecord(path=self.path, feature=self.feature,
                           offset=self.offset,
                           reason=self.reason.splitlines()[0][:200],
                           error_type=type(self).__name__)


@contextlib.contextmanager
def decode_guard(path: Optional[str] = None,
                 feature: Optional[str] = None,
                 offset: Optional[int] = None):
    """Turn raw byte-level parser exceptions into a located CodecError.

    Truncated buffers raise ``struct.error`` from ``struct.unpack``,
    ``zlib.error`` from ``decompress``, ``ValueError`` from
    ``np.frombuffer``, ``IndexError`` from short slices — all of them
    come out as ``CodecError("<file> @ <feature> @ byte offset N: …")``.
    An already-located CodecError passes through unchanged.
    """
    try:
        yield
    except CodecError:
        raise
    except _RAW_DECODE_ERRORS as e:
        err = CodecError(f"{type(e).__name__}: {e}", path=path,
                         feature=feature, offset=offset)
        # flight-recorder event regardless of on_error mode: a "raise"
        # that escapes to the excepthook dumps with the located error
        recorder.record("codec_error", path=path, feature=feature,
                        offset=offset,
                        reason=f"{type(e).__name__}: {e}"[:200])
        raise err from e


class ErrorSink:
    """Threads the ``on_error`` policy through one codec invocation."""

    def __init__(self, on_error: Optional[str] = None,
                 driver: str = "io", path: Optional[str] = None):
        if on_error is None:
            from .. import config as _config
            on_error = _config.default_config().io_on_error
        if on_error not in ON_ERROR_MODES:
            raise ValueError(
                f"on_error={on_error!r} invalid "
                f"(choose from {ON_ERROR_MODES})")
        self.on_error = on_error
        self.driver = driver
        self.path = path
        self.records: List[ErrorRecord] = []

    @property
    def raising(self) -> bool:
        return self.on_error == "raise"

    def handle(self, exc: BaseException,
               feature: Optional[str] = None,
               offset: Optional[int] = None) -> None:
        """Record a malformed record, or re-raise under ``"raise"``.

        After ``handle`` returns (skip/null modes) the caller drops or
        nulls the record and keeps going.
        """
        if self.on_error == "raise":
            raise exc
        if isinstance(exc, CodecError):
            rec = exc.record()
            if rec.path is None and self.path is not None:
                rec = dataclasses.replace(rec, path=self.path)
        else:
            rec = ErrorRecord(
                path=self.path, feature=feature, offset=offset,
                reason=f"{type(exc).__name__}: {exc}"[:200],
                error_type=type(exc).__name__)
        self.records.append(rec)
        recorder.record("codec_record_dropped", driver=self.driver,
                        path=rec.path, feature=rec.feature,
                        offset=rec.offset, reason=rec.reason,
                        error_type=rec.error_type)
        metrics.count("io/records_dropped")
        metrics.count(f"io/records_dropped/{self.driver}")

    def dropped(self) -> int:
        return len(self.records)

    def export(self, errors: Optional[list]) -> None:
        """Append this sink's records to a caller-supplied list."""
        if errors is not None:
            errors.extend(self.records)

    def meta_records(self) -> List[dict]:
        """Records as plain dicts (for ``tile.meta`` stamping)."""
        return [dataclasses.asdict(r) for r in self.records]
