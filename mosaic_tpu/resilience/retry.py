"""Retry / timeout / backoff policies.

Reference counterpart: Spark's task scheduler retries a failed task up
to ``spark.task.maxFailures`` times with its own backoff — the
reference's checkpoint writes and JNI calls ride on that for free.
Standalone, transient IO faults (NFS blips, a concurrently-swept native
``.so``, a checkpoint volume hiccup) need an explicit policy object.

:class:`RetryPolicy` is immutable and declarative: attempt budget,
exponential backoff with **deterministic jitter** (seeded from the
armed fault plan, so chaos runs replay byte-identically), an exception
allowlist, and per-attempt obs counters (``retry/attempts/<name>``,
``retry/recovered/<name>``, ``retry/giveups/<name>``).  Each attempt
also lands a structured ``retry`` / ``retry_recovered`` /
``retry_giveup`` event (error text, backoff) in the flight recorder
(``obs.recorder``), so post-hoc "which call retried and why" survives.
Apply with ``policy.call(fn, ...)`` or the ``retrying(policy)``
decorator.
"""

from __future__ import annotations

import dataclasses
import functools
import random
import subprocess as _subprocess
import time
from typing import Callable, Optional, Tuple, Type

from ..obs import metrics
from ..obs.recorder import recorder
from . import faults

__all__ = ["RetryPolicy", "retrying", "ProbeFailure",
           "CHECKPOINT_RETRY", "NATIVE_COMPILE_RETRY",
           "NATIVE_LOAD_RETRY", "BENCH_PROBE_RETRY",
           "SERVE_SPAWN_RETRY", "FLEET_RESPAWN_BACKOFF",
           "LOADTEST_CONNECT_RETRY"]


class ProbeFailure(RuntimeError):
    """One out-of-process backend probe attempt failed (nonzero exit
    or hung subprocess).  Raised by bench.py's TPU probe so the
    BENCH_PROBE_RETRY allowlist can name a type narrower than
    SubprocessError."""


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Declarative retry/backoff policy.

    ``delay(attempt)`` for attempt ``a`` (0-based) is
    ``min(base * multiplier**a, max_delay)`` scaled by a deterministic
    jitter in ``[1-jitter, 1+jitter]`` derived from the fault-plan seed
    (0 when no plan is armed), the policy name, and the attempt number
    — never from wall-clock entropy.
    """

    name: str = "default"
    max_attempts: int = 3
    base_delay_s: float = 0.01
    max_delay_s: float = 1.0
    multiplier: float = 2.0
    jitter: float = 0.25
    retry_on: Tuple[Type[BaseException], ...] = (OSError,)

    def delay(self, attempt: int, seed: Optional[int] = None) -> float:
        d = min(self.base_delay_s * self.multiplier ** attempt,
                self.max_delay_s)
        if self.jitter:
            if seed is None:
                plan = faults.active()
                seed = plan.seed if plan is not None else 0
            rnd = random.Random(f"{seed}:{self.name}:{attempt}")
            d *= 1.0 + self.jitter * (2.0 * rnd.random() - 1.0)
        return d

    def call(self, fn: Callable, *args,
             on_retry: Optional[Callable[[BaseException, int], None]]
             = None,
             sleep: Callable[[float], None] = time.sleep, **kwargs):
        """Run ``fn(*args, **kwargs)``, retrying allowlisted exceptions.

        ``on_retry(exc, attempt)`` runs before each re-attempt (e.g.
        invalidate a cache); the final failure re-raises the last
        exception unchanged.
        """
        last: Optional[BaseException] = None
        for attempt in range(max(1, self.max_attempts)):
            try:
                out = fn(*args, **kwargs)
                if attempt:
                    metrics.count(f"retry/recovered/{self.name}")
                    recorder.record("retry_recovered", policy=self.name,
                                    attempts=attempt + 1)
                return out
            except self.retry_on as e:
                last = e
                metrics.count(f"retry/attempts/{self.name}")
                if attempt + 1 >= max(1, self.max_attempts):
                    break
                if on_retry is not None:
                    on_retry(e, attempt)
                delay = self.delay(attempt)
                recorder.record("retry", policy=self.name,
                                attempt=attempt, backoff_s=round(delay, 6),
                                error=f"{type(e).__name__}: {e}"[:200])
                sleep(delay)
        metrics.count(f"retry/giveups/{self.name}")
        assert last is not None
        recorder.record("retry_giveup", policy=self.name,
                        attempts=max(1, self.max_attempts),
                        error=f"{type(last).__name__}: {last}"[:200])
        raise last


def retrying(policy: RetryPolicy):
    """Decorator form of :meth:`RetryPolicy.call`."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return policy.call(fn, *args, **kwargs)
        return wrapper
    return deco


#: raster / model checkpoint file IO (read and write sides)
CHECKPOINT_RETRY = RetryPolicy(name="checkpoint", max_attempts=3,
                               base_delay_s=0.01, max_delay_s=0.5,
                               retry_on=(OSError,))

#: native toolchain invocation (g++ subprocess): one re-attempt covers
#: transient fork/tmpfile failures; a missing compiler fails fast twice
NATIVE_COMPILE_RETRY = RetryPolicy(
    name="native.compile", max_attempts=2, base_delay_s=0.05,
    max_delay_s=0.2,
    retry_on=(OSError, _subprocess.SubprocessError))

#: CDLL load of the cached .so: the retry hook rebuilds the artifact
#: (replaces the pre-resilience hand-rolled double-try)
NATIVE_LOAD_RETRY = RetryPolicy(name="native.load", max_attempts=2,
                                base_delay_s=0.0, jitter=0.0,
                                retry_on=(OSError,))

#: bench.py's out-of-process TPU probe: a down tunnel HANGS
#: jax.devices(), so each attempt is subprocess+timeout and the policy
#: bounds the retries (backoff 10s -> 30s max; retry/* counters +
#: flight-recorder events replace the old hand-rolled sleep loop)
BENCH_PROBE_RETRY = RetryPolicy(name="bench.probe", max_attempts=3,
                                base_delay_s=10.0, max_delay_s=30.0,
                                multiplier=2.0,
                                retry_on=(ProbeFailure, OSError,
                                          _subprocess.SubprocessError))

#: fleet worker exec (serve/supervisor.py): transient fork/exec
#: failures (the ``serve.spawn`` fault site among them) retry fast; a
#: missing interpreter fails fast three times and the health loop's
#: breaker takes over
SERVE_SPAWN_RETRY = RetryPolicy(
    name="serve.spawn", max_attempts=3, base_delay_s=0.05,
    max_delay_s=0.5, retry_on=(OSError,
                               _subprocess.SubprocessError))

#: crash-respawn schedule (not a call-retry: the supervisor only uses
#: ``delay(k)`` for the k-th respawn inside the breaker window, so a
#: crash-looping worker backs off exponentially instead of spinning)
FLEET_RESPAWN_BACKOFF = RetryPolicy(
    name="fleet.respawn", max_attempts=1_000_000,
    base_delay_s=0.1, max_delay_s=2.0, multiplier=2.0)

#: loadtest client connects (tools/loadtest.py): jittered backoff over
#: refused/reset connects, so the kill drill's clients ride through a
#: worker SIGKILL window instead of booking instant errors
LOADTEST_CONNECT_RETRY = RetryPolicy(
    name="loadtest.connect", max_attempts=4, base_delay_s=0.05,
    max_delay_s=0.5, retry_on=(ConnectionError, OSError))
