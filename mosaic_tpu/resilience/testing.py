"""Pytest fixture so chaos tests are ordinary tier-1 tests.

Register from a conftest with::

    from mosaic_tpu.resilience.testing import fault_plan  # noqa: F401

then in a test::

    def test_checkpoint_rides_out_transient_io(fault_plan):
        plan = fault_plan("seed=7;site=checkpoint.write,fails=2")
        ...  # first two writes raise InjectedOSError, third succeeds
"""

from __future__ import annotations

import pytest

from . import faults

__all__ = ["fault_plan", "no_faults"]


@pytest.fixture
def fault_plan():
    """Arm a fault plan for one test; restore the prior plan after.

    Yields an ``arm(spec_or_plan) -> FaultPlan`` callable; whatever was
    armed before the test (e.g. a chaos-lane env plan) is re-armed on
    teardown, so tests compose with ``MOSAIC_TPU_FAULT_PLAN`` lanes.
    """
    prev = faults.active()

    def _arm(spec_or_plan) -> faults.FaultPlan:
        return faults.arm(spec_or_plan)

    try:
        yield _arm
    finally:
        if prev is None:
            faults.disarm()
        else:
            faults.arm(prev)


@pytest.fixture
def no_faults():
    """Disarm injection for one test; restore the prior plan after.

    For tests asserting clean-path behavior (byte parity, probe no-ops)
    that must hold even under a chaos-lane ``MOSAIC_TPU_FAULT_PLAN``.
    """
    prev = faults.active()
    faults.disarm()
    try:
        yield
    finally:
        if prev is not None:
            faults.arm(prev)
