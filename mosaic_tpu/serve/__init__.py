"""Multi-tenant query service: a long-lived server over one
SQLSession — bounded admission with per-tenant quotas, micro-batched
point lookups, cooperative cancel on disconnect/deadline, and
degrade-not-die overload behavior (shed lowest priority first, drain
on SIGTERM).  Stdlib only: asyncio streams + hand-rolled HTTP/1.1.

Usage::

    from mosaic_tpu.serve import QueryServer
    with QueryServer(session, port=8817) as srv:
        srv.install_sigterm_drain()
        ...

Tuned by the ``mosaic.serve.*`` conf keys (docs/usage/serving.md).
"""

from .admission import AdmissionQueue, Deny, ServeRequest
from .batching import KERNEL_NAME, execute_batch
from .scoreboard import Scoreboard, ScoreboardError, SlotToken
from .server import QueryServer, current_server, install_sigterm_drain
from .supervisor import ServeFleet, WorkerSlot, worker_main
from .workers import WorkerPool

__all__ = [
    "AdmissionQueue", "Deny", "ServeRequest",
    "KERNEL_NAME", "execute_batch",
    "Scoreboard", "ScoreboardError", "SlotToken",
    "QueryServer", "current_server", "install_sigterm_drain",
    "ServeFleet", "WorkerSlot", "worker_main",
    "WorkerPool",
]
