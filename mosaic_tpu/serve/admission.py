"""Bounded admission queue with per-tenant quotas and priority shed.

The scheduler half of the LocationSpark split (arxiv 1907.03736): the
server never throws concurrent load straight at the executor.  Every
request passes :meth:`AdmissionQueue.offer`, which applies — in order
of increasing cost — the tenant's rate quota (admissions per second
over a 1 s sliding window), the tenant's concurrency quota (queued +
running), the device-memory budget (:meth:`~..obs.memwatch.
MemoryBudget.admit` over the planner's byte estimate — deny, never
OOM), and finally the global queue depth.  A full queue load-sheds
the LOWEST-priority entry: an arriving request evicts a strictly
lower-priority queued one (which completes with 429), otherwise it is
itself shed.  Every deny carries a Retry-After hint; the concurrency
hint is derived from the tenant's own observed mean query latency
(the :class:`~..obs.accounting.PrincipalMeter` feed), so a tenant
running heavy queries is told to back off longer than one running
point lookups.

Workers drain the queue highest-priority-first (FIFO within a
priority) via :meth:`take`; :meth:`take_compatible` additionally pulls
queued point lookups that share a batch signature so one device
launch can serve several queries (serve/batching.py).

Fleet mode (serve/supervisor.py): constructed with a shared
:class:`~.scoreboard.Scoreboard`, the rate and concurrency checks
become one atomic count-and-claim against the mmap'd scoreboard, so
the same quotas hold across every worker process; the claim token
rides on the request and is released on completion, shed, or flush —
and by the supervisor's reaper if this whole process dies holding it.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Deque, Dict, List, Optional, Tuple

from ..obs.metrics import metrics
from ..obs.recorder import recorder

__all__ = ["ServeRequest", "Deny", "AdmissionQueue"]

_seq = itertools.count(1)

#: rate-quota sliding window (seconds) — quota.qps admissions per this
_RATE_WINDOW_S = 1.0


class Deny:
    """One admission refusal: HTTP status, machine reason, retry hint."""

    __slots__ = ("status", "reason", "retry_after")

    def __init__(self, status: int, reason: str, retry_after: float):
        self.status = status
        self.reason = reason
        self.retry_after = max(0.05, round(float(retry_after), 3))

    def payload(self) -> Dict[str, object]:
        return {"error": "denied", "reason": self.reason,
                "retry_after_s": self.retry_after}


class ServeRequest:
    """One admitted (or pending-admission) query riding through the
    server: identity, priority, the worker-resolved result future,
    and the cancellation plumbing that joins the asyncio side (client
    disconnect, server deadline) to the inflight ticket."""

    def __init__(self, sql: str, principal: str, priority: int = 0,
                 deadline_ms: float = 0.0, lookup=None,
                 traceparent: Optional[str] = None):
        import concurrent.futures
        #: fleet mode: the scoreboard CONC claim this request holds
        #: from admission until release/shed/flush (SlotToken)
        self.sb_token = None
        self.sql = sql
        self.label = " ".join(sql.split())[:60]
        self.principal = principal
        self.priority = int(priority)
        self.deadline_ms = float(deadline_ms)
        #: the client's W3C traceparent header, if it sent one — the
        #: worker links the query's trace to it (cross-process trees)
        self.traceparent = traceparent
        #: engine.BatchableLookup when the query may micro-batch
        self.lookup = lookup
        self.seq = next(_seq)
        self.t_enqueue = time.perf_counter()
        self.future: "concurrent.futures.Future" = \
            concurrent.futures.Future()
        self._lock = threading.Lock()
        self.cancel_reason: Optional[str] = None
        self.ticket = None

    # -- cancellation join (asyncio side calls these)
    def request_cancel(self, reason: str) -> None:
        """Flag the request; if a ticket is already attached the flag
        lands there too, so the running query raises at its next
        checkpoint (within one pipeline chunk)."""
        with self._lock:
            if self.cancel_reason is None:
                self.cancel_reason = reason
            ticket = self.ticket
        if ticket is not None:
            from ..obs.inflight import inflight
            inflight.cancel(ticket.query_id, reason)

    def attach_ticket(self, ticket) -> None:
        """Worker-side: bind the ticket ``SQLSession.sql`` registered
        (via ``obs.inflight.ticket_observer``).  Applies the
        per-request deadline and any cancel that raced registration."""
        with self._lock:
            self.ticket = ticket
            reason = self.cancel_reason
        if ticket is None:
            return
        # the shared session registers under its own principal; the
        # meter / audit / SLO feed must see the TENANT who sent this
        ticket.principal = self.principal
        if self.deadline_ms > 0:
            d = ticket._t0 + self.deadline_ms / 1e3
            ticket.deadline = d if ticket.deadline is None \
                else min(ticket.deadline, d)
        if reason is not None:
            ticket.request_cancel(reason)

    def resolve(self, status: int, body, outcome: str) -> None:
        """Deliver the response (idempotent — a shed racing a worker
        pick-up must not raise InvalidStateError)."""
        if not self.future.done():
            try:
                self.future.set_result((status, body, outcome))
            except Exception:
                pass

    def queued_ms(self) -> float:
        return (time.perf_counter() - self.t_enqueue) * 1e3


class AdmissionQueue:
    """Priority queue + quota book-keeping; every method thread-safe
    (callers: the asyncio loop thread offers, worker threads take)."""

    def __init__(self, depth: int, quota_concurrency: int,
                 quota_qps: float, scoreboard=None):
        self.depth = int(depth)
        self.quota_concurrency = int(quota_concurrency)
        self.quota_qps = float(quota_qps)
        #: fleet mode (serve/scoreboard.py): when set, the rate and
        #: concurrency quotas are enforced against the shared mmap
        #: scoreboard (atomic count-and-claim across every worker
        #: process) instead of this queue's process-local state
        self.scoreboard = scoreboard
        self._cond = threading.Condition()
        self._queued: List[ServeRequest] = []
        self._running: Dict[str, int] = collections.defaultdict(int)
        self._rate: Dict[str, Deque[float]] = \
            collections.defaultdict(collections.deque)
        self._admitted: Dict[str, int] = collections.defaultdict(int)
        self._shed: Dict[str, int] = collections.defaultdict(int)
        self.draining = False

    # -- admission -----------------------------------------------------
    def offer(self, req: ServeRequest,
              est_bytes: int = 0) -> Optional[Deny]:
        """Admit ``req`` (returns None) or refuse it (returns the
        :class:`Deny`; the request's future stays untouched so the
        caller writes the 429/503 itself)."""
        now = time.perf_counter()
        with self._cond:
            if self.draining:
                return self._deny(req, Deny(503, "draining", 1.0))
            if self.scoreboard is not None:
                deny = self._offer_scoreboard_locked(req)
                if deny is not None:
                    return deny
            else:
                win = self._rate[req.principal]
                while win and now - win[0] > _RATE_WINDOW_S:
                    win.popleft()
                if self.quota_qps > 0 and len(win) >= self.quota_qps:
                    return self._deny(req, Deny(
                        429, "rate_quota",
                        win[0] + _RATE_WINDOW_S - now))
                if self.quota_concurrency > 0:
                    held = self._running[req.principal] + \
                        sum(1 for r in self._queued
                            if r.principal == req.principal)
                    if held >= self.quota_concurrency:
                        return self._deny(req, Deny(
                            429, "concurrency_quota",
                            self._latency_hint(req.principal)))
            if est_bytes > 0:
                from ..obs.memwatch import mem_budget
                if not mem_budget.admit(est_bytes):
                    self._release_token(req)
                    return self._deny(req, Deny(429, "memory_budget",
                                                1.0))
            if len(self._queued) >= self.depth:
                victim = min(self._queued,
                             key=lambda r: (r.priority, -r.seq))
                if victim.priority >= req.priority:
                    self._release_token(req)
                    return self._shed_one(req, evicted=False)
                self._queued.remove(victim)
                self._shed_one(victim, evicted=True)
            self._queued.append(req)
            if self.scoreboard is None:
                self._rate[req.principal].append(now)
            self._admitted[req.principal] += 1
            self._cond.notify()
            if metrics.enabled:
                metrics.count("serve/admitted")
                metrics.gauge("serve/queue_depth",
                              float(len(self._queued)))
        return None

    def _offer_scoreboard_locked(self,
                                 req: ServeRequest) -> Optional[Deny]:
        """Fleet-wide admission: one atomic count-and-claim against
        the shared scoreboard.  On success the request carries the
        CONC token until release/shed/flush; a worker dying with it
        leaks nothing — the supervisor's reap (or the next admission
        for the tenant) frees dead-owner slots."""
        token, refused = self.scoreboard.admit(
            req.principal, self.quota_concurrency, self.quota_qps)
        if refused is not None:
            reason, retry_after = refused
            if reason == "concurrency_quota":
                retry_after = self._latency_hint(req.principal)
            status = 503 if reason == "scoreboard_full" else 429
            return self._deny(req, Deny(status, reason, retry_after))
        req.sb_token = token
        return None

    def _release_token(self, req: ServeRequest) -> None:
        """Give a held scoreboard claim back (idempotent)."""
        token, req.sb_token = req.sb_token, None
        if token is not None and self.scoreboard is not None:
            self.scoreboard.release(token)

    def _deny(self, req: ServeRequest, deny: Deny) -> Deny:
        if metrics.enabled:
            metrics.count("serve/denied")
            metrics.count(f"serve/denied_{deny.reason}")
        return deny

    def _shed_one(self, req: ServeRequest, evicted: bool) -> Deny:
        """Overload shed: count it, flight-record it, and — for an
        evicted queued request — resolve its future with the 429.
        Either way the victim's scoreboard claim goes back."""
        self._release_token(req)
        self._shed[req.principal] += 1
        deny = Deny(429, "shed", 1.0)
        if metrics.enabled:
            metrics.count("serve/shed")
            metrics.count(f"serve/shed/{req.principal}")
        recorder.record("serve_shed", principal=req.principal,
                        priority=req.priority, evicted=evicted,
                        sql=req.label)
        if evicted:
            req.resolve(deny.status, deny.payload(), "shed")
        return deny

    def _latency_hint(self, principal: str) -> float:
        """Retry-After for a concurrency deny: the tenant's own mean
        query latency (PrincipalMeter totals), clamped to [0.05, 5]s —
        heavier workloads are told to wait longer."""
        try:
            from ..obs.accounting import meter
            ms = meter.mean_wall_ms(principal)
            if ms is not None:
                return min(5.0, max(0.05, ms / 1e3))
        except Exception:
            pass
        return 0.1

    # -- worker side ---------------------------------------------------
    def take(self, timeout: float = 0.1) -> Optional[ServeRequest]:
        """Pop the highest-priority queued request (FIFO within a
        priority); None on timeout."""
        with self._cond:
            if not self._queued:
                self._cond.wait(timeout)
            if not self._queued:
                return None
            req = max(self._queued, key=lambda r: (r.priority, -r.seq))
            self._queued.remove(req)
            self._running[req.principal] += 1
            if metrics.enabled:
                metrics.gauge("serve/queue_depth",
                              float(len(self._queued)))
        return req

    def take_compatible(self, signature: tuple,
                        limit: int) -> List[ServeRequest]:
        """Pop up to ``limit`` queued point lookups sharing
        ``signature`` (arrival order) for one micro-batch launch."""
        if limit <= 0:
            return []
        out: List[ServeRequest] = []
        with self._cond:
            for r in sorted(self._queued, key=lambda r: r.seq):
                if r.lookup is not None and \
                        r.lookup.signature == signature and \
                        r.cancel_reason is None:
                    out.append(r)
                    if len(out) >= limit:
                        break
            for r in out:
                self._queued.remove(r)
                self._running[r.principal] += 1
            if out and metrics.enabled:
                metrics.gauge("serve/queue_depth",
                              float(len(self._queued)))
        return out

    def release(self, req: ServeRequest) -> None:
        """A worker finished (or abandoned) a taken request."""
        with self._cond:
            self._running[req.principal] = \
                max(0, self._running[req.principal] - 1)
            self._release_token(req)

    # -- drain + reads -------------------------------------------------
    def start_drain(self) -> None:
        with self._cond:
            self.draining = True

    def queued_count(self) -> int:
        with self._cond:
            return len(self._queued)

    def running_count(self) -> int:
        with self._cond:
            return sum(self._running.values())

    def flush(self, status: int, reason: str) -> int:
        """Resolve every still-queued request (drain deadline hit);
        returns how many were flushed."""
        with self._cond:
            pending, self._queued = self._queued, []
        for r in pending:
            self._release_token(r)
            r.resolve(status, {"error": "denied", "reason": reason,
                               "retry_after_s": 1.0}, reason)
        return len(pending)

    def snapshot(self) -> Dict[str, object]:
        """Per-principal queue state for ``/api/server``."""
        with self._cond:
            queued: Dict[str, int] = collections.defaultdict(int)
            for r in self._queued:
                queued[r.principal] += 1
            principals: Dict[str, Dict[str, int]] = {}
            for p in set(queued) | set(self._running) | \
                    set(self._admitted) | set(self._shed):
                principals[p] = {
                    "queued": queued.get(p, 0),
                    "running": self._running.get(p, 0),
                    "admitted": self._admitted.get(p, 0),
                    "shed": self._shed.get(p, 0),
                }
            return {"depth": self.depth,
                    "queued": len(self._queued),
                    "running": sum(self._running.values()),
                    "draining": self.draining,
                    "principals": principals}
