"""Micro-batching: several point-lookup queries, one device launch.

Small cell-id lookups dominate multi-tenant point workloads, and each
one alone wastes a device dispatch on a few thousand rows.  Queries
classified by :func:`~..sql.engine.classify_batchable` share a batch
signature ``(function, resolution)``; a worker that picks one up
drains every compatible queued request (``AdmissionQueue.
take_compatible``, bounded by ``mosaic.serve.batch.max``), concatenates
the member tables' coordinate columns, pads to the existing pow2
bucket (so batch-size jitter never recompiles), and runs ONE jitted
kernel from the shared warm cache.  Per-row math is elementwise
(``CustomIndexSystem.point_to_cell_jax`` and friends), so each
member's slice of the batched output is bit-identical to what its
query would have produced alone — the serial path (``batch.max=1``)
runs the very same kernel one query at a time, which is what the
parity + fewer-launches acceptance drill compares via the
:class:`~..obs.profiler.KernelLedger`.

Accounting stays per-query: every member gets its own
:class:`~..obs.inflight.QueryTicket` under a synthetic trace id (so
the shared launch's ledger charge does NOT auto-join any one member),
and the launch's device seconds / H2D / D2H bytes are split across
members by row share before each ticket completes through the normal
:func:`~..obs.accounting.complete` path — audit records, principal
meter, SLOs and the leak sentinel all see N queries, not one.
"""

from __future__ import annotations

import time
from typing import List

import numpy as np

from ..obs import metrics
from ..obs.accounting import complete as _complete
from ..obs.inflight import inflight
from ..perf.bucketing import pow2_bucket
from ..perf.jit_cache import kernel_cache
from ..perf.pipeline import staged_put
from ..sql.engine import Table
from .admission import ServeRequest

__all__ = ["execute_batch", "KERNEL_NAME"]

#: kernel-ledger / jit-cache name of the shared point-lookup kernel —
#: the loadtest and the parity drill count launches under this name
KERNEL_NAME = "serve/point_lookup"


def _member_tickets(members: List[ServeRequest]) -> list:
    """Open one ticket per member under a synthetic per-member trace
    id: tickets stay individually addressable (cancel-on-disconnect)
    while the batch launch itself runs traceless, so the kernel
    ledger's automatic trace join charges nobody twice — the split
    below is the only device-seconds feed."""
    tickets = []
    for m in members:
        t = inflight.register(m.label, principal=m.principal,
                              deadline_ms=m.deadline_ms,
                              trace_id=f"serve-batch:{m.seq}")
        if t is not None:
            t.strategies["serve"] = f"batched[{len(members)}]"
        m.attach_ticket(t)
        tickets.append(t)
    return tickets


def execute_batch(session, members: List[ServeRequest]) -> None:
    """Run one micro-batch (possibly of size 1) and resolve every
    member's future.  Members must share a batch signature."""
    lookup = members[0].lookup
    system = session.mc.index_system
    res = lookup.res
    tickets = _member_tickets(members)
    t0 = time.perf_counter()
    try:
        parts = []
        for m in members:
            table = session.table(m.lookup.table)
            parts.append(np.stack(
                [np.asarray(table.columns[m.lookup.lon], np.float64),
                 np.asarray(table.columns[m.lookup.lat], np.float64)],
                axis=-1))
        rows_list = [len(p) for p in parts]   # authoritative (the
        # catalog may have grown since classification froze .rows)
        xy = np.concatenate(parts, axis=0) if len(parts) > 1 \
            else parts[0]
        n = len(xy)
        bucket = pow2_bucket(n)
        if bucket > n:
            xy = np.concatenate(
                [xy, np.zeros((bucket - n, 2), np.float64)], axis=0)
        key = (getattr(system, "name", type(system).__name__),
               repr(getattr(system, "conf", None)), res, bucket)

        def _build():
            import jax
            return jax.jit(lambda a: system.point_to_cell_jax(a, res))

        kernel = kernel_cache.get_or_build(KERNEL_NAME, key, _build)
        dev, tok = staged_put(xy, site=f"{KERNEL_NAME}/staged")
        try:
            launch_t = time.perf_counter()
            cells = np.asarray(kernel(dev))[:n]     # blocks until done
            launch_s = time.perf_counter() - launch_t
        finally:
            from ..obs.memwatch import memwatch
            memwatch.release(tok)
        from ..obs.profiler import ledger
        ledger.observe(KERNEL_NAME, key, launch_s, rows=n)
        if metrics.enabled:
            metrics.count("serve/batches")
            metrics.count("serve/batched_queries", float(len(members)))
    except BaseException as exc:
        for m, t in zip(members, tickets):
            _complete(t, outcome="error", error=exc)
            m.resolve(500, {"error": f"{type(exc).__name__}: {exc}"},
                      "error")
        if metrics.enabled:
            metrics.count("serve/errors")
        return
    # split: per-member result slice + per-member cost share
    wall_ms = (time.perf_counter() - t0) * 1e3
    bytes_in = xy.nbytes
    bytes_out = cells.nbytes
    off = 0
    for m, t, rows in zip(members, tickets, rows_list):
        part = cells[off:off + rows]
        off += rows
        if t is not None:
            share = rows / max(1, n)
            t.device_s += launch_s * share
            t.h2d_bytes += int(bytes_in * share)
            t.d2h_bytes += int(bytes_out * share)
            t.rows_in = rows
            t.rows = rows
        if m.cancel_reason is not None or \
                (t is not None and t.cancel_requested):
            reason = m.cancel_reason or t._cancel_reason or "cancel"
            outcome = "deadline" if reason == "deadline" \
                else "cancelled"
            _complete(t, outcome=outcome, wall_ms=wall_ms)
            m.resolve(499 if outcome == "cancelled" else 504,
                      {"error": outcome}, outcome)
            continue
        table = session.table(m.lookup.table)
        cols = {}
        for name, src in m.lookup.outputs:
            cols[name] = part if src is None else table.columns[src]
        _complete(t, outcome="ok", wall_ms=wall_ms)
        m.resolve(200, Table(cols), "ok")
