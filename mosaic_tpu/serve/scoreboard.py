"""Fleet-wide admission state: an mmap-backed tenant scoreboard.

PR 15's :class:`~.admission.AdmissionQueue` keeps rate windows and
concurrency counts in process memory, which is exactly right for one
worker and exactly wrong for a fleet: N workers each enforcing a
per-tenant quota of Q admit N x Q.  The scoreboard moves that state
into one mmap'd file every worker opens, so quotas hold fleet-wide
and — the robustness half — a SIGKILLed worker *releases* its claims
instead of leaking them.

Design (all sizes fixed so readers can never mis-frame a record):

* one 64-byte header (magic, version, geometry, a monotone high-water
  mark of per-tenant concurrency observed at claim time — the kill
  drill's over-admission witness), then ``nslots`` 64-byte slots;
* a slot is ``seq | kind | owner pid | claim ts | tenant``; ``kind``
  is FREE / CONC (one queued-or-running query) / RATE (one admission
  in the 1 s sliding window);
* every mutation runs under ONE advisory ``fcntl.lockf`` region (plus
  an in-process ``threading.Lock`` — POSIX record locks do not
  exclude threads of the same process), so admit is an atomic
  count-and-claim: **over-admission is impossible by construction**,
  and because the kernel drops a dead process's locks, a worker dying
  inside the critical section cannot wedge the fleet;
* slot sequence numbers are a seqlock: a writer bumps ``seq`` odd,
  writes the record, bumps it even.  A slot left odd means its writer
  died mid-write; parsers treat it (and any unparseable bytes — the
  ``scoreboard.slot`` fault site corrupts reads in chaos tests) as
  invalid, count ``scoreboard/torn``, and the allocator reuses it —
  torn state degrades to a fresh slot, never a crash;
* CONC slots carry the owner pid; :meth:`reap` frees slots whose
  owner is gone (``os.kill(pid, 0)``).  Admission also self-heals: a
  tenant about to be denied on concurrency first reaps its own dead
  holders and recounts.  Under-admission is therefore bounded by the
  supervisor's reap interval (``mosaic.serve.fleet.reap.ms``), and by
  one denied request under load.

RATE slots expire out of the window by timestamp and are reclaimed by
the allocator; they need no owner liveness.
"""

from __future__ import annotations

import contextlib
import errno
import mmap
import os
import struct
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from ..obs import metrics
from ..resilience import faults

try:                                    # POSIX advisory record locks;
    import fcntl                        # the repo targets linux (CI +
except ImportError:                     # container), but keep imports
    fcntl = None                        # degradable for doc tooling

__all__ = ["Scoreboard", "ScoreboardError", "SlotToken",
           "RATE_WINDOW_S"]

#: rate-quota sliding window — must match admission._RATE_WINDOW_S
RATE_WINDOW_S = 1.0

_MAGIC = b"MSCB"
_VERSION = 1

#: header: magic 4s | version I | nslots I | slot_size I | created d |
#: high_water I (max per-tenant concurrency ever observed at claim)
_HEADER = struct.Struct("<4sIIIdI")
_HEADER_SIZE = 64

#: slot: seq I | kind B | pad 3x | pid I | ts d | tenant 44s
_SLOT = struct.Struct("<IBxxxId44s")
_SLOT_SIZE = 64
assert _SLOT.size == _SLOT_SIZE and _HEADER.size <= _HEADER_SIZE

_FREE, _CONC, _RATE = 0, 1, 2
_TENANT_BYTES = 44

#: default slot count when config carries none (import-order safety)
_DEFAULT_SLOTS = 512


class ScoreboardError(RuntimeError):
    """The scoreboard file is unusable (wrong magic/version/geometry).
    Raised at open time only — a live scoreboard degrades per-slot."""


class SlotToken:
    """One held concurrency claim: slot index + the seq stamped at
    claim time, so a stale release (the slot was reaped and reused)
    is detected instead of freeing someone else's claim."""

    __slots__ = ("index", "seq")

    def __init__(self, index: int, seq: int):
        self.index = index
        self.seq = seq

    def __repr__(self) -> str:          # pragma: no cover - debug aid
        return f"SlotToken(index={self.index}, seq={self.seq})"


def _pid_alive(pid: int) -> bool:
    """Liveness probe for a slot owner.  Signal 0 delivers nothing;
    EPERM means the pid exists under another uid — alive."""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return True                     # unknown: do not reap
    return True


class Scoreboard:
    """Shared per-tenant admission ledger over one mmap'd file.

    Thread-safe and process-safe: every mutation (and every counting
    read that feeds an admit decision) runs under the in-process lock
    plus the advisory file lock.  ``snapshot()`` is read-only but
    takes the same locks — the file is tiny (64 KiB at the default
    512 slots) and admission latency is dominated by the query, not
    this scan.
    """

    def __init__(self, path: str, slots: Optional[int] = None,
                 reap_ms: Optional[float] = None):
        from .. import config as _config
        cfg = _config.default_config()
        self.path = path
        self.nslots = int(slots if slots is not None else getattr(
            cfg, "serve_scoreboard_slots", _DEFAULT_SLOTS))
        if self.nslots <= 0:
            raise ScoreboardError("scoreboard needs at least one slot")
        self.reap_ms = float(reap_ms if reap_ms is not None else
                             getattr(cfg, "serve_fleet_reap_ms",
                                     1_000.0))
        self._lock = threading.Lock()
        self._fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        self._mm: Optional[mmap.mmap] = None
        try:
            with self._flock():
                self._init_or_attach_locked()
        except Exception:
            os.close(self._fd)
            raise

    # -- file lifecycle ------------------------------------------------
    def _init_or_attach_locked(self) -> None:
        """Called under the file lock: first opener writes the header
        and zeroed slots; later openers validate geometry (a mismatch
        means two configs disagree about the same path — refuse)."""
        size = _HEADER_SIZE + self.nslots * _SLOT_SIZE
        st = os.fstat(self._fd)
        if st.st_size == 0:
            os.ftruncate(self._fd, size)
            os.pwrite(self._fd, _HEADER.pack(
                _MAGIC, _VERSION, self.nslots, _SLOT_SIZE,
                time.time(), 0), 0)
        else:
            head = os.pread(self._fd, _HEADER.size, 0)
            if len(head) < _HEADER.size:
                raise ScoreboardError(
                    f"scoreboard {self.path}: truncated header")
            magic, ver, nslots, ssize, _, _ = _HEADER.unpack(head)
            if magic != _MAGIC or ver != _VERSION \
                    or ssize != _SLOT_SIZE:
                raise ScoreboardError(
                    f"scoreboard {self.path}: bad magic/version "
                    f"({magic!r} v{ver} slot {ssize})")
            self.nslots = nslots
            size = _HEADER_SIZE + nslots * _SLOT_SIZE
            if st.st_size < size:
                raise ScoreboardError(
                    f"scoreboard {self.path}: file shorter than its "
                    f"declared geometry")
        self._mm = mmap.mmap(self._fd, size)

    def close(self) -> None:
        with self._lock:
            if self._mm is not None:
                self._mm.close()
                self._mm = None
            if self._fd >= 0:
                os.close(self._fd)
                self._fd = -1

    def __enter__(self) -> "Scoreboard":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- locking -------------------------------------------------------
    @contextlib.contextmanager
    def _flock(self) -> Iterator[None]:
        """The cross-process critical section.  The kernel releases
        record locks when the holder dies, so a worker SIGKILLed here
        cannot deadlock the fleet."""
        if fcntl is None:               # pragma: no cover - non-posix
            yield
            return
        while True:
            try:
                fcntl.lockf(self._fd, fcntl.LOCK_EX, 1)
                break
            except OSError as e:        # pragma: no cover - rare
                if e.errno != errno.EINTR:
                    raise
        try:
            yield
        finally:
            try:
                fcntl.lockf(self._fd, fcntl.LOCK_UN, 1)
            except OSError:             # pragma: no cover - teardown
                pass

    # -- slot codec ----------------------------------------------------
    def _slot_off(self, i: int) -> int:
        return _HEADER_SIZE + i * _SLOT_SIZE

    def _read_slot_locked(self, i: int
                          ) -> Optional[Tuple[int, int, int, float,
                                              bytes]]:
        """Parse slot ``i`` -> (seq, kind, pid, ts, tenant) or None
        when the bytes are torn (odd seq, bad kind, undecodable).
        Routes the raw bytes through the ``scoreboard.slot`` fault
        site so chaos tests can tear any read deterministically."""
        raw = self._mm[self._slot_off(i):self._slot_off(i) + _SLOT_SIZE]
        raw = faults.corrupt("scoreboard.slot", raw)
        try:
            seq, kind, pid, ts, tenant = _SLOT.unpack(raw)
        except struct.error:
            metrics.count("scoreboard/torn")
            return None
        if seq % 2 or kind not in (_FREE, _CONC, _RATE):
            metrics.count("scoreboard/torn")
            return None
        return seq, kind, pid, ts, tenant.rstrip(b"\0")

    def _write_slot_locked(self, i: int, kind: int, pid: int,
                           ts: float, tenant: bytes,
                           prev_seq: int) -> int:
        """Seqlock write: odd (in progress) -> record -> even.  Only
        ever called under both locks; the odd intermediate exists so a
        writer dying mid-write leaves a self-describing torn slot."""
        off = self._slot_off(i)
        odd = (prev_seq + 1) | 1
        struct.pack_into("<I", self._mm, off, odd & 0xFFFFFFFF)
        new_seq = (odd + 1) & 0xFFFFFFFF
        self._mm[off:off + _SLOT_SIZE] = _SLOT.pack(
            new_seq, kind, pid, ts,
            tenant[:_TENANT_BYTES].ljust(_TENANT_BYTES, b"\0"))
        return new_seq

    def _free_slot_locked(self, i: int, prev_seq: int) -> None:
        self._write_slot_locked(i, _FREE, 0, 0.0, b"", prev_seq)

    # -- header helpers ------------------------------------------------
    def _high_water_locked(self) -> int:
        try:
            return _HEADER.unpack(
                bytes(self._mm[:_HEADER.size]))[5]
        except struct.error:            # pragma: no cover - torn header
            return 0

    def _bump_high_water_locked(self, conc: int) -> None:
        if conc > self._high_water_locked():
            struct.pack_into("<I", self._mm, _HEADER.size - 4, conc)

    # -- core scan -----------------------------------------------------
    def _scan_locked(self, now: float):
        """One pass over every slot -> (per-tenant conc list, rate
        list, free indices).  Torn slots land in ``free`` (we hold the
        lock, so no live writer can own them)."""
        conc: Dict[bytes, List[Tuple[int, int, int]]] = {}
        rate: Dict[bytes, List[Tuple[int, float]]] = {}
        free: List[Tuple[int, int]] = []
        for i in range(self.nslots):
            parsed = self._read_slot_locked(i)
            if parsed is None:
                free.append((i, 0))     # torn: reuse, seq restarts
                continue
            seq, kind, pid, ts, tenant = parsed
            if kind == _FREE:
                free.append((i, seq))
            elif kind == _CONC:
                conc.setdefault(tenant, []).append((i, seq, pid))
            else:                       # RATE: expired == free
                if now - ts <= RATE_WINDOW_S:
                    rate.setdefault(tenant, []).append((i, ts))
                else:
                    free.append((i, seq))
        return conc, rate, free

    # -- public API ----------------------------------------------------
    def admit(self, tenant: str, quota_concurrency: int,
              quota_qps: float, now: Optional[float] = None
              ) -> Tuple[Optional[SlotToken],
                         Optional[Tuple[str, float]]]:
        """Atomic count-and-claim for one request.

        Returns ``(token, None)`` on admission — the token holds the
        CONC slot until :meth:`release` — or ``(None, (reason,
        retry_after_s))`` on refusal, with the same reason strings the
        in-process queue uses (``rate_quota`` / ``concurrency_quota``)
        plus ``scoreboard_full`` when no slot is free.
        """
        now = time.time() if now is None else now
        tb = tenant.encode("utf-8", "replace")[:_TENANT_BYTES]
        with self._lock, self._flock():
            conc, rate, free = self._scan_locked(now)
            tr = rate.get(tb, [])
            if quota_qps > 0 and len(tr) >= quota_qps:
                oldest = min(ts for _, ts in tr)
                return None, ("rate_quota",
                              max(0.05, oldest + RATE_WINDOW_S - now))
            holders = conc.get(tb, [])
            if quota_concurrency > 0 \
                    and len(holders) >= quota_concurrency:
                # self-heal before refusing: a dead holder's claim
                # must not deny a live tenant for a full reap interval
                live = []
                for i, seq, pid in holders:
                    if _pid_alive(pid):
                        live.append((i, seq, pid))
                    else:
                        self._free_slot_locked(i, seq)
                        free.append((i, seq + 2))
                        metrics.count("scoreboard/reaped")
                holders = live
                if len(holders) >= quota_concurrency:
                    return None, ("concurrency_quota", 0.1)
            need = 1 + (1 if quota_qps > 0 else 0)
            if len(free) < need:
                metrics.count("scoreboard/full")
                return None, ("scoreboard_full", 1.0)
            i, seq = free[0]
            new_seq = self._write_slot_locked(i, _CONC, os.getpid(),
                                              now, tb, seq)
            if quota_qps > 0:
                j, jseq = free[1]
                self._write_slot_locked(j, _RATE, os.getpid(), now,
                                        tb, jseq)
            self._bump_high_water_locked(len(holders) + 1)
            metrics.count("scoreboard/admits")
            return SlotToken(i, new_seq), None

    def release(self, token: Optional[SlotToken]) -> bool:
        """Free a held CONC slot.  A stale token (the slot was reaped
        and reused after its owner was presumed dead) is refused with
        a counter, never corrupts the new holder's claim."""
        if token is None:
            return False
        with self._lock, self._flock():
            parsed = self._read_slot_locked(token.index)
            if parsed is None:
                return False
            seq, kind, pid, _, _ = parsed
            if kind != _CONC or seq != token.seq:
                metrics.count("scoreboard/release_stale")
                return False
            self._free_slot_locked(token.index, seq)
            return True

    def reap(self, now: Optional[float] = None) -> int:
        """Free CONC slots whose owner pid is gone (plus expired RATE
        slots and torn slots); returns the number of dead-owner claims
        reclaimed.  The supervisor calls this on its health tick, so
        under-admission after a worker SIGKILL is bounded by
        ``mosaic.serve.fleet.reap.ms``."""
        now = time.time() if now is None else now
        reaped = 0
        with self._lock, self._flock():
            for i in range(self.nslots):
                parsed = self._read_slot_locked(i)
                if parsed is None:
                    self._free_slot_locked(i, 0)
                    continue
                seq, kind, pid, ts, _ = parsed
                if kind == _CONC and not _pid_alive(pid):
                    self._free_slot_locked(i, seq)
                    reaped += 1
                elif kind == _RATE and now - ts > RATE_WINDOW_S:
                    self._free_slot_locked(i, seq)
        if reaped:
            metrics.count("scoreboard/reaped", reaped)
        return reaped

    def counts(self, tenant: str, now: Optional[float] = None
               ) -> Dict[str, int]:
        """Live claim counts for one tenant (dead owners included —
        call :meth:`reap` first for the healed view)."""
        now = time.time() if now is None else now
        tb = tenant.encode("utf-8", "replace")[:_TENANT_BYTES]
        with self._lock, self._flock():
            conc, rate, _ = self._scan_locked(now)
            return {"concurrency": len(conc.get(tb, [])),
                    "rate": len(rate.get(tb, []))}

    def high_water(self) -> int:
        """Max per-tenant concurrency ever observed at claim time —
        the over-admission witness the kill drill asserts on."""
        with self._lock, self._flock():
            return self._high_water_locked()

    def snapshot(self, now: Optional[float] = None
                 ) -> Dict[str, object]:
        """Aggregate view for /stats and supervisor.json."""
        now = time.time() if now is None else now
        with self._lock, self._flock():
            conc, rate, free = self._scan_locked(now)
            tenants = sorted({t.decode("utf-8", "replace")
                              for t in (set(conc) | set(rate))})
            return {
                "path": self.path,
                "slots": self.nslots,
                "free": len(free),
                "high_water": self._high_water_locked(),
                "tenants": {
                    t: {"concurrency":
                        len(conc.get(t.encode(), [])),
                        "rate": len(rate.get(t.encode(), []))}
                    for t in tenants},
            }
