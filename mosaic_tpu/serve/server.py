"""The long-lived multi-tenant query frontend over one SQLSession.

Hand-rolled HTTP/1.1 on asyncio streams (stdlib only — no http.server
thread-per-connection, no external framework): an event loop in a
background thread accepts connections, admits queries through
:class:`~.admission.AdmissionQueue`, and hands them to the shared
:class:`~.workers.WorkerPool`.  The asyncio side owns everything a
socket can tell us that a worker can't: a client that disconnects
mid-query (stream EOF) and a request that outlives its deadline both
flow into the request's cancel plumbing → ``inflight.cancel`` → the
running query raises at its next checkpoint, within one pipeline
chunk.  Overload degrades, never dies: quota and budget denies answer
429 with Retry-After, a full queue sheds lowest-priority principals
first, and SIGTERM (opt-in :func:`install_sigterm_drain`) drains with
a deadline — stop accepting, let in-flight work finish, then cancel
stragglers — instead of dropping connections on the floor.

Endpoints::

    POST /query     body = SQL text (or JSON {"sql": ...})
                    headers: X-Mosaic-Principal, X-Mosaic-Priority,
                             X-Mosaic-Deadline-Ms
                    200 JSON-lines stream | 400 | 429(+Retry-After) |
                    499 client closed | 503 draining | 504 deadline
    GET  /healthz   liveness + queue/worker gauges
    GET  /stats     the same payload the dashboard's /api/server shows
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import threading
import time
import weakref
from typing import Dict, Optional, Tuple

import numpy as np

from .. import config as _config
from ..obs import metrics
from ..obs.recorder import recorder
from ..obs.timeseries import timeseries
from ..resilience import faults
from ..resilience.faults import InjectedFault
from ..sql.engine import SQLSession, Table, classify_batchable
from .admission import AdmissionQueue, ServeRequest
from .workers import WorkerPool

__all__ = ["QueryServer", "current_server", "install_sigterm_drain"]

#: rows per JSON-lines response chunk — small enough that a torn
#: connection surfaces within one write, large enough to amortize
#: serialization
_RESPONSE_CHUNK_ROWS = 8_192

_MAX_HEADER_BYTES = 65_536

#: the live server (weakly held) the dashboard's /api/server reads
_current: "Optional[weakref.ref]" = None


def current_server() -> "Optional[QueryServer]":
    return _current() if _current is not None else None


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    return str(o)


def _column_cell(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (bool, int, float, str)) or v is None:
        return v
    return str(v)


class QueryServer:
    """One server = one session, one admission queue, one worker pool,
    one background asyncio loop.  Context manager: ``with
    QueryServer(session) as srv: ...`` serves until exit."""

    def __init__(self, session: SQLSession,
                 host: str = "127.0.0.1",
                 port: Optional[int] = None,
                 workers: Optional[int] = None,
                 sock=None,
                 reuse_port: bool = False,
                 scoreboard=None):
        cfg = _config.default_config()
        self.session = session
        self.host = host
        self._want_port = cfg.serve_port if port is None else int(port)
        self.port: int = 0
        #: fleet mode (serve/supervisor.py): either an already-bound
        #: listening socket inherited from the supervisor, or
        #: SO_REUSEPORT so N worker processes share one (host, port)
        self._sock = sock
        self._reuse_port = bool(reuse_port)
        #: shared mmap Scoreboard — when set, per-tenant rate +
        #: concurrency quotas are enforced fleet-wide
        self.scoreboard = scoreboard
        self.queue = AdmissionQueue(
            depth=cfg.serve_queue_depth,
            quota_concurrency=cfg.serve_quota_concurrency,
            quota_qps=cfg.serve_quota_qps,
            scoreboard=scoreboard)
        self.pool = WorkerPool(
            session, self.queue,
            workers=cfg.serve_workers if workers is None else workers,
            batch_max=cfg.serve_batch_max,
            batch_window_ms=cfg.serve_batch_window_ms)
        self._default_deadline_ms = cfg.serve_deadline_ms
        self._drain_ms = cfg.serve_drain_ms
        self._batch_rows_max = cfg.serve_batch_rows_max
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._ready = threading.Event()
        self._stopped = threading.Event()
        self.draining = False
        self._sigterm_prev = None
        self.t_start = 0.0

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "QueryServer":
        if self._thread is not None:
            return self
        self.t_start = time.time()
        self.pool.start()
        self._thread = threading.Thread(target=self._loop_main,
                                        daemon=True,
                                        name="mosaic-serve-loop")
        self._thread.start()
        if not self._ready.wait(10.0):
            raise RuntimeError("query server failed to start listening")
        from ..obs.slo import monitor, serve_objectives
        for obj in serve_objectives(self.queue.depth):
            monitor.add_objective(obj)
        global _current
        _current = weakref.ref(self)
        return self

    def stop(self, drain: bool = False) -> None:
        """Stop serving.  ``drain=True`` runs the graceful SIGTERM
        path first (finish in-flight work until ``mosaic.serve.
        drain.ms``); plain stop just closes and joins."""
        if drain:
            self.initiate_drain()
            self.await_drained(self._drain_ms / 1e3)
        loop = self._loop
        if loop is not None and not loop.is_closed():
            loop.call_soon_threadsafe(self._shutdown_loop)
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        self.queue.flush(503, "shutdown")
        self.pool.stop()
        if self._sigterm_prev is not None:
            try:
                signal.signal(signal.SIGTERM, self._sigterm_prev)
            except (ValueError, OSError):
                pass
            self._sigterm_prev = None
        global _current
        if _current is not None and _current() is self:
            _current = None

    def __enter__(self) -> "QueryServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- drain-on-SIGTERM ----------------------------------------------
    def initiate_drain(self) -> None:
        """Flip into drain mode: new queries answer 503, queued +
        running ones keep going until the drain deadline."""
        if self.draining:
            return
        self.draining = True
        self.queue.start_drain()
        recorder.record("serve_drain",
                        queued=self.queue.queued_count(),
                        running=self.queue.running_count(),
                        deadline_ms=self._drain_ms)
        if metrics.enabled:
            metrics.count("serve/drains")

    def await_drained(self, timeout_s: float) -> bool:
        """Wait for queue + workers to empty; past the deadline,
        cancel whatever still runs (reason ``drain`` → cooperative
        stop within one chunk) and flush the queue with 503s."""
        deadline = time.perf_counter() + max(0.0, timeout_s)
        while time.perf_counter() < deadline:
            if self.queue.queued_count() == 0 and self.pool.idle():
                return True
            time.sleep(0.02)
        from ..obs.inflight import inflight
        for snap in inflight.list_active():
            inflight.cancel(snap["query_id"], "drain")
        self.queue.flush(503, "draining")
        return False

    def _on_sigterm(self, signum, frame) -> None:
        # signal handlers must return fast: run the drain elsewhere
        threading.Thread(target=self.stop, kwargs={"drain": True},
                         daemon=True,
                         name="mosaic-serve-drain").start()

    def install_sigterm_drain(self) -> None:
        """Route SIGTERM into drain-then-stop (main thread only —
        CPython restricts ``signal.signal``)."""
        self._sigterm_prev = signal.signal(signal.SIGTERM,
                                           self._on_sigterm)

    def wait_stopped(self, timeout: Optional[float] = None) -> bool:
        """Block until the serve loop exited (drain finished or plain
        stop); fleet workers park their main thread here.  True when
        it stopped within ``timeout``."""
        return self._stopped.wait(timeout)

    # -- asyncio side --------------------------------------------------
    def _loop_main(self) -> None:
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        try:
            loop.run_until_complete(self._serve_forever())
        finally:
            try:
                loop.run_until_complete(loop.shutdown_asyncgens())
            except Exception:
                pass
            loop.close()
            self._stopped.set()

    async def _serve_forever(self) -> None:
        if self._sock is not None:
            self._server = await asyncio.start_server(
                self._handle_conn, sock=self._sock)
        elif self._reuse_port:
            self._server = await asyncio.start_server(
                self._handle_conn, self.host, self._want_port,
                reuse_port=True)
        else:
            self._server = await asyncio.start_server(
                self._handle_conn, self.host, self._want_port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._ready.set()
        try:
            await self._server.serve_forever()
        except asyncio.CancelledError:
            pass

    def _shutdown_loop(self) -> None:
        if self._server is not None:
            self._server.close()
        for task in asyncio.all_tasks(self._loop):
            task.cancel()

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            faults.maybe_fail("serve.accept")
        except InjectedFault:
            # degrade, don't die: this connection is refused with a
            # retryable 503, the listener keeps accepting
            if metrics.enabled:
                metrics.count("serve/accept_errors")
            await self._respond_json(
                writer, 503, {"error": "accept fault injected",
                              "retry_after_s": 0.1},
                extra=[("Retry-After", "1")])
            await self._close(writer)
            return
        if metrics.enabled:
            metrics.count("serve/connections")
        try:
            while True:
                parsed = await self._read_request(reader)
                if parsed is None:
                    break
                method, target, headers, body = parsed
                keep = await self._route(reader, writer, method,
                                         target, headers, body)
                if not keep:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            if metrics.enabled:
                metrics.count("serve/conn_errors")
        except asyncio.CancelledError:
            raise
        except Exception:
            if metrics.enabled:
                metrics.count("serve/conn_errors")
        finally:
            await self._close(writer)

    @staticmethod
    async def _close(writer: asyncio.StreamWriter) -> None:
        try:
            writer.close()
            await writer.wait_closed()
        except Exception:
            pass

    async def _read_request(self, reader: asyncio.StreamReader
                            ) -> Optional[Tuple[str, str,
                                                Dict[str, str], bytes]]:
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except (asyncio.IncompleteReadError, ConnectionError):
            return None
        except asyncio.LimitOverrunError:
            return None
        if len(head) > _MAX_HEADER_BYTES:
            return None
        lines = head.decode("latin-1").split("\r\n")
        parts = lines[0].split()
        if len(parts) < 3:
            return None
        method, target = parts[0].upper(), parts[1]
        headers: Dict[str, str] = {}
        for line in lines[1:]:
            if ":" in line:
                k, v = line.split(":", 1)
                headers[k.strip().lower()] = v.strip()
        body = b""
        n = int(headers.get("content-length", "0") or 0)
        if n > 0:
            body = await reader.readexactly(n)
        return method, target, headers, body

    async def _route(self, reader, writer, method: str, target: str,
                     headers: Dict[str, str], body: bytes) -> bool:
        keep = headers.get("connection", "").lower() != "close"
        if method == "GET" and target == "/healthz":
            await self._respond_json(writer, 200, {
                "status": "draining" if self.draining else "ok",
                "pid": os.getpid(),
                "queued": self.queue.queued_count(),
                "running": self.queue.running_count(),
                "workers": self.pool.workers}, keep=keep)
            return keep
        if method == "GET" and target == "/stats":
            await self._respond_json(writer, 200, self.stats(),
                                     keep=keep)
            return keep
        if method == "POST" and target == "/query":
            await self._handle_query(reader, writer, headers, body)
            return False            # /query always closes (streamed)
        await self._respond_json(writer, 404,
                                 {"error": f"no route {target}"},
                                 keep=keep)
        return keep

    # -- the query path ------------------------------------------------
    def _parse_query_body(self, headers: Dict[str, str],
                          body: bytes) -> str:
        text = body.decode("utf-8", "replace")
        if "json" in headers.get("content-type", ""):
            obj = json.loads(text)
            return str(obj["sql"])
        return text

    def _est_bytes(self, sql: str) -> int:
        """The planner's byte pre-pass for memory admission; 0 when
        the query can't be planned (it will fail in the worker with a
        proper 400 instead)."""
        try:
            from ..sql.parser import parse
            from ..sql.planner import planner
            if not planner.enabled:
                return 0
            plan = planner.plan_query(parse(sql), self.session)
            return plan.est_bytes_peak() if plan is not None else 0
        except Exception:
            return 0

    async def _handle_query(self, reader, writer,
                            headers: Dict[str, str],
                            body: bytes) -> None:
        t0 = time.perf_counter()
        if metrics.enabled:
            metrics.count("serve/requests")
        try:
            sql = self._parse_query_body(headers, body)
        except Exception as exc:
            await self._respond_json(
                writer, 400, {"error": f"bad request body: {exc}"})
            return
        principal = headers.get("x-mosaic-principal", "").strip() \
            or "anonymous"
        try:
            priority = int(headers.get("x-mosaic-priority", "0"))
        except ValueError:
            priority = 0
        try:
            deadline_ms = float(headers.get("x-mosaic-deadline-ms",
                                            self._default_deadline_ms))
        except ValueError:
            deadline_ms = self._default_deadline_ms
        lookup = classify_batchable(sql, self.session,
                                    max_rows=self._batch_rows_max) \
            if self.pool.batch_max > 0 else None
        # W3C cross-process trace propagation: a malformed header is
        # ignored per spec (the request still runs, unlinked)
        traceparent = headers.get("traceparent", "").strip() or None
        req = ServeRequest(sql, principal, priority=priority,
                           deadline_ms=deadline_ms, lookup=lookup,
                           traceparent=traceparent)
        deny = self.queue.offer(req, est_bytes=self._est_bytes(sql))
        if deny is not None:
            await self._respond_json(
                writer, deny.status, deny.payload(),
                extra=[("Retry-After",
                        str(max(1, int(round(deny.retry_after)))))])
            self._observe_request(principal, "denied:" + deny.reason,
                                  t0)
            return
        status, payload, outcome = await self._await_result(
            reader, req, deadline_ms)
        if status is None:
            # client vanished; the worker (or queue flush) still
            # resolves the future and the ticket books close — there
            # is just nobody left to write to
            self._observe_request(principal, outcome or "disconnect",
                                  t0)
            return
        trace_headers = self._trace_headers(req)
        if isinstance(payload, Table):
            await self._stream_table(writer, payload,
                                     extra=trace_headers)
        else:
            await self._respond_json(writer, status, payload,
                                     extra=trace_headers)
        self._observe_request(principal, outcome, t0)

    @staticmethod
    def _trace_headers(req: ServeRequest):
        """Response trace headers for one served query: the W3C
        ``traceparent`` (the client's trace id when it sent one, else
        one derived from the worker's local trace; the span id is this
        server's own — derived exactly the way the worker derives it,
        so client-side logs and the fleet bundle name the same span)
        plus ``X-Mosaic-Trace`` with the worker-local trace id the
        flight recorder / dashboard key off."""
        ticket = req.ticket
        local = getattr(ticket, "trace_id", None) if ticket else None
        if not local:
            return None
        from ..obs.context import (TraceContext, make_traceparent,
                                   parse_traceparent)
        link = parse_traceparent(req.traceparent)
        hdr = make_traceparent(TraceContext(
            trace_id=local, name=req.label,
            w3c_trace=link[0] if link else None,
            w3c_parent=link[1] if link else None))
        return [("traceparent", hdr), ("X-Mosaic-Trace", local)]

    def _observe_request(self, principal: str, outcome: str,
                         t0: float) -> None:
        dt_ms = (time.perf_counter() - t0) * 1e3
        if metrics.enabled:
            metrics.observe("serve/request_ms", dt_ms)
            metrics.count(f"serve/outcome_{outcome.split(':')[0]}")
        timeseries.record("serve/request_ms", dt_ms)
        # feed the saturation SLO (gauge_max reads the series store)
        timeseries.record("serve/queue_depth",
                          float(self.queue.queued_count()))

    async def _await_result(self, reader, req: ServeRequest,
                            deadline_ms: float):
        """Wait for the worker's result while watching the socket for
        client disconnect and the clock for the request deadline —
        both flow into the request's cancel plumbing.  Returns
        ``(status, payload, outcome)``; status None means the client
        is gone."""
        loop = asyncio.get_running_loop()
        result_f = asyncio.wrap_future(req.future, loop=loop)
        watch = asyncio.ensure_future(reader.read(1))
        timeout = deadline_ms / 1e3 + 1.0 if deadline_ms > 0 else None
        disconnect = False
        try:
            while True:
                done, _ = await asyncio.wait(
                    {result_f, watch},
                    timeout=timeout,
                    return_when=asyncio.FIRST_COMPLETED)
                if result_f in done:
                    break
                if watch in done:
                    data = watch.result()
                    if not data:          # EOF: the client hung up
                        disconnect = True
                        if metrics.enabled:
                            metrics.count("serve/disconnects")
                        req.request_cancel("disconnect")
                        await result_f    # cooperative: ≤ one chunk
                        break
                    # stray pipelined bytes — ignore, keep waiting
                    watch = asyncio.ensure_future(reader.read(1))
                    continue
                # timeout: enforce the deadline even for queued work
                req.request_cancel("deadline")
                timeout = None
        finally:
            if not watch.done():
                watch.cancel()
        status, payload, outcome = result_f.result()
        if disconnect:
            return None, None, outcome
        return status, payload, outcome

    # -- response writing ----------------------------------------------
    async def _respond_json(self, writer, code: int, payload,
                            extra=None, keep: bool = False) -> None:
        body = json.dumps(payload, default=_json_default,
                          sort_keys=True).encode()
        await self._write_head(writer, code, "application/json",
                               len(body), extra, keep)
        writer.write(body)
        await writer.drain()

    async def _stream_table(self, writer, table: Table,
                            extra=None) -> None:
        """200 + JSON-lines: a header object, then row chunks.  Each
        chunk drains the socket, so a torn connection surfaces (and
        stops the serialization work) within one chunk."""
        names = list(table.columns)
        head = json.dumps({"columns": names, "rows": len(table)},
                          default=_json_default).encode() + b"\n"
        await self._write_head(writer, 200, "application/jsonl",
                               None, extra, False)
        writer.write(head)
        try:
            cols = [table.columns[n] for n in names]
            for s in range(0, max(1, len(table)),
                           _RESPONSE_CHUNK_ROWS):
                rows = []
                hi = min(len(table), s + _RESPONSE_CHUNK_ROWS)
                for i in range(s, hi):
                    rows.append([_column_cell(c[i]) for c in cols])
                writer.write(json.dumps(rows).encode() + b"\n")
                await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            # torn mid-response: the query already completed; count it
            # and let the connection close — nothing leaks (buffers
            # were host-side rows, tickets are long closed)
            if metrics.enabled:
                metrics.count("serve/response_errors")
            raise

    @staticmethod
    async def _write_head(writer, code: int, ctype: str,
                          length: Optional[int], extra,
                          keep: bool) -> None:
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  429: "Too Many Requests", 499: "Client Closed",
                  500: "Internal Server Error",
                  503: "Service Unavailable",
                  504: "Gateway Timeout"}.get(code, "Status")
        lines = [f"HTTP/1.1 {code} {reason}",
                 f"Content-Type: {ctype}",
                 "Cache-Control: no-store"]
        if length is not None:
            lines.append(f"Content-Length: {length}")
        lines.append("Connection: keep-alive" if keep
                     else "Connection: close")
        for k, v in (extra or []):
            lines.append(f"{k}: {v}")
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode())
        await writer.drain()

    # -- introspection -------------------------------------------------
    def stats(self) -> Dict[str, object]:
        """The /api/server payload: queue + quotas + workers +
        counters (the dashboard's server panel polls this)."""
        q = self.queue.snapshot()
        counters = {}
        for name in ("serve/requests", "serve/admitted", "serve/shed",
                     "serve/denied", "serve/batches",
                     "serve/batched_queries", "serve/disconnects",
                     "serve/errors", "serve/dispatch_errors",
                     "serve/accept_errors", "serve/drains"):
            v = metrics.counter_value(name)
            if v:
                counters[name.split("/", 1)[1]] = int(v)
        out = {
            "running": True,
            "pid": os.getpid(),
            "addr": f"{self.host}:{self.port}",
            "draining": self.draining,
            "uptime_s": round(time.time() - self.t_start, 1)
            if self.t_start else 0.0,
            "workers": {"total": self.pool.workers,
                        "busy": self.pool.busy,
                        "utilization": round(
                            self.pool.busy / max(1, self.pool.workers),
                            3)},
            "queue": q,
            "quotas": {"concurrency": self.queue.quota_concurrency,
                       "qps": self.queue.quota_qps,
                       "queue_depth": self.queue.depth,
                       "scope": "fleet" if self.scoreboard is not None
                       else "process"},
            "batching": {"max": self.pool.batch_max,
                         "window_ms": self.pool.batch_window_ms},
            # warm-fleet proof: a respawned worker over a shared
            # persistent XLA cache must show persistent_misses == 0
            "jit": {"persistent_hits": int(
                        metrics.counter_value("jax/cache/cache_hits")),
                    "persistent_misses": int(
                        metrics.counter_value(
                            "jax/cache/cache_misses"))},
            "counters": counters,
        }
        if self.scoreboard is not None:
            try:
                out["scoreboard"] = self.scoreboard.snapshot()
            except (OSError, ValueError):
                out["scoreboard"] = None
        return out


def install_sigterm_drain(server: QueryServer) -> None:
    """Module-level convenience mirroring the method (docs + __main__
    style usage: ``install_sigterm_drain(srv)``)."""
    server.install_sigterm_drain()
