"""Supervised serving fleet: N crash-recovering worker processes.

ROADMAP item 1's scheduler/executor split (LocationSpark, arxiv
1907.03736) at the process level: :class:`ServeFleet` spawns N worker
processes, each running its own :class:`~.server.QueryServer` +
``SQLSession`` on a **shared listening socket** — ``SO_REUSEPORT``
where the kernel supports it (per-connection load balancing, each
worker owns its accept queue), else one parent-bound socket inherited
through ``pass_fds`` (shared accept queue).  All workers point at one
persistent XLA compile cache, so a warm fleet performs zero backend
compiles (``jax/cache/cache_misses == 0`` in each worker's spool is
the proof the kill drill asserts).

Robustness contract — the fleet degrades, never dies:

* the supervisor health-checks children every ``mosaic.serve.fleet.
  health.ms``: ``Popen.poll`` liveness, a ``/healthz`` probe on the
  shared port, and spool-mtime staleness (``obs/spool.py`` heartbeat;
  a hung worker is SIGKILLed and treated as a crash);
* a crashed worker respawns through ``resilience.RetryPolicy``
  backoff (``FLEET_RESPAWN_BACKOFF`` schedules the delay, the
  ``serve.spawn`` fault site + ``SERVE_SPAWN_RETRY`` cover exec
  failures); K respawns inside ``mosaic.serve.fleet.restart.window.
  ms`` trips the circuit breaker: the slot is parked, a
  ``fleet_degraded`` event + ``fleet/degraded_workers`` gauge (SLO
  ``fleet_degraded``) fire, and the fleet runs at N-1;
* per-tenant admission state lives in the shared
  :class:`~.scoreboard.Scoreboard`; the supervisor reaps dead-owner
  slots every ``mosaic.serve.fleet.reap.ms``;
* SIGTERM/SIGINT forward to every child, which drains (the workers
  install :meth:`QueryServer.install_sigterm_drain`); children still
  alive after ``mosaic.serve.drain.ms`` are hard-killed and counted
  in ``serve/drain_forced``.  The parent-bound socket (fallback mode)
  closes only after the last worker exits, so queued connections
  drain before the listener disappears.

CLI (also the worker entry point — the supervisor re-execs this
module with ``--worker``)::

    python -m mosaic_tpu.serve.supervisor --workers 3 --port 8817 \
        --tables /path/tables.npz --conf mosaic.serve.quota.qps=50

Status is written atomically to ``<fleet.dir>/supervisor.json`` each
tick; the same directory doubles as the telemetry fleet plane
(``mosaic.obs.fleet.dir``), so ``tools/fleetctl.py`` and the
dashboard's fleet panel see supervisor + workers in one place.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Deque, Dict, List, Optional, Sequence

from ..obs import metrics
from ..obs.recorder import recorder
from ..obs.timeseries import timeseries
from ..resilience import faults
from ..resilience.retry import FLEET_RESPAWN_BACKOFF, SERVE_SPAWN_RETRY
from .scoreboard import Scoreboard

__all__ = ["ServeFleet", "WorkerSlot", "worker_main", "main",
           "SCOREBOARD_FILE", "SUPERVISOR_FILE"]

SCOREBOARD_FILE = "scoreboard.bin"
SUPERVISOR_FILE = "supervisor.json"
_READY_PREFIX = "ready-"

#: environment contract between supervisor and worker processes
_ENV_DIR = "MOSAIC_FLEET_DIR"
_ENV_HOST = "MOSAIC_FLEET_HOST"
_ENV_PORT = "MOSAIC_FLEET_PORT"
_ENV_SOCK_FD = "MOSAIC_FLEET_SOCKET_FD"
_ENV_TABLES = "MOSAIC_FLEET_TABLES"
_ENV_FACTORY = "MOSAIC_FLEET_FACTORY"
_ENV_CONF = "MOSAIC_FLEET_CONF"
_ENV_GRID = "MOSAIC_FLEET_GRID"
_ENV_INDEX = "MOSAIC_FLEET_INDEX"

_DEFAULT_GRID = "CUSTOM(-180,180,-90,90,2,360,180)"


def _atomic_write_json(path: str, payload: Dict[str, object]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, sort_keys=True)
    os.replace(tmp, path)


def _reuse_port_supported() -> bool:
    return hasattr(socket, "SO_REUSEPORT")


class WorkerSlot:
    """One worker position in the fleet: the live process (if any),
    its restart history inside the breaker window, and the respawn
    schedule.  Mutated only under the fleet's lock."""

    __slots__ = ("index", "proc", "pid", "spawned_t", "restarts",
                 "degraded", "next_respawn_t", "ready")

    def __init__(self, index: int):
        self.index = index
        self.proc: Optional[subprocess.Popen] = None
        self.pid: int = 0
        self.spawned_t: float = 0.0
        #: crash timestamps inside the breaker window
        self.restarts: Deque[float] = collections.deque()
        self.degraded = False
        self.next_respawn_t: float = 0.0
        self.ready = False

    def view(self, now: float) -> Dict[str, object]:
        alive = self.proc is not None and self.proc.poll() is None
        return {"index": self.index, "pid": self.pid,
                "alive": alive, "ready": self.ready,
                "degraded": self.degraded,
                "restarts": len(self.restarts),
                "uptime_s": round(now - self.spawned_t, 1)
                if alive and self.spawned_t else 0.0}


class ServeFleet:
    """Spawn, watch, and drain N query-server worker processes.

    ``worker_cmd`` swaps the child argv (tests use a jax-free stub
    that writes its ready file and sleeps); the default re-execs this
    module with ``--worker`` so the child builds a real
    ``QueryServer`` from the environment contract above.
    """

    def __init__(self, workers: Optional[int] = None,
                 host: str = "127.0.0.1",
                 port: Optional[int] = None,
                 fleet_dir: Optional[str] = None,
                 tables: Optional[Dict[str, Dict[str, object]]] = None,
                 tables_npz: Optional[str] = None,
                 factory: Optional[str] = None,
                 grid: str = _DEFAULT_GRID,
                 conf: Optional[Dict[str, object]] = None,
                 worker_cmd: Optional[Sequence[str]] = None,
                 force_parent_socket: bool = False):
        from .. import config as _config
        cfg = _config.default_config()
        self.workers_n = int(cfg.serve_fleet_workers
                             if workers is None else workers)
        if self.workers_n <= 0:
            raise ValueError("a fleet needs at least one worker")
        self.host = host
        self.port = int(cfg.serve_port if port is None else port)
        self.fleet_dir = fleet_dir or cfg.serve_fleet_dir or ""
        self.grid = grid
        self.conf = dict(conf or {})
        self.factory = factory or ""
        self.worker_cmd = list(worker_cmd) if worker_cmd else None
        self._tables = tables
        self._tables_npz = tables_npz or ""
        self._restart_max = int(cfg.serve_fleet_restart_max)
        self._restart_window_s = cfg.serve_fleet_restart_window_ms / 1e3
        self._health_ms = float(cfg.serve_fleet_health_ms)
        self._reap_s = cfg.serve_fleet_reap_ms / 1e3
        self._stale_s = cfg.obs_fleet_stale_ms / 1e3
        self._drain_s = cfg.serve_drain_ms / 1e3
        self._force_parent_socket = bool(force_parent_socket)
        self.mode = ""                  # reuse_port | parent_socket
        self.scoreboard: Optional[Scoreboard] = None
        self._sock: Optional[socket.socket] = None
        self._slots: List[WorkerSlot] = []
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        self._started = False
        self._stopping = False
        self._last_reap = 0.0
        self._prev_handlers: Dict[int, object] = {}

    # -- lifecycle -----------------------------------------------------
    def start(self, wait_ready: bool = True,
              ready_timeout_s: float = 90.0) -> "ServeFleet":
        with self._lock:
            if self._started:
                return self
            self._started = True
            if not self.fleet_dir:
                import tempfile
                self.fleet_dir = tempfile.mkdtemp(prefix="mosaic-fleet-")
            os.makedirs(self.fleet_dir, exist_ok=True)
            if self._tables is not None and not self._tables_npz:
                self._tables_npz = os.path.join(self.fleet_dir,
                                                "tables.npz")
                self._save_tables_locked()
            self._bind_locked()
            self.scoreboard = Scoreboard(
                os.path.join(self.fleet_dir, SCOREBOARD_FILE))
            self._slots = [WorkerSlot(i) for i in range(self.workers_n)]
        for slot in self._slots:
            self._spawn(slot, respawn=False)
        if wait_ready:
            self._wait_ready(ready_timeout_s)
        from ..obs.slo import fleet_objectives, monitor
        for obj in fleet_objectives():
            monitor.add_objective(obj)
        metrics.gauge("fleet/live_workers", float(self.workers_n))
        timeseries.record("fleet/degraded_workers", 0.0)
        if self._health_ms > 0:
            t = threading.Thread(target=self._health_main, daemon=True,
                                 name="mosaic-fleet-health")
            with self._lock:
                self._health_thread = t
            t.start()
        self._write_status()
        return self

    def _save_tables_locked(self) -> None:
        import numpy as np
        flat = {f"{t}::{c}": arr
                for t, cols in (self._tables or {}).items()
                for c, arr in cols.items()}
        np.savez(self._tables_npz, **flat)

    def _bind_locked(self) -> None:
        """Pick the socket-sharing mode and pin the fleet port."""
        if _reuse_port_supported() and not self._force_parent_socket:
            self.mode = "reuse_port"
            if self.port == 0:
                probe = socket.socket(socket.AF_INET,
                                      socket.SOCK_STREAM)
                probe.setsockopt(socket.SOL_SOCKET,
                                 socket.SO_REUSEPORT, 1)
                probe.bind((self.host, 0))
                self.port = probe.getsockname()[1]
                probe.close()
            return
        self.mode = "parent_socket"
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(128)
        s.set_inheritable(True)
        self._sock = s
        self.port = s.getsockname()[1]

    def __enter__(self) -> "ServeFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- spawning ------------------------------------------------------
    def _worker_env(self, index: int) -> Dict[str, str]:
        env = dict(os.environ)
        env[_ENV_DIR] = self.fleet_dir
        env[_ENV_HOST] = self.host
        env[_ENV_PORT] = str(self.port)
        env[_ENV_GRID] = self.grid
        env[_ENV_INDEX] = str(index)
        env[_ENV_CONF] = json.dumps(self.conf)
        if self._tables_npz:
            env[_ENV_TABLES] = self._tables_npz
        if self.factory:
            env[_ENV_FACTORY] = self.factory
        if self._sock is not None:
            env[_ENV_SOCK_FD] = str(self._sock.fileno())
        else:
            env.pop(_ENV_SOCK_FD, None)
        return env

    def _spawn_once(self, slot: WorkerSlot) -> subprocess.Popen:
        faults.maybe_fail("serve.spawn")
        cmd = self.worker_cmd or [sys.executable, "-m",
                                  "mosaic_tpu.serve.supervisor",
                                  "--worker"]
        pass_fds = (self._sock.fileno(),) if self._sock is not None \
            else ()
        return subprocess.Popen(cmd, env=self._worker_env(slot.index),
                                pass_fds=pass_fds)

    def _spawn(self, slot: WorkerSlot, respawn: bool) -> bool:
        """Spawn one worker through the retry policy; returns False
        when even the retried spawn failed (the health loop treats
        that as a crash for the breaker)."""
        try:
            proc = SERVE_SPAWN_RETRY.call(self._spawn_once, slot)
        except OSError:
            metrics.count("serve/worker_spawn_failures")
            return False
        now = time.time()
        with self._lock:
            slot.proc = proc
            slot.pid = proc.pid
            slot.spawned_t = now
            slot.ready = False
        metrics.count("serve/worker_spawns")
        if respawn:
            metrics.count("serve/worker_respawns")
        recorder.record("fleet_worker_spawn", index=slot.index,
                        pid=proc.pid, respawn=respawn)
        return True

    def _wait_ready(self, timeout_s: float) -> int:
        """Block until every live slot's pid has published its ready
        file (workers write it once their listener is up).  Returns
        the ready count; raises only when NOTHING came up."""
        deadline = time.time() + timeout_s
        while True:
            ready = self._ready_pids()
            n = pending = 0
            with self._lock:
                for slot in self._slots:
                    if slot.pid in ready:
                        slot.ready = True
                for slot in self._slots:
                    n += bool(slot.ready)
                    if not slot.ready and slot.proc is not None \
                            and slot.proc.poll() is None:
                        pending += 1
            if n >= self.workers_n or time.time() >= deadline:
                break
            if pending == 0:
                break       # the rest crashed or never spawned
            time.sleep(0.05)
        if n == 0:
            self.stop(drain=False)
            raise RuntimeError(
                f"no fleet worker became ready within {timeout_s}s")
        return n

    def _ready_pids(self) -> set:
        out = set()
        try:
            names = os.listdir(self.fleet_dir)
        except OSError:
            return out
        for name in names:
            if name.startswith(_READY_PREFIX) \
                    and name.endswith(".json"):
                try:
                    out.add(int(name[len(_READY_PREFIX):-5]))
                except ValueError:
                    continue
        return out

    # -- health loop ---------------------------------------------------
    def _health_main(self) -> None:
        period = self._health_ms / 1e3
        while not self._stop_evt.wait(period):
            try:
                self.tick()
            except Exception:           # the watchdog must outlive any
                metrics.count("serve/health_errors")     # one bad tick

    def tick(self, now: Optional[float] = None) -> None:
        """One health pass (public so tests drive it without the
        thread): crash detection + breaker, due respawns, stale-spool
        kills, scoreboard reaping, status publication."""
        now = time.time() if now is None else now
        with self._lock:
            if self._stopping:
                return
            slots = list(self._slots)
        ready = self._ready_pids()
        for slot in slots:
            self._check_slot(slot, now, ready)
        with self._lock:
            if now - self._last_reap >= self._reap_s \
                    and self.scoreboard is not None:
                self._last_reap = now
                sb = self.scoreboard
            else:
                sb = None
        if sb is not None:
            sb.reap(now)
        self._probe_healthz()
        n_live = sum(1 for s in slots
                     if s.proc is not None and s.proc.poll() is None)
        n_deg = sum(1 for s in slots if s.degraded)
        metrics.gauge("fleet/live_workers", float(n_live))
        metrics.gauge("fleet/degraded_workers", float(n_deg))
        timeseries.record("fleet/degraded_workers", float(n_deg))
        self._write_status(now)

    def _check_slot(self, slot: WorkerSlot, now: float,
                    ready: set) -> None:
        with self._lock:
            proc = slot.proc
            if proc is not None and slot.pid in ready:
                slot.ready = True
        if slot.degraded:
            return
        if proc is not None:
            rc = proc.poll()
            if rc is None:
                self._check_stale(slot, proc, now)
                return
            # the worker died under us: book the crash, schedule the
            # respawn (or trip the breaker)
            metrics.count("serve/worker_crashes")
            recorder.record("fleet_worker_exit", index=slot.index,
                            pid=slot.pid, returncode=rc)
            with self._lock:
                slot.proc = None
                slot.ready = False
                slot.restarts.append(now)
                while slot.restarts and \
                        now - slot.restarts[0] > self._restart_window_s:
                    slot.restarts.popleft()
                if len(slot.restarts) > self._restart_max:
                    slot.degraded = True
                    n = len(slot.restarts)
                else:
                    slot.next_respawn_t = now + \
                        FLEET_RESPAWN_BACKOFF.delay(
                            max(0, len(slot.restarts) - 1))
                    return
            # breaker tripped: run degraded at N-1, never exit
            metrics.count("serve/fleet_degraded")
            recorder.record(
                "fleet_degraded", index=slot.index, restarts=n,
                window_ms=self._restart_window_s * 1e3)
            return
        # parked between crash and respawn: is the backoff due?
        if now >= slot.next_respawn_t:
            if not self._spawn(slot, respawn=True):
                with self._lock:
                    slot.restarts.append(now)
                    if len(slot.restarts) > self._restart_max:
                        slot.degraded = True
                    else:
                        slot.next_respawn_t = now + \
                            FLEET_RESPAWN_BACKOFF.delay(
                                max(0, len(slot.restarts) - 1))

    def _check_stale(self, slot: WorkerSlot,
                     proc: subprocess.Popen, now: float) -> None:
        """A live pid whose telemetry spool stopped aging is hung
        (deadlocked loop, wedged device call): SIGKILL it and let the
        crash path respawn a fresh one.  Only applies once the worker
        has spooled at least once — spooling is conf-gated."""
        from ..obs.spool import spool_path
        path = spool_path(self.fleet_dir, slot.pid)
        try:
            age = now - os.stat(path).st_mtime
        except OSError:
            return
        if age > max(0.1, 4.0 * self._stale_s):
            metrics.count("serve/worker_stale_kills")
            try:
                proc.kill()
            except OSError:
                pass

    def _probe_healthz(self) -> None:
        """One GET /healthz against the shared port per tick.  With
        SO_REUSEPORT the kernel picks a worker, so over successive
        ticks this samples the fleet; failures are counted, not
        attributed (a single refused connect cannot name a pid)."""
        import http.client
        try:
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=1.0)
            try:
                conn.request("GET", "/healthz")
                if conn.getresponse().status == 200:
                    metrics.count("serve/healthz_ok")
                else:
                    metrics.count("serve/healthz_errors")
            finally:
                conn.close()
        except OSError:
            metrics.count("serve/healthz_errors")

    # -- status --------------------------------------------------------
    def status(self, now: Optional[float] = None) -> Dict[str, object]:
        now = time.time() if now is None else now
        with self._lock:
            slots = [s.view(now) for s in self._slots]
            stopping = self._stopping
        sb = self.scoreboard
        return {
            "pid": os.getpid(),
            "t": now,
            "host": self.host,
            "port": self.port,
            "mode": self.mode,
            "stopping": stopping,
            "workers": slots,
            "live": sum(1 for s in slots if s["alive"]),
            "degraded": sum(1 for s in slots if s["degraded"]),
            "scoreboard": sb.snapshot(now) if sb is not None else None,
        }

    def _write_status(self, now: Optional[float] = None) -> None:
        try:
            _atomic_write_json(
                os.path.join(self.fleet_dir, SUPERVISOR_FILE),
                self.status(now))
        except OSError:
            metrics.count("serve/status_write_errors")

    def worker_pids(self) -> List[int]:
        with self._lock:
            return [s.pid for s in self._slots
                    if s.proc is not None and s.proc.poll() is None]

    # -- signals + drain -----------------------------------------------
    def install_signal_handlers(self) -> None:
        """Forward SIGTERM/SIGINT into the fleet drain (main thread
        only — CPython restricts ``signal.signal``)."""
        def _on_signal(signum, frame):
            threading.Thread(target=self.stop, kwargs={"drain": True},
                             daemon=True,
                             name="mosaic-fleet-drain").start()
        with self._lock:
            self._prev_handlers = {
                signal.SIGTERM: signal.signal(signal.SIGTERM,
                                              _on_signal),
                signal.SIGINT: signal.signal(signal.SIGINT,
                                             _on_signal),
            }

    def stop(self, drain: bool = True) -> None:
        """Stop the fleet.  ``drain=True`` forwards SIGTERM to every
        child (each worker runs its own drain-with-deadline) and
        waits ``mosaic.serve.drain.ms`` + grace; whatever survives is
        hard-killed and counted in ``serve/drain_forced``."""
        with self._lock:
            if self._stopping:
                return
            self._stopping = True
            health = self._health_thread
            self._health_thread = None
            prev, self._prev_handlers = self._prev_handlers, {}
        self._stop_evt.set()
        if health is not None and health is not \
                threading.current_thread():
            health.join(5.0)
        with self._lock:
            procs = [(s, s.proc) for s in self._slots
                     if s.proc is not None]
        sig = signal.SIGTERM if drain else signal.SIGKILL
        for _, p in procs:
            try:
                p.send_signal(sig)
            except (OSError, ProcessLookupError):
                pass
        # workers drain against their own mosaic.serve.drain.ms; give
        # them that budget plus scheduling grace before forcing
        deadline = time.time() + (self._drain_s + 2.0 if drain else 5.0)
        pending = list(procs)
        while pending and time.time() < deadline:
            pending = [(s, p) for s, p in pending if p.poll() is None]
            if pending:
                time.sleep(0.05)
        for _, p in pending:
            metrics.count("serve/drain_forced")
            try:
                p.kill()
            except (OSError, ProcessLookupError):
                pass
        for _, p in procs:
            try:
                p.wait(5.0)
            except Exception:
                pass
        # the shared listener (fallback mode) outlives every worker:
        # queued connections drained above, nothing new gets lost
        with self._lock:
            sock, self._sock = self._sock, None
            sb, self.scoreboard = self.scoreboard, None
        if sock is not None:
            sock.close()
        if sb is not None:
            sb.close()
        for signum, handler in prev.items():
            try:
                signal.signal(signum, handler)
            except (ValueError, OSError):
                pass
        self._write_status()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until :meth:`stop` ran (signal handler or another
        thread); True when it did."""
        return self._stop_evt.wait(timeout)


# ---------------------------------------------------------------- worker

def _apply_worker_conf(fleet_dir: str, conf: Dict[str, object]) -> None:
    from .. import config as _config
    cfg = _config.default_config()
    merged = dict(conf)
    # the fleet runtime dir IS the telemetry fleet dir unless the
    # operator pointed spools elsewhere — one directory, one plane
    merged.setdefault(_config.MOSAIC_OBS_FLEET_DIR, fleet_dir)
    for key, value in merged.items():
        cfg = _config.apply_conf(cfg, key, str(value))
    _config.set_default_config(cfg)


def _build_session(grid: str, tables_npz: str, factory: str):
    from ..functions.context import MosaicContext
    from ..sql.engine import SQLSession
    ctx = MosaicContext.build(grid)
    if factory:
        mod, _, fn = factory.partition(":")
        import importlib
        session = getattr(importlib.import_module(mod), fn)(ctx)
        if not isinstance(session, SQLSession):
            raise TypeError(f"fleet factory {factory!r} returned "
                            f"{type(session).__name__}, not SQLSession")
        return session
    session = SQLSession(ctx)
    if tables_npz:
        import numpy as np
        with np.load(tables_npz) as data:
            tables: Dict[str, Dict[str, object]] = {}
            for key in data.files:
                tname, _, col = key.partition("::")
                tables.setdefault(tname, {})[col] = data[key]
        for tname, cols in tables.items():
            session.create_table(tname, cols)
    return session


def worker_main() -> int:
    """Child entry: build the session from the environment contract,
    serve on the shared socket, heartbeat via the telemetry spool,
    drain on SIGTERM, exit 0."""
    fleet_dir = os.environ[_ENV_DIR]
    host = os.environ.get(_ENV_HOST, "127.0.0.1")
    port = int(os.environ.get(_ENV_PORT, "0"))
    conf = json.loads(os.environ.get(_ENV_CONF, "{}"))
    _apply_worker_conf(fleet_dir, conf)
    metrics.enable()
    recorder.enable()
    from ..obs.jaxmon import install_jax_listeners
    install_jax_listeners()
    session = _build_session(
        os.environ.get(_ENV_GRID, _DEFAULT_GRID),
        os.environ.get(_ENV_TABLES, ""),
        os.environ.get(_ENV_FACTORY, ""))
    # MosaicContext.build installs its own fresh MosaicConfig as the
    # process default, wiping the fleet conf (sampler, jit cache,
    # quotas) — re-apply so serving runs under the supervisor's conf
    _apply_worker_conf(fleet_dir, conf)
    sock = None
    fd = os.environ.get(_ENV_SOCK_FD, "")
    if fd:
        sock = socket.fromfd(int(fd), socket.AF_INET,
                             socket.SOCK_STREAM)
    sb = Scoreboard(os.path.join(fleet_dir, SCOREBOARD_FILE))
    from .server import QueryServer
    srv = QueryServer(session, host=host, port=port, sock=sock,
                      reuse_port=sock is None, scoreboard=sb)
    srv.start()
    srv.install_sigterm_drain()
    _atomic_write_json(
        os.path.join(fleet_dir, f"{_READY_PREFIX}{os.getpid()}.json"),
        {"pid": os.getpid(), "port": srv.port, "t": time.time()})
    try:
        srv.wait_stopped()
    finally:
        sb.close()
    return 0


# ------------------------------------------------------------------ CLI

def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="mosaic_tpu serving-fleet supervisor")
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)   # internal child mode
    ap.add_argument("--workers", type=int, default=None)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--fleet-dir", default=None)
    ap.add_argument("--tables", default=None,
                    help="npz of table columns (keys 'table::col')")
    ap.add_argument("--factory", default=None,
                    help="module:callable -> SQLSession(ctx)")
    ap.add_argument("--grid", default=_DEFAULT_GRID)
    ap.add_argument("--conf", action="append", default=[],
                    metavar="KEY=VALUE",
                    help="conf forwarded to every worker (repeat)")
    args = ap.parse_args(argv)
    if args.worker:
        return worker_main()
    conf: Dict[str, object] = {}
    for item in args.conf:
        if "=" not in item:
            ap.error(f"--conf wants KEY=VALUE, got {item!r}")
        k, v = item.split("=", 1)
        conf[k.strip()] = v.strip()
    fleet = ServeFleet(workers=args.workers, host=args.host,
                       port=args.port, fleet_dir=args.fleet_dir,
                       tables_npz=args.tables, factory=args.factory,
                       grid=args.grid, conf=conf)
    fleet.start()
    fleet.install_signal_handlers()
    print(json.dumps({"port": fleet.port, "mode": fleet.mode,
                      "fleet_dir": fleet.fleet_dir,
                      "workers": fleet.workers_n}))
    sys.stdout.flush()
    fleet.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
