"""Worker pool: admitted queries onto the shared warm engine.

Each worker is one daemon thread looping take → :meth:`dispatch`.
``dispatch`` is the server's operator boundary (the graftlint
``cancel-checkpoint`` rule holds it to the same contract as the
engine's ``stage()``): it probes the inflight checkpoint, honors the
``serve.dispatch`` fault site, routes micro-batchable point lookups
through :func:`~.batching.execute_batch`, and runs everything else
through ``SQLSession.sql`` with the request's cancellation plumbing
attached (``obs.inflight.ticket_observer``), so a client disconnect
or deadline observed on the asyncio side lands in the running query
within one pipeline chunk.  All workers share ONE session — and
therefore one warm jit cache, one planner coefficient store, one
catalog — which is the entire point of a long-lived server process.
"""

from __future__ import annotations

import threading
import time
from typing import List

from ..obs import metrics
from ..obs.context import link_traceparent
from ..obs.inflight import QueryCancelled, checkpoint, ticket_observer
from ..resilience import faults
from ..resilience.faults import InjectedFault
from ..sql.engine import SQLError
from ..sql.parser import SQLParseError
from .admission import AdmissionQueue, ServeRequest

__all__ = ["WorkerPool"]


class WorkerPool:
    def __init__(self, session, queue: AdmissionQueue,
                 workers: int, batch_max: int,
                 batch_window_ms: float):
        self.session = session
        self.queue = queue
        self.workers = int(workers)
        self.batch_max = int(batch_max)
        self.batch_window_ms = float(batch_window_ms)
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._busy_lock = threading.Lock()
        self.busy = 0

    # -- lifecycle
    def start(self) -> None:
        for i in range(self.workers):
            t = threading.Thread(target=self._run, daemon=True,
                                 name=f"mosaic-serve-worker-{i}")
            t.start()
            self._threads.append(t)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        deadline = time.perf_counter() + timeout
        for t in self._threads:
            t.join(max(0.0, deadline - time.perf_counter()))
        self._threads = [t for t in self._threads if t.is_alive()]

    def idle(self) -> bool:
        with self._busy_lock:
            return self.busy == 0

    def _run(self) -> None:
        while not self._stop.is_set():
            req = self.queue.take(timeout=0.05)
            if req is None:
                continue
            with self._busy_lock:
                self.busy += 1
                if metrics.enabled:
                    metrics.gauge("serve/workers_busy",
                                  float(self.busy))
            try:
                self.dispatch(req)
            finally:
                self.queue.release(req)
                with self._busy_lock:
                    self.busy -= 1
                    if metrics.enabled:
                        metrics.gauge("serve/workers_busy",
                                      float(self.busy))

    # -- the per-request operator boundary
    def dispatch(self, req: ServeRequest) -> None:
        """Execute one admitted request and resolve its future.  Never
        raises: every outcome — including an injected ``serve.
        dispatch`` fault — becomes a response, and ticket lifecycle is
        owned by the paths below (sql() completes its own ticket in
        its finally; the batcher completes per-member tickets), so a
        worker unwinding mid-query leaks neither tickets nor threads."""
        checkpoint("serve.dispatch")     # boundary probe (no-op unless
        # this worker thread somehow still carries a query trace)
        try:
            faults.maybe_fail("serve.dispatch")
        except InjectedFault as exc:
            if metrics.enabled:
                metrics.count("serve/dispatch_errors")
            req.resolve(500, {"error": f"{type(exc).__name__}: {exc}"},
                        "error")
            return
        if req.cancel_reason is not None:
            # disconnected (or deadline-cancelled) while queued: no
            # ticket was ever opened, nothing ran — just answer
            outcome = "deadline" if req.cancel_reason == "deadline" \
                else "cancelled"
            req.resolve(499 if outcome == "cancelled" else 504,
                        {"error": outcome}, outcome)
            return
        if req.lookup is not None and self.batch_max > 0:
            members = [req]
            if self.batch_max > 1:
                if self.batch_window_ms > 0:
                    # brief window so a concurrent burst of lookups
                    # lands in this launch instead of the next
                    time.sleep(self.batch_window_ms / 1e3)
                members += self.queue.take_compatible(
                    req.lookup.signature, self.batch_max - 1)
            try:
                from .batching import execute_batch
                execute_batch(self.session, members)
            finally:
                for m in members[1:]:
                    self.queue.release(m)
            return
        self._run_single(req)

    def _run_single(self, req: ServeRequest) -> None:
        # link_traceparent parks the client's W3C trace context so the
        # engine's new_trace stitches this query into the caller's
        # cross-process tree (no-op when the client sent no header).
        # The micro-batch path skips linking: one device launch serves
        # many clients, and a batch trace has no single parent.
        with link_traceparent(req.traceparent), \
                ticket_observer(req.attach_ticket):
            try:
                out = self.session.sql(req.sql)
            except QueryCancelled as exc:
                req.resolve(499 if exc.outcome == "cancelled" else 504,
                            {"error": exc.outcome}, exc.outcome)
            except (SQLError, SQLParseError) as exc:
                req.resolve(400, {"error": str(exc)}, "error")
            except Exception as exc:
                if metrics.enabled:
                    metrics.count("serve/errors")
                req.resolve(500,
                            {"error": f"{type(exc).__name__}: {exc}"},
                            "error")
            else:
                req.resolve(200, out, "ok")
