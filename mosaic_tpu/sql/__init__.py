"""SQL surface: parser + columnar engine + prettifier.

Reference counterpart: mosaic/sql/ (MosaicSQL/MosaicSQLDefault
SparkSessionExtensions, Prettifier, MosaicAnalyzer).  The analyzer lives
at :mod:`mosaic_tpu.analyzer`; this package supplies the query engine the
reference gets for free from Spark.
"""

from .engine import SQLError, SQLSession, Table
from .parser import SQLParseError, parse
from .prettifier import prettified

__all__ = ["SQLSession", "Table", "SQLError", "SQLParseError", "parse",
           "prettified"]
