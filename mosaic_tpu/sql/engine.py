"""Columnar SQL engine over the registered function surface.

Reference counterpart: sql/extensions/MosaicSQL.scala:21-47 (+
MosaicSQLDefault) expose every registered expression to Spark SQL; the
Quickstart notebook's PIP-join is written in exactly the query shapes this
engine executes:

    points  = SELECT *, grid_pointascellid(geom, 9) AS cell FROM trips
    chips   = SELECT zone_id, grid_tessellateexplode(geom, 9) FROM zones
    joined  = SELECT ... FROM points JOIN chips ON points.cell = chips.index_id
              WHERE is_core OR st_contains(wkb, geom)

Tables are dicts of equal-length columns; a column is a numpy array, a
``GeometryArray``, or a python list (e.g. WKB bytes).  Function calls
dispatch by name through ``MosaicContext.call`` — the same string-dispatch
boundary the reference's SQL registration uses — and evaluate columnar
(row-wise semantics via equal-length vectorized kernels).

Execution order: FROM/JOIN (inner or LEFT OUTER) -> explode generator
(if any select item is a generator call) -> WHERE -> GROUP BY/aggregate
(+ HAVING over the groups) -> projection -> ORDER BY -> LIMIT.  WHERE runs after the explode so filters can reference the
generated ``is_core``/``index_id``/``wkb`` columns, matching how the
reference's users filter tessellations.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.geometry.array import GeometryArray
from ..obs import metrics, new_trace, recorder, tracer
from ..obs.devicemon import devicemon, format_device_ms
from .parser import (Binary, Call, Column, Literal, Query, SelectItem,
                     Star, TableRef, Unary, parse)
from .planner import planner

GENERATORS = {"grid_tessellateexplode", "mosaic_explode",
              "grid_cellkringexplode", "grid_cellkloopexplode",
              "grid_geometrykringexplode", "grid_geometrykloopexplode"}

AGGREGATES = {"count", "sum", "avg", "mean", "min", "max", "first"}


class SQLError(ValueError):
    pass


# ------------------------------------------------------------- columns

def col_len(col) -> int:
    return len(col)


def col_take_nullable(col, idx: np.ndarray):
    """col_take where idx -1 means NULL (the LEFT JOIN emission).

    FLOAT arrays host nulls as NaN; integer columns switch to python
    lists with None (a float cast would corrupt int64 cell ids above
    2^53); geometry columns cannot hold a null row — selecting one
    through an outer join raises rather than emitting a broken
    column."""
    idx = np.asarray(idx, np.int64)
    if -1 not in idx:
        return col_take(col, idx)
    if isinstance(col, GeometryArray):
        raise SQLError(
            "LEFT JOIN produced NULL geometry rows; geometry columns "
            "have no null slot — select the right side's non-geometry "
            "columns, or filter to matched rows first")
    if isinstance(col, np.ndarray) and \
            np.issubdtype(col.dtype, np.floating) and len(col):
        out = col.astype(np.float64)[np.maximum(idx, 0)]
        out[idx < 0] = np.nan
        return out
    return [None if (i < 0 or len(col) == 0) else col[int(i)]
            for i in idx]


def col_take(col, idx: np.ndarray):
    if isinstance(col, GeometryArray):
        return col.take(idx)
    if isinstance(col, np.ndarray):
        return col[idx]
    return [col[int(i)] for i in idx]


def _as_mask(col, n: int) -> np.ndarray:
    m = np.asarray(col)
    if m.shape == ():
        m = np.full(n, bool(m))
    return m.astype(bool)


class Table:
    """Ordered named columns of equal length."""

    def __init__(self, columns: Dict[str, object]):
        self.columns = dict(columns)
        lens = {col_len(c) for c in self.columns.values()}
        if len(lens) > 1:
            raise SQLError(f"ragged columns: "
                           f"{ {k: col_len(v) for k, v in columns.items()} }")
        self._n = lens.pop() if lens else 0

    def __len__(self) -> int:
        return self._n

    def column(self, name: str):
        if name not in self.columns:
            raise SQLError(f"no column {name!r}; have "
                           f"{list(self.columns)}")
        return self.columns[name]

    def take(self, idx: np.ndarray) -> "Table":
        return Table({k: col_take(v, idx) for k, v in self.columns.items()})

    def head(self, n: int) -> "Table":
        return self.take(np.arange(min(n, self._n)))

    def to_dict(self) -> Dict[str, object]:
        return dict(self.columns)

    def __repr__(self) -> str:
        return (f"Table[{self._n} rows x {len(self.columns)} cols: "
                f"{list(self.columns)}]")


# ---------------------------------------------------------- evaluation

class _Env:
    """Column resolution over one or two (joined) tables."""

    def __init__(self, tables: Dict[str, Table]):
        self.tables = tables            # qualifier -> Table

    def resolve(self, name: str, qualifier: Optional[str]):
        if qualifier is not None:
            if qualifier not in self.tables:
                raise SQLError(f"unknown table qualifier {qualifier!r}")
            return self.tables[qualifier].column(name)
        hits = [(q, t) for q, t in self.tables.items()
                if name in t.columns]
        if not hits:
            raise SQLError(f"no column {name!r} in "
                           f"{[list(t.columns) for t in self.tables.values()]}")
        if len({id(t) for _, t in hits}) > 1:
            raise SQLError(f"ambiguous column {name!r} "
                           f"(in {[q for q, _ in hits]})")
        return hits[0][1].column(name)


def _numeric(x):
    if isinstance(x, list):
        return np.asarray(x)
    return x


def _vectorized_equi_join(lk: np.ndarray, rk: np.ndarray):
    """Sort-based single-key equi-join emitting the exact pair order
    of the dict-loop: left ascending, right index-ascending within
    each key (stable argsort preserves insertion order of dups)."""
    order = np.argsort(rk, kind="stable")
    rs = rk[order]
    starts = np.searchsorted(rs, lk, "left")
    counts = np.searchsorted(rs, lk, "right") - starts
    total = int(counts.sum())
    li = np.repeat(np.arange(len(lk), dtype=np.int64), counts)
    offs = np.repeat(np.cumsum(counts) - counts, counts)
    out = np.arange(total, dtype=np.int64) - offs + \
        np.repeat(starts, counts)
    return li, order[out].astype(np.int64)


class SQLSession:
    """Named tables + query execution (reference: the SparkSession the
    MosaicSQL extension installs into)."""

    def __init__(self, context=None):
        from ..functions.context import MosaicContext
        self.mc = context or MosaicContext.context()
        self._tables: Dict[str, Table] = {}
        # out-of-core chip stores registered as scannable tables
        # (mosaic_tpu/store/): a store scan prunes partitions against
        # the WHERE clause's bbox before reading a data byte
        self._stores: Dict[str, object] = {}
        # Accounting identity: queries from this session are metered
        # under this principal; None falls back to the
        # ``mosaic.principal`` conf, then "anonymous" (obs/accounting).
        self.principal: Optional[str] = None

    # -- catalog
    def create_table(self, name: str, columns: Dict[str, object]) -> Table:
        t = Table(columns)
        self._tables[name.lower()] = t
        return t

    def table(self, name: str) -> Table:
        key = name.lower()
        if key not in self._tables:
            raise SQLError(f"unknown table {name!r}")
        return self._tables[key]

    def drop_table(self, name: str) -> None:
        self._tables.pop(name.lower(), None)

    def register_store(self, name: str, store) -> None:
        """Register a chip store (a path or an opened
        :class:`~..store.reader.ChipStore`) as a scannable table.
        Scans of it push the WHERE clause's bbox down into partition
        pruning (EXPLAIN's ``partitions`` column shows scanned/total);
        only the surviving partitions' rows materialize, in store
        order.  An in-memory table of the same name shadows the
        store."""
        if isinstance(store, str):
            from ..store.reader import ChipStore
            store = ChipStore(store)
        self._stores[name.lower()] = store

    def drop_store(self, name: str) -> None:
        self._stores.pop(name.lower(), None)

    def _store_for(self, name: str):
        """The store a table reference resolves to, or None (in-memory
        tables shadow stores of the same name)."""
        key = name.lower()
        if key in self._tables:
            return None
        return self._stores.get(key)

    def _store_scan(self, name: str, where) -> Table:
        """Materialize a store scan: bbox pushdown from the WHERE
        clause -> partition pruning -> read only the survivors.  The
        WHERE still runs over the scanned rows downstream, so pruning
        only has to be conservative, never exact."""
        from ..store.pushdown import bbox_from_where
        store = self._stores[name.lower()]
        bbox = bbox_from_where(where, *store.point_cols)
        return Table(store.read_columns(bbox=bbox))

    # -- query entry
    def sql(self, query: str) -> Table:
        """Run a query.  ``EXPLAIN ANALYZE SELECT ...`` executes the
        query and returns the per-operator profile instead of the
        result (operator, detail, rows out, wall ms); bare ``EXPLAIN``
        returns the plan without executing.  ``SET mosaic.key = value``
        updates the session-default :class:`MosaicConfig` through the
        validated conf path (reference: ``spark.conf.set`` on the
        mosaic.* namespace) and returns the applied pair.

        Every call runs under a fresh :class:`TraceContext` (the
        Spark-UI "one timeline per SQL execution" analogue): operator
        stages become child spans of an ``sql/query`` root span, keyed
        by the query's trace id in ``tracer.report()["traces"]`` and
        the Chrome-trace export.  Queries slower than
        ``mosaic.obs.slow.query.ms`` (when > 0) trigger an automatic
        flight-recorder dump.

        SLO feed: every call bumps ``sql/queries``; unexpected
        failures (not :class:`SQLError` — user mistakes are the
        client's fault, not the service's) bump ``sql/errors``; wall
        time lands as a ``sql/query_ms`` time-series point so the
        ``sql_latency`` burn-rate objective sees true per-query
        latency (``obs.slo``).  The ``sql.query`` fault site injects
        deterministic stalls for alert drills.

        Accounting: the call registers a ticket in the in-flight
        registry (``obs.inflight``) under ``session.principal`` /
        ``mosaic.principal`` for its whole lifetime — visible in the
        dashboard's ``/api/queries``, cancellable via
        ``inflight.cancel(query_id)`` or the console, subject to
        ``mosaic.query.deadline.ms``.  Cancellation is cooperative:
        :class:`~..obs.inflight.QueryCancelled` rises from the next
        operator boundary (or streamed-chunk boundary) and completes
        the ticket with a *partial* cost record in the audit log
        (outcome ``cancelled`` / ``deadline`` — never ``sql/errors``,
        which stays reserved for unexpected service faults)."""
        from ..resilience import faults as _faults
        from .. import config as _config
        from ..obs.inflight import QueryCancelled, checkpoint, inflight
        from ..obs import accounting as _accounting
        label = " ".join(query.split())[:60]
        cfg = _config.default_config()
        t0 = time.perf_counter()
        with new_trace(f"sql:{label}") as ctx:
            ticket = inflight.register(
                label,
                principal=self.principal or cfg.principal or "anonymous",
                deadline_ms=cfg.query_deadline_ms)
            outcome: str = "ok"
            err: Optional[BaseException] = None
            try:
                recorder.record("sql", query=label)
                _faults.stall("sql.query")
                metrics.count("sql/queries")
                # a cancel/deadline that landed during the stall (or
                # before any operator ran) surfaces here
                checkpoint("sql")
                with tracer.span("sql/query"):
                    out = self._sql_impl(query)
            except QueryCancelled as e:
                outcome, err = e.outcome, e
                raise               # operator action: not an SLO fault
            except SQLError as e:
                outcome, err = "error", e
                raise               # client error: not an SLO fault
            except Exception as e:
                outcome, err = "error", e
                metrics.count("sql/errors")
                raise
            finally:
                _accounting.complete(
                    ticket, outcome=outcome, error=err,
                    wall_ms=(time.perf_counter() - t0) * 1e3)
        dt_ms = (time.perf_counter() - t0) * 1e3
        if metrics.enabled:
            from ..obs.timeseries import timeseries
            timeseries.record("sql/query_ms", dt_ms)
        from .. import config as _config
        threshold = _config.default_config().obs_slow_query_ms
        if threshold and dt_ms > threshold:
            recorder.record("slow_query", query=label,
                            ms=round(dt_ms, 3), threshold_ms=threshold,
                            trace=ctx.trace_id)
            # throttled: at most one auto-dump per
            # mosaic.obs.dump.cooldown.ms across slow queries AND SLO
            # breaches — a sustained slow workload is otherwise a dump
            # storm.  The bundle embeds the profiler snapshot (host
            # stacks + kernel ledger), so a slow query leaves a
            # profile, not just a mark.
            try:
                recorder.dump_throttled(reason="slow_query")
            except OSError:
                pass
        return out

    def _sql_impl(self, query: str) -> Table:
        import re as _re
        m = _re.match(r"\s*SET\s+([A-Za-z][\w.]*)\s*=\s*(.+?)\s*;?\s*$",
                      query, _re.IGNORECASE)
        if m:
            key, raw = m.group(1), m.group(2)
            value = raw.strip("'\"")
            from .. import config as _config
            try:
                cfg = _config.apply_conf(
                    _config.default_config(), key, value)
            except _config.ConfigError as e:
                raise SQLError(str(e)) from e
            _config.set_default_config(cfg)
            return Table({"key": [key], "value": [value]})
        q = parse(query)
        if q.explain == "plan":
            ops = self._plan_ops(q)
            # strategy column: the planner's chosen path + why per
            # operator ("-" when the planner is off or has no choice);
            # fused column: the fusion group id each operator compiles
            # into ("-" = dispatches alone — see perf/fusion.py)
            plan = planner.plan_query(q, self) if planner.enabled \
                else None
            fplan = plan.fusion if plan is not None else None

            def _est_bytes(o: str) -> int:
                s = plan.steps.get(o) if plan is not None else None
                return s.est_bytes if s is not None else -1

            def _partitions(o: str) -> str:
                # store scans show the bbox pushdown's pruning as
                # "scanned/total" — computed from the manifest alone
                # (EXPLAIN moves no data bytes); "-" everywhere else
                store = self._store_for(q.table.name) \
                    if o == "scan" and q.join is None else None
                if store is None:
                    return "-"
                from ..store.pushdown import bbox_from_where
                bbox = bbox_from_where(q.where, *store.point_cols)
                scanned = len(store.prune(bbox, record=False))
                return f"{scanned}/{len(store.partitions)}"
            # est_bytes: the planner's byte pre-pass (cardinality x
            # source row width; -1 = no estimate) — what the memory
            # budget's admission check reads; refine: the adaptive
            # PIP-refinement pick per operator — static plans have
            # none (the decision needs the first batch's selectivity
            # probe), so the column is "-" until EXPLAIN ANALYZE
            return Table({"operator": [o for o, _ in ops],
                          "detail": [d for _, d in ops],
                          "strategy": [plan.label(o) if plan is not None
                                       else "-" for o, _ in ops],
                          "est_bytes": np.asarray(
                              [_est_bytes(o) for o, _ in ops],
                              np.int64),
                          "partitions": [_partitions(o)
                                         for o, _ in ops],
                          "refine": ["-" for _ in ops],
                          "fused": [fplan.gid_for(o) if fplan is not None
                                    else "-" for o, _ in ops]})
        if q.explain == "analyze":
            prof: List[tuple] = []
            self._execute(q, prof)
            # refine column: the per-call refinement summaries the
            # adaptive join noted on this query's ticket (levels used /
            # cells refined / cells flat), attributed to the operator
            # the ticket was in when each refined join ran; summaries
            # noted outside any operator stage roll up on the first
            # (scan/join) row.  The ticket is still open here — it
            # completes in sql()'s finally, after this table is built.
            from ..obs.context import current_trace_id
            from ..obs.inflight import inflight as _inflight
            tkt = _inflight.ticket_for_trace(current_trace_id())
            rops = list(tkt.refine_ops) if tkt is not None else []
            prof_ops = {p[0] for p in prof}

            def _refine_for(i: int, op: str) -> str:
                hits = [s for o, s in rops if o == op]
                if i == 0:
                    hits += [s for o, s in rops if o not in prof_ops]
                return "; ".join(hits) if hits else "-"
            # all_to_all_bytes / shard_skew attribute the sharded
            # exchange (parallel/overlay collective accounting) to the
            # operator row that moved the bytes — zero rows mean the
            # operator never left one device; est_rows is the planner's
            # pre-pass cardinality estimate (-1 = no estimate), placed
            # next to actual rows so mispredicts read off per operator;
            # device_ms is the per-device wall-time split the device
            # monitor attributed while the stage ran ("-" when the
            # operator never touched a mesh — see obs.devicemon);
            # fused marks the operators a fusion group executed as one
            # XLA program — the group's device/wall time rolls up on
            # its FIRST member's row, later members just unpack;
            # peak_bytes is the device-memory ledger's per-trace
            # allocation delta while the stage ran (obs.memwatch —
            # registered + transient bytes, 0 when the ledger is off)
            return Table({"operator": [p[0] for p in prof],
                          "detail": [p[1] for p in prof],
                          "rows": np.asarray([p[2] for p in prof],
                                             np.int64),
                          "est_rows": np.asarray([p[6] for p in prof],
                                                 np.int64),
                          "time_ms": np.asarray([p[3] * 1e3
                                                 for p in prof]),
                          "all_to_all_bytes": np.asarray(
                              [p[4] for p in prof], np.int64),
                          "shard_skew": np.asarray(
                              [p[5] for p in prof]),
                          "device_ms": [p[7] for p in prof],
                          "refine": [_refine_for(i, p[0])
                                     for i, p in enumerate(prof)],
                          "fused": [p[8] for p in prof],
                          "peak_bytes": np.asarray(
                              [p[9] for p in prof], np.int64)})
        return self._execute(q, None)

    def _plan_ops(self, q: Query) -> List[tuple]:
        """Static operator list in execution order (EXPLAIN output)."""
        ops = []
        if q.join is not None:
            ops.append((f"{q.join_kind}_join",
                        f"{q.table.name} ⋈ {q.join.name}"))
        else:
            ops.append(("scan", q.table.name))
        gens = [it.expr.name for it in q.items
                if isinstance(it.expr, Call) and
                it.expr.name in GENERATORS]
        if gens:
            ops.append(("generate", gens[0]))
        if q.where is not None:
            ops.append(("filter", "WHERE"))
        if q.group_by is not None or self._has_aggregate(q.items):
            ops.append(("aggregate",
                        f"{len(q.group_by or [])} group keys"))
        else:
            ops.append(("project", f"{len(q.items)} items"))
        if q.order_by:
            ops.append(("order", f"{len(q.order_by)} keys"))
        if q.limit is not None:
            ops.append(("limit", str(q.limit)))
        return ops

    #: skew-gauge sites the profiler checks when a stage moved
    #: all_to_all bytes (parallel/{overlay,pip_join} accounting)
    _SKEW_SITES = ("overlay", "overlay_pairs", "pip_join")

    def _execute(self, q: Query, prof: Optional[List[tuple]]) -> Table:
        # cost-based pre-pass: per-operator cardinality estimates +
        # strategy picks.  _equi_join reads the join decision off the
        # plan; every stage below closes its estimate so the planner's
        # coefficient store learns from this run (sql/planner.py)
        plan = planner.plan_query(q, self) if planner.enabled else None
        self._active_plan = plan
        from ..obs.inflight import (checkpoint as _checkpoint,
                                    note_rows as _note_rows,
                                    note_rows_in as _note_rows_in,
                                    note_strategies as _note_strategies)
        from ..obs.memwatch import mem_budget as _mem_budget, \
            memwatch as _memwatch
        if plan is not None:
            # advisory admission check against the planner's byte
            # pre-pass: a denial is counted + flight-recorded (the
            # admission-control arc's ground truth) but the query
            # still runs — the stream degrades via chunk shrink
            # instead of dying at the gate
            _mem_budget.admit(plan.est_bytes_peak())
        if plan is not None:
            # strategy picks land on the active ticket here (not read
            # off self._active_plan at completion — that attribute is
            # racy under concurrent sessions; the ticket is trace-local)
            _note_strategies(
                {op: plan.label(op) for op in plan.steps})

        # fusion: the planner's pre-pass may have stitched adjacent
        # eligible operators into one XLA program (perf/fusion.py).
        # The group runs inside its FIRST member's stage; later member
        # stages just unpack the cached FusedResult.  A runtime
        # bailout (dtype drift, sum bound, x64 off) latches "bailed"
        # and every member falls back to the unfused path — results
        # stay bit-for-bit identical either way.
        fplan = plan.fusion if plan is not None else None
        fstate = {"res": None, "bailed": False}

        def _try_group(g, genv):
            from ..perf import fusion as _fusion
            try:
                fstate["res"] = _fusion.execute_group(g, q, genv, self)
                return fstate["res"]
            except _fusion.FusionBailout as e:
                fstate["bailed"] = True
                if metrics.enabled:
                    metrics.count("fusion/bailouts")
                recorder.record("fusion_bailout", group=g.gid,
                                reason=str(e))
                return None

        def _fused_gid(op: str) -> str:
            if fplan is None or fstate["res"] is None:
                return "-"
            return fplan.gid_for(op)

        def stage(op: str, detail: str, fn, rows_of, fused_of=None):
            # operator boundary: the cooperative cancellation probe —
            # a cancel()/expired deadline raises QueryCancelled before
            # the next operator starts, never mid-kernel
            _checkpoint(op)
            # nested under the sql/query root span -> qualified as
            # sql/query/<op>, a child in the query's trace tree
            a2a0 = metrics.counter_value("collective/all_to_all_bytes")
            dev0 = devicemon.busy_by_device() if prof is not None \
                else None
            mem0 = _memwatch.current_trace_alloc_bytes() \
                if prof is not None else 0
            with tracer.span(op):
                t0 = time.perf_counter()
                res = fn()
                dt = time.perf_counter() - t0
            rows = rows_of(res)
            _note_rows(rows)
            if op == "scan" or op.endswith("_join"):
                _note_rows_in(rows)
            gid = fused_of() if fused_of is not None else "-"
            step = plan.steps.get(op) if plan is not None else None
            if step is not None:
                if gid != "-":
                    # the stage ran inside a fusion group: its wall
                    # time belongs to the group's fusion/<opset> cost
                    # key (fed by execute_group), so only close the
                    # cardinality side here — feeding dt to the member
                    # op would poison the unfused coefficient the
                    # fusion gate compares against
                    planner.observe_ratio(step.op, step.key_n, rows)
                    planner.observe_estimate(step.op, step.est_rows,
                                             rows)
                else:
                    planner.observe_step(step, rows, dt)
            if prof is not None:
                # bytes this stage pushed through sharded exchanges;
                # when nonzero, the current shard/skew/* gauges were
                # (re)written by those exchanges, so snapshot the worst
                a2a = metrics.counter_value(
                    "collective/all_to_all_bytes") - a2a0
                skew = max((metrics.gauge_value(f"shard/skew/{s}")
                            or 0.0)
                           for s in self._SKEW_SITES) if a2a else 0.0
                # per-device wall-time split attributed while this
                # stage ran (sharded ops feed obs.devicemon by load
                # share) — the EXPLAIN ANALYZE device_ms column
                dev1 = devicemon.busy_by_device()
                delta = {k: v - (dev0.get(k, 0.0) if dev0 else 0.0)
                         for k, v in dev1.items()}
                # device bytes this stage allocated (registered +
                # transient) under the query's trace — the EXPLAIN
                # ANALYZE peak_bytes column; the per-trace allocation
                # total is monotone, so the diff is stage-local
                mem1 = _memwatch.current_trace_alloc_bytes()
                prof.append((op, detail, rows, dt, int(a2a),
                             float(skew),
                             step.est_rows if step is not None else -1,
                             format_device_ms(delta), gid,
                             max(0, int(mem1 - mem0))))
            if metrics.enabled:
                metrics.observe(f"sql/{op}_s", dt)
            return res

        if q.join is not None:
            base_env = stage(f"{q.join_kind}_join",
                             f"{q.table.name} ⋈ {q.join.name}",
                             lambda: self._from_clause(q),
                             self._env_len)
        else:
            base_env = stage("scan", q.table.name,
                             lambda: self._from_clause(q),
                             self._env_len)
        # explode generators before WHERE so filters see generated cols
        env, gen_items = stage(
            "generate",
            next((it.expr.name for it in q.items
                  if isinstance(it.expr, Call) and
                  it.expr.name in GENERATORS), "-"),
            lambda: self._apply_generators(q, base_env),
            lambda r: self._env_len(r[0]))
        if not gen_items and prof is not None:
            prof.pop()            # no generator ran; drop the stub row
        if q.where is not None:
            g_f = fplan.group_with("filter") if fplan is not None \
                else None

            def _filter():
                if g_f is not None and not fstate["bailed"]:
                    r = _try_group(g_f, env)
                    if r is not None:
                        # terminal output already computed on device;
                        # the filtered env is only materialised when a
                        # later stage still needs per-row host columns
                        # (ORDER BY against a projected query)
                        if g_f.terminal == "project" and q.order_by:
                            return self._take_env(
                                env, np.flatnonzero(r.mask))
                        return env
                n = self._env_len(env)
                mask = _as_mask(self._eval(q.where, env), n)
                return self._take_env(env, np.flatnonzero(mask))

            def _filter_rows(renv):
                r = fstate["res"]
                return r.rows_filter if r is not None \
                    else self._env_len(renv)

            env = stage("filter", "WHERE", _filter, _filter_rows,
                        fused_of=lambda: _fused_gid("filter"))
        if q.group_by is not None or self._has_aggregate(q.items):
            g_a = fplan.group_with("aggregate") if fplan is not None \
                else None

            def _agg():
                r = fstate["res"]
                if r is None and g_a is not None and \
                        not fstate["bailed"]:
                    # [aggregate]-only group (WHERE absent or unfused):
                    # runs here against the already-filtered env
                    r = _try_group(g_a, env)
                return r.out if r is not None \
                    else self._aggregate(q, env, gen_items)

            out = stage("aggregate",
                        f"{len(q.group_by or [])} group keys",
                        _agg, len,
                        fused_of=lambda: _fused_gid("aggregate"))
        else:
            def _proj():
                r = fstate["res"]
                return r.out if r is not None \
                    else self._project(q.items, env, gen_items)

            out = stage("project", f"{len(q.items)} items", _proj, len,
                        fused_of=lambda: _fused_gid("project"))
        if q.order_by:
            def _order():
                grouped = q.group_by is not None or \
                    self._has_aggregate(q.items)
                keys = []
                for e, desc in reversed(q.order_by):
                    try:
                        v = self._eval(e, _Env({"_t": out}))
                    except SQLError:
                        if grouped:
                            raise  # pre-aggregation rows no longer exist
                        # non-projected or qualified column: evaluate
                        # against the pre-projection env (same row count
                        # and order as the projected output)
                        v = self._eval(e, env)
                    k = np.asarray(_numeric(v))
                    if not np.issubdtype(k.dtype, np.number):
                        # rank-encode so lexsort and DESC negation apply
                        _, k = np.unique(k, return_inverse=True)
                    keys.append(-k if desc else k)
                idx = np.lexsort(keys)
                return out.take(idx)
            out = stage("order", f"{len(q.order_by)} keys", _order, len)
        if q.limit is not None:
            out = stage("limit", str(q.limit),
                        lambda: out.head(q.limit), len)
        return out

    # -- FROM / JOIN
    def _scan_source(self, ref: TableRef, where) -> Table:
        """One FROM side: in-memory table, or a registered chip store
        scan.  ``where`` enables bbox pushdown — passed only for the
        single-table scan (a join's WHERE filters post-join rows, so
        pushing it into a side is not generally sound; joined store
        sides full-scan)."""
        if self._store_for(ref.name) is not None:
            return self._store_scan(ref.name, where)
        return self.table(ref.name)

    def _from_clause(self, q: Query) -> _Env:
        left = self._scan_source(q.table,
                                 q.where if q.join is None else None)
        lq = (q.table.alias or q.table.name).lower()
        if q.join is None:
            return _Env({lq: left})
        right = self._scan_source(q.join, None)
        rq = (q.join.alias or q.join.name).lower()
        if lq == rq:
            raise SQLError(f"self-join needs distinct aliases "
                           f"(both sides are {lq!r})")
        li, ri = self._equi_join(left, lq, right, rq, q.join_on)
        if q.join_kind == "left":
            # unmatched left rows survive with nulls on the right
            matched = np.zeros(len(left), bool)
            matched[li] = True
            lost = np.nonzero(~matched)[0]
            li = np.concatenate([li, lost])
            ri = np.concatenate([ri, np.full(len(lost), -1, np.int64)])
            order = np.argsort(li, kind="stable")
            li, ri = li[order], ri[order]
            jl = left.take(li)
            jr = Table({name: col_take_nullable(col, ri)
                        for name, col in right.columns.items()})
            return _Env({lq: jl, rq: jr})
        jl, jr = left.take(li), right.take(ri)
        return _Env({lq: jl, rq: jr})

    def _equi_join(self, left, lq, right, rq, on):
        """Hash join on a conjunction of equality predicates."""
        conjuncts: List = []

        def flat(e):
            if isinstance(e, Binary) and e.op == "and":
                flat(e.left)
                flat(e.right)
            else:
                conjuncts.append(e)

        flat(on)
        lkeys, rkeys = [], []
        for c in conjuncts:
            if not (isinstance(c, Binary) and c.op == "="):
                raise SQLError("JOIN ON supports conjunctions of "
                               "equalities only")
            le, re = c.left, c.right
            lv = self._try_eval(le, _Env({lq: left}))
            if lv is None:                 # sides written right = left
                le, re = re, le
                lv = self._try_eval(le, _Env({lq: left}))
            rv = self._try_eval(re, _Env({rq: right}))
            if lv is None or rv is None:
                raise SQLError("each JOIN equality must reference one "
                               "table per side")
            lkeys.append(np.asarray(_numeric(lv)))
            rkeys.append(np.asarray(_numeric(rv)))
        # planner strategy: dict-loop (low fixed cost) vs. vectorized
        # sort-join (wins past a few thousand rows).  Both emit pairs
        # left-ascending with right rows index-ascending within each
        # key, so the choice is invisible in the result.  The decision
        # usually rides in on the query plan; direct _equi_join calls
        # decide here.
        d = None
        if getattr(self, "_active_plan", None) is not None:
            js = next((s for s in self._active_plan.steps.values()
                       if s.op.endswith("_join")), None)
            d = getattr(js, "decision", None)
        if d is None and planner.enabled:
            d = planner.decide_equi_join(len(left), len(right))
        use_vec = (d is not None and d.strategy == "vectorized" and
                   self._vector_join_ok(lkeys, rkeys))
        t0 = time.perf_counter()
        if use_vec:
            li, ri = _vectorized_equi_join(lkeys[0], rkeys[0])
        else:
            # composite key -> dict of right-row lists
            rmap: Dict[object, List[int]] = {}
            for j in range(len(right)):
                k = tuple(rk[j] for rk in rkeys)
                rmap.setdefault(k, []).append(j)
            li, ri = [], []
            for i in range(len(left)):
                k = tuple(lk[i] for lk in lkeys)
                for j in rmap.get(k, ()):
                    li.append(i)
                    ri.append(j)
            li = np.asarray(li, np.int64)
            ri = np.asarray(ri, np.int64)
        if d is not None:
            # feed the coefficient of the path that actually ran (a
            # vectorized pick can fall back on ineligible keys)
            key = d.cost_key if use_vec or d.strategy != "vectorized" \
                else "equi_join/loop"
            planner.observe_op(key, d.key_n,
                               time.perf_counter() - t0,
                               rows_out=int(len(li)))
        return li, ri

    @staticmethod
    def _vector_join_ok(lkeys, rkeys) -> bool:
        """The sort-join handles exactly the cases where its equality
        semantics match the dict loop: one key pair, same non-object
        dtype, and no NaN keys (NaN never equals itself in the dict
        but searchsorted would pair NaNs)."""
        if len(lkeys) != 1:
            return False
        lk, rk = lkeys[0], rkeys[0]
        if lk.dtype != rk.dtype or lk.dtype.kind not in "iufSU":
            return False
        if lk.dtype.kind == "f" and (np.isnan(lk).any() or
                                     np.isnan(rk).any()):
            return False
        return True

    @staticmethod
    def _take_env(env: "_Env", idx: np.ndarray) -> "_Env":
        return _Env({qn: t.take(idx) for qn, t in env.tables.items()})

    def _try_eval(self, e, env):
        try:
            return self._eval(e, env)
        except SQLError:
            return None

    # -- generators (explode)
    def _apply_generators(self, q: Query, env: _Env):
        gens = [it for it in q.items
                if isinstance(it.expr, Call) and it.expr.name in GENERATORS]
        if not gens:
            return env, {}
        if len(gens) > 1:
            raise SQLError("only one generator per SELECT "
                           "(reference: Spark's Generate operator)")
        it = gens[0]
        call = it.expr
        args = [self._eval(a, env) for a in call.args]
        name = call.name
        if name in ("grid_tessellateexplode", "mosaic_explode"):
            chips = self.mc.call(name, *args)
            src = chips.geom_id
            gcols = {"is_core": chips.is_core.copy(),
                     "index_id": chips.cell_id.copy(),
                     "wkb": chips.geoms}
        else:
            src, cells = self.mc.call(name, *args)
            gcols = {(it.alias or "cellid"): cells}
        src = np.asarray(src, np.int64)
        env2 = _Env({qn: t.take(src) for qn, t in env.tables.items()})
        gtab = Table(gcols)
        env2.tables["#gen"] = gtab
        return env2, {id(call): gtab}

    # -- aggregation
    def _has_aggregate(self, items: Sequence[SelectItem]) -> bool:
        return any(isinstance(it.expr, Call) and
                   it.expr.name in AGGREGATES for it in items)

    def _aggregate(self, q: Query, env: _Env, gen_items) -> Table:
        n = self._env_len(env)
        if q.group_by:
            gkeys = [np.asarray(_numeric(self._eval(e, env)))
                     for e in q.group_by]
            key_rows = list(zip(*[k.tolist() for k in gkeys])) \
                if n else []
            seen: Dict[object, int] = {}
            gid = np.empty(n, np.int64)
            for i, k in enumerate(key_rows):
                gid[i] = seen.setdefault(k, len(seen))
            ngroups = len(seen)
            group_idx = [np.flatnonzero(gid == g) for g in range(ngroups)]
        else:
            group_idx = [np.arange(n)]
        if q.having is not None:
            self._having_group_by = q.group_by
            keep = _as_mask(self._eval_grouped(q.having, env,
                                               group_idx),
                            len(group_idx))
            group_idx = [g for g, k in zip(group_idx, keep) if k]
        cols: Dict[str, object] = {}
        for pos, it in enumerate(q.items):
            name = it.alias or self._default_name(it.expr, pos)
            e = it.expr
            if isinstance(e, Call) and e.name in AGGREGATES:
                cols[name] = self._agg_call(e, env, group_idx)
            else:
                # must be a constant or match a grouping expression —
                # silently taking any column's first row per group
                # masks user errors a real engine rejects (round-4
                # ADVICE).  Constants are legal alongside aggregates;
                # Column matches ignore the table qualifier (t.x
                # groups by x, like Spark's resolution).
                def _matches(a, b):
                    if a == b:
                        return True
                    return (isinstance(a, Column) and
                            isinstance(b, Column) and a.name == b.name)
                if not isinstance(e, Literal) and (
                        q.group_by is None or
                        not any(_matches(e, g) for g in q.group_by)):
                    raise SQLError(
                        f"non-aggregate SELECT item {name!r} must "
                        "appear in GROUP BY")
                vals = self._eval(e, env)
                firsts = np.asarray([g[0] for g in group_idx], np.int64)
                cols[name] = col_take(vals, firsts)
        return Table(cols)

    def _eval_grouped(self, e, env: _Env, group_idx):
        """Evaluate a HAVING expression to one value per group:
        aggregate calls run per group, other columns take each group's
        first row (they are grouping expressions)."""
        if isinstance(e, Literal):
            return e.value
        if isinstance(e, Call) and e.name in AGGREGATES:
            return self._agg_call(e, env, group_idx)
        if isinstance(e, Column):
            # same discipline as grouped SELECT items: a bare column in
            # HAVING must be a grouping expression, or the result would
            # silently depend on each group's arbitrary first row
            if self._having_group_by is None or not any(
                    e == g or (isinstance(g, Column) and
                               g.name == e.name)
                    for g in self._having_group_by):
                raise SQLError(
                    f"HAVING column {e.name!r} must appear in GROUP BY")
            vals = self._eval(e, env)
            firsts = np.asarray([g[0] for g in group_idx], np.int64)
            return _numeric(col_take(vals, firsts))
        if isinstance(e, Unary):
            if e.op == "not":
                return ~_as_mask(self._eval_grouped(e.operand, env,
                                                    group_idx),
                                 len(group_idx))
            v = self._eval_grouped(e.operand, env, group_idx)
            if e.op == "-":
                return -np.asarray(_numeric(v))
            arr = np.asarray(_numeric(v), np.float64)
            isna = np.asarray([x is None or (isinstance(x, float) and
                                             np.isnan(x))
                               for x in np.asarray(v).tolist()]) \
                if not np.issubdtype(arr.dtype, np.number) else \
                np.isnan(arr)
            return isna if e.op == "isnull" else ~isna
        if isinstance(e, Binary):
            a = self._eval_grouped(e.left, env, group_idx)
            b = self._eval_grouped(e.right, env, group_idx)
            if e.op in ("and", "or"):
                a = _as_mask(a, len(group_idx))
                b = _as_mask(b, len(group_idx))
                return (a & b) if e.op == "and" else (a | b)
            import operator as op_
            fn = {"+": op_.add, "-": op_.sub, "*": op_.mul,
                  "/": op_.truediv, "%": op_.mod,
                  "=": op_.eq, "!=": op_.ne, "<": op_.lt,
                  "<=": op_.le, ">": op_.gt, ">=": op_.ge}[e.op]
            return fn(_numeric(a), _numeric(b))
        raise SQLError(f"unsupported HAVING expression {e!r}")

    def _agg_call(self, e: Call, env: _Env, group_idx):
        if e.name == "count":
            if len(e.args) == 0 or isinstance(e.args[0], Star):
                return np.asarray([len(g) for g in group_idx],
                                  np.int64)
            # SQL semantics: count(col) skips NULL/NaN rows
            vals = self._eval(e.args[0], env)
            lst = vals if isinstance(vals, list) else \
                np.asarray(vals).tolist()
            ok = np.asarray(
                [not (v is None or (isinstance(v, float) and
                                    np.isnan(v))) for v in lst])
            return np.asarray([int(ok[g].sum()) for g in group_idx],
                              np.int64)
        if len(e.args) != 1:
            raise SQLError(f"{e.name} takes one argument")
        raw = self._eval(e.args[0], env)
        lst = raw if isinstance(raw, list) else \
            np.asarray(raw).tolist()
        # SQL NULL semantics: aggregates skip NULL (None / NaN) rows;
        # an all-null group aggregates to NULL (NaN here)
        vals = np.asarray(
            [np.nan if v is None else float(v) for v in lst])
        ok = ~np.isnan(vals)
        fn = {"sum": np.sum, "avg": np.mean, "mean": np.mean,
              "min": np.min, "max": np.max,
              "first": lambda v: v[0]}[e.name]
        out = []
        for g in group_idx:
            sel = np.asarray(g)[ok[g]] if len(g) else np.empty(0,
                                                               int)
            out.append(fn(vals[sel]) if len(sel) else np.nan)
        return np.asarray(out)

    # -- projection
    def _project(self, items, env: _Env, gen_items) -> Table:
        cols: Dict[str, object] = {}
        for pos, it in enumerate(items):
            if isinstance(it.expr, Star):
                for qn, t in env.tables.items():
                    if qn == "#gen":
                        continue
                    for cname, c in t.columns.items():
                        cols[cname if cname not in cols
                             else f"{qn}.{cname}"] = c
                if "#gen" in env.tables:
                    cols.update(env.tables["#gen"].columns)
                continue
            if isinstance(it.expr, Call) and id(it.expr) in gen_items:
                # resolve from the env's '#gen' table — _take_env has
                # already applied WHERE to it; the gen_items snapshot
                # predates the filter and only identifies generator
                # calls (round-4 ADVICE: a WHERE that dropped rows made
                # the stale snapshot ragged vs the other columns)
                cols.update(env.tables["#gen"].columns)
                continue
            name = it.alias or self._default_name(it.expr, pos)
            cols[name] = self._eval(it.expr, env)
        return Table(cols)

    @staticmethod
    def _default_name(e, pos: int) -> str:
        if isinstance(e, Column):
            return e.name
        if isinstance(e, Call):
            return e.name
        return f"col{pos}"

    # -- expression evaluation
    def _env_len(self, env: _Env) -> int:
        for t in env.tables.values():
            return len(t)
        return 0

    def _eval(self, e, env: _Env):
        if isinstance(e, Literal):
            return e.value
        if isinstance(e, Column):
            return env.resolve(e.name, e.table)
        if isinstance(e, Unary):
            v = self._eval(e.operand, env)
            if e.op == "-":
                return -np.asarray(_numeric(v))
            if e.op == "not":
                return ~_as_mask(v, self._env_len(env))
            a = np.asarray(
                [x is None or (isinstance(x, float) and np.isnan(x))
                 for x in (v if isinstance(v, list) else
                           np.asarray(v).tolist())])
            return a if e.op == "isnull" else ~a
        if isinstance(e, Binary):
            n = self._env_len(env)
            if e.op in ("and", "or"):
                a = _as_mask(self._eval(e.left, env), n)
                b = _as_mask(self._eval(e.right, env), n)
                return (a & b) if e.op == "and" else (a | b)
            a = self._eval(e.left, env)
            b = self._eval(e.right, env)
            a, b = _numeric(a), _numeric(b)
            import operator as op_
            fn = {"+": op_.add, "-": op_.sub, "*": op_.mul,
                  "/": op_.truediv, "%": op_.mod,
                  "=": op_.eq, "!=": op_.ne, "<": op_.lt,
                  "<=": op_.le, ">": op_.gt, ">=": op_.ge}[e.op]
            return fn(a, b)
        if isinstance(e, Call):
            if e.name in GENERATORS:
                raise SQLError(f"{e.name} is a generator — use it as a "
                               "top-level SELECT item")
            if e.name in AGGREGATES:
                raise SQLError(f"{e.name} requires GROUP BY context")
            from ..functions.registry import REGISTRY
            if e.name not in REGISTRY:     # pre-dispatch check so real
                raise SQLError(            # function errors surface as-is
                    f"unknown function {e.name!r}")
            args = [self._eval(a, env) for a in e.args]
            return self.mc.call(e.name, *args)
        raise SQLError(f"cannot evaluate {e!r}")


# ---------------------------------------------- batchable point lookups

#: calls the serve-layer micro-batcher may coalesce across queries:
#: elementwise cell-id assignment over scalar coordinate columns — one
#: row in, one row out, no cross-row state — so concatenating several
#: queries' rows into one padded device launch returns bit-identical
#: per-row results (serve/batching.py executes; this module only
#: classifies, because the query shape is the engine's contract)
BATCHABLE_CALLS = {"grid_longlatascellid"}


@dataclasses.dataclass(frozen=True)
class BatchableLookup:
    """Classification of one micro-batchable point-lookup query.

    ``outputs`` preserves the SELECT-item order the engine would
    produce: ``(name, column)`` entries echo a source column through
    unchanged, the single ``(name, None)`` entry is the lookup call's
    cell-id result — so the batcher can assemble a result table
    column-for-column identical to :meth:`SQLSession.sql`."""

    table: str                       # catalog name (lowercased)
    func: str                        # the BATCHABLE_CALLS member
    res: int                         # the call's literal resolution
    lon: str                         # x/longitude column name
    lat: str                         # y/latitude column name
    outputs: Tuple[Tuple[str, Optional[str]], ...]
    rows: int                        # table length at classification

    @property
    def signature(self) -> tuple:
        """Queries with equal signatures may share one device launch
        (same kernel, same static args; rows just concatenate)."""
        return (self.func, self.res)


def classify_batchable(query: str, session: "SQLSession",
                       max_rows: int = 0) -> Optional[BatchableLookup]:
    """Decide whether ``query`` is a micro-batchable point lookup.

    The shape is deliberately narrow: a single-table ``SELECT`` whose
    items are plain columns plus exactly one :data:`BATCHABLE_CALLS`
    call over ``(numeric column, numeric column, integer literal)`` —
    no join, filter, generator, aggregate, ordering, or limit, and at
    most ``max_rows`` source rows (0 = unlimited).  Anything else
    returns None and runs the ordinary ``sql()`` path; classification
    must never raise on arbitrary input (the serve layer probes every
    admitted query with it)."""
    try:
        q = parse(query)
    except Exception:
        return None                  # not even parseable SELECT syntax
    if q.explain is not None or q.join is not None or \
            q.where is not None or q.group_by is not None or \
            q.having is not None or q.order_by or q.limit is not None:
        return None
    call: Optional[Call] = None
    outputs: List[Tuple[str, Optional[str]]] = []
    for pos, it in enumerate(q.items):
        e = it.expr
        if isinstance(e, Call):
            if call is not None or e.name not in BATCHABLE_CALLS:
                return None
            if len(e.args) != 3 or \
                    not isinstance(e.args[0], Column) or \
                    not isinstance(e.args[1], Column) or \
                    not isinstance(e.args[2], Literal) or \
                    not isinstance(e.args[2].value, int):
                return None
            call = e
            outputs.append((it.alias or e.name, None))
        elif isinstance(e, Column) and e.table is None:
            outputs.append((it.alias or e.name, e.name))
        else:
            return None              # Star / expression / qualified col
    if call is None:
        return None
    try:
        table = session.table(q.table.name)
    except SQLError:
        return None
    lon, lat = call.args[0].name, call.args[1].name
    for name in {lon, lat} | {c for _, c in outputs if c is not None}:
        if name not in table.columns:
            return None
    for name in (lon, lat):
        col = table.columns[name]
        if not isinstance(col, np.ndarray) or \
                not np.issubdtype(col.dtype, np.number):
            return None
    if max_rows and len(table) > max_rows:
        return None
    return BatchableLookup(table=q.table.name.lower(), func=call.name,
                           res=int(call.args[2].value), lon=lon,
                           lat=lat, outputs=tuple(outputs),
                           rows=len(table))
