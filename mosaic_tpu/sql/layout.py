"""Learned store-layout advisor: pick the grid from the workload.

``mosaic.store.grid.res`` has been a hand-picked constant since the
chip store landed — SOLAR (arxiv 2504.01292) argues the system's own
run statistics should pick it instead, and this repo already persists
exactly the statistics that need: the partition-heat plane
(``obs/heat.py``, decayed rows/scans per store cell plus the hot/cold
skew ratio), the workload-history windows (``obs/history.py``,
partition columns per completed query), and the store manifest itself
(rows, partitions, current resolution).

:func:`advise_layout` folds that evidence into one recommendation:

* **target occupancy** — ``mosaic.layout.rows.per.cell`` rows per
  occupied cell.  Occupied-cell count scales like ``res ** d`` where
  the exponent ``d`` comes from the observed heat skew: a uniform
  workload (skew 1) fills area (``d -> 2``), a heavily skewed one
  concentrates on a corridor (``d -> 1``), so the same row count
  justifies a deeper grid.
* **shard size** — a pow2 multiple of the streamed executor's chunk
  (``mosaic.stream.chunk.rows``), at least the per-cell target, capped
  by the configured ``mosaic.store.shard.rows``: every full shard then
  feeds whole jit size classes downstream.
* **clamp** — the result never strays outside
  ``mosaic.layout.{min,max}.res``.

Consumers: ``StoreWriter(grid_res="auto")`` resolves through here at
construction time (workload evidence only — the writer hasn't seen
its data yet), ``mosaicstat layout`` prints the recommendation from
the outside, and :func:`rewrite_store` re-buckets an existing store
onto the advised grid and PROVES read-back bit-parity (byte-exact
row-multiset comparison over every column) before reporting success.
Every recommendation lands in the flight recorder as a
``layout_advice`` event with the evidence it was derived from.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import numpy as np

__all__ = ["LayoutAdvice", "advise_layout", "rewrite_store"]


@dataclasses.dataclass(frozen=True)
class LayoutAdvice:
    """One store-layout recommendation plus its provenance."""

    grid_res: int             # recommended mosaic.store.grid.res
    shard_rows: int           # recommended mosaic.store.shard.rows
    reason: str               # human-readable derivation
    evidence: Dict[str, Any]  # the stats the numbers came from


def _pow2(n: float, lo: int, hi: int) -> int:
    """Nearest power of two to ``n``, clamped to [lo, hi] (both
    assumed powers of two)."""
    n = max(float(n), 1.0)
    exp = int(round(math.log2(n)))
    return int(min(max(1 << max(exp, 0), lo), hi))


def advise_layout(store_root: Optional[str] = None, *,
                  total_rows: Optional[int] = None,
                  partitions: Optional[int] = None,
                  current_res: Optional[int] = None,
                  history_dir: Optional[str] = None,
                  record: bool = True) -> LayoutAdvice:
    """Recommend ``(grid_res, shard_rows)`` for a dataset.

    Evidence resolution, most direct first: an existing store's
    manifest (``store_root``) supplies rows / partition count /
    current resolution; explicit keyword overrides beat it; with
    neither, the heat plane's decayed row totals stand in (the
    ``grid_res="auto"`` writer path — the data hasn't been seen yet,
    so the workload that WILL read it is the only evidence there is).
    The heat skew always shapes the occupancy exponent; a history
    directory (argument, else the configured ``mosaic.history.dir``)
    contributes its touched-partition count as corroborating evidence.

    With no evidence at all the configured ``mosaic.store.grid.res``
    comes back unchanged, reason ``"no evidence"`` — auto mode never
    degrades below the static default."""
    from .. import config as _config
    from ..obs.heat import heat
    from ..perf.bucketing import pow2_bucket

    cfg = _config.default_config()
    target = max(1, int(cfg.layout_rows_per_cell))
    lo = max(1, int(cfg.layout_min_res))
    hi = max(lo, int(cfg.layout_max_res))

    evidence: Dict[str, Any] = {}
    if store_root:
        from ..store.manifest import Manifest
        man = Manifest.load(store_root)
        if total_rows is None:
            total_rows = int(man.total_rows)
        if partitions is None:
            partitions = len(man.partitions)
        if current_res is None:
            current_res = int(man.grid_res)
        evidence["manifest"] = {"root": str(store_root),
                                "total_rows": int(man.total_rows),
                                "partitions": len(man.partitions),
                                "grid_res": int(man.grid_res)}

    rep = heat.report(top=1)
    skew = max(1.0, float(rep.get("skew", 1.0)))
    evidence["heat"] = {"tracked": int(rep.get("tracked", 0)),
                        "total_rows": float(rep.get("total_rows", 0.0)),
                        "skew": skew}
    if total_rows is None and rep.get("tracked"):
        total_rows = int(rep["total_rows"])

    hist_dir = history_dir or cfg.history_dir
    if hist_dir:
        try:
            from ..obs.history import report as _hreport
            totals = _hreport(hist_dir, None)["totals"]
            hist_parts = len(totals.get("partitions", {}))
            evidence["history"] = {"queries": int(totals["queries"]),
                                   "partitions": hist_parts}
            if partitions is None and hist_parts:
                partitions = hist_parts
        except Exception:
            pass                    # corroboration only, never a gate

    chunk = pow2_bucket(int(cfg.stream_chunk_rows), floor=64)
    shard_cap = pow2_bucket(int(cfg.store_shard_rows), floor=chunk)
    shard_rows = min(max(chunk, pow2_bucket(target, floor=64)),
                     shard_cap)

    if not total_rows:
        adv = LayoutAdvice(int(cfg.store_grid_res), shard_rows,
                           "no evidence: configured default", evidence)
    else:
        # occupied cells ~ res ** d; skewed workloads concentrate on a
        # corridor (d -> 1), uniform ones fill area (d -> 2)
        d = 1.0 + 1.0 / skew
        if partitions and current_res:
            # rescale the OBSERVED occupancy from the current grid:
            # occupied(res) = partitions * (res / current_res) ** d
            res_f = current_res * (total_rows /
                                   (target * partitions)) ** (1.0 / d)
        else:
            res_f = (total_rows / target) ** (1.0 / d)
        res = _pow2(res_f, lo, hi)
        adv = LayoutAdvice(
            res, shard_rows,
            f"{total_rows} rows / {target} per cell at skew "
            f"{skew:.2f} (d={d:.2f}) -> res {res}", evidence)

    if record:
        from ..obs.recorder import recorder
        recorder.record("layout_advice", grid_res=adv.grid_res,
                        shard_rows=adv.shard_rows, reason=adv.reason,
                        evidence=adv.evidence)
    return adv


def _canonical_rows(cols: Dict[str, np.ndarray]) -> np.ndarray:
    """Byte-exact sortable view of a column dict's row multiset:
    rows packed into one record array, viewed as raw bytes (void), and
    sorted — NaN payloads and signed zeros compare by bit pattern, so
    equality here IS bit-parity, not value-parity."""
    names = sorted(cols)
    n = int(cols[names[0]].shape[0]) if names else 0
    packed = np.empty(n, dtype=[(c, cols[c].dtype) for c in names])
    for c in names:
        packed[c] = np.ascontiguousarray(cols[c])
    flat = np.ascontiguousarray(packed).view(
        [("", f"V{max(packed.dtype.itemsize, 1)}")]).ravel()
    return np.sort(flat)


def rewrite_store(src_root: str, dst_root: str, *,
                  grid_res: Optional[int] = None,
                  shard_rows: Optional[int] = None,
                  advice: Optional[LayoutAdvice] = None
                  ) -> Tuple["object", LayoutAdvice]:
    """Re-bucket an existing store onto an advised layout, with proof.

    Streams every partition of ``src_root`` (one partition's columns
    in memory at a time) into a fresh :class:`~..store.writer.
    StoreWriter` at ``dst_root`` using ``advice`` (computed from the
    source store when not supplied; explicit ``grid_res`` /
    ``shard_rows`` override it).  Before returning, reads BOTH stores
    back in full and compares their row multisets byte-for-byte over
    every column — a mismatch raises ``AssertionError`` and the
    destination should be discarded.  Returns ``(manifest, advice)``.

    Row order is the one thing a re-bucket legitimately changes (rows
    regroup under new cells), which is why the proof is multiset
    parity; within a destination partition, source order is preserved
    (the writer's stable bucketing sort)."""
    from ..obs import metrics
    from ..store.reader import ChipStore
    from ..store.writer import StoreWriter

    src = ChipStore(src_root)
    if advice is None:
        advice = advise_layout(store_root=src_root)
    res = int(grid_res or advice.grid_res)
    rows = int(shard_rows or advice.shard_rows)
    xcol, ycol = src.point_cols
    w = StoreWriter(dst_root, grid_res=res, shard_rows=rows,
                    point_cols=src.point_cols)
    moved = 0
    for part in src.partitions:
        cols = src.read_partition(part)
        pts = np.stack([cols.pop(xcol), cols.pop(ycol)], axis=1)
        moved += w.append(pts, cols or None)
    man = w.finalize()

    # read-back bit-parity proof: every row of the source must come
    # back from the destination byte-identical (as a multiset)
    dst = ChipStore(dst_root)
    a = _canonical_rows(src.read_columns())
    b = _canonical_rows(dst.read_columns())
    if a.shape != b.shape or not np.array_equal(a, b):
        raise AssertionError(
            f"rewrite_store parity proof failed: {src_root} !~ "
            f"{dst_root} ({a.shape[0]} vs {b.shape[0]} rows)")
    if metrics.enabled:
        metrics.count("layout/rows_rewritten", float(moved))
    return man, advice
