"""SQL parser for the mosaic_tpu SQL surface.

Reference counterpart: sql/extensions/MosaicSQL.scala:21-47 registers the
function surface into Spark's SQL parser; here (no Spark) a small
recursive-descent parser covers the query shapes the reference's docs and
Quickstart notebook actually use: projections with ``st_*``/``grid_*``
function calls, tessellate-explode generators, equi-joins on cell id,
filters (``is_core OR st_contains(...)``), grouped aggregation, ordering
and limits.

Grammar (case-insensitive keywords)::

    query   := (EXPLAIN ANALYZE?)? SELECT item (',' item)* FROM ref
               (JOIN ref ON expr)?
               (WHERE expr)? (GROUP BY expr (',' expr)*)?
               (ORDER BY expr (ASC|DESC)?)? (LIMIT int)?
    ref     := ident (AS? ident)?
    item    := '*' | expr (AS? ident)?
    expr    := OR-chain of AND-chains of NOT/comparison/arith terms;
               calls ``f(a, b, ...)``, qualified names ``t.col``,
               numeric/string/bool/NULL literals, parens, unary '-',
               ``IS [NOT] NULL``.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Tuple


# ---------------------------------------------------------------- AST

@dataclasses.dataclass
class Literal:
    value: object


@dataclasses.dataclass
class Column:
    name: str
    table: Optional[str] = None


@dataclasses.dataclass
class Star:
    pass


@dataclasses.dataclass
class Call:
    name: str
    args: List[object]


@dataclasses.dataclass
class Unary:
    op: str                    # '-' | 'not' | 'isnull' | 'notnull'
    operand: object


@dataclasses.dataclass
class Binary:
    op: str
    left: object
    right: object


@dataclasses.dataclass
class SelectItem:
    expr: object
    alias: Optional[str] = None


@dataclasses.dataclass
class TableRef:
    name: str
    alias: Optional[str] = None


@dataclasses.dataclass
class Query:
    items: List[SelectItem]
    table: TableRef
    join: Optional[TableRef] = None
    join_on: Optional[object] = None
    join_kind: str = "inner"
    where: Optional[object] = None
    group_by: Optional[List[object]] = None
    having: Optional[object] = None
    order_by: Optional[List[Tuple[object, bool]]] = None   # (expr, desc)
    limit: Optional[int] = None
    explain: Optional[str] = None      # None | 'plan' | 'analyze'


# ------------------------------------------------------------- tokens

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<num>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?
             |\d+(?:[eE][+-]?\d+)?)
    | (?P<str>'(?:[^']|'')*')
    | (?P<id>[A-Za-z_][A-Za-z_0-9]*)
    | (?P<op><>|!=|<=|>=|==|[=<>+\-*/%(),.\*])
    )""", re.VERBOSE)

_KEYWORDS = {"select", "from", "where", "group", "by", "order", "limit",
             "and", "or", "not", "as", "join", "on", "asc", "desc",
             "true", "false", "null", "is", "inner", "left", "outer",
             "having", "explain", "analyze"}


def _tokenize(sql: str) -> List[Tuple[str, str]]:
    out, pos = [], 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if not m or m.end() == pos:
            if sql[pos:].strip():
                raise SQLParseError(f"unexpected character at: "
                                    f"{sql[pos:pos+20]!r}")
            break
        pos = m.end()
        if m.lastgroup == "num":
            out.append(("num", m.group("num")))
        elif m.lastgroup == "str":
            out.append(("str", m.group("str")[1:-1].replace("''", "'")))
        elif m.lastgroup == "id":
            word = m.group("id")
            if word.lower() in _KEYWORDS:
                out.append(("kw", word.lower()))
            else:
                out.append(("id", word))
        else:
            out.append(("op", m.group("op")))
    out.append(("eof", ""))
    return out


class SQLParseError(ValueError):
    pass


class _Parser:
    def __init__(self, sql: str):
        self.toks = _tokenize(sql)
        self.i = 0

    # -- token helpers
    def peek(self) -> Tuple[str, str]:
        return self.toks[self.i]

    def next(self) -> Tuple[str, str]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind: str, val: Optional[str] = None) -> bool:
        k, v = self.peek()
        if k == kind and (val is None or v == val):
            self.i += 1
            return True
        return False

    def expect(self, kind: str, val: Optional[str] = None) -> str:
        k, v = self.next()
        if k != kind or (val is not None and v != val):
            want = val or kind
            raise SQLParseError(f"expected {want!r}, got {v!r}")
        return v

    # -- grammar
    def query(self) -> Query:
        explain = None
        if self.accept("kw", "explain"):
            explain = "analyze" if self.accept("kw", "analyze") \
                else "plan"
        self.expect("kw", "select")
        items = [self.select_item()]
        while self.accept("op", ","):
            items.append(self.select_item())
        self.expect("kw", "from")
        table = self.table_ref()
        join = join_on = None
        join_kind = "inner"
        if self.accept("kw", "inner"):
            self.expect("kw", "join")
            join = self.table_ref()
            self.expect("kw", "on")
            join_on = self.expr()
        elif self.accept("kw", "left"):
            self.accept("kw", "outer")
            self.expect("kw", "join")
            join_kind = "left"
            join = self.table_ref()
            self.expect("kw", "on")
            join_on = self.expr()
        elif self.accept("kw", "join"):
            join = self.table_ref()
            self.expect("kw", "on")
            join_on = self.expr()
        where = None
        if self.accept("kw", "where"):
            where = self.expr()
        group_by = None
        having = None
        if self.accept("kw", "group"):
            self.expect("kw", "by")
            group_by = [self.expr()]
            while self.accept("op", ","):
                group_by.append(self.expr())
        # standard SQL allows HAVING without GROUP BY (whole-table
        # implicit group)
        if self.accept("kw", "having"):
            having = self.expr()

        order_by = None
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            order_by = [self.order_item()]
            while self.accept("op", ","):
                order_by.append(self.order_item())
        limit = None
        if self.accept("kw", "limit"):
            limit = int(self.expect("num"))
        self.expect("eof")
        return Query(items, table, join, join_on, join_kind, where,
                     group_by, having, order_by, limit, explain)

    def order_item(self) -> Tuple[object, bool]:
        e = self.expr()
        desc = False
        if self.accept("kw", "desc"):
            desc = True
        else:
            self.accept("kw", "asc")
        return (e, desc)

    def table_ref(self) -> TableRef:
        name = self.expect("id")
        alias = None
        if self.accept("kw", "as"):
            alias = self.expect("id")
        elif self.peek()[0] == "id":
            alias = self.next()[1]
        return TableRef(name, alias)

    def select_item(self) -> SelectItem:
        if self.accept("op", "*"):
            return SelectItem(Star())
        e = self.expr()
        alias = None
        if self.accept("kw", "as"):
            alias = self.expect("id")
        elif self.peek()[0] == "id":
            alias = self.next()[1]
        return SelectItem(e, alias)

    def expr(self):
        return self.or_expr()

    def or_expr(self):
        e = self.and_expr()
        while self.accept("kw", "or"):
            e = Binary("or", e, self.and_expr())
        return e

    def and_expr(self):
        e = self.not_expr()
        while self.accept("kw", "and"):
            e = Binary("and", e, self.not_expr())
        return e

    def not_expr(self):
        if self.accept("kw", "not"):
            return Unary("not", self.not_expr())
        return self.comparison()

    def comparison(self):
        e = self.additive()
        k, v = self.peek()
        if k == "op" and v in ("=", "==", "!=", "<>", "<", "<=", ">", ">="):
            self.next()
            op = {"==": "=", "<>": "!="}.get(v, v)
            return Binary(op, e, self.additive())
        if k == "kw" and v == "is":
            self.next()
            if self.accept("kw", "not"):
                self.expect("kw", "null")
                return Unary("notnull", e)
            self.expect("kw", "null")
            return Unary("isnull", e)
        return e

    def additive(self):
        e = self.multiplicative()
        while True:
            k, v = self.peek()
            if k == "op" and v in ("+", "-"):
                self.next()
                e = Binary(v, e, self.multiplicative())
            else:
                return e

    def multiplicative(self):
        e = self.unary()
        while True:
            k, v = self.peek()
            if k == "op" and v in ("*", "/", "%"):
                self.next()
                e = Binary(v, e, self.unary())
            else:
                return e

    def unary(self):
        if self.accept("op", "-"):
            return Unary("-", self.unary())
        return self.primary()

    def primary(self):
        k, v = self.next()
        if k == "num":
            return Literal(float(v) if ("." in v or "e" in v.lower())
                           else int(v))
        if k == "str":
            return Literal(v)
        if k == "kw" and v in ("true", "false"):
            return Literal(v == "true")
        if k == "kw" and v == "null":
            return Literal(None)
        if k == "op" and v == "(":
            e = self.expr()
            self.expect("op", ")")
            return e
        if k == "id":
            # call?
            if self.accept("op", "("):
                if self.accept("op", "*"):       # count(*)
                    self.expect("op", ")")
                    return Call(v.lower(), [Star()])
                args = []
                if not self.accept("op", ")"):
                    args.append(self.expr())
                    while self.accept("op", ","):
                        args.append(self.expr())
                    self.expect("op", ")")
                return Call(v.lower(), args)
            # qualified column?
            if self.accept("op", "."):
                col = self.expect("id")
                return Column(col, table=v)
            return Column(v)
        raise SQLParseError(f"unexpected token {v!r}")


def parse(sql: str) -> Query:
    return _Parser(sql).query()
