"""Cost-based adaptive query planner.

The engine has accumulated several real execution strategies for the
same logical operator — the dict-loop vs. vectorized equi-join in
``sql/engine.py``, brute vs. ring KNN in ``models/knn.py``, the
monolithic vs. streamed vs. sharded PIP join family in
``parallel/pip_join.py``, and the streamed executor's chunk classes —
but until now every call site hard-coded its path.  This module picks
the path per query from a cheap pre-pass (row counts, bbox overlap
fraction) plus **observed** per-(operator, pow2 size-class) cost
coefficients, and closes the loop after execution: estimated vs.
actual rows and wall time feed back into the bounded coefficient
store, so the second run of a workload plans from measurements, not
guesses (SOLAR, arxiv 2504.01292; Adaptive Geospatial Joins, arxiv
1802.09488 — the right strategy flips with cardinality/selectivity).

Planner choices are **pure strategy transforms**: every candidate
path produces bit-for-bit identical results, so the planner can only
change *where and how fast* the answer is computed, never the answer.
Escape hatches: ``mosaic.planner.enabled`` (default on) and
``mosaic.planner.force.<op>`` conf keys (see ``config.py``).

Observability contract: every decision counts into
``planner/decisions`` (+ ``planner/decisions/<op>``), every closed
estimate lands in the ``planner/estimate_error`` histogram (ratio
``max(est, actual) / min(est, actual)``, so 1.0 = perfect), errors
above :data:`MISPREDICT_FACTOR` count into ``planner/mispredicts``,
and decisions/mispredicts are flight-recorder events.  Learned
coefficients persist across processes via ``mosaic.planner.stats.path``
/ ``MOSAIC_TPU_PLANNER_STATS`` (the ``mosaic.jit.cache.dir`` pattern);
a corrupt stats file degrades to a cold start — it never kills the
process (resilience probe site ``planner.stats.load``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs import metrics, recorder
from ..perf.bucketing import pow2_bucket

__all__ = ["Planner", "Decision", "PlanStep", "QueryPlan", "planner",
           "FORCE_CHOICES", "STATS_PATH_ENV", "STATS_VERSION",
           "MISPREDICT_FACTOR"]

#: env var mirroring the ``mosaic.planner.stats.path`` conf key
STATS_PATH_ENV = "MOSAIC_TPU_PLANNER_STATS"
#: on-disk schema version; a file with any other version is ignored
#: (treated as cold, never an error)
STATS_VERSION = 1
#: an estimate off by more than this factor counts as a mispredict
MISPREDICT_FACTOR = 2.0

#: plannable operators and the strategies ``mosaic.planner.force.<op>``
#: accepts ("auto" clears the force)
FORCE_CHOICES = {
    "equi_join": ("auto", "loop", "vectorized"),
    "knn": ("auto", "brute", "ring"),
    "pip_join": ("auto", "monolithic", "streamed", "sharded"),
    "fusion": ("auto", "on", "off"),
    "refine": ("auto", "refined", "flat"),
}

#: EWMA weight of the newest observation in the coefficient store
_ALPHA = 0.4
#: coefficient-store entry cap (LRU beyond this)
_STORE_CAP = 1024
#: below this combined row count the dict-loop join beats the
#: vectorized sort-join's fixed overhead (cold-start crossover; the
#: learned per-size-class coefficients override it once calibrated)
_JOIN_VECTOR_CROSSOVER = 4096
#: below this input row count a fused group's dispatch+fetch overhead
#: beats the saved host round-trips (cold-start crossover; learned
#: fused-vs-unfused coefficients override it once calibrated)
_FUSION_CROSSOVER = 1024
#: cold-start crossover for adaptive PIP refinement: refine only when
#: at least this fraction of the estimated candidate pairs sits in the
#: dense cells (otherwise the second index buys back too little probe
#: work); learned refined-vs-flat coefficients override it
_REFINE_PAIR_CROSSOVER = 0.5


@dataclasses.dataclass
class Decision:
    """One strategy choice, with enough context to close the loop."""

    op: str                 # plannable operator ("knn", "pip_join", ...)
    strategy: str           # chosen path
    reason: str             # human-readable why (EXPLAIN strategy col)
    est_rows: int = -1      # estimated input/output rows (-1 unknown)
    cost_key: str = ""      # coefficient-store op key for feedback
    key_n: int = 0          # the n the size-class bucket was taken from
    forced: bool = False    # an escape hatch pinned this, not the model

    @property
    def label(self) -> str:
        return f"{self.strategy}: {self.reason}" if self.reason \
            else self.strategy


@dataclasses.dataclass
class PlanStep:
    """Per-operator estimate for one SQL query (EXPLAIN row)."""

    op: str
    est_rows: int
    strategy: str = "-"
    reason: str = ""
    key_n: int = 0          # input rows the ratio estimate was keyed on
    est_bytes: int = -1     # est_rows x source row width (-1 unknown)
                            # — the memory budget's admission estimate

    @property
    def label(self) -> str:
        return f"{self.strategy}: {self.reason}" if self.reason \
            else self.strategy


class QueryPlan:
    """Ordered per-operator :class:`PlanStep` map for one query."""

    def __init__(self):
        self.steps: "OrderedDict[str, PlanStep]" = OrderedDict()
        #: the fusion pass's :class:`~...perf.fusion.FusionPlan` (None
        #: when fusion is off, ineligible, or decided against)
        self.fusion = None

    def add(self, step: PlanStep) -> PlanStep:
        self.steps[step.op] = step
        return step

    def est(self, op: str) -> int:
        s = self.steps.get(op)
        return s.est_rows if s is not None else -1

    def est_bytes_peak(self) -> int:
        """The widest single operator's byte estimate (stages run one
        at a time, so the peak — not the sum — is what admission
        checks against the budget); 0 when no step has one."""
        return max((s.est_bytes for s in self.steps.values()
                    if s.est_bytes > 0), default=0)

    def label(self, op: str) -> str:
        s = self.steps.get(op)
        return s.label if s is not None else "-"


def _bucket(n: int) -> int:
    return pow2_bucket(max(int(n), 1))


def _row_bytes(table) -> int:
    """Bytes per materialized row of a table: dtype itemsize summed
    over columns, 8 per column without a dtype (object/geometry refs).
    Feeds the ``est_bytes`` pre-pass — a width estimate, not an exact
    footprint."""
    try:
        cols = table.columns
    except Exception:
        return 0
    total = 0
    for c in cols.values():
        dt = getattr(c, "dtype", None)
        total += int(getattr(dt, "itemsize", 0) or 8)
    return total


class Planner:
    """Process-level cost model + decision/feedback API.

    Thread-safe; all state lives in two bounded EWMA stores keyed
    ``(op, pow2 size-class)``:

    * ``ms_per_row`` — observed wall ms per input row of a strategy
      (the per-size-class key absorbs fixed setup cost: small buckets
      carry the amortized overhead that makes streaming lose there).
    * ``ratio`` — observed output rows / input rows of an operator
      (join fanout, filter selectivity, generator explosion factor).
    """

    def __init__(self, stats_path: Optional[str] = None):
        self._lock = threading.RLock()
        self._ms: "OrderedDict[Tuple[str, int], float]" = OrderedDict()
        self._ratio: "OrderedDict[Tuple[str, int], float]" = \
            OrderedDict()
        self.decisions = 0
        self.mispredicts = 0
        self.observations = 0
        #: recent estimate-error ratios (>= 1.0), newest last — tests
        #: and the bench report compute windowed percentiles from this
        self.error_history: "deque[float]" = deque(maxlen=2048)
        self._stats_path = stats_path
        self._loaded = False
        if stats_path:
            self.load(stats_path)

    # ------------------------------------------------------- switches

    @property
    def enabled(self) -> bool:
        from ..config import default_config
        return bool(getattr(default_config(), "planner_enabled", True))

    def force_for(self, op: str) -> str:
        """The ``mosaic.planner.force.<op>`` pin ("auto" = none)."""
        from ..config import default_config, planner_force_for
        return planner_force_for(default_config(), op)

    def chunk_rows(self) -> int:
        """The streamed executor's configured chunk size
        (``mosaic.stream.chunk.rows``)."""
        from ..config import default_config
        return int(getattr(default_config(), "stream_chunk_rows",
                           262_144))

    # ------------------------------------------------ coefficient store

    def _put(self, store: "OrderedDict", key: Tuple[str, int],
             value: float) -> None:
        prev = store.get(key)
        store[key] = value if prev is None else \
            (1.0 - _ALPHA) * prev + _ALPHA * value
        store.move_to_end(key)
        while len(store) > _STORE_CAP:
            store.popitem(last=False)

    def _get(self, store: "OrderedDict", op: str,
             n: int) -> Optional[float]:
        """Exact (op, bucket) hit, else the op's nearest known bucket
        (log-distance) — a coefficient learned at 32k rows is a better
        guess for 64k than nothing at all."""
        b = _bucket(n)
        v = store.get((op, b))
        if v is not None:
            return v
        best, best_d = None, None
        for (o, ob), val in store.items():
            if o != op:
                continue
            d = abs(ob.bit_length() - b.bit_length())
            if best_d is None or d < best_d:
                best, best_d = val, d
        return best

    def ms_per_row(self, op: str, n: int) -> Optional[float]:
        with self._lock:
            return self._get(self._ms, op, n)

    def ratio(self, op: str, n: int) -> Optional[float]:
        with self._lock:
            return self._get(self._ratio, op, n)

    def est_cost_ms(self, op: str, n: int) -> Optional[float]:
        c = self.ms_per_row(op, n)
        return None if c is None else c * max(int(n), 1)

    # ------------------------------------------------------- decisions

    def record_decision(self, d: Decision) -> Decision:
        with self._lock:
            self.decisions += 1
        if metrics.enabled:
            metrics.count("planner/decisions")
            metrics.count(f"planner/decisions/{d.op}")
            if d.forced:
                metrics.count("planner/forced")
        recorder.record("planner_decision", op=d.op,
                        strategy=d.strategy, reason=d.reason,
                        est_rows=int(d.est_rows), forced=d.forced)
        return d

    def decide_equi_join(self, nl: int, nr: int) -> Decision:
        """Dict-loop vs. vectorized sort-join (both emit pairs in the
        identical left-ascending / right-ascending-within-key order)."""
        n = nl + nr
        forced = self.force_for("equi_join")
        if forced != "auto":
            return self.record_decision(Decision(
                "equi_join", forced, "forced by conf", n,
                cost_key=f"equi_join/{forced}", key_n=n, forced=True))
        c_loop = self.est_cost_ms("equi_join/loop", n)
        c_vec = self.est_cost_ms("equi_join/vectorized", n)
        if c_loop is not None and c_vec is not None:
            s = "loop" if c_loop <= c_vec else "vectorized"
            why = (f"learned {min(c_loop, c_vec):.3g}ms vs "
                   f"{max(c_loop, c_vec):.3g}ms at {n} rows")
        else:
            s = "loop" if n < _JOIN_VECTOR_CROSSOVER else "vectorized"
            why = (f"{nl}+{nr} rows "
                   f"{'<' if s == 'loop' else '>='} "
                   f"{_JOIN_VECTOR_CROSSOVER} crossover")
        return self.record_decision(Decision(
            "equi_join", s, why, n, cost_key=f"equi_join/{s}",
            key_n=n))

    def decide_knn(self, n_left: int, n_right: int,
                   default_max: int) -> Decision:
        """Brute all-pairs device pass vs. ring marching (both exact,
        both tie-break by right id — identical output).  The conf
        force (``mosaic.knn.strategy``) is resolved by the caller;
        this is the "auto" path."""
        forced = self.force_for("knn")
        if forced != "auto":
            return self.record_decision(Decision(
                "knn", forced, "forced by conf", n_left,
                cost_key=f"knn/{forced}", key_n=n_left, forced=True))
        c_b = self.est_cost_ms("knn/brute", n_left)
        c_r = self.est_cost_ms("knn/ring", n_left)
        # memory guard: the brute pass streams left blocks against the
        # WHOLE right side — never auto-pick it far past the threshold
        brute_ok = 0 < n_right <= 4 * max(default_max, 1)
        if c_b is not None and c_r is not None and brute_ok:
            s = "brute" if c_b <= c_r else "ring"
            why = (f"learned {min(c_b, c_r):.3g}ms vs "
                   f"{max(c_b, c_r):.3g}ms, right={n_right}")
        else:
            s = "brute" if 0 < n_right <= default_max else "ring"
            why = (f"right {n_right} "
                   f"{'<=' if s == 'brute' else '>'} "
                   f"threshold {default_max}")
        return self.record_decision(Decision(
            "knn", s, why, n_left, cost_key=f"knn/{s}", key_n=n_left))

    def pip_join_candidates(self, n: int, mesh_devices: int = 1
                            ) -> List[Tuple[str, int]]:
        """(strategy, chunk) candidates for an ``n``-point join —
        every one produces bit-identical zones.  Streamed appears in
        two chunk classes (the configured one and one 8x smaller)
        because the throughput plateau moves with the backend."""
        chunk = self.chunk_rows()
        cands: List[Tuple[str, int]] = []
        if n <= chunk:
            cands.append(("monolithic", max(n, 1)))
        cands.append(("streamed", chunk))
        if chunk >= (1 << 17) and n > chunk // 8:
            cands.append(("streamed", chunk // 8))
        if mesh_devices > 1:
            cands.append(("sharded", chunk))
        return cands

    @staticmethod
    def pip_cost_key(strategy: str, chunk: int) -> str:
        if strategy == "streamed":
            return f"pip_join/streamed/c{int(chunk).bit_length()}"
        return f"pip_join/{strategy}"

    def decide_pip_join(self, n: int, mesh_devices: int = 1,
                        in_extent_frac: Optional[float] = None
                        ) -> Decision:
        """Monolithic vs. streamed (per chunk class) vs. sharded.

        ``in_extent_frac`` is the cheap bbox-overlap sketch: the
        fraction of the point batch's bbox that intersects the
        polygon index's extent (an upper bound on matched rows) — it
        feeds the estimate the EXPLAIN strategy column prints."""
        est = int(n if in_extent_frac is None
                  else round(n * max(0.0, min(1.0, in_extent_frac))))
        forced = self.force_for("pip_join")
        if forced != "auto":
            chunk = self.chunk_rows()
            return self.record_decision(Decision(
                "pip_join", forced, "forced by conf", est,
                cost_key=self.pip_cost_key(forced, chunk), key_n=n,
                forced=True))
        cands = self.pip_join_candidates(n, mesh_devices)
        costs = [(self.est_cost_ms(self.pip_cost_key(s, c), n), s, c)
                 for s, c in cands]
        known = [(ms, s, c) for ms, s, c in costs if ms is not None]
        if known:
            ms, s, chunk = min(known, key=lambda t: t[0])
            why = (f"learned {ms:.3g}ms at est {_fmt_rows(est)} rows "
                   f"({len(known)}/{len(cands)} candidates "
                   f"calibrated)")
        else:
            chunk = self.chunk_rows()
            if n <= chunk:
                s, why = "monolithic", (f"est {_fmt_rows(est)} rows "
                                        f"<= chunk {chunk}")
            else:
                s, why = "streamed", (f"est {_fmt_rows(est)} rows > "
                                      f"chunk {chunk}")
        d = Decision("pip_join", s, why, est,
                     cost_key=self.pip_cost_key(s, chunk), key_n=n)
        d.chunk = chunk           # dynamic attr: the chosen chunk rows
        return self.record_decision(d)

    def decide_fusion(self, opset: str, member_ops: List[str],
                      n: int) -> Decision:
        """Fused whole-group XLA program vs. per-operator dispatch
        (bit-identical either way — ``perf.fusion`` only admits ops
        whose fused evaluation provably matches the host path).

        Learned comparison: the group's ``fusion/<opset>`` coefficient
        (fed by every fused execution) against the SUM of the member
        operators' unfused coefficients (fed by every unfused run of
        the same stages) at this size class; static row-count
        crossover while either side is cold."""
        forced = self.force_for("fusion")
        if forced != "auto":
            s = "fused" if forced == "on" else "unfused"
            return self.record_decision(Decision(
                "fusion", s, "forced by conf", n,
                cost_key=f"fusion/{opset}", key_n=n, forced=True))
        c_f = self.est_cost_ms(f"fusion/{opset}", n)
        mcosts = [self.est_cost_ms(op, n) for op in member_ops]
        c_u = sum(mcosts) if all(c is not None for c in mcosts) \
            else None
        if c_f is not None and c_u is not None:
            s = "fused" if c_f <= c_u else "unfused"
            why = (f"learned fused {c_f:.3g}ms vs unfused "
                   f"{c_u:.3g}ms at {_fmt_rows(n)} rows")
        else:
            s = "fused" if n >= _FUSION_CROSSOVER else "unfused"
            why = (f"{_fmt_rows(n)} rows "
                   f"{'>=' if s == 'fused' else '<'} "
                   f"{_FUSION_CROSSOVER} crossover (cold)")
        return self.record_decision(Decision(
            "fusion", s, why, n, cost_key=f"fusion/{opset}", key_n=n))

    def decide_refine(self, n: int, dense_pair_frac: float,
                      max_dup: int, depth: Optional[int] = None
                      ) -> Decision:
        """Adaptive per-cell PIP refinement vs. the flat single-level
        join (bit-identical either way — the refined path shares the
        flat path's base index and only re-tessellates the dense cells'
        polygons one level deeper; see ``make_refined_pip_join``).

        ``dense_pair_frac`` is the measured selectivity signal: the
        fraction of estimated candidate pairs (sampled points x chips
        sharing their cell) that land in the dense-cell set.
        ``max_dup`` is the base index's probe width — when every cell
        holds few chips there is nothing to refine away.  The kill
        switch (``mosaic.join.refine.enabled = false``) beats any pin,
        mirroring fusion's contract."""
        from ..config import default_config
        cfg = default_config()
        if depth is None:
            depth = int(getattr(cfg, "join_refine_depth", 1))
        if not bool(getattr(cfg, "join_refine_enabled", True)):
            d = Decision("refine", "flat", "disabled by conf", n,
                         cost_key="refine/flat", key_n=n, forced=True)
            d.depth = depth
            return self.record_decision(d)
        forced = self.force_for("refine")
        if forced != "auto":
            d = Decision("refine", forced, "forced by conf", n,
                         cost_key=f"refine/{forced}", key_n=n,
                         forced=True)
            d.depth = depth
            return self.record_decision(d)
        dup_floor = int(getattr(cfg, "join_refine_dup_threshold", 8))
        c_r = self.est_cost_ms("refine/refined", n)
        c_f = self.est_cost_ms("refine/flat", n)
        if c_r is not None and c_f is not None:
            s = "refined" if c_r <= c_f else "flat"
            why = (f"learned {min(c_r, c_f):.3g}ms vs "
                   f"{max(c_r, c_f):.3g}ms at {_fmt_rows(n)} rows")
        elif dense_pair_frac >= _REFINE_PAIR_CROSSOVER and \
                max_dup >= dup_floor:
            s = "refined"
            why = (f"dense pair frac {dense_pair_frac:.2f} >= "
                   f"{_REFINE_PAIR_CROSSOVER} at dup {max_dup} (cold)")
        else:
            s = "flat"
            why = (f"dense pair frac {dense_pair_frac:.2f} < "
                   f"{_REFINE_PAIR_CROSSOVER} or dup {max_dup} < "
                   f"{dup_floor} (cold)")
        d = Decision("refine", s, why, n, cost_key=f"refine/{s}",
                     key_n=n)
        d.depth = depth           # dynamic attr: levels to deepen by
        return self.record_decision(d)

    # ----------------------------------------------------- SQL pre-pass

    def plan_query(self, q, session) -> Optional[QueryPlan]:
        """Cheap pre-pass over a parsed :class:`~.parser.Query`: exact
        scan cardinalities from the catalog, learned ratios for
        everything downstream.  Returns None when the referenced
        tables are unknown (the engine raises its own error)."""
        from .parser import Call
        from .engine import GENERATORS
        try:
            left = session.table(q.table.name)
        except Exception:
            return None
        plan = QueryPlan()
        nl = len(left)
        row_width = _row_bytes(left)
        if q.join is not None:
            try:
                right = session.table(q.join.name)
            except Exception:
                return None
            nr = len(right)
            row_width += _row_bytes(right)
            op = f"{q.join_kind}_join"
            n_in = nl + nr
            r = self.ratio(op, n_in)
            if r is not None:
                rows = int(round(r * max(n_in, 1)))
                why_est = "learned fanout"
            else:
                rows = max(nl, nr)
                why_est = "cold: max(sides)"
            d = self.decide_equi_join(nl, nr)
            step = plan.add(PlanStep(op, rows, d.strategy,
                                     f"{d.reason}; est "
                                     f"{_fmt_rows(rows)} rows "
                                     f"({why_est})", key_n=n_in))
            step.decision = d   # _equi_join executes this exact pick
        else:
            rows = nl
            plan.add(PlanStep("scan", rows, "scan",
                              f"{_fmt_rows(rows)} rows (exact)",
                              key_n=nl))
        gens = [it.expr.name for it in q.items
                if isinstance(it.expr, Call) and
                it.expr.name in GENERATORS]
        if gens:
            op = f"generate/{gens[0]}"
            r = self.ratio(op, rows)
            fan = r if r is not None else 4.0
            key_n = rows
            rows = int(round(fan * max(rows, 1)))
            plan.add(PlanStep("generate", rows, gens[0],
                              f"est {fan:.2g}x fanout "
                              f"{'(learned)' if r is not None else '(cold)'}",
                              key_n=key_n))
        if q.where is not None:
            r = self.ratio("filter", rows)
            sel = r if r is not None else 1.0
            key_n = rows
            rows = int(round(sel * rows))
            plan.add(PlanStep("filter", rows, "filter",
                              f"est selectivity {sel:.2g} "
                              f"{'(learned)' if r is not None else '(cold)'}",
                              key_n=key_n))
        from .engine import AGGREGATES
        has_agg = any(isinstance(it.expr, Call) and
                      it.expr.name in AGGREGATES for it in q.items)
        if q.group_by is not None or has_agg:
            r = self.ratio("aggregate", rows)
            key_n = rows
            if r is not None:
                rows = int(round(r * max(rows, 1)))
                why = "learned group count"
            elif q.group_by is None:
                rows, why = 1, "implicit single group"
            else:
                why = "cold: rows upper bound"
            plan.add(PlanStep("aggregate", rows, "hash-agg",
                              f"est {_fmt_rows(rows)} groups ({why})",
                              key_n=key_n))
        else:
            plan.add(PlanStep("project", rows, "project",
                              f"est {_fmt_rows(rows)} rows",
                              key_n=rows))
        if q.order_by:
            plan.add(PlanStep("order", rows, "sort",
                              f"est {_fmt_rows(rows)} rows",
                              key_n=rows))
        if q.limit is not None:
            key_n = rows
            rows = min(q.limit, rows)
            plan.add(PlanStep("limit", rows, "limit",
                              f"{_fmt_rows(rows)} rows (exact cap)",
                              key_n=key_n))
        # byte pre-pass: cardinality x source row width per operator —
        # the EXPLAIN est_bytes column and the memory budget's
        # admit() estimate (a width heuristic, not an exact footprint:
        # projections narrow, generators widen)
        if row_width > 0:
            for step in plan.steps.values():
                if step.est_rows >= 0:
                    step.est_bytes = int(step.est_rows) * row_width
        # fusion pass: walk the finished plan and group adjacent
        # eligible operators into whole-group XLA programs (gated per
        # size class by decide_fusion).  Degrade-not-die: a fusion
        # planning fault leaves the query on the unfused path.
        try:
            from ..perf.fusion import plan_fusion
            plan.fusion = plan_fusion(q, session, plan)
        except Exception as e:
            recorder.record("fusion_plan_error",
                            error=f"{type(e).__name__}: {e}")
            if metrics.enabled:
                metrics.count("fusion/plan_errors")
        return plan

    # -------------------------------------------------------- feedback

    def observe_op(self, op: str, n: int, wall_s: float,
                   rows_out: Optional[int] = None) -> None:
        """Raw coefficient feedback: ``op`` processed ``n`` input rows
        in ``wall_s`` seconds (optionally emitting ``rows_out``)."""
        n = max(int(n), 1)
        with self._lock:
            self._put(self._ms, (op, _bucket(n)),
                      wall_s * 1e3 / n)
            if rows_out is not None:
                self._put(self._ratio, (op, _bucket(n)),
                          rows_out / n)
            self.observations += 1
        if metrics.enabled:
            metrics.observe(f"planner/op_ms/{op}", wall_s)
        self._maybe_autosave()

    def observe_ratio(self, op: str, n: int, rows_out: int) -> None:
        """Cardinality-only feedback: keep an operator's selectivity /
        fanout ratio learning WITHOUT touching its cost coefficient.
        Fused stages use this — their wall time belongs to the group's
        ``fusion/<opset>`` key, and feeding it to the member op would
        poison the unfused cost the fusion gate compares against."""
        n = max(int(n), 1)
        with self._lock:
            self._put(self._ratio, (op, _bucket(n)), rows_out / n)
            self.observations += 1
        self._maybe_autosave()

    def observe_estimate(self, op: str, est_rows: int,
                         actual_rows: int) -> float:
        """Close one cardinality estimate; returns the error ratio
        (>= 1.0, where 1.0 is a perfect estimate)."""
        e = (est_rows + 1.0) / (actual_rows + 1.0)
        err = max(e, 1.0 / e)
        with self._lock:
            self.error_history.append(err)
            mis = err > MISPREDICT_FACTOR
            if mis:
                self.mispredicts += 1
        if metrics.enabled:
            metrics.observe("planner/estimate_error", err, scale=1.0)
            if mis:
                metrics.count("planner/mispredicts")
        if mis:
            # the active query's ticket carries the mispredict into
            # its durable history record (obs/history.py)
            from ..obs.inflight import note_mispredict
            note_mispredict()
            recorder.record("planner_mispredict", op=op,
                            est_rows=int(est_rows),
                            actual_rows=int(actual_rows),
                            error=round(err, 3))
        return err

    def observe_step(self, step: PlanStep, rows_out: int,
                     wall_s: float) -> None:
        """SQL-stage feedback: update the step's ratio/cost
        coefficients under the SAME (op, size-class) key the estimate
        was made with, and close the estimate."""
        self.observe_op(step.op if step.op not in ("generate",)
                        else f"generate/{step.strategy}",
                        step.key_n, wall_s, rows_out=rows_out)
        self.observe_estimate(step.op, step.est_rows, rows_out)

    def observe_decision(self, d: Decision, wall_s: float,
                         rows_out: Optional[int] = None) -> None:
        """Operator-dispatch feedback (KNN / PIP join / equi-join):
        the chosen strategy's cost coefficient learns from the run."""
        if d.cost_key:
            self.observe_op(d.cost_key, d.key_n, wall_s,
                            rows_out=rows_out)
        if rows_out is not None and d.est_rows >= 0:
            self.observe_estimate(d.op, d.est_rows, rows_out)

    # ------------------------------------------------------ persistence

    def _resolve_stats_path(self) -> Optional[str]:
        if self._stats_path:
            return self._stats_path
        path = os.environ.get(STATS_PATH_ENV)
        if path:
            return path
        from ..config import default_config
        return getattr(default_config(), "planner_stats_path",
                       "") or None

    def configure_stats(self, path: Optional[str] = None
                        ) -> Optional[str]:
        """Wire persistence (resolution: explicit arg >
        ``MOSAIC_TPU_PLANNER_STATS`` env > the conf key) and load any
        existing file.  Mirrors
        :func:`~mosaic_tpu.perf.jit_cache.configure_persistent_cache`."""
        if path:
            with self._lock:
                self._stats_path = str(path)
        resolved = self._resolve_stats_path()
        if resolved and not self._loaded:
            self.load(resolved)
        return resolved

    def load(self, path: Optional[str] = None) -> bool:
        """Warm-start the coefficient store from a stats file.

        Degrade-not-die: a missing, corrupt, or wrong-version file
        leaves the planner cold and records why — it never raises
        (resilience fault site ``planner.stats.load``)."""
        path = path or self._resolve_stats_path()
        if not path:
            return False
        with self._lock:
            self._loaded = True
        from ..resilience import faults
        try:
            faults.maybe_fail("planner.stats.load")
            with open(path) as f:
                blob = json.load(f)
            if not isinstance(blob, dict) or \
                    blob.get("version") != STATS_VERSION:
                raise ValueError(
                    f"planner stats version "
                    f"{blob.get('version') if isinstance(blob, dict) else '?'}"
                    f" != {STATS_VERSION}")
            ms = {_parse_key(k): float(v)
                  for k, v in blob.get("ms_per_row", {}).items()}
            ratio = {_parse_key(k): float(v)
                     for k, v in blob.get("ratio", {}).items()}
        except FileNotFoundError:
            return False
        except Exception as e:          # corrupt file: cold start
            recorder.record("planner_stats_corrupt", path=path,
                            error=f"{type(e).__name__}: {e}")
            if metrics.enabled:
                metrics.count("planner/stats_corrupt")
            return False
        with self._lock:
            for k, v in ms.items():
                self._put(self._ms, k, v)
            for k, v in ratio.items():
                self._put(self._ratio, k, v)
        recorder.record("planner_stats_loaded", path=path,
                        ms_keys=len(ms), ratio_keys=len(ratio))
        return True

    def save(self, path: Optional[str] = None) -> Optional[str]:
        """Atomic (tmp + rename) versioned snapshot of the coefficient
        store; IO failure is recorded, not raised."""
        path = path or self._resolve_stats_path()
        if not path:
            return None
        with self._lock:
            blob = {
                "version": STATS_VERSION,
                "ms_per_row": {_fmt_key(k): v
                               for k, v in self._ms.items()},
                "ratio": {_fmt_key(k): v
                          for k, v in self._ratio.items()},
            }
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(blob, f)
            os.replace(tmp, path)
        except OSError as e:
            recorder.record("planner_stats_save_failed", path=path,
                            error=str(e))
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        return path

    def _maybe_autosave(self) -> None:
        if self.observations % 32 == 0 and \
                self._resolve_stats_path():
            self.save()

    # ------------------------------------------------------- reporting

    def error_p95(self, window: int = 256) -> float:
        """p95 of the last ``window`` closed estimate errors (1.0 when
        none yet)."""
        with self._lock:
            errs = list(self.error_history)[-window:]
        return float(np.percentile(errs, 95)) if errs else 1.0

    def report(self) -> Dict[str, object]:
        with self._lock:
            return {
                "decisions": self.decisions,
                "mispredicts": self.mispredicts,
                "observations": self.observations,
                "mispredict_rate": round(
                    self.mispredicts / max(len(self.error_history), 1),
                    4),
                "estimate_error_p95": round(self.error_p95(), 3),
                "ms_keys": len(self._ms),
                "ratio_keys": len(self._ratio),
            }

    def reset(self) -> None:
        """Forget everything (tests)."""
        with self._lock:
            self._ms.clear()
            self._ratio.clear()
            self.decisions = self.mispredicts = self.observations = 0
            self.error_history.clear()
            self._loaded = False


def _fmt_key(k: Tuple[str, int]) -> str:
    return f"{k[0]}|{k[1]}"


def _parse_key(s: str) -> Tuple[str, int]:
    op, _, b = s.rpartition("|")
    return op, int(b)


def _fmt_rows(n: int) -> str:
    n = int(n)
    if n >= 10_000_000:
        return f"{n / 1e6:.0f}M"
    if n >= 1_000_000:
        return f"{n / 1e6:.1f}M"
    if n >= 10_000:
        return f"{n / 1e3:.0f}k"
    return str(n)


#: the process-global planner every dispatch site consults
planner = Planner()
