"""Pretty table rendering.

Reference counterpart: sql/Prettifier.scala:13 — ``prettified(df)``
renders result rows with binary geometry columns truncated to a readable
prefix instead of a wall of bytes.  Same idea here: geometry columns show
truncated WKT, byte columns show a hex prefix, floats are shortened.
"""

from __future__ import annotations

import numpy as np

from ..core.geometry.array import GeometryArray
from .engine import Table

_MAXW = 40


def _cell(v) -> str:
    if isinstance(v, (bytes, bytearray)):
        h = v[:8].hex()
        return f"0x{h}{'…' if len(v) > 8 else ''}"
    if isinstance(v, float) or isinstance(v, np.floating):
        s = f"{v:.6g}"
    else:
        s = str(v)
    return s if len(s) <= _MAXW else s[:_MAXW - 1] + "…"


def _column_cells(col, n: int):
    if isinstance(col, GeometryArray):
        from ..core.geometry.wkt import write_wkt
        out = []
        for i in range(n):
            w = write_wkt(col.take(np.asarray([i])))[0]
            out.append(w if len(w) <= _MAXW else w[:_MAXW - 1] + "…")
        return out
    if isinstance(col, np.ndarray):
        return [_cell(v) for v in col[:n].tolist()]
    return [_cell(v) for v in col[:n]]


def prettified(table: Table, num_rows: int = 20) -> str:
    """Render ``table`` as an aligned text grid (reference:
    Prettifier.prettified)."""
    n = min(num_rows, len(table))
    names = list(table.columns)
    grid = [_column_cells(table.columns[c], n) for c in names]
    widths = [max(len(names[j]), *(len(r) for r in grid[j])) if n else
              len(names[j]) for j in range(len(names))]
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    lines = [sep,
             "|" + "|".join(f" {names[j]:<{widths[j]}} "
                            for j in range(len(names))) + "|",
             sep]
    for i in range(n):
        lines.append("|" + "|".join(
            f" {grid[j][i]:<{widths[j]}} " for j in range(len(names)))
            + "|")
    lines.append(sep)
    if len(table) > n:
        lines.append(f"({len(table) - n} more rows)")
    return "\n".join(lines)
