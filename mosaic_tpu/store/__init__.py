"""Out-of-core chip store: grid-partitioned columnar shards.

The store persists point datasets as a fixed world-grid partitioning —
each non-empty grid cell owns one partition of row-sharded, raw
little-endian column files — under a versioned JSON manifest carrying
every partition's bbox, row count, and the dtype schema
(:mod:`.manifest`).  A writer ingests from arrays or any codec that
yields point blocks (:mod:`.writer`, atomic tmp+rename, fault sites
``store.write``); a reader prunes partitions against a query bbox from
the manifest alone — before a single data byte moves — and yields
bounded chunks lazily into :func:`mosaic_tpu.perf.pipeline.stream`
(:mod:`.reader`, fault sites ``store.read`` / ``store.shard``,
torn-shard degrade per the codec ``on_error`` convention).
:mod:`.pushdown` extracts the bbox from a SQL ``WHERE`` clause so the
engine's store scans prune without user annotations.

Reference shape: partition-parallel spatial joins over pre-partitioned
on-disk data (arxiv 1908.11740); the per-partition stats persisted
here are the substrate for learned layouts later (arxiv 2504.01292).
"""

from .manifest import Manifest, Partition, grid_cells, cell_bbox
from .reader import ChipStore, StoreChunk
from .writer import StoreWriter, write_store, write_store_from_chunks
from .pushdown import bbox_from_where

__all__ = ["Manifest", "Partition", "grid_cells", "cell_bbox",
           "ChipStore", "StoreChunk", "StoreWriter", "write_store",
           "write_store_from_chunks", "bbox_from_where"]
