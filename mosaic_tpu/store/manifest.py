"""Fixed world-grid partitioning + the store's versioned manifest.

The grid is global and resolution-keyed, never data-fitted: ``res x
res`` cells spanning lon [-180, 180) x lat [-90, 90), cell id ``iy *
res + ix``.  Two stores written at the same resolution therefore share
cell identities — the substrate for partition-aligned merges later.
Only non-empty cells materialize as partitions, so a clustered dataset
on a fine grid stays cheap.

The manifest is the store's single source of truth: schema (column
dtypes), total rows, the dataset bbox, and per-partition ``(cell,
bbox, rows, shard row counts)``.  It is written LAST, via tmp+rename —
a crash mid-ingest leaves shard temp files but no manifest, so a
half-written store is indistinguishable from no store (readers only
trust what the manifest names).  The per-partition bbox is the ACTUAL
data extent (tighter than the cell), so pruning discards cells whose
points cluster away from a query box even when the cell itself
overlaps it.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Tuple

import numpy as np

from ..resilience import faults
from ..resilience.ingest import CodecError, decode_guard

__all__ = ["MANIFEST_VERSION", "Manifest", "Partition", "grid_cells",
           "cell_bbox", "bbox_intersects", "shard_path"]

MANIFEST_VERSION = 1
MANIFEST_NAME = "manifest.json"
PARTS_DIR = "parts"


def grid_cells(x: np.ndarray, y: np.ndarray, res: int) -> np.ndarray:
    """Cell id per point on the fixed ``res x res`` world grid.

    Points outside the valid lon/lat range clip into the edge cells
    (degrade, not die — the partition bbox still records their true
    extent, so pruning stays correct for them)."""
    cw = 360.0 / res
    ch = 180.0 / res
    ix = np.clip(np.floor((np.asarray(x, np.float64) + 180.0) / cw)
                 .astype(np.int64), 0, res - 1)
    iy = np.clip(np.floor((np.asarray(y, np.float64) + 90.0) / ch)
                 .astype(np.int64), 0, res - 1)
    return iy * np.int64(res) + ix


def cell_bbox(cell: int, res: int) -> Tuple[float, float, float, float]:
    """Grid-aligned ``(xmin, ymin, xmax, ymax)`` of one cell."""
    cw = 360.0 / res
    ch = 180.0 / res
    iy, ix = divmod(int(cell), res)
    return (-180.0 + ix * cw, -90.0 + iy * ch,
            -180.0 + (ix + 1) * cw, -90.0 + (iy + 1) * ch)


def bbox_intersects(a, b) -> bool:
    """Closed-interval bbox overlap — boundary contact counts as
    overlap, so pruning against strict (< / >) predicates can only
    over-scan, never drop a matching row."""
    return not (a[2] < b[0] or b[2] < a[0] or
                a[3] < b[1] or b[3] < a[1])


def shard_path(root: str, cell: int, k: int, col: str) -> str:
    """``<root>/parts/p<cell>.s<k>.<col>`` — raw little-endian values
    of the manifest's dtype for ``col``, nothing else (offsets are
    pure arithmetic, so a torn tail is detectable from file size)."""
    return os.path.join(root, PARTS_DIR, f"p{cell:012d}.s{k}.{col}")


@dataclasses.dataclass(frozen=True)
class Partition:
    """One non-empty grid cell: where its data lives and what it spans."""

    cell: int
    bbox: Tuple[float, float, float, float]   # actual data extent
    rows: int
    shards: Tuple[int, ...]                   # rows per shard file


@dataclasses.dataclass
class Manifest:
    """The store's catalog — everything pruning needs, no data bytes."""

    grid_res: int
    point_cols: Tuple[str, str]               # (x column, y column)
    columns: Dict[str, str]                   # name -> numpy dtype str
    total_rows: int
    bbox: Tuple[float, float, float, float]
    partitions: List[Partition]
    version: int = MANIFEST_VERSION

    # -- serialization -----------------------------------------------
    def to_obj(self) -> dict:
        return {
            "version": self.version,
            "grid_res": self.grid_res,
            "point_cols": list(self.point_cols),
            "columns": dict(self.columns),
            "total_rows": self.total_rows,
            "bbox": [float(v) for v in self.bbox],
            "partitions": [
                {"cell": p.cell,
                 "bbox": [float(v) for v in p.bbox],
                 "rows": p.rows,
                 "shards": list(p.shards)}
                for p in self.partitions],
        }

    @classmethod
    def from_obj(cls, obj: dict, path: str = None) -> "Manifest":
        with decode_guard(path=path, feature="manifest"):
            version = int(obj["version"])
            if version > MANIFEST_VERSION:
                raise CodecError(
                    f"manifest version {version} is newer than this "
                    f"build understands (<= {MANIFEST_VERSION})",
                    path=path, feature="manifest")
            parts = [Partition(cell=int(p["cell"]),
                               bbox=tuple(float(v) for v in p["bbox"]),
                               rows=int(p["rows"]),
                               shards=tuple(int(s)
                                            for s in p["shards"]))
                     for p in obj["partitions"]]
            for p in parts:
                if sum(p.shards) != p.rows:
                    raise CodecError(
                        f"partition {p.cell}: shard rows "
                        f"{sum(p.shards)} != partition rows {p.rows}",
                        path=path, feature=f"partition {p.cell}")
            columns = {str(k): str(np.dtype(v).str)
                       for k, v in obj["columns"].items()}
            pc = tuple(str(c) for c in obj["point_cols"])
            if len(pc) != 2 or any(c not in columns for c in pc):
                raise CodecError(
                    f"point_cols {pc!r} must name two schema columns "
                    f"(have {sorted(columns)})",
                    path=path, feature="manifest")
            return cls(grid_res=int(obj["grid_res"]),
                       point_cols=pc, columns=columns,
                       total_rows=int(obj["total_rows"]),
                       bbox=tuple(float(v) for v in obj["bbox"]),
                       partitions=parts, version=version)

    # -- disk --------------------------------------------------------
    def save(self, root: str) -> str:
        """Atomic write: serialize to ``manifest.json.tmp``, fsync,
        rename.  The ``store.write`` fault site fires before the
        rename — an injected crash leaves the old manifest (or none)
        intact."""
        path = os.path.join(root, MANIFEST_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_obj(), f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        faults.maybe_fail("store.write")
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, root: str) -> "Manifest":
        path = os.path.join(root, MANIFEST_NAME)
        faults.maybe_fail("store.read")
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            raise CodecError("no manifest (not a chip store, or an "
                             "ingest that never finalized)",
                             path=path, feature="manifest") from None
        with decode_guard(path=path, feature="manifest"):
            obj = json.loads(raw.decode("utf-8"))
        return cls.from_obj(obj, path=path)
