"""Bbox extraction from a SQL WHERE clause — the pruning pushdown.

The planner never needs the user to annotate a spatial range: any
top-level AND-conjunct of the WHERE clause that compares one of the
store's point columns against a numeric literal tightens the scan
bbox (``x >= a AND x < b AND y > c ...``).  Everything else — OR
branches, function calls, comparisons between columns — is ignored,
which is always SAFE: an ignored predicate only means a looser bbox,
and pruning with a looser bbox scans more partitions, never fewer.
The WHERE clause itself still runs over the scanned rows, so results
are exact regardless of how much the pushdown understood.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

__all__ = ["bbox_from_where"]

#: comparison spellings the extractor understands, normalized to
#: (tightens_min, tightens_max) for ``col OP literal``
_OPS = {">": (True, False), ">=": (True, False),
        "<": (False, True), "<=": (False, True),
        "=": (True, True), "==": (True, True)}

#: mirror for ``literal OP col``
_FLIP = {">": "<", ">=": "<=", "<": ">", "<=": ">=",
         "=": "=", "==": "=="}


def _conjuncts(expr, out: List) -> None:
    from ..sql.parser import Binary
    if isinstance(expr, Binary) and expr.op == "and":
        _conjuncts(expr.left, out)
        _conjuncts(expr.right, out)
    else:
        out.append(expr)


def _as_number(expr) -> Optional[float]:
    from ..sql.parser import Literal, Unary
    if isinstance(expr, Literal) and \
            isinstance(expr.value, (int, float)) and \
            not isinstance(expr.value, bool):
        return float(expr.value)
    if isinstance(expr, Unary) and expr.op == "-":
        v = _as_number(expr.operand)
        return -v if v is not None else None
    return None


def bbox_from_where(where, xcol: str, ycol: str,
                    qualifier: Optional[str] = None
                    ) -> Optional[Tuple[float, float, float, float]]:
    """``(xmin, ymin, xmax, ymax)`` the WHERE clause confines the
    point columns to, or None when it confines neither axis.

    ``qualifier`` restricts which column references count: None
    accepts only unqualified references; a table alias accepts
    unqualified ones plus those qualified by that alias.  Unbounded
    sides come back infinite — partition-bbox intersection handles
    half-bounded boxes for free."""
    if where is None:
        return None
    from ..sql.parser import Binary, Column
    lo = {xcol: -math.inf, ycol: -math.inf}
    hi = {xcol: math.inf, ycol: math.inf}
    found = False
    conj: List = []
    _conjuncts(where, conj)
    for c in conj:
        if not isinstance(c, Binary):
            continue
        op, left, right = c.op, c.left, c.right
        if not isinstance(left, Column):
            # literal OP column -> column flipped-OP literal
            left, right = right, left
            op = _FLIP.get(op)
        if op not in _OPS or not isinstance(left, Column):
            continue
        name = left.name.lower()
        if name not in lo:
            continue
        if left.table is not None and left.table.lower() != \
                (qualifier or "").lower():
            continue
        v = _as_number(right)
        if v is None:
            continue
        tmin, tmax = _OPS[op]
        if tmin:
            lo[name] = max(lo[name], v)
        if tmax:
            hi[name] = min(hi[name], v)
        found = True
    if not found:
        return None
    return (lo[xcol], lo[ycol], hi[xcol], hi[ycol])
