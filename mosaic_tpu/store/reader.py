"""Read side of the chip store: manifest-driven pruning + lazy chunks.

:class:`ChipStore` opens a store by loading its manifest only — no
data bytes move until a partition is actually read.  :meth:`prune`
intersects the query bbox with every partition's recorded bbox (pure
manifest arithmetic; ``store/partitions_pruned`` counts what it
discarded), and :meth:`iter_chunks` is a GENERATOR that walks the
surviving partitions shard by shard, assembling bounded point chunks
for :func:`mosaic_tpu.perf.pipeline.stream` — at no moment does more
than one shard plus one chunk of carry-over live on the host, so a
store bigger than RAM streams through a fixed-size window.

Torn shards (file shorter than the manifest's row count — a crash,
truncation, or injected ``store.shard`` corruption) degrade per the
codec ``on_error`` convention: ``raise`` surfaces a located
:class:`~mosaic_tpu.resilience.ingest.CodecError`, ``skip`` drops the
incomplete tail rows, ``null`` zero-fills them; either degrade path
counts ``store/shards_torn`` and flight-records ``store_shard_torn``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import metrics
from ..obs.heat import heat
from ..obs.inflight import note_partitions
from ..obs.recorder import recorder
from ..resilience import faults
from ..resilience.ingest import CodecError, ON_ERROR_MODES
from .manifest import Manifest, Partition, bbox_intersects, shard_path

__all__ = ["ChipStore", "StoreChunk"]


@dataclasses.dataclass(frozen=True)
class StoreChunk:
    """One streamed unit: a bounded block of points plus the
    provenance needed to attribute its bytes per partition."""

    offset: int               # row offset within this scan's output
    points: np.ndarray        # (n, 2) float64 [x, y]
    parts: Tuple[Tuple[int, int], ...]   # (cell, rows) spans, in order

    @property
    def rows(self) -> int:
        return self.points.shape[0]


class ChipStore:
    """A readable chip store rooted at ``root`` (see :mod:`.writer`)."""

    def __init__(self, root: str, *, mmap: Optional[bool] = None,
                 on_error: Optional[str] = None):
        from .. import config as _config
        cfg = _config.default_config()
        self.root = str(root)
        self.mmap = cfg.store_mmap if mmap is None else bool(mmap)
        self.on_error = on_error or cfg.io_on_error
        if self.on_error not in ON_ERROR_MODES:
            raise ValueError(f"on_error={self.on_error!r} invalid "
                             f"(choose from {ON_ERROR_MODES})")
        self.manifest = Manifest.load(self.root)

    # -- manifest views ----------------------------------------------
    @property
    def point_cols(self) -> Tuple[str, str]:
        return self.manifest.point_cols

    @property
    def total_rows(self) -> int:
        return self.manifest.total_rows

    @property
    def bbox(self) -> Tuple[float, float, float, float]:
        return self.manifest.bbox

    @property
    def partitions(self) -> List[Partition]:
        return self.manifest.partitions

    def nbytes(self) -> int:
        """The dataset's in-RAM size per the manifest (rows x row
        width) — the out-of-core bench's comparison denominator."""
        width = sum(np.dtype(d).itemsize
                    for d in self.manifest.columns.values())
        return self.total_rows * width

    # -- pruning -----------------------------------------------------
    def prune(self, bbox=None, record: bool = True) -> List[Partition]:
        """Partitions a query over ``bbox`` must scan — manifest
        arithmetic only, no data reads.  Closed-interval overlap, so
        the survivors are always a superset of the partitions holding
        matching rows (pruning can over-scan, never drop)."""
        parts = self.manifest.partitions
        if bbox is None:
            scanned = list(parts)
        else:
            scanned = [p for p in parts if bbox_intersects(p.bbox, bbox)]
        if record and metrics.enabled:
            metrics.count("store/partitions_scanned", len(scanned))
            metrics.count("store/partitions_pruned",
                          len(parts) - len(scanned))
        return scanned

    # -- shard IO ----------------------------------------------------
    def _shard_bytes(self, path: str) -> bytes:
        """Raw shard payload.  mmap stays zero-copy; with a fault plan
        armed the bytes route through ``faults.corrupt`` (a memoryview
        cannot be truncated in place), so chaos drills always bite."""
        faults.maybe_fail("store.read")
        try:
            if self.mmap and faults.active() is None:
                if os.path.getsize(path) == 0:
                    return b""
                return memoryview(np.memmap(path, dtype=np.uint8,
                                            mode="r"))
            with open(path, "rb") as f:
                return faults.corrupt("store.shard", f.read())
        except FileNotFoundError:
            raise CodecError("shard file missing", path=path) from None

    def _read_shard(self, cell: int, k: int, col: str,
                    rows: int) -> np.ndarray:
        """One shard column, torn-tail handling per ``on_error``."""
        dtype = np.dtype(self.manifest.columns[col])
        path = shard_path(self.root, cell, k, col)
        raw = self._shard_bytes(path)
        complete = len(raw) // dtype.itemsize
        arr = np.frombuffer(raw, dtype=dtype, count=min(complete, rows))
        if complete < rows:
            # torn: the manifest promised more rows than the file holds
            err = CodecError(
                f"torn shard: {rows} rows promised, "
                f"{complete} complete on disk",
                path=path, feature=f"partition {cell} shard {k}",
                offset=complete * dtype.itemsize)
            if self.on_error == "raise":
                raise err
            if metrics.enabled:
                metrics.count("store/shards_torn")
            recorder.record("store_shard_torn", path=path, cell=cell,
                            shard=k, column=col, rows=rows,
                            complete=complete, mode=self.on_error)
            if self.on_error == "null":
                pad = np.zeros(rows, dtype=dtype)
                pad[:arr.shape[0]] = arr
                return pad
            # "skip": the incomplete tail rows drop
        return arr

    def read_partition(self, part: Partition,
                       cols: Optional[Sequence[str]] = None
                       ) -> Dict[str, np.ndarray]:
        """All of one partition's rows, columns concatenated across
        shards.  Under ``skip`` a torn shard truncates EVERY requested
        column to the shortest column's row count for that shard, so
        the result stays rectangular."""
        names = list(cols) if cols is not None \
            else list(self.manifest.columns)
        out: Dict[str, List[np.ndarray]] = {c: [] for c in names}
        read_rows = 0
        for k, rows in enumerate(part.shards):
            arrs = {c: self._read_shard(part.cell, k, c, rows)
                    for c in names}
            usable = min(a.shape[0] for a in arrs.values())
            read_rows += usable
            for c in names:
                out[c].append(arrs[c][:usable])
        # partition-heat feed: this read touches exactly one cell
        heat.touch(part.cell, rows=read_rows)
        note_partitions(((part.cell, read_rows),))
        return {c: np.concatenate(segs) if segs else
                np.empty(0, np.dtype(self.manifest.columns[c]))
                for c, segs in out.items()}

    def read_columns(self, cols: Optional[Sequence[str]] = None,
                     bbox=None) -> Dict[str, np.ndarray]:
        """Materialize the scanned subset (post-pruning) as one
        column dict — the SQL scan path.  For out-of-core streaming
        use :meth:`iter_chunks` instead."""
        parts = self.prune(bbox)
        names = list(cols) if cols is not None \
            else list(self.manifest.columns)
        segs: Dict[str, List[np.ndarray]] = {c: [] for c in names}
        for p in parts:
            got = self.read_partition(p, names)
            for c in names:
                segs[c].append(got[c])
        return {c: np.concatenate(s) if s else
                np.empty(0, np.dtype(self.manifest.columns[c]))
                for c, s in segs.items()}

    # -- lazy streaming ----------------------------------------------
    def iter_chunks(self, bbox=None,
                    chunk_rows: Optional[int] = None
                    ) -> Iterator[StoreChunk]:
        """Generator over the scanned partitions, yielding
        :class:`StoreChunk` blocks of exactly ``chunk_rows`` points
        (final remainder excepted), each carrying its per-partition
        row spans.  Reads one shard at a time — the host working set
        is one shard plus one chunk of carry-over, independent of
        store size.  Feed this straight into ``perf.pipeline.stream``
        (which pulls it one chunk ahead of the running compute)."""
        from .. import config as _config
        from ..perf.bucketing import pow2_bucket
        cfg = _config.default_config()
        target = int(chunk_rows or cfg.stream_chunk_rows)
        # pow2-bucket the chunk size itself so every full chunk lands
        # in one jit size class downstream
        target = pow2_bucket(target, floor=64)
        xcol, ycol = self.manifest.point_cols
        parts = self.prune(bbox)
        # carry: (cell, (n, 2) array) segments not yet emitted
        carry: List[Tuple[int, np.ndarray]] = []
        carry_rows = 0
        offset = 0

        def emit(take: int) -> StoreChunk:
            nonlocal carry, carry_rows, offset
            spans: List[Tuple[int, int]] = []
            pieces: List[np.ndarray] = []
            left = take
            while left > 0:
                cell, seg = carry[0]
                if seg.shape[0] <= left:
                    carry.pop(0)
                    piece = seg
                else:
                    carry[0] = (cell, seg[left:])
                    piece = seg[:left]
                pieces.append(piece)
                left -= piece.shape[0]
                if spans and spans[-1][0] == cell:
                    spans[-1] = (cell, spans[-1][1] + piece.shape[0])
                else:
                    spans.append((cell, piece.shape[0]))
            carry_rows -= take
            chunk = StoreChunk(offset=offset,
                               points=np.concatenate(pieces)
                               if len(pieces) > 1 else pieces[0],
                               parts=tuple(spans))
            offset += take
            # partition-heat feed: rows actually streamed per cell (a
            # pruned partition never reaches a chunk — it stays cold)
            for cell, r in spans:
                heat.touch(cell, rows=r)
            note_partitions(spans)
            if metrics.enabled:
                metrics.count("store/chunks_streamed")
                metrics.count("store/rows_scanned", take)
            return chunk

        for p in parts:
            for k, rows in enumerate(p.shards):
                xs = self._read_shard(p.cell, k, xcol, rows)
                ys = self._read_shard(p.cell, k, ycol, rows)
                usable = min(xs.shape[0], ys.shape[0])
                if usable == 0:
                    continue
                pts = np.empty((usable, 2), np.float64)
                pts[:, 0] = xs[:usable]
                pts[:, 1] = ys[:usable]
                carry.append((p.cell, pts))
                carry_rows += usable
                while carry_rows >= target:
                    yield emit(target)
        if carry_rows:
            yield emit(carry_rows)
