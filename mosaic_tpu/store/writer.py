"""Ingest into a chip store: grid-bucketed, row-sharded column files.

:class:`StoreWriter` accepts point blocks incrementally (so a source
larger than RAM streams straight through), buckets each block onto the
fixed world grid, and appends every bucket's rows to that partition's
current shard temp file — rolling to a new shard whenever the current
one reaches ``mosaic.store.shard.rows``.  :meth:`StoreWriter.finalize`
renames every temp shard into place and writes the manifest LAST, so
a crash at any earlier point leaves no readable store (see
:mod:`.manifest`).

Within a partition, rows keep their ingest order (the bucketing sort
is stable), so a store round-trip is bit-reproducible: read the
partitions in manifest order and each partition's rows come back
exactly as appended.

``write_store`` is the one-shot array path; ``write_store_from_chunks``
adapts any iterable of point blocks — e.g. a loop over the io codecs'
decoded tiles — to the incremental writer.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, Optional, Tuple

import numpy as np

from ..obs import metrics
from ..resilience import faults
from .manifest import (MANIFEST_VERSION, Manifest, PARTS_DIR, Partition,
                       grid_cells, shard_path)

__all__ = ["StoreWriter", "write_store", "write_store_from_chunks"]


class StoreWriter:
    """Incremental grid-partitioned ingest; call :meth:`append` any
    number of times, then :meth:`finalize` exactly once."""

    def __init__(self, root: str, *, grid_res=None,
                 shard_rows: Optional[int] = None,
                 point_cols: Tuple[str, str] = ("x", "y")):
        from .. import config as _config
        cfg = _config.default_config()
        self.root = str(root)
        if isinstance(grid_res, str):
            # learned layout: resolve "auto" through the advisor
            # (sql/layout.py) — heat/history workload evidence, else
            # the configured default.  shard_rows follows the advice
            # unless pinned explicitly.
            if grid_res != "auto":
                raise ValueError(
                    f"grid_res={grid_res!r} invalid: an int or 'auto'")
            from ..sql.layout import advise_layout
            adv = advise_layout()
            grid_res = adv.grid_res
            if shard_rows is None:
                shard_rows = adv.shard_rows
        self.grid_res = int(grid_res or cfg.store_grid_res)
        self.shard_rows = int(shard_rows or cfg.store_shard_rows)
        self.point_cols = (str(point_cols[0]), str(point_cols[1]))
        # partition state: cell -> {"rows", "shards": [rows...],
        # "bbox": [xmin, ymin, xmax, ymax]}
        self._parts: Dict[int, dict] = {}
        self._columns: Dict[str, np.dtype] = {}   # fixed at 1st append
        self._bytes = 0
        self._done = False
        os.makedirs(os.path.join(self.root, PARTS_DIR), exist_ok=True)

    # -- ingest ------------------------------------------------------
    def append(self, points: np.ndarray,
               columns: Optional[Dict[str, np.ndarray]] = None) -> int:
        """Bucket one ``(n, 2)`` float64 point block (plus optional
        equal-length payload columns) onto the grid and append it to
        the partition shard files.  Returns rows written."""
        if self._done:
            raise ValueError("StoreWriter already finalized")
        pts = np.asarray(points, np.float64)
        if pts.ndim != 2 or pts.shape[1] != 2:
            raise ValueError(f"points must be (n, 2); got {pts.shape}")
        n = pts.shape[0]
        cols: Dict[str, np.ndarray] = {
            self.point_cols[0]: np.ascontiguousarray(pts[:, 0]),
            self.point_cols[1]: np.ascontiguousarray(pts[:, 1]),
        }
        for name, arr in (columns or {}).items():
            if name in cols:
                raise ValueError(f"column {name!r} collides with a "
                                 "point column")
            a = np.asarray(arr)
            if a.shape[0] != n:
                raise ValueError(f"column {name!r} has {a.shape[0]} "
                                 f"rows, points have {n}")
            cols[name] = np.ascontiguousarray(a)
        if not self._columns:
            self._columns = {k: v.dtype for k, v in cols.items()}
        elif set(cols) != set(self._columns):
            raise ValueError(
                f"column set changed mid-ingest: {sorted(cols)} vs "
                f"{sorted(self._columns)}")
        if n == 0:
            return 0
        faults.maybe_fail("store.write")
        cells = grid_cells(pts[:, 0], pts[:, 1], self.grid_res)
        # stable sort: rows within a cell keep ingest order, so the
        # read-back order is a pure function of (data, grid), not of
        # block boundaries' interleaving
        order = np.argsort(cells, kind="stable")
        sorted_cells = cells[order]
        uniq, starts = np.unique(sorted_cells, return_index=True)
        bounds = np.append(starts, n)
        for ci, cell in enumerate(uniq):
            sel = order[bounds[ci]:bounds[ci + 1]]
            self._append_cell(int(cell), {k: v[sel]
                                          for k, v in cols.items()})
        if metrics.enabled:
            metrics.count("store/rows_ingested", n)
        return n

    def _append_cell(self, cell: int,
                     cols: Dict[str, np.ndarray]) -> None:
        part = self._parts.get(cell)
        xs = cols[self.point_cols[0]]
        ys = cols[self.point_cols[1]]
        if part is None:
            part = self._parts[cell] = {
                "rows": 0, "shards": [0],
                "bbox": [float(xs.min()), float(ys.min()),
                         float(xs.max()), float(ys.max())]}
        else:
            bb = part["bbox"]
            bb[0] = min(bb[0], float(xs.min()))
            bb[1] = min(bb[1], float(ys.min()))
            bb[2] = max(bb[2], float(xs.max()))
            bb[3] = max(bb[3], float(ys.max()))
        n = xs.shape[0]
        off = 0
        while off < n:
            k = len(part["shards"]) - 1
            room = self.shard_rows - part["shards"][k]
            if room <= 0:
                part["shards"].append(0)
                continue
            take = min(room, n - off)
            for name, arr in cols.items():
                seg = np.ascontiguousarray(arr[off:off + take])
                with open(shard_path(self.root, cell, k, name) + ".tmp",
                          "ab") as f:
                    f.write(memoryview(seg).cast("B"))
                self._bytes += seg.nbytes
            part["shards"][k] += take
            part["rows"] += take
            off += take

    # -- commit ------------------------------------------------------
    def finalize(self) -> Manifest:
        """Rename every shard into place and write the manifest last.
        The store becomes visible to readers atomically at the
        manifest rename; until then it does not exist."""
        if self._done:
            raise ValueError("StoreWriter already finalized")
        faults.maybe_fail("store.write")
        partitions = []
        for cell in sorted(self._parts):
            part = self._parts[cell]
            for k in range(len(part["shards"])):
                for name in self._columns:
                    p = shard_path(self.root, cell, k, name)
                    os.replace(p + ".tmp", p)
            partitions.append(Partition(
                cell=cell, bbox=tuple(part["bbox"]),
                rows=part["rows"], shards=tuple(part["shards"])))
        if partitions:
            bbox = (min(p.bbox[0] for p in partitions),
                    min(p.bbox[1] for p in partitions),
                    max(p.bbox[2] for p in partitions),
                    max(p.bbox[3] for p in partitions))
        else:
            bbox = (0.0, 0.0, 0.0, 0.0)
        man = Manifest(
            grid_res=self.grid_res, point_cols=self.point_cols,
            columns={k: np.dtype(v).str
                     for k, v in self._columns.items()},
            total_rows=sum(p.rows for p in partitions),
            bbox=bbox, partitions=partitions,
            version=MANIFEST_VERSION)
        man.save(self.root)
        if metrics.enabled:
            metrics.count("store/bytes_written", self._bytes)
        self._done = True
        return man


def write_store(root: str, points: np.ndarray,
                columns: Optional[Dict[str, np.ndarray]] = None,
                **kw) -> Manifest:
    """One-shot array ingest (the in-memory path's mirror image)."""
    w = StoreWriter(root, **kw)
    w.append(points, columns)
    return w.finalize()


def write_store_from_chunks(root: str, chunks: Iterable,
                            **kw) -> Manifest:
    """Ingest from any iterable of blocks — each item either a
    ``(n, 2)`` point array or a ``(points, columns dict)`` pair — so a
    codec read loop (or any generator) streams to disk without ever
    holding the whole dataset."""
    w = StoreWriter(root, **kw)
    for item in chunks:
        if isinstance(item, tuple) and len(item) == 2 and \
                isinstance(item[1], dict):
            w.append(item[0], item[1])
        else:
            w.append(item)
    return w.finalize()
