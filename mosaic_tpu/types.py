"""Wire types: chips and raster tiles.

Reference counterparts: core/types/ChipType.scala:9-30 (struct(is_core,
index_id, wkb)), core/types/model/MosaicChip.scala:21, and
core/types/RasterTileType.scala / model/MosaicRasterTile.scala:22.  Columnar
instead of row structs: a ChipSet is the whole exploded
``grid_tessellateexplode`` output for a batch.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from .core.geometry.array import GeometryArray


@dataclasses.dataclass
class ChipSet:
    """Columnar chip batch = rows of ChipType plus source-geometry ids.

    geom_id[i]  — index of the source geometry in the input batch
    cell_id[i]  — grid cell id (int64 bit pattern)
    is_core[i]  — cell fully inside the source geometry
    geoms       — chip geometries; core chips carry the cell geometry when
                  keep_core_geom was set, else an empty polygon (the
                  reference's null wkb)
    """

    geom_id: np.ndarray
    cell_id: np.ndarray
    is_core: np.ndarray
    geoms: GeometryArray

    def __len__(self) -> int:
        return len(self.cell_id)

    def __post_init__(self):
        self.geom_id = np.asarray(self.geom_id, dtype=np.int64)
        self.cell_id = np.asarray(self.cell_id, dtype=np.int64)
        self.is_core = np.asarray(self.is_core, dtype=bool)

    @staticmethod
    def concat(parts) -> "ChipSet":
        parts = list(parts)
        if not parts:
            return ChipSet(np.empty(0, np.int64), np.empty(0, np.int64),
                           np.empty(0, bool), GeometryArray.empty())
        return ChipSet(
            np.concatenate([p.geom_id for p in parts]),
            np.concatenate([p.cell_id for p in parts]),
            np.concatenate([p.is_core for p in parts]),
            GeometryArray.concat([p.geoms for p in parts]))
