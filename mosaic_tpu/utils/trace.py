"""Compat shim: the tracing subsystem moved to :mod:`mosaic_tpu.obs`.

Everything that used to live here (``tracer``, ``Tracer``,
``record_command``, ``record_error``, ``device_trace``) re-exports from
the grown observability package, which adds the metrics registry,
JAX compile/memory telemetry, and Chrome-trace export.  Import from
``mosaic_tpu.obs`` in new code.
"""

from __future__ import annotations

from ..obs import (Tracer, device_trace, metrics, record_command,
                   record_error, tracer)

__all__ = ["Tracer", "tracer", "metrics", "record_command",
           "record_error", "device_trace"]
