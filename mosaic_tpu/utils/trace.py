"""Tracing / profiling subsystem.

Reference counterpart: Mosaic has no custom tracer — it leans on the
Spark UI for task timing and records ``last_command``/``last_error``/
``full_error`` into raster tile metadata for post-hoc debugging
(core/raster/operator/gdal/GDALCalc.scala:39-55); micro-benchmarks use
``SparkSuite.benchmark`` (test/SparkSuite.scala:30-36).  Standalone, we
supply the equivalent surface ourselves:

* ``tracer`` — process-global span timer + counters (the Spark-UI
  analogue).  Disabled by default; enable with ``tracer.enable()`` or
  ``MOSAIC_TPU_TRACE=1``.  ``MosaicContext.call`` wraps every by-name
  dispatch in a span, so external engines driving the string surface get
  per-function wall times for free.
* ``record_command`` / ``record_error`` — the GDALCalc metadata pattern:
  raster operators stamp what ran (and what failed) into ``tile.meta``.
* ``device_trace`` — context manager around ``jax.profiler.trace`` for
  XLA/TPU timeline captures (inspect with tensorboard or xprof).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, List, Optional


class _Span:
    __slots__ = ("name", "total_s", "calls", "max_s")

    def __init__(self, name: str):
        self.name = name
        self.total_s = 0.0
        self.calls = 0
        self.max_s = 0.0


class Tracer:
    """Span wall-times + named counters, thread-safe, ~zero cost when
    disabled (one attribute check per span)."""

    def __init__(self):
        self._enabled = bool(os.environ.get("MOSAIC_TPU_TRACE"))
        self._lock = threading.Lock()
        self._spans: Dict[str, _Span] = {}
        self._counters: Dict[str, float] = {}
        self._stack = threading.local()

    # -- switches
    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self._counters.clear()

    # -- spans
    @contextlib.contextmanager
    def span(self, name: str):
        if not self._enabled:
            yield
            return
        stack: List[str] = getattr(self._stack, "names", None) or []
        self._stack.names = stack
        stack.append(name)
        qual = "/".join(stack)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            stack.pop()
            with self._lock:
                s = self._spans.setdefault(qual, _Span(qual))
                s.total_s += dt
                s.calls += 1
                s.max_s = max(s.max_s, dt)

    # -- counters
    def count(self, name: str, value: float = 1.0) -> None:
        if not self._enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    # -- reporting
    def report(self) -> Dict[str, object]:
        with self._lock:
            return {
                "spans": {n: {"total_s": s.total_s, "calls": s.calls,
                              "max_s": s.max_s}
                          for n, s in self._spans.items()},
                "counters": dict(self._counters),
            }

    def format_report(self) -> str:
        rep = self.report()
        lines = [f"{'span':<44} {'calls':>6} {'total_s':>9} {'max_s':>8}"]
        for n, s in sorted(rep["spans"].items(),
                           key=lambda kv: -kv[1]["total_s"]):
            lines.append(f"{n:<44} {s['calls']:>6} "
                         f"{s['total_s']:>9.4f} {s['max_s']:>8.4f}")
        for n, v in sorted(rep["counters"].items()):
            lines.append(f"counter {n} = {v:g}")
        return "\n".join(lines)


tracer = Tracer()


# -- raster-op provenance (reference: GDALCalc.scala:39-55 records
#    last_command / last_error / full_error into tile metadata)

def record_command(tile, command: str) -> None:
    tile.meta["last_command"] = command


def record_error(tile, err: BaseException) -> None:
    tile.meta["last_error"] = f"{type(err).__name__}: {err}"[:200]
    tile.meta["full_error"] = repr(err)


@contextlib.contextmanager
def device_trace(logdir: str, host_tracer_level: int = 2):
    """Capture an XLA/TPU profiler timeline into ``logdir`` (reference
    analogue: the Spark UI stage timeline).  View with xprof/tensorboard."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()
