"""Result visualization/export: GeoJSON for kepler.gl, standalone SVG.

Reference counterpart: python/mosaic/utils/kepler_magic.py:24 (the
%%mosaic_kepler Jupyter magic feeding KeplerGL) and display_handler.py.
keplergl is not in this image, so the observability surface here is
(a) kepler-ready GeoJSON export of chips/cells/zones — drop the file
into kepler.gl or any GIS tool — and (b) a dependency-free SVG renderer
for quick visual checks in tests/notebooks without any viewer.
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Sequence

import numpy as np

from ..core.geometry.array import GeometryArray
from ..core.index.base import IndexSystem
from ..types import ChipSet

__all__ = ["chips_to_geojson", "cells_to_geojson", "render_svg"]


def chips_to_geojson(chips: ChipSet) -> str:
    """ChipSet -> FeatureCollection with is_core/cell_id/geom_id
    properties (the kepler view of grid_tessellateexplode output)."""
    from ..core.geometry.geojson import write_geojson
    feats = []
    gj = write_geojson(chips.geoms)
    for i in range(len(chips)):
        feats.append({
            "type": "Feature",
            "geometry": json.loads(gj[i]),
            "properties": {
                "cell_id": format(int(chips.cell_id[i]) &
                                  0xFFFFFFFFFFFFFFFF, "x"),
                "geom_id": int(chips.geom_id[i]),
                "is_core": bool(chips.is_core[i]),
            }})
    return json.dumps({"type": "FeatureCollection", "features": feats})


def cells_to_geojson(cells: np.ndarray, grid: IndexSystem,
                     values: Optional[Dict[int, float]] = None) -> str:
    """Cell ids (+ optional per-cell measure) -> boundary polygons —
    the raster_to_grid / zone-histogram view."""
    cells = np.asarray(cells, np.int64)
    verts, counts = grid.cell_boundary(cells)
    feats = []
    for i, c in enumerate(cells):
        ring = verts[i, :counts[i]].tolist()
        ring.append(ring[0])
        props = {"cell_id": format(int(c) & 0xFFFFFFFFFFFFFFFF, "x")}
        if values is not None:
            props["value"] = values.get(int(c))
        feats.append({"type": "Feature",
                      "geometry": {"type": "Polygon",
                                   "coordinates": [ring]},
                      "properties": props})
    return json.dumps({"type": "FeatureCollection", "features": feats})


def render_svg(geoms: GeometryArray,
               values: Optional[Sequence[float]] = None,
               width: int = 640, stroke: str = "#333") -> str:
    """Dependency-free SVG of a geometry batch, optionally choropleth-
    colored by ``values`` (linear blue→red)."""
    bb = geoms.bboxes()
    ok = ~np.any(np.isnan(bb), axis=1)
    if not ok.any():
        return f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}"' \
               f' height="{width}"></svg>'
    x0, y0 = bb[ok, 0].min(), bb[ok, 1].min()
    x1, y1 = bb[ok, 2].max(), bb[ok, 3].max()
    w = max(x1 - x0, 1e-12)
    h = max(y1 - y0, 1e-12)
    height = int(width * h / w)
    sx = width / w

    if values is not None:
        v = np.asarray(values, np.float64)
        lo, hi = np.nanmin(v), np.nanmax(v)
        span = (hi - lo) or 1.0

    def color(i):
        if values is None:
            return "#9ecae1"
        t = (values[i] - lo) / span
        r = int(70 + 180 * t)
        b = int(250 - 180 * t)
        return f"rgb({r},90,{b})"

    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
             f'height="{height}" viewBox="0 0 {width} {height}">']
    for gi in range(len(geoms)):
        _, gparts = geoms.geom_slices(gi)
        path = []
        for rings in gparts:
            for ring in rings:
                if len(ring) < 2:
                    continue
                pts = np.asarray(ring)[:, :2]
                px = (pts[:, 0] - x0) * sx
                py = (y1 - pts[:, 1]) * sx
                d = "M" + " L".join(f"{a:.2f},{b:.2f}"
                                    for a, b in zip(px, py)) + " Z"
                path.append(d)
        if path:
            parts.append(f'<path d="{" ".join(path)}" fill="{color(gi)}"'
                         f' fill-opacity="0.55" stroke="{stroke}" '
                         f'stroke-width="0.6" fill-rule="evenodd"/>')
    parts.append("</svg>")
    return "".join(parts)
