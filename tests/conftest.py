"""Test harness: force a virtual 8-device CPU mesh before JAX imports.

Mirrors the reference's local-cluster distribution testing
(test/SparkSuite.scala:8-50 spins local[4]): no real pod, but the sharding
/ collective paths are exercised for real across 8 XLA host devices.
"""

import os

# NB: this image force-registers a TPU backend from sitecustomize at
# interpreter start, so the env-var route (JAX_PLATFORMS=cpu) is already
# decided by the time conftest runs; jax.config.update after import is the
# authoritative switch.  XLA_FLAGS is still read lazily at CPU-client init,
# so setting it here works.
import re

flags = os.environ.get("XLA_FLAGS", "")
flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

assert jax.device_count() == 8, jax.devices()

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from mosaic_tpu.resilience.testing import (fault_plan,  # noqa: E402,F401
                                           no_faults)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
