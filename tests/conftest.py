"""Test harness: force a virtual 8-device CPU mesh before JAX imports.

Mirrors the reference's local-cluster distribution testing
(test/SparkSuite.scala:8-50 spins local[4]): no real pod, but the sharding
/ collective paths are exercised for real across 8 XLA host devices.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
