"""The query accounting plane (``obs.inflight`` + ``obs.accounting``).

Covers the acceptance surface of the accounting PR: ticket lifecycle
through ``SQLSession.sql()`` (principal resolution, cost vector,
planner strategies in the audit record), cooperative cancellation at
operator and streamed-chunk boundaries (partial cost record, no
leaked worker threads), deadline expiry, per-principal meter splits
under concurrent interleaved queries, device-seconds attribution
joined from the kernel ledger, the audit JSONL spool, per-principal
SLO auto-registration, OpenMetrics label escaping with a hostile
principal name, the pipeline ``observe`` hardening, and the
dashboard's query console routes (JSON 404 / 405 / no-store).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mosaic_tpu as mos
from mosaic_tpu import config as _config
from mosaic_tpu.obs import metrics, recorder
from mosaic_tpu.obs.accounting import accounted, audit, meter
from mosaic_tpu.obs.inflight import (QueryCancelled, QueryTicket,
                                     checkpoint, inflight)
from mosaic_tpu.obs.profiler import ledger
from mosaic_tpu.obs.slo import monitor
from mosaic_tpu.resilience import faults
from mosaic_tpu.sql import SQLError, SQLSession


@pytest.fixture
def clean_acct():
    """Reset the accounting singletons around each test (the registry
    itself holds no state once every query completes)."""
    audit.reset()
    meter.reset()
    metrics.reset()
    metrics.enable()
    recorder.reset()
    recorder.enable()
    yield
    faults.disarm()
    audit.reset()
    meter.reset()
    metrics.disable()
    metrics.reset()
    recorder.reset()


@pytest.fixture
def clean_config():
    prev = _config.default_config()
    yield
    _config.set_default_config(prev)


@pytest.fixture
def session():
    ctx = mos.enable_mosaic("CUSTOM(-180,180,-90,90,2,360,180)")
    s = mos.SQLSession(ctx)
    s.create_table("pts", {"x": np.arange(100.0),
                           "y": np.arange(100.0) / 10.0})
    return s


def _streamed_join():
    """A tiny warm streamed PIP join (the flagship shape)."""
    from mosaic_tpu import read_wkt
    from mosaic_tpu.core.index.custom import CustomIndexSystem, GridConf
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.parallel.pip_join import (build_pip_index,
                                              make_streamed_pip_join)
    grid = CustomIndexSystem(GridConf(0, 16, 0, 16, 2, 1.0, 1.0))
    arr = read_wkt(
        ["POLYGON ((1.3 1.7, 6.8 2.1, 5.9 6.3, 2.2 5.8, 1.3 1.7))",
         "POLYGON ((8.5 1.5, 14.5 1.5, 14.5 6.5, 8.5 6.5, 8.5 1.5))"])
    idx = build_pip_index(arr, 1, grid, chips=tessellate(arr, 1, grid))
    pts = np.random.default_rng(3).uniform(0, 16, (8192, 2))
    sjoin = make_streamed_pip_join(idx, grid, polys=arr, chunk=2048)
    sjoin(pts)                                # warm (compile)
    return sjoin, pts


# ----------------------------------------------------- ticket basics

def test_sql_writes_one_audit_record_with_cost_and_strategies(
        clean_acct, session):
    session.principal = "alice"
    out = session.sql("SELECT x FROM pts WHERE x > 50")
    assert len(out) == 49
    recs = audit.records()
    assert len(recs) == 1
    r = recs[0]
    assert r["principal"] == "alice"
    assert r["outcome"] == "ok"
    assert r["cost"]["rows_in"] == 100
    assert r["cost"]["rows_out"] == 49
    assert r["cost"]["wall_ms"] > 0
    assert "scan" in r["strategies"]          # planner picks ride along
    assert r["trace"] and r["query_id"].startswith("q")
    assert not inflight.list_active()         # ticket closed
    m = meter.report()["alice"]
    assert m["queries"] == 1 and m["rows_out"] == 49
    assert m["outcomes"] == {"ok": 1}


def test_principal_resolution_conf_then_anonymous(
        clean_acct, clean_config, session):
    session.principal = None
    session.sql("SET mosaic.principal = team-geo")
    session.sql("SELECT x FROM pts LIMIT 1")
    assert audit.records()[-1]["principal"] == "team-geo"
    session.sql("SET mosaic.principal = ''")
    session.sql("SELECT x FROM pts LIMIT 1")
    assert audit.records()[-1]["principal"] == "anonymous"
    session.principal = "alice"               # session attr wins
    session.sql("SELECT x FROM pts LIMIT 1")
    assert audit.records()[-1]["principal"] == "alice"


def test_error_outcomes_split_client_vs_service(clean_acct, session):
    session.principal = "alice"
    with pytest.raises(SQLError):
        session.sql("SELECT nope FROM pts")
    r = audit.records()[-1]
    assert r["outcome"] == "error" and "nope" in r["error"]
    # client mistakes stay out of the service-fault SLO feed
    assert metrics.counter_value("sql/errors") == 0
    assert meter.report()["alice"]["outcomes"]["error"] == 1
    assert not inflight.list_active()


def test_disabled_registry_is_a_no_op(clean_acct, session):
    session.principal = "alice"
    inflight.enabled = False
    try:
        out = session.sql("SELECT x FROM pts LIMIT 3")
        assert len(out) == 3                  # queries still run
        assert audit.records() == []          # nothing accounted
        assert meter.report() == {}
    finally:
        inflight.enabled = True


def test_ticket_deadline_check_raises_deadline_outcome():
    t = QueryTicket("q-test", "p", "SELECT 1", "trace-x",
                    deadline_ms=1.0)
    time.sleep(0.01)
    with pytest.raises(QueryCancelled) as ei:
        t.check()
    assert ei.value.outcome == "deadline"
    assert ei.value.query_id == "q-test"
    # not an SQLError: cancellation is an operator action
    assert not isinstance(ei.value, SQLError)


def test_checkpoint_is_noop_outside_any_query(clean_acct):
    checkpoint("anywhere")                    # must not raise


# ----------------------------------------------------- cancellation

def test_cancel_stalled_sql_query_mid_flight(clean_acct, session):
    session.principal = "alice"
    faults.arm("site=sql.query,mode=delay,fails=1,delay_ms=700")
    n0 = threading.active_count()
    res = {}

    def run():
        try:
            session.sql("SELECT x FROM pts")
        except QueryCancelled as e:
            res["exc"] = e

    th = threading.Thread(target=run)
    th.start()
    deadline = time.time() + 0.5
    act = []
    while not act and time.time() < deadline:
        act = inflight.list_active()
        time.sleep(0.01)
    assert act and act[0]["principal"] == "alice"
    assert inflight.cancel(act[0]["query_id"])
    th.join(timeout=10)
    assert not th.is_alive()
    assert res["exc"].outcome == "cancelled"
    r = audit.records()[-1]
    assert r["outcome"] == "cancelled"
    assert metrics.counter_value("sql/errors") == 0
    assert recorder.events("query_cancel_requested")
    assert threading.active_count() <= n0 + 1
    assert not inflight.list_active()


def test_cancel_streamed_join_within_one_chunk_boundary(clean_acct):
    """The acceptance drill: a stalled streamed query cancelled
    mid-stream stops at the next chunk boundary with a partial cost
    record and no leaked worker threads."""
    sjoin, pts = _streamed_join()
    faults.arm("site=pipeline.chunk,mode=delay,fails=1,delay_ms=700")
    n0 = threading.active_count()
    res = {}

    def run():
        try:
            with accounted("stalled-join", principal="bob"):
                sjoin(pts)
        except QueryCancelled as e:
            res["exc"] = e

    th = threading.Thread(target=run)
    th.start()
    deadline = time.time() + 0.5
    act = []
    while not act and time.time() < deadline:
        act = inflight.list_active()
        time.sleep(0.01)
    assert act
    t0 = time.perf_counter()
    assert inflight.cancel(act[0]["query_id"])
    th.join(timeout=10)
    assert not th.is_alive()
    # one chunk boundary: the 700 ms stall plus slack, not the whole
    # 4-chunk stream stalled once per chunk
    assert time.perf_counter() - t0 < 5.0
    assert res["exc"].outcome == "cancelled"
    r = audit.records()[-1]
    assert r["outcome"] == "cancelled"
    assert r["cost"]["wall_ms"] > 0           # partial, not empty
    assert r["cost"]["h2d_bytes"] > 0         # chunk 0 was staged
    time.sleep(0.2)                           # executor teardown
    assert threading.active_count() <= n0 + 1
    assert not inflight.list_active()


def test_deadline_expires_during_stall(clean_acct, clean_config,
                                       session):
    session.principal = "alice"
    cfg = _config.apply_conf(_config.default_config(),
                             "mosaic.query.deadline.ms", "100")
    _config.set_default_config(cfg)
    faults.arm("site=sql.query,mode=delay,fails=1,delay_ms=300")
    with pytest.raises(QueryCancelled) as ei:
        session.sql("SELECT x FROM pts")
    assert ei.value.outcome == "deadline"
    assert audit.records()[-1]["outcome"] == "deadline"
    assert meter.report()["alice"]["outcomes"] == {"deadline": 1}


# ------------------------------------------ concurrency + attribution

def test_concurrent_queries_get_disjoint_tickets_and_splits(
        clean_acct):
    """Two principals in two threads: disjoint query ids and traces,
    correct per-principal meter splits."""
    ctx = mos.enable_mosaic("CUSTOM(-180,180,-90,90,2,360,180)")
    barrier = threading.Barrier(2)
    seen = {}

    def worker(principal, n_rows):
        s = mos.SQLSession(ctx)
        s.principal = principal
        s.create_table("t", {"a": np.arange(float(n_rows))})
        barrier.wait()
        for _ in range(3):
            s.sql("SELECT a FROM t WHERE a >= 0")
        seen[principal] = n_rows

    t1 = threading.Thread(target=worker, args=("alice", 50))
    t2 = threading.Thread(target=worker, args=("bob", 80))
    t1.start(); t2.start(); t1.join(10); t2.join(10)
    assert seen == {"alice": 50, "bob": 80}
    recs = audit.records()
    assert len(recs) == 6
    assert len({r["query_id"] for r in recs}) == 6     # disjoint ids
    assert len({r["trace"] for r in recs}) == 6        # disjoint traces
    rep = meter.report()
    assert rep["alice"]["queries"] == 3
    assert rep["alice"]["rows_out"] == 150
    assert rep["bob"]["queries"] == 3
    assert rep["bob"]["rows_out"] == 240


def test_device_seconds_attribute_to_the_owning_principal(clean_acct):
    """The ledger->ticket join: >= 90% of measured ledger time lands
    on the principal that ran the work (acceptance floor)."""
    sjoin, pts = _streamed_join()
    ledger.reset()
    with accounted("join-a", principal="alice"):
        sjoin(pts)
    with accounted("join-b", principal="bob"):
        sjoin(pts)
        sjoin(pts)
    total = ledger.seconds("pip/streamed")
    rep = meter.report()
    attributed = rep["alice"]["device_s"] + rep["bob"]["device_s"]
    assert total > 0
    assert attributed >= 0.9 * total
    # and the split leans the right way: bob ran 2 of 3 passes
    assert rep["bob"]["device_s"] > rep["alice"]["device_s"]


def test_accounted_charges_h2d_and_registers_slos(clean_acct):
    sjoin, pts = _streamed_join()
    with accounted("join", principal="carol"):
        sjoin(pts)
    assert meter.report()["carol"]["h2d_bytes"] > 0
    names = {o.name for o in monitor.objectives()}
    assert "principal_latency:carol" in names
    assert "principal_qps:carol" in names
    # principal series got the per-query latency point
    from mosaic_tpu.obs.timeseries import timeseries
    s = timeseries.series("principal/query_ms/carol")
    assert s is not None and s.raw


# ----------------------------------------------------- audit log

def test_audit_ring_is_bounded_and_filterable(clean_acct):
    small = type(audit)(capacity=4)
    for i in range(10):
        small.append({"query_id": f"q{i}", "principal": "p",
                      "outcome": "ok" if i % 2 else "error"})
    assert small.written() == 10
    assert len(small.records()) == 4          # ring keeps the tail
    assert [r["query_id"] for r in small.records(limit=2)] \
        == ["q8", "q9"]
    assert all(r["outcome"] == "ok"
               for r in small.records(outcome="ok"))


def test_audit_spool_writes_jsonl(clean_acct, clean_config, session,
                                  tmp_path):
    spool = tmp_path / "audit.jsonl"
    session.principal = "alice"
    session.sql(f"SET mosaic.audit.path = {spool}")
    session.sql("SELECT x FROM pts LIMIT 2")
    session.sql("SELECT x FROM pts LIMIT 3")
    lines = [json.loads(ln) for ln
             in spool.read_text().strip().splitlines()]
    # the SET itself may spool depending on ordering; the two SELECTs
    # must be the last two records
    assert len(lines) >= 2
    assert [r["cost"]["rows_out"] for r in lines[-2:]] == [2, 3]
    assert all(r["principal"] == "alice" for r in lines[-2:])


# ----------------------------------------------------- openmetrics

def test_openmetrics_escapes_hostile_principal_label(clean_acct):
    hostile = 'evil"name\nwith\\stuff'
    meter.charge(hostile, {"wall_ms": 5.0})
    from mosaic_tpu.obs.openmetrics import to_openmetrics
    txt = to_openmetrics()
    want = 'mosaic_principal_queries_total{principal=' \
        '"evil\\"name\\nwith\\\\stuff"} 1'
    assert want in txt
    # no raw newline/quote leaks into any sample line
    for ln in txt.splitlines():
        if "principal=" in ln:
            assert "\n" not in ln
    # HELP lines are escaped too (never a raw newline mid-line)
    helps = [ln for ln in txt.splitlines()
             if ln.startswith("# HELP mosaic_principal_")]
    assert helps


def test_openmetrics_principal_series_share_one_family(clean_acct):
    meter.charge("a", {"wall_ms": 1.0})
    meter.charge("b", {"wall_ms": 2.0})
    from mosaic_tpu.obs.openmetrics import to_openmetrics
    txt = to_openmetrics()
    fam = [ln for ln in txt.splitlines()
           if ln.startswith("mosaic_principal_queries_total{")]
    assert len(fam) == 2                      # one labeled series each
    assert txt.count("# TYPE mosaic_principal_queries_total") == 1


# ----------------------------------------------------- pipeline

def test_raising_observer_does_not_kill_the_stream(clean_acct):
    from mosaic_tpu.perf.pipeline import stream
    chunks = [np.arange(4.0), np.arange(4.0) + 4]

    def bad_observe(i, payload, seconds):
        raise RuntimeError("observer bug")

    out = stream(chunks, compute=lambda x: x * 2,
                 observe=bad_observe)
    np.testing.assert_allclose(out[0], chunks[0] * 2)
    np.testing.assert_allclose(out[1], chunks[1] * 2)
    assert metrics.counter_value("pipeline/observe_errors") == 2
    # flight-recorded once per stream, not once per chunk
    assert len(recorder.events("pipeline_observe_error")) == 1


# ----------------------------------------------------- dashboard

def _req(base, path, method="GET"):
    req = urllib.request.Request(base + path, method=method)
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_dashboard_query_console_routes(clean_acct, session):
    from mosaic_tpu.obs import serve_dashboard
    session.principal = "carol"
    session.sql("SELECT x FROM pts LIMIT 5")
    with serve_dashboard(port=0) as h:
        base = f"http://127.0.0.1:{h.port}"
        st, hd, body = _req(base, "/api/queries")
        assert st == 200
        assert hd.get("Cache-Control") == "no-store"
        q = json.loads(body)
        assert q["inflight"] == []
        assert q["recent"][-1]["principal"] == "carol"
        st, hd, body = _req(base, "/api/principals")
        assert st == 200 and hd.get("Cache-Control") == "no-store"
        assert json.loads(body)["principals"]["carol"]["queries"] == 1
        # unknown /api/* -> JSON 404, still no-store
        st, hd, body = _req(base, "/api/bogus")
        assert st == 404
        assert hd.get("Cache-Control") == "no-store"
        assert json.loads(body)["error"] == "not found"
        # cancel is POST-only
        st, hd, body = _req(base, "/api/queries/qx/cancel")
        assert st == 405 and hd.get("Allow") == "POST"
        st, _, body = _req(base, "/api/queries/qx/cancel", "POST")
        assert st == 404
        assert json.loads(body) == {"query_id": "qx",
                                    "cancelled": False}
        # the console sections are on the page
        st, _, body = _req(base, "/")
        assert st == 200 and b"Queries in flight" in body


def test_dashboard_cancels_live_query_via_post(clean_acct, session):
    from mosaic_tpu.obs import serve_dashboard
    session.principal = "carol"
    faults.arm("site=sql.query,mode=delay,fails=1,delay_ms=700")
    res = {}

    def run():
        try:
            session.sql("SELECT x FROM pts")
        except QueryCancelled as e:
            res["exc"] = e

    with serve_dashboard(port=0) as h:
        base = f"http://127.0.0.1:{h.port}"
        th = threading.Thread(target=run)
        th.start()
        deadline = time.time() + 0.5
        q = []
        while not q and time.time() < deadline:
            q = json.loads(_req(base, "/api/queries")[2])["inflight"]
            time.sleep(0.01)
        assert q and q[0]["principal"] == "carol"
        st, _, body = _req(
            base, f"/api/queries/{q[0]['query_id']}/cancel", "POST")
        assert st == 200 and json.loads(body)["cancelled"] is True
        th.join(timeout=10)
    assert res["exc"].outcome == "cancelled"
    assert audit.records()[-1]["outcome"] == "cancelled"


# ----------------------------------------------------- recorder bundle

def test_flight_bundle_carries_query_console_state(clean_acct,
                                                   session):
    session.principal = "alice"
    session.sql("SELECT x FROM pts LIMIT 1")
    b = recorder.bundle(reason="test")
    assert b["queries"]["recent"][-1]["principal"] == "alice"
    assert b["queries"]["principals"]["alice"]["queries"] == 1
    assert b["queries"]["inflight"] == []
