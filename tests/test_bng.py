"""BNGIndexSystem + the grid backend matrix.

Mirrors the reference's backend-matrix idea
(test/MosaicSpatialQueryTest.scala:17-131: every engine test runs across
index systems) and BNGIndexSystemTest behaviors: id encoding, string
round-trip, quadrant resolutions, kRing/kLoop, polyfill over the engine.
"""

import numpy as np
import pytest

from mosaic_tpu.core.geometry.wkt import read_wkt
from mosaic_tpu.core.index.bng import BNGIndexSystem
from mosaic_tpu.core.index.factory import get_index_system
from mosaic_tpu.core.tessellate import tessellate, polyfill


@pytest.fixture(scope="module")
def bng():
    return BNGIndexSystem()


class TestIds:
    def test_known_grid_reference(self, bng):
        """OSGB: E=538000, N=177000 lies in TQ (London)."""
        ids = bng.point_to_cell(np.array([[538000.0, 177000.0]]), 1)
        assert bng.format_cell_id(ids)[0] == "TQ"
        ids4 = bng.point_to_cell(np.array([[538123.0, 177987.0]]), 4)
        # 100m res: eBin=381, nBin=779 from (38123, 77987)
        assert bng.format_cell_id(ids4)[0] == "TQ381779"

    def test_quadrant_strings(self, bng):
        # 500m resolution = quadrant of the 1km cell
        pts = np.array([[538100.0, 177100.0],    # SW of km cell
                        [538100.0, 177900.0],    # NW
                        [538900.0, 177900.0],    # NE
                        [538900.0, 177100.0]])   # SE
        ids = bng.point_to_cell(pts, -4)
        names = bng.format_cell_id(ids)
        assert names == ["TQ3877SW", "TQ3877NW", "TQ3877NE", "TQ3877SE"]

    def test_res_minus_one_blocks(self, bng):
        """500km blocks S,T,N,O,H,J round-trip and decode distinctly
        (the reference's own res −1 encode is lossy — see _encode)."""
        pts = np.array([[100.0, 100.0], [600_000.0, 100.0],
                        [100.0, 600_000.0], [600_000.0, 600_000.0],
                        [100.0, 1_100_000.0], [600_000.0, 1_100_000.0]])
        ids = bng.point_to_cell(pts, -1)
        assert len(set(ids.tolist())) == 6
        names = bng.format_cell_id(ids)
        assert names == ["S", "T", "N", "O", "H", "J"]
        np.testing.assert_array_equal(bng.parse_cell_id(names), ids)
        c = bng.cell_center(ids)
        assert np.all(bng.point_to_cell(c, -1) == ids)
        assert np.all(bng.is_valid_cell(ids))
        import jax.numpy as jnp
        np.testing.assert_array_equal(
            np.asarray(bng.point_to_cell_jax(jnp.asarray(pts), -1)), ids)

    @pytest.mark.parametrize("res", [1, 2, 3, 4, 5, 6, -2, -3, -4, -5,
                                     -6])
    def test_roundtrip_ids(self, bng, rng, res):
        pts = np.stack([rng.uniform(0, 700_000, 200),
                        rng.uniform(0, 1_300_000, 200)], -1)
        ids = bng.point_to_cell(pts, res)
        assert np.all(bng.resolution_of(ids) == res)
        back = bng.parse_cell_id(bng.format_cell_id(ids))
        np.testing.assert_array_equal(back, ids)

    @pytest.mark.parametrize("res", [1, 3, 4, -2, -4, -6])
    def test_center_in_cell_and_containment(self, bng, rng, res):
        pts = np.stack([rng.uniform(0, 700_000, 100),
                        rng.uniform(0, 1_300_000, 100)], -1)
        ids = bng.point_to_cell(pts, res)
        verts, counts = bng.cell_boundary(ids)
        assert np.all(counts == 4)
        # each source point inside its own cell square
        x0 = verts[:, 0, 0]
        y0 = verts[:, 0, 1]
        x1 = verts[:, 2, 0]
        y1 = verts[:, 2, 1]
        assert np.all((pts[:, 0] >= x0) & (pts[:, 0] < x1))
        assert np.all((pts[:, 1] >= y0) & (pts[:, 1] < y1))
        c = bng.cell_center(ids)
        assert np.all(bng.point_to_cell(c, res) == ids)

    def test_edge_sizes(self, bng):
        assert bng.edge_size(1) == 100_000
        assert bng.edge_size(6) == 1
        assert bng.edge_size(-1) == 500_000
        assert bng.edge_size(-4) == 500
        assert bng.cell_area(np.array([
            bng.point_to_cell(np.array([[1000.0, 1000.0]]), 3)[0]
        ]))[0] == pytest.approx(1_000_000.0)

    def test_jax_kernel_matches_host(self, bng, rng):
        import jax.numpy as jnp
        pts = np.stack([rng.uniform(0, 700_000, 500),
                        rng.uniform(0, 1_300_000, 500)], -1)
        for res in (2, 4, -3, -5):
            host = bng.point_to_cell(pts, res)
            dev = np.asarray(bng.point_to_cell_jax(jnp.asarray(pts), res))
            np.testing.assert_array_equal(host, dev)

    def test_invalid_res(self, bng):
        with pytest.raises(ValueError, match="resolution"):
            bng.point_to_cell(np.array([[0.0, 0.0]]), 0)
        with pytest.raises(ValueError, match="resolution"):
            bng.point_to_cell(np.array([[0.0, 0.0]]), 9)

    def test_parse_errors(self, bng):
        with pytest.raises(ValueError, match="letter pair"):
            bng.parse_cell_id(["ZZ12"])


class TestNeighbours:
    def test_k_ring_counts(self, bng):
        c = bng.point_to_cell(np.array([[350_000.0, 650_000.0]]), 3)
        ring = bng.k_ring(c, 1)
        assert (ring[0] >= 0).sum() == 9
        ring2 = bng.k_ring(c, 2)
        assert (ring2[0] >= 0).sum() == 25

    def test_k_loop_counts(self, bng):
        c = bng.point_to_cell(np.array([[350_000.0, 650_000.0]]), 3)
        loop = bng.k_loop(c, 1)
        assert (loop[0] >= 0).sum() == 8
        loop2 = bng.k_loop(c, 2)
        assert (loop2[0] >= 0).sum() == 16

    def test_edge_of_domain_truncates(self, bng):
        c = bng.point_to_cell(np.array([[500.0, 500.0]]), 3)  # SW corner
        ring = bng.k_ring(c, 1)
        assert (ring[0] >= 0).sum() == 4    # only NE quadrant exists

    def test_grid_distance(self, bng):
        a = bng.point_to_cell(np.array([[100_500.0, 100_500.0]]), 3)
        b = bng.point_to_cell(np.array([[103_500.0, 101_500.0]]), 3)
        assert bng.grid_distance(a, b)[0] == 3


GRIDS = [
    ("BNG", 3, (100_000, 100_000, 200_000, 200_000)),
    ("CUSTOM(0,16,0,16,2,1,1)", 2, (0, 0, 16, 16)),
    ("H3", 7, (-74.1, 40.6, -73.9, 40.8)),
]


@pytest.mark.parametrize("name,res,domain", GRIDS,
                         ids=[g[0].split("(")[0] for g in GRIDS])
class TestBackendMatrix:
    """Same engine assertions across all three grids (reference:
    MosaicSpatialQueryTest backend matrix)."""

    def _poly(self, domain):
        x0, y0, x1, y1 = domain
        w, h = x1 - x0, y1 - y0
        ring = [(x0 + 0.2 * w, y0 + 0.2 * h), (x0 + 0.8 * w, y0 + 0.25 * h),
                (x0 + 0.7 * w, y0 + 0.8 * h), (x0 + 0.4 * w, y0 + 0.6 * h),
                (x0 + 0.2 * w, y0 + 0.75 * h), (x0 + 0.2 * w, y0 + 0.2 * h)]
        wkt = "POLYGON((" + ", ".join(f"{x} {y}" for x, y in ring) + "))"
        return read_wkt([wkt])

    def test_tessellate_core_border(self, name, res, domain):
        grid = get_index_system(name)
        polys = self._poly(domain)
        chips = tessellate(polys, res, grid)
        assert len(chips.cell_id) > 10
        assert chips.is_core.sum() > 0
        assert (~chips.is_core).sum() > 0
        # polyfill ⊆ touching cells; core cells ⊆ polyfill
        pf = set(polyfill(polys, res, grid)[0].tolist())
        cells = set(chips.cell_id.tolist())
        core = set(chips.cell_id[chips.is_core].tolist())
        assert core <= pf <= cells

    def test_chip_areas_sum_to_polygon(self, name, res, domain):
        """Σ chip areas == polygon area (exact tessellation)."""
        from mosaic_tpu.core.geometry.clip import (geometry_rings,
                                                   ring_signed_area)
        grid = get_index_system(name)
        polys = self._poly(domain)
        chips = tessellate(polys, res, grid, keep_core_geom=True)
        total = 0.0
        for i in range(len(chips.cell_id)):
            rings = geometry_rings(chips.geoms, i)
            total += sum(ring_signed_area(r) for r in rings)
        want = sum(ring_signed_area(r)
                   for r in geometry_rings(polys, 0))
        assert total == pytest.approx(want, rel=1e-6)

    def test_pip_join_parity(self, name, res, domain, rng):
        import jax
        import jax.numpy as jnp
        from mosaic_tpu.parallel.pip_join import (build_pip_index,
                                                  host_recheck, localize,
                                                  make_pip_join_fn,
                                                  pip_host_truth)
        grid = get_index_system(name)
        polys = self._poly(domain)
        idx = build_pip_index(polys, res, grid)
        fn = jax.jit(make_pip_join_fn(idx, grid))
        x0, y0, x1, y1 = domain
        pts = np.stack([rng.uniform(x0, x1, 3000),
                        rng.uniform(y0, y1, 3000)], -1)
        z, u = fn(jnp.asarray(localize(idx, pts)))
        final = host_recheck(pts, np.asarray(z), np.asarray(u), polys)
        truth = pip_host_truth(pts, polys)
        assert np.array_equal(final, truth)
