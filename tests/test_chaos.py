"""Chaos tests: seeded fault plans driven through real code paths.

Every test arms a deterministic :class:`FaultPlan` (same seed -> same
injections) and asserts the resilience contract end to end: skip-mode
recovers every intact record with counts asserted, retried checkpoint /
native ops succeed after transient injected failures, and raise-mode on
clean inputs matches the undamaged decode byte for byte.
"""

import dataclasses
import os
import shutil
import struct

import numpy as np
import pytest

from mosaic_tpu import config as _config
from mosaic_tpu.core.raster.gtiff import read_gtiff, write_gtiff
from mosaic_tpu.core.raster.tile import GeoTransform, RasterTile
from mosaic_tpu.resilience import faults

GRIB_FIX = os.path.join(os.path.dirname(__file__), "data",
                        "cams_sample.grb")
SHP_FIX = os.path.join(os.path.dirname(__file__), "data",
                       "nyc_taxi_zones_2263.shp")


def _tile(bands=1, h=8, w=512, nodata=None):
    """Striped GeoTIFF fixture: w*8 bytes/row -> 2 rows/strip -> 4
    strips, so one damaged strip leaves the rest intact."""
    data = np.arange(bands * h * w, dtype=np.float64).reshape(
        bands, h, w) + 1.0
    gt = GeoTransform(0.0, 1.0, 0.0, 0.0, 0.0, -1.0)
    return RasterTile(data, gt, nodata=nodata)


# ------------------------------------------------------------- gtiff

def test_gtiff_strip_corruption_skip_recovers_rest(fault_plan):
    tile = _tile()
    blob = write_gtiff(tile)
    clean = read_gtiff(blob)
    plan = fault_plan(
        "seed=21;site=gtiff.read_strip,fails=1,mode=truncate")
    out = read_gtiff(blob, on_error="skip", path="t.tif")
    assert [s for s, _, _ in plan.injected] == ["gtiff.read_strip"]
    recs = out.meta["decode_errors"]
    assert len(recs) == 1
    assert recs[0]["feature"] == "strip 0"
    assert recs[0]["path"] == "t.tif"
    # strip 0 = rows 0..1 zeroed; every other row byte-identical
    got = np.asarray(out.data)
    want = np.asarray(clean.data)
    assert np.array_equal(got[:, 2:], want[:, 2:])
    assert np.all(got[:, :2] == 0.0)


def test_gtiff_strip_corruption_null_fills_nodata(fault_plan):
    blob_nan = write_gtiff(_tile())
    blob_nd = write_gtiff(_tile(nodata=-9999.0))
    fault_plan("seed=21;site=gtiff.read_strip,fails=1,mode=truncate")
    out = read_gtiff(blob_nan, on_error="null")
    assert np.all(np.isnan(np.asarray(out.data)[:, :2]))
    fault_plan("seed=21;site=gtiff.read_strip,fails=1,mode=truncate")
    out = read_gtiff(blob_nd, on_error="null")
    assert np.all(np.asarray(out.data)[:, :2] == -9999.0)


def test_gtiff_strip_corruption_raise_mode_locates(fault_plan):
    blob = write_gtiff(_tile())
    fault_plan("seed=21;site=gtiff.read_strip,fails=1,mode=truncate")
    with pytest.raises(ValueError, match="strip 0"):
        read_gtiff(blob)                  # default on_error="raise"


def test_gtiff_clean_input_parity_across_modes(no_faults):
    tile = _tile(bands=2, h=6, w=256)
    blob = write_gtiff(tile)
    want = np.asarray(read_gtiff(blob).data)
    for mode in ("raise", "skip", "null"):
        out = read_gtiff(blob, on_error=mode)
        assert np.array_equal(np.asarray(out.data), want)
        assert "decode_errors" not in out.meta


# -------------------------------------------------------------- grib

@pytest.fixture(scope="module")
def grib_bytes():
    with open(GRIB_FIX, "rb") as f:
        return f.read()


def test_grib_injected_message_failure_skip(fault_plan, grib_bytes):
    from mosaic_tpu.io.grib import read_grib
    clean = read_grib(grib_bytes)
    plan = fault_plan(
        "seed=22;site=grib.read_message,fails=1,error=ValueError")
    errs = []
    out = read_grib(grib_bytes, on_error="skip", path="cams.grb",
                    errors=errs)
    assert len(plan.injected) == 1
    assert len(errs) == 1
    assert errs[0].feature == "message 0"
    assert errs[0].path == "cams.grb"
    # every message except the damaged one decodes identically
    assert set(out) < set(clean)
    for name in out:
        np.testing.assert_array_equal(out[name].data, clean[name].data)
    lost = set(clean) - set(out)
    assert lost and all(n.endswith("_0") or "_0_" in n for n in lost)


def test_grib_injected_message_failure_raise(fault_plan, grib_bytes):
    from mosaic_tpu.io.grib import read_grib
    fault_plan("seed=22;site=grib.read_message,fails=1,error=ValueError")
    with pytest.raises(ValueError, match="message 0"):
        read_grib(grib_bytes)


# --------------------------------------------------------- shapefile

def test_shapefile_record_corruption_skip_drops_row(fault_plan,
                                                    tmp_path):
    from mosaic_tpu.io.shapefile import read_shapefile
    clean_geoms, clean_cols = read_shapefile(SHP_FIX)
    n = len(clean_geoms)
    plan = fault_plan(
        "seed=23;site=shapefile.read_record,fails=1,mode=truncate")
    errs = []
    geoms, cols = read_shapefile(SHP_FIX, on_error="skip", errors=errs)
    assert len(plan.injected) == 1
    assert len(errs) == 1 and errs[0].feature == "record 0"
    assert len(geoms) == n - 1
    for k, v in cols.items():
        assert len(v) == n - 1                 # dbf row dropped too
        assert v == clean_cols[k][1:]


def test_shapefile_record_corruption_null_keeps_alignment(fault_plan):
    from mosaic_tpu.core.geometry.array import GeometryType
    from mosaic_tpu.io.shapefile import read_shapefile
    clean_geoms, clean_cols = read_shapefile(SHP_FIX)
    n = len(clean_geoms)
    fault_plan(
        "seed=23;site=shapefile.read_record,fails=1,mode=truncate")
    geoms, cols = read_shapefile(SHP_FIX, on_error="null")
    assert len(geoms) == n
    assert geoms.geom_type(0) == GeometryType.GEOMETRYCOLLECTION
    for k, v in cols.items():
        assert v == clean_cols[k]              # all rows kept


def test_dbf_bad_numeric_degrades_to_null(tmp_path):
    from mosaic_tpu.core.geometry.array import GeometryBuilder
    from mosaic_tpu.io.shapefile import read_shapefile, write_shapefile
    b = GeometryBuilder()
    for x in (0.0, 2.0, 4.0):
        b.add_point(np.array([x, 0.0]))
    base = str(tmp_path / "pts")
    write_shapefile(base, b.finish(), {"val": [1, 2, 3]})
    with open(base + ".dbf", "rb") as f:
        buf = f.read()
    patched = buf.replace(b" " * 17 + b"2", b" " * 17 + b"x")
    assert patched != buf
    with open(base + ".dbf", "wb") as f:
        f.write(patched)
    with pytest.raises(ValueError, match="field val"):
        read_shapefile(base)
    errs = []
    geoms, cols = read_shapefile(base, on_error="skip", errors=errs)
    assert len(geoms) == 3                     # geometry row survives
    assert cols["val"] == [1, None, 3]
    assert len(errs) == 1 and "field val" in errs[0].feature


# ------------------------------------------------------------- netcdf

def test_netcdf_truncated_variable_skip(fault_plan):
    from mosaic_tpu.io.netcdf import read_netcdf, write_netcdf
    a = np.arange(12.0).reshape(3, 4)
    blob = write_netcdf({"aa": a, "zz": a * 2})
    clean = read_netcdf(blob)
    assert set(clean) == {"aa", "zz"}
    damaged = blob[:-16]          # tail = end of the last variable (zz)
    with pytest.raises(ValueError, match="variable zz"):
        read_netcdf(damaged, path="t.nc")
    errs = []
    out = read_netcdf(damaged, on_error="skip", errors=errs)
    assert set(out) == {"aa"}
    np.testing.assert_array_equal(out["aa"].data, clean["aa"].data)
    assert len(errs) == 1 and errs[0].feature == "variable zz"


# -------------------------------------------------------------- gpkg

def test_gpkg_malformed_blob_skip(tmp_path):
    import sqlite3

    from mosaic_tpu.core.geometry.array import GeometryBuilder
    from mosaic_tpu.io.geopackage import read_gpkg, write_gpkg
    b = GeometryBuilder()
    for x in (0.0, 1.0, 2.0):
        ring = np.array([[x, 0.0], [x + 0.5, 0.0], [x + 0.5, 0.5],
                         [x, 0.5], [x, 0.0]])
        b.add_polygon(ring)
    path = str(tmp_path / "t.gpkg")
    write_gpkg(path, b.finish(), {"fid_val": [10, 20, 30]})
    con = sqlite3.connect(path)
    con.execute("UPDATE layer SET geom = X'DEADBEEF' WHERE rowid = 2")
    con.commit()
    con.close()
    with pytest.raises(ValueError, match="row 1"):
        read_gpkg(path)
    errs = []
    geoms, cols = read_gpkg(path, on_error="skip", errors=errs)
    assert len(geoms) == 2
    assert cols["fid_val"] == [10, 30]
    assert len(errs) == 1 and errs[0].feature == "row 1"


# ---------------------------------------------------------------- mvt

def test_mvt_injected_feature_failures_skip(fault_plan):
    from mosaic_tpu.core.geometry.array import GeometryBuilder
    from mosaic_tpu.io.vectortile import decode_mvt, st_asmvttileagg
    b = GeometryBuilder()
    for x in (-0.4, 0.2, 0.4):
        ring = np.array([[x, 0.1], [x + 0.1, 0.1], [x + 0.1, 0.2],
                         [x, 0.2], [x, 0.1]])
        b.add_polygon(ring)
    blob = st_asmvttileagg(b.finish(), {"v": [1, 2, 3]}, 0, 0, 0)
    clean = decode_mvt(blob)["layer"]
    nfeat = len(clean["features"])
    assert nfeat == 3
    plan = fault_plan(
        "seed=27;site=mvt.decode_feature,rate=0.5,error=ValueError")
    errs = []
    out = decode_mvt(blob, on_error="skip", errors=errs)["layer"]
    assert len(out["features"]) + len(errs) == nfeat
    assert 1 <= len(errs) <= nfeat
    assert len(errs) == len([1 for s, _, _ in plan.injected
                             if s == "mvt.decode_feature"])


# ------------------------------------------------- checkpoint retries

def test_raster_checkpoint_rides_out_transient_io(fault_plan, tmp_path):
    from mosaic_tpu.core.raster.checkpoint import (deserialize_tile,
                                                   serialize_tile)
    cfg = dataclasses.replace(_config.default_config(),
                              raster_use_checkpoint=True,
                              raster_checkpoint=str(tmp_path))
    tile = _tile(h=4, w=64)
    plan = fault_plan("seed=31;site=checkpoint.write,fails=2;"
                      "site=checkpoint.read,fails=2")
    rec = serialize_tile(tile, cfg)
    assert isinstance(rec["raster"], str)      # path mode
    out = deserialize_tile(rec)
    np.testing.assert_array_equal(np.asarray(out.data),
                                  np.asarray(tile.data))
    sites = [s for s, _, _ in plan.injected]
    assert sites.count("checkpoint.write") == 2
    assert sites.count("checkpoint.read") == 2


def test_model_checkpoint_save_retry_and_torn_latest(fault_plan,
                                                     tmp_path):
    from mosaic_tpu.models.checkpoint import CheckpointManager
    from mosaic_tpu.models.core import IterationState
    mgr = CheckpointManager(str(tmp_path), keep=3)
    plan = fault_plan("seed=32;site=checkpoint.model_write,fails=2")
    mgr.save(IterationState(iteration=1,
                            payload={"x": np.arange(3.0)}))
    assert len(plan.injected) == 2             # retried through
    fault_plan("seed=32")                      # no rules: clean writes
    mgr.save(IterationState(iteration=2,
                            payload={"x": np.arange(4.0)}))
    # tear the newest checkpoint: resume must degrade to iteration 1
    with open(mgr._file(2), "wb") as f:
        f.write(b"this is not an npz archive")
    got = mgr.load_latest()
    assert got is not None and got.iteration == 1
    np.testing.assert_array_equal(got.payload["x"], np.arange(3.0))


# ----------------------------------------------------- native rebuild

def test_native_cdll_lost_library_rebuild(fault_plan):
    if shutil.which("g++") is None:
        pytest.skip("no g++ toolchain in this environment")
    if os.environ.get("MOSAIC_TPU_DISABLE_NATIVE"):
        pytest.skip("native layer disabled via env")
    import mosaic_tpu.native as native
    prev_lib, prev_tried = native._LIB, native._TRIED
    try:
        native._LIB, native._TRIED = None, False
        plan = fault_plan("seed=33;site=native.cdll,fails=1")
        lib = native.get_lib()
        assert lib is not None                 # rebuilt + reloaded
        assert ("native.cdll", 0, "OSError") in plan.injected
    finally:
        native._LIB, native._TRIED = prev_lib, prev_tried


def test_native_compile_transient_failure_recovers(fault_plan,
                                                   tmp_path):
    if shutil.which("g++") is None:
        pytest.skip("no g++ toolchain in this environment")
    import mosaic_tpu.native as native
    src = os.path.join(os.path.dirname(native.__file__),
                       "geokernels.cpp")
    lib_path = str(tmp_path / "geokernels-test.so")
    plan = fault_plan("seed=34;site=native.compile,fails=1")
    assert native._compile(src, lib_path) is True
    assert os.path.exists(lib_path)
    assert ("native.compile", 0, "OSError") in plan.injected


# ------------------------------------------- overlay capacity degrade

def test_overlay_survives_degraded_capacities(fault_plan):
    from mosaic_tpu.core.geometry.array import GeometryBuilder
    from mosaic_tpu.core.index.factory import get_index_system
    from mosaic_tpu.parallel.overlay import (overlay_host_truth,
                                             overlay_intersects)
    rng = np.random.default_rng(7)
    b = GeometryBuilder()
    for _ in range(40):
        cx = rng.uniform(-74.05, -73.90)
        cy = rng.uniform(40.65, 40.80)
        w = rng.uniform(2e-4, 2e-3)
        h = rng.uniform(2e-4, 2e-3)
        b.add_polygon(np.array([[cx - w, cy - h], [cx + w, cy - h],
                                [cx + w, cy + h], [cx - w, cy + h],
                                [cx - w, cy - h]]))
    a = b.finish()
    from mosaic_tpu.bench.workloads import nyc_zones
    zones = nyc_zones(n_side=3, seed=2,
                      bbox=(-74.05, 40.65, -73.90, 40.80))
    plan = fault_plan(
        "seed=35;site=overlay.*,mode=degrade,rate=1.0,factor=4")
    got = overlay_intersects(a, zones, 9, get_index_system("H3"))
    assert any(s.startswith("overlay.") for s, _, _ in plan.injected)
    faults.disarm()
    want = overlay_host_truth(a, zones)
    assert np.array_equal(got, want)


# ------------------------------------------- planner stats warm start

def test_planner_stats_load_transient_io_cold_start(fault_plan,
                                                    tmp_path):
    """An injected read failure on ``planner.stats.load`` degrades to
    a cold start (never raises); once the fault is spent the same file
    warm-starts a fresh planner."""
    from mosaic_tpu.sql.planner import Planner
    path = str(tmp_path / "stats.json")
    p = Planner()
    p.observe_op("pip_join/streamed/c16", 32768, 0.050, rows_out=900)
    assert p.save(path) == path

    plan = fault_plan(
        "seed=41;site=planner.stats.load,fails=1,error=OSError")
    p2 = Planner()
    assert p2.load(path) is False            # degraded: cold start
    assert p2.ms_per_row("pip_join/streamed/c16", 32768) is None
    assert ("planner.stats.load", 0, "OSError") in plan.injected

    p3 = Planner()                           # fault spent: warm start
    assert p3.load(path) is True
    assert p3.ms_per_row("pip_join/streamed/c16", 32768) == \
        pytest.approx(0.050 * 1e3 / 32768)


# ------------------------------------------------ fusion group stall

def test_fusion_group_stall_keeps_parity(fault_plan):
    """Latency chaos at the ``fusion.group`` boundary: the injected
    stall must not change what the fused program computes (parity vs
    the unfused pin), only when it starts."""
    from mosaic_tpu.functions.context import MosaicContext
    from mosaic_tpu.sql import SQLSession

    mc = MosaicContext.build("CUSTOM(-180,180,-90,90,2,360,180)")
    s = SQLSession(mc)
    rng = np.random.default_rng(11)
    s.create_table("cx", {"px": rng.normal(size=256),
                          "k": rng.integers(0, 100, size=256)})
    # fused sums are integer-only (float sums are order-dependent),
    # so aggregate over k to keep the group eligible
    q = "SELECT sum(k) AS t, count(*) AS n FROM cx WHERE k < 50"

    prev = _config.default_config()
    try:
        _config.set_default_config(_config.apply_conf(
            _config.default_config(),
            "mosaic.planner.force.fusion", "on"))
        plan = fault_plan(
            "seed=42;site=fusion.group,mode=delay,fails=1,delay_ms=1")
        fused = s.sql(q)
        assert ("fusion.group", 0, "delay") in plan.injected
        faults.disarm()
        _config.set_default_config(_config.apply_conf(
            _config.default_config(),
            "mosaic.planner.force.fusion", "off"))
        unfused = s.sql(q)
        assert np.array_equal(np.asarray(fused.columns["t"]),
                              np.asarray(unfused.columns["t"]))
    finally:
        _config.set_default_config(prev)


# ------------------------------------------------- gpkg row corruption

def test_gpkg_row_corruption_skip_drops_only_that_row(fault_plan,
                                                      tmp_path):
    """An injected per-row failure inside the GeoPackage feature loop
    (``gpkg.read_row``) drops exactly that row in skip mode and leaves
    the rest byte-identical; raise mode on the clean read matches the
    original geometries."""
    from mosaic_tpu.core.geometry.wkt import read_wkt, write_wkt
    from mosaic_tpu.io.geopackage import read_gpkg, write_gpkg

    geoms = read_wkt(["POINT (1 2)", "POINT (3 4)",
                      "LINESTRING (0 0, 3 4)"])
    path = str(tmp_path / "chaos.gpkg")
    write_gpkg(path, geoms, {"name": ["a", "b", "c"]},
               layer="t", srs_id=4326)

    plan = fault_plan(
        "seed=43;site=gpkg.read_row,fails=1,error=ValueError")
    errors: list = []
    got, cols = read_gpkg(path, on_error="skip", errors=errors)
    assert write_wkt(got) == write_wkt(geoms)[1:]    # row 0 dropped
    assert cols["name"] == ["b", "c"]
    assert len(errors) == 1
    assert ("gpkg.read_row", 0, "ValueError") in plan.injected

    faults.disarm()                     # clean read: full parity
    got2, cols2 = read_gpkg(path)
    assert write_wkt(got2) == write_wkt(geoms)
    assert cols2["name"] == ["a", "b", "c"]


# --------------------------------------- whole-file open fault sites

def test_shapefile_open_fault_raises_then_clean_read_matches(
        fault_plan):
    """``shapefile.read`` guards the whole-file open: an injected
    failure there surfaces straight to the caller (nothing salvageable
    before the .shp buffer exists), and the next, un-armed read is
    byte-for-byte what an undamaged session sees."""
    from mosaic_tpu.core.geometry.wkt import write_wkt
    from mosaic_tpu.io.shapefile import read_shapefile

    plan = fault_plan("seed=61;site=shapefile.read,fails=1,error=OSError")
    with pytest.raises(OSError):
        read_shapefile(SHP_FIX)
    assert ("shapefile.read", 0, "OSError") in plan.injected

    faults.disarm()
    geoms, cols = read_shapefile(SHP_FIX)
    geoms2, cols2 = read_shapefile(SHP_FIX)
    assert write_wkt(geoms) == write_wkt(geoms2)
    assert cols == cols2


def test_netcdf_open_fault_raises_then_clean_read_matches(fault_plan):
    """Same contract for ``netcdf.read``: the pre-header fault site
    fails the whole decode (header damage is never salvageable), and
    recovery after disarm is exact."""
    from mosaic_tpu.io.netcdf import read_netcdf, write_netcdf

    h, w = 6, 9
    yy, xx = np.mgrid[0:h, 0:w]
    blob = write_netcdf({"sst": (xx + yy).astype(np.float64)},
                        xs=0.5 + np.arange(w), ys=0.5 + np.arange(h))

    plan = fault_plan("seed=62;site=netcdf.read,fails=1,error=OSError")
    with pytest.raises(OSError):
        read_netcdf(blob)
    assert ("netcdf.read", 0, "OSError") in plan.injected

    faults.disarm()
    subs = read_netcdf(blob)
    np.testing.assert_array_equal(np.asarray(subs["sst"].data)[0],
                                  (xx + yy).astype(np.float64)[::-1])
