"""Raster tile checkpointing: bytes vs path serialization modes.

Reference pattern: SharedSparkSessionGDAL runs every raster test twice —
checkpointing on and off (src/test/.../SharedSparkSessionGDAL.scala:19) —
and RasterTileType switches the wire type accordingly.  Here the same
mini-pipeline runs in both modes and must agree exactly.
"""

import os

import numpy as np
import pytest

from mosaic_tpu import config as cfgmod
from mosaic_tpu.core.raster import checkpoint as ck
from mosaic_tpu.core.raster import rops
from mosaic_tpu.core.raster.tile import GeoTransform, RasterTile


@pytest.fixture
def tile():
    gt = GeoTransform(-74.1, 0.002, 0.0, 40.9, 0.0, -0.002)
    rng = np.random.default_rng(3)
    data = rng.uniform(0, 100, (2, 32, 40))
    return RasterTile(data, gt, nodata=-1.0, srid=4326, cell_id=42,
                      meta={"parent": "synthetic"})


@pytest.fixture(autouse=True)
def reset_config():
    prev = cfgmod.default_config()
    yield
    cfgmod.set_default_config(prev)


@pytest.mark.parametrize("use_checkpoint", [False, True])
def test_round_trip_both_modes(tile, tmp_path, use_checkpoint):
    if use_checkpoint:
        ck.enable_checkpoint(str(tmp_path / "ckpt"))
    else:
        ck.disable_checkpoint()
    rec = ck.serialize_tile(tile)
    if use_checkpoint:
        assert isinstance(rec["raster"], str)
        assert os.path.exists(rec["raster"])
        assert rec["raster"].startswith(str(tmp_path / "ckpt"))
    else:
        assert isinstance(rec["raster"], (bytes, bytearray))
    back = ck.deserialize_tile(rec)
    assert back.cell_id == 42
    assert back.srid == tile.srid
    assert back.gt.to_tuple() == pytest.approx(tile.gt.to_tuple())
    np.testing.assert_allclose(np.asarray(back.data),
                               np.asarray(tile.data), rtol=1e-6)
    assert back.meta.get("parent") == "synthetic"


def test_pipeline_identical_both_modes(tile, tmp_path):
    """Every-op-twice: serialize between stages in both modes; results
    must be bitwise identical."""
    def pipeline():
        rec = ck.serialize_tile(tile)
        t1 = ck.deserialize_tile(rec)
        t2 = rops.convolve(t1, np.ones((3, 3)) / 9.0)
        rec2 = ck.serialize_tile(t2)
        t3 = ck.deserialize_tile(rec2)
        return np.asarray(t3.data)

    ck.disable_checkpoint()
    a = pipeline()
    ck.enable_checkpoint(str(tmp_path / "ck2"))
    b = pipeline()
    np.testing.assert_array_equal(a, b)
    assert len(os.listdir(tmp_path / "ck2")) >= 1


def test_checkpoint_dedupe_and_management(tile, tmp_path):
    ck.enable_checkpoint(str(tmp_path / "ck3"))
    assert ck.is_checkpoint_enabled()
    assert ck.checkpoint_dir() == str(tmp_path / "ck3")
    r1 = ck.serialize_tile(tile)
    r2 = ck.serialize_tile(tile)
    # identical content -> same hashed file, no duplicates
    assert r1["raster"] == r2["raster"]
    assert len([f for f in os.listdir(tmp_path / "ck3")
                if f.endswith(".tif")]) == 1
    ck.disable_checkpoint()
    assert not ck.is_checkpoint_enabled()
    r3 = ck.serialize_tile(tile)
    assert isinstance(r3["raster"], bytes)
