"""Polygon boolean ops (clip.py) + overlay function surface.

Oracle strategy (no JTS/shapely in the image): a point p is in the result
region iff (p ∈ A) op (p ∈ B) under even-odd membership — checked on dense
random samples away from input boundaries — plus exact area identities on
hand-built cases.  Mirrors the reference's ST_Intersection/ST_Union
behavior tests (expressions/geometry/ST_IntersectionBehaviors.scala).
"""

import numpy as np
import pytest

from mosaic_tpu.core.geometry.clip import (_edges_of, _pip_rings,
                                           _seg_point_dist, boolean_op,
                                           ring_signed_area, rings_boolean,
                                           unary_union_rings)
from mosaic_tpu.functions.context import MosaicContext


def sq(x0, y0, x1, y1):
    return np.array([[x0, y0], [x1, y0], [x1, y1], [x0, y1]], float)


def region_area(rings):
    return sum(ring_signed_area(r) for r in rings)


OPS = ["intersection", "union", "difference", "symdifference"]


class TestRingsBoolean:
    def test_overlapping_squares(self):
        A, B = [sq(0, 0, 2, 2)], [sq(1, 1, 3, 3)]
        expect = {"intersection": 1.0, "union": 7.0, "difference": 3.0,
                  "symdifference": 6.0}
        for op, want in expect.items():
            assert region_area(rings_boolean(A, B, op)) == \
                pytest.approx(want)

    def test_disjoint(self):
        A, B = [sq(0, 0, 1, 1)], [sq(5, 5, 6, 6)]
        assert rings_boolean(A, B, "intersection") == []
        assert region_area(rings_boolean(A, B, "union")) == \
            pytest.approx(2.0)
        assert region_area(rings_boolean(A, B, "difference")) == \
            pytest.approx(1.0)

    def test_contained_makes_hole(self):
        A, B = [sq(0, 0, 4, 4)], [sq(1, 1, 2, 2)]
        assert region_area(rings_boolean(A, B, "intersection")) == \
            pytest.approx(1.0)
        diff = rings_boolean(A, B, "difference")
        assert region_area(diff) == pytest.approx(15.0)
        assert len(diff) == 2        # shell + hole

    def test_shared_edge(self):
        A, B = [sq(0, 0, 1, 1)], [sq(1, 0, 2, 1)]
        assert region_area(rings_boolean(A, B, "union")) == \
            pytest.approx(2.0)
        assert rings_boolean(A, B, "intersection") == []

    def test_identical(self):
        A = [sq(0, 0, 1, 1)]
        assert region_area(rings_boolean(A, A, "intersection")) == \
            pytest.approx(1.0)
        assert rings_boolean(A, A, "difference") == []
        assert region_area(rings_boolean(A, A, "union")) == \
            pytest.approx(1.0)

    def test_hole_interaction(self):
        A = [sq(0, 0, 4, 4), sq(1, 1, 3, 3)[::-1]]   # donut
        B = [sq(2, 2, 5, 5)]
        assert region_area(rings_boolean(A, B, "intersection")) == \
            pytest.approx(3.0)
        assert region_area(rings_boolean(A, B, "union")) == \
            pytest.approx(18.0)

    def test_empty_inputs(self):
        A = [sq(0, 0, 1, 1)]
        assert rings_boolean(A, [], "intersection") == []
        assert region_area(rings_boolean(A, [], "union")) == \
            pytest.approx(1.0)
        assert region_area(rings_boolean([], A, "union")) == \
            pytest.approx(1.0)
        assert rings_boolean([], A, "difference") == []


def _star(cx, cy, rng, n=None):
    n = n or int(rng.integers(5, 12))
    while True:
        th = np.sort(rng.uniform(0, 2 * np.pi, n))
        gaps = np.diff(np.concatenate([th, [th[0] + 2 * np.pi]]))
        if gaps.max() < 2.6:
            break
    rad = rng.uniform(0.3, 1.5, n)
    return (np.stack([cx + rad * np.cos(th), cy + rad * np.sin(th)], -1),
            np.array([cx, cy]))


class TestMonteCarlo:
    def test_random_concave(self, rng):
        bad = 0
        for trial in range(40):
            s1, c1 = _star(rng.uniform(-0.5, 0.5), rng.uniform(-0.5, 0.5),
                           rng)
            s2, _ = _star(rng.uniform(-0.5, 0.5), rng.uniform(-0.5, 0.5),
                          rng)
            A, B = [s1], [s2]
            if trial % 3 == 1:
                A.append((c1[None] + (s1 - c1[None]) * 0.3)[::-1])
            if trial % 5 == 2:
                s3, _ = _star(rng.uniform(4.0, 5.0), rng.uniform(4.0, 5.0),
                              rng)
                B.append(s3)
            pts = rng.uniform(-2.5, 6.0, (2000, 2))
            in_a = _pip_rings(pts, A)
            in_b = _pip_rings(pts, B)
            d = np.minimum(_seg_point_dist(pts, _edges_of(A)),
                           _seg_point_dist(pts, _edges_of(B)))
            ok = d > 1e-3
            for op, want in [("intersection", in_a & in_b),
                             ("union", in_a | in_b),
                             ("difference", in_a & ~in_b),
                             ("symdifference", in_a ^ in_b)]:
                got = _pip_rings(pts, rings_boolean(A, B, op))
                bad += int((got[ok] != want[ok]).sum())
        assert bad == 0


class TestUnaryUnion:
    def test_chain_of_squares(self):
        parts = [[sq(i, 0, i + 1.5, 1)] for i in range(4)]
        rings = unary_union_rings(parts)
        # overlapping chain 0..4.5 × 0..1
        assert region_area(rings) == pytest.approx(4.5)


@pytest.fixture(scope="module")
def ctx():
    return MosaicContext.build("CUSTOM(0,16,0,16,2,1,1)")


class TestContextOverlay:
    def test_st_intersection_union(self, ctx):
        a = ctx.st_geomfromwkt(["POLYGON((0 0, 2 0, 2 2, 0 2, 0 0))"])
        b = ctx.st_geomfromwkt(["POLYGON((1 1, 3 1, 3 3, 1 3, 1 1))"])
        assert ctx.st_area(ctx.st_intersection(a, b))[0] == \
            pytest.approx(1.0)
        assert ctx.st_area(ctx.st_union(a, b))[0] == pytest.approx(7.0)
        assert ctx.st_area(ctx.st_difference(a, b))[0] == \
            pytest.approx(3.0)
        assert ctx.st_area(ctx.st_symdifference(a, b))[0] == \
            pytest.approx(6.0)

    def test_st_unaryunion(self, ctx):
        g = ctx.st_geomfromwkt([
            "MULTIPOLYGON(((0 0, 2 0, 2 2, 0 2, 0 0)),"
            "((1 1, 3 1, 3 3, 1 3, 1 1)))"])
        assert ctx.st_area(ctx.st_unaryunion(g))[0] == pytest.approx(7.0)

    def test_intersection_agg_reconstructs_overlay(self, ctx):
        """BASELINE config 3 in miniature: tessellate two overlapping
        concave polygons, join chips per cell, aggregate, compare to the
        direct polygon∩polygon."""
        a = ctx.st_geomfromwkt(
            ["POLYGON((1 1, 9 1, 9 5, 5 5, 5 9, 1 9, 1 1))"])   # L-shape
        b = ctx.st_geomfromwkt(["POLYGON((3 3, 12 3, 12 12, 3 12, 3 3))"])
        res = 2
        ca = ctx.grid_tessellate(a, res)
        cb = ctx.grid_tessellate(b, res)
        common, ia, ib = np.intersect1d(ca.cell_id, cb.cell_id,
                                        return_indices=True)
        la = ca.take(ia) if hasattr(ca, "take") else None
        import mosaic_tpu.types as T
        take = lambda cs, idx: T.ChipSet(cs.geom_id[idx], cs.cell_id[idx],
                                         cs.is_core[idx],
                                         cs.geoms.take(idx))
        agg = ctx.st_intersection_agg(take(ca, ia), take(cb, ib))
        direct = ctx.st_intersection(a, b)
        assert ctx.st_area(agg)[0] == \
            pytest.approx(ctx.st_area(direct)[0], rel=1e-9)

    def test_union_agg(self, ctx):
        a = ctx.st_geomfromwkt(["POLYGON((1 1, 7 1, 7 7, 1 7, 1 1))"])
        chips = ctx.grid_tessellate(a, 2)
        back = ctx.st_union_agg(chips)
        assert ctx.st_area(back)[0] == pytest.approx(36.0, rel=1e-9)

    def test_grid_cell_intersection_union(self, ctx):
        a = ctx.st_geomfromwkt(["POLYGON((1 1, 9 1, 9 9, 1 9, 1 1))"])
        b = ctx.st_geomfromwkt(["POLYGON((2 2, 10 2, 10 10, 2 10, 2 2))"])
        res = 2
        ca = ctx.grid_tessellate(a, res)
        cb = ctx.grid_tessellate(b, res)
        import mosaic_tpu.types as T
        common, ia, ib = np.intersect1d(ca.cell_id, cb.cell_id,
                                        return_indices=True)
        take = lambda cs, idx: T.ChipSet(cs.geom_id[idx], cs.cell_id[idx],
                                         cs.is_core[idx],
                                         cs.geoms.take(idx))
        la, lb = take(ca, ia), take(cb, ib)
        inter = ctx.grid_cell_intersection(la, lb)
        union = ctx.grid_cell_union(la, lb)
        # per-cell: area(inter) + area(union) == area(a chip) + area(b chip)
        # (inclusion-exclusion per cell; core chips count the whole cell)
        cell_area = 4.0  # res 2 on 16×16 with splits 2 → 4×4 cells
        def areas(cs):
            out = np.asarray(ctx.st_area(cs.geoms))
            return np.where(cs.is_core, cell_area, out)
        lhs = areas(inter) + areas(union)
        rhs = areas(la) + areas(lb)
        np.testing.assert_allclose(lhs, rhs, rtol=1e-9)

    def test_cell_agg(self, ctx):
        a = ctx.st_geomfromwkt(["POLYGON((1 1, 9 1, 9 9, 1 9, 1 1))",
                                "POLYGON((2 2, 10 2, 10 10, 2 10, 2 2))"])
        chips = ctx.grid_tessellate(a, 2)
        uni = ctx.grid_cell_union_agg(chips)
        assert len(np.unique(chips.cell_id)) == len(uni.cell_id)
        inter = ctx.grid_cell_intersection_agg(chips)
        assert len(inter.cell_id) == len(uni.cell_id)

    def test_registry_has_overlay(self, ctx):
        names = ctx.function_names()
        for n in ("st_intersection", "st_union", "st_difference",
                  "st_unaryunion", "grid_cell_intersection",
                  "grid_cell_union"):
            assert n in names
        assert len(names) >= 70
