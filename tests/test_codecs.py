"""NetCDF-3 and Zarr codecs (io/netcdf.py, io/zarr.py).

Reference keeps small real NetCDF/Zarr fixtures in test resources
(binary/netcdf-coral, zarr-example); with zero egress the writers
produce the fixtures and readers are validated by round trip plus the
subdataset surface (RST_Subdatasets / RST_GetSubdataset semantics).
"""

import numpy as np
import pytest

from mosaic_tpu.functions.context import MosaicContext
from mosaic_tpu.io.netcdf import (netcdf_subdatasets, read_netcdf,
                                  write_netcdf)
from mosaic_tpu.io.zarr import read_zarr, write_zarr


@pytest.fixture
def nc_blob():
    h, w = 12, 17
    yy, xx = np.mgrid[0:h, 0:w]
    sst = (xx * 1.5 + yy).astype(np.float64)
    chl = (xx - yy).astype(np.float64)
    xs = -74.0 + 0.25 * np.arange(w)
    ys = 40.0 + 0.25 * np.arange(h)          # south-up: reader flips
    return write_netcdf({"sst": sst, "chl": chl}, xs=xs, ys=ys,
                        fill_value=-999.0), sst, chl, xs, ys


def test_netcdf_round_trip(nc_blob):
    blob, sst, chl, xs, ys = nc_blob
    subs = read_netcdf(blob)
    assert sorted(subs) == ["chl", "sst"]
    t = subs["sst"]
    # south-up input flipped to north-up
    np.testing.assert_allclose(np.asarray(t.data)[0], sst[::-1])
    assert t.gt.px_h < 0
    # world coords: x of col 0 center == xs[0]
    x0, y0 = t.gt.to_world(0.5, 0.5)
    assert x0 == pytest.approx(xs[0])
    assert y0 == pytest.approx(ys[-1])
    assert t.nodata == -999.0
    assert netcdf_subdatasets(blob) == ["chl", "sst"]


def test_netcdf_rejects_hdf5():
    with pytest.raises(ValueError):
        read_netcdf(b"\x89HDF\r\n\x1a\nrest")
    with pytest.raises(ValueError):
        read_netcdf(b"garbage")


def test_netcdf_through_function_surface(nc_blob, tmp_path):
    blob = nc_blob[0]
    p = tmp_path / "coral.nc"
    p.write_bytes(blob)
    mc = MosaicContext.build("H3")
    tiles = mc.rst_fromfile([str(p)])
    assert tiles[0].meta["driver"] == "netcdf"
    subs = mc.rst_subdatasets(tiles)
    assert subs[0] == {"chl": "chl", "sst": "sst"}
    sst = mc.rst_getsubdataset(tiles, "sst")[0]
    assert sst.meta["variable"] == "sst"
    with pytest.raises(ValueError):
        mc.rst_getsubdataset(tiles, "nope")


@pytest.mark.parametrize("compress", [False, True])
def test_zarr_round_trip(tmp_path, compress):
    rng = np.random.default_rng(5)
    a = rng.uniform(0, 10, (9, 14))
    b = rng.uniform(0, 1, (2, 9, 14))        # 3D: leading dim -> bands
    path = str(tmp_path / "store")
    write_zarr(path, {"elev": a, "rgbish": b}, chunks=None,
               geotransform=(-74.0, 0.1, 0.0, 41.0, 0.0, -0.1),
               compress=compress)
    subs = read_zarr(path)
    assert sorted(subs) == ["elev", "rgbish"]
    np.testing.assert_allclose(np.asarray(subs["elev"].data)[0], a)
    np.testing.assert_allclose(np.asarray(subs["rgbish"].data), b)
    assert subs["elev"].gt.px_w == pytest.approx(0.1)


def test_zarr_chunked(tmp_path):
    a = np.arange(130.0).reshape(10, 13)
    path = str(tmp_path / "chunked")
    write_zarr(path, {"v": a}, chunks=(4, 5))
    back = read_zarr(path)["v"]
    np.testing.assert_allclose(np.asarray(back.data)[0], a)


def test_zarr_through_function_surface(tmp_path):
    mc = MosaicContext.build("H3")
    a = np.ones((6, 6))
    path = str(tmp_path / "z")
    write_zarr(path, {"only": a})
    tiles = mc.rst_fromfile([path])
    assert tiles[0].meta["driver"] == "zarr"
    got = mc.rst_getsubdataset(tiles, "only")[0]
    np.testing.assert_allclose(np.asarray(got.data)[0], a)
