"""Arbitrary-EPSG coordinate transforms (round-5, VERDICT r4 task 4).

The table-driven engine (crs.py generic engine + epsg_params.npz,
built by tools/build_epsg_params.py from the PROJ EPSG registry)
covers 5,053 projected CRSs across LCC 1SP/2SP (+West Orientated),
Albers, Mercator A/B, TM (+South Orientated), Polar Stereographic
A/B, Oblique Stereographic, LAEA, Cassini-Soldner, and Hotine
Oblique Mercator A/B.  Reference counterpart: proj4j-backed
MosaicGeometry.transformCRSXY (MosaicGeometry.scala:136-160) and
OSR-backed RasterProject (RasterProject.scala:45).

Correctness evidence is layered and independent:
  - published landmark coordinates (Empire State Building in the NY
    Long Island state plane, Paris in Lambert-93, Amsterdam in RD);
  - the origin identity (natural/false origin must project exactly to
    the false easting/northing) across a sweep of codes;
  - round-trip closure < 1e-7 deg;
  - containment of projected geographic-extent centers inside the
    independently published projected extents (epsg_bounds.npz, from
    spatialreference.org — a different source than proj.db).
"""

import numpy as np
import pytest

from mosaic_tpu.core.geometry.crs import (epsg_from_name, _generic_forward,
                                          _generic_inverse, _proj_entry,
                                          _proj_table, transform_xy,
                                          _wgs84_to_datum)


class TestLandmarks:
    def test_empire_state_building_epsg2263(self):
        # NY Long Island state plane (LCC 2SP, NAD83, US survey feet).
        # Published SPCS coordinates ~ (988 220, 211 950) ftUS; the
        # NAD83<->WGS84 Helmert approximation contributes ~1-2 m.
        x, y = transform_xy(np.array([[-73.9857, 40.7484]]),
                            4326, 2263)[0]
        assert x == pytest.approx(988_220, abs=300)
        assert y == pytest.approx(211_950, abs=300)

    def test_one_latitude_degree_scale_epsg2263(self):
        a = transform_xy(np.array([[-74.0, 40.70], [-74.0, 40.71]]),
                         4326, 2263)
        dy = float(a[1, 1] - a[0, 1])
        # 0.01 deg of latitude ~ 1111.9 m ~ 3648 usft near 40.7N
        assert dy == pytest.approx(3648, rel=0.005)

    def test_paris_lambert93_epsg2154(self):
        x, y = transform_xy(np.array([[2.3522, 48.8566]]),
                            4326, 2154)[0]
        assert x == pytest.approx(652_470, abs=500)
        assert y == pytest.approx(6_862_000, abs=1500)

    def test_amsterdam_rd_epsg28992(self):
        # Oblique (double) stereographic on Bessel + datum shift
        x, y = transform_xy(np.array([[4.9041, 52.3676]]),
                            4326, 28992)[0]
        assert x == pytest.approx(122_090, abs=500)
        assert y == pytest.approx(486_750, abs=500)

    def test_conus_albers_epsg5070_origin(self):
        x, y = transform_xy(np.array([[-96.0, 23.0]]), 4326, 5070)[0]
        assert abs(x) < 2.0 and abs(y) < 2.0

    def test_polar_stereographic(self):
        # EPSG 3031 Antarctic PS (variant B): on the lon0 meridian the
        # easting is 0 and the northing points toward 0°E
        x, y = transform_xy(np.array([[0.0, -75.0]]), 4326, 3031)[0]
        assert abs(x) < 1e-6
        assert y == pytest.approx(1_638_783, abs=2000)
        for code, pt in ((3031, [45.0, -70.0]), (3413, [-30.0, 75.0])):
            rt = transform_xy(transform_xy(np.array([pt]), 4326, code),
                              code, 4326)
            assert np.abs(rt - pt).max() < 1e-9, code

    def test_tail_methods(self):
        # Cassini (Berlin Soldner), HOM-B (Malaysia RSO): round-trip +
        # plausibility of known city coordinates
        kl = transform_xy(np.array([[101.69, 3.14]]), 4326, 3375)[0]
        assert kl[0] == pytest.approx(410_400, abs=2000)
        assert kl[1] == pytest.approx(347_500, abs=2000)
        b = transform_xy(np.array([[13.4, 52.52]]), 4326, 3068)[0]
        assert b[0] == pytest.approx(24_700, abs=2000)
        assert b[1] == pytest.approx(21_500, abs=2000)
        for code, pt in ((3375, [101.7, 3.1]), (3068, [13.4, 52.5])):
            rt = transform_xy(transform_xy(np.array([pt]), 4326, code),
                              code, 4326)
            assert np.abs(rt - pt).max() < 5e-7, code

    def test_roundtrips(self):
        pts = np.array([[-74.05, 40.60], [-73.80, 40.90]])
        for code in (2263, 2154, 5070, 28992, 3035, 3395):
            loc = transform_xy(pts, 4326, code)
            back = transform_xy(loc, code, 4326)
            p = _proj_entry(code)
            # codes with a datum shift keep the second-order residue
            # of the linearized Helmert (~3 cm); pure-projection codes
            # must close to machine precision
            tol = 1e-9 if all(v == 0 for v in p["helmert"]) else 5e-7
            assert np.abs(back - pts).max() < tol, code


class TestTableSweep:
    @pytest.fixture(scope="class")
    def table(self):
        return _proj_table()

    def test_origin_identity_and_roundtrip_sample(self, table):
        rng = np.random.default_rng(5)
        codes = table["epsg"][::17]          # ~290 codes
        bad = []
        for c in codes:
            p = _proj_entry(int(c))
            lat0 = p["sp1"] if p["method"] == 9829 else p["lat0"]
            polar = p["method"] in (9810, 9829, 9812)
            if polar and abs(lat0) == 90:
                lat0 = 89.0 * np.sign(lat0)
            x, y = _generic_forward(np.array([p["lon0"]]),
                                    np.array([lat0]), p)
            if not polar:
                if abs(float(x[0]) - p["fe"] / p["axis_m"]) > 0.5 or \
                        abs(float(y[0]) - p["fn"] / p["axis_m"]) > 0.5:
                    bad.append(("origin", int(c)))
                    continue
            lons = p["lon0"] + rng.uniform(-2, 2, 6)
            lats = np.clip(lat0 + rng.uniform(-2, 2, 6), -89, 89)
            X, Y = _generic_forward(lons, lats, p)
            lo, la = _generic_inverse(X, Y, p)
            err = max(np.max(np.abs(lo - lons)), np.max(np.abs(la - lats)))
            if err > 1e-7:
                bad.append(("roundtrip", int(c), err))
        assert not bad, bad[:10]

    def test_projected_extent_containment(self, table):
        import os
        zb = np.load(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "mosaic_tpu", "core",
            "geometry", "epsg_bounds.npz"))
        b_epsg, b_geo, b_proj = zb["epsg"], zb["geo"], zb["proj"]
        checked = inside = 0
        for c in table["epsg"][::7]:
            j = np.searchsorted(b_epsg, c)
            if j >= len(b_epsg) or b_epsg[j] != c:
                continue
            p = _proj_entry(int(c))
            gx0, gy0, gx1, gy1 = b_geo[j]
            px0, py0, px1, py1 = b_proj[j]
            if not np.all(np.isfinite(b_geo[j])) or \
                    not np.all(np.isfinite(b_proj[j])):
                continue
            if gx1 < gx0:                    # antimeridian-crossing
                continue
            cx, cy = (gx0 + gx1) / 2, (gy0 + gy1) / 2
            lon, lat = _wgs84_to_datum(np.array([cx]),
                                       np.array([cy]), p)
            try:
                x, y = _generic_forward(lon, lat, p)
            except Exception:
                continue
            sx = (px1 - px0) * 0.25 + 1.0
            sy = (py1 - py0) * 0.25 + 1.0
            checked += 1
            if px0 - sx <= float(x[0]) <= px1 + sx and \
                    py0 - sy <= float(y[0]) <= py1 + sy:
                inside += 1
        assert checked > 200
        assert inside / checked > 0.97, (inside, checked)

    def test_unknown_code_raises(self):
        with pytest.raises(ValueError, match="unsupported"):
            transform_xy(np.zeros((1, 2)), 4326, 999999)


class TestNameResolution:
    def test_epsg_name(self):
        assert epsg_from_name("NAD83 / New York Long Island (ftUS)") \
            == 2263

    def test_esri_alias(self):
        assert epsg_from_name(
            "NAD_1983_StatePlane_New_York_Long_Island_FIPS_3104_Feet"
        ) == 2263

    def test_unknown(self):
        assert epsg_from_name("Atlantis Grid 1900") is None


class TestStatePlaneIngest:
    """The real-world blocker VERDICT r4 named: NYC taxi zones ship in
    EPSG:2263 and round-4 could not ingest them.  The committed
    fixture's geometry values are derived from the 4326 Quickstart
    fixture via the (independently validated, see above) forward
    transform — it pins the INGESTION path: .prj AUTHORITY detection,
    srid propagation, and st_transform back to 4326."""

    def test_shapefile_prj_detect_and_transform(self):
        import json
        import os
        import mosaic_tpu as mos
        base = os.path.join(os.path.dirname(__file__), "data")
        geoms, cols = mos.io.read_shapefile(
            os.path.join(base, "nyc_taxi_zones_2263.shp"))
        assert geoms.srid == 2263
        assert len(geoms) == 35
        # projected magnitudes are in the Long Island ftUS range
        c = np.asarray(geoms.coords)[:, :2]
        assert 900_000 < np.median(c[:, 0]) < 1_100_000
        ctx = mos.enable_mosaic("H3")
        back = ctx.st_transform(geoms, 4326)
        feats = [json.loads(l) for l in
                 open(os.path.join(base, "nyc_taxi_zones.geojson"))
                 if l.strip()]
        truth = mos.read_geojson([json.dumps(f["geometry"])
                                  for f in feats])
        # the shapefile round trip reorients rings (shapefile spec:
        # outer rings CW), so compare per-zone area + centroid, not
        # raw vertex order
        a_back = np.asarray(ctx.st_area(back))
        a_true = np.asarray(ctx.st_area(truth))
        assert np.abs(a_back - a_true).max() < 1e-11
        c_back = ctx.st_centroid(back)
        c_true = ctx.st_centroid(truth)
        # st_centroid runs on the f32 device path: ~1e-7 relative on
        # degree-scale coords => ~1e-5 absolute is its own precision
        assert np.abs(np.asarray(c_back.coords)[:, :2] -
                      np.asarray(c_true.coords)[:, :2]).max() < 2e-5

    def test_geographic_authority_prj_degrades_to_4326(self):
        # a GDAL-written NAD83 .prj must not produce an unroutable
        # srid (4269 is geographic, not in the projected table)
        from mosaic_tpu.io.shapefile import _prj_to_epsg
        assert _prj_to_epsg(
            'GEOGCS["GCS_North_American_1983",'
            'AUTHORITY["EPSG","4269"]]') == 4326

    def test_nested_unit_authority_not_trusted(self):
        # 9001 (= metre) is a unit code, not a CRS: must not become
        # the srid just because it is the last AUTHORITY in the WKT
        from mosaic_tpu.io.shapefile import _prj_to_epsg
        assert _prj_to_epsg(
            'PROJCS["Custom_Lambert",UNIT["Meter",1.0,'
            'AUTHORITY["EPSG","9001"]]]') == 4326

    def test_esri_prj_spelling_detected(self, tmp_path):
        import shutil
        import os
        import mosaic_tpu as mos
        base = os.path.join(os.path.dirname(__file__), "data")
        for ext in (".shp", ".shx", ".dbf"):
            shutil.copy(os.path.join(base, "nyc_taxi_zones_2263" + ext),
                        tmp_path / ("z" + ext))
        (tmp_path / "z.prj").write_text(
            'PROJCS["NAD_1983_StatePlane_New_York_Long_Island_'
            'FIPS_3104_Feet",GEOGCS["GCS_North_American_1983"]]')
        geoms, _ = mos.io.read_shapefile(str(tmp_path / "z.shp"))
        assert geoms.srid == 2263
