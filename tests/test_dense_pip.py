"""Dense lattice-window PIP index (parallel/pip_join.py, round 3).

The dense path replaces the sorted-table binary searches (29 serial
gathers/point measured at 56% of the TPU join) with one entry-table
gather + one merged-chip-pool gather.  These tests pin its exactness
contract against the float64 host oracle and its equivalence with the
grid-agnostic sorted path.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mosaic_tpu.bench.workloads import build_workload, nyc_points
from mosaic_tpu.core.index.factory import get_index_system
from mosaic_tpu.core.geometry.wkt import read_wkt
from mosaic_tpu.parallel.pip_join import (DensePIPIndex, PIPIndex,
                                          build_pip_index, host_recheck,
                                          host_recheck_fn, localize,
                                          make_pip_join_fn, pip_host_truth)


@pytest.fixture(scope="module")
def workload():
    polys, grid, res = build_workload(n_side=5, grid_name="H3",
                                      zones="taxi")
    return polys, grid, res


@pytest.fixture(scope="module")
def dense_idx(workload):
    polys, grid, res = workload
    idx = build_pip_index(polys, res, grid)
    assert isinstance(idx, DensePIPIndex)
    return idx


def test_dense_selected_for_city_h3(dense_idx):
    assert dense_idx.W > 10 and dense_idx.H > 10
    assert dense_idx.pool.shape[-1] == 5


def test_dense_join_matches_host_oracle(workload, dense_idx, rng):
    polys, grid, res = workload
    fn = jax.jit(make_pip_join_fn(dense_idx, grid))
    pts64 = nyc_points(20_000, seed=3)
    zone, unc = fn(jnp.asarray(localize(dense_idx, pts64)))
    zone = np.asarray(zone)
    unc = np.asarray(unc)
    truth = pip_host_truth(pts64, polys)
    # contract: every device/f64 disagreement is flagged
    assert not np.any((zone != truth) & ~unc)
    # and the recheck resolves all flags exactly
    final = host_recheck_fn(dense_idx)(pts64, zone, unc)
    assert np.array_equal(final, truth)
    # the flag set stays a sliver
    assert unc.mean() < 5e-3


def test_dense_equals_sorted_path(workload, dense_idx):
    polys, grid, res = workload
    sorted_idx = build_pip_index(polys, res, grid, dense="never")
    assert isinstance(sorted_idx, PIPIndex)
    pts64 = nyc_points(10_000, seed=4)
    fd = jax.jit(make_pip_join_fn(dense_idx, grid))
    fs = jax.jit(make_pip_join_fn(sorted_idx, grid))
    zd, ud = fd(jnp.asarray(localize(dense_idx, pts64)))
    zs, us = fs(jnp.asarray(localize(sorted_idx, pts64)))
    zd = host_recheck_fn(dense_idx)(pts64, np.asarray(zd), np.asarray(ud))
    zs = host_recheck(pts64, np.asarray(zs), np.asarray(us), polys)
    assert np.array_equal(zd, zs)


def test_vectorized_recheck_equals_polygon_loop(workload, dense_idx):
    """host_recheck_fn (chip CSR, vectorized) == the per-polygon loop."""
    polys, grid, res = workload
    fn = jax.jit(make_pip_join_fn(dense_idx, grid))
    pts64 = nyc_points(30_000, seed=5)
    zone, unc = fn(jnp.asarray(localize(dense_idx, pts64)))
    zone = np.asarray(zone)
    # recheck EVERYTHING through both paths (not just the flagged set)
    all_on = np.ones(len(pts64), bool)
    via_chips = host_recheck_fn(dense_idx)(pts64, zone.copy(), all_on)
    via_polys = host_recheck(pts64, zone.copy(), all_on, polys)
    assert np.array_equal(via_chips, via_polys)


def test_fallback_out_of_window():
    """Points far outside the window resolve to -1, certainly."""
    polys, grid, res = build_workload(n_side=4, grid_name="H3",
                                      zones="quad")
    idx = build_pip_index(polys, res, grid)
    if not isinstance(idx, DensePIPIndex):
        pytest.skip("dense path not selected")
    fn = jax.jit(make_pip_join_fn(idx, grid))
    far = np.array([[-73.0, 41.5], [-75.3, 40.0], [-74.0, 41.4]])
    zone, unc = fn(jnp.asarray(localize(idx, far)))
    assert np.all(np.asarray(zone) == -1)


def test_multiface_falls_back_to_sorted():
    """A polygon spanning icosahedron faces can't use the dense window."""
    wkt = ["POLYGON((-30 20, 20 20, 20 60, -30 60, -30 20))"]
    polys = read_wkt(wkt)
    grid = get_index_system("H3")
    idx = build_pip_index(polys, 2, grid)
    assert isinstance(idx, PIPIndex)
