"""Documentation tests.

Reference counterpart: docs/source/api/*.rst + usage pages.  Pins the
generated API reference to the live registry (lock-step, like the R
bindings) and sanity-checks the usage pages' code references.
"""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
API = os.path.join(REPO, "docs", "api")
USAGE = os.path.join(REPO, "docs", "usage")


def test_api_reference_in_lockstep(tmp_path):
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "generate_docs.py"),
                        str(tmp_path)], capture_output=True, text=True,
                       cwd=REPO)
    assert r.returncode == 0, r.stderr
    for name in os.listdir(tmp_path):
        fresh = open(os.path.join(tmp_path, name)).read()
        committed = open(os.path.join(API, name)).read()
        assert fresh == committed, \
            f"docs/api/{name} stale — rerun tools/generate_docs.py"


def test_every_registered_function_documented():
    import mosaic_tpu.functions.context  # noqa: F401 (fills registry)
    from mosaic_tpu.functions.registry import REGISTRY
    docs = ""
    for name in os.listdir(API):
        docs += open(os.path.join(API, name)).read()
    documented = set(re.findall(r"^## `([a-z_0-9]+)", docs, re.MULTILINE))
    missing = set(REGISTRY) - documented
    assert not missing, f"undocumented: {sorted(missing)}"


def test_usage_pages_reference_real_symbols():
    """Backticked mosaic_tpu symbols in usage pages must exist (guards
    against docs drifting from the API)."""
    import mosaic_tpu as mos
    from mosaic_tpu.functions.context import MosaicContext
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        MosaicContext.build("CUSTOM(0,16,0,16,2,1,1)")
    pages = [os.path.join(USAGE, f) for f in os.listdir(USAGE)]
    pages.append(os.path.join(REPO, "docs", "index.md"))
    for page in pages:
        src = open(page).read()
        for call in re.findall(r"mc\.([a-z_0-9]+)\(", src):
            assert hasattr(MosaicContext, call), \
                f"{os.path.basename(page)} references mc.{call} " \
                f"which does not exist"
        for call in re.findall(r"mos\.([a-z_0-9]+)\(", src):
            assert hasattr(mos, call), \
                f"{os.path.basename(page)} references mos.{call} " \
                f"which does not exist"


def test_usage_pages_exist_per_index():
    index = open(os.path.join(REPO, "docs", "index.md")).read()
    for rel in re.findall(r"\]\((usage/[a-z-]+\.md|api/index\.md)\)",
                          index):
        assert os.path.exists(os.path.join(REPO, "docs", rel)), rel
