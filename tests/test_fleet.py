"""The fleet telemetry plane (``obs/spool.py`` + ``obs/fleet.py``).

The exactness contract is the headline: merging N per-worker spools
bucket-wise must reproduce — bit-for-bit — the counters, sums, and
p50/p95/p99 one registry fed every sample would report.  Around it,
the degrade paths the ISSUE names: a torn spool (partial JSON), an
alien ``SPOOL_VERSION``, and a stale worker each mark the view and
record an event instead of raising; fleet SLO burn rates evaluate
over re-hydrated per-worker series (rate = SUM of per-worker rates);
``stitched_traces`` reunites spans spooled by different pids under
one W3C trace id; and the operator surfaces (fleetctl, OpenMetrics
exposition, dashboard panel) render the merged view.
"""

import json
import os
import random
import time

import pytest

from mosaic_tpu import config as _config
from mosaic_tpu.obs import metrics
from mosaic_tpu.obs.fleet import (FleetAggregator, FleetStore,
                                  aggregator_for)
from mosaic_tpu.obs.recorder import recorder
from mosaic_tpu.obs.slo import SLObjective, evaluate_fleet, monitor
from mosaic_tpu.obs.spool import (SPOOL_VERSION, SpoolError, read_spool,
                                  spool_path, spool_snapshot,
                                  write_spool)
from mosaic_tpu.obs.timeseries import timeseries


@pytest.fixture
def fleet_env():
    """Clean obs singletons + config around each fleet test."""
    prev = _config.default_config()
    metrics.reset()
    metrics.enable()
    recorder.reset()
    recorder.enable()
    timeseries.reset()
    monitor.reset()
    yield
    _config.set_default_config(prev)
    metrics.disable()
    metrics.reset()
    recorder.reset()
    timeseries.reset()
    monitor.reset()


def _write_worker(directory, pid, feed):
    """Spool one fabricated worker: reset the registry, run ``feed``
    against it, snapshot through the real spool machinery, and write
    the file under the fabricated pid."""
    metrics.reset()
    feed(metrics)
    snap = spool_snapshot()
    snap["pid"] = pid
    path = spool_path(str(directory), pid)
    os.makedirs(str(directory), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(snap, fh)
    return path


# ------------------------------------------------- the exactness property

@pytest.mark.parametrize("seed", [11, 23, 47])
def test_merge_equals_single_registry(tmp_path, fleet_env, seed):
    """Property: aggregating N worker spools is indistinguishable from
    one registry that saw every sample — counters and histogram count/
    sum/min/max/p50/p95/p99 all bit-equal."""
    rng = random.Random(seed)
    n_workers = rng.randint(2, 5)
    hists = [("q/wall_ms", 1e-6), ("q/bytes", 1.0)]
    counters = ["sql/queries", "sql/errors", "serve/admitted"]
    all_samples = {n: [] for n, _ in hists}
    all_counts = {n: 0.0 for n in counters}
    for i in range(n_workers):
        samples = {n: [rng.lognormvariate(3.0, 2.0)
                       for _ in range(rng.randint(5, 200))]
                   for n, _ in hists}
        counts = {n: float(rng.randint(0, 50)) for n in counters}

        def feed(reg, samples=samples, counts=counts):
            for (name, scale) in hists:
                for v in samples[name]:
                    reg.observe(name, v, scale=scale)
            for name, v in counts.items():
                if v:
                    reg.count(name, v)

        _write_worker(tmp_path, 50_000 + i, feed)
        for n, _ in hists:
            all_samples[n].extend(samples[n])
        for n in counters:
            all_counts[n] += counts[n]

    view = FleetAggregator(str(tmp_path)).scan()
    assert view.merge_errors == 0
    assert len(view.workers) == n_workers

    # the oracle: one registry fed every sample
    metrics.reset()
    for name, scale in hists:
        for v in all_samples[name]:
            metrics.observe(name, v, scale=scale)
    for name, v in all_counts.items():
        if v:
            metrics.count(name, v)
    oracle = metrics.full_snapshot()

    for name, v in oracle["counters"].items():
        assert view.counters[name] == v          # bit-equal, not approx
    for name, _ in hists:
        want = metrics.histogram(name).snapshot()
        got = view.histograms[name].snapshot()
        assert got["count"] == want["count"]
        assert got["sum"] == pytest.approx(want["sum"], rel=1e-12)
        assert got["min"] == want["min"]
        assert got["max"] == want["max"]
        for q in ("p50", "p95", "p99"):
            assert got[q] == want[q], (name, q)
        assert view.histograms[name].counts == \
            metrics.histogram(name).counts


# ------------------------------------------------------- spool mechanics

def test_spool_roundtrip_and_unconfigured_noop(tmp_path, fleet_env):
    assert write_spool() is None          # no dir configured: no-op
    metrics.count("a/b", 3.0)
    metrics.observe("a/ms", 1.5)
    path = write_spool(str(tmp_path))
    assert path == spool_path(str(tmp_path))
    snap = read_spool(path)
    assert snap["version"] == SPOOL_VERSION
    assert snap["pid"] == os.getpid()
    assert snap["metrics"]["counters"]["a/b"] == 3.0
    assert snap["metrics"]["histograms"]["a/ms"]["count"] == 1
    # the write itself is accounted
    assert metrics.counter_value("fleet/spool_writes") == 1.0


def test_spool_rides_sampler_tick(tmp_path, fleet_env):
    from mosaic_tpu.obs.timeseries import Sampler
    cfg = _config.apply_conf(_config.default_config(),
                             "mosaic.obs.fleet.dir", str(tmp_path))
    _config.set_default_config(cfg)
    metrics.count("tick/works")
    Sampler(1000.0, timeseries).tick(now=time.time())
    snap = read_spool(spool_path(str(tmp_path)))
    assert snap["metrics"]["counters"]["tick/works"] == 1.0


def test_torn_spool_degrades_not_raises(tmp_path, fleet_env):
    _write_worker(tmp_path, 50_001,
                  lambda reg: reg.count("ok/seen", 7.0))
    torn = spool_path(str(tmp_path), 50_002)
    with open(torn, "w", encoding="utf-8") as fh:
        fh.write('{"version": 1, "pid": 50002, "metri')   # mid-write
    with pytest.raises(SpoolError):
        read_spool(torn)
    agg = FleetAggregator(str(tmp_path))
    view = agg.scan()
    assert view.merge_errors == 1
    assert view.counters["ok/seen"] == 7.0    # good worker still merged
    bad = [w for w in view.workers if w.pid == 50_002][0]
    assert not bad.readable and "torn" in bad.error
    evs = recorder.events("fleet_merge_error")
    assert evs and evs[-1]["pid"] == 50_002


def test_version_mismatch_degrades(tmp_path, fleet_env):
    path = _write_worker(tmp_path, 50_003,
                         lambda reg: reg.count("x/y", 1.0))
    snap = json.load(open(path))
    snap["version"] = 99
    json.dump(snap, open(path, "w"))
    view = FleetAggregator(str(tmp_path)).scan()
    assert view.merge_errors == 1
    assert "version" in view.workers[0].error
    assert view.counters == {}


def test_stale_worker_flagged_once_counters_kept(tmp_path, fleet_env):
    fresh = _write_worker(
        tmp_path, 50_010, lambda reg: (reg.count("work/done", 2.0),
                                       reg.gauge("q/depth", 3.0)))
    stale = _write_worker(
        tmp_path, 50_011, lambda reg: (reg.count("work/done", 5.0),
                                       reg.gauge("q/depth", 9.0)))
    old = time.time() - 3600.0
    os.utime(stale, (old, old))
    os.utime(fresh, None)
    agg = FleetAggregator(str(tmp_path), stale_ms=5_000.0)
    view = agg.scan()
    by_pid = {w.pid: w for w in view.workers}
    assert by_pid[50_011].stale and not by_pid[50_010].stale
    # counters sum over stale too (completed work doesn't un-happen)...
    assert view.counters["work/done"] == 7.0
    # ...but gauges come from FRESH workers only
    assert view.gauges["q/depth"] == {"value": 3.0, "worker": 50_010}
    # one event per stale TRANSITION, not per scan
    agg.scan()
    agg.scan()
    evs = recorder.events("fleet_worker_stale")
    assert len(evs) == 1 and evs[0]["pid"] == 50_011


def test_histogram_scale_mismatch_skipped(tmp_path, fleet_env):
    _write_worker(tmp_path, 50_020,
                  lambda reg: reg.observe("h/ms", 5.0, scale=1e-6))
    _write_worker(tmp_path, 50_021,
                  lambda reg: reg.observe("h/ms", 5.0, scale=1.0))
    view = FleetAggregator(str(tmp_path)).scan()
    assert view.merge_errors == 1
    # first worker's histogram survives un-poisoned
    assert view.histograms["h/ms"].count == 1
    assert "scale" in recorder.events("fleet_merge_error")[-1]["why"]


# --------------------------------------------------- series + fleet SLO

def test_fleet_rates_sum_and_slo_evaluates(tmp_path, fleet_env):
    """Counter rate over the fleet = sum of per-worker rates, and a
    counter_rate objective breaches on the SUM even when every single
    worker is individually under its ceiling."""
    now = time.time()
    per_worker_rate = 1.5            # events/s each, over 60 s
    for i, pid in enumerate((50_030, 50_031, 50_032)):
        timeseries.reset()
        for k in range(7):
            t = now - 60.0 + k * 10.0
            timeseries.record("jax/recompiles",
                              per_worker_rate * (60.0 - (now - t)), t)

        def feed(reg):
            reg.count("jax/recompiles", per_worker_rate * 60.0)

        _write_worker(tmp_path, pid, feed)
    timeseries.reset()
    agg = FleetAggregator(str(tmp_path))
    view = agg.scan()
    store = agg.fleet_store(view)
    assert isinstance(store, FleetStore)
    got = store.rate("jax/recompiles", 60.0, now)
    assert got == pytest.approx(3 * per_worker_rate, rel=0.05)
    obj = SLObjective(name="recompile_fleet", kind="counter_rate",
                      series="jax/recompiles", max_rate=2.0,
                      windows=(60.0, 60.0))
    rows = evaluate_fleet(store, objectives=[obj], now=now)
    assert rows[0]["breached"]       # 4.5/s fleet-wide > 2.0 ceiling
    solo = SLObjective(name="recompile_solo", kind="counter_rate",
                       series="jax/recompiles", max_rate=2.0,
                       windows=(60.0, 60.0))
    one = FleetStore({50_030: {
        "jax/recompiles": store._workers[50_030]["jax/recompiles"]}})
    assert not evaluate_fleet(one, objectives=[solo],
                              now=now)[0]["breached"]


# --------------------------------------------------- stitched traces

def test_stitched_traces_across_pids(tmp_path, fleet_env):
    w3c = "0af7651916cd43dd8448eb211c80319c"

    def feed_client(reg):
        recorder.reset()
        recorder.record("trace_link", trace="t50040-00001",
                        w3c_trace=w3c, w3c_parent="b7ad6b7169203331",
                        name="client:load")
        recorder.record("span", trace="t50040-00001",
                        name="client/request", span="s1", parent=None,
                        dur_s=0.2)

    def feed_server(reg):
        recorder.reset()
        recorder.record("trace_link", trace="t50041-00007",
                        w3c_trace=w3c, w3c_parent="b7ad6b7169203331",
                        name="sql:SELECT 1")
        recorder.record("span", trace="t50041-00007",
                        name="sql/query", span="s2", parent=None,
                        dur_s=0.1)
        recorder.record("span", trace="t99999-00001",
                        name="unlinked/other", span="s3", parent=None,
                        dur_s=0.1)

    _write_worker(tmp_path, 50_040, feed_client)
    _write_worker(tmp_path, 50_041, feed_server)
    agg = FleetAggregator(str(tmp_path))
    traces = agg.stitched_traces()
    assert set(traces) == {w3c}
    tree = traces[w3c]
    assert sorted(tree["workers"]) == [50_040, 50_041]
    names = {s["name"] for s in tree["spans"]}
    assert names == {"client/request", "sql/query"}   # unlinked: out
    assert {s["worker"] for s in tree["spans"]} == {50_040, 50_041}
    bundle = agg.bundle()
    assert bundle["reason"] == "fleet"
    assert w3c in bundle["traces"]
    assert set(bundle["events_by_worker"]) == {50_040, 50_041}


# --------------------------------------------- operator surfaces

def test_fleetctl_openmetrics_and_dashboard(tmp_path, fleet_env,
                                            capsys):
    _write_worker(tmp_path, 50_050,
                  lambda reg: (reg.count("serve/admitted", 4.0),
                               reg.observe("q/ms", 2.5)))
    metrics.reset()

    from mosaic_tpu.obs.openmetrics import fleet_to_openmetrics
    view = FleetAggregator(str(tmp_path)).scan()
    text = fleet_to_openmetrics(view)
    assert 'worker="50050"' in text
    assert "mosaic_fleet_workers 1" in text
    assert text.endswith("# EOF\n")

    import tools.fleetctl as fleetctl
    assert fleetctl.main(["--dir", str(tmp_path), "list"]) == 0
    out = capsys.readouterr().out
    assert "50050" in out and "fresh" in out
    assert fleetctl.main(["--dir", str(tmp_path), "alerts"]) == 0
    assert fleetctl.main(
        ["--dir", str(tmp_path), "bundle",
         "--out", str(tmp_path / "b.json")]) == 0
    assert json.load(open(tmp_path / "b.json"))["reason"] == "fleet"
    empty = tmp_path / "nothing"
    empty.mkdir()
    assert fleetctl.main(["--dir", str(empty), "list"]) == 1

    from mosaic_tpu.obs.dashboard import _fleet_payload
    assert _fleet_payload({}) == {"enabled": False}
    payload = _fleet_payload({"dir": [str(tmp_path)]})
    assert payload["enabled"]
    assert payload["fleet"]["counters"]["serve/admitted"] == 4.0


def test_aggregator_for_is_cached(tmp_path, fleet_env):
    a = aggregator_for(str(tmp_path))
    assert aggregator_for(str(tmp_path)) is a
