"""MosaicContext function-surface tests (reference: python/test/
test_vector_functions.py shape: call every function once on small data)."""

import numpy as np
import pytest

import mosaic_tpu as mos
from mosaic_tpu.functions.context import MosaicContext


@pytest.fixture(scope="module")
def ctx():
    return MosaicContext.build("CUSTOM(0,16,0,16,2,1,1)")


def test_enable_and_context(ctx):
    assert MosaicContext.context() is ctx
    c2 = mos.enable_mosaic("CUSTOM(0,16,0,16,2,1,1)")
    assert MosaicContext.context() is c2


def test_constructors(ctx):
    g = ctx.st_point([1.0, 2.0], [3.0, 4.0])
    assert len(g) == 2
    assert ctx.st_aswkt(g)[0] == "POINT (1 3)"
    g2 = ctx.st_geomfromwkt(["POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))"])
    assert ctx.st_geometrytype(g2) == ["POLYGON"]
    blobs = ctx.st_aswkb(g2)
    g3 = ctx.st_geomfromwkb(blobs)
    assert np.allclose(g2.coords, g3.coords)
    js = ctx.st_asgeojson(g2)
    g4 = ctx.st_geomfromgeojson(js)
    assert np.allclose(g2.coords, g4.coords)


def test_measures(ctx):
    g = ctx.st_geomfromwkt(["POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))"])
    assert ctx.st_area(g)[0] == pytest.approx(16.0)
    assert ctx.st_perimeter(g)[0] == pytest.approx(16.0)
    assert ctx.st_xmin(g)[0] == 0 and ctx.st_xmax(g)[0] == 4
    assert ctx.st_numpoints(g)[0] == 5
    assert ctx.st_dimension(g)[0] == 2
    c = ctx.st_centroid(g)
    assert ctx.st_x(c)[0] == pytest.approx(2.0)
    env = ctx.st_envelope(ctx.st_geomfromwkt(["LINESTRING (1 2, 5 7)"]))
    assert ctx.st_area(env)[0] == pytest.approx(20.0)


def test_predicates_and_distance(ctx):
    polys = ctx.st_geomfromwkt(["POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))"])
    pts = ctx.st_point([2.0], [2.0])
    assert ctx.st_contains(polys, pts)[0]
    assert ctx.st_within(pts, polys)[0]
    d = ctx.st_distance(ctx.st_point([6.0], [2.0]), polys)
    assert d[0] == pytest.approx(2.0)
    assert ctx.st_distance(pts, polys)[0] == 0.0
    a = ctx.st_geomfromwkt(["POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))"])
    b = ctx.st_geomfromwkt(["POLYGON ((2 2, 6 2, 6 6, 2 6, 2 2))"])
    assert ctx.st_intersects(a, b)[0]


def test_affine(ctx):
    g = ctx.st_point([1.0], [2.0])
    t = ctx.st_translate(g, 10, 20)
    assert ctx.st_x(t)[0] == 11 and ctx.st_y(t)[0] == 22
    s = ctx.st_scale(g, 2, 3)
    assert ctx.st_x(s)[0] == 2 and ctx.st_y(s)[0] == 6
    r = ctx.st_rotate(g, np.pi / 2)
    assert ctx.st_x(r)[0] == pytest.approx(-2.0)
    assert ctx.st_y(r)[0] == pytest.approx(1.0)


def test_dump(ctx):
    g = ctx.st_geomfromwkt(
        ["MULTIPOLYGON (((0 0, 1 0, 1 1, 0 1, 0 0)), "
         "((5 5, 6 5, 6 6, 5 6, 5 5)))"])
    d = ctx.st_dump(g)
    assert len(d) == 2
    assert ctx.st_geometrytype(d) == ["POLYGON", "POLYGON"]


def test_grid_functions(ctx):
    cells = ctx.grid_longlatascellid([1.5, 2.5], [3.5, 4.5], 0)
    assert len(cells) == 2
    pts = ctx.st_point([1.5], [3.5])
    assert ctx.grid_pointascellid(pts, 0)[0] == cells[0]
    b = ctx.grid_boundary(cells)
    assert ctx.st_area(b)[0] == pytest.approx(1.0)
    wkbs = ctx.grid_boundaryaswkb(cells)
    assert len(wkbs) == 2
    assert ctx.grid_cellarea(cells)[0] == pytest.approx(1.0)
    src, ring = ctx.grid_cellkringexplode(cells, 1)
    assert set(src.tolist()) == {0, 1}
    g = ctx.st_geomfromwkt(["POLYGON ((1.2 1.2, 3.2 1.2, 3.2 3.2, 1.2 3.2,"
                            " 1.2 1.2))"])
    pf = ctx.grid_polyfill(g, 0)
    assert len(pf[0]) == 4
    chips = ctx.grid_tessellate(g, 0)
    assert len(chips) > 4
    kr = ctx.grid_geometrykring(g, 0, 1)
    assert len(kr[0]) > len(ctx.grid_polyfill_union(g, 0)[0])
    kl = ctx.grid_geometrykloop(g, 0, 1)
    assert len(np.intersect1d(kl[0], ctx.grid_polyfill_union(g, 0)[0])) == 0
    s = ctx.grid_cellid_to_string(cells)
    assert np.array_equal(ctx.grid_cellid_from_string(s), cells)


def test_multipoint_multicell_chips(ctx):
    g = ctx.st_geomfromwkt(["MULTIPOINT ((3.1 3.1), (3.2 3.2), (9.5 9.5))"])
    chips = ctx.grid_tessellate(g, 0)
    assert len(chips) == 2
    nv = chips.geoms.vertex_counts()
    assert sorted(nv.tolist()) == [1, 2]  # two co-celled points kept


def test_hole_inside_single_cell_not_core(ctx):
    """Regression: a hole strictly inside one cell must make that cell a
    border chip (with the hole), not core."""
    g = ctx.st_geomfromwkt([
        "POLYGON ((0.5 0.5, 7.5 0.5, 7.5 7.5, 0.5 7.5, 0.5 0.5),"
        " (4.3 4.3, 4.7 4.3, 4.7 4.7, 4.3 4.7, 4.3 4.3))"])
    chips = ctx.grid_tessellate(g, 0)
    cell = ctx.index_system.point_to_cell(np.array([[4.5, 4.5]]), 0)[0]
    k = np.nonzero(chips.cell_id == cell)[0]
    assert len(k) == 1 and not chips.is_core[k[0]]
    # the chip must exclude the hole: point inside the hole not contained
    from mosaic_tpu.core.tessellate import _pip, _poly_edges
    chip_edges = _poly_edges(chips.geoms, int(k[0]))
    assert not _pip(np.array([[4.5, 4.5]]), chip_edges)[0]
    assert _pip(np.array([[4.1, 4.1]]), chip_edges)[0]


def test_multipolygon_part_inside_cell(ctx):
    """Regression: a whole multipolygon part swallowed by one cell whose
    center is outside the part must still produce a chip."""
    g = ctx.st_geomfromwkt([
        "MULTIPOLYGON (((0.5 0.5, 1.5 0.5, 1.5 1.5, 0.5 1.5, 0.5 0.5)),"
        " ((2.05 2.05, 2.2 2.05, 2.2 2.2, 2.05 2.2, 2.05 2.05)))"])
    chips = ctx.grid_tessellate(g, 0)
    cell = ctx.index_system.point_to_cell(np.array([[2.1, 2.1]]), 0)[0]
    k = np.nonzero(chips.cell_id == cell)[0]
    assert len(k) == 1
    from mosaic_tpu.core.tessellate import _pip, _poly_edges
    chip_edges = _poly_edges(chips.geoms, int(k[0]))
    assert _pip(np.array([[2.1, 2.1]]), chip_edges)[0]
    assert not _pip(np.array([[2.5, 2.5]]), chip_edges)[0]


def test_empty_point_wkt_roundtrip(ctx):
    g = ctx.st_geomfromwkt(["POINT EMPTY"])
    blobs = ctx.st_aswkb(g)
    g2 = ctx.st_geomfromwkb(blobs)
    assert ctx.st_aswkt(g2) == ["POINT EMPTY"]


def test_union_agg_no_core_chips(ctx):
    """Aggregating a border-only ChipSet must not call grid_boundary
    with an empty id batch (round-4 review: IndexError on H3)."""
    import mosaic_tpu as mos
    g = mos.read_wkt(
        ["POLYGON ((-74.001 40.701, -73.9995 40.701, -73.9995 40.7025,"
         " -74.001 40.7025, -74.001 40.701))"])
    chips = ctx.grid_tessellate(g, 9, keep_core_geom=True)
    border_only = chips
    if chips.is_core.any():
        import numpy as np
        keep = np.nonzero(~chips.is_core)[0]
        from mosaic_tpu.types import ChipSet
        border_only = ChipSet(chips.geom_id[keep], chips.cell_id[keep],
                              chips.is_core[keep],
                              chips.geoms.take(keep))
    u = ctx.st_union_agg(border_only)
    assert len(u) >= 1
    ia = ctx.st_intersection_agg(border_only, border_only)
    assert len(ia) >= 1
