"""Whole-query fusion tests (perf/fusion.py).

Fusion is a pure strategy transform: adjacent eligible operators
compile into ONE jitted XLA program, and the answer must be bit for
bit what the unfused path produces — these tests assert group
formation, every eligibility break, the parity contract (including
NaN and composite-expression cases), one-compile-per-(group, bucket),
the planner's learned flip, the forced/disabled pins, ledger
attribution and the EXPLAIN surface.
"""

import numpy as np
import pytest

from mosaic_tpu import config as _config
from mosaic_tpu.functions.context import MosaicContext
from mosaic_tpu.obs import metrics, recorder
from mosaic_tpu.obs.profiler import ledger
from mosaic_tpu.perf.jit_cache import kernel_cache
from mosaic_tpu.sql import SQLSession
from mosaic_tpu.sql.planner import planner


@pytest.fixture(scope="module")
def mc():
    return MosaicContext.build("CUSTOM(-180,180,-90,90,2,360,180)")


@pytest.fixture(scope="module")
def session(mc):
    s = SQLSession(mc)
    rng = np.random.default_rng(42)
    n = 4000
    px = rng.normal(size=n)
    px[::53] = np.nan                     # NaN rows ride along
    s.create_table("fx", {
        "px": px,
        "py": rng.normal(size=n),
        "k": rng.integers(0, 100, size=n),
        "b32": rng.integers(0, 9, size=n).astype(np.int32),
        "flag": rng.integers(0, 2, size=n).astype(bool),
        "tag": np.array(["a", "b"] * (n // 2))})
    return s


@pytest.fixture()
def pin():
    """Force-pin the fusion decision for one test; restore auto."""
    prev = _config.default_config()

    def _pin(mode):
        _config.set_default_config(_config.apply_conf(
            _config.default_config(),
            "mosaic.planner.force.fusion", mode))

    yield _pin
    _config.set_default_config(prev)


def _ab(session, pin, q):
    """Run ``q`` fused and unfused; return both result tables."""
    pin("on")
    fused = session.sql(q)
    pin("off")
    unfused = session.sql(q)
    pin("auto")
    return fused, unfused


def _assert_identical(a, b):
    assert list(a.columns) == list(b.columns)
    for col in a.columns:
        x, y = np.asarray(a.columns[col]), np.asarray(b.columns[col])
        assert x.dtype == y.dtype, (col, x.dtype, y.dtype)
        nan_ok = np.issubdtype(x.dtype, np.floating)
        assert np.array_equal(x, y, equal_nan=nan_ok), col


def _fused_ops(session, q):
    plan = session.sql("EXPLAIN " + q)
    return {o: f for o, f in zip(plan.columns["operator"],
                                 plan.columns["fused"])}


# ------------------------------------------------- group formation

def test_group_covers_filter_and_aggregate(session, pin):
    pin("on")
    fused = _fused_ops(session, "SELECT count(*) AS n, max(px) AS mx "
                                "FROM fx WHERE py > 0.0 AND k < 50")
    assert fused["filter"] == fused["aggregate"] == "g1"
    assert fused["scan"] == "-"


def test_group_covers_filter_and_project(session, pin):
    pin("on")
    fused = _fused_ops(session, "SELECT px + py AS s FROM fx "
                                "WHERE k < 50")
    assert fused["filter"] == fused["project"] == "g1"


def test_lone_aggregate_still_fuses(session, pin):
    # a single aggregate beats a compile: its unfused fallback is a
    # per-row python loop, so MIN_GROUP_OPS exempts it
    pin("on")
    fused = _fused_ops(session, "SELECT sum(k) AS s, count(*) AS n "
                                "FROM fx")
    assert fused["aggregate"] == "g1"


def test_lone_filter_does_not_fuse(session, pin):
    # [filter] alone is below MIN_GROUP_OPS when the terminal is
    # ineligible (Star expansion breaks the project member)
    pin("on")
    fused = _fused_ops(session, "SELECT * FROM fx WHERE k < 50")
    assert set(fused.values()) == {"-"}


# ------------------------------------------------- eligibility breaks

@pytest.mark.parametrize("q,expect", [
    # object/string column in the predicate -> the filter is host-only,
    # but the count(*) terminal still fuses alone (lone-agg exemption)
    ("SELECT count(*) AS n FROM fx WHERE tag = 'a' AND k < 50",
     {"filter": "-", "aggregate": "g1"}),
    # GROUP BY aggregation is host-side; the lone filter is then dropped
    ("SELECT k, count(*) AS n FROM fx WHERE py > 0.0 GROUP BY k",
     {"filter": "-", "aggregate": "-"}),
    # string projection breaks the terminal; lone filter dropped too
    ("SELECT tag AS t FROM fx WHERE k < 50",
     {"filter": "-", "project": "-"}),
    # mixed concrete dtypes promote differently (i32 + i64)
    ("SELECT count(*) AS n FROM fx WHERE b32 + k > 10",
     {"filter": "-", "aggregate": "g1"}),
    # % differs between numpy and XLA for negative operands
    ("SELECT count(*) AS n FROM fx WHERE k % 7 = 0",
     {"filter": "-", "aggregate": "g1"}),
    # float sums are reduction-order dependent; lone filter dropped
    ("SELECT sum(px) AS s FROM fx WHERE k < 50",
     {"filter": "-", "aggregate": "-"}),
])
def test_eligibility_breaks(session, pin, q, expect):
    pin("on")
    fused = _fused_ops(session, q)
    for op, want in expect.items():
        assert fused[op] == want, (q, fused)
    # and the ineligible query still answers identically either way
    a, b = _ab(session, pin, q)
    _assert_identical(a, b)


# ------------------------------------------------- bit-for-bit parity

@pytest.mark.parametrize("q", [
    # flagship reference shape: composite predicate + mixed aggregates
    "SELECT count(*) AS n, max(px) AS mx, min(py) AS mn, sum(k) AS sk"
    " FROM fx WHERE px*px + py*py < 1.44 AND px > 0.1",
    # NaN-aware: count(col) skips NaN, min/max ignore NaN rows
    "SELECT count(px) AS c, max(px) AS mx, avg(k) AS ak FROM fx "
    "WHERE py > 0.0",
    # projection chain with literals, division (int/int -> f64),
    # unary minus and OR
    "SELECT -px AS np_, (k + 1) / 2 AS h, px * 0.5 + py AS m FROM fx "
    "WHERE flag OR py > 1.0",
    # IS [NOT] NULL against the NaN-bearing column
    "SELECT count(*) AS n FROM fx WHERE px IS NULL OR k < 5",
    "SELECT count(*) AS n, first(k) AS f FROM fx "
    "WHERE px IS NOT NULL AND py < 0.0",
    # bool column straight through the mask path
    "SELECT count(*) AS n FROM fx WHERE not flag",
    # int32 column alone (no mixing) is eligible
    "SELECT min(b32) AS mn, max(b32) AS mx FROM fx WHERE b32 > 2",
    # ORDER BY + LIMIT after a fused filter+project group
    "SELECT px + py AS s FROM fx WHERE k < 30 ORDER BY s LIMIT 11",
])
def test_bit_parity_fused_vs_unfused(session, pin, q):
    a, b = _ab(session, pin, q)
    _assert_identical(a, b)


def test_empty_table_bails_out_identically(mc, pin):
    s = SQLSession(mc)
    s.create_table("empty0", {"x": np.zeros(0), "k": np.zeros(0, np.int64)})
    a, b = _ab(s, pin, "SELECT count(*) AS n, max(x) AS mx "
                       "FROM empty0 WHERE k < 5")
    _assert_identical(a, b)


# ------------------------------------------------- runtime bailouts

def test_sum_exactness_bound_bails_out(mc, pin):
    # n * max|v| >= 2**53: the int64 device sum can no longer be
    # proven equal to the unfused float64 accumulation -> fall back
    s = SQLSession(mc)
    s.create_table("big", {
        "v": np.full(64, 2 ** 50, dtype=np.int64)})
    was = metrics.enabled
    metrics.enable()
    b0 = metrics.counter_value("fusion/bailouts")
    a, b = _ab(s, pin, "SELECT sum(v) AS s, count(*) AS n FROM big")
    b1 = metrics.counter_value("fusion/bailouts")
    if not was:
        metrics.disable()
    _assert_identical(a, b)
    assert b1 - b0 >= 1
    assert any("2**53" in e["reason"]
               for e in recorder.events("fusion_bailout"))


def test_left_join_null_conversion_bails_out(mc, pin):
    # the catalog pre-pass saw an int64 column; the LEFT JOIN turned
    # it into a python list with Nones -> runtime re-check bails, the
    # unfused path answers, results identical
    s = SQLSession(mc)
    s.create_table("lj_l", {"k": np.arange(10, dtype=np.int64),
                            "px": np.linspace(-1, 1, 10)})
    s.create_table("lj_r", {"k": np.arange(5, dtype=np.int64),
                            "w": np.arange(5, dtype=np.int64) * 10})
    q = ("SELECT count(*) AS n, max(w) AS mw FROM lj_l "
         "LEFT JOIN lj_r ON lj_l.k = lj_r.k WHERE px > -0.5")
    a, b = _ab(s, pin, q)
    _assert_identical(a, b)
    assert any("at runtime" in e["reason"]
               for e in recorder.events("fusion_bailout"))


# ------------------------------------------------- compile accounting

def test_one_compile_per_group_and_bucket(mc, pin):
    s = SQLSession(mc)
    rng = np.random.default_rng(5)

    def make(n):
        s.create_table("cb", {"x": rng.normal(size=n),
                              "c": rng.integers(0, 7, size=n)})

    q = "SELECT count(*) AS n, max(x) AS mx FROM cb WHERE c < 3"
    pin("on")
    make(100)                                     # bucket 128
    st0 = kernel_cache.stats()
    s.sql(q)
    st1 = kernel_cache.stats()
    assert st1["misses"] - st0["misses"] == 1     # the one compile
    s.sql(q)                                      # warm: same bucket
    make(100)                                     # new data, same shape
    s.sql(q)
    st2 = kernel_cache.stats()
    assert st2["misses"] - st1["misses"] == 0
    assert st2["hits"] - st1["hits"] == 2
    make(1000)                                    # bucket 1024
    s.sql(q)
    st3 = kernel_cache.stats()
    assert st3["misses"] - st2["misses"] == 1     # one per size class
    pin("auto")


def test_ledger_attribution_for_fused_kernels(mc, pin):
    s = SQLSession(mc)
    s.create_table("lg", {"x": np.linspace(0, 1, 300),
                          "c": np.arange(300, dtype=np.int64)})
    pin("on")
    s.sql("SELECT count(*) AS n, min(x) AS mn FROM lg WHERE c > 10")
    pin("auto")
    rows = [k for k in ledger.report()["kernels"]
            if k["name"].startswith("fused:filter+aggregate:")]
    assert rows, "fused launch missing from the kernel ledger"
    assert any(k["launches"] >= 1 and k["seconds"] >= 0.0
               and k["rows"] >= 300 for k in rows)


# ------------------------------------------------- planner decision

def test_learned_flip_and_cold_crossover(mc):
    from mosaic_tpu.sql.planner import _FUSION_CROSSOVER
    planner.reset()          # earlier tests in this process train it
    try:
        n = 2048
        opset, members = "filter+aggregate", ["filter", "aggregate"]
        # cold: static crossover decides
        d = planner.decide_fusion(opset, members, n)
        assert d.strategy == "fused" and "cold" in d.reason
        d = planner.decide_fusion(opset, members,
                                  _FUSION_CROSSOVER - 1)
        assert d.strategy == "unfused"
        # teach it: fused slow, members fast -> learned flip to unfused
        for _ in range(12):
            planner.observe_op(f"fusion/{opset}", n, 0.10)
            planner.observe_op("filter", n, 0.001)
            planner.observe_op("aggregate", n, 0.001)
        d = planner.decide_fusion(opset, members, n)
        assert d.strategy == "unfused" and "learned" in d.reason
        # re-teach: fused cheap again -> flips back
        for _ in range(40):
            planner.observe_op(f"fusion/{opset}", n, 0.0001)
        d = planner.decide_fusion(opset, members, n)
        assert d.strategy == "fused" and "learned" in d.reason
    finally:
        planner.reset()


def test_forced_pins_and_kill_switch(session, pin):
    q = "SELECT count(*) AS n FROM fx WHERE k < 50 AND py > 0.0"
    pin("off")
    assert set(_fused_ops(session, q).values()) == {"-"}
    pin("on")
    assert _fused_ops(session, q)["filter"] == "g1"
    # mosaic.fusion.enabled=false beats even a forced-on pin: the
    # fusion pass never runs, so there is nothing to force
    prev = _config.default_config()
    _config.set_default_config(_config.apply_conf(
        prev, "mosaic.fusion.enabled", "false"))
    try:
        assert set(_fused_ops(session, q).values()) == {"-"}
    finally:
        _config.set_default_config(prev)


def test_max_ops_truncates_from_the_front(session, pin):
    # group-size cap 1: the terminal survives, earlier members unfuse
    prev = _config.default_config()
    _config.set_default_config(_config.apply_conf(
        prev, "mosaic.fusion.max.ops", "1"))
    try:
        pin("on")
        fused = _fused_ops(session,
                           "SELECT count(*) AS n, max(px) AS mx "
                           "FROM fx WHERE k < 50")
        assert fused["filter"] == "-"
        assert fused["aggregate"] == "g1"
    finally:
        _config.set_default_config(prev)


# ------------------------------------------------- config validation

@pytest.mark.parametrize("key,bad", [
    ("mosaic.fusion.enabled", "maybe"),
    ("mosaic.fusion.max.ops", "zero"),
    ("mosaic.fusion.max.ops", "-3"),
    ("mosaic.planner.force.fusion", "sideways"),
])
def test_config_validation_names_the_key(key, bad):
    with pytest.raises(_config.ConfigError) as ei:
        _config.apply_conf(_config.default_config(), key, bad)
    assert key in str(ei.value)


def test_config_keys_accept_valid_values():
    cfg = _config.apply_conf(_config.default_config(),
                             "mosaic.fusion.enabled", "false")
    assert cfg.fusion_enabled is False
    cfg = _config.apply_conf(cfg, "mosaic.fusion.max.ops", "4")
    assert cfg.fusion_max_ops == 4
    for mode in ("on", "off", "auto"):
        _config.apply_conf(_config.default_config(),
                           "mosaic.planner.force.fusion", mode)
