"""Randomized cross-validation of the polygon boolean engine.

The round-4 sliver-filter bug (result rings smaller than q*|coordinate|
silently dropped) slipped through because every unit test ran at unit
coordinate scale.  This harness fuzzes random simple polygon pairs
across coordinate REGIMES (unit box, lon/lat magnitudes, tiny
footprints at lon ~74, large offsets) and checks three independent
implementations against each other:

* rings_boolean (the stitching overlay engine),
* pairs_intersection_area (the fragment-shoelace kernel — C++ when
  built, python fallback otherwise),
* the inclusion–exclusion identity area(A∪B) = A + B − area(A∩B) and
  area(A\\B) = A − area(A∩B), which ties union/difference/intersection
  to each other exactly.
"""

import numpy as np
import pytest

from mosaic_tpu.core.geometry.array import GeometryBuilder
from mosaic_tpu.core.geometry.clip import (_normalize_rings,
                                           geometry_rings,
                                           pairs_intersection_area,
                                           ring_signed_area,
                                           rings_boolean)

REGIMES = [
    ("unit", 0.0, 0.0, 1.0),
    ("lonlat_nyc", -74.0, 40.7, 1e-3),
    ("lonlat_big", 151.2, -33.8, 0.5),
    ("offset_huge", 5000.0, -3000.0, 2.0),
]


def _rand_poly(rng, cx, cy, r, n):
    ang = 2 * np.pi * (np.arange(n) + rng.uniform(-0.35, 0.35, n)) / n
    rad = r * rng.uniform(0.35, 1.0, n)
    ring = np.stack([cx + rad * np.cos(ang), cy + rad * np.sin(ang)],
                    -1)
    return np.vstack([ring, ring[:1]])


def _area(rings):
    return sum(ring_signed_area(r) for r in _normalize_rings(rings))


@pytest.mark.parametrize("name,cx,cy,scale", REGIMES)
def test_boolean_identities(name, cx, cy, scale):
    # crc32, NOT hash(): str hashes are salted per process, which made
    # this fuzz a different workload every run — the round-4 "1/359
    # unreproduced flake" was a rare seed landing outside the
    # tolerance envelope, unfindable because the seed died with the
    # process
    import zlib
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    ba, bb = GeometryBuilder(), GeometryBuilder()
    P = 40
    for _ in range(P):
        dx, dy = rng.uniform(-0.8, 0.8, 2) * scale
        ba.add_polygon(_rand_poly(rng, cx + dx, cy + dy,
                                  scale * rng.uniform(0.3, 1.0),
                                  rng.integers(5, 11)))
        dx, dy = rng.uniform(-0.8, 0.8, 2) * scale
        bb.add_polygon(_rand_poly(rng, cx + dx, cy + dy,
                                  scale * rng.uniform(0.3, 1.0),
                                  rng.integers(5, 11)))
    A, B = ba.finish(), bb.finish()
    ia = ib = np.arange(P)
    kern = pairs_intersection_area(A, ia, B, ib)
    # measured accuracy envelope of the stitching engine: ~1e-9
    # relative at unit coordinate-to-size ratio, ~1e-6 when geometries
    # are ~1e-5 of the coordinate magnitude (snap-rounding floor; see
    # rings_boolean's tolerance note).  The kernel cross-check stays
    # tight — it shares no stitching.
    mag = max(abs(cx), abs(cy), 1.0)
    # identity error is f64 shoelace cancellation: terms ~mag^2 summed
    # to an area ~scale^2, so rel err ~ eps * (mag/scale)^2.  Measured
    # worst over 60 seeds x 40 pairs: 4.3e-5 at mag/scale 7.4e4
    # (~8e-15 * ratio^2); 5e-14 gives ~6x margin.  The old 4e-6
    # envelope undershot this regime — the round-4 flake.
    ident_rel = max(1e-9, 5e-14 * (mag / scale) ** 2)
    # engine-vs-kernel: both are exact selections of the same split
    # points but sum shoelace terms (~mag^2 each) in different orders,
    # so the comparison floor is the f64 cancellation bound ~1e-15*mag^2
    # plus the same snap envelope
    cross_abs = 1e-13 * mag * mag
    cross_rel = max(2e-7, ident_rel)
    for p in range(P):
        ra = _normalize_rings(geometry_rings(A, p))
        rb = _normalize_rings(geometry_rings(B, p))
        a_area = _area(ra)
        b_area = _area(rb)
        inter = _area(rings_boolean(ra, rb, "intersection"))
        union = _area(rings_boolean(ra, rb, "union"))
        diff = _area(rings_boolean(ra, rb, "difference"))
        ref = max(a_area, b_area)
        # engine vs fragment kernel
        assert inter == pytest.approx(kern[p], rel=cross_rel,
                                      abs=cross_abs), (name, p)
        # inclusion-exclusion ties the three ops together
        assert union == pytest.approx(a_area + b_area - inter,
                                      rel=ident_rel,
                                      abs=ident_rel * ref), (name, p)
        assert diff == pytest.approx(a_area - inter, rel=ident_rel,
                                     abs=ident_rel * ref), (name, p)
        # bounds
        assert -1e-12 * ref <= inter <= min(a_area, b_area) + \
            1e-9 * ref


def test_self_ops_identity():
    rng = np.random.default_rng(77)
    b = GeometryBuilder()
    for _ in range(12):
        b.add_polygon(_rand_poly(rng, -74 + rng.uniform(-0.1, 0.1),
                                 40.7 + rng.uniform(-0.1, 0.1),
                                 rng.uniform(1e-4, 1e-2),
                                 rng.integers(5, 10)))
    A = b.finish()
    for p in range(12):
        ra = _normalize_rings(geometry_rings(A, p))
        a_area = _area(ra)
        assert _area(rings_boolean(ra, ra, "intersection")) == \
            pytest.approx(a_area, rel=1e-9)
        assert _area(rings_boolean(ra, ra, "union")) == \
            pytest.approx(a_area, rel=1e-9)
        assert abs(_area(rings_boolean(ra, ra, "difference"))) \
            <= 1e-9 * a_area
