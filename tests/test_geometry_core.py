"""Geometry core tests: codecs, measures, predicates.

Modelled on the reference behaviors suites
(src/test/scala/.../expressions/geometry/*Behaviors.scala): round-trips
across encodings and measure/predicate assertions on known shapes.
"""

import numpy as np
import pytest

from mosaic_tpu import (GeometryArray, GeometryBuilder, GeometryType,
                        read_geojson, read_wkb, read_wkt, write_geojson,
                        write_wkb, write_wkt)
from mosaic_tpu.core.geometry import measures, predicates
from mosaic_tpu.core.geometry.padded import build_edges, points_block

WKTS = [
    "POINT (1 2)",
    "LINESTRING (0 0, 1 1, 2 0)",
    "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))",
    "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (2 2, 4 2, 4 4, 2 4, 2 2))",
    "MULTIPOINT ((1 1), (2 2))",
    "MULTILINESTRING ((0 0, 1 1), (2 2, 3 3))",
    "MULTIPOLYGON (((0 0, 1 0, 1 1, 0 1, 0 0)), ((5 5, 6 5, 6 6, 5 6, 5 5)))",
    "GEOMETRYCOLLECTION (POINT (1 1), LINESTRING (0 0, 1 1))",
]


def test_wkt_roundtrip():
    arr = read_wkt(WKTS)
    assert len(arr) == len(WKTS)
    back = write_wkt(arr)
    arr2 = read_wkt(back)
    assert np.allclose(arr.coords, arr2.coords)
    assert np.array_equal(arr.types, arr2.types)
    assert np.array_equal(arr.ring_offsets, arr2.ring_offsets)


def test_wkb_roundtrip():
    arr = read_wkt(WKTS[:7])  # collections re-infer member types, test sep.
    blobs = write_wkb(arr)
    arr2 = read_wkb(blobs)
    assert np.allclose(arr.coords, arr2.coords)
    assert np.array_equal(arr.types, arr2.types)
    assert np.array_equal(arr.ring_offsets, arr2.ring_offsets)


def test_wkb_point_fast_path():
    pts = np.array([[1.5, 2.5], [3.0, -4.0]])
    arr = GeometryArray.from_points(pts)
    blobs = write_wkb(arr)
    arr2 = read_wkb(blobs)
    assert np.allclose(arr2.coords, pts)
    assert all(t == GeometryType.POINT for t in arr2.types)


def test_geojson_roundtrip():
    arr = read_wkt(WKTS[:7])
    js = write_geojson(arr)
    arr2 = read_geojson(js)
    assert np.allclose(arr.coords, arr2.coords)
    assert np.array_equal(arr.types, arr2.types)


def test_z_coordinates():
    arr = read_wkt(["POINT Z (1 2 3)", "LINESTRING Z (0 0 1, 1 1 2)"])
    assert arr.ndim == 3
    assert arr.coords[0, 2] == 3
    blobs = write_wkb(arr)
    arr2 = read_wkb(blobs)
    assert arr2.ndim == 3
    assert np.allclose(arr.coords, arr2.coords)


def test_area_length_centroid():
    arr = read_wkt([
        "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))",
        "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (2 2, 4 2, 4 4, 2 4, 2 2))",
    ])
    e = build_edges(arr, dtype=np.float64)
    a = np.asarray(measures.area(e))
    assert np.allclose(a, [16.0, 96.0])
    ln = np.asarray(measures.length(e))
    assert np.allclose(ln, [16.0, 48.0])
    c = np.asarray(measures.centroid(e))
    assert np.allclose(c[0], [2.0, 2.0])


def test_centroid_with_hole():
    # hole off-center pulls centroid away
    arr = read_wkt([
        "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (6 6, 9 6, 9 9, 6 9, 6 6))"])
    e = build_edges(arr, dtype=np.float64)
    c = np.asarray(measures.centroid(e))[0]
    assert c[0] < 5.0 and c[1] < 5.0


def test_bounds():
    arr = read_wkt(["LINESTRING (1 2, 5 -3, 2 7)"])
    e = build_edges(arr, dtype=np.float64)
    b = np.asarray(measures.bounds(e))[0]
    assert np.allclose(b, [1, -3, 5, 7])


def test_winding_normalization():
    # CW shell input must still give positive area
    arr = read_wkt(["POLYGON ((0 0, 0 4, 4 4, 4 0, 0 0))"])
    e = build_edges(arr, dtype=np.float64)
    assert np.allclose(np.asarray(measures.area(e)), [16.0])


def test_points_in_polygons():
    polys = read_wkt([
        "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))",
        "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (2 2, 4 2, 4 4, 2 4, 2 2))",
    ])
    e = build_edges(polys, dtype=np.float64)
    pts = np.array([[2.0, 2.0],   # in sq; not in donut (inside hole... wait)
                    [3.0, 3.0],   # in sq; in hole of donut
                    [5.0, 5.0],   # out sq; in donut
                    [20.0, 1.0]])  # out both
    inside, dist = predicates.points_in_polygons(
        np.asarray(pts), e, with_boundary_dist=True)
    inside = np.asarray(inside)
    assert inside[0, 0] and inside[1, 0]
    assert not inside[2, 0] and not inside[3, 0]
    assert not inside[1, 1]          # in the hole
    assert inside[2, 1]
    assert not inside[3, 1]
    d = np.asarray(dist)
    assert d[0, 0] == pytest.approx(2.0)


def test_haversine_km():
    # London -> Paris ≈ 344 km
    d = float(measures.haversine(51.5074, -0.1278, 48.8566, 2.3522))
    assert 330 < d < 360


def test_distance_points_to_geoms():
    arr = read_wkt(["LINESTRING (0 0, 10 0)"])
    e = build_edges(arr, dtype=np.float64)
    d = np.asarray(measures.distance_points_to_geoms(
        np.array([[5.0, 3.0], [-3.0, 4.0]]), e))
    assert d[0, 0] == pytest.approx(3.0)
    assert d[1, 0] == pytest.approx(5.0)


def test_polygons_intersect():
    polys = read_wkt([
        "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))",
        "POLYGON ((2 2, 6 2, 6 6, 2 6, 2 2))",
        "POLYGON ((10 10, 12 10, 12 12, 10 12, 10 10))",
        "POLYGON ((1 1, 2 1, 2 2, 1 2, 1 1))",  # inside poly 0
    ])
    e = build_edges(polys, dtype=np.float64)
    m = np.asarray(predicates.polygons_intersect(e, e))
    assert m[0, 1] and m[1, 0]
    assert not m[0, 2] and not m[2, 1]
    assert m[0, 3] and m[3, 0]          # containment counts as intersects
    c = np.asarray(predicates.polygon_contains_polygon(e, e))
    assert c[0, 3] and not c[3, 0] and not c[0, 1]


def test_geometry_array_take():
    arr = read_wkt(WKTS)
    sub = arr.take([2, 0])
    assert len(sub) == 2
    assert sub.geom_type(0) == GeometryType.POLYGON
    assert sub.geom_type(1) == GeometryType.POINT
    assert np.allclose(sub.coords[-1], [1, 2])


def test_vertex_counts_and_bboxes():
    arr = read_wkt(WKTS)
    vc = arr.vertex_counts()
    assert vc[0] == 1 and vc[2] == 5 and vc[3] == 10
    bb = arr.bboxes()
    assert np.allclose(bb[2], [0, 0, 4, 4])
