"""GeoPackage codec: write -> read round trip + OGR-style dispatch.

Reference: the GPKG driver reached through OGRFileFormat's driver
dispatch (datasource/OGRFileFormat.scala:27); the container is SQLite
(CPython's bundled sqlite3), the GPKG catalog/blob layers are ours.
"""

import numpy as np
import pytest

from mosaic_tpu.core.geometry.wkt import read_wkt, write_wkt
from mosaic_tpu.io.geopackage import gpkg_layers, read_gpkg, write_gpkg


@pytest.fixture()
def sample(tmp_path):
    geoms = read_wkt([
        "POINT (1 2)",
        "POLYGON ((0 0, 2 0, 2 2, 0 2, 0 0), "
        "(0.5 0.5, 1.5 0.5, 1.5 1.5, 0.5 1.5, 0.5 0.5))",
        "MULTIPOLYGON (((5 5, 6 5, 6 6, 5 5)))",
        "LINESTRING (0 0, 3 4)",
    ])
    attrs = {"name": ["a", "b", "c", "d"],
             "score": [1.5, 2.5, -3.0, 0.0]}
    path = str(tmp_path / "sample.gpkg")
    write_gpkg(path, geoms, attrs, layer="stuff", srs_id=4326)
    return path, geoms, attrs


def test_round_trip(sample):
    path, geoms, attrs = sample
    assert gpkg_layers(path) == ["stuff"]
    got, cols = read_gpkg(path)
    assert write_wkt(got) == write_wkt(geoms)
    assert cols["name"] == attrs["name"]
    assert cols["score"] == attrs["score"]
    assert got.srid == 4326


def test_read_vector_dispatch(sample):
    path, geoms, _ = sample
    from mosaic_tpu.io.shapefile import read_vector
    got, cols = read_vector(path)
    assert write_wkt(got) == write_wkt(geoms)
    got2, _ = read_vector(path, driver="GPKG")
    assert write_wkt(got2) == write_wkt(geoms)


def test_layer_selection_and_errors(sample, tmp_path):
    path, _, _ = sample
    with pytest.raises(ValueError, match="no layer"):
        read_gpkg(path, layer="nope")
    # a plain sqlite db is not a geopackage
    import sqlite3
    bad = str(tmp_path / "bad.gpkg")
    sqlite3.connect(bad).execute("CREATE TABLE t (x)")
    with pytest.raises((ValueError, sqlite3.OperationalError)):
        read_gpkg(bad)


def test_gpb_envelope_variants(tmp_path):
    # blobs with an envelope present must still strip correctly
    import sqlite3
    import struct
    from mosaic_tpu.core.geometry.wkb import write_wkb
    geoms = read_wkt(["POINT (7 8)"])
    path = str(tmp_path / "env.gpkg")
    write_gpkg(path, geoms, layer="pts")
    con = sqlite3.connect(path)
    wkb = write_wkb(geoms)[0]
    hdr = b"GP" + bytes([0, 0x03]) + struct.pack("<i", 4326) + \
        struct.pack("<4d", 7, 7, 8, 8)        # envelope code 1
    con.execute('UPDATE "pts" SET geom = ?', (hdr + wkb,))
    con.commit()
    con.close()
    got, _ = read_gpkg(path)
    assert write_wkt(got) == ["POINT (7 8)"]
