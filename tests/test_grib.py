"""GRIB codec over the reference's real CAMS fixture (binary copy of
src/test/resources/binary/grib-cams — mixed GRIB1/GRIB2 messages)."""

import os

import numpy as np
import pytest

from mosaic_tpu.io.grib import read_grib

FIX = os.path.join(os.path.dirname(__file__), "data", "cams_sample.grb")


@pytest.fixture(scope="module")
def tiles():
    with open(FIX, "rb") as f:
        return read_grib(f.read())


def test_message_count_and_shapes(tiles):
    assert len(tiles) == 14
    for t in tiles.values():
        assert t.data.shape == (1, 14, 14)
        assert np.isfinite(t.data).all()


def test_values_plausible(tiles):
    # CAMS GO3 mass mixing ratios: ~1e-6 kg/kg
    first = tiles[sorted(tiles)[0]].data
    assert 1e-7 < np.nanmean(first) < 1e-5


def test_georeferencing(tiles):
    t = tiles[sorted(tiles)[0]]
    # 14x14 cells of 0.75 deg, corner near (0, 9.75+half)
    assert t.gt.px_w == pytest.approx(0.75)
    assert t.gt.px_h == pytest.approx(-0.75)
    # north-up: top-left latitude above bottom
    assert t.gt.y0 > t.gt.y0 + 14 * t.gt.px_h


def test_raster_api_dispatch():
    from mosaic_tpu.functions.context import MosaicContext
    mc = MosaicContext.build("H3")
    t = mc.rst_fromfile([FIX])[0]
    assert t.meta["driver"] == "GRIB"
    subs = mc.rst_subdatasets([t])[0]
    assert len(subs) == 14
    other = sorted(subs)[1]
    t2 = mc.rst_getsubdataset([t], other)[0]
    assert t2.data.shape == (1, 14, 14)


def test_editions_mixed(tiles):
    # the fixture mixes GRIB2 (message 0) and GRIB1 messages
    eds = {t.meta.get("edition") for t in tiles.values()}
    assert eds == {"1", "2"}


def test_grib_raster_to_grid():
    """Real CAMS data through the raster->H3 pipeline (BASELINE config
    5 semantics over an actual reanalysis product)."""
    import jax
    from mosaic_tpu.core.index.factory import get_index_system
    from mosaic_tpu.io.grib import read_grib
    from mosaic_tpu.io.raster_grid import raster_to_grid
    grid = get_index_system("H3")
    with open(FIX, "rb") as f:
        tiles = read_grib(f.read())
    t = tiles[sorted(tiles)[0]]
    cells = raster_to_grid([t], 2, grid, combiner="avg")
    assert len(cells) > 10
    vals = np.asarray(list(cells.values()))
    ok = vals[np.isfinite(vals)]
    assert len(ok) and 1e-8 < np.nanmean(ok) < 1e-5
