"""GRIB codec over the reference's real CAMS fixture (binary copy of
src/test/resources/binary/grib-cams — mixed GRIB1/GRIB2 messages)."""

import os

import numpy as np
import pytest

from mosaic_tpu.io.grib import read_grib

FIX = os.path.join(os.path.dirname(__file__), "data", "cams_sample.grb")


@pytest.fixture(scope="module")
def tiles():
    with open(FIX, "rb") as f:
        return read_grib(f.read())


def test_message_count_and_shapes(tiles):
    assert len(tiles) == 14
    for t in tiles.values():
        assert t.data.shape == (1, 14, 14)
        assert np.isfinite(t.data).all()


def test_values_plausible(tiles):
    # CAMS GO3 mass mixing ratios: ~1e-6 kg/kg
    first = tiles[sorted(tiles)[0]].data
    assert 1e-7 < np.nanmean(first) < 1e-5


def test_georeferencing(tiles):
    t = tiles[sorted(tiles)[0]]
    # 14x14 cells of 0.75 deg, corner near (0, 9.75+half)
    assert t.gt.px_w == pytest.approx(0.75)
    assert t.gt.px_h == pytest.approx(-0.75)
    # north-up: top-left latitude above bottom
    assert t.gt.y0 > t.gt.y0 + 14 * t.gt.px_h


def test_raster_api_dispatch():
    from mosaic_tpu.functions.context import MosaicContext
    mc = MosaicContext.build("H3")
    t = mc.rst_fromfile([FIX])[0]
    assert t.meta["driver"] == "GRIB"
    subs = mc.rst_subdatasets([t])[0]
    assert len(subs) == 14
    other = sorted(subs)[1]
    t2 = mc.rst_getsubdataset([t], other)[0]
    assert t2.data.shape == (1, 14, 14)


def test_editions_mixed(tiles):
    # the fixture mixes GRIB2 (message 0) and GRIB1 messages
    eds = {t.meta.get("edition") for t in tiles.values()}
    assert eds == {"1", "2"}
