"""H3 grid: from-scratch aperture-7 icosahedral DGGS validation.

The reference delegates these invariants to Uber's C library via JNI
(core/index/H3IndexSystem.scala); with no reference build available the
grid is validated self-consistently: exact round-trips, exhaustive
cell-universe enumeration, topology symmetry, sphere partition, and
device-kernel agreement with the float64 host path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mosaic_tpu.core.index.h3.index as ix
from mosaic_tpu.core.index.h3 import hexmath as hm
from mosaic_tpu.core.index.h3.jaxkernel import latlng_to_cell_jax
from mosaic_tpu.core.index.h3.system import H3IndexSystem
from mosaic_tpu.core.index.h3.tables import tables
from mosaic_tpu.core.index.factory import get_index_system


@pytest.fixture(scope="module")
def rng_pts():
    rng = np.random.default_rng(7)
    n = 5000
    lat = np.arcsin(rng.uniform(-1, 1, n))
    lng = rng.uniform(-np.pi, np.pi, n)
    return np.stack([lat, lng], -1)


def test_base_cells_and_pentagons():
    t = tables()
    assert len(t.center_xyz) == 122
    # the canonical H3 pentagon numbers fall out of latitude ordering
    assert np.nonzero(t.is_pentagon)[0].tolist() == \
        [4, 14, 24, 38, 49, 58, 63, 72, 83, 97, 107, 117]


@pytest.mark.parametrize("res", [0, 1, 2, 5, 9, 15])
def test_roundtrip(rng_pts, res):
    cells = ix.latlng_to_cell(rng_pts, res)
    assert np.all(ix.is_valid_cell(cells))
    centers = ix.cell_to_latlng(cells)
    assert np.array_equal(ix.latlng_to_cell(centers, res), cells)


def test_exhaustive_res2_universe():
    t = tables()
    base, digits, ijk = t._descend(2)
    # _descend yields internal wedge labels; ids carry published labels
    cells = ix.pack(base, ix._pent_to_external(base, digits), 2)
    assert len(cells) == 2 + 120 * 49
    assert len(np.unique(cells)) == len(cells)
    centers = t.develop(base, digits, ijk, 2)[1]
    assert np.array_equal(ix.latlng_to_cell(centers, 2), cells)
    # parent of every cell is the res-1 ancestor
    parents = ix.cell_to_parent(cells, 1)
    assert np.array_equal(parents,
                          ix.latlng_to_cell(centers, 1))


def test_neighbor_symmetry():
    t = tables()
    base, digits, ijk = t._descend(1)
    cells = ix.pack(base, ix._pent_to_external(base, digits), 1)
    nb, valid = ix.neighbors(cells)
    idx = {int(c): i for i, c in enumerate(cells)}
    for i in range(len(cells)):
        for j in range(6):
            if valid[i, j]:
                assert int(cells[i]) in nb[idx[int(nb[i, j])]].tolist()
    pent = ix.is_pentagon_cell(cells)
    assert np.all(valid[pent].sum(axis=1) == 5)
    assert np.all(valid[~pent].sum(axis=1) == 6)


def test_kring_kloop_counts(rng_pts):
    cells = ix.latlng_to_cell(rng_pts[:100], 6)
    for k in (1, 2, 3):
        disk = ix.k_ring(cells, k)
        assert np.all((disk >= 0).sum(axis=1) == 3 * k * k + 3 * k + 1)
        loop = ix.k_loop(cells, k)
        assert np.all((loop >= 0).sum(axis=1) == 6 * k)
        # loop == disk minus inner disk
        inner = ix.k_ring(cells, k - 1)
        for i in range(5):
            d = set(disk[i][disk[i] >= 0].tolist())
            inn = set(inner[i][inner[i] >= 0].tolist())
            lo = set(loop[i][loop[i] >= 0].tolist())
            assert lo == d - inn


def test_boundary_partitions_sphere():
    t = tables()
    base, digits, ijk = t._descend(1)
    cells = ix.pack(base, ix._pent_to_external(base, digits), 1)
    sysm = H3IndexSystem()
    areas = sysm.cell_area(cells)
    earth = 4 * np.pi * 6371.0088 ** 2
    # projected-corner boundaries (chosen so boundaries agree with
    # point_to_cell, like the reference H3) are not an exact spherical
    # partition across face edges; defect shrinks with resolution
    assert abs(areas.sum() / earth - 1) < 5e-3
    # hexagons of the same res are within ~2x area of each other
    hexes = ~ix.is_pentagon_cell(cells)
    assert areas[hexes].max() / areas[hexes].min() < 2.0


def test_index_system_adapter(rng_pts):
    grid = get_index_system("H3")
    xy = np.stack([np.degrees(rng_pts[:500, 1]),
                   np.degrees(rng_pts[:500, 0])], -1)
    cells = grid.point_to_cell(xy, 9)
    assert np.all(grid.resolution_of(cells) == 9)
    centers = grid.cell_center(cells)
    assert np.array_equal(grid.point_to_cell(centers, 9), cells)
    verts, counts = grid.cell_boundary(cells)
    assert verts.shape[1:] == (6, 2)
    # centers fall inside their own boundary (planar lon/lat test away
    # from the antimeridian)
    from mosaic_tpu.core.tessellate import _pip
    for i in range(50):
        ring = verts[i, :counts[i]]
        if np.ptp(ring[:, 0]) > 180:
            continue
        edges = np.stack([ring, np.roll(ring, -1, axis=0)], axis=1)
        assert _pip(centers[i:i + 1], edges)[0]


def test_candidate_cells_cover_bbox():
    grid = get_index_system("H3")
    bbox = np.array([-74.1, 40.6, -73.9, 40.8])
    res = 7
    cand = set(grid.candidate_cells(bbox, res).tolist())
    rng = np.random.default_rng(3)
    pts = np.stack([rng.uniform(bbox[0], bbox[2], 2000),
                    rng.uniform(bbox[1], bbox[3], 2000)], -1)
    cells = grid.point_to_cell(pts, res)
    assert set(cells.tolist()) <= cand


def test_jax_kernel_matches_host(rng_pts):
    host = ix.latlng_to_cell(rng_pts, 9)
    dev = np.asarray(jax.jit(
        lambda la, ln: latlng_to_cell_jax(la, ln, 9))(
            jnp.asarray(rng_pts[:, 0], jnp.float32),
            jnp.asarray(rng_pts[:, 1], jnp.float32)))
    agree = np.mean(host == dev)
    assert agree > 0.98, agree
    assert np.all(ix.is_valid_cell(dev))


def test_children_parent():
    t = tables()
    cells = ix.latlng_to_cell(np.array([[0.7, 0.1], [-1.0, 2.0]]), 3)
    kids = ix.cell_to_children(cells, 5)
    for c, k in zip(cells, kids):
        assert len(k) == 49
        assert np.all(ix.cell_to_parent(k, 3) == c)
    # pentagon has 6 children per level
    pent = ix.pack(np.array([4]), np.zeros((1, 0), np.int64), 0)
    kids = ix.cell_to_children(pent, 1)[0]
    assert len(kids) == 6


def test_tessellate_h3():
    from mosaic_tpu.core.geometry.array import GeometryBuilder
    from mosaic_tpu.core.tessellate import tessellate
    grid = get_index_system("H3")
    b = GeometryBuilder()
    ring = np.array([[-74.02, 40.70], [-73.95, 40.70], [-73.95, 40.76],
                     [-74.02, 40.76], [-74.02, 40.70]])
    b.add_polygon(ring)
    polys = b.finish()
    chips = tessellate(polys, 9, grid, keep_core_geom=False)
    assert len(chips) > 50
    assert chips.is_core.sum() > 0
    # random points in the polygon land in chip cells
    rng = np.random.default_rng(5)
    pts = np.stack([rng.uniform(-74.02, -73.95, 500),
                    rng.uniform(40.70, 40.76, 500)], -1)
    cells = grid.point_to_cell(pts, 9)
    assert set(cells.tolist()) <= set(chips.cell_id.tolist())


def test_hex_quantization_bruteforce():
    # regression: cube rounding must use the 60°-basis frame; the
    # 120°-basis triple only agrees at lattice points
    rng = np.random.default_rng(0)
    pts = rng.uniform(-5, 5, (5000, 2))
    got = hm.hex2d_to_ijk(pts)
    ga, gb = hm.ijk_to_axial(got)
    aa, bb = np.meshgrid(np.arange(-8, 9), np.arange(-8, 9),
                         indexing="ij")
    cand = np.stack([aa.ravel(), bb.ravel(),
                     np.zeros_like(aa.ravel())], -1)
    cxy = hm.ijk_to_hex2d(cand)
    d = np.linalg.norm(pts[:, None, :] - cxy[None], axis=-1)
    best = np.argmin(d, axis=1)
    assert np.array_equal(ga, cand[best, 0])
    assert np.array_equal(gb, cand[best, 1])


def test_candidate_cells_high_latitude_span():
    """Latitude-banded sampling: candidate generation must not drop
    cells on spans reaching high latitude (regression: a single
    whole-span cos under-sampled low-latitude rows, silently omitting
    bbox-intersecting cells — wrong PIP joins, unflagged)."""
    from mosaic_tpu.core.index.factory import get_index_system
    grid = get_index_system("H3")
    rng = np.random.default_rng(21)
    bbs = np.array([[-100.0, lat, -97.0, lat + 4.0]
                    for lat in range(10, 78, 4)])
    got = grid.candidate_cells_batch(bbs, 3)
    for i, b in enumerate(bbs):
        pts = np.stack([rng.uniform(b[0], b[2], 5000),
                        rng.uniform(b[1], b[3], 5000)], -1)
        pc = np.unique(grid.point_to_cell(pts, 3))
        assert len(np.setdiff1d(pc, got[i])) == 0
        single = grid.candidate_cells(b, 3)
        assert len(np.setdiff1d(pc, single)) == 0


def test_candidate_cells_stream_large_extent():
    """Streaming candidates for extents beyond the in-memory bound:
    batches are disjoint, bounded, and their union covers every cell a
    direct (small-extent) query finds."""
    from mosaic_tpu.core.index.factory import get_index_system
    grid = get_index_system("H3")
    bbox = np.array([-80.0, 30.0, -70.0, 42.0])
    res = 5
    seen = []
    for batch in grid.candidate_cells_stream(bbox, res,
                                             batch_cells=2000):
        assert len(batch) <= 4 * 2000 + 16
        seen.append(batch)
    allc = np.concatenate(seen)
    assert len(allc) == len(np.unique(allc)), "stream emitted dupes"
    direct = grid.candidate_cells(bbox, res)
    assert len(np.setdiff1d(direct, allc)) == 0


def test_grid_distance_closed_form_long_range():
    """Same-face pairs any distance apart resolve without ring walks
    (regression: 64-ring BFS cap raised on distant pairs)."""
    from mosaic_tpu.core.index.factory import get_index_system
    grid = get_index_system("H3")
    a = grid.point_to_cell(np.array([[-74.0, 40.7]]), 9)
    b = grid.point_to_cell(np.array([[-73.0, 41.2]]), 9)   # ~100km away
    d = grid.grid_distance(a, b)
    assert d[0] > 200        # far beyond the old 64-ring cap
    # consistency with the BFS for a near pair
    c = grid.point_to_cell(np.array([[-73.998, 40.701]]), 9)
    d2 = grid.grid_distance(a, c)
    ring = grid.k_ring(a, int(d2[0]))
    assert c[0] in ring[0]
    if d2[0] > 0:
        inner = grid.k_ring(a, int(d2[0]) - 1)
        assert c[0] not in inner[0]
