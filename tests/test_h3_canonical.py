"""Canonical H3 interop: ids must be bit-equal to Uber H3 library output.

The reference's ids ARE Uber ids (H3IndexSystem.scala:168 pointToIndex ->
h3.geoToH3 via JNI), so parity requires the canonical base-cell numbering
and digit labels, not merely a self-consistent grid.  The vectors below
are published H3 values (library README/docs examples and ids carried in
the reference's own test suite).
"""

import numpy as np
import pytest

from mosaic_tpu.core.index.h3 import index as ix
from mosaic_tpu.core.index.h3.canonical import PENTAGON_BASE_CELLS
from mosaic_tpu.core.index.h3.system import H3IndexSystem


def _hex(h):
    return format(int(h), "x")


def test_geo_to_h3_readme_vector():
    # h3.geo_to_h3(37.3615593, -122.0553238, 5) == '85283473fffffff'
    # (the H3 library's canonical README example)
    cells = ix.latlng_to_cell(
        np.radians([[37.3615593, -122.0553238]]), 5)
    assert _hex(cells[0]) == "85283473fffffff"
    assert int(cells[0]) == 599686042433355775


def test_h3_to_geo_readme_vector():
    # h3.h3_to_geo('85283473fffffff')
    #   == (37.34579337536848, -121.97637597255124)
    geo = np.degrees(ix.cell_to_latlng(
        np.array([0x85283473fffffff], np.int64)))
    assert abs(geo[0, 0] - 37.34579337536848) < 1e-6
    assert abs(geo[0, 1] - (-121.97637597255124)) < 1e-6


def test_k_ring_readme_vector():
    # h3.k_ring('8928308280fffff', 1) (h3-py docs example)
    want = {
        "8928308280fffff", "8928308280bffff", "89283082873ffff",
        "89283082877ffff", "8928308283bffff", "89283082807ffff",
        "89283082803ffff",
    }
    ring = ix.k_ring(np.array([0x8928308280fffff], np.int64), 1)[0]
    got = {_hex(c) for c in ring if c >= 0}
    assert got == want


def test_reference_suite_ids_roundtrip():
    # ids carried in the reference's own tests: hex <-> long pairs
    # (ST_IntersectionBehaviors.scala:259-263,
    #  IndexGeometryBehaviors.scala:26-31)
    assert _hex(622236750694711295) == "8a2a1072b59ffff"
    assert _hex(623060282076758015) == "8a58e0682d6ffff"
    cells = np.array([622236750694711295, 623060282076758015], np.int64)
    assert ix.is_valid_cell(cells).all()
    # decode -> encode must round-trip through the canonical tables
    geo = ix.cell_to_latlng(cells)
    back = ix.latlng_to_cell(geo, 10)
    assert np.array_equal(back, cells)


def test_reference_cell_area_vector():
    # CellAreaBehaviors.scala:22: grid_cellarea('871969500ffffff')
    #   == 4.327624974422719 km^2 (via h3.cellArea)
    sysm = H3IndexSystem()
    area = sysm.cell_area(np.array([0x871969500ffffff], np.int64))
    assert area[0] == pytest.approx(4.327624974422719, rel=2e-4)


def test_pentagon_base_cells_published_set():
    assert PENTAGON_BASE_CELLS == (4, 14, 24, 38, 49, 58, 63, 72, 83,
                                   97, 107, 117)
    res0 = ix.pack(np.arange(122, dtype=np.int64),
                   np.zeros((122, 0), np.int64), 0)
    pent = ix.is_pentagon_cell(res0)
    assert set(np.nonzero(pent)[0].tolist()) == set(PENTAGON_BASE_CELLS)


def test_pentagon_relabel_direction_constraints():
    """No Uber-generated vector inside a pentagon subtree was available
    offline, so the relabel direction (index.py _pent_to_external:
    leading {1,5} rotate ccw) rests on the published decode semantics —
    H3's _h3ToFaceIjk rotates leading-5 strings cw before walking, which
    forces label 5 onto the planar K wedge, and continuity forces label
    4 onto the deficit-collapsed sector.  This test pins everything the
    spec constrains WITHOUT a vector: validity (no leading-1 pentagon id
    is ever produced), uniqueness across the relabeled subtrees, and
    roundtrip through the geometric decode."""
    from mosaic_tpu.core.index.h3.tables import tables
    t = tables()
    rng = np.random.default_rng(11)
    pc = t.center_geo[t.is_pentagon]
    pts = np.repeat(pc, 400, axis=0)
    pts = pts + rng.normal(0, 0.12, pts.shape)   # blanket the subtrees
    for res in (1, 2, 3):
        cells = ix.latlng_to_cell(pts, res)
        assert ix.is_valid_cell(cells).all()
        base, digits, _ = ix.unpack(cells)
        lead = ix._leading_digit(digits)
        pent = t.is_pentagon[base]
        # all five published-valid wedges must actually occur
        assert set(np.unique(lead[pent]).tolist()) >= {2, 3, 4, 5, 6}
        assert not np.any(pent & (lead == 1))
        centers = ix.cell_to_latlng(cells)
        assert np.array_equal(ix.latlng_to_cell(centers, res), cells)


def test_pentagon_k_subsequence_deleted():
    # published invariant: pentagons have no K-axis (digit 1) children
    for b in (4, 117):
        parent = ix.pack(np.array([b], np.int64),
                         np.zeros((1, 0), np.int64), 0)
        kids = ix.cell_to_children(parent, 1)[0]
        assert len(kids) == 6
        digs = (kids >> ix._digit_shift(1)) & 7
        assert 1 not in digs.tolist()
        assert ix.is_valid_cell(kids).all()
        # a forged leading-1 child must be invalid
        forged = int(kids[0]) & ~(7 << ix._digit_shift(1)) | \
            (1 << ix._digit_shift(1))
        assert not ix.is_valid_cell(np.array([forged], np.int64))[0]


def test_poles():
    # north pole lies in base cell 0, south pole in base cell 121
    # ('8001fffffffffff' / '80f3fffffffffff')
    n = ix.latlng_to_cell(np.radians([[89.9999, 0.0]]), 0)
    s = ix.latlng_to_cell(np.radians([[-89.9999, 0.0]]), 0)
    assert _hex(n[0]) == "8001fffffffffff"
    assert _hex(s[0]) == "80f3fffffffffff"


def test_base_cell_latitude_antisymmetry():
    # the canonical numbering is antipodally symmetric:
    # center(b) == -center(121 - b) (latitude); a strong structural
    # pin on the embedded table
    from mosaic_tpu.core.index.h3.tables import tables
    t = tables()
    lat = t.center_geo[:, 0]
    assert np.allclose(lat, -lat[::-1], atol=1e-9)


def test_device_kernel_matches_host_canonical():
    # the jax encode path must produce the same canonical ids,
    # including pentagon relabeling
    import jax
    from mosaic_tpu.core.index.h3.jaxkernel import latlng_to_cell_jax
    rng = np.random.default_rng(7)
    n = 2000
    lat = np.arcsin(rng.uniform(-1, 1, n))
    lng = rng.uniform(-np.pi, np.pi, n)
    # sprinkle points near pentagon centers to exercise the relabel
    from mosaic_tpu.core.index.h3.tables import tables
    t = tables()
    pc = t.center_geo[t.is_pentagon]
    extra = np.repeat(pc, 40, axis=0)
    extra = extra + rng.normal(0, 0.03, extra.shape)
    lat = np.concatenate([lat, extra[:, 0]])
    lng = np.concatenate([lng, extra[:, 1]])
    for res in (2, 5):
        host = ix.latlng_to_cell(np.stack([lat, lng], -1), res)
        with jax.enable_x64(True):
            dev = np.asarray(latlng_to_cell_jax(
                jax.numpy.asarray(lat), jax.numpy.asarray(lng), res))
        # ignore points whose assignment is boundary-ambiguous in f32
        agree = dev == host
        assert agree.mean() > 0.995
        bad = np.nonzero(~agree)[0]
        if len(bad):
            # disagreements must be boundary cells (neighbor ids)
            ring = ix.k_ring(host[bad], 1)
            assert np.all(np.any(ring == dev[bad, None], axis=1))


def test_cell_universe_counts_and_mean_areas():
    """Published H3 universe constants: cell counts per res are exact
    (122 / 842 / 5882); mean hexagon areas match the published tables
    within the projected-corner boundary convention's deviation (this
    framework's boundaries are chosen to agree with point_to_cell, not
    the true spherical cell — ~0.5% at res 1, ~0.07% at res 2,
    vanishing at city resolutions)."""
    sysm = H3IndexSystem()
    res0 = ix.pack(np.arange(122, dtype=np.int64),
                   np.zeros((122, 0), np.int64), 0)
    k1 = np.concatenate(ix.cell_to_children(res0, 1))
    assert len(k1) == 842
    a1 = sysm.cell_area(k1)
    hex1 = ~ix.is_pentagon_cell(k1)
    assert a1[hex1].mean() == pytest.approx(607220.9782, rel=1e-2)
    k2 = np.concatenate(ix.cell_to_children(res0, 2))
    assert len(k2) == 5882
    a2 = sysm.cell_area(k2)
    hex2 = ~ix.is_pentagon_cell(k2)
    assert a2[hex2].mean() == pytest.approx(86745.85403, rel=2e-3)
