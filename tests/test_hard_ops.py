"""Hard geometry ops: buffer, simplify, hulls, validity, CRS,
triangulation (reference behaviors: ST_BufferBehaviors,
ST_SimplifyBehaviors, ST_TransformBehaviors, ST_TriangulateBehaviors).
"""

import numpy as np
import pytest

from mosaic_tpu.core.geometry.clip import (_pip_rings, geometry_rings,
                                           ring_signed_area)
from mosaic_tpu.core.geometry.crs import (crs_bounds, transform_xy,
                                          has_valid_coordinates)
from mosaic_tpu.core.geometry.ops import (convex_hull_points,
                                          is_valid_rings, simplify_ring)
from mosaic_tpu.core.geometry.triangulate import (concave_hull_points,
                                                  conforming_delaunay,
                                                  delaunay,
                                                  interpolate_z)
from mosaic_tpu.functions.context import MosaicContext


@pytest.fixture(scope="module")
def ctx():
    return MosaicContext.build("CUSTOM(0,16,0,16,2,1,1)")


class TestBuffer:
    def test_square_buffer_area(self, ctx):
        g = ctx.st_geomfromwkt(["POLYGON((0 0, 10 0, 10 10, 0 10, 0 0))"])
        out = ctx.st_buffer(g, 1.0)
        # area = 100 + perimeter*r + pi*r² (rounded corners)
        want = 100 + 40 * 1.0 + np.pi
        assert ctx.st_area(out)[0] == pytest.approx(want, rel=1e-2)

    def test_negative_buffer(self, ctx):
        g = ctx.st_geomfromwkt(["POLYGON((0 0, 10 0, 10 10, 0 10, 0 0))"])
        out = ctx.st_buffer(g, -1.0)
        assert ctx.st_area(out)[0] == pytest.approx(64.0, rel=1e-2)

    def test_point_buffer(self, ctx):
        g = ctx.st_geomfromwkt(["POINT(3 3)"])
        out = ctx.st_buffer(g, 2.0)
        assert ctx.st_area(out)[0] == pytest.approx(np.pi * 4, rel=1e-2)

    def test_line_buffer_cap_styles(self, ctx):
        g = ctx.st_geomfromwkt(["LINESTRING(0 0, 10 0)"])
        round_a = ctx.st_area(ctx.st_buffer(g, 1.0, "round"))[0]
        flat_a = ctx.st_area(ctx.st_buffer(g, 1.0, "flat"))[0]
        square_a = ctx.st_area(ctx.st_buffer(g, 1.0, "square"))[0]
        assert flat_a == pytest.approx(20.0, rel=1e-6)
        assert round_a == pytest.approx(20 + np.pi, rel=1e-2)
        assert square_a == pytest.approx(24.0, rel=1e-2)

    def test_buffer_contains_original(self, ctx, rng):
        g = ctx.st_geomfromwkt(
            ["POLYGON((1 1, 9 1, 9 5, 5 5, 5 9, 1 9, 1 1))"])
        out = ctx.st_buffer(g, 0.5)
        rings = geometry_rings(out, 0)
        pts = rng.uniform(0, 10, (2000, 2))
        orig = _pip_rings(pts, geometry_rings(g, 0))
        buf = _pip_rings(pts, rings)
        assert not np.any(orig & ~buf)

    def test_bufferloop(self, ctx):
        g = ctx.st_geomfromwkt(["POLYGON((2 2, 8 2, 8 8, 2 8, 2 2))"])
        ring = ctx.st_bufferloop(g, 0.5, 1.0)
        inner = ctx.st_area(ctx.st_buffer(g, 0.5))[0]
        outer = ctx.st_area(ctx.st_buffer(g, 1.0))[0]
        assert ctx.st_area(ring)[0] == pytest.approx(outer - inner,
                                                     rel=1e-6)


class TestSimplify:
    def test_collinear_removed(self):
        r = np.array([[0, 0], [1, 0], [2, 0], [3, 0], [3, 3], [0, 3]])
        s = simplify_ring(r, 1e-9, closed=True)
        assert len(s) == 4

    def test_tolerance_monotone(self, ctx, rng):
        th = np.linspace(0, 2 * np.pi, 100, endpoint=False)
        ring = np.stack([5 + 3 * np.cos(th) + rng.normal(0, .05, 100),
                         5 + 3 * np.sin(th) + rng.normal(0, .05, 100)],
                        -1)
        wkt = "POLYGON((" + ", ".join(
            f"{x} {y}" for x, y in np.vstack([ring, ring[:1]])) + "))"
        g = ctx.st_geomfromwkt([wkt])
        n0 = ctx.st_numpoints(g)[0]
        n1 = ctx.st_numpoints(ctx.st_simplify(g, 0.05))[0]
        n2 = ctx.st_numpoints(ctx.st_simplify(g, 0.5))[0]
        assert n2 < n1 < n0
        a = ctx.st_area(ctx.st_simplify(g, 0.05))[0]
        assert a == pytest.approx(np.pi * 9, rel=0.1)


class TestHulls:
    def test_convex_hull_square(self):
        pts = np.vstack([np.random.default_rng(0).uniform(0, 1, (100, 2)),
                         [[0, 0], [1, 0], [1, 1], [0, 1]]])
        hull = convex_hull_points(pts)
        assert ring_signed_area(hull) == pytest.approx(1.0, rel=1e-9)

    def test_concave_hull_tighter_than_convex(self, rng):
        # C-shaped point cloud
        th = np.linspace(0.3, 2 * np.pi - 0.3, 200)
        pts = np.stack([np.cos(th), np.sin(th)], -1) * \
            rng.uniform(0.7, 1.0, (200, 1))
        concave = concave_hull_points(pts, 0.2)
        convex = convex_hull_points(pts)
        assert abs(ring_signed_area(concave)) < \
            abs(ring_signed_area(convex))

    def test_st_convexhull(self, ctx):
        g = ctx.st_geomfromwkt(["MULTIPOINT(0 0, 4 0, 4 4, 0 4, 2 2)"])
        hull = ctx.st_convexhull(g)
        assert ctx.st_area(hull)[0] == pytest.approx(16.0)


class TestValidity:
    def test_valid_polygon(self, ctx):
        g = ctx.st_geomfromwkt(
            ["POLYGON((0 0, 4 0, 4 4, 0 4, 0 0), (1 1, 2 1, 2 2, 1 2, 1 1))"])
        assert ctx.st_isvalid(g)[0]

    def test_bowtie_invalid(self, ctx):
        g = ctx.st_geomfromwkt(["POLYGON((0 0, 2 2, 2 0, 0 2, 0 0))"])
        assert not ctx.st_isvalid(g)[0]

    def test_hole_crossing_shell_invalid(self):
        shell = np.array([[0, 0], [4, 0], [4, 4], [0, 4]], float)
        hole = np.array([[3, 3], [6, 3], [6, 6], [3, 6]], float)[::-1]
        assert not is_valid_rings([shell, hole])


class TestCRS:
    def test_osgb_known_point(self):
        # London (-0.1276, 51.5072) -> BNG ~ (530042, 180358)
        en = transform_xy(np.array([[-0.1276, 51.5072]]), 4326, 27700)
        assert en[0, 0] == pytest.approx(530042, abs=60)
        assert en[0, 1] == pytest.approx(180358, abs=60)

    def test_roundtrips(self, rng):
        ll = np.stack([rng.uniform(-5, 1, 50),
                       rng.uniform(50, 58, 50)], -1)
        for epsg in (3857, 27700, 32630):
            out = transform_xy(transform_xy(ll, 4326, epsg), epsg, 4326)
            assert np.abs(out - ll).max() < 1e-6

    def test_webmercator_values(self):
        out = transform_xy(np.array([[180.0, 0.0]]), 4326, 3857)
        assert out[0, 0] == pytest.approx(20037508.34, rel=1e-6)
        assert out[0, 1] == pytest.approx(0.0, abs=1e-6)

    def test_st_transform_surface(self, ctx):
        g = ctx.st_geomfromwkt(["POINT(-0.1276 51.5072)"])
        out = ctx.st_transform(g, 27700)
        assert out.srid == 27700
        assert ctx.st_x(out)[0] == pytest.approx(530042, abs=60)

    def test_bounds_and_validity(self, ctx):
        b = crs_bounds(4326)
        assert b == (-180.0, -90.0, 180.0, 90.0)
        ok = has_valid_coordinates(
            np.array([[0.0, 51.0], [3.0, 51.0]]), 27700)
        assert ok.tolist() == [True, False]
        g = ctx.st_geomfromwkt(["POINT(0 51)", "POINT(200 0)"])
        assert ctx.st_hasvalidcoordinates(g, 4326).tolist() == \
            [True, False]

    def test_unsupported_epsg(self):
        # 2154 (Lambert-93) became table-supported in round 5
        # (tests/test_crs_families.py); a code absent from the table
        # must still raise cleanly
        with pytest.raises(ValueError, match="EPSG"):
            transform_xy(np.zeros((1, 2)), 4326, 999999)


class TestTriangulate:
    def test_delaunay_area_partition(self, rng):
        pts = rng.uniform(0, 10, (60, 2))
        verts, tri = delaunay(pts)
        hull = convex_hull_points(pts)
        total = sum(abs(ring_signed_area(verts[t])) for t in tri)
        assert total == pytest.approx(abs(ring_signed_area(hull)),
                                      rel=1e-9)

    def test_delaunay_empty_circumcircles(self, rng):
        from mosaic_tpu.core.geometry.triangulate import \
            _circumcircle_contains
        pts = rng.uniform(0, 1, (40, 2))
        verts, tri = delaunay(pts)
        for t in tri[:20]:
            others = np.setdiff1d(np.arange(len(verts)), t)
            for o in others[:10]:
                assert not _circumcircle_contains(verts[t], verts[o])

    def test_conforming_contains_constraint(self, rng):
        pts = rng.uniform(0, 10, (40, 2))
        seg = np.array([[[1.0, 1.0], [9.0, 9.0]]])
        verts, tri = conforming_delaunay(pts, seg)
        # every point of the constraint line lies on some edge
        from mosaic_tpu.core.geometry.triangulate import _edges_of_tris
        edges = _edges_of_tris(tri)
        samples = np.linspace(0, 1, 20)[:, None] * (seg[0, 1] -
                                                    seg[0, 0]) + seg[0, 0]
        for s in samples:
            on = False
            for (i, j) in edges:
                a, b = verts[i], verts[j]
                d = b - a
                ln2 = d @ d
                if ln2 == 0:
                    continue
                t = np.clip(((s - a) @ d) / ln2, 0, 1)
                if np.hypot(*(a + t * d - s)) < 1e-6:
                    on = True
                    break
            assert on

    def test_interpolate_plane(self, rng):
        # z = 2x + 3y + 1 must be reproduced exactly by a TIN
        xy = rng.uniform(0, 10, (50, 2))
        z = 2 * xy[:, 0] + 3 * xy[:, 1] + 1
        verts, tri = delaunay(xy)
        zv = 2 * verts[:, 0] + 3 * verts[:, 1] + 1
        q = rng.uniform(2, 8, (30, 2))
        got = interpolate_z(verts, zv, tri, q)
        want = 2 * q[:, 0] + 3 * q[:, 1] + 1
        np.testing.assert_allclose(got, want, rtol=1e-9)

    def test_st_triangulate_surface(self, ctx):
        g = ctx.st_geomfromwkt(["MULTIPOINT(0 0, 4 0, 4 4, 0 4, 2 2)"])
        tin = ctx.st_triangulate(g)
        assert ctx.st_area(tin)[0] == pytest.approx(16.0, rel=1e-9)

    def test_st_interpolateelevation(self, ctx):
        from mosaic_tpu.core.geometry.array import GeometryBuilder
        b = GeometryBuilder(ndim=3)
        pts = [(0, 0, 1.0), (10, 0, 1.0), (10, 10, 1.0), (0, 10, 1.0),
               (5, 5, 11.0)]
        from mosaic_tpu.core.geometry.array import GeometryType
        for p in pts:
            b.add(GeometryType.POINT, [[np.array(p)[None]]])
        mass = b.finish()
        q = ctx.st_point([5.0], [5.0])
        z = ctx.st_interpolateelevation(mass, q)
        assert z[0] == pytest.approx(11.0)


def test_st_distance_nested_and_crossing():
    """ST_Distance must be 0 for intersecting AND nested geometries
    (regression: the vertex-only formulation returned a positive
    distance for a polygon strictly inside another)."""
    from mosaic_tpu.core.geometry.wkt import read_wkt
    from mosaic_tpu.functions.context import MosaicContext
    mc = MosaicContext.build("H3")
    outer = read_wkt(["POLYGON((0 0, 10 0, 10 10, 0 10, 0 0))",
                      "POLYGON((0 0, 10 0, 10 10, 0 10, 0 0))",
                      "POLYGON((0 0, 4 0, 4 4, 0 4, 0 0))"])
    inner = read_wkt(["POLYGON((4 4, 6 4, 6 6, 4 6, 4 4))",   # nested
                      "POLYGON((8 8, 12 8, 12 12, 8 12, 8 8))",  # crossing
                      "POLYGON((6 6, 8 6, 8 8, 6 8, 6 6))"])  # disjoint
    d = mc.st_distance(outer, inner)
    assert d[0] == 0.0
    assert d[1] == 0.0
    assert d[2] == pytest.approx(np.hypot(2, 2))


def test_st_distance_mixed_types_and_multipart():
    """Mixed POINT rows and nested multipolygon components (review
    repro regressions)."""
    from mosaic_tpu.core.geometry.wkt import read_wkt
    from mosaic_tpu.functions.context import MosaicContext
    mc = MosaicContext.build("H3")
    a = read_wkt(["POLYGON((0 0, 1 0, 1 1, 0 1, 0 0))",
                  "POINT(5 5)",
                  "MULTIPOLYGON(((100 100, 101 100, 101 101, 100 101,"
                  " 100 100)), ((4 4, 6 4, 6 6, 4 6, 4 4)))"])
    b = read_wkt(["POLYGON((3 0, 4 0, 4 1, 3 1, 3 0))",
                  "POLYGON((7 5, 9 5, 9 7, 7 7, 7 5))",
                  "POLYGON((0 0, 10 0, 10 10, 0 10, 0 0))"])
    d = mc.st_distance(a, b)
    assert d[0] == pytest.approx(2.0)
    assert d[1] == pytest.approx(2.0)
    assert d[2] == 0.0                 # nested second component
    # point vs point
    p1 = read_wkt(["POINT(0 0)"])
    p2 = read_wkt(["POINT(3 4)"])
    assert mc.st_distance(p1, p2)[0] == pytest.approx(5.0)


def test_st_distance_mixed_point_rows():
    """Fast path must not claim inf for POINT rows on the right side
    (review finding: all-POINT left x mixed right)."""
    from mosaic_tpu.core.geometry.wkt import read_wkt
    from mosaic_tpu.functions.context import MosaicContext
    mc = MosaicContext.context()
    a = read_wkt(["POINT (0 0)", "POINT (1 1)"])
    b = read_wkt(["POLYGON ((2 0, 3 0, 3 1, 2 1, 2 0))", "POINT (4 5)"])
    d = mc.st_distance(a, b)
    assert d[0] == pytest.approx(2.0)
    assert d[1] == pytest.approx(5.0)


def test_st_distance_collection_open_linestring():
    """Open linestring in a GEOMETRYCOLLECTION must not read as a filled
    region (crossing-parity only holds over closed rings)."""
    from mosaic_tpu.core.geometry.wkt import read_wkt
    from mosaic_tpu.functions.context import MosaicContext
    mc = MosaicContext.context()
    a = read_wkt(["POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))"])
    b = read_wkt(["GEOMETRYCOLLECTION (LINESTRING (5 -5, 5 15))"])
    d = mc.st_distance(a, b)
    assert d[0] == pytest.approx(4.0)
    # and the symmetric direction
    d2 = mc.st_distance(b, a)
    assert d2[0] == pytest.approx(4.0)


def test_st_distance_closed_linestring_in_collection():
    """A closed LINESTRING member is a curve, not a surface: a point
    inside the loop is 5 away (JTS semantics), not 0 (review finding:
    part types were lost in the flattened collection layout)."""
    from mosaic_tpu.core.geometry.wkt import read_wkt
    from mosaic_tpu.functions.context import MosaicContext
    mc = MosaicContext.context()
    pt = read_wkt(["POINT (5 5)"])
    loop = read_wkt(
        ["GEOMETRYCOLLECTION (LINESTRING (0 0, 10 0, 10 10, 0 10, 0 0))"])
    assert mc.st_distance(pt, loop)[0] == pytest.approx(5.0)
    # a POLYGON member with the same shell IS filled
    filled = read_wkt(
        ["GEOMETRYCOLLECTION (POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0)))"])
    assert mc.st_distance(pt, filled)[0] == 0.0


def test_collection_member_types_round_trip():
    """Collection member types survive WKT/WKB/GeoJSON round trips
    (the writers used to re-infer, closing linestring loops into
    polygons)."""
    from mosaic_tpu.core.geometry.wkt import read_wkt, write_wkt
    from mosaic_tpu.core.geometry.wkb import read_wkb, write_wkb
    from mosaic_tpu.core.geometry.geojson import (read_geojson,
                                                  write_geojson)
    src = "GEOMETRYCOLLECTION (LINESTRING (0 0, 10 0, 10 10, 0 10, 0 0)," \
          " POINT (1 1), POLYGON ((2 2, 3 2, 3 3, 2 3, 2 2)))"
    g = read_wkt([src])
    out = write_wkt(g)[0]
    assert "LINESTRING" in out and "POINT" in out and "POLYGON" in out
    g2 = read_wkb(write_wkb(g))
    assert "LINESTRING" in write_wkt(g2)[0]
    g3 = read_geojson(write_geojson(g))
    assert "LINESTRING" in write_wkt(g3)[0]
    # take/concat preserve member types
    from mosaic_tpu.core.geometry.array import GeometryArray
    cat = GeometryArray.concat([g, g])
    assert "LINESTRING" in write_wkt(cat.take(np.asarray([1])))[0]


def test_st_length_collection_linestring():
    """Collection linestring members must not gain a closing edge."""
    from mosaic_tpu.core.geometry.wkt import read_wkt
    from mosaic_tpu.functions.context import MosaicContext
    mc = MosaicContext.context()
    g = read_wkt(["GEOMETRYCOLLECTION (LINESTRING (0 0, 10 0, 10 10))"])
    plain = read_wkt(["LINESTRING (0 0, 10 0, 10 10)"])
    assert mc.st_length(g)[0] == pytest.approx(mc.st_length(plain)[0])
