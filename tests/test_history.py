"""The workload history plane (``obs/history.py`` + ``obs/heat.py``).

The ISSUE's acceptance surface, directly:

* **exactly one record per completed query** — ok, error, AND
  cancelled outcomes all land one history record through
  ``accounting.complete``, widened with mispredicts / fusion groups /
  partitions touched;
* **degrade, not die** — torn tails keep their intact prefix, alien
  versions are skipped whole, a full-disk/injected write fault costs
  a counter and never the query (``history_segment_torn`` event +
  ``history/segments_torn`` / ``history/write_errors`` counters);
* **crash safety** — a ``kill -9`` mid-append leaves the directory
  loadable with loss confined to the open segment's torn tail, and
  two pids appending into one directory never collide (per-pid open
  segments);
* **exact fleet merge** — N workers' summaries merged window-by-
  window reproduce the single-store oracle's percentiles and integer
  counters bit-for-bit;
* **heat is observational** — the heat prior hands the rebalancer a
  placement hint only: a primed store-fed join returns bit-identical
  results to an unprimed one.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from mosaic_tpu import config as _config
from mosaic_tpu.obs import metrics
from mosaic_tpu.obs.accounting import accounted, audit, meter
from mosaic_tpu.obs.heat import HeatTracker, heat
from mosaic_tpu.obs.history import (HISTORY_VERSION, HistoryStore,
                                    history, load_records,
                                    merged_windows, read_segment,
                                    report, segment_paths,
                                    summarize_records, summary_paths,
                                    summary_payload, window_diff)
from mosaic_tpu.obs.inflight import QueryCancelled, inflight
from mosaic_tpu.obs.recorder import recorder
from mosaic_tpu.resilience.testing import fault_plan  # noqa: F401
from mosaic_tpu.store import ChipStore, write_store

RES = 4096
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def clean_obs(monkeypatch):
    """Clean obs singletons + pinned-off history env around a test."""
    monkeypatch.delenv("MOSAIC_TPU_HISTORY_DIR", raising=False)
    prev = _config.default_config()
    audit.reset()
    meter.reset()
    history.reset()
    heat.reset()
    metrics.reset()
    metrics.enable()
    recorder.reset()
    recorder.enable()
    yield
    _config.set_default_config(prev)
    audit.reset()
    meter.reset()
    history.reset()
    heat.reset()
    metrics.disable()
    metrics.reset()
    recorder.reset()


def _rec(i, ts=100.0, principal="alice", outcome="ok", wall=5.0,
         operator="pip_join"):
    return {"query_id": f"q{i}", "principal": principal,
            "sql": f"SELECT {i}", "trace": f"t{i}",
            "start_ts": ts - 0.01, "end_ts": ts, "outcome": outcome,
            "operator": operator,
            "strategies": {"join": "bnl" if i % 2 else "hash"},
            "cost": {"wall_ms": wall, "device_s": 0.25,
                     "rows_in": 100, "rows_out": 50,
                     "h2d_bytes": 4096, "d2h_bytes": 128,
                     "mem_peak_bytes": 1 << 20, "compiles": 1},
            "mispredicts": i % 3, "fusion_groups": ["pip.fused"],
            "partitions": {"3": {"rows": 100, "bytes": 800},
                           "9": {"rows": 2, "bytes": 16}}}


# ------------------------------------------------- rotation/retention

def test_append_rotates_and_retains(tmp_path, clean_obs):
    st = HistoryStore(str(tmp_path), segment_bytes=600, retain=3)
    for i in range(30):
        st.append(_rec(i))
    st.close()
    closed, opens = segment_paths(str(tmp_path))
    assert closed and len(closed) <= 3            # retention held
    assert metrics.counter_value("history/segments_rotated") > 0
    assert metrics.counter_value("history/segments_dropped") > 0
    assert metrics.counter_value("history/records_written") == 30
    # every surviving record is intact and name order is age order
    for p in closed:
        for r in read_segment(p):
            assert r["principal"] == "alice"
    assert closed == sorted(closed)


def test_age_rotation(tmp_path, clean_obs):
    st = HistoryStore(str(tmp_path), segment_bytes=1 << 20,
                      segment_age_ms=1.0)
    st.append(_rec(0))
    time.sleep(0.02)
    st.append(_rec(1))                 # over age: rotates first
    st.close()
    closed, _ = segment_paths(str(tmp_path))
    assert len(closed) == 1
    assert len(read_segment(closed[0])) == 1


# ------------------------------------------------------ degrade paths

def test_torn_tail_keeps_prefix(tmp_path, clean_obs):
    st = HistoryStore(str(tmp_path))
    for i in range(5):
        st.append(_rec(i))
    st.close()
    path = segment_paths(str(tmp_path))[1][0]
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[:len(raw) - 30])   # tear mid-record
    recs = read_segment(path)
    assert len(recs) == 4                          # prefix survives
    assert [r["query_id"] for r in recs] == ["q0", "q1", "q2", "q3"]
    assert recorder.events("history_segment_torn")
    assert metrics.counter_value("history/segments_torn") == 1


def test_alien_version_segment_skipped_whole(tmp_path, clean_obs):
    path = tmp_path / "history-123.open.jsonl"
    with open(path, "w") as fh:
        fh.write(json.dumps({"history": HISTORY_VERSION + 99,
                             "pid": 123}) + "\n")
        fh.write(json.dumps(_rec(0)) + "\n")
    assert read_segment(str(path)) == []
    ev = recorder.events("history_segment_torn")
    assert ev and "version" in ev[-1]["why"]
    # an unparseable header likewise
    with open(path, "w") as fh:
        fh.write("{torn json\n")
    assert read_segment(str(path)) == []
    assert metrics.counter_value("history/segments_torn") == 2


def test_write_fault_costs_counter_not_query(tmp_path, clean_obs,
                                             monkeypatch, fault_plan):
    monkeypatch.setenv("MOSAIC_TPU_HISTORY_DIR", str(tmp_path))
    fault_plan("seed=23;site=history.write,fails=1")
    with accounted("join-a", principal="alice"):
        pass                                      # survives the fault
    with accounted("join-b", principal="alice"):
        pass
    assert metrics.counter_value("history/write_errors") == 1
    assert history.write_errors() == 1
    recs = load_records(str(tmp_path))
    assert len(recs) == 1                         # second one landed
    assert recs[0]["sql"] == "join-b"
    assert audit.records(limit=10) and len(audit.records(limit=10)) == 2


# -------------------------------------------------------- crash drill

def test_two_pids_one_directory(tmp_path, clean_obs):
    """Per-pid open segments make concurrent writers collision-free
    by construction; a reader merges both."""
    st = HistoryStore(str(tmp_path))
    st.append(_rec(0))
    st.close()
    # fabricate a second live writer's open segment under another pid
    other = tmp_path / "history-99999999.open.jsonl"
    with open(other, "w") as fh:
        fh.write(json.dumps({"history": HISTORY_VERSION,
                             "pid": 99999999,
                             "opened_ts": time.time()}) + "\n")
        fh.write(json.dumps(_rec(1, principal="bob")) + "\n")
    recs = load_records(str(tmp_path))
    assert {r["query_id"] for r in recs} == {"q0", "q1"}
    assert metrics.counter_value("history/segments_torn") == 0


def test_sigkill_mid_write_leaves_store_loadable(tmp_path):
    """kill -9 a writer mid-append: the directory still loads and the
    loss is confined to the open segment's torn tail."""
    child = subprocess.Popen(
        [sys.executable, "-c", f"""
import sys, time
sys.path.insert(0, {_REPO!r})
from mosaic_tpu.obs.history import HistoryStore
st = HistoryStore({str(tmp_path)!r}, segment_bytes=2000)
i = 0
while True:
    st.append({{"query_id": f"q{{i}}", "principal": "p",
               "outcome": "ok", "end_ts": 100.0, "operator": "scan",
               "cost": {{"wall_ms": 1.0}}}})
    i += 1
"""],
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    deadline = time.time() + 30
    while time.time() < deadline:
        closed, _ = segment_paths(str(tmp_path))
        if len(closed) >= 2:           # it rotated at least twice
            break
        time.sleep(0.05)
    os.kill(child.pid, signal.SIGKILL)
    child.wait()
    closed, opens = segment_paths(str(tmp_path))
    assert len(closed) >= 2
    recs = load_records(str(tmp_path))   # must not raise
    assert recs and all(r["principal"] == "p" for r in recs)
    # closed segments were published with fsync+rename: never torn
    closed_recs = sum(len(read_segment(p)) for p in closed)
    assert closed_recs > 0


# --------------------------------------------- compaction/fleet merge

def test_compaction_matches_in_memory_oracle(tmp_path, clean_obs):
    st = HistoryStore(str(tmp_path), window_ms=1_000.0)
    recs = [_rec(i, ts=100.0 + (i % 3), wall=float(2 ** (i % 8)))
            for i in range(40)]
    for r in recs:
        st.append(r)
    st.rotate()
    stats = st.compact()
    st.close()
    assert stats["records"] == 40 and stats["summaries"] == 3
    assert not segment_paths(str(tmp_path))[0]    # segments gone
    assert len(summary_paths(str(tmp_path))) == 3
    assert metrics.counter_value("history/segments_compacted") > 0
    oracle = summarize_records(recs, 1_000.0)
    got = merged_windows(str(tmp_path), 1_000.0)
    assert set(got) == set(oracle)
    for wid in oracle:
        assert summary_payload(got[wid]) == summary_payload(oracle[wid])


def test_fleet_merge_equals_single_oracle_bit_for_bit(tmp_path,
                                                      clean_obs):
    """Split one workload across three 'workers'; the fleet merge must
    reproduce the single-store summary exactly — histogram buckets
    sum, so percentiles and every integer counter are bit-equal."""
    from mosaic_tpu.obs.fleet import merge_history
    recs = [_rec(i, ts=100.0 + (i % 2),
                 principal=("alice", "bob", "carol")[i % 3],
                 outcome=("ok", "ok", "error", "cancelled")[i % 4],
                 wall=float(3 ** (i % 6)))
            for i in range(60)]
    dirs = []
    for w in range(3):
        d = tmp_path / f"worker{w}"
        st = HistoryStore(str(d), window_ms=1_000.0)
        for r in recs[w::3]:
            st.append(r)
        st.rotate()
        if w == 1:
            st.compact()               # mixed: summaries + segments
        st.close()
        dirs.append(str(d))
    merged = merge_history(dirs, window_ms=1_000.0)
    assert merged["errors"] == 0
    oracle = summarize_records(recs, 1_000.0)
    want = [summary_payload(oracle[w]) for w in sorted(oracle)]
    assert merged["windows"] == want
    totals = merged["totals"]
    assert totals["queries"] == 60
    assert totals["outcomes"] == {"cancelled": 15, "error": 15,
                                  "ok": 30}
    # unreadable dir degrades, the rest still merge
    bad = merge_history(dirs + [str(tmp_path / "nope")],
                        window_ms=1_000.0)
    assert bad["totals"]["queries"] == 60


def test_window_diff_flags_regression(clean_obs):
    a = summarize_records([_rec(i, ts=1.0, wall=10.0)
                           for i in range(20)], 1_000.0)[1]
    b = summarize_records([_rec(i, ts=2.5, wall=30.0)
                           for i in range(20)], 1_000.0)[2]
    d = window_diff(summary_payload(a), summary_payload(b))
    assert d["flagged"] == ["pip_join"]
    assert d["operators"]["pip_join"]["slip_p50"] > 0.20
    # and a flat pair is quiet
    d2 = window_diff(summary_payload(a), summary_payload(a))
    assert d2["flagged"] == []


# ------------------------------------------------------------ the feed

def test_one_record_per_query_all_outcomes(tmp_path, clean_obs,
                                           monkeypatch):
    monkeypatch.setenv("MOSAIC_TPU_HISTORY_DIR", str(tmp_path))
    with accounted("ok-query", principal="alice"):
        pass
    with pytest.raises(RuntimeError):
        with accounted("err-query", principal="alice"):
            raise RuntimeError("boom")
    with pytest.raises(QueryCancelled):
        with accounted("cancel-query", principal="alice") as t:
            inflight.cancel(t.query_id)
            from mosaic_tpu.obs.inflight import checkpoint
            checkpoint("test")
    recs = load_records(str(tmp_path))
    assert len(recs) == 3                 # exactly one per query
    by_name = {r["sql"]: r for r in recs}
    assert by_name["ok-query"]["outcome"] == "ok"
    assert by_name["err-query"]["outcome"] == "error"
    assert by_name["cancel-query"]["outcome"] == "cancelled"
    for r in recs:                        # widened columns present
        assert "mispredicts" in r and "fusion_groups" in r \
            and "partitions" in r
        assert set(r["cost"]) >= {"wall_ms", "device_s", "rows_in",
                                  "rows_out", "h2d_bytes", "d2h_bytes",
                                  "mem_peak_bytes", "compiles"}
    assert metrics.counter_value("history/records_written") == 3


def test_feed_off_by_default_and_follows_conf(tmp_path, clean_obs):
    with accounted("q", principal="alice"):
        pass
    assert history.store() is None        # "" = plane off
    cfg = _config.MosaicConfig.from_confs(
        {"mosaic.history.dir": str(tmp_path)})
    _config.set_default_config(cfg)
    with accounted("q2", principal="alice"):
        pass
    assert [r["sql"] for r in load_records(str(tmp_path))] == ["q2"]


# ------------------------------------------------------------- heat

def test_heat_report_ranks_and_decays(clean_obs):
    ht = HeatTracker(halflife_ms=0)       # no decay
    now = 1_000.0
    for _ in range(9):
        ht.touch(3, rows=100, nbytes=800, now=now)
    ht.touch(7, rows=10, nbytes=40, now=now)
    rep = ht.report(now=now)
    assert rep["tracked"] == 2
    assert [c["cell"] for c in rep["cells"]] == [3, 7]
    assert rep["cells"][0]["bytes_per_row"] == pytest.approx(8.0)
    assert rep["skew"] > 1.5
    # decay: after one half-life the hot cell halves
    ht2 = HeatTracker(halflife_ms=1_000.0)
    ht2.touch(3, rows=100, now=now)
    rep2 = ht2.report(now=now + 1.0)
    assert rep2["cells"][0]["rows"] == pytest.approx(50.0)
    assert metrics.counter_value("heat/touches") == 11


def test_store_scan_feeds_heat_and_pruned_stays_cold(tmp_path,
                                                     clean_obs):
    rng = np.random.default_rng(5)
    pts = np.column_stack([rng.uniform(-74.3, -73.7, 8_000),
                           rng.uniform(40.5, 40.95, 8_000)])
    write_store(str(tmp_path), pts, grid_res=RES, shard_rows=1024)
    st = ChipStore(str(tmp_path))
    bbox = (-74.05, 40.6, -73.9, 40.75)
    scanned = {p.cell for p in st.prune(bbox, record=False)}
    pruned = {p.cell for p in st.partitions} - scanned
    assert scanned and pruned
    for _ in st.iter_chunks(bbox=bbox, chunk_rows=1024):
        pass
    rep = heat.report(top=len(st.partitions))
    hot = {c["cell"] for c in rep["cells"]}
    assert hot and hot <= scanned          # pruned cells stay cold
    assert not (hot & pruned)


def test_heat_prior_is_pure_hint_bit_parity(tmp_path, clean_obs):
    """A heat-primed store-fed join returns results bit-identical to
    an unprimed run — the prior moves placement only."""
    from mosaic_tpu.bench.workloads import build_workload
    from mosaic_tpu.parallel.pip_join import (build_pip_index,
                                              make_store_sharded_pip_join)
    polys, grid, res = build_workload(n_side=4, res_cells=64)
    idx = build_pip_index(polys, res, grid)
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("data",))
    rng = np.random.default_rng(6)
    pts = np.column_stack([rng.uniform(-74.3, -73.7, 12_000),
                           rng.uniform(40.5, 40.95, 12_000)])
    write_store(str(tmp_path), pts, grid_res=RES, shard_rows=2048)
    st = ChipStore(str(tmp_path))

    def run():
        sj = make_store_sharded_pip_join(st, idx, grid, mesh,
                                         polys=polys, chunk=4096)
        return sj()

    zone_cold, rc_cold = run()             # also seeds the heat map
    assert heat.report()["tracked"] > 0
    cfg = _config.MosaicConfig.from_confs({"mosaic.heat.prior": "true"})
    _config.set_default_config(cfg)
    zone_hot, rc_hot = run()
    assert metrics.counter_value("heat/prior_primes") >= 1
    assert np.array_equal(np.asarray(zone_cold), np.asarray(zone_hot))
    assert rc_cold == rc_hot


def test_rebalancer_prime_validates_shape(clean_obs):
    from mosaic_tpu.parallel.placement import SkewRebalancer
    rb = SkewRebalancer(n_shards=4, nbins=8)
    with pytest.raises(ValueError):
        rb.prime((0.0, 0.0, 1.0, 1.0), np.ones(7))
    rb.prime((0.0, 0.0, 1.0, 1.0), np.ones(64))
    assert rb.rebalances == 1 and rb._assign is not None


# --------------------------------------------------- audit rotation

def test_audit_spool_rotation_and_retention(tmp_path, clean_obs):
    spool = tmp_path / "audit.jsonl"
    cfg = _config.MosaicConfig.from_confs({
        "mosaic.audit.path": str(spool),
        "mosaic.audit.rotate.bytes": "256",
        "mosaic.audit.retain": "2"})
    _config.set_default_config(cfg)
    for i in range(12):
        with accounted(f"q{i}", principal="alice"):
            pass
    rotated = [p for p in os.listdir(tmp_path)
               if p.startswith("audit.jsonl.")]
    assert rotated and len(rotated) <= 2          # cap held
    assert metrics.counter_value("audit/spool_rotations") >= 3
    for p in rotated:                             # every line intact
        for line in open(tmp_path / p):
            assert json.loads(line)["outcome"] == "ok"


# ------------------------------------------------- operator surfaces

def test_mosaicstat_cli_and_diff_gate(tmp_path, clean_obs):
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import mosaicstat
    finally:
        sys.path.pop(0)
    st = HistoryStore(str(tmp_path), window_ms=1_000.0)
    for i in range(10):
        st.append(_rec(i, ts=1.0, wall=10.0))
    for i in range(10):
        st.append(_rec(i + 10, ts=2.5, wall=40.0))
    st.close()
    base = ["--dir", str(tmp_path), "--window-ms", "1000"]
    assert mosaicstat.main(base + ["top", "--by", "wall_ms"]) == 0
    assert mosaicstat.main(base + ["principals"]) == 0
    assert mosaicstat.main(base + ["strategies"]) == 0
    assert mosaicstat.main(base + ["heatmap"]) == 0
    assert mosaicstat.main(base + ["report"]) == 0
    assert mosaicstat.main(base + ["diff"]) == 3   # gated regression
    # two dirs merge fleet-wide through the same CLI
    assert mosaicstat.main(["--dir", str(tmp_path), "--dir",
                            str(tmp_path), "--window-ms", "1000",
                            "principals"]) == 0
    assert mosaicstat.main(["--dir", str(tmp_path / "void"),
                            "--window-ms", "1000", "top"]) == 1


def test_dashboard_history_endpoint(tmp_path, clean_obs, monkeypatch):
    import urllib.request
    from mosaic_tpu.obs.dashboard import serve_dashboard
    monkeypatch.setenv("MOSAIC_TPU_HISTORY_DIR", str(tmp_path))
    with accounted("q-dash", principal="alice"):
        pass
    heat.touch(5, rows=42, nbytes=84)
    handle = serve_dashboard(port=0)
    try:
        url = f"http://127.0.0.1:{handle.port}/api/history"
        payload = json.loads(urllib.request.urlopen(url).read())
        assert payload["enabled"] is True
        assert payload["totals"]["queries"] == 1
        assert payload["heat"]["cells"][0]["cell"] == 5
        # unconfigured -> stand-alone contract
        monkeypatch.setenv("MOSAIC_TPU_HISTORY_DIR", "")
        payload = json.loads(urllib.request.urlopen(url).read())
        assert payload["enabled"] is False
    finally:
        handle.close()
