"""Batched pair intersection-area kernel vs the exact boolean engine.

The fragment-shoelace design (native/geokernels.cpp
intersect_area_pairs) must agree with rings_boolean + signed-area to
f64 precision — it is the scalable core of the distributed
ST_IntersectionAgg path (reference ST_IntersectionAgg.scala:41-58).
"""

import numpy as np
import pytest

from mosaic_tpu.core.geometry.array import GeometryBuilder
from mosaic_tpu.core.geometry.clip import (_normalize_rings,
                                           _pip_rings, geometry_rings,
                                           pairs_intersection_area,
                                           ring_signed_area,
                                           rings_boolean)


def _rand_poly(rng, cx, cy, r, n):
    # evenly spaced angles + jitter keep gaps < pi => star-simple
    ang = 2 * np.pi * (np.arange(n) + rng.uniform(-0.35, 0.35, n)) / n
    rad = r * rng.uniform(0.4, 1.0, n)
    return np.stack([cx + rad * np.cos(ang), cy + rad * np.sin(ang)],
                    -1)


@pytest.fixture(scope="module")
def pair_batch():
    rng = np.random.default_rng(3)
    ba, bb = GeometryBuilder(), GeometryBuilder()
    P = 120
    for _ in range(P):
        cx, cy = rng.uniform(-1, 1, 2)
        pa = _rand_poly(rng, cx, cy, 0.5, 8)
        pb = _rand_poly(rng, cx + rng.uniform(-0.3, 0.3),
                        cy + rng.uniform(-0.3, 0.3), 0.5, 7)
        ba.add_polygon(np.vstack([pa, pa[:1]]))
        bb.add_polygon(np.vstack([pb, pb[:1]]))
    return ba.finish(), bb.finish(), P


def test_matches_boolean_engine(pair_batch):
    A, B, P = pair_batch
    ia = ib = np.arange(P)
    got = pairs_intersection_area(A, ia, B, ib)
    for p in range(P):
        rings = rings_boolean(
            _normalize_rings(geometry_rings(A, p)),
            _normalize_rings(geometry_rings(B, p)), "intersection")
        want = sum(ring_signed_area(r)
                   for r in _normalize_rings(rings))
        assert got[p] == pytest.approx(want, abs=1e-12), p


def test_monte_carlo_sanity(pair_batch):
    A, B, P = pair_batch
    rng = np.random.default_rng(9)
    ps = rng.integers(0, P, 6)
    got = pairs_intersection_area(A, ps, B, ps)
    for k, p in enumerate(ps):
        ra = _normalize_rings(geometry_rings(A, int(p)))
        rb = _normalize_rings(geometry_rings(B, int(p)))
        allv = np.vstack(ra + rb)
        lo, hi = allv.min(0), allv.max(0)
        pts = rng.uniform(lo, hi, (150000, 2))
        mc = (_pip_rings(pts, ra) & _pip_rings(pts, rb)).mean() * \
            np.prod(hi - lo)
        assert abs(mc - got[k]) < 0.01 + 0.05 * got[k]


def test_identity_disjoint_nested(pair_batch):
    A, B, P = pair_batch
    # self-intersection == own area
    ia = np.arange(10)
    self_area = pairs_intersection_area(A, ia, A, ia)
    for p in range(10):
        a = sum(ring_signed_area(r) for r in
                _normalize_rings(geometry_rings(A, p)))
        assert self_area[p] == pytest.approx(a, abs=1e-12)
    # disjoint and nested synthetic cases, incl. a hole
    bo, bi = GeometryBuilder(), GeometryBuilder()
    sq = np.array([[0, 0], [4, 0], [4, 4], [0, 4], [0, 0]], float)
    hole = np.array([[1, 1], [1, 3], [3, 3], [3, 1], [1, 1]], float)
    inner = np.array([[1.5, 1.5], [2.5, 1.5], [2.5, 2.5], [1.5, 2.5],
                      [1.5, 1.5]], float)
    far = inner + 100.0
    bo.add_polygon(sq, holes=[hole])
    bo.add_polygon(sq, holes=[hole])
    bi.add_polygon(inner)
    bi.add_polygon(far)
    O, I = bo.finish(), bi.finish()
    got = pairs_intersection_area(O, [0, 1], I, [0, 1])
    assert got[0] == pytest.approx(0.0, abs=1e-12)   # inner in the hole
    assert got[1] == pytest.approx(0.0, abs=1e-12)   # disjoint
    # square minus hole against itself
    got2 = pairs_intersection_area(O, [0], O, [0])
    assert got2[0] == pytest.approx(16.0 - 4.0, abs=1e-12)


def test_shared_edge_counted_once():
    # two unit squares sharing an edge: zero overlap area
    b1, b2 = GeometryBuilder(), GeometryBuilder()
    b1.add_polygon(np.array([[0, 0], [1, 0], [1, 1], [0, 1], [0, 0]],
                            float))
    b2.add_polygon(np.array([[1, 0], [2, 0], [2, 1], [1, 1], [1, 0]],
                            float))
    got = pairs_intersection_area(b1.finish(), [0], b2.finish(), [0])
    assert got[0] == pytest.approx(0.0, abs=1e-12)
    # identical squares: full area, not double-counted
    b3 = GeometryBuilder()
    b3.add_polygon(np.array([[0, 0], [1, 0], [1, 1], [0, 1], [0, 0]],
                            float))
    S = b3.finish()
    assert pairs_intersection_area(S, [0], S, [0])[0] == \
        pytest.approx(1.0, abs=1e-12)
