"""SpatialKNN (models/knn.py) vs the brute-force f64 oracle.

Reference test shape: the KNN suite checks transform output counts,
ordering and early stopping (models/knn/SpatialKNNTest.scala behaviors);
here the oracle is exact brute force, and the multi-device lane runs the
same transform sharded over the 8-device CPU mesh.
"""

import numpy as np
import pytest

from mosaic_tpu.core.index.factory import get_index_system
from mosaic_tpu.models import (CheckpointManager, SpatialKNN,
                               knn_host_truth)

NYC = (-74.25, 40.5, -73.7, 40.9)


@pytest.fixture(scope="module")
def grid():
    return get_index_system("H3")


def _pts(n, seed, bbox=NYC):
    rng = np.random.default_rng(seed)
    return np.stack([rng.uniform(bbox[0], bbox[2], n),
                     rng.uniform(bbox[1], bbox[3], n)], -1)


def _check_against_oracle(out, left, right, k, thr=None):
    ids, dist = knn_host_truth(left, right, k, thr)
    assert np.array_equal(out["right_id"], ids)
    both = np.isfinite(dist)
    assert np.allclose(out["distance"][both], dist[both], rtol=0,
                       atol=1e-12)
    assert not np.any(np.isfinite(out["distance"]) ^ both)


def test_knn_matches_bruteforce(grid):
    left = _pts(2000, 1)
    right = _pts(300, 2)
    knn = SpatialKNN(grid, k=5, index_resolution=7, max_iterations=32)
    out = knn.transform(left, right)
    _check_against_oracle(out, left, right, 5)
    assert out["iterations"] < 32          # early stop engaged


def test_knn_k_larger_than_candidates_nearby(grid):
    """k larger than any cell's population forces multi-ring search."""
    left = _pts(500, 3)
    right = _pts(40, 4)
    knn = SpatialKNN(grid, k=7, index_resolution=8, max_iterations=64)
    out = knn.transform(left, right)
    _check_against_oracle(out, left, right, 7)


def test_knn_distance_threshold(grid):
    left = _pts(800, 5)
    right = _pts(200, 6)
    thr = 0.02
    knn = SpatialKNN(grid, k=4, index_resolution=8, max_iterations=64,
                     distance_threshold=thr)
    out = knn.transform(left, right)
    _check_against_oracle(out, left, right, 4, thr)
    # some rows must be truncated by the threshold for the test to bite
    assert np.any(out["right_id"] < 0)


def test_knn_checkpoint_resume(grid, tmp_path):
    left = _pts(600, 7)
    right = _pts(150, 8)
    # full run
    ref = SpatialKNN(grid, k=3, index_resolution=8,
                     max_iterations=64).transform(left, right)
    # interrupted run: stop after 2 rings, then resume from checkpoint
    ck = CheckpointManager(str(tmp_path / "ck"))
    knn1 = SpatialKNN(grid, k=3, index_resolution=8, max_iterations=2,
                      checkpoint=ck)
    knn1.transform(left, right)
    knn2 = SpatialKNN(grid, k=3, index_resolution=8, max_iterations=64,
                      checkpoint=ck)
    out = knn2.transform(left, right)
    assert np.array_equal(out["right_id"], ref["right_id"])


def test_knn_sharded_8dev(grid):
    import jax
    from jax.sharding import Mesh
    devices = np.array(jax.devices()[:8])
    mesh = Mesh(devices, axis_names=("data",))
    left = _pts(2048, 9)               # divisible by 8
    right = _pts(256, 10)
    knn = SpatialKNN(grid, k=5, index_resolution=7, max_iterations=32,
                     mesh=mesh)
    out = knn.transform(left, right)
    _check_against_oracle(out, left, right, 5)


def test_knn_small_right_side(grid):
    """k larger than the whole right set: pad with -1, no crash."""
    left = _pts(50, 11)
    right = _pts(2, 12)
    out = SpatialKNN(grid, k=5, index_resolution=8,
                     max_iterations=64).transform(left, right)
    _check_against_oracle(out, left, right, 5)
    assert np.all(out["right_id"][:, 2:] == -1)


def test_knn_vertex_anchored_left_points(grid):
    """Left points sitting ON cell vertices — the worst case for the
    ring separation floor (regression: the d*2*inradius bound was loose
    along hex-vertex directions and returned a non-nearest neighbour
    with no flag)."""
    right = _pts(120, 13)
    # anchor left points exactly at vertices of cells in the area
    cells = np.unique(grid.point_to_cell(_pts(64, 14), 8))
    verts, counts = grid.cell_boundary(cells)
    left = verts.reshape(-1, 2)[:256]
    out = SpatialKNN(grid, k=3, index_resolution=8,
                     max_iterations=64).transform(left, right)
    _check_against_oracle(out, left, right, 3)
